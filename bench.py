"""Benchmarks for the BASELINE.md configs.

Default (bare ``python bench.py``) runs the full sweep and prints ONE JSON
line whose headline metric is **engine-level** vote-ingest throughput at
config-3 scale (10k concurrent proposals × 64 voters, single TPU core) —
the full TpuConsensusEngine service surface: proposal-id resolution, voter
lane resolution, per-vote status codes, round bookkeeping, event emission —
driven through the columnar batch API. ``detail`` carries every other
BASELINE shape:

  pool_level   raw ProposalPool throughput, same shape (no service layer)
  config2      1 proposal × 1024 voters, P2P: p50 finality latency
  config4      256 scopes × 1k proposals × 256 voters, 30% absent,
               liveness-timeout path (sharded when >1 device)
  config5      streaming mixed Gossipsub+P2P replay to 1M proposals
  lanes1024    12k proposals × 1024 voter lanes (per-chip slice of the
               100k-proposal north star)

Individual runs via argv: engine | pool (alias config3) | config2 |
config4 | config5 | lanes1024 | crypto | validated | redelivery | wal |
fleet | slo-overhead | default | all (``all`` prints newline-separated
JSON, one line
per section). ``wal`` measures the durability subsystem: append
throughput per fsync policy, DurableEngine ingest overhead vs a bare
engine, and recovery replay rate (host-only — not part of the BASELINE
sweep). ``redelivery`` measures amortized vote verification
(VerifiedVoteCache + validated-chain watermark) under gossip redelivery
and incremental chain growth, cache-on vs cache-off, with real ECDSA
signatures. ``fleet`` measures the scope-sharded fleet
(hashgraph_tpu.parallel.ConsensusFleet): an aggregate votes/sec headline
across all local devices with a per-shard breakdown, a paired fleet-vs-
single-shard A/B ``noise_verdict``, and a MULTICHIP-compatible record;
``fleet --smoke`` is the 2-shard CI short run. Decision-driving benches
(``fleet``, ``gossip``, ``churn``, ``fleet --hosts N``) add an ``slo``
block to their JSON — windowed p50/p95/p99 decide latency plus a
burn-rate verdict from :mod:`hashgraph_tpu.obs.slo`; ``slo-overhead``
is the paired A/B asserting always-on SLO tracking costs under 5%
throughput. The federated fleet bench additionally scrapes the merged
``/metrics`` + ``/slo`` views and induces one SLO breach to assert the
alert fires and an exemplar-linked Perfetto incident dump lands on the
owning host.

JAX's persistent compilation cache is ON BY DEFAULT at
``~/.cache/hashgraph_tpu/xla-cache`` (re-runs at the same geometry skip
XLA compile warmup entirely); ``--compile-cache DIR`` relocates it,
``--no-compile-cache`` disables it. Multi-device CPU meshes default it
off — the pinned jaxlib mis-deserializes cached multi-device CPU
programs (wrong results + segfault; an explicit ``--compile-cache DIR``
still forces it).

``--metrics-out PATH`` additionally snapshots the always-on observability
registry (:mod:`hashgraph_tpu.obs` — counter totals, gauges, and histogram
quantiles such as ``wal_fsync_seconds`` p50/p90/p99) into the emitted JSON
and writes the full result object to PATH. ``--metrics-port N`` serves the
HTTP ``/metrics`` + ``/healthz`` sidecar for the run's duration so the
histograms can be scraped live while the bench executes. ``--trace-out
PATH`` runs the bench under one distributed trace context and exports the
context-tagged spans (device ingest, verify batches, WAL fsyncs, per-
proposal lifecycles) as a Chrome trace-event file for Perfetto.
``--health-out PATH`` writes the consensus-health snapshot (peer
scorecards with grades, equivocation/fork evidence, watchdog, firing
alert rules — :mod:`hashgraph_tpu.obs.health`) to PATH and folds the
alert counts into the emitted JSON under ``health``.

Traces are pre-validated replays (signature/hash verification is the
pluggable host stage — measured separately by ``python bench.py crypto``
and the validated end-to-end mode; the reference's own tests hand-deliver
already-validated votes the same way).
"""

from __future__ import annotations

import json
import time

import numpy as np


def spread_pct(vals: "list[float]") -> float:
    """Max-min spread of a rep list as % of the median — the shared
    denominator of every bench's noise_verdict separation bar."""
    vals = sorted(vals)
    mid = vals[len(vals) // 2]
    return round(100.0 * (vals[-1] - vals[0]) / mid, 1) if mid else 0.0


def _slo_block(objective_ms: "float | None" = None) -> dict:
    """Windowed decision-latency quantiles + an SLO verdict from the
    process-global SloEngine — the ``slo`` block the decision-driving
    benches (fleet / gossip / churn) append to their BENCH_*.json.

    ``objective_ms`` is the bench's declared decide-latency objective:
    the verdict passes when the fast-window global p99 meets it AND no
    burn-rate alert is firing at readout."""
    from hashgraph_tpu.obs import slo_engine

    state = slo_engine.state()
    window = state["global"]
    block = {
        "windowed_latency_ms": {
            "count": window["count"],
            "p50": round(window["p50"] * 1e3, 3),
            "p95": round(window["p95"] * 1e3, 3),
            "p99": round(window["p99"] * 1e3, 3),
        },
        "per_shard_p99_ms": {
            sid: round(s["p99"] * 1e3, 3)
            for sid, s in state["shards"].items()
        },
        "alerts_firing": state["alerts_firing"],
    }
    if objective_ms is not None:
        block["verdict"] = {
            "objective_ms": objective_ms,
            "p99_ms": block["windowed_latency_ms"]["p99"],
            "pass": bool(
                not state["alerts_firing"]
                and window["p99"] * 1e3 <= objective_ms
            ),
        }
    return block


def run_bench(
    p_count: int = 10_240,
    v_count: int = 64,
    votes_per_dispatch: int = 8,
    cycles: int = 5,
) -> dict:
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import required_votes_np

    rng = np.random.default_rng(7)
    now = 1_700_000_000

    pool = ProposalPool(p_count, v_count)

    def allocate(cycle: int) -> None:
        # Gossipsub mode, threshold 1.0: every vote is accepted (round cap 2
        # admits any count) and no session decides before its last voter, so
        # every dispatch carries only real, accepted votes.
        pool.allocate_batch(
            keys=[(f"bench{cycle}", i) for i in range(p_count)],
            n=np.full(p_count, v_count),
            req=required_votes_np(np.full(p_count, v_count), 1.0),
            cap=np.full(p_count, 2),
            gossip=np.ones(p_count, bool),
            liveness=np.ones(p_count, bool),
            expiry=np.full(p_count, now + 10_000),
            created_at=np.full(p_count, now),
        )

    L = votes_per_dispatch
    dispatches_per_cycle = v_count // L
    slots = np.repeat(np.arange(p_count, dtype=np.int64), L)

    def dispatch(d: int):
        # L votes per proposal per dispatch: lanes d*L..(d+1)*L-1.
        lanes = np.tile(np.arange(d * L, (d + 1) * L, dtype=np.int32), p_count)
        values = rng.random(p_count * L) < 0.5
        return pool.ingest_async(slots, lanes, values, now)

    def run_cycle(check: bool) -> None:
        pendings = [dispatch(d) for d in range(dispatches_per_cycle)]
        results = pool.complete_all(pendings)
        if check:
            for d, (statuses, _) in enumerate(results):
                assert int(statuses[0]) == 0, f"dispatch {d}: {statuses[0]}"

    # Warmup: compile every kernel the timed loop uses (allocate, ingest,
    # release) so the measured window is pure steady-state throughput.
    all_slots = list(range(p_count))
    allocate(0)
    run_cycle(check=True)
    pool.release(all_slots)
    allocate(0)
    run_cycle(check=True)

    jax.block_until_ready(pool._state)
    # Per-cycle timing with a median report: the tunneled link has high
    # run-to-run jitter (2x between identical runs), and one slow RPC
    # shouldn't define the engine's throughput number.
    cycle_votes = p_count * v_count
    rates = []
    for cycle in range(1, cycles + 1):
        start = time.perf_counter()
        pool.release(all_slots)
        allocate(cycle)
        run_cycle(check=False)
        rates.append(cycle_votes / (time.perf_counter() - start))
    rates.sort()
    throughput = rates[len(rates) // 2]
    return {
        "metric": "vote_ingest_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "proposals": p_count,
            "voters": v_count,
            "votes_per_cycle": cycle_votes,
            "cycles": cycles,
            "cycle_rates": [round(r, 1) for r in rates],
            "platform": jax.devices()[0].platform,
        },
    }


def run_engine_bench(
    p_count: int = 10_240, v_count: int = 64, cycles: int = 6
) -> dict:
    """Engine-level config 3: the full TpuConsensusEngine service surface —
    batch proposal creation, vectorized proposal-id + voter-lane resolution,
    per-vote status codes, round bookkeeping, event emission — via the
    columnar API. This is the honest north-star number (the service the
    embedder actually calls); ``run_bench`` measures the raw pool under it.
    """
    import jax

    from hashgraph_tpu import CreateProposalRequest, StubConsensusSigner
    from hashgraph_tpu.engine import TpuConsensusEngine

    now = 1_700_000_000
    engine = TpuConsensusEngine(
        StubConsensusSigner(b"\x01" * 20),
        capacity=p_count,
        voter_capacity=v_count,
        max_sessions_per_scope=p_count + 1,
    )
    engine.scope("s").with_threshold(1.0).initialize()
    requests = [
        CreateProposalRequest(
            name="p",
            payload=b"",
            proposal_owner=b"o",
            expected_voters_count=v_count,
            expiration_timestamp=10_000,
            liveness_criteria_yes=True,
        )
        for _ in range(p_count)
    ]
    owners = [
        bytes([1 + (i % 250), i // 250]) + b"\x00" * 18 for i in range(v_count)
    ]
    col_vals = (np.arange(p_count * v_count) % 2).astype(bool)

    ingest_rates, create_rates = [], []
    for cycle in range(cycles + 1):  # first is compile warmup
        engine.delete_scope("s")
        engine.scope("s").with_threshold(1.0).initialize()
        # Re-intern per cycle: delete_scope evicted the previous cycle's
        # gids (refcounted registry), so reusing them would measure the
        # EMPTY_VOTE_OWNER rejection fast path, not vote ingest — the
        # all-OK assert below guards every timed cycle against exactly
        # that regression.
        gids = np.array([engine.voter_gid(o) for o in owners], np.int64)
        col_gids = np.repeat(gids, p_count)
        t0 = time.perf_counter()
        proposals = engine.create_proposals("s", requests, now)
        t1 = time.perf_counter()
        pids = np.fromiter(
            (p.proposal_id for p in proposals), np.int64, p_count
        )
        col_pids = np.tile(pids, v_count)
        t2 = time.perf_counter()
        statuses = engine.ingest_columnar("s", col_pids, col_gids, col_vals, now)
        t3 = time.perf_counter()
        assert int(np.sum(statuses == 0)) == p_count * v_count, "not all OK"
        if cycle > 0:
            create_rates.append(p_count / (t1 - t0))
            ingest_rates.append(p_count * v_count / (t3 - t2))
    ingest_rates.sort()
    create_rates.sort()
    throughput = ingest_rates[len(ingest_rates) // 2]
    return {
        "metric": "engine_vote_ingest_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "proposals": p_count,
            "voters": v_count,
            "cycles": cycles,
            "ingest_rates": [round(r, 1) for r in ingest_rates],
            "proposal_creation_rate": round(
                create_rates[len(create_rates) // 2], 1
            ),
            "platform": jax.devices()[0].platform,
        },
    }


def run_engine_lanes1024(
    p_count: int = 12_288, v_count: int = 1024, cycles: int = 3
) -> dict:
    """Engine-level north-star shape: 12k concurrent proposals × 1024 voter
    lanes under P2P round caps, driven through the FULL service surface
    (batch creation, pid resolution, lane resolution, round bookkeeping,
    statuses, events) via the columnar API — the per-chip slice of "100k
    concurrent 1024-voter proposals" measured at the layer embedders call,
    not the raw pool."""
    import jax

    from hashgraph_tpu import CreateProposalRequest, StubConsensusSigner
    from hashgraph_tpu import ScopeConfigBuilder
    from hashgraph_tpu.engine import TpuConsensusEngine

    rng = np.random.default_rng(13)
    now = 1_700_000_000
    engine = TpuConsensusEngine(
        StubConsensusSigner(b"\x01" * 20),
        capacity=p_count,
        voter_capacity=v_count,
        max_sessions_per_scope=p_count + 1,
    )
    fill = 672  # ceil(2n/3)=683 P2P vote cap; stay under mid-stream decisions
    requests = [
        CreateProposalRequest(
            name="p",
            payload=b"",
            proposal_owner=b"o",
            expected_voters_count=v_count,
            expiration_timestamp=10_000,
            liveness_criteria_yes=True,
        )
        for _ in range(p_count)
    ]
    owners = [
        bytes([1 + (i % 250), i // 250]) + b"\x00" * 18 for i in range(fill)
    ]
    col_vals = rng.random(p_count * fill) < 0.5

    ingest_rates, create_rates = [], []
    for cycle in range(cycles + 1):  # first is compile warmup
        engine.delete_scope("s")
        engine.set_scope_config("s", ScopeConfigBuilder().p2p_preset().build())
        # Re-intern per cycle (delete_scope evicted the previous cycle's
        # gids); the every-cycle all-OK assert guards against timing the
        # rejection path as throughput.
        cycle_gids = np.array([engine.voter_gid(o) for o in owners], np.int64)
        # One fresh (slot, gid) stream per cycle: proposal-major, arrival
        # order = lane order; every pair is first-occurrence so lane
        # resolution stays on the vectorized fresh-assignment path.
        col_gids = np.tile(cycle_gids, p_count)
        t0 = time.perf_counter()
        proposals = engine.create_proposals("s", requests, now)
        t1 = time.perf_counter()
        pids = np.fromiter((p.proposal_id for p in proposals), np.int64, p_count)
        col_pids = np.repeat(pids, fill)
        t2 = time.perf_counter()
        statuses = engine.ingest_columnar("s", col_pids, col_gids, col_vals, now)
        t3 = time.perf_counter()
        ok = int(np.sum(statuses == 0))
        assert ok == p_count * fill, (ok, p_count * fill)
        if cycle > 0:
            create_rates.append(p_count / (t1 - t0))
            ingest_rates.append(p_count * fill / (t3 - t2))
    ingest_rates.sort()
    create_rates.sort()
    throughput = ingest_rates[len(ingest_rates) // 2]
    return {
        "metric": "engine_lanes1024_ingest_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "proposals": p_count,
            "voter_lanes": v_count,
            "network_type": "p2p",
            "votes_per_cycle": p_count * fill,
            "ingest_rates": [round(r, 1) for r in ingest_rates],
            "proposal_creation_rate": round(
                create_rates[len(create_rates) // 2], 1
            ),
            "platform": jax.devices()[0].platform,
        },
    }


def run_engine_config5(
    scopes: int = 256,
    proposals_per_scope: int = 128,
    v_count: int = 48,
    waves: int = 8,
    retain: bool = False,
) -> dict:
    """Engine-level config 5: mixed-scope streaming churn. Every wave
    registers 256 scopes' worth of fresh proposals (half gossipsub, half
    P2P scope configs), streams a shuffled mixed-scope vote batch through
    ingest_columnar_multi (one fused device pipeline, per-scope work =
    one table probe each), then deletes every scope — live-deployment
    session churn through the real service surface."""
    import jax

    from hashgraph_tpu import CreateProposalRequest, StubConsensusSigner
    from hashgraph_tpu import ScopeConfigBuilder
    from hashgraph_tpu.engine import TpuConsensusEngine

    rng = np.random.default_rng(29)
    now = 1_700_000_000
    p_count = scopes * proposals_per_scope
    engine = TpuConsensusEngine(
        StubConsensusSigner(b"\x01" * 20),
        capacity=p_count,
        voter_capacity=v_count,
        max_sessions_per_scope=proposals_per_scope + 1,
    )
    scope_names = [f"s{i}" for i in range(scopes)]

    def set_configs() -> None:
        # delete_scope drops the scope config with the sessions, so churn
        # waves must re-establish the mixed gossip/P2P split every wave.
        for i, scope in enumerate(scope_names):
            builder = ScopeConfigBuilder()
            builder = (
                builder.p2p_preset() if i % 2 else builder.gossipsub_preset()
            )
            engine.set_scope_config(scope, builder.build())

    owners = [bytes([1 + i]) * 20 for i in range(v_count)]
    requests = [
        CreateProposalRequest(
            name="p",
            payload=b"",
            proposal_owner=b"o",
            expected_voters_count=v_count,
            expiration_timestamp=10_000,
            liveness_criteria_yes=bool(rng.integers(2)),
        )
        for _ in range(proposals_per_scope)
    ]

    def run_wave(wave: int) -> tuple[int, int]:
        """Returns (votes_applied, proposals_registered)."""
        set_configs()
        # Re-intern per wave: the end-of-wave delete_scope sweep evicts
        # every gid (refcounted registry), so carrying gids across waves
        # would measure the EMPTY_VOTE_OWNER rejection path, not churn
        # (the every-wave applied-fraction assert below enforces this).
        gids = np.array([engine.voter_gid(o) for o in owners], np.int64)
        # One cross-scope allocate dispatch for the whole wave's population.
        batches = engine.create_proposals_multi(
            [(scope, requests) for scope in scope_names], now
        )
        all_pids = []
        scope_of = []
        for k, proposals in enumerate(batches):
            all_pids.extend(p.proposal_id for p in proposals)
            scope_of.extend([k] * len(proposals))
        pids = np.array(all_pids, np.int64)
        sidx = np.array(scope_of, np.int64)
        # 70% participation, proposal-major arrival order, scope-shuffled
        # at proposal granularity (within-proposal order must hold).
        present = int(v_count * 0.7)
        order = rng.permutation(p_count)
        col_pids = np.repeat(pids[order], present)
        col_sidx = np.repeat(sidx[order], present)
        col_gids = np.tile(gids[:present], p_count)
        col_vals = rng.random(p_count * present) < 0.55
        wire = None
        if retain:
            # Synthetic fixed-width vote bytes: retention stores verbatim
            # bytes without decoding (decode happens on export), so dummy
            # payloads price the retention machinery itself.
            width = 72
            wire = (
                np.zeros(p_count * present * width, np.uint8),
                np.arange(p_count * present + 1, dtype=np.int64) * width,
            )
        statuses = engine.ingest_columnar_multi(
            scope_names, col_sidx, col_pids, col_gids, col_vals, now,
            wire_votes=wire,
        )
        # Correctness gate on EVERY wave: a resolution or identity
        # regression must fail the bench, not get timed as throughput.
        # P2P round-cap overruns (24) and their followups (19) are
        # legitimate in this mixed workload; what must never appear is
        # an unresolved session (20) or a rejected voter identity (10),
        # and the bulk must apply.
        assert int(np.sum(statuses == 20)) == 0, "unresolved proposal ids"
        assert int(np.sum(statuses == 10)) == 0, "stale voter gids"
        applied = int(np.sum((statuses == 0) | (statuses == 28)))
        assert applied >= int(0.9 * len(statuses)), (applied, len(statuses))
        votes = len(statuses)
        engine.delete_scopes(scope_names)  # one release dispatch, not 256
        return votes, p_count

    run_wave(-1)  # warmup/compile
    total_votes = total_proposals = 0
    start = time.perf_counter()
    for wave in range(waves):
        votes, registered = run_wave(wave)
        total_votes += votes
        total_proposals += registered
    elapsed = time.perf_counter() - start
    throughput = total_votes / elapsed
    return {
        "metric": "engine_mixed_scope_churn_throughput"
        + ("_retained" if retain else ""),
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "scopes": scopes,
            "proposals_per_wave": p_count,
            "waves": waves,
            "proposals_churned": total_proposals,
            "votes": total_votes,
            "seconds": round(elapsed, 3),
            "proposals_per_sec": round(total_proposals / elapsed, 1),
            "platform": jax.devices()[0].platform,
        },
    }


def run_lanes1024(p_count: int = 12_288, v_count: int = 1024) -> dict:
    """1024-voter-lane pool run: ~the per-chip slice of 100k concurrent
    1024-voter proposals on a v5e-8 (BASELINE north-star shape)."""
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import required_votes_np

    rng = np.random.default_rng(3)
    now = 1_700_000_000
    pool = ProposalPool(p_count, v_count)
    L = 8
    fill = 672  # most of the ceil(2n/3)=683 quorum, no mid-stream decisions

    def allocate(cycle: int) -> None:
        pool.allocate_batch(
            keys=[(cycle, i) for i in range(p_count)],
            n=np.full(p_count, v_count),
            req=required_votes_np(np.full(p_count, v_count), 2.0 / 3.0),
            cap=np.full(p_count, 2),
            gossip=np.ones(p_count, bool),
            liveness=np.ones(p_count, bool),
            expiry=np.full(p_count, now + 10_000),
            created_at=np.full(p_count, now),
        )

    def run_cycle() -> int:
        pendings = []
        votes = 0
        for base in range(0, fill, L):
            slots = np.repeat(np.arange(p_count, dtype=np.int64), L)
            lanes = np.tile(np.arange(base, base + L, dtype=np.int32), p_count)
            values = rng.random(p_count * L) < 0.5
            pendings.append(pool.ingest_async(slots, lanes, values, now))
            votes += p_count * L
            if len(pendings) >= 16:
                pool.complete_all(pendings)
                pendings = []
        if pendings:
            pool.complete_all(pendings)
        return votes

    allocate(0)
    run_cycle()  # warmup/compile
    rates = []
    for cycle in range(1, 4):
        pool.release(list(range(p_count)))
        allocate(cycle)
        start = time.perf_counter()
        votes = run_cycle()
        rates.append(votes / (time.perf_counter() - start))
    rates.sort()
    throughput = rates[len(rates) // 2]
    return {
        "metric": "lanes1024_ingest_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "proposals": p_count,
            "voter_lanes": v_count,
            "votes_per_cycle": p_count * fill,
            "platform": jax.devices()[0].platform,
        },
    }


def run_crypto(count: int = 4096) -> dict:
    """Host crypto stage: native threaded ECDSA recover throughput
    (the reference's dominant validate_vote cost,
    /root/reference/src/utils.rs:150-158)."""
    from hashgraph_tpu import EthereumConsensusSigner
    from hashgraph_tpu import native

    signers = [EthereumConsensusSigner.random() for _ in range(8)]
    payloads = [b"vote-payload-%d" % i for i in range(count)]
    t0 = time.perf_counter()
    sigs = [signers[i % 8].sign(p) for i, p in enumerate(payloads)]
    sign_rate = count / (time.perf_counter() - t0)
    idents = [signers[i % 8].identity() for i in range(count)]
    # Warmup (thread pool spinup) then timed run.
    EthereumConsensusSigner.verify_batch(idents[:64], payloads[:64], sigs[:64])
    t0 = time.perf_counter()
    verdicts = EthereumConsensusSigner.verify_batch(idents, payloads, sigs)
    verify_rate = count / (time.perf_counter() - t0)
    assert all(v is True for v in verdicts)
    return {
        "metric": "ecdsa_verify_throughput",
        "value": round(verify_rate, 1),
        "unit": "sigs/sec",
        "vs_baseline": None,
        "detail": {
            "signatures": count,
            "sign_rate": round(sign_rate, 1),
            "native_runtime": native.available(),
        },
    }


def run_validated(p_count: int = 1024, v_count: int = 16) -> dict:
    """End-to-end validated ingest: real EIP-191 ECDSA signatures through
    host validation (structural checks + hash recompute + native batched
    recover) into the columnar device path — the full
    process_incoming_vote pipeline at batch scale, nothing pre-validated.
    """
    from hashgraph_tpu import (
        CreateProposalRequest,
        EthereumConsensusSigner,
        StubConsensusSigner,
    )
    from hashgraph_tpu.engine import TpuConsensusEngine
    from hashgraph_tpu.protocol import compute_vote_hash
    from hashgraph_tpu.wire import Vote

    now = 1_700_000_000
    engine = TpuConsensusEngine(
        EthereumConsensusSigner.random(),
        capacity=p_count,
        voter_capacity=v_count,
        max_sessions_per_scope=p_count + 1,
    )
    engine.scope("s").with_threshold(1.0).initialize()
    # Device-path warmup on a throwaway scope (same grid shapes) so the
    # reported host/device split is steady-state, not compile time.
    warm = engine.create_proposals(
        "warm",
        [
            CreateProposalRequest(
                name="w",
                payload=b"",
                proposal_owner=b"o",
                expected_voters_count=v_count,
                expiration_timestamp=10_000,
                liveness_criteria_yes=True,
            )
            for _ in range(p_count)
        ],
        now,
    )
    warm_gids = np.array(
        [engine.voter_gid(bytes([1 + i]) * 20) for i in range(v_count)], np.int64
    )
    engine.ingest_columnar(
        "warm",
        np.tile(np.fromiter((p.proposal_id for p in warm), np.int64, p_count), v_count),
        np.repeat(warm_gids, p_count),
        np.zeros(p_count * v_count, bool),
        now,
    )
    engine.delete_scope("warm")

    proposals = engine.create_proposals(
        "s",
        [
            CreateProposalRequest(
                name="p",
                payload=b"",
                proposal_owner=b"o",
                expected_voters_count=v_count,
                expiration_timestamp=10_000,
                liveness_criteria_yes=True,
            )
            for _ in range(p_count)
        ],
        now,
    )
    signers = [EthereumConsensusSigner.random() for _ in range(v_count)]
    votes: list[Vote] = []
    for lane, signer in enumerate(signers):
        for p in proposals:
            vote = Vote(
                vote_id=lane + 1,
                vote_owner=signer.identity(),
                proposal_id=p.proposal_id,
                timestamp=now,
                vote=bool(lane % 2),
                parent_hash=b"",
                received_hash=b"",
                vote_hash=b"",
                signature=b"",
            )
            vote.vote_hash = compute_vote_hash(vote)
            vote.signature = signer.sign(vote.signing_payload())
            votes.append(vote)

    total = len(votes)
    gids = np.fromiter(
        (engine.voter_gid(v.vote_owner) for v in votes), np.int64, total
    )
    pids = np.fromiter((v.proposal_id for v in votes), np.int64, total)
    vals = np.fromiter((v.vote for v in votes), bool, total)

    start = time.perf_counter()
    # Host validation stage (reference: src/utils.rs:127-171 order):
    # structural + hash equality + batched signature recovery.
    hashes = [compute_vote_hash(v) for v in votes]
    hash_ok = all(h == v.vote_hash for h, v in zip(hashes, votes))
    t_hash = time.perf_counter()
    verdicts = EthereumConsensusSigner.verify_batch(
        [v.vote_owner for v in votes],
        [v.signing_payload() for v in votes],
        [v.signature for v in votes],
    )
    sig_ok = all(v is True for v in verdicts)
    t_verify = time.perf_counter()
    statuses = engine.ingest_columnar("s", pids, gids, vals, now)
    t_ingest = time.perf_counter()
    assert hash_ok and sig_ok
    assert int(np.sum(statuses == 0)) == total
    elapsed = t_ingest - start
    return {
        "metric": "validated_ingest_throughput",
        "value": round(total / elapsed, 1),
        "unit": "votes/sec",
        "vs_baseline": round(total / elapsed / 1_000_000, 4),
        "detail": {
            "votes": total,
            "hash_seconds": round(t_hash - start, 3),
            "verify_seconds": round(t_verify - t_hash, 3),
            "device_ingest_seconds": round(t_ingest - t_verify, 3),
            "host_share_pct": round(100 * (t_verify - start) / elapsed, 1),
        },
    }


def _sweep_build_batches(engine, scope, waves, p_count, v_count, signers,
                         batch_size, now):
    """Untimed setup for one cold validated rep: create fresh proposals,
    sign every vote (the sender's cost, excluded from ingest timing, as
    in run_validated), and pre-slice the columnar id arrays. Returns a
    list of (votes, pids, gids, vals) pipeline batches."""
    from hashgraph_tpu import CreateProposalRequest
    from hashgraph_tpu.protocol import compute_vote_hash
    from hashgraph_tpu.wire import Vote

    engine.scope(scope).with_threshold(1.0).initialize()
    votes: list[Vote] = []
    for _ in range(waves):
        proposals = engine.create_proposals(
            scope,
            [
                CreateProposalRequest(
                    name="p",
                    payload=b"",
                    proposal_owner=b"o",
                    expected_voters_count=v_count,
                    expiration_timestamp=10_000,
                    liveness_criteria_yes=True,
                )
                for _ in range(p_count)
            ],
            now,
        )
        for lane, signer in enumerate(signers):
            ident = signer.identity()
            for p in proposals:
                vote = Vote(
                    vote_id=lane + 1,
                    vote_owner=ident,
                    proposal_id=p.proposal_id,
                    timestamp=now,
                    vote=bool(lane % 2),
                    parent_hash=b"",
                    received_hash=b"",
                    vote_hash=b"",
                    signature=b"",
                )
                vote.vote_hash = compute_vote_hash(vote)
                vote.signature = signer.sign(vote.signing_payload())
                votes.append(vote)
    batches = []
    for lo in range(0, len(votes), batch_size):
        chunk = votes[lo : lo + batch_size]
        n = len(chunk)
        pids = np.fromiter((v.proposal_id for v in chunk), np.int64, n)
        gids = np.fromiter(
            (engine.voter_gid(v.vote_owner) for v in chunk), np.int64, n
        )
        vals = np.fromiter((v.vote for v in chunk), bool, n)
        batches.append((chunk, pids, gids, vals))
    return batches


def _sweep_timed_rep(engine, scope, batches, now, pipelined, scheme) -> dict:
    """ONE timed cold rep over pre-built batches: full host validation
    (hash recompute + equality + batched signature verify) feeding the
    columnar device path — run_validated's flow, restructured as
    double-buffered stages when ``pipelined`` (crypto for batch k+1 runs
    on the verify pool while batch k ingests on device)."""
    from hashgraph_tpu.protocol import compute_vote_hash

    total = sum(len(b[0]) for b in batches)
    applied = 0
    all_valid = True
    start = time.perf_counter()
    if pipelined:
        prev = None
        for batch in [*batches, None]:
            pend = (
                engine.verify_votes_async(batch[0])
                if batch is not None
                else None
            )
            if prev is not None:
                (votes, pids, gids, vals), prev_pend = prev
                verdicts, hashes = prev_pend.collect()
                all_valid &= all(v is True for v in verdicts)
                all_valid &= all(
                    h == v.vote_hash for h, v in zip(hashes, votes)
                )
                statuses = engine.ingest_columnar(scope, pids, gids, vals, now)
                applied += int(np.sum(statuses == 0))
            prev = (batch, pend) if batch is not None else None
    else:
        for votes, pids, gids, vals in batches:
            hashes = [compute_vote_hash(v) for v in votes]
            all_valid &= all(h == v.vote_hash for h, v in zip(hashes, votes))
            verdicts = scheme.verify_batch(
                [v.vote_owner for v in votes],
                [v.signing_payload() for v in votes],
                [v.signature for v in votes],
            )
            all_valid &= all(v is True for v in verdicts)
            statuses = engine.ingest_columnar(scope, pids, gids, vals, now)
            applied += int(np.sum(statuses == 0))
    elapsed = time.perf_counter() - start
    assert all_valid, "cold sweep produced an invalid verdict"
    assert applied == total, f"applied {applied} of {total}"
    return {"votes": total, "seconds": round(elapsed, 3),
            "votes_per_sec": round(total / elapsed, 1)}


def run_validated_sweep(p_count: int = 256, v_count: int = 64) -> dict:
    """Cold validated ingest sweep: batch-size × scheme × pool-threads,
    sequential vs pipelined, every vote carrying a REAL signature that is
    hashed and verified in the timed window (nothing cached, nothing
    redelivered — the admission cache cannot help cold traffic, so the
    sweep engines run verify_cache=None, today's uncached flow).

    Headline: Ed25519 batch-verified + pipelined throughput. Paired
    same-window A/B (ROADMAP 5b): the baseline arm re-measures BENCH_r05's
    exact validated flow (ECDSA, sequential) interleaved rep-for-rep with
    the headline arm inside ONE window, with a fixed-size host-crypto
    control (native ECDSA verify, the `crypto` metric's workload) timed
    between reps as a weather normalizer. The machine-readable
    ``noise_verdict`` refuses the claim unless the arms separate by more
    than the window's own spread — a speedup inside BENCHMARKS.md's
    documented ~26% weather band must not pass."""
    import os

    from hashgraph_tpu import Ed25519ConsensusSigner, EthereumConsensusSigner
    from hashgraph_tpu import native
    from hashgraph_tpu.engine import TpuConsensusEngine

    now = 1_700_000_000
    cores = os.cpu_count() or 1
    rng_scope = iter(range(10_000))

    def fresh_engine(scheme_cls, capacity):
        return TpuConsensusEngine(
            scheme_cls.random(),
            capacity=capacity,
            voter_capacity=v_count,
            max_sessions_per_scope=capacity + 1,
            verify_cache=None,
        )

    def run_cell(scheme_cls, waves, batch_size, pool_threads, pipelined,
                 warm=True) -> dict:
        if native.available():
            native.pool_configure(pool_threads)
        scheme_name = scheme_cls.__name__.replace("ConsensusSigner", "").lower()
        engine = fresh_engine(scheme_cls, waves * p_count + 8)
        scope = f"sweep-{next(rng_scope)}"
        signers = [scheme_cls.random() for _ in range(v_count)]
        batches = _sweep_build_batches(
            engine, scope, waves, p_count, v_count, signers, batch_size, now
        )
        if warm:
            # Columnar-path warmup at the same grid shapes (compile time
            # must not be billed to the first batch): a throwaway wave.
            warm_scope = f"warm-{next(rng_scope)}"
            warm_signers = [scheme_cls.random() for _ in range(v_count)]
            warm_batches = _sweep_build_batches(
                engine, warm_scope, 1, p_count, v_count, warm_signers,
                batch_size, now,
            )
            _sweep_timed_rep(engine, warm_scope, warm_batches, now,
                             pipelined, scheme_cls)
            engine.delete_scope(warm_scope)
        rep = _sweep_timed_rep(engine, scope, batches, now, pipelined,
                               scheme_cls)
        engine.delete_scope(scope)
        rep.update(
            scheme=scheme_name,
            batch_size=batch_size,
            pool_threads=pool_threads,
            mode="pipelined" if pipelined else "sequential",
        )
        return rep

    # ── Host-crypto control: fixed native ECDSA workload (the `crypto`
    # metric), timed between A/B reps as the weather normalizer. ──
    ctl_signers = [EthereumConsensusSigner.random() for _ in range(8)]
    ctl_payloads = [b"ctl-%d" % i for i in range(1024)]
    ctl_sigs = [ctl_signers[i % 8].sign(p) for i, p in enumerate(ctl_payloads)]
    ctl_ids = [ctl_signers[i % 8].identity() for i in range(1024)]
    EthereumConsensusSigner.verify_batch(ctl_ids[:64], ctl_payloads[:64],
                                         ctl_sigs[:64])  # pool warmup

    def control_rate() -> float:
        t0 = time.perf_counter()
        verdicts = EthereumConsensusSigner.verify_batch(
            ctl_ids, ctl_payloads, ctl_sigs
        )
        assert all(v is True for v in verdicts)
        return round(1024 / (time.perf_counter() - t0), 1)

    # ── Sweep cells (single rep each; the A/B below carries the noise
    # statistics for the headline claim). ──
    sweep: list[dict] = []
    for batch_size in (4096, 16384):
        for pool_threads in (1, 0):
            sweep.append(
                run_cell(Ed25519ConsensusSigner, 2, batch_size,
                         pool_threads, True)
            )
    sweep.append(run_cell(Ed25519ConsensusSigner, 2, 16384, 0, False))
    sweep.append(run_cell(EthereumConsensusSigner, 1, 16384, 0, True))

    # ── Paired same-window A/B: headline arm (Ed25519 batch, pipelined)
    # interleaved with the BENCH_r05 baseline arm (ECDSA, sequential),
    # control timed around every rep. ──
    if native.available():
        native.pool_configure(0)
    headline_reps: list[float] = []
    baseline_reps: list[float] = []
    controls: list[float] = []
    controls.append(control_rate())
    for _ in range(3):
        rep = run_cell(Ed25519ConsensusSigner, 8, 16384, 0, True, warm=False)
        headline_reps.append(rep["votes_per_sec"])
        controls.append(control_rate())
        rep = run_cell(EthereumConsensusSigner, 1, 16384, 0, False,
                       warm=False)
        baseline_reps.append(rep["votes_per_sec"])
        controls.append(control_rate())

    headline = sorted(headline_reps)[1]
    baseline = sorted(baseline_reps)[1]
    speedup = round(headline / baseline, 2)
    max_spread = max(
        spread_pct(headline_reps),
        spread_pct(baseline_reps),
        spread_pct(controls),
    )
    # The claim must clear the window's own weather: the slowest headline
    # rep has to beat the fastest baseline rep, and the speedup has to
    # exceed twice the worst observed spread.
    separated = min(headline_reps) > max(baseline_reps)
    outside_noise = speedup > 1.0 + 2.0 * max_spread / 100.0
    noise_verdict = {
        "pass": bool(separated and outside_noise),
        "criterion": (
            "min(headline reps) > max(baseline reps) AND "
            "speedup > 1 + 2*max_spread"
        ),
        "headline_votes_per_sec": headline,
        "baseline_votes_per_sec": baseline,
        "speedup": speedup,
        "vs_bench_r05_8632": round(headline / 8632.5, 2),
        "headline_reps": headline_reps,
        "baseline_reps": baseline_reps,
        "control_sigs_per_sec": controls,
        "spread_pct": {
            "headline": spread_pct(headline_reps),
            "baseline": spread_pct(baseline_reps),
            "control": spread_pct(controls),
        },
    }
    # Device-vs-host-pool verify arm (ROADMAP item 2): the same paired
    # same-window A/B discipline, batch sizes 256/1k/4k/16k, per-phase
    # device timings, winner named honestly (on CPU backends the native
    # pool wins; the wall-clock budget skips — and records — sizes the
    # backend cannot afford).
    device_arm = run_device_verify()

    return {
        "metric": "cold_validated_ingest_throughput",
        "value": headline,
        "unit": "votes/sec",
        "vs_baseline": round(headline / 8632.5, 2),
        "detail": {
            "cores": cores,
            "native_runtime": native.available(),
            "pool_size": native.pool_size(),
            "scheme_headline": "ed25519 (randomized-linear-combination "
                               "batch verify, pipelined)",
            "sweep": sweep,
            "noise_verdict": noise_verdict,
            "device_verify": device_arm,
        },
    }


def run_device_verify(smoke: bool = False, budget_seconds: float = 45.0) -> dict:
    """Device-vs-host-pool Ed25519 batch verify: paired same-window A/B.

    Arms verify the SAME signed corpus through the same
    ``verify_batch`` contract — ``Ed25519DeviceConsensusSigner`` (the
    JAX pipeline: decompression, vectorized SHA-512, Straus MSM) vs
    ``Ed25519ConsensusSigner`` (the native verify pool, or the
    pure-Python twin without the runtime) — interleaved rep for rep at
    batch sizes 256/1k/4k/16k. Each size reports both medians, the
    device pipeline's per-phase seconds (decompress / hash / MSM from
    the backend's own clocks), and a machine-readable ``noise_verdict``
    that names the WINNER honestly: on the CPU backend the device arm
    is expected to lose to the native pool by orders of magnitude (the
    u32-limb field core exists for accelerators, not host cores), and
    the verdict says so rather than hiding the direction. A wall-clock
    budget bounds every size at the warm rep: a blown warm rep skips
    later sizes outright and degrades the FIRST size to one timed rep
    per arm (at least one paired cell always ships, flagged as
    degraded) — skips are recorded, not silent."""
    from hashgraph_tpu import crypto_device, native
    from hashgraph_tpu.signing import (
        Ed25519ConsensusSigner,
        Ed25519DeviceConsensusSigner,
    )

    if not crypto_device.available():
        return {
            "metric": "device_verify_throughput",
            "value": 0.0,
            "unit": "sigs/sec",
            "detail": {"skipped": "crypto_device backend unavailable"},
        }
    import jax

    platform = jax.devices()[0].platform
    sizes = (256, 1024) if smoke else (256, 1024, 4096, 16384)
    reps = 2 if smoke else 3
    if native.available():
        native.pool_configure(0)  # affinity-sized: the pool's best foot

    # One shared corpus (vote-sized payloads, real signatures), sliced
    # per batch size so both arms always see identical bytes.
    signers = [Ed25519ConsensusSigner.random() for _ in range(64)]
    top = max(sizes)
    payloads = [b"device-verify-%6d:" % i + b"p" * 73 for i in range(top)]
    idents = [signers[i % 64].identity() for i in range(top)]
    sigs = [signers[i % 64].sign(p) for i, p in enumerate(payloads)]

    def time_arm(scheme_cls, n: int) -> float:
        t0 = time.perf_counter()
        verdicts = scheme_cls.verify_batch(
            idents[:n], payloads[:n], sigs[:n]
        )
        elapsed = time.perf_counter() - t0
        assert all(v is True for v in verdicts), "A/B corpus must verify"
        return elapsed

    cells: list[dict] = []
    skipped: list[dict] = []
    over_budget = False
    for n in sizes:
        if over_budget:
            skipped.append({
                "batch_size": n,
                "reason": "previous size exceeded the device budget; "
                          "honest skip instead of a stalled driver",
            })
            continue
        # Warm both arms at this shape (device: XLA compile for the
        # size's lane/block buckets; host: pool threads) off the clock.
        warm = time_arm(Ed25519DeviceConsensusSigner, n)
        time_arm(Ed25519ConsensusSigner, n)
        # The warm rep is the budget's first honest look at this size:
        # past it, skip (later sizes) or degrade to ONE timed rep per
        # arm (the smallest size — the sweep always emits at least one
        # paired cell, and a 1-rep cell says so in its verdict).
        size_reps = reps
        if warm > budget_seconds:
            over_budget = True
            if cells:
                skipped.append({
                    "batch_size": n,
                    "reason": "warm rep %.1fs exceeded the %.0fs budget"
                              % (warm, budget_seconds),
                })
                continue
            size_reps = 1
        device_reps: list[float] = []
        host_reps: list[float] = []
        for _ in range(size_reps):
            device_reps.append(time_arm(Ed25519DeviceConsensusSigner, n))
            host_reps.append(time_arm(Ed25519ConsensusSigner, n))
        phases = crypto_device.last_phase_seconds()
        dev = sorted(device_reps)[len(device_reps) // 2]
        host = sorted(host_reps)[len(host_reps) // 2]
        device_sps = round(n / dev, 1)
        host_sps = round(n / host, 1)
        device_faster = dev < host
        speedup = round((host / dev) if device_faster else (dev / host), 2)
        max_spread = max(spread_pct(device_reps), spread_pct(host_reps))
        separated = (
            max(device_reps) < min(host_reps)
            if device_faster
            else max(host_reps) < min(device_reps)
        )
        cells.append({
            "batch_size": n,
            "reps": size_reps,
            "budget_degraded_to_single_rep": size_reps < reps,
            "device_sigs_per_sec": device_sps,
            "host_pool_sigs_per_sec": host_sps,
            "device_phase_seconds": {
                k: round(v, 4) for k, v in phases.items()
            },
            "device_reps_seconds": [round(t, 4) for t in device_reps],
            "host_reps_seconds": [round(t, 4) for t in host_reps],
            "noise_verdict": {
                "winner": "device" if device_faster else "host_pool",
                "speedup": speedup,
                "pass": bool(
                    separated and speedup > 1.0 + 2.0 * max_spread / 100.0
                ),
                "criterion": (
                    "winner's every rep beats loser's every rep AND "
                    "speedup > 1 + 2*max_spread"
                ),
                "max_spread_pct": max_spread,
            },
        })
        if max(device_reps) + warm > budget_seconds:
            over_budget = True

    headline = cells[-1] if cells else {}
    return {
        "metric": "device_verify_throughput",
        "value": headline.get("device_sigs_per_sec", 0.0),
        "unit": "sigs/sec",
        "detail": {
            "platform": platform,
            "native_runtime": native.available(),
            "pool_size": native.pool_size(),
            "smoke": smoke,
            "cells": cells,
            "skipped_sizes": skipped,
            "honest_summary": (
                "device arm wins" if headline.get("noise_verdict", {}).get(
                    "winner") == "device"
                else "host pool wins on this backend — the device path "
                     "pays off on accelerator hardware, not host cores"
            ),
        },
    }


def run_config2(voters: int = 1024, repeats: int = 9) -> dict:
    """1 proposal × 1024 voters, P2P dynamic rounds: p50 finality latency.

    The P2P cap is ceil(2n/3) votes; a unanimous YES replay decides at
    req = ceil(2n/3) = 683 votes. The whole chain arrives as one dispatch
    (scan depth = 683), timing first-vote-to-decision wall clock.
    """
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import STATE_REACHED_YES, required_votes_np

    now = 1_700_000_000
    cap = (2 * voters + 2) // 3
    pool = ProposalPool(40, voters)  # headroom for the 32-chain slope below
    latencies = []
    for rep in range(repeats + 1):  # first is compile warmup
        pool.allocate_batch(
            keys=[(rep, 0)],
            n=np.array([voters]),
            req=required_votes_np(np.array([voters]), 2.0 / 3.0),
            cap=np.array([cap]),
            gossip=np.array([False]),
            liveness=np.array([True]),
            expiry=np.array([now + 1000]),
            created_at=np.array([now]),
        )
        slots = np.zeros(cap, np.int64)
        lanes = np.arange(cap, dtype=np.int32)
        values = np.ones(cap, bool)
        start = time.perf_counter()
        statuses, transitions = pool.ingest(slots, lanes, values, now)
        latency = time.perf_counter() - start
        assert transitions and transitions[0][1] == STATE_REACHED_YES
        if rep > 0:
            latencies.append(latency)
        pool.release([0])
    latencies.sort()
    p50 = latencies[len(latencies) // 2]

    # Decouple device execution from the link: K chained dispatches on
    # distinct slots pay the host<->device round-trip ONCE (async queue +
    # one blocking readback), so wall(K) ≈ link + K*device and the slope
    # (wall(K) - wall(1)) / (K - 1) is the on-device decision time. K=32
    # makes the slope signal (~tens of ms) far larger than the link's
    # same-day jitter band, and three paired samples are reported so the
    # spread is visible. On a tunneled TPU the p50 above is ~one link RTT
    # that directly-attached hardware does not pay; BASELINE's finality
    # metric wants the device-side figure.
    def chain_wall(n_chains: int, fresh: bool) -> float:
        slot_ids = pool.allocate_batch(
            keys=[("lat", i) for i in range(n_chains)],
            n=np.full(n_chains, voters),
            req=required_votes_np(np.full(n_chains, voters), 2.0 / 3.0),
            cap=np.full(n_chains, cap),
            gossip=np.zeros(n_chains, bool),
            liveness=np.ones(n_chains, bool),
            expiry=np.full(n_chains, now + 1000),
            created_at=np.full(n_chains, now),
        )
        lanes_l = np.arange(cap, dtype=np.int32)
        values_l = np.ones(cap, bool)
        t0 = time.perf_counter()
        if fresh:
            # The closed-form kernel the engine fast path dispatches:
            # whole chains, no sequential scan.
            pendings = [
                pool.ingest_async_grouped(
                    np.array([s], np.int64),
                    np.zeros(cap, np.int64),
                    np.arange(cap, dtype=np.int64),
                    cap,
                    lanes_l,
                    values_l,
                    now,
                    fresh=True,
                )
                for s in slot_ids
            ]
        else:
            pendings = [
                pool.ingest_async(
                    np.full(cap, s, np.int64), lanes_l, values_l, now
                )
                for s in slot_ids
            ]
        results = pool.complete_all(pendings)
        wall = time.perf_counter() - t0
        for _, transitions in results:
            assert transitions and transitions[0][1] == STATE_REACHED_YES
        pool.release(slot_ids)
        return wall

    K = 32

    def slope(fresh: bool) -> tuple[float, list[float]]:
        chain_wall(K, fresh)  # warmup (bucket + stack-kernel compiles)
        samples = []
        for _ in range(3):
            w1 = chain_wall(1, fresh)
            wk = chain_wall(K, fresh)
            samples.append(max(wk - w1, 0.0) / (K - 1) * 1000)
        return sorted(samples)[1], samples

    device_ms, samples_ms = slope(fresh=False)
    fresh_ms, fresh_samples = slope(fresh=True)
    return {
        "metric": "p2p_finality_latency_p50",
        "value": round(p50 * 1000, 3),
        "unit": "ms",
        "vs_baseline": None,
        "detail": {
            "voters": voters,
            "votes_to_quorum": cap,
            "latencies_ms": [round(l * 1000, 2) for l in latencies],
            "device_exec_ms_per_decision": round(device_ms, 3),
            "device_exec_samples_ms": [round(s, 3) for s in samples_ms],
            # Closed-form (scan-free) kernel — the engine fast path's
            # dispatch for fresh chains.
            "device_exec_fresh_ms_per_decision": round(fresh_ms, 3),
            "device_exec_fresh_samples_ms": [round(s, 3) for s in fresh_samples],
            "platform": jax.devices()[0].platform,
        },
    }


def run_config4(
    scopes: int = 256, proposals_per_scope: int = 1000, voters: int = 256
) -> dict:
    """Byzantine/absent liveness path: 30% of voters never vote; sessions
    finalize via the timeout sweep. Sharded over all available devices."""
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import (
        STATE_ACTIVE,
        required_votes_np,
    )
    from hashgraph_tpu.parallel import ShardedPool, consensus_mesh

    rng = np.random.default_rng(11)
    now = 1_700_000_000
    p_count = scopes * proposals_per_scope
    n_dev = len(jax.devices())
    if n_dev > 1:
        per_dev = -(-p_count // n_dev)
        pool = ShardedPool(per_dev, voters, consensus_mesh())
    else:
        pool = ProposalPool(p_count, voters)

    pool.allocate_batch(
        keys=[(f"s{i % scopes}", i) for i in range(p_count)],
        n=np.full(p_count, voters),
        req=required_votes_np(np.full(p_count, voters), 2.0 / 3.0),
        cap=np.full(p_count, 2),
        gossip=np.ones(p_count, bool),
        liveness=rng.random(p_count) < 0.5,
        expiry=np.full(p_count, now + 100),
        created_at=np.full(p_count, now),
    )

    # 70% participation, random yes/no, streamed in lane-rounds.
    present = int(voters * 0.7)
    slots = np.repeat(np.arange(p_count, dtype=np.int64), 8)
    start = time.perf_counter()
    total_votes = 0
    pendings = []
    for base_lane in range(0, present, 8):
        width = min(8, present - base_lane)
        sl = np.repeat(np.arange(p_count, dtype=np.int64), width)
        lanes = np.tile(
            np.arange(base_lane, base_lane + width, dtype=np.int32), p_count
        )
        values = rng.random(p_count * width) < 0.5
        pendings.append(pool.ingest_async(sl, lanes, values, now))
        total_votes += p_count * width
    pool.complete_all(pendings)
    # Liveness sweep finalizes everything still active.
    active = [s for s in range(p_count) if pool.state_of(s) == STATE_ACTIVE]
    swept = pool.timeout(active)
    elapsed = time.perf_counter() - start

    undecided = sum(1 for _, st in swept if st == STATE_ACTIVE)
    throughput = total_votes / elapsed
    return {
        "metric": "byzantine_timeout_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "scopes": scopes,
            "proposals": p_count,
            "voters": voters,
            "absent_pct": 30,
            "votes": total_votes,
            "timeout_decisions": len(swept),
            "undecided_after_sweep": undecided,
            "seconds": round(elapsed, 3),
            "devices": n_dev,
        },
    }


def run_engine_config4(
    scopes: int = 256, proposals_per_scope: int = 500, voters: int = 256
) -> dict:
    """Engine-level config 4: 256 scopes × 500 proposals × 256 voters, 30%
    absent, mixed liveness, finalized by the engine's timeout sweep — the
    Byzantine/absent path through the FULL service surface (registration,
    multi-scope columnar ingest, sweep with events), not the raw pool.
    (Half the BASELINE population by default to bound sweep wall time; the
    full 256×1000 shape runs at the same votes/sec — measured 0.48M/s
    end-to-end incl. compile, vs the raw pool's ~1M/s at that shape.)"""
    import jax

    from hashgraph_tpu import CreateProposalRequest, StubConsensusSigner
    from hashgraph_tpu.engine import TpuConsensusEngine

    rng = np.random.default_rng(17)
    now = 1_700_000_000
    p_count = scopes * proposals_per_scope
    engine = TpuConsensusEngine(
        StubConsensusSigner(b"\x01" * 20),
        capacity=p_count,
        voter_capacity=voters,
        max_sessions_per_scope=proposals_per_scope + 1,
    )
    present = int(voters * 0.7)
    owners = [
        bytes([1 + (i % 250), i // 250]) + b"\x00" * 18 for i in range(present)
    ]

    def requests_for(scope_idx: int) -> list[CreateProposalRequest]:
        return [
            CreateProposalRequest(
                name="p",
                payload=b"",
                proposal_owner=b"o",
                expected_voters_count=voters,
                expiration_timestamp=100,
                liveness_criteria_yes=bool((scope_idx + k) % 2),
            )
            for k in range(proposals_per_scope)
        ]

    def run_round(round_idx: int) -> dict:
        """One full registration -> ingest -> sweep pass. Round 0 is the
        compile warmup at the EXACT production shapes (allocate, ingest,
        timeout, readback-stack programs all compile there); the timed
        round measures steady-state service throughput — the same warmup
        discipline as the other engine benches' cycle 0 and the pool-level
        config4, which allocates before its clock starts."""
        scope_names = [f"r{round_idx}-s{i}" for i in range(scopes)]
        gids = np.array([engine.voter_gid(o) for o in owners], np.int64)
        start = time.perf_counter()
        batches = engine.create_proposals_multi(
            [(scope, requests_for(i)) for i, scope in enumerate(scope_names)],
            now,
        )
        t_create = time.perf_counter()

        pids = np.array(
            [p.proposal_id for batch in batches for p in batch], np.int64
        )
        sidx = np.repeat(np.arange(scopes, dtype=np.int64), proposals_per_scope)
        # Chunked by PROPOSAL block (each chunk carries all its proposals'
        # votes), bounding host memory and keeping lane resolution on the
        # vectorized fresh-assignment path.
        total_votes = 0
        chunk = max(1, p_count // 8)
        for base in range(0, p_count, chunk):
            sel = slice(base, min(base + chunk, p_count))
            n_sel = sel.stop - sel.start
            col_pids = np.repeat(pids[sel], present)
            col_sidx = np.repeat(sidx[sel], present)
            col_gids = np.tile(gids, n_sel)
            col_vals = rng.random(n_sel * present) < 0.5
            statuses = engine.ingest_columnar_multi(
                scope_names, col_sidx, col_pids, col_gids, col_vals, now
            )
            # Correctness gate on every round (see run_engine_config5): a
            # resolution or identity regression must fail the bench, not
            # get timed as throughput.
            assert int(np.sum(statuses == 20)) == 0, "unresolved proposal ids"
            assert int(np.sum(statuses == 10)) == 0, "stale voter gids"
            applied = int(np.sum((statuses == 0) | (statuses == 28)))
            assert applied >= int(0.9 * len(statuses)), (applied, len(statuses))
            total_votes += n_sel * present
        t_ingest = time.perf_counter()

        swept = engine.sweep_timeouts(now + 200)
        elapsed = time.perf_counter() - start
        return {
            "votes": total_votes,
            "seconds": elapsed,
            "create_seconds": t_create - start,
            "ingest_seconds": t_ingest - t_create,
            "sweep_seconds": elapsed - (t_ingest - start),
            "timeout_decisions": len(swept),
            "scope_names": scope_names,
        }

    warm = run_round(0)
    engine.delete_scopes(warm["scope_names"])
    timed = run_round(1)

    throughput = timed["votes"] / timed["seconds"]
    return {
        "metric": "engine_byzantine_timeout_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "scopes": scopes,
            "proposals": p_count,
            "voters": voters,
            "absent_pct": 30,
            "votes": timed["votes"],
            "create_seconds": round(timed["create_seconds"], 3),
            "ingest_seconds": round(timed["ingest_seconds"], 3),
            "sweep_seconds": round(timed["sweep_seconds"], 3),
            "timeout_decisions": timed["timeout_decisions"],
            "seconds": round(timed["seconds"], 3),
            "warmup_seconds": round(warm["seconds"], 3),
            "platform": jax.devices()[0].platform,
        },
    }


def run_config5(
    p_count: int = 65_536, v_count: int = 48, waves: int = 16
) -> dict:
    """Streaming mixed Gossipsub+P2P replay to 1M proposals: ``waves``
    arrival-ordered populations (16 × 65536 ≈ 1.05M) streamed through the
    pipelined ingest path, each wave recycling the pool like a live
    deployment churns sessions."""
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import required_votes_np

    rng = np.random.default_rng(23)
    now = 1_700_000_000
    pool = ProposalPool(p_count, v_count)
    all_slots = list(range(p_count))

    def allocate(wave: int) -> None:
        gossip = rng.random(p_count) < 0.5
        caps = np.where(gossip, 2, (2 * v_count + 2) // 3)
        pool.allocate_batch(
            keys=[(wave, i) for i in range(p_count)],
            n=np.full(p_count, v_count),
            req=required_votes_np(np.full(p_count, v_count), 2.0 / 3.0),
            cap=caps,
            gossip=gossip,
            liveness=rng.random(p_count) < 0.5,
            expiry=np.full(p_count, now + 10_000),
            created_at=np.full(p_count, now),
        )

    def stream_wave() -> int:
        # Rounds of one-vote-per-proposal through the full voter set:
        # gossip sessions decide once quorum lands (~vote 32 of 48), P2P
        # sessions hit their ceil(2n/3) caps, and later rounds exercise the
        # ALREADY_REACHED / SESSION_NOT_ACTIVE absorption paths — exactly
        # like a replayed gossip trace.
        votes = 0
        pendings = []
        slots = np.arange(p_count, dtype=np.int64)
        for r in range(v_count):
            lanes = np.full(p_count, r, np.int32)
            values = rng.random(p_count) < 0.55
            pendings.append(pool.ingest_async(slots, lanes, values, now))
            votes += p_count
            if len(pendings) >= 8:
                pool.complete_all(pendings)
                pendings = []
        if pendings:
            pool.complete_all(pendings)
        return votes

    allocate(0)
    stream_wave()  # warmup/compile wave (uncounted)
    total_votes = 0
    start = time.perf_counter()
    for wave in range(waves):
        pool.release(all_slots)
        allocate(wave + 1)
        total_votes += stream_wave()
    elapsed = time.perf_counter() - start

    counts = pool.state_counts()
    throughput = total_votes / elapsed
    return {
        "metric": "streaming_mixed_replay_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "proposals_replayed": p_count * waves,
            "pool_slots": p_count,
            "voters": v_count,
            "votes": total_votes,
            "seconds": round(elapsed, 3),
            "proposals_per_sec": round(p_count * waves / elapsed, 1),
            "final_wave_state_counts": {str(k): v for k, v in counts.items()},
            "platform": jax.devices()[0].platform,
        },
    }


def run_deepchain(
    p_count: int = 64, depth: int = 2048, reps: int = 3
) -> dict:
    """Deep-chain replay: 64 fresh sessions × 2048-vote chains, scan kernel
    vs closed-form kernel on identical batches. The scan pays `depth`
    sequential steps; the closed form is log-depth (cumsum + reductions) —
    this mode makes that design win directly measurable on hardware."""
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import required_votes_np

    rng = np.random.default_rng(41)
    now = 1_700_000_000
    waves = 8  # pipelined dispatches per timing: device work dominates RTT
    votes = p_count * depth * waves
    pool = ProposalPool(p_count * waves, depth)
    lanes = np.tile(np.arange(depth, dtype=np.int64), p_count)
    rows = np.repeat(np.arange(p_count, dtype=np.int64), depth)
    cols = np.tile(np.arange(depth, dtype=np.int64), p_count)
    vals = rng.random(p_count * depth) < 0.5  # threshold 1.0: never decides

    def run_once(fresh: bool) -> float:
        n_slots = p_count * waves
        slot_ids = pool.allocate_batch(
            keys=[("d", i) for i in range(n_slots)],
            n=np.full(n_slots, depth),
            req=required_votes_np(np.full(n_slots, depth), 1.0),
            cap=np.full(n_slots, depth + 1),
            gossip=np.zeros(n_slots, bool),
            liveness=np.ones(n_slots, bool),
            expiry=np.full(n_slots, now + 1000),
            created_at=np.full(n_slots, now),
        )
        groups = np.asarray(slot_ids, np.int64).reshape(waves, p_count)
        t0 = time.perf_counter()
        pendings = [
            pool.ingest_async_grouped(
                groups[w], rows, cols, depth, lanes, vals, now, fresh=fresh
            )
            for w in range(waves)
        ]
        results = pool.complete_all(pendings)
        dt = time.perf_counter() - t0
        for statuses, _ in results:
            assert int(np.sum(statuses == 0)) == p_count * depth
        pool.release(slot_ids)
        return dt

    for fresh in (False, True):
        run_once(fresh)  # compile warmup
    scan_s = sorted(run_once(False) for _ in range(reps))[reps // 2]
    fresh_s = sorted(run_once(True) for _ in range(reps))[reps // 2]
    return {
        "metric": "deepchain_fresh_vs_scan",
        "value": round(votes / fresh_s, 1),
        "unit": "votes/sec",
        "vs_baseline": round(votes / fresh_s / 1_000_000, 4),
        "detail": {
            "sessions": p_count,
            "chain_depth": depth,
            "votes": votes,
            "scan_seconds": round(scan_s, 3),
            "fresh_seconds": round(fresh_s, 3),
            "scan_votes_per_sec": round(votes / scan_s, 1),
            "speedup": round(scan_s / fresh_s, 2),
            "platform": jax.devices()[0].platform,
        },
    }


def run_redelivery(
    chain_len: int = 48,
    expected_voters: int = 64,
    redelivery_waves: int = 8,
) -> dict:
    """Amortized vote verification under gossip redelivery and incremental
    chain growth — the workload ISSUE 4 targets: the reference protocol
    gossips *growing vote chains*, so a chain of length L delivered one
    extension at a time costs O(L²) signature checks without memoization.
    Real EIP-191 ECDSA signatures throughout (the honest host-crypto-bound
    envelope, same convention as ``validated``).

    Three sub-workloads, each measured cache-on (engine default) vs
    cache-off (``verify_cache=None``):

    - ``growth``: a fresh receiver is handed the chain at every length
      1..L via ``process_incoming_proposal`` (session dropped between
      deliveries — the new-peer-per-delivery shape). Cache-off verifies
      L(L+1)/2 signatures; cache-on verifies L. This is the headline.
    - ``watermark``: the same growth delivered to ONE persistent session
      via ``deliver_proposals`` — the validated-chain watermark applies
      just the suffix, so even cache-off is O(L); shows the structural
      (non-cache) half of the amortization.
    - ``waves``: the full chain redelivered ``redelivery_waves`` times
      through ``ingest_votes`` (the embedder fallback pattern); duplicate
      rejection happens *after* admission validation, so cache-off pays
      waves×L ECDSA recovers.

    The headline ``value`` is cache-on growth throughput; ``speedup`` in
    detail is cache-off/cache-on wall time on that same workload.

    Sessions run on the HOST substrate (``expected_voters_count`` above
    the engine's lane capacity spills them, exactly the graceful-degrade
    path oversized proposals take): admission verification is a pure host
    stage, and on a tunneled TPU the per-delivery link RTT would otherwise
    swamp the quantity under test. The device ingest path is measured by
    the other modes; its cost is identical cache-on and cache-off.
    """
    from hashgraph_tpu import CreateProposalRequest, EthereumConsensusSigner
    from hashgraph_tpu.engine import TpuConsensusEngine

    now = 1_700_000_000
    L = chain_len

    def fresh_engine(cache) -> TpuConsensusEngine:
        engine = TpuConsensusEngine(
            EthereumConsensusSigner.random(),
            capacity=16,
            voter_capacity=16,  # < expected_voters: sessions host-spill
            verify_cache=cache,
        )
        engine.scope("s").with_threshold(1.0).initialize()
        return engine

    # One signed chain, reused verbatim by every mode/engine (the bytes a
    # gossip network would redeliver). threshold 1.0 with L < n keeps every
    # session undecided, so no wave short-circuits on ALREADY_REACHED
    # before validating.
    sender = fresh_engine(None)
    base = sender.create_proposal(
        "s",
        CreateProposalRequest(
            name="p",
            payload=b"",
            proposal_owner=b"o",
            expected_voters_count=expected_voters,
            expiration_timestamp=10_000,
            liveness_criteria_yes=True,
        ),
        now,
    )
    from hashgraph_tpu import build_vote

    signers = [EthereumConsensusSigner.random() for _ in range(L)]
    chain = base.clone()
    for k, signer in enumerate(signers):
        chain.votes.append(build_vote(chain, bool(k % 2), signer, now + 1 + k))
    grown = [chain.clone() for _ in range(L)]
    for k in range(L):
        grown[k].votes = [v.clone() for v in chain.votes[: k + 1]]

    def run_growth(engine) -> float:
        t0 = time.perf_counter()
        for k in range(L):
            engine.process_incoming_proposal("s", grown[k].clone(), now + 50)
            engine.delete_scope("s")
            engine.scope("s").with_threshold(1.0).initialize()
        return time.perf_counter() - t0

    def run_watermark(engine) -> float:
        t0 = time.perf_counter()
        for k in range(L):
            [code] = engine.deliver_proposals(
                [("s", grown[k].clone())], now + 50
            )
            assert code == 0, code
        return time.perf_counter() - t0

    def run_waves(engine) -> float:
        engine.process_incoming_proposal("s", grown[-1].clone(), now + 50)
        batch = [("s", v.clone()) for v in chain.votes]
        t0 = time.perf_counter()
        for _ in range(redelivery_waves):
            engine.ingest_votes(batch, now + 60)
        return time.perf_counter() - t0

    # Compile warmup: the pool kernels are module-level jits, so one
    # throwaway engine pass compiles every shape the timed runs dispatch.
    for fn in (run_growth, run_watermark, run_waves):
        fn(fresh_engine(None))

    growth_votes = L * (L + 1) // 2
    wave_votes = redelivery_waves * L
    t_growth_off = run_growth(fresh_engine(None))
    t_growth_on = run_growth(fresh_engine("default"))
    t_mark_off = run_watermark(fresh_engine(None))
    t_mark_on = run_watermark(fresh_engine("default"))
    t_waves_off = run_waves(fresh_engine(None))
    t_waves_on = run_waves(fresh_engine("default"))

    throughput = growth_votes / t_growth_on
    return {
        "metric": "redelivery_amortized_ingest_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": None,
        "detail": {
            "chain_len": L,
            "growth_votes_delivered": growth_votes,
            "speedup": round(t_growth_off / t_growth_on, 2),
            "growth_cached_votes_per_sec": round(growth_votes / t_growth_on, 1),
            "growth_uncached_votes_per_sec": round(
                growth_votes / t_growth_off, 1
            ),
            "watermark_speedup_vs_uncached_growth": round(
                t_growth_off / t_mark_on, 2
            ),
            "watermark_cached_votes_per_sec": round(
                growth_votes / t_mark_on, 1
            ),
            "watermark_uncached_votes_per_sec": round(
                growth_votes / t_mark_off, 1
            ),
            "waves": redelivery_waves,
            "waves_votes_redelivered": wave_votes,
            "waves_speedup": round(t_waves_off / t_waves_on, 2),
            "waves_cached_votes_per_sec": round(wave_votes / t_waves_on, 1),
            "waves_uncached_votes_per_sec": round(wave_votes / t_waves_off, 1),
        },
    }


def run_wal(
    p_count: int = 256,
    voters_per_proposal: int = 12,
    wave: int = 512,
    raw_records: int = 2_000,
) -> dict:
    """Durability subsystem overhead: WAL append throughput per fsync
    policy, engine vote-ingest bare vs DurableEngine-wrapped, and crash
    recovery replay rate. Host-only (filesystem + engine scalar surface);
    runs identically under JAX_PLATFORMS=cpu.

    The headline is the wrapped/bare ingest ratio at the "batch" policy —
    the number an embedder pays for durability on the hot path. "always"
    appends are fsync-bound and measured on a smaller count.
    """
    import os
    import tempfile

    from hashgraph_tpu import CreateProposalRequest, StubConsensusSigner, build_vote
    from hashgraph_tpu.engine import TpuConsensusEngine
    from hashgraph_tpu.wal import DurableEngine, WalWriter, replay
    from hashgraph_tpu.wal import format as WF

    def fresh_engine(identity: bytes) -> TpuConsensusEngine:
        return TpuConsensusEngine(
            StubConsensusSigner(identity),
            capacity=max(512, p_count * 2),
            voter_capacity=64,
        )

    now = 1_700_000_000
    identity = os.urandom(20)
    scope = "bench"

    # Workload: p_count proposals, each voted on by voters_per_proposal
    # distinct remote voters (pre-validated replay, same convention as the
    # BASELINE configs), delivered in waves through ingest_votes.
    requests = [
        CreateProposalRequest(
            name=f"b{i}",
            payload=b"x",
            proposal_owner=b"owner",
            expected_voters_count=voters_per_proposal + 1,
            expiration_timestamp=10_000,
            liveness_criteria_yes=True,
        )
        for i in range(p_count)
    ]
    signers = [StubConsensusSigner(os.urandom(20)) for _ in range(voters_per_proposal)]

    def build_workload(engine):
        proposals = engine.create_proposals(scope, requests, now)
        votes = [
            (scope, build_vote(p, True, s, now + 1))
            for p in proposals
            for s in signers
        ]
        return [votes[i : i + wave] for i in range(0, len(votes), wave)]

    def timed_ingest(engine):
        waves = build_workload(engine)
        total = sum(len(w) for w in waves)
        t0 = time.perf_counter()
        for batch in waves:
            engine.ingest_votes(batch, now + 2, pre_validated=True)
        return total, time.perf_counter() - t0

    # Warm the jit cache on a throwaway engine so neither timed side pays
    # first-call compilation (the workload shapes are identical).
    timed_ingest(fresh_engine(identity))

    detail = {}
    with tempfile.TemporaryDirectory() as root:
        # Raw append throughput per policy (vote-record-sized payloads).
        sample = build_vote(
            fresh_engine(identity).create_proposal(scope, requests[0], now),
            True,
            signers[0],
            now,
        ).encode()
        payload = WF.encode_votes(now, True, [(scope, sample)] * 4)
        for policy, count in (
            ("off", raw_records),
            ("batch", raw_records),
            ("always", max(64, raw_records // 20)),
        ):
            with WalWriter(
                os.path.join(root, f"raw-{policy}"), fsync_policy=policy
            ) as wal:
                t0 = time.perf_counter()
                for _ in range(count):
                    wal.append(WF.KIND_VOTES, payload)
                dt = time.perf_counter() - t0
            detail[f"append_{policy}_records_per_sec"] = round(count / dt)
            detail[f"append_{policy}_mb_per_sec"] = round(
                count * (len(payload) + WF.HEADER_BYTES + WF.BODY_LEAD_BYTES)
                / dt
                / 1e6,
                1,
            )

        # Engine ingest: bare vs wrapped (batch policy — the default).
        bare_votes, bare_dt = timed_ingest(fresh_engine(identity))
        wal_dir = os.path.join(root, "engine")
        durable = DurableEngine(
            fresh_engine(identity), wal_dir, fsync_policy="batch"
        )
        wrapped_votes, wrapped_dt = timed_ingest(durable)
        durable.close()
        bare_rate = bare_votes / bare_dt
        wrapped_rate = wrapped_votes / wrapped_dt
        detail["ingest_bare_votes_per_sec"] = round(bare_rate)
        detail["ingest_durable_votes_per_sec"] = round(wrapped_rate)

        # Recovery: replay the log just written into a fresh engine.
        recovered = fresh_engine(identity)
        t0 = time.perf_counter()
        stats = replay(wal_dir, recovered)
        dt = time.perf_counter() - t0
        detail["recover_records_per_sec"] = round(stats.records_applied / dt)
        detail["recover_votes_per_sec"] = round(stats.votes_replayed / dt)
        detail["recover_records"] = stats.records_applied

    return {
        "metric": "wal_durable_vs_bare_ingest",
        "value": round(wrapped_rate / bare_rate, 3),
        "unit": "ratio",
        "detail": detail,
    }


def run_catchup(
    history_votes: "tuple[int, ...]" = (256, 1024, 4096),
    v_count: int = 16,
    wave: int = 8,
    reps: int = 3,
    smoke: bool = False,
) -> dict:
    """State-sync catch-up: snapshot+tail vs full WAL replay, paired
    same-window A/B at several history lengths (ROADMAP 4 + 5b).

    A source peer on a real BridgeServer accumulates a signed vote
    history in gossip-sized waves (``wave`` votes per record — the
    realistic replay granularity: full replay re-verifies at that batch
    size, while the snapshot path verifies the whole history in ONE
    batched pool pass). Per history length, ``reps`` interleaved rep
    pairs each catch a FRESH joiner up twice over the wire:

    - **A (baseline)**: ``CatchUpClient.full_replay`` — stream the whole
      WAL, per-record validation (O(history) crypto);
    - **B (headline)**: ``CatchUpClient.catch_up`` — manifest + chunks
      (digest-checked), one batched chain/signature verify, atomic
      install, then tail the post-snapshot suffix. The source's snapshot
      is invalidated between reps (a sweep record moves the watermark)
      so every B rep pays the full snapshot build + transfer + verify,
      not a cached manifest.

    Every rep asserts byte-identical convergence
    (``sync.state_fingerprint`` equality of joiner vs source) before its
    time counts. The ``noise_verdict`` (at the largest history) refuses
    the claim unless the arms separate beyond the window's own spread,
    with a fixed host-crypto control timed between reps as the weather
    normalizer. Headline: catch-up seconds + verified votes/sec at the
    largest history.
    """
    import os
    import tempfile

    from hashgraph_tpu import build_vote
    from hashgraph_tpu import native
    from hashgraph_tpu.bridge.client import BridgeClient
    from hashgraph_tpu.bridge.server import BridgeServer
    from hashgraph_tpu.engine import TpuConsensusEngine
    from hashgraph_tpu.signing.ed25519 import Ed25519ConsensusSigner
    from hashgraph_tpu.sync import CatchUpClient, state_fingerprint
    from hashgraph_tpu.wire import Proposal

    if smoke:
        history_votes = (64,)
        reps = 2
    now = 1_700_000_000
    scheme = Ed25519ConsensusSigner

    # Host-crypto control: fixed batch-verify workload timed between A/B
    # reps — the shared-host weather normalizer (BENCHMARKS.md).
    ctl_signers = [scheme.random() for _ in range(8)]
    ctl_payloads = [b"ctl-%d" % i for i in range(1024)]
    ctl_sigs = [ctl_signers[i % 8].sign(p) for i, p in enumerate(ctl_payloads)]
    ctl_ids = [ctl_signers[i % 8].identity() for i in range(1024)]

    def control_rate() -> float:
        """Median of three back-to-back runs: one control point should
        track the window's crypto weather, not a single scheduler
        preemption (isolated runs show rare 2x dips on shared hosts)."""
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            verdicts = scheme.verify_batch(ctl_ids, ctl_payloads, ctl_sigs)
            assert all(v is True for v in verdicts)
            rates.append(1024 / (time.perf_counter() - t0))
        return round(sorted(rates)[1], 1)

    def fresh_joiner(capacity: int) -> TpuConsensusEngine:
        return TpuConsensusEngine(
            scheme.random(),
            capacity=capacity,
            voter_capacity=v_count + 2,
        )

    def build_history(client, peer, total: int) -> int:
        """Drive ``total`` chained signed votes (spread over
        total/v_count proposals) through the bridge; returns the
        proposal count."""
        p_count = max(1, total // v_count)
        signers = [scheme.random() for _ in range(v_count)]
        for p in range(p_count):
            # One scope per proposal: the history must be RETAINED (the
            # engine's per-scope session cap would otherwise evict early
            # sessions, shrinking the very state catch-up ships).
            scope = f"scope-{p}"
            pid, blob = client.create_proposal(
                peer, scope, now, f"p{p}", b"payload", v_count + 1, 3_600
            )
            proposal = Proposal.decode(blob)
            batch: list[bytes] = []
            for signer in signers:
                vote = build_vote(proposal, True, signer, now + 1)
                proposal.votes.append(vote)
                batch.append(vote.encode())
                if len(batch) == wave:
                    client.process_votes(peer, scope, batch, now + 1)
                    batch = []
            if batch:
                client.process_votes(peer, scope, batch, now + 1)
        return p_count

    lengths: list[dict] = []
    with tempfile.TemporaryDirectory() as root:
        for total in history_votes:
            server = BridgeServer(
                capacity=max(64, total // v_count + 8),
                voter_capacity=v_count + 2,
                wal_dir=os.path.join(root, f"wal-{total}"),
                wal_fsync="off",  # catch-up reads the log; fsync is not under test
                signer_factory=scheme,  # peers verify the Ed25519 votes
            )
            with server:
                host, port = server.address
                with BridgeClient(host, port) as client:
                    key = os.urandom(32)
                    peer, identity = client.add_peer(key)
                    p_count = build_history(client, peer, total)
                    source = server.durable_engine(identity)
                    src_fp = state_fingerprint(source)
                    capacity = max(64, p_count + 8)

                    # Untimed warmup pair: both arms' one-time costs (jit
                    # at these shapes, Ed25519 table builds, snapshot
                    # build) land here, not on the first timed rep.
                    with CatchUpClient(host, port, peer) as cu:
                        cu.full_replay(fresh_joiner(capacity))
                    with CatchUpClient(host, port, peer) as cu:
                        cu.catch_up(fresh_joiner(capacity))

                    a_seconds: list[float] = []
                    b_seconds: list[float] = []
                    b_votes_verified = 0
                    # Per-length control window: the verdict compares the
                    # weather DURING these reps, not across the whole
                    # sweep (earlier lengths' samples would inflate the
                    # spread without describing this window).
                    controls: list[float] = [control_rate()]
                    for _ in range(reps):
                        joiner = fresh_joiner(capacity)
                        with CatchUpClient(host, port, peer) as cu:
                            rep = cu.full_replay(joiner)
                        assert state_fingerprint(joiner) == src_fp, (
                            "full replay diverged"
                        )
                        a_seconds.append(rep.seconds)
                        controls.append(control_rate())

                        # Move the watermark so THIS rep's manifest is a
                        # fresh snapshot build, not a cached artifact.
                        source.sweep_timeouts(now + 2)
                        src_fp = state_fingerprint(source)
                        joiner = fresh_joiner(capacity)
                        with CatchUpClient(host, port, peer) as cu:
                            rep = cu.catch_up(joiner)
                        assert state_fingerprint(joiner) == src_fp, (
                            "snapshot+tail diverged"
                        )
                        b_seconds.append(rep.seconds)
                        b_votes_verified = rep.votes_verified + rep.tail_votes
                        controls.append(control_rate())

                    med_a = sorted(a_seconds)[len(a_seconds) // 2]
                    med_b = sorted(b_seconds)[len(b_seconds) // 2]
                    lengths.append({
                        "history_votes": total,
                        "proposals": p_count,
                        "wal_last_lsn": source.wal.last_lsn,
                        "replay_seconds": a_seconds,
                        "catchup_seconds": b_seconds,
                        "replay_votes_per_sec": round(total / med_a, 1),
                        "catchup_votes_per_sec": round(total / med_b, 1),
                        "votes_verified": b_votes_verified,
                        "speedup": round(med_a / med_b, 2),
                        "control_sigs_per_sec": controls,
                    })

    largest = lengths[-1]
    a_reps = largest["replay_seconds"]
    b_reps = largest["catchup_seconds"]
    controls = largest["control_sigs_per_sec"]
    med_a = sorted(a_reps)[len(a_reps) // 2]
    med_b = sorted(b_reps)[len(b_reps) // 2]
    speedup = round(med_a / med_b, 2)
    max_spread = max(spread_pct(a_reps), spread_pct(b_reps), spread_pct(controls))
    separated = max(b_reps) < min(a_reps)
    outside_noise = speedup > 1.0 + 2.0 * max_spread / 100.0
    noise_verdict = {
        "pass": bool(separated and outside_noise),
        "criterion": (
            "max(catchup reps) < min(replay reps) AND "
            "speedup > 1 + 2*max_spread (largest history)"
        ),
        "history_votes": largest["history_votes"],
        "catchup_seconds": med_b,
        "replay_seconds": med_a,
        "speedup": speedup,
        "catchup_reps": b_reps,
        "replay_reps": a_reps,
        "control_sigs_per_sec": controls,
        "spread_pct": {
            "catchup": spread_pct(b_reps),
            "replay": spread_pct(a_reps),
            "control": spread_pct(controls),
        },
    }
    return {
        "metric": "catchup_verified_votes_per_sec",
        "value": largest["catchup_votes_per_sec"],
        "unit": "votes/sec",
        "detail": {
            "scheme": "ed25519",
            "native_runtime": native.available(),
            "wave_votes_per_record": wave,
            "catchup_seconds_headline": med_b,
            "lengths": lengths,
            "noise_verdict": noise_verdict,
        },
    }


def run_chaos(smoke: bool = False, seeds: "list[int] | None" = None) -> dict:
    """Deterministic chaos harness: the full scenario corpus
    (hashgraph_tpu.sim) at pinned seeds, plus the blindness self-test.

    Every scenario must pass all four machine-checked verdicts —
    convergence (honest state-fingerprint equality), accountability
    (exactly the injected culprits convicted, offline-verifiable
    evidence, zero honest convictions), safety (no divergent honest
    decisions), liveness (decisions propagate everywhere within a fixed
    tick bound, zero honest peers left under a stale watchdog
    conviction) — and a run is a pure function of its seed, so a failure
    here is a deterministic regression, never a flake. ``--smoke`` is
    the CI shape (3 pinned seeds); the full mode adds two more. The
    ``scenarios: {passed, failed, seeds}`` block is the machine-readable
    summary downstream tooling keys on."""
    import time as _time

    from hashgraph_tpu.sim import SCENARIOS, run_corpus, run_scenario

    if seeds is None:
        seeds = [7, 99, 1234] if smoke else [7, 99, 1234, 31337, 777]
    t0 = _time.perf_counter()
    corpus = run_corpus(seeds)
    # The harness must be able to detect its own blindness: a run with
    # the evidence layer disabled HAS to fail accountability, or every
    # green corpus above is meaningless.
    blind = run_scenario("equivocator", seeds[0], blind=True)
    blind_ok = (
        not blind["passed"]
        and not blind["verdicts"]["accountability"]["ok"]
        and bool(blind["verdicts"]["accountability"]["missed_culprits"])
    )
    seconds = round(_time.perf_counter() - t0, 3)
    total = corpus["scenarios"]["passed"] + corpus["scenarios"]["failed"]
    # Gate hard, like every other smoke bench: a failed scenario or a
    # blindness self-test that passes (i.e. fails to fail) must exit the
    # runner non-zero or the CI job cannot hold the line. The assert
    # message names the (scenario, seed) pairs — each reproduces
    # byte-for-byte from its seed.
    assert not corpus["failures"], (
        "chaos scenarios FAILED (deterministic — rerun these seeds): "
        + ", ".join(
            f"{f['scenario']}@{f['seed']}" for f in corpus["failures"]
        )
    )
    assert blind_ok, (
        "blindness self-test failed: a run with the evidence layer "
        "disabled did NOT fail the accountability verdict — the harness "
        "cannot detect its own blindness"
    )
    return {
        "metric": "chaos_scenarios_passed",
        "value": corpus["scenarios"]["passed"],
        "unit": f"of {total} scenario-runs",
        "detail": {
            "scenarios": corpus["scenarios"],
            "corpus": sorted(SCENARIOS),
            "results": corpus["results"],
            "failures": corpus["failures"],
            "blind_selftest_detects_disabled_evidence": blind_ok,
            "seconds": seconds,
        },
    }


def run_liveness(smoke: bool = False, seeds: "list[int] | None" = None) -> dict:
    """Liveness battery: the three liveness scenarios at pinned seeds,
    each run TWICE per seed — the adaptive (φ-accrual) watchdog arm and
    a paired binary-floor-only baseline (``overrides={"phi_threshold":
    None}``, same seed, same traffic) — with the A/B claims hard-gated:

    - the adaptive arm SEES every flapping-links flap (``phi`` crosses
      the threshold on every survivor) while the static arm is blind to
      the identical silence (130 ticks, far under the 500 000-tick
      binary floor) — strictly more detections, same zero stale
      convictions after heal in BOTH arms;
    - slow-never-dead's counterfactual is the conviction half: a static
      bar tuned tight enough to catch that flap (the scenario computes
      ``phi_from_deviation`` for the equivalent deviation) WOULD convict
      the slow-but-alive peer (1 stale conviction) where the variance-
      aware φ keeps it healthy (0) — adaptive strictly fewer stale
      convictions under jitter;
    - stale-partial-synchrony closes the loop: when silence really does
      blow past every bound, BOTH detectors convict, and both clear
      after GST.

    Deterministic like run_chaos: every line reproduces byte-for-byte
    from its (scenario, seed) pair, so the asserts are regression gates,
    not weather reports."""
    import time as _time

    from hashgraph_tpu.sim import run_scenario

    if seeds is None:
        seeds = [7, 99, 1234] if smoke else [7, 99, 1234, 31337, 777]
    battery = ("flapping-links", "slow-never-dead", "stale-partial-synchrony")
    t0 = _time.perf_counter()
    results: dict = {}
    failures: list[str] = []
    adaptive_detections = 0
    static_detections = 0
    adaptive_stale = 0
    static_stale = 0
    counterfactual_static_convictions = 0
    for name in battery:
        for seed in seeds:
            run = run_scenario(name, seed)
            if not run["passed"]:
                failures.append(f"{name}@{seed}")
            entry = {
                "passed": run["passed"],
                "checks": run["checks"],
                "max_decide_ticks": run["verdicts"]["liveness"][
                    "max_decide_ticks"
                ],
                "stale_convictions": run["verdicts"]["liveness"][
                    "stale_convictions"
                ],
            }
            adaptive_stale += len(entry["stale_convictions"])
            if name == "flapping-links":
                # Paired baseline arm: identical seed + traffic, binary
                # silence floor only (phi_threshold=None). Its four
                # verdicts must STILL pass — the floor is a correct
                # detector, just a blind one at sub-floor silences.
                base = run_scenario(name, seed, overrides={"phi_threshold": None})
                # ``passed`` gates scenario CHECKS too, and the φ-
                # detection checks legitimately read False here — that
                # blindness IS the baseline. The bar for this arm is the
                # four verdicts.
                base_ok = all(v["ok"] for v in base["verdicts"].values())
                if not base_ok:
                    failures.append(f"{name}@{seed}(static-arm)")
                adaptive_detections += int(
                    run["checks"]["phi_suspected_during_flap"]
                )
                static_detections += int(
                    base["checks"]["phi_suspected_during_flap"]
                )
                static_stale += len(
                    base["verdicts"]["liveness"]["stale_convictions"]
                )
                entry["static_arm"] = {
                    "verdicts_ok": base_ok,
                    "checks": base["checks"],
                    "stale_convictions": base["verdicts"]["liveness"][
                        "stale_convictions"
                    ],
                }
            if name == "slow-never-dead":
                # The counterfactual static bar (tuned tight enough to
                # catch the flap) convicts the slow-but-alive peer; the
                # deployed φ detector does not.
                counterfactual_static_convictions += int(
                    run["checks"]["metronome_counterfactual_convicts"]
                )
            results[f"{name}@{seed}"] = entry
    seconds = round(_time.perf_counter() - t0, 3)
    assert not failures, (
        "liveness scenarios FAILED (deterministic — rerun these seeds): "
        + ", ".join(failures)
    )
    # A/B gates, all hard: adaptive sees every flap the static floor
    # misses, neither arm leaves a stale conviction after heal, and the
    # tight-static counterfactual convicts where φ does not.
    assert adaptive_detections == len(seeds) and static_detections == 0, (
        adaptive_detections,
        static_detections,
    )
    assert adaptive_stale == 0 and static_stale == 0, (
        adaptive_stale,
        static_stale,
    )
    assert counterfactual_static_convictions == len(seeds), (
        counterfactual_static_convictions
    )
    total = len(battery) * len(seeds)
    return {
        "metric": "liveness_scenarios_passed",
        "value": total - len(failures),
        "unit": f"of {total} scenario-runs",
        "detail": {
            "battery": list(battery),
            "seeds": seeds,
            "results": results,
            "ab": {
                "flap_detections": {
                    "adaptive": adaptive_detections,
                    "static_floor": static_detections,
                },
                "stale_convictions_after_heal": {
                    "adaptive": adaptive_stale,
                    "static_floor": static_stale,
                },
                "tight_static_counterfactual_convictions": (
                    counterfactual_static_convictions
                ),
                "adaptive_phi_convictions_same_traffic": 0,
            },
            "seconds": seconds,
        },
    }


def _rss_bytes() -> int:
    """Resident set size of this process (Linux /proc, no psutil dep)."""
    with open("/proc/self/status") as fh:
        for line in fh:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) * 1024
    raise RuntimeError("VmRSS not found in /proc/self/status")


def run_churn(
    smoke: bool = False,
    target_sessions: "int | None" = None,
    scopes: int = 16,
    per_scope: int = 256,
    v_count: int = 4,
) -> dict:
    """Tiered session-lifecycle churn: 10M+ CUMULATIVE sessions through a
    fixed-size engine under a HARD, asserted RSS + device-slot ceiling.

    Every wave creates ``scopes × per_scope`` fresh sessions
    (create_proposals_multi), decides them all with exactly the quorum's
    worth of columnar votes, advances the logical clock one tick, and
    runs the engine's ``sweep_timeouts`` — whose lifecycle hook demotes
    decided sessions to the serialized tier after ``demote_after`` ticks
    and garbage-collects them ``evict_decided_after`` ticks after their
    deciding activity. The working set (live sessions + tier population
    + RSS) is asserted bounded on EVERY wave, so the 10M headline is a
    held ceiling, not an observation.

    The throughput claim rides the repo's paired same-window A/B: the
    tiered lifecycle arm vs an untier'd arm running the identical
    create/vote traffic with the reference's only lifecycle
    (delete_scope after every wave), interleaved T/U within one window,
    with a machine-readable ``noise_verdict`` gating "steady-state
    ingest within 2x of the untier'd arm".
    """
    import jax

    from hashgraph_tpu import CreateProposalRequest, ScopeConfig, StubConsensusSigner
    from hashgraph_tpu.engine import TpuConsensusEngine
    from hashgraph_tpu.obs import slo_engine

    slo_engine.reset()

    now0 = 1_700_000_000
    wave_sessions = scopes * per_scope
    if target_sessions is None:
        target_sessions = 60_000 if smoke else 10_000_000
    demote_after, evict_after = 2.0, 4.0
    # Ceilings (hard asserts, not observations). Live: waves still inside
    # the demotion window plus the in-flight wave. Tier: waves between
    # demotion and GC. RSS: growth budget over the post-warmup baseline.
    live_ceiling = wave_sessions * (int(demote_after) + 2)
    tier_ceiling = wave_sessions * (int(evict_after - demote_after) + 2)
    capacity = live_ceiling  # the device-slot ceiling: pool cannot exceed it
    rss_budget = (512 if not smoke else 384) * 1024 * 1024
    scope_names = [f"s{i}" for i in range(scopes)]
    owners = [bytes([1 + i]) * 20 for i in range(v_count)]
    # Exactly the quorum's worth of YES votes per session (div_ceil(2n,3)
    # under the gossipsub default): every vote is an accept, every session
    # decides on its last vote — no ALREADY_REACHED extras in the timing.
    present = -(-2 * v_count // 3)
    requests = [
        CreateProposalRequest(
            name="p",
            payload=b"",
            proposal_owner=b"o",
            expected_voters_count=v_count,
            expiration_timestamp=now0 + 100_000_000,
            liveness_criteria_yes=True,
        )
        for _ in range(per_scope)
    ]

    def make_engine(tiered: bool) -> TpuConsensusEngine:
        engine = TpuConsensusEngine(
            StubConsensusSigner(b"\x01" * 20),
            capacity=capacity,
            voter_capacity=v_count,
            max_sessions_per_scope=live_ceiling + tier_ceiling,
        )
        config = ScopeConfig(
            demote_after=demote_after if tiered else None,
            evict_decided_after=evict_after if tiered else None,
        )
        for scope in scope_names:
            engine.set_scope_config(scope, config.clone())
        return engine

    def run_wave(engine, now: int, tiered: bool) -> int:
        """One churn wave; returns votes applied."""
        gids = np.array([engine.voter_gid(o) for o in owners], np.int64)
        batches = engine.create_proposals_multi(
            [(scope, requests) for scope in scope_names], now
        )
        all_pids = []
        scope_of = []
        for k, proposals in enumerate(batches):
            all_pids.extend(p.proposal_id for p in proposals)
            scope_of.extend([k] * len(proposals))
        pids = np.array(all_pids, np.int64)
        sidx = np.array(scope_of, np.int64)
        col_pids = np.repeat(pids, present)
        col_sidx = np.repeat(sidx, present)
        col_gids = np.tile(gids[:present], wave_sessions)
        col_vals = np.ones(wave_sessions * present, bool)
        statuses = engine.ingest_columnar_multi(
            scope_names, col_sidx, col_pids, col_gids, col_vals, now
        )
        # Correctness gate every wave: an unresolved session (20) or a
        # stale voter identity (10) is a lifecycle bug, not throughput.
        assert int(np.sum(statuses != 0)) == 0, (
            "churn wave rejected votes: "
            + str(np.unique(statuses[statuses != 0]))
        )
        if tiered:
            engine.sweep_timeouts(now)  # lifecycle hook: demote + GC
        else:
            engine.delete_scopes(scope_names)  # the reference's lifecycle
            config = ScopeConfig()
            for scope in scope_names:
                engine.set_scope_config(scope, config.clone())
        return len(statuses)

    # ── Paired same-window A/B (steady-state rate, small windows) ──────
    window_waves = 3 if smoke else 6
    reps = 3 if smoke else 5
    arm_t = make_engine(tiered=True)
    arm_u = make_engine(tiered=False)
    # Warmup both arms through the full lifecycle (compile + steady tier).
    warm = int(demote_after + evict_after) + 1
    now_t = now_u = now0
    for _ in range(warm):
        run_wave(arm_t, now_t, True)
        now_t += 1
        run_wave(arm_u, now_u, False)
        now_u += 1
    t_rates: list[float] = []
    u_rates: list[float] = []
    for _ in range(reps):
        t0 = time.perf_counter()
        votes = 0
        for _ in range(window_waves):
            votes += run_wave(arm_t, now_t, True)
            now_t += 1
        t_rates.append(votes / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        votes = 0
        for _ in range(window_waves):
            votes += run_wave(arm_u, now_u, False)
            now_u += 1
        u_rates.append(votes / (time.perf_counter() - t0))
    med_t = sorted(t_rates)[len(t_rates) // 2]
    med_u = sorted(u_rates)[len(u_rates) // 2]
    slowdown = med_u / med_t if med_t else float("inf")
    max_spread = max(spread_pct(t_rates), spread_pct(u_rates))
    within_2x = slowdown <= 2.0
    noise_verdict = {
        "pass": bool(within_2x),
        "criterion": (
            "median tiered-arm ingest rate within 2x of the untier'd "
            "paired arm, same window, interleaved reps"
        ),
        "tiered_votes_per_sec": round(med_t, 1),
        "untiered_votes_per_sec": round(med_u, 1),
        "slowdown_vs_untiered": round(slowdown, 3),
        "tiered_reps": [round(r, 1) for r in t_rates],
        "untiered_reps": [round(r, 1) for r in u_rates],
        "spread_pct": {
            "tiered": spread_pct(t_rates),
            "untiered": spread_pct(u_rates),
        },
        "max_spread_pct": max_spread,
    }
    assert within_2x, (
        f"tiered steady-state ingest {med_t:.0f}/s is more than 2x below "
        f"the untier'd arm {med_u:.0f}/s"
    )
    del arm_u

    # ── Headline: cumulative sessions under the asserted ceilings ──────
    engine = arm_t  # continue the warmed tiered engine
    cumulative = warm * wave_sessions + reps * window_waves * wave_sessions
    # The headline loop must actually run (and sample its ceilings) even
    # at smoke scale, on top of whatever the warmup + A/B consumed.
    target_sessions = max(target_sessions, cumulative + 20 * wave_sessions)
    votes_total = cumulative * present
    import gc as _gc

    _gc.collect()
    rss0 = _rss_bytes()
    rss_peak = 0
    occ_peak = {"live_sessions": 0, "tier_sessions": 0, "tier_bytes": 0}
    start = time.perf_counter()
    while cumulative < target_sessions:
        votes_total += run_wave(engine, now_t, True)
        now_t += 1
        cumulative += wave_sessions
        # EVERY ceiling asserts on EVERY wave — the headline is a held
        # bound, not an average that can hide a transient overshoot.
        rss = _rss_bytes()
        rss_peak = max(rss_peak, rss)
        assert rss - rss0 <= rss_budget, (
            f"RSS ceiling broken at {cumulative} cumulative sessions: "
            f"{(rss - rss0) / 1e6:.1f} MB over a "
            f"{rss_budget / 1e6:.0f} MB budget"
        )
        occ = engine.occupancy()
        for key in occ_peak:
            occ_peak[key] = max(occ_peak[key], occ[key])
        assert occ["device_slots_used"] <= capacity
        assert occ["live_sessions"] <= live_ceiling, occ
        assert occ["tier_sessions"] <= tier_ceiling, occ
    elapsed = time.perf_counter() - start
    occ = engine.occupancy()
    return {
        "metric": "churn_cumulative_sessions",
        "value": cumulative,
        "unit": "sessions",
        "detail": {
            "wave_sessions": wave_sessions,
            "scopes": scopes,
            "voters_per_session": v_count,
            "votes_per_session": present,
            "votes_total": votes_total,
            "headline_seconds": round(elapsed, 3),
            "sessions_per_sec": round(
                (cumulative - warm * wave_sessions
                 - reps * window_waves * wave_sessions) / elapsed, 1
            ),
            "ceilings": {
                "device_slots": capacity,
                "live_sessions": live_ceiling,
                "tier_sessions": tier_ceiling,
                "rss_budget_bytes": rss_budget,
                "asserted_every_wave": True,
            },
            "observed_peaks": {
                "rss_over_baseline_bytes": max(rss_peak - rss0, 0),
                **occ_peak,
            },
            "final_occupancy": occ,
            "policy": {
                "demote_after_ticks": demote_after,
                "evict_decided_after_ticks": evict_after,
            },
            "noise_verdict": noise_verdict,
            "slo": _slo_block(objective_ms=5_000.0),
            "platform": jax.devices()[0].platform,
        },
    }


def run_gossip(
    n_peers: int = 4,
    p_count: int = 8,
    v_count: int = 128,
    chunk: int = 16,
    reps: int = 3,
    smoke: bool = False,
    stages: bool = True,
    reactor_ab: bool = True,
    reactor_only: bool = False,
) -> dict:
    """Networked gossip fabric: aggregate votes/sec ACROSS A SOCKET.

    ``n_peers`` bridge servers each host one consensus peer over real TCP
    (loopback). A driver distributes proposals to every peer (untimed
    setup), then delivers every proposal's signed vote chain in
    gossip-sized ``chunk``-vote units, timed, in two paired arms per rep:

    - **A (baseline)**: the serial ``BridgeClient`` loop — today's
      embedder: one ``OP_PROCESS_VOTES`` frame per (peer, chunk), each
      blocking a full round trip + a per-frame engine dispatch;
    - **B (headline)**: the gossip fabric — the same chunks submitted to
      a :class:`~hashgraph_tpu.gossip.GossipNode` driver, coalesced into
      columnar ``OP_VOTE_BATCH`` frames (many chunks per frame), many
      frames in flight per connection (pipelining), landed via
      ``ingest_votes_pipelined`` on the receiving side.

    The workload is stub-signed: the transport and dispatch path is
    under test, not host crypto (the validated-sweep bench owns that
    wall; real schemes pay the same crypto in both arms and would only
    compress the ratio). Aggregate networked votes/sec counts every vote
    crossing a socket: ``p_count * v_count * n_peers / wall``.

    Every rep asserts ``sync.state_fingerprint`` EQUALITY across all
    peers for both arms before its time counts. The ``noise_verdict``
    refuses the claim unless the arms separate beyond the window's own
    spread (serial-ping control as the loopback/scheduler weather
    normalizer); ``target_5x`` reports the ISSUE acceptance bar.

    ``reactor_ab`` appends a SECOND paired A/B — reactor-off vs
    reactor-on fabric arms on dedicated peer sets with the apply
    reactor pinned per arm — reporting its own ``noise_verdict``,
    ``votes_per_dispatch`` per arm, and each arm's device-apply share
    of server busy time against the r06 66.8% attribution.
    ``reactor_only`` runs just that pair (``make bench-reactor``).

    ``smoke`` (CI): 3 IN-PROCESS peers, tiny shapes, one A/B pair, plus
    a sampled-fanout + one-anti-entropy-round convergence phase
    asserting fingerprint-identical state across peers. The full bench
    spawns each peer as its OWN PROCESS (``examples/gossip_peer.py``):
    in-process "peers" share one GIL, so an aggregate networked number
    measured there is really one interpreter's ceiling, not a fabric's.
    """
    import os
    import subprocess

    from hashgraph_tpu import build_vote
    from hashgraph_tpu.bridge.client import BridgeClient
    from hashgraph_tpu.bridge.server import BridgeServer
    from hashgraph_tpu.gossip import GossipNode
    from hashgraph_tpu.signing.stub import StubConsensusSigner
    from hashgraph_tpu.wire import Proposal

    if smoke:
        n_peers, p_count, v_count, reps = 3, 2, 16, 1
    now = 1_700_000_000
    total_votes = p_count * v_count
    networked = total_votes * n_peers
    # +1 warmup pair, +1 smoke convergence phase; one scope per proposal
    # so every session is retained for the fingerprint comparison.
    capacity = (2 * (reps + 1) + 2) * p_count + 8

    servers: list[BridgeServer] = []  # in-process (smoke) only
    procs: "list[subprocess.Popen]" = []  # one per peer (full bench)
    clients: list[BridgeClient] = []
    peer_ids: list[int] = []
    if smoke:
        for _ in range(n_peers):
            server = BridgeServer(
                capacity=capacity,
                voter_capacity=v_count + 2,
                signer_factory=StubConsensusSigner,
            )
            server.start()
            servers.append(server)
        addresses = [server.address for server in servers]
    else:
        # Peers on CPU regardless of the driver's backend: four small
        # engines contending for one accelerator would measure device
        # queueing, and TPU runtimes are single-process anyway.
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        runner = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "examples", "gossip_peer.py",
        )
        addresses = []
        for _ in range(n_peers):
            proc = subprocess.Popen(
                [sys.executable, runner,
                 "--capacity", str(capacity),
                 "--voter-capacity", str(v_count + 2)],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            procs.append(proc)
        for proc in procs:  # jax init per process; generous but parallel
            line = proc.stdout.readline().decode()
            assert line.startswith("PORT "), f"peer runner said: {line!r}"
            addresses.append(("127.0.0.1", int(line.split()[1])))
    for address in addresses:
        client = BridgeClient(*address, timeout=60.0)
        pid, _identity = client.add_peer(os.urandom(32))
        clients.append(client)
        peer_ids.append(pid)

    def build_epoch(
        tag: str, cs=None, pids=None, expected_voters=None
    ) -> "list[tuple[str, int, list[bytes]]]":
        """Create + distribute p_count proposals (untimed), return
        (scope, proposal_id, chained signed votes as wire bytes).
        ``cs``/``pids`` target an alternate peer set (the reactor A/B
        arms); default is the main one. ``expected_voters`` above
        2*v_count keeps quorum unreachable: a decided session freezes
        its chain, so votes landing in frames AFTER the decide frame
        answer RECEIVED_HASH_MISMATCH — benign with the main arm's
        512-vote windows (every late row shares the decide frame and
        settles ALREADY_REACHED) but surfaced by gossip-frame-sized
        windows, which would make acked != networked without any vote
        actually dropping."""
        cs = clients if cs is None else cs
        pids = peer_ids if pids is None else pids
        out = []
        signers = [StubConsensusSigner(os.urandom(20)) for _ in range(v_count)]
        for p in range(p_count):
            scope = f"{tag}-{p}"
            pid, blob = cs[0].create_proposal(
                pids[0], scope, now, f"p{p}", b"payload",
                v_count + 1 if expected_voters is None else expected_voters,
                3_600,
            )
            for i in range(1, len(cs)):
                cs[i].process_proposal(pids[i], scope, blob, now)
            proposal = Proposal.decode(blob)
            votes: list[bytes] = []
            for signer in signers:
                vote = build_vote(proposal, True, signer, now + 1)
                proposal.votes.append(vote)  # chain each vote on the last
                votes.append(vote.encode())
            out.append((scope, pid, votes))
        return out

    def chunks(votes: "list[bytes]") -> "list[list[bytes]]":
        return [votes[i : i + chunk] for i in range(0, len(votes), chunk)]

    def assert_converged(tag: str) -> str:
        fps = {
            client.state_fingerprint(pid)
            for client, pid in zip(clients, peer_ids)
        }
        assert len(fps) == 1, f"{tag}: peers diverged ({len(fps)} states)"
        return next(iter(fps))

    def run_serial(epoch) -> float:
        t0 = time.perf_counter()
        for scope, _pid, votes in epoch:
            for part in chunks(votes):
                for client, pid in zip(clients, peer_ids):
                    client.process_votes(pid, scope, part, now + 1)
        wall = time.perf_counter() - t0
        assert_converged("serial")
        return wall

    fabric_node: "list[GossipNode]" = []  # lazily built, reused across reps

    def run_fabric(epoch) -> float:
        if not fabric_node:
            # Full bench: peers are co-located OS processes — attach the
            # shared-memory ring lane (FEATURE_SHM_RING; TCP fallback is
            # automatic when a peer can't map the rings). The smoke's
            # in-process peers keep TCP so CI covers both lanes.
            node = GossipNode(
                "bench-driver", fanout=None, flush_votes=512,
                shm_ring_bytes=None if smoke else 8 * 1024 * 1024,
            )
            for i, address in enumerate(addresses):
                node.add_peer(f"peer{i}", *address, peer_ids[i])
            fabric_node.append(node)
        node = fabric_node[0]
        t0 = time.perf_counter()
        for scope, pid, votes in epoch:
            for part in chunks(votes):
                node.submit_votes(scope, pid, part, now + 1, local=False)
        report = node.drain()
        wall = time.perf_counter() - t0
        assert report["acked"] == networked, (
            f"fabric dropped votes: {report}"
        )
        assert_converged("fabric")
        return wall

    # Control: serial ping round trips on peer 0 — the loopback +
    # scheduler weather normalizer (median of 3 runs of 200).
    def control_rate() -> float:
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(200):
                clients[0].ping()
            rates.append(200 / (time.perf_counter() - t0))
        return round(sorted(rates)[1], 1)

    # Stage attribution: the servers' wire-path counters (decode /
    # crypto / device-apply wall seconds + frames per path) scraped over
    # GET_METRICS, summed across peer processes. In-process smoke peers
    # share one registry, so scrape exactly one client there.
    _STAGE_FAMILIES = {
        "hashgraph_bridge_wire_decode_seconds_total": "wire_decode_s",
        "hashgraph_bridge_wire_crypto_seconds_total": "crypto_s",
        "hashgraph_bridge_wire_apply_seconds_total": "device_apply_s",
        "hashgraph_bridge_wire_columnar_frames_total": "columnar_frames",
        "hashgraph_bridge_wire_fallback_frames_total": "fallback_frames",
        "hashgraph_bridge_shm_rings_attached_total": "shm_rings",
        # Dispatch amortization (ISSUE 19): fused device calls and the
        # rows they carried — votes_per_dispatch = apply_rows /
        # device_dispatches is the measured amortization factor.
        "hashgraph_bridge_wire_device_dispatches_total": "device_dispatches",
        "hashgraph_bridge_wire_apply_rows_total": "apply_rows",
    }

    def scrape_stages(cs=None) -> "dict[str, float]":
        cs = clients if cs is None else cs
        out = {name: 0.0 for name in _STAGE_FAMILIES.values()}
        for client in cs[:1] if smoke else cs:
            for line in client.get_metrics().splitlines():
                if line.startswith("#") or " " not in line:
                    continue
                family, _, value = line.partition(" ")
                key = _STAGE_FAMILIES.get(family)
                if key is not None:
                    out[key] += float(value)
        return out

    def stage_delta(before: dict, after: dict) -> dict:
        return {
            key: round(after[key] - before[key], 4)
            for key in before
        }

    def spawn_peer_set(pin: str):
        """A dedicated peer set with the apply reactor PINNED on/off —
        the A/B arms must not inherit HASHGRAPH_TPU_APPLY_REACTOR from
        the environment (the main arms deliberately do, so the CI
        reactor smoke leg exercises the reactor on the headline path)."""
        r_servers: list = []
        r_procs: list = []
        r_clients: list = []
        r_pids: list = []
        r_capacity = (reps + 2) * p_count + 8
        if smoke:
            for _ in range(n_peers):
                server = BridgeServer(
                    capacity=r_capacity,
                    voter_capacity=v_count + 2,
                    signer_factory=StubConsensusSigner,
                    apply_reactor=(pin == "on"),
                )
                server.start()
                r_servers.append(server)
            addrs = [server.address for server in r_servers]
        else:
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            runner = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "examples", "gossip_peer.py",
            )
            addrs = []
            for _ in range(n_peers):
                proc = subprocess.Popen(
                    [sys.executable, runner,
                     "--capacity", str(r_capacity),
                     "--voter-capacity", str(v_count + 2),
                     "--reactor", pin],
                    stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE,
                    env=env,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                )
                r_procs.append(proc)
            for proc in r_procs:
                line = proc.stdout.readline().decode()
                assert line.startswith("PORT "), f"peer runner said: {line!r}"
                addrs.append(("127.0.0.1", int(line.split()[1])))
        for address in addrs:
            client = BridgeClient(*address, timeout=60.0)
            pid, _identity = client.add_peer(os.urandom(32))
            r_clients.append(client)
            r_pids.append(pid)
        return r_servers, r_procs, r_clients, r_pids, addrs

    def run_reactor_pair() -> dict:
        """Paired reactor-off/on A/B on DEDICATED pinned peer sets.

        Both arms run the identical fabric workload, but with
        gossip-frame-sized coalescer windows (``flush_votes=chunk``):
        many small pipelined OP_VOTE_BATCH frames per connection — the
        exact per-dispatch-amortization regime the reactor exists for.
        The off arm pays one device dispatch per frame; the on arm's
        per-engine windows merge in-flight frames into fused dispatches.
        Reps interleave off/on so scheduler weather hits both arms;
        per-arm metric scrapes around each timed run attribute stage
        seconds and ``votes_per_dispatch`` to the right arm even in
        smoke mode, where every in-process server shares one registry."""
        arms: dict = {}
        try:
            for pin in ("off", "on"):
                servers_, procs_, clients_, pids_, addrs_ = spawn_peer_set(pin)
                node = GossipNode(
                    f"reactor-{pin}-driver", fanout=None, flush_votes=chunk,
                )
                for i, address in enumerate(addrs_):
                    node.add_peer(f"peer{i}", *address, pids_[i])
                arms[pin] = {
                    "servers": servers_, "procs": procs_,
                    "clients": clients_, "pids": pids_, "node": node,
                }

            def run_arm(arm, tag: str) -> float:
                node, cs, pids = arm["node"], arm["clients"], arm["pids"]
                # Quorum unreachable (see build_epoch): every row must
                # ack, so the arms measure pure dispatch amortization
                # with an exact acked == networked accounting even at
                # chunk-sized flush windows.
                epoch = build_epoch(tag, cs, pids, expected_voters=2 * v_count + 2)
                t0 = time.perf_counter()
                for scope, pid, votes in epoch:
                    for part in chunks(votes):
                        node.submit_votes(
                            scope, pid, part, now + 1, local=False
                        )
                report = node.drain()
                wall = time.perf_counter() - t0
                assert report["acked"] == networked, (
                    f"reactor arm {tag} dropped votes: {report}"
                )
                fps = {
                    client.state_fingerprint(pid)
                    for client, pid in zip(cs, pids)
                }
                assert len(fps) == 1, f"reactor arm {tag}: peers diverged"
                return wall

            # Untimed warmup per arm: jit at these shapes.
            run_arm(arms["off"], "rw-off")
            run_arm(arms["on"], "rw-on")
            rates: dict = {"off": [], "on": []}
            stage_totals = {
                pin: {name: 0.0 for name in _STAGE_FAMILIES.values()}
                for pin in ("off", "on")
            }
            for rep in range(reps):
                for pin in ("off", "on"):
                    before = scrape_stages(arms[pin]["clients"])
                    rates[pin].append(
                        networked / run_arm(arms[pin], f"rr{rep}-{pin}")
                    )
                    delta = stage_delta(
                        before, scrape_stages(arms[pin]["clients"])
                    )
                    for key, value in delta.items():
                        stage_totals[pin][key] += value
        finally:
            for arm in arms.values():
                node = arm.get("node")
                if node is not None:
                    node.close()
                for client in arm.get("clients", ()):
                    client.close()
                for server in arm.get("servers", ()):
                    server.stop()
                for proc in arm.get("procs", ()):
                    try:
                        proc.stdin.close()
                        proc.wait(timeout=15)
                    except Exception:
                        proc.kill()

        def med(values):
            return sorted(values)[len(values) // 2]

        def vpd(totals) -> float:
            dispatches = totals.get("device_dispatches", 0.0)
            if not dispatches:
                return 0.0
            return round(totals.get("apply_rows", 0.0) / dispatches, 2)

        def apply_share(totals) -> float:
            busy = sum(
                totals[key]
                for key in ("wire_decode_s", "crypto_s", "device_apply_s")
            )
            return round(totals["device_apply_s"] / busy, 3) if busy else 0.0

        med_off, med_on = med(rates["off"]), med(rates["on"])
        speedup = round(med_on / med_off, 3) if med_off else 0.0
        max_spread = max(spread_pct(rates["off"]), spread_pct(rates["on"]))
        separated = min(rates["on"]) > max(rates["off"])
        outside_noise = speedup > 1.0 + 2.0 * max_spread / 100.0
        return {
            "noise_verdict": {
                "pass": bool(separated and outside_noise),
                "criterion": (
                    "min(reactor-on reps) > max(reactor-off reps) AND "
                    "speedup > 1 + 2*max_spread"
                ),
                "speedup": speedup,
                "reactor_on_votes_per_sec": round(med_on, 1),
                "reactor_off_votes_per_sec": round(med_off, 1),
                "on_reps": [round(r, 1) for r in rates["on"]],
                "off_reps": [round(r, 1) for r in rates["off"]],
                "spread_pct": {
                    "on": spread_pct(rates["on"]),
                    "off": spread_pct(rates["off"]),
                },
            },
            "votes_per_dispatch": {
                "off": vpd(stage_totals["off"]),
                "on": vpd(stage_totals["on"]),
            },
            "device_apply_share": {
                "off": apply_share(stage_totals["off"]),
                "on": apply_share(stage_totals["on"]),
                "r06_baseline": 0.668,
            },
            "stage_totals": {
                pin: {key: round(value, 4) for key, value in totals.items()}
                for pin, totals in stage_totals.items()
            },
            "coalescer_flush_votes": chunk,
        }

    reactor_block = None
    a_rates: list[float] = []
    b_rates: list[float] = []
    stage_reps: list[dict] = []
    controls: list[float] = []
    final_stages = None
    slo_frames: list = []
    profile_frames: list = []
    convergence = None
    try:
        if not reactor_only:
            # Untimed warmup pair: jit at these shapes, connection setup.
            run_serial(build_epoch("w-a"))
            run_fabric(build_epoch("w-b"))

            controls.append(control_rate())
            for rep in range(reps):
                a_rates.append(
                    networked / run_serial(build_epoch(f"r{rep}-a"))
                )
                controls.append(control_rate())
                before = scrape_stages() if stages else None
                b_rates.append(
                    networked / run_fabric(build_epoch(f"r{rep}-b"))
                )
                if stages:
                    stage_reps.append(stage_delta(before, scrape_stages()))
                controls.append(control_rate())
            final_stages = scrape_stages() if stages else None
            # One OP_METRICS_PULL frame per peer: each process's windowed
            # SLO state rides home with the bench (the peers decided the
            # sessions, so THEIR SloEngines hold the latency windows).
            slo_frames = [client.metrics_pull() for client in clients]
            # Continuous-profiling readout (round 20): when the
            # always-on sampler is armed (HASHGRAPH_TPU_PROFILE=1 — the
            # profile-smoke CI leg) pull one OP_PROFILE attribution
            # frame per peer. Old peers answer UNKNOWN_OPCODE and the
            # client returns None — filtered, not fatal.
            from hashgraph_tpu.obs.profiler import profiler_enabled

            if profiler_enabled():
                profile_frames = [
                    frame
                    for frame in (client.profile() for client in clients)
                    if frame is not None
                ]

        # Smoke convergence phase: sampled fanout misses peers on
        # purpose; ONE anti-entropy round (same logical now) repairs
        # them to fingerprint-identical state.
        if smoke and not reactor_only:
            node = GossipNode(
                "smoke-node",
                engine=servers[0].peer_engine(peer_ids[0]),
                fanout=1,
                seed=1234,
            )
            for i in range(1, n_peers):
                node.add_peer(f"peer{i}", *addresses[i], peer_ids[i])
            try:
                epoch = build_epoch("ae")
                for scope, pid, votes in epoch:
                    # local=False: peer 0 already holds these votes if
                    # sampled; it gets them via anti-entropy otherwise —
                    # no, peer 0 IS the node's engine: apply locally so
                    # it can serve the repair push.
                    node.submit_votes(scope, pid, votes, now + 1, local=True)
                node.drain()
                diverged = len({
                    client.state_fingerprint(pid)
                    for client, pid in zip(clients, peer_ids)
                }) > 1
                round_report = node.anti_entropy(now + 1)
                fingerprint = assert_converged("anti-entropy")
                convergence = {
                    "sampled_fanout": 1,
                    "diverged_before_round": diverged,
                    "anti_entropy": round_report,
                    "fingerprint": fingerprint,
                }
            finally:
                node.close()

        if reactor_ab or reactor_only:
            reactor_block = run_reactor_pair()
    finally:
        for node in fabric_node:
            node.close()
        for client in clients:
            client.close()
        for server in servers:
            server.stop()
        for proc in procs:
            try:
                proc.stdin.close()  # EOF = the runner's shutdown signal
                proc.wait(timeout=15)
            except Exception:
                proc.kill()

    if reactor_only:
        verdict = reactor_block["noise_verdict"]
        return {
            "metric": "gossip_reactor_votes_per_sec",
            "value": verdict["reactor_on_votes_per_sec"],
            "unit": "votes/sec",
            "detail": {
                "n_peers": n_peers,
                "proposals": p_count,
                "votes_per_proposal": v_count,
                "chunk_votes": chunk,
                "votes_networked_per_rep": networked,
                "reactor_ab": reactor_block,
            },
        }

    med_a = sorted(a_rates)[len(a_rates) // 2]
    med_b = sorted(b_rates)[len(b_rates) // 2]
    speedup = round(med_b / med_a, 2) if med_a else 0.0
    max_spread = max(spread_pct(a_rates), spread_pct(b_rates),
                     spread_pct(controls))
    separated = min(b_rates) > max(a_rates)
    outside_noise = speedup > 1.0 + 2.0 * max_spread / 100.0
    noise_verdict = {
        "pass": bool(separated and outside_noise),
        "criterion": (
            "min(fabric reps) > max(serial reps) AND "
            "speedup > 1 + 2*max_spread"
        ),
        "speedup": speedup,
        "target_5x": bool(speedup >= 5.0),
        "fabric_votes_per_sec": round(med_b, 1),
        "serial_votes_per_sec": round(med_a, 1),
        "fabric_reps": [round(r, 1) for r in b_rates],
        "serial_reps": [round(r, 1) for r in a_rates],
        "control_pings_per_sec": controls,
        "spread_pct": {
            "fabric": spread_pct(b_rates),
            "serial": spread_pct(a_rates),
            "control": spread_pct(controls),
        },
    }
    from hashgraph_tpu.parallel.rollup import merge_slo_states

    merged_slo = merge_slo_states(slo_frames)
    slo_objective_ms = 5_000.0
    worst_p99_ms = round(merged_slo["global"]["worst_p99"] * 1e3, 3)
    detail = {
        "n_peers": n_peers,
        "proposals": p_count,
        "votes_per_proposal": v_count,
        "chunk_votes": chunk,
        "votes_networked_per_rep": networked,
        "fingerprints_identical": True,  # asserted every rep, both arms
        "noise_verdict": noise_verdict,
        "slo": {
            "windowed_decisions": merged_slo["global"]["count"],
            "worst_peer_p99_ms": worst_p99_ms,
            "per_peer_latency_ms": {
                host: {
                    "p50": round(s["global"]["p50"] * 1e3, 3),
                    "p95": round(s["global"]["p95"] * 1e3, 3),
                    "p99": round(s["global"]["p99"] * 1e3, 3),
                }
                for host, s in merged_slo["hosts"].items()
            },
            "alerts_firing": merged_slo["alerts_firing"],
            "verdict": {
                "objective_ms": slo_objective_ms,
                "p99_ms": worst_p99_ms,
                "pass": bool(
                    not merged_slo["alerts_firing"]
                    and worst_p99_ms <= slo_objective_ms
                ),
            },
        },
    }
    if stages and stage_reps:
        # Per-rep wall seconds inside the fabric arm's server path (wire
        # decode / crypto / device apply) plus frames per path: the
        # residual gap to the in-process number is attributable stage by
        # stage, and a regression in any one stage is visible in the
        # BENCH json without re-profiling. shm_rings reports the
        # ABSOLUTE attach count (attachment happens once at warmup, so a
        # per-rep delta would always read 0).
        totals = {
            key: round(sum(rep[key] for rep in stage_reps), 4)
            for key in stage_reps[0]
        }
        totals["shm_rings"] = final_stages["shm_rings"]
        busy = sum(
            totals[key]
            for key in ("wire_decode_s", "crypto_s", "device_apply_s")
        )
        dispatches = totals.get("device_dispatches", 0.0)
        detail["stage_attribution"] = {
            "per_rep": stage_reps,
            "totals": totals,
            "stage_share": {
                key: round(totals[key] / busy, 3) if busy else 0.0
                for key in ("wire_decode_s", "crypto_s", "device_apply_s")
            },
            # Amortization factor: rows landed per fused device call.
            "votes_per_dispatch": (
                round(totals.get("apply_rows", 0.0) / dispatches, 2)
                if dispatches else 0.0
            ),
        }
    if profile_frames:
        # Fleet attribution via the ONE merge (rollup discipline), held
        # to its contract in-bench: only known stage names, and shares
        # that sum to a probability mass — a broken denominator fails
        # the profile-smoke CI leg here, not in a dashboard later.
        from hashgraph_tpu.obs.attribution import STAGE_KEYS
        from hashgraph_tpu.parallel.rollup import merge_profile_states

        merged_profile = merge_profile_states(profile_frames)
        shares = {
            key: stage["share"]
            for key, stage in merged_profile["stages"].items()
        }
        assert set(shares) == set(STAGE_KEYS), shares
        assert sum(shares.values()) <= 1.0 + 1e-6, shares
        samples = merged_profile["samples"]
        detail["profile"] = {
            "hosts": sorted(merged_profile["hosts"]),
            "stage_shares": shares,
            "busy_seconds": merged_profile["busy_seconds"],
            "votes_per_dispatch": (
                merged_profile["device"]["votes_per_dispatch"]
            ),
            "samples": samples["total"],
            "samples_dropped": samples["dropped"],
            "sample_roles": samples["roles"],
            "profiler_overhead_s": samples["overhead_seconds"],
        }
    if reactor_block is not None:
        detail["reactor_ab"] = reactor_block
    if smoke:
        detail["convergence"] = convergence
    return {
        "metric": "gossip_networked_votes_per_sec",
        "value": round(med_b, 1),
        "unit": "votes/sec",
        "detail": detail,
    }


def run_fleet(
    n_shards: int | None = None,
    scopes_per_shard: int = 2,
    p_count: int = 256,
    v_count: int = 64,
    reps: int = 3,
    smoke: bool = False,
) -> dict:
    """Scope-sharded fleet throughput: one engine per local device, scopes
    rendezvous-placed across them, a sustained mixed gossip+P2P columnar
    workload routed by :class:`hashgraph_tpu.parallel.ConsensusFleet`, and
    an AGGREGATE fleet votes/sec headline with a per-shard breakdown.

    Paired same-window A/B (the PR-6 methodology): the fleet arm (all
    shards) interleaves rep-for-rep with a single-shard arm (the same
    per-shard workload confined to one shard) inside one window, and the
    machine-readable ``noise_verdict`` refuses the scaling claim unless
    the arms separate beyond the window's own spread. ``scaling`` is
    aggregate-fleet / best-single-shard from the same window; on >= 4
    distinct-device shards, near-linear means >= 3x (ISSUE 7 acceptance).

    ``smoke`` shrinks to 2 shards x tiny shapes for the CI job: routing,
    the psum tally path, and the sweep are exercised; the verdict is
    reported but not asserted (2 CPU "devices" share one core).

    Emits a ``MULTICHIP_*``-compatible record (``multichip_record``) so
    the multichip artifact finally carries throughput, per-device slot
    occupancy, and sweep seconds instead of just ``ok``/``tail``.
    """
    import jax

    from hashgraph_tpu import (
        CreateProposalRequest,
        ScopeConfigBuilder,
        StubConsensusSigner,
    )
    from hashgraph_tpu.parallel import ConsensusFleet

    from hashgraph_tpu.obs import slo_engine

    slo_engine.reset()
    rng = np.random.default_rng(31)
    now = 1_700_000_000
    if smoke:
        scopes_per_shard, p_count, v_count, reps = 1, 32, 16, 1
        n_shards = 2 if n_shards is None else n_shards
    n_devices = len(jax.devices())
    if n_shards is None:
        n_shards = n_devices
    present = max(2, min(int(v_count * 0.7), (2 * v_count + 2) // 3 - 3))
    capacity_per_shard = scopes_per_shard * p_count

    fleet = ConsensusFleet(
        lambda k: StubConsensusSigner(bytes([k + 1]) * 20),
        n_shards=n_shards,
        capacity_per_shard=capacity_per_shard,
        voter_capacity=v_count,
        max_sessions_per_scope=p_count + 1,
    )
    distinct_devices = len({str(fleet.shard(s).device) for s in fleet.shard_ids})

    # Deterministically pick scopes_per_shard scope names per shard per
    # rep epoch (rendezvous placement decides ownership; we just probe
    # names until every shard's quota fills).
    def pick_scopes(epoch: int, shard_ids) -> "dict[str, list[str]]":
        got = {sid: [] for sid in shard_ids}
        i = 0
        while any(len(v) < scopes_per_shard for v in got.values()):
            scope = f"e{epoch}-s{i}"
            i += 1
            sid = fleet.owner_of(scope)
            if sid in got and len(got[sid]) < scopes_per_shard:
                got[sid].append(scope)
        return got

    owners = [
        bytes([1 + (i % 250), i // 250]) + b"\x00" * 18 for i in range(present)
    ]
    requests = [
        CreateProposalRequest(
            name="p",
            payload=b"",
            proposal_owner=b"o",
            expected_voters_count=v_count,
            expiration_timestamp=100,
            liveness_criteria_yes=bool(rng.integers(2)),
        )
        for _ in range(p_count)
    ]

    def run_arm(epoch: int, shard_ids, adaptive: bool = False) -> dict:
        """One rep of the sustained workload over ``shard_ids``' scopes:
        register, columnar-ingest via the fleet router (mixed gossip/P2P
        scopes, shuffled at proposal granularity), sweep, verify. Only
        the ingest window feeds votes/sec (create/sweep timed apart).
        ``adaptive=True`` declares consensus-timeout bounds on every
        scope so the per-scope timeout learner rides the hot path — the
        liveness A/B's treatment arm."""
        by_shard = pick_scopes(epoch, shard_ids)
        scopes = [s for group in by_shard.values() for s in group]
        scope_shard = {
            s: sid for sid, group in by_shard.items() for s in group
        }
        for i, scope in enumerate(scopes):
            builder = ScopeConfigBuilder()
            builder = (
                builder.p2p_preset() if i % 2 else builder.gossipsub_preset()
            )
            # A declared decide-latency objective on every bench scope:
            # the SLO plane tracks the run end to end (per-scope burn
            # windows, alert machinery live) and the BENCH json carries
            # a windowed-p99 verdict against it. Generous on purpose —
            # a CI box breaching 5s would be a real regression.
            builder = builder.with_decide_p99_ms(5_000.0)
            if adaptive:
                # Liveness A/B treatment arm: identical workload, but
                # every scope opts into adaptive consensus timeouts
                # (engine/adaptive.py) so each decision feeds the
                # learner. Advisory-only by design — the decide path is
                # byte-identical, which is exactly what the within-noise
                # gate below verifies.
                builder = builder.with_timeout_bounds(0.5, 30.0)
            fleet.set_scope_config(scope, builder.build())
        t0 = time.perf_counter()
        pids = {}
        for scope in scopes:
            pids[scope] = np.fromiter(
                (
                    p.proposal_id
                    for p in fleet.create_proposals(scope, requests, now)
                ),
                np.int64,
                p_count,
            )
        t_create = time.perf_counter()
        gids = {
            scope: np.array(
                [fleet.voter_gid(scope, o) for o in owners], np.int64
            )
            for scope in scopes
        }
        # Proposal-major rows, scope-shuffled at proposal granularity.
        all_pids = np.concatenate([pids[s] for s in scopes])
        all_sidx = np.repeat(np.arange(len(scopes), dtype=np.int64), p_count)
        order = rng.permutation(len(all_pids))
        col_pids = np.repeat(all_pids[order], present)
        col_sidx = np.repeat(all_sidx[order], present)
        col_gids = np.concatenate(
            [gids[scopes[k]] for k in all_sidx[order]]
        )
        col_vals = rng.random(len(col_pids)) < 0.5
        t1 = time.perf_counter()
        statuses = fleet.ingest_columnar_multi(
            scopes, col_sidx, col_pids, col_gids, col_vals, now
        )
        t2 = time.perf_counter()
        # Correctness gate every rep (run_engine_config5 discipline): a
        # resolution/identity regression fails the bench, not the timer.
        assert int(np.sum(statuses == 20)) == 0, "unresolved proposal ids"
        assert int(np.sum(statuses == 10)) == 0, "stale voter gids"
        applied = int(np.sum((statuses == 0) | (statuses == 28)))
        assert applied >= int(0.9 * len(statuses)), (applied, len(statuses))
        # Per-shard slice of the SAME concurrent window.
        per_shard_votes = {sid: 0 for sid in shard_ids}
        for k, scope in enumerate(scopes):
            per_shard_votes[scope_shard[scope]] += int(
                np.sum(all_sidx[order] == k)
            ) * present
        occupancy = fleet.occupancy()
        t3 = time.perf_counter()
        swept = fleet.sweep_timeouts(now + 200)
        counts = fleet.fleet_state_counts()  # ONE psum (device path)
        t4 = time.perf_counter()
        for scope in scopes:
            fleet.delete_scope(scope)
        wall = t2 - t1
        return {
            "votes": len(statuses),
            "votes_per_sec": round(len(statuses) / wall, 1),
            "ingest_seconds": round(wall, 3),
            "create_seconds": round(t_create - t0, 3),
            "sweep_seconds": round(t4 - t3, 3),
            "swept": len(swept),
            "per_shard_votes_per_sec": {
                sid: round(v / wall, 1) for sid, v in per_shard_votes.items()
            },
            "state_counts": {str(k): v for k, v in counts.items()},
            "occupancy": occupancy,
        }

    all_shards = fleet.shard_ids
    single = all_shards[:1]
    # The single-shard arm repeats its scope-set workload ``single_waves``
    # times per rep so both arms' timing windows are comparable in wall
    # length (a 20 ms window is timer-jitter-bound; the fleet arm's window
    # is naturally ~n_shards longer).
    single_waves = max(1, min(n_shards, 4))

    def run_single_rep(epoch_base: int) -> dict:
        waves = [
            run_arm(epoch_base + w, single) for w in range(single_waves)
        ]
        votes = sum(w["votes"] for w in waves)
        seconds = sum(w["ingest_seconds"] for w in waves)
        out = dict(waves[0])
        out.update(
            votes=votes,
            ingest_seconds=round(seconds, 3),
            votes_per_sec=round(votes / seconds, 1),
        )
        return out

    # Warmup epoch (uncounted): compiles every shard's kernels at the
    # production shapes for BOTH arms.
    run_arm(0, all_shards)
    run_arm(1, single)

    fleet_reps: list[dict] = []
    single_reps: list[dict] = []
    epoch = 2
    for _ in range(reps):
        single_reps.append(run_single_rep(epoch))
        epoch += single_waves
        fleet_reps.append(run_arm(epoch, all_shards))
        epoch += 1

    fleet_rates = [r["votes_per_sec"] for r in fleet_reps]
    single_rates = [r["votes_per_sec"] for r in single_reps]
    headline_rep = sorted(fleet_reps, key=lambda r: r["votes_per_sec"])[
        len(fleet_reps) // 2
    ]
    headline = headline_rep["votes_per_sec"]
    best_single = max(single_rates)
    scaling = round(headline / best_single, 2) if best_single else None
    max_spread = max(spread_pct(fleet_rates), spread_pct(single_rates))
    separated = min(fleet_rates) > max(single_rates)
    outside_noise = (
        scaling is not None and scaling > 1.0 + 2.0 * max_spread / 100.0
    )
    # The scaling CLAIM is only made on real parallel hardware: >= 4
    # shards on >= 4 distinct non-CPU devices. Virtual CPU "devices"
    # share the host's cores, so a single shard already saturates the
    # substrate and aggregate/single is physically capped near 1x there —
    # the bench still runs the A/B and reports the ratio, it just doesn't
    # pretend shared cores are a scaling testbed.
    shared_substrate = jax.devices()[0].platform == "cpu"
    scaling_target = (
        3.0
        if (n_shards >= 4 and distinct_devices >= 4 and not shared_substrate)
        else None
    )
    if scaling_target is not None:
        # Real parallel hardware: the headline is trustworthy when the
        # arms separate beyond the window's own weather (PR-6 criterion).
        verdict_pass = bool(separated and outside_noise)
        criterion = (
            "min(fleet reps) > max(single-shard reps) AND "
            "scaling > 1 + 2*max_spread"
        )
    else:
        # No parallel-scaling claim to defend (shared CPU substrate, or
        # too few shards/devices for the near-linear bar); the verdict
        # gates the aggregate number's own reproducibility against
        # BENCHMARKS.md's documented weather band.
        reason = (
            "shared substrate"
            if shared_substrate
            else "fewer than 4 shards on distinct devices"
        )
        verdict_pass = spread_pct(fleet_rates) < 33.3
        criterion = f"no scaling claim ({reason}): fleet rep spread < 33%"
    noise_verdict = {
        "pass": verdict_pass,
        "criterion": criterion,
        "aggregate_votes_per_sec": headline,
        "best_single_shard_votes_per_sec": best_single,
        "scaling": scaling,
        "scaling_target": scaling_target,
        "scaling_pass": (
            None if scaling_target is None else bool(scaling >= scaling_target)
        ),
        "shared_substrate": shared_substrate,
        "fleet_reps": fleet_rates,
        "single_shard_reps": single_rates,
        "spread_pct": {
            "fleet": spread_pct(fleet_rates),
            "single": spread_pct(single_rates),
        },
    }
    per_device_occupancy = [
        occ
        for sid in all_shards
        for occ in headline_rep["occupancy"][sid]["per_device_slots_used"]
    ]
    multichip_record = {
        "n_devices": n_devices,
        "n_shards": n_shards,
        "ok": True,
        "votes_per_sec": headline,
        "per_device_slot_occupancy": per_device_occupancy,
        "sweep_seconds": headline_rep["sweep_seconds"],
        "votes": headline_rep["votes"],
        "tally_path": "psum" if fleet._tally() is not None else "host-sum",
    }
    # ── Liveness block: adaptive-timeout learner ON vs OFF, paired ────
    # Interleaved same-window arms over the identical workload; the
    # treatment arm declares [0.5s, 30s] bounds on every scope. The
    # learner is ADVISORY (Engine.adaptive_timeout(); timers stay
    # embedder-owned, reference src/lib.rs:15-34), so the machine check
    # is two-sided: enabling it on a healthy network costs nothing the
    # window's own weather can't explain (ingest within noise of
    # static), and it actually LEARNED (book updates land only in
    # adaptive arms, every learned value inside the declared bounds).
    # The conviction half of the liveness story — adaptive strictly
    # fewer stale convictions under flapping links — is seed-
    # deterministic and gated by `python bench.py liveness`, not by
    # wall-clock arms.
    def _book_updates() -> int:
        total = 0
        for sid in all_shards:
            snap = fleet.shard(sid).engine.adaptive_timeout_snapshot()
            total += snap["decays_total"] + snap["backoffs_total"]
        return total

    ab_pairs = 1 if smoke else 2
    static_ab: list[float] = []
    adaptive_ab: list[float] = []
    static_updates = adaptive_updates = 0
    last_updates = _book_updates()
    for _ in range(ab_pairs):
        static_ab.append(run_arm(epoch, all_shards)["votes_per_sec"])
        epoch += 1
        cur = _book_updates()
        static_updates += cur - last_updates
        last_updates = cur
        adaptive_ab.append(
            run_arm(epoch, all_shards, adaptive=True)["votes_per_sec"]
        )
        epoch += 1
        cur = _book_updates()
        adaptive_updates += cur - last_updates
        last_updates = cur
    learned_values = [
        v
        for sid in all_shards
        for v in fleet.shard(sid)
        .engine.adaptive_timeout_snapshot()["scopes"]
        .values()
    ]
    bounds_held = all(0.5 <= v <= 30.0 for v in learned_values)
    med_static = sorted(static_ab)[len(static_ab) // 2]
    med_adaptive = sorted(adaptive_ab)[len(adaptive_ab) // 2]
    ab_spread = max(spread_pct(static_ab), spread_pct(adaptive_ab))
    ratio = round(med_adaptive / med_static, 4) if med_static else None
    within_noise = ratio is not None and abs(ratio - 1.0) <= max(
        0.10, 2.0 * ab_spread / 100.0
    )
    slo = _slo_block(objective_ms=5_000.0)
    liveness_block = {
        "pass": bool(
            within_noise
            and adaptive_updates > 0
            and static_updates == 0
            and bounds_held
        ),
        "criterion": (
            "adaptive-timeout arm within max(10%, 2*max_spread) of static "
            "AND learner updates land only in adaptive arms AND every "
            "learned timeout inside declared [0.5s, 30s] bounds"
        ),
        "decide_p99_ms": slo["windowed_latency_ms"]["p99"],
        "adaptive_vs_static_ratio": ratio,
        "within_noise": bool(within_noise),
        "static_reps": static_ab,
        "adaptive_reps": adaptive_ab,
        "spread_pct": {
            "static": spread_pct(static_ab),
            "adaptive": spread_pct(adaptive_ab),
        },
        "learner": {
            "adaptive_arm_updates": adaptive_updates,
            "static_arm_updates": static_updates,
            "learned_timeouts_sampled": len(learned_values),
            "bounds_held": bool(bounds_held),
        },
        "stale_conviction_ab": (
            "seed-deterministic; gated by `python bench.py liveness` "
            "(flapping-links adaptive-vs-static arms)"
        ),
    }
    fleet.close()
    return {
        "metric": "fleet_aggregate_ingest_throughput",
        "value": headline,
        "unit": "votes/sec",
        "vs_baseline": round(headline / 1_000_000, 4),
        "detail": {
            "n_shards": n_shards,
            "n_devices": n_devices,
            "distinct_devices": distinct_devices,
            "scopes_per_shard": scopes_per_shard,
            "proposals_per_scope": p_count,
            "voters": v_count,
            "present": present,
            "smoke": smoke,
            "per_shard": headline_rep["per_shard_votes_per_sec"],
            "sweep_seconds": headline_rep["sweep_seconds"],
            "swept": headline_rep["swept"],
            "state_counts": headline_rep["state_counts"],
            "noise_verdict": noise_verdict,
            "multichip_record": multichip_record,
            "slo": slo,
            "liveness": liveness_block,
            "platform": jax.devices()[0].platform,
        },
    }


def run_federation(
    n_hosts: int = 2,
    shards_per_host: int = 2,
    p_count: int = 48,
    v_count: int = 64,
    chunk: int = 32,
    reps: int = 3,
    smoke: bool = False,
) -> dict:
    """Federated multi-host fleet: aggregate votes/sec across N OS
    processes, plus a LIVE SHARD MIGRATION under sustained traffic.

    ``n_hosts`` federation hosts (``examples/federation_host.py`` — each
    a full FleetGroup: scope-sharded ConsensusFleet behind a bridge
    server) run as separate processes over real TCP. A
    :class:`~hashgraph_tpu.parallel.federation.FederationDriver` routes
    every scope's signed vote chain to its two-level-rendezvous owner
    (host, then shard) as coalesced pipelined ``OP_VOTE_BATCH`` frames —
    each vote crosses the wire ONCE, to the host that owns it.

    Paired same-window A/B: the federated arm (scopes spread over all
    hosts) interleaves rep-for-rep with a single-host arm (the same
    workload confined to host 0), and the machine-readable
    ``noise_verdict`` applies the fleet bench's criterion — a scaling
    claim only on real parallel hardware; on a shared-substrate CPU box
    the verdict gates the aggregate number's reproducibility instead.

    The **migration rep** then re-homes one of host 0's shards onto
    host 1 while the driver keeps submitting: freeze (in-flight frames
    for the shard come back ``STATUS_SHARD_MIGRATING`` and re-route;
    new submits buffer into the shard's tail), snapshot at the frozen
    WAL watermark + tail catch-up on the adopter, SOURCE == DESTINATION
    ``state_fingerprint`` asserted, atomic placement flip on every
    participant, tail replay, retire. Asserts ZERO lost votes
    (``acked == submitted``, nothing buffered or rejected) and ZERO
    lost decisions (every session decided True on its current owner),
    and reports the throughput dip + recovery as per-window rates.

    ``smoke`` (CI): 2 hosts x tiny shapes, one A/B pair, one migration.
    """
    import os
    import subprocess
    import threading as _threading

    from hashgraph_tpu import build_vote
    from hashgraph_tpu.bridge.client import BridgeClient
    from hashgraph_tpu.parallel.federation import (
        FederationDriver,
        FederationPlacement,
    )
    from hashgraph_tpu.signing.stub import StubConsensusSigner
    from hashgraph_tpu.wire import Proposal

    if smoke:
        p_count, v_count, chunk, reps = 8, 12, 8, 1
    now = 1_700_000_000
    total_votes = p_count * v_count
    host_ids = [f"h{i}" for i in range(n_hosts)]
    placement = FederationPlacement.uniform(host_ids, shards_per_host)
    single = FederationPlacement(
        {"h0": [f"h0:{k}" for k in range(shards_per_host)]}
    )

    import shutil
    import tempfile

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.abspath(__file__))
    runner = os.path.join(repo, "examples", "federation_host.py")
    # Each host process gets its own incident directory: the induced
    # breach below must produce an exemplar-linked Perfetto dump ON THE
    # OWNING HOST, and the parent asserts on it from the outside.
    incident_root = tempfile.mkdtemp(prefix="bench-federation-incidents-")
    # Containers declared before the try so the finally can clean up
    # whatever a PARTIAL startup managed to spawn (a runner dying before
    # READY must not leak its siblings' processes or WAL flocks).
    procs: "dict[str, subprocess.Popen]" = {}
    clients: "dict[str, BridgeClient]" = {}
    ports: "dict[str, int]" = {}
    peer_ids: "dict[str, int]" = {}
    drivers: list = []

    def command(host_id: str, line: str) -> str:
        proc = procs[host_id]
        proc.stdin.write((line + "\n").encode())
        proc.stdin.flush()
        resp = proc.stdout.readline().decode().strip()
        if not resp or resp.startswith("ERROR"):
            raise RuntimeError(f"{host_id}: {line!r} -> {resp!r}")
        return resp

    def build_epoch(tag: str, plc) -> list:
        """Create + pin p_count proposals on their owning hosts
        (untimed); return (scope, pid, owner_host, chained vote bytes)."""
        out = []
        signers = [StubConsensusSigner(os.urandom(20)) for _ in range(v_count)]
        for p in range(p_count):
            scope = f"{tag}-p{p}"
            host, shard = plc.owner(scope)
            pid, blob = clients[host].create_proposal(
                peer_ids[host], scope, now, f"p{p}", b"payload",
                v_count, 3_600,
            )
            plc.pin(scope, shard)
            proposal = Proposal.decode(blob)
            votes: list[bytes] = []
            for signer in signers:
                vote = build_vote(proposal, True, signer, now + 1)
                proposal.votes.append(vote)
                votes.append(vote.encode())
            out.append((scope, pid, host, votes))
        return out

    def chunks(votes: "list[bytes]") -> "list[list[bytes]]":
        return [votes[i : i + chunk] for i in range(0, len(votes), chunk)]

    def run_arm(driver, epoch) -> float:
        t0 = time.perf_counter()
        for scope, _pid, _host, votes in epoch:
            for part in chunks(votes):
                driver.submit(scope, part, now + 1)
            driver.pump()
        report = driver.drain()
        wall = time.perf_counter() - t0
        assert report["rejected"] == 0 and report["buffered"] == 0, report
        assert report["acked"] == total_votes, report
        return wall

    def control_rate() -> float:
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(200):
                clients["h0"].ping()
            rates.append(200 / (time.perf_counter() - t0))
        return round(sorted(rates)[1], 1)

    migration: "dict | None" = None
    slo_detail: "dict | None" = None
    try:
        for host_id in host_ids:
            procs[host_id] = subprocess.Popen(
                [sys.executable, runner,
                 "--host-id", host_id,
                 "--hosts", ",".join(host_ids),
                 "--shards-per-host", str(shards_per_host),
                 "--capacity", "512",
                 "--voter-capacity", str(v_count + 2)],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                env=dict(
                    env,
                    HASHGRAPH_INCIDENT_DIR=os.path.join(
                        incident_root, host_id
                    ),
                ),
                cwd=repo,
            )
        for host_id, proc in procs.items():
            line = proc.stdout.readline().decode()
            assert line.startswith("READY "), f"host runner said: {line!r}"
            _, port_s, peer_s = line.split()
            ports[host_id] = int(port_s)
            peer_ids[host_id] = int(peer_s)
            clients[host_id] = BridgeClient(
                "127.0.0.1", int(port_s), timeout=60.0
            )

        driver_fed = FederationDriver(placement)
        drivers.append(driver_fed)
        driver_single = FederationDriver(single)
        drivers.append(driver_single)
        for host_id in host_ids:
            driver_fed.connect(
                host_id, "127.0.0.1", ports[host_id], peer_ids[host_id]
            )
        driver_single.connect(
            "h0", "127.0.0.1", ports["h0"], peer_ids["h0"]
        )

        # Untimed warmup pair: jit at these shapes on every host.
        run_arm(driver_fed, build_epoch("w-fed", placement))
        run_arm(driver_single, build_epoch("w-one", single))

        fed_rates: list[float] = []
        single_rates: list[float] = []
        controls: list[float] = [control_rate()]
        per_host = {h: 0 for h in host_ids}
        for rep in range(reps):
            single_rates.append(
                total_votes / run_arm(
                    driver_single, build_epoch(f"r{rep}-one", single)
                )
            )
            controls.append(control_rate())
            epoch_fed = build_epoch(f"r{rep}-fed", placement)
            if rep == 0:
                # Attribution captured AT BUILD TIME: the later
                # migration rep re-homes a shard and would otherwise
                # rewrite rep 0's ownership history.
                for _scope, _pid, owner_host, owner_votes in epoch_fed:
                    per_host[owner_host] += len(owner_votes)
            fed_rates.append(total_votes / run_arm(driver_fed, epoch_fed))
            controls.append(control_rate())

        # ── The live-migration rep (under sustained traffic) ───────────
        epoch = build_epoch("mig", placement)
        h0_scopes = [e for e in epoch if e[2] == "h0"]
        assert h0_scopes, "no scope landed on h0 (placement bug)"
        shard = placement.pinned(h0_scopes[0][0])
        dst_host = host_ids[1]
        # Proposal-major interleave so every shard sees traffic across
        # the whole window.
        stream = [
            (scope, part)
            for parts in zip(*(
                [(scope, part) for part in chunks(votes)]
                for scope, _pid, _host, votes in epoch
            ))
            for scope, part in parts
        ]
        trigger = max(1, int(len(stream) * 0.4))
        mig_out: dict = {}
        mig_err: list = []

        def do_migration() -> None:
            try:
                t0 = time.perf_counter()
                driver_fed.begin_shard_migration(shard, retry_after=0.25)
                resp = command("h0", f"EXPORT {shard} 0.25")
                _, export_peer, src_fp = resp.split()
                resp = command(
                    dst_host,
                    f"ADOPT {shard} 127.0.0.1 {ports['h0']} {export_peer}",
                )
                _, sessions_s, dst_fp = resp.split()
                assert src_fp == dst_fp, (
                    f"migration fingerprint mismatch: {src_fp[:16]} != "
                    f"{dst_fp[:16]}"
                )
                for host_id in host_ids:
                    command(host_id, f"FLIP {shard} {dst_host}")
                flip = driver_fed.complete_shard_migration(shard, dst_host)
                command("h0", f"RETIRE {shard} {export_peer}")
                mig_out.update(
                    shard=shard,
                    to=dst_host,
                    sessions_moved=int(sessions_s),
                    fingerprint_equal=True,
                    fingerprint=src_fp,
                    tail_votes_replayed=flip["tail_votes"],
                    seconds=round(time.perf_counter() - t0, 3),
                )
            except BaseException as exc:  # surfaced after the join
                mig_err.append(exc)

        # Pace the stream so the submission window is LONGER than the
        # migration: the dip (the frozen shard's votes buffering instead
        # of flowing) and the recovery (tail replay + resumed routing)
        # are then visible as per-window rates instead of one spike.
        target_window = 2.5 if smoke else 4.0
        pace = target_window / len(stream)
        marks: list[tuple[float, int]] = []  # (t, votes flowing)
        mig_thread = None
        t0 = time.perf_counter()
        mig_t = [None, None]
        for k, (scope, part) in enumerate(stream):
            if k == trigger:
                mig_t[0] = time.perf_counter() - t0
                mig_thread = _threading.Thread(
                    target=do_migration, name="migration"
                )
                mig_thread.start()
            outcome = driver_fed.submit(scope, part, now + 1)
            driver_fed.pump()
            if outcome == "sent":
                marks.append((time.perf_counter() - t0, len(part)))
            deadline = t0 + pace * (k + 1)
            while time.perf_counter() < deadline:
                driver_fed.pump()
                time.sleep(0.002)
        assert mig_thread is not None
        mig_thread.join(timeout=120)
        assert not mig_thread.is_alive(), "migration thread hung"
        if mig_err:
            raise mig_err[0]
        mig_t[1] = mig_t[0] + mig_out["seconds"]
        # The drained tail replayed at the flip: its votes re-enter the
        # flow there — the recovery half of the dip.
        marks.append(
            (mig_t[1], mig_out["tail_votes_replayed"])
        )
        report = driver_fed.drain()
        wall = time.perf_counter() - t0
        # ZERO LOST VOTES: everything submitted (incl. the frozen-window
        # tail, replayed after the flip) was acked by an owner.
        assert report["buffered"] == 0 and report["rejected"] == 0, report
        assert report["acked"] == total_votes, report
        # ZERO LOST DECISIONS: every session decided on its CURRENT
        # owner (migrated scopes now answer from the adopting host).
        for scope, pid, _host, _votes in epoch:
            owner_host, _shard = placement.owner(scope)
            result = clients[owner_host].get_result(
                peer_ids[owner_host], scope, pid
            )
            assert result is True, (scope, pid, owner_host, result)
        # Dip + recovery: votes/s in ~10 equal windows across the rep.
        n_windows = 10
        window_rates = []
        for w in range(n_windows):
            lo, hi = wall * w / n_windows, wall * (w + 1) / n_windows
            votes_in = sum(v for t, v in marks if lo <= t < hi)
            window_rates.append(round(votes_in / (wall / n_windows), 1))
        migration = dict(mig_out)
        migration.update(
            rep_votes_per_sec=round(total_votes / wall, 1),
            window_votes_per_sec=window_rates,
            migration_window=[round(mig_t[0], 3), round(mig_t[1], 3)],
            decisions_verified=len(epoch),
            zero_lost_votes=True,
            zero_lost_decisions=True,
        )

        # ── SLO plane: merged federated scrape + induced breach ────────
        # Healthy picture first: every decision so far was best-effort
        # (no declared objective), so the per-host windowed quantiles
        # describe the bench traffic and nothing alerts.
        healthy_slo = driver_fed.merged_slo()
        per_host_p99_ms = {
            h: round(
                (healthy_slo["hosts"].get(h, {}).get("global") or {})
                .get("p99", 0.0) * 1_000.0, 3,
            )
            for h in host_ids
        }
        worst_p99_ms = max(per_host_p99_ms.values())
        slo_objective_ms = 5_000.0
        healthy_verdict = {
            "objective_ms": slo_objective_ms,
            "worst_host_p99_ms": worst_p99_ms,
            "pass": bool(
                not healthy_slo["alerts_firing"]
                and worst_p99_ms <= slo_objective_ms
            ),
        }

        # Induced breach: declare an impossible objective (1us) on a few
        # fresh scopes, each ON ITS OWNING HOST, then decide them — every
        # decide breaches, the multi-window burn rate saturates, and the
        # owning host's SLO engine fires + dumps an incident linking the
        # breaching decision's trace id.
        breach_scopes = [f"slo-probe-{i}" for i in range(3)]
        probe_signers = [
            StubConsensusSigner(os.urandom(20)) for _ in range(v_count)
        ]
        for probe in breach_scopes:
            owner_host, owner_shard = placement.owner(probe)
            command(owner_host, f"SLOCFG {probe} 0.001")
            pid, blob = clients[owner_host].create_proposal(
                peer_ids[owner_host], probe, now, "slo", b"payload",
                v_count, 3_600,
            )
            placement.pin(probe, owner_shard)
            proposal = Proposal.decode(blob)
            probe_votes: "list[bytes]" = []
            for signer in probe_signers:
                vote = build_vote(proposal, True, signer, now + 1)
                proposal.votes.append(vote)
                probe_votes.append(vote.encode())
            for part in chunks(probe_votes):
                driver_fed.submit(probe, part, now + 1)
            driver_fed.pump()
        probe_report = driver_fed.drain()
        assert probe_report["acked"] == len(breach_scopes) * v_count, (
            probe_report
        )

        merged_text = driver_fed.merged_metrics_text()
        merged_slo = driver_fed.merged_slo()
        hosts_labelled = all(
            f'host="{h}"' in merged_text for h in host_ids
        )
        decision_histogram = (
            "hashgraph_decision_latency_seconds_bucket" in merged_text
        )
        assert hosts_labelled, "merged scrape missing a host label"
        assert decision_histogram, "merged scrape missing decide histogram"
        firing = sorted(merged_slo["alerts_firing"])
        for probe in breach_scopes:
            assert any(a.endswith(f"/{probe}") for a in firing), (
                probe, firing,
            )

        incidents = []
        for host_id in host_ids:
            host_dir = os.path.join(incident_root, host_id)
            if not os.path.isdir(host_dir):
                continue
            for name in sorted(os.listdir(host_dir)):
                inc_dir = os.path.join(host_dir, name)
                with open(os.path.join(inc_dir, "incident.json")) as fh:
                    meta = json.load(fh)
                with open(os.path.join(inc_dir, "trace.json")) as fh:
                    trace_doc = json.load(fh)
                incidents.append({
                    "host": host_id,
                    "name": name,
                    "reason": meta["reason"],
                    "scope": meta["scope"],
                    "trace_linked": bool(meta.get("trace_id")),
                    "perfetto_loadable": "traceEvents" in trace_doc,
                })
        assert incidents, "induced breach produced no incident dump"
        assert any(
            i["perfetto_loadable"] and i["trace_linked"] for i in incidents
        ), incidents

        slo_detail = {
            "windowed_per_host_p99_ms": per_host_p99_ms,
            "windowed_decisions": healthy_slo["global"]["count"],
            "verdict": healthy_verdict,
            "merged_scrape": {
                "hosts_labelled": hosts_labelled,
                "decision_histogram": decision_histogram,
            },
            "induced_breach": {
                "scopes": breach_scopes,
                "alerts_firing": firing,
                "incidents": incidents,
            },
        }
    finally:
        for driver in drivers:
            driver.close()
        for client in clients.values():
            client.close()
        for proc in procs.values():
            try:
                proc.stdin.close()  # EOF = the runner's shutdown signal
                proc.wait(timeout=15)
            except Exception:
                proc.kill()
        shutil.rmtree(incident_root, ignore_errors=True)

    med_fed = sorted(fed_rates)[len(fed_rates) // 2]
    med_single = sorted(single_rates)[len(single_rates) // 2]
    scaling = round(med_fed / med_single, 2) if med_single else None
    max_spread = max(
        spread_pct(fed_rates), spread_pct(single_rates), spread_pct(controls)
    )
    separated = min(fed_rates) > max(single_rates)
    outside_noise = (
        scaling is not None and scaling > 1.0 + 2.0 * max_spread / 100.0
    )
    # The scaling CLAIM needs real parallel hardware (run_fleet's
    # criterion): N host processes on a shared-core CPU box contend for
    # the same substrate, so the verdict gates reproducibility there.
    shared_substrate = (os.cpu_count() or 2) < 2 * n_hosts
    if not shared_substrate:
        verdict_pass = bool(separated and outside_noise)
        criterion = (
            "min(federated reps) > max(single-host reps) AND "
            "scaling > 1 + 2*max_spread"
        )
    else:
        verdict_pass = spread_pct(fed_rates) < 33.3
        criterion = (
            f"no scaling claim ({os.cpu_count()} cores for {n_hosts} "
            "host processes + driver): federated rep spread < 33%"
        )
    noise_verdict = {
        "pass": verdict_pass,
        "criterion": criterion,
        "federated_votes_per_sec": round(med_fed, 1),
        "single_host_votes_per_sec": round(med_single, 1),
        "scaling": scaling,
        "shared_substrate": shared_substrate,
        "federated_reps": [round(r, 1) for r in fed_rates],
        "single_host_reps": [round(r, 1) for r in single_rates],
        "control_pings_per_sec": controls,
        "spread_pct": {
            "federated": spread_pct(fed_rates),
            "single_host": spread_pct(single_rates),
            "control": spread_pct(controls),
        },
    }
    return {
        "metric": "federation_aggregate_votes_per_sec",
        "value": round(med_fed, 1),
        "unit": "votes/sec",
        "detail": {
            "hosts": n_hosts,
            "shards_per_host": shards_per_host,
            "proposals": p_count,
            "votes_per_proposal": v_count,
            "chunk_votes": chunk,
            "votes_per_rep": total_votes,
            "per_host_votes_r0": per_host,
            "tally_path": "fabric",  # CPU backend: no cross-process psum
            "noise_verdict": noise_verdict,
            "migration": migration,
            "slo": slo_detail,
            "smoke": smoke,
        },
    }


def run_slo_overhead(
    p_count: int = 192,
    v_count: int = 32,
    reps: int = 5,
    smoke: bool = False,
) -> dict:
    """Always-on SLO tracking cost: paired A/B of the same decision-heavy
    workload with the process-global SloEngine enabled vs disabled.

    Each rep runs one engine through ``p_count`` proposals x ``v_count``
    voters to decision with ``decide_p99_ms`` declared on every scope —
    the WORST case for the SLO plane, since every decide walks the full
    observe path (windowed histogram + burn-rate evaluation + labelled
    gauge upkeep). Arms interleave on-off-on-off in the same window so
    drift hits both; the verdict asserts the median overhead stays under
    the 5% acceptance bar, noise-aware (an overhead claim smaller than
    the rep spread is reported but not failed on).

    ``smoke`` (CI): tiny shapes, 3 paired reps.
    """
    from hashgraph_tpu import (
        CreateProposalRequest,
        ScopeConfigBuilder,
        StubConsensusSigner,
        build_vote,
    )
    from hashgraph_tpu.engine import TpuConsensusEngine
    from hashgraph_tpu.obs import slo_engine

    if smoke:
        p_count, v_count, reps = 48, 16, 3
    now = 1_700_000_000
    total_votes = p_count * v_count
    scope_cfg = ScopeConfigBuilder().with_decide_p99_ms(5_000.0).build()
    signers = [StubConsensusSigner(bytes([k + 1]) * 20) for k in range(v_count)]
    engine = TpuConsensusEngine(
        StubConsensusSigner(b"\x09" * 20),
        capacity=p_count + 8,
        voter_capacity=v_count + 2,
    )

    slo_engine.reset()

    def run_arm(tag: str) -> float:
        # One proposal per scope, every scope carrying a declared
        # objective: each rep is p_count decisions walking the full SLO
        # observe path (all built untimed; only the ingest is timed).
        batch: "list[tuple[str, object]]" = []
        scopes = []
        for p in range(p_count):
            scope = f"{tag}-p{p}"
            scopes.append(scope)
            engine.set_scope_config(scope, scope_cfg)
            request = CreateProposalRequest(
                f"p{p}", b"payload", b"o", v_count, 3_600, True
            )
            pid = engine.create_proposal(scope, request, now).proposal_id
            proposal = engine.get_proposal(scope, pid)
            for signer in signers:
                vote = build_vote(proposal, True, signer, now + 1)
                proposal.votes.append(vote)
                batch.append((scope, vote))
            scopes[-1] = (scope, pid)
        t0 = time.perf_counter()
        engine.ingest_votes(batch, now + 1)
        wall = time.perf_counter() - t0
        for scope, pid in scopes:
            assert engine.get_consensus_result(scope, pid) is True, scope
        engine.delete_scopes([scope for scope, _pid in scopes])
        return wall

    # Untimed warmups compile at these shapes AND pre-install the
    # per-scope labelled gauge families before either arm is timed.
    # Scope names are FIXED per arm (reps recreate the same scopes), so
    # the registry stays bounded and no timed rep pays a gauge install.
    slo_engine.enabled = True
    run_arm("on")
    slo_engine.enabled = False
    run_arm("off")

    on_rates: list[float] = []
    off_rates: list[float] = []
    try:
        for _rep in range(reps):
            slo_engine.enabled = True
            on_rates.append(total_votes / run_arm("on"))
            slo_engine.enabled = False
            off_rates.append(total_votes / run_arm("off"))
    finally:
        slo_engine.enabled = True  # never leave the plane off

    med_on = sorted(on_rates)[len(on_rates) // 2]
    med_off = sorted(off_rates)[len(off_rates) // 2]
    overhead_pct = round(100.0 * (med_off - med_on) / med_off, 2)
    max_spread = max(spread_pct(on_rates), spread_pct(off_rates))
    # Noise-aware bar: an apparent overhead smaller than the rep-to-rep
    # spread is indistinguishable from measurement noise, so it cannot
    # fail the 5% ceiling on its own.
    within_noise = bool(abs(overhead_pct) <= max_spread)
    verdict = {
        "pass": bool(overhead_pct < 5.0 or within_noise),
        "criterion": (
            "median SLO-on throughput within 5% of SLO-off, or the gap "
            "is inside the rep spread (noise)"
        ),
        "overhead_pct": overhead_pct,
        "within_noise": within_noise,
        "spread_pct": {
            "slo_on": spread_pct(on_rates),
            "slo_off": spread_pct(off_rates),
        },
    }
    state = slo_engine.state()
    return {
        "metric": "slo_tracking_overhead_pct",
        "value": overhead_pct,
        "unit": "%",
        "detail": {
            "proposals": p_count,
            "votes_per_proposal": v_count,
            "reps": reps,
            "slo_on_votes_per_sec": [round(r, 1) for r in on_rates],
            "slo_off_votes_per_sec": [round(r, 1) for r in off_rates],
            "median_on": round(med_on, 1),
            "median_off": round(med_off, 1),
            "windowed_decisions_tracked": state["global"]["count"],
            "alerts_firing": state["alerts_firing"],
            "verdict": verdict,
            "smoke": smoke,
        },
    }


def run_profile_overhead(
    p_count: int = 192,
    v_count: int = 32,
    reps: int = 5,
    smoke: bool = False,
) -> dict:
    """Always-on stack-sampling cost: paired A/B of the same
    decision-heavy workload with the continuous profiler sampling vs
    parked — the round-20 analogue of ``run_slo_overhead``.

    The profiler THREAD stays alive in both arms (that is how it ships:
    started once at server start, never joined per-request); only
    ``enabled`` toggles, so the A/B isolates exactly the cost the kill
    switch can remove — ``sys._current_frames()`` walks plus aggregate
    upkeep at the adaptive rate. Arms interleave on-off-on-off in one
    window so drift hits both; the verdict asserts the median overhead
    stays under the 2% acceptance bar, noise-aware (a gap smaller than
    the rep spread is reported, not failed on).

    ``smoke`` (CI): tiny shapes, 3 paired reps.
    """
    from hashgraph_tpu import (
        CreateProposalRequest,
        ScopeConfigBuilder,
        StubConsensusSigner,
        build_vote,
    )
    from hashgraph_tpu.engine import TpuConsensusEngine
    from hashgraph_tpu.obs import default_profiler

    if smoke:
        p_count, v_count, reps = 48, 16, 3
    now = 1_700_000_000
    total_votes = p_count * v_count
    scope_cfg = ScopeConfigBuilder().build()
    signers = [StubConsensusSigner(bytes([k + 1]) * 20) for k in range(v_count)]
    engine = TpuConsensusEngine(
        StubConsensusSigner(b"\x0a" * 20),
        capacity=p_count + 8,
        voter_capacity=v_count + 2,
    )

    def run_arm(tag: str) -> float:
        batch: "list[tuple[str, object]]" = []
        scopes = []
        for p in range(p_count):
            scope = f"{tag}-p{p}"
            engine.set_scope_config(scope, scope_cfg)
            request = CreateProposalRequest(
                f"p{p}", b"payload", b"o", v_count, 3_600, True
            )
            pid = engine.create_proposal(scope, request, now).proposal_id
            proposal = engine.get_proposal(scope, pid)
            for signer in signers:
                vote = build_vote(proposal, True, signer, now + 1)
                proposal.votes.append(vote)
                batch.append((scope, vote))
            scopes.append((scope, pid))
        t0 = time.perf_counter()
        engine.ingest_votes(batch, now + 1)
        wall = time.perf_counter() - t0
        for scope, pid in scopes:
            assert engine.get_consensus_result(scope, pid) is True, scope
        engine.delete_scopes([scope for scope, _pid in scopes])
        return wall

    was_running = default_profiler.running
    was_enabled = default_profiler.enabled
    default_profiler.reset()
    default_profiler.enabled = True
    default_profiler.start()

    # Untimed warmup pair compiles at these shapes before either arm.
    run_arm("on")
    default_profiler.enabled = False
    run_arm("off")

    on_rates: list[float] = []
    off_rates: list[float] = []
    try:
        for _rep in range(reps):
            default_profiler.enabled = True
            on_rates.append(total_votes / run_arm("on"))
            default_profiler.enabled = False
            off_rates.append(total_votes / run_arm("off"))
    finally:
        default_profiler.enabled = was_enabled
        if not was_running:
            default_profiler.stop()

    med_on = sorted(on_rates)[len(on_rates) // 2]
    med_off = sorted(off_rates)[len(off_rates) // 2]
    overhead_pct = round(100.0 * (med_off - med_on) / med_off, 2)
    max_spread = max(spread_pct(on_rates), spread_pct(off_rates))
    within_noise = bool(abs(overhead_pct) <= max_spread)
    snap = default_profiler.snapshot()
    verdict = {
        "pass": bool(overhead_pct < 2.0 or within_noise),
        "criterion": (
            "median profiler-on throughput within 2% of profiler-off, "
            "or the gap is inside the rep spread (noise)"
        ),
        "overhead_pct": overhead_pct,
        "within_noise": within_noise,
        "spread_pct": {
            "profiler_on": spread_pct(on_rates),
            "profiler_off": spread_pct(off_rates),
        },
    }
    return {
        "metric": "profiler_overhead_pct",
        "value": overhead_pct,
        "unit": "%",
        "detail": {
            "proposals": p_count,
            "votes_per_proposal": v_count,
            "reps": reps,
            "profiler_on_votes_per_sec": [round(r, 1) for r in on_rates],
            "profiler_off_votes_per_sec": [round(r, 1) for r in off_rates],
            "median_on": round(med_on, 1),
            "median_off": round(med_off, 1),
            "samples": snap["samples"],
            "sample_roles": snap["roles"],
            "rate_hz": snap["rate_hz"],
            "self_measured_overhead_s": snap["overhead_seconds"],
            "verdict": verdict,
            "smoke": smoke,
        },
    }


def run_regress() -> dict:
    """Perf-regression sentry over the checked-in BENCH_*.json
    trajectory (``tools/bench_regress.py`` as a bench runner, so the
    verdict lands in the same artifact stream it audits). Host-only: no
    engine, no device — it reads the corpus next to this file."""
    import pathlib

    from tools.bench_regress import build_verdict

    verdict = build_verdict(pathlib.Path(__file__).resolve().parent)
    return {
        "metric": "bench_regressions",
        "value": len(verdict["regressions"]),
        "unit": "regressions",
        "detail": verdict,
    }


def run_default() -> dict:
    """The driver-visible sweep: engine-level config 3 as the headline,
    every other BASELINE shape in ``detail`` (one JSON line total).

    The headline is the MEDIAN of three full engine-bench repetitions
    (each itself a median over per-cycle rates), with the cross-repetition
    spread reported alongside — the tunneled TPU link jitters up to 2x
    between identical runs, and a claim that can't survive a bad tunnel
    day isn't a claim (BENCHMARKS.md)."""
    reps = [run_engine_bench() for _ in range(3)]
    values = sorted(r["value"] for r in reps)
    engine = next(r for r in reps if r["value"] == values[1])
    spread_pct = 100.0 * (values[-1] - values[0]) / values[1]
    sections = {
        "pool_level": run_bench(),
        "config2": run_config2(),
        "lanes1024": run_lanes1024(),
        "engine_lanes1024": run_engine_lanes1024(),
        "validated": run_validated(),
        "validated_sweep": run_validated_sweep(),
        "crypto": run_crypto(),
        "config4": run_config4(),
        "engine_config4": run_engine_config4(),
        "config5": run_config5(),
        "engine_config5": run_engine_config5(),
    }
    detail = dict(engine["detail"])
    for name, result in sections.items():
        detail[name] = {
            "metric": result["metric"],
            "value": result["value"],
            "unit": result["unit"],
            "detail": result["detail"],
        }
    # Key order is deliberate: the driver's artifact stores only the TAIL
    # of this (long) line, so the headline fields and the compact per-
    # section summary go LAST — the captured artifact then always carries
    # the headline, vs_baseline, the repetition evidence, and one number
    # per BASELINE shape even when the full detail is truncated away.
    return {
        "metric": engine["metric"],
        "unit": engine["unit"],
        "detail": detail,
        "summary": {name: result["value"] for name, result in sections.items()},
        "headline_repetitions": values,
        "headline_spread_pct": round(spread_pct, 1),
        "value": engine["value"],
        "vs_baseline": engine["vs_baseline"],
    }


if __name__ == "__main__":
    import sys

    # --metrics-out PATH: after the run, snapshot the always-on metrics
    # registry (counters, gauges, histogram count/sum/p50/p90/p99 — e.g.
    # wal_fsync_seconds quantiles, hashgraph_decision_latency_seconds)
    # into the BENCH json alongside the throughput numbers, and also write
    # the full result to PATH (one JSON object).
    args = sys.argv[1:]

    def _pop_flag(name: str) -> str | None:
        """Extract `NAME VALUE` from args; None when absent."""
        if name not in args:
            return None
        flag = args.index(name)
        if flag + 1 >= len(args):
            raise SystemExit(f"{name} requires a value")
        value = args[flag + 1]
        del args[flag : flag + 2]
        return value

    metrics_out = _pop_flag("--metrics-out")

    # --health-out PATH: after the run, snapshot the process-wide health
    # monitor (peer scorecards with grades, equivocation/fork evidence,
    # watchdog state, firing alert rules) to PATH, and fold the alert
    # counts into the BENCH json line — a bench run that tripped an
    # anomaly rule should say so in the artifact, not just in a side file.
    health_out = _pop_flag("--health-out")

    # fleet --hosts N: N > 1 switches the fleet bench to the FEDERATED
    # topology — N OS processes (examples/federation_host.py), two-level
    # (host, shard) placement, cross-host vote routing over the gossip
    # fabric, and a live shard migration under sustained traffic.
    fleet_hosts = _pop_flag("--hosts")

    # fleet --smoke: the CI topology — 2 simulated shards on virtual CPU
    # devices (the conftest trick), exercising routing + the psum tally on
    # boxes with one physical device. Must run before anything initializes
    # the jax backend (incl. the compile-cache default logic below, which
    # reads the device topology); if the backend already initialized
    # (e.g. this interpreter's sitecustomize compiled on the real chip),
    # the fleet falls back to shards sharing a device and says so in
    # ``tally_path``.
    # gossip --stages: force the wire-path stage-attribution block into
    # the BENCH json (decode / crypto / device-apply seconds per rep).
    # Attribution is on by default; the flag exists so `make
    # bench-gossip STAGES=1` has an explicit, stable spelling and so it
    # can be turned OFF (--no-stages) for minimal artifacts.
    gossip_stages = True
    if "--stages" in args:
        args.remove("--stages")
    if "--no-stages" in args:
        args.remove("--no-stages")
        gossip_stages = False
    # gossip --reactor-only: run ONLY the paired reactor-off/on A/B
    # (dedicated pinned peer sets) — `make bench-reactor`'s spelling.
    # --no-reactor-ab drops the reactor pair from the full gossip bench
    # for minimal artifacts.
    gossip_reactor_ab = True
    gossip_reactor_only = False
    if "--no-reactor-ab" in args:
        args.remove("--no-reactor-ab")
        gossip_reactor_ab = False
    if "--reactor-only" in args:
        args.remove("--reactor-only")
        gossip_reactor_only = True

    fleet_smoke = "--smoke" in args
    if fleet_smoke:
        args.remove("--smoke")
        import os as _os

        _flags = _os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            _os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=2"
            ).strip()
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass

    # --compile-cache DIR: JAX's persistent compilation cache, ON BY
    # DEFAULT (BENCH_r05 measured 147.7 s of compile warmup in
    # engine_config4 alone; a re-run at the same geometry should never
    # pay it twice). Default location is per-user
    # (~/.cache/hashgraph_tpu/xla-cache); pass --compile-cache DIR to
    # relocate or --no-compile-cache to opt out (e.g. when measuring
    # compile time itself). Thresholds are zeroed so every program is
    # cached, tiny ones included — the bench's many small dispatch shapes
    # are exactly the ones worth keeping.
    #
    # EXCEPTION (defaulted off, explicit flag still wins): multi-device
    # CPU meshes. On the pinned jaxlib, programs deserialized from the
    # persistent cache under --xla_force_host_platform_device_count>1
    # return WRONG RESULTS and segfault at teardown (reproduced with the
    # fleet's shard_map kernels: corrupted psum tallies, 1936/2048 OK
    # rows on a batch that applies 2048/2048 cold — see BENCHMARKS.md
    # "Fleet" methodology note). Single-device CPU and TPU paths verify
    # clean, so only the known-bad combination opts out.
    compile_cache = _pop_flag("--compile-cache")
    no_compile_cache = "--no-compile-cache" in args
    if no_compile_cache:
        args.remove("--no-compile-cache")
        if compile_cache is not None:
            raise SystemExit(
                "--compile-cache and --no-compile-cache are mutually exclusive"
            )

    def _setup_compile_cache(which: str) -> None:
        """Resolve + activate the compile-cache default. Deferred until
        the mode is known: the default-enable decision probes the device
        topology, which initializes the accelerator backend — a cost the
        host-only modes (pure filesystem / host crypto, zero XLA
        programs) must not pay just for arg parsing."""
        global compile_cache
        import os

        if no_compile_cache:
            return
        if compile_cache is None:
            if which in ("wal", "crypto", "regress"):
                return  # host-only: nothing to cache
            import jax

            devices = jax.devices()
            if devices[0].platform == "cpu" and len(devices) > 1:
                print(
                    "compile cache left off: multi-device CPU meshes "
                    "mis-deserialize cached programs on this jaxlib "
                    "(wrong tallies + teardown segfault); pass "
                    "--compile-cache DIR to force",
                    file=sys.stderr,
                )
                return
            compile_cache = os.path.join(
                os.path.expanduser("~"), ".cache", "hashgraph_tpu", "xla-cache"
            )
            try:
                os.makedirs(compile_cache, exist_ok=True)
            except OSError as exc:
                print(
                    f"compile cache disabled ({exc}); pass --compile-cache "
                    "DIR for a writable location",
                    file=sys.stderr,
                )
                compile_cache = None
                return
        import jax

        jax.config.update("jax_compilation_cache_dir", compile_cache)
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except AttributeError:
            # Older JAX: the directory option alone still caches programs
            # above its built-in thresholds.
            pass
        print(f"persistent compilation cache at {compile_cache}",
              file=sys.stderr)

    # --trace-out PATH: run the whole bench under one distributed trace
    # context (so every observed_span — device ingest, verify batches,
    # WAL fsyncs — lands context-tagged in the trace store) and export a
    # Chrome trace-event file Perfetto opens directly. Pair with a
    # jax.profiler capture over the same window to correlate host spans
    # with device timelines on one wall-clock axis.
    trace_out = _pop_flag("--trace-out")
    _trace_cm = None
    if trace_out is not None:
        from hashgraph_tpu.obs.trace import (
            TraceContext,
            trace_store,
            use_context,
        )

        _root_ctx = TraceContext.generate()
        _trace_cm = use_context(_root_ctx)
        _trace_cm.__enter__()
        print(
            f"trace context {_root_ctx.to_traceparent()} -> {trace_out}",
            file=sys.stderr,
        )

    # --metrics-port N: serve /metrics + /healthz for the duration of the
    # run (0 = ephemeral; the bound address is printed to stderr so stdout
    # stays one JSON line), so `curl` can watch histograms fill live.
    sidecar = None
    sidecar_port = _pop_flag("--metrics-port")
    if sidecar_port is not None:
        from hashgraph_tpu.obs import MetricsSidecar, registry

        sidecar = MetricsSidecar(registry, port=int(sidecar_port))
        host, port = sidecar.start()
        print(f"metrics sidecar listening on http://{host}:{port}/metrics",
              file=sys.stderr)

    which = args[0] if args else "default"
    _setup_compile_cache(which)
    runners = {
        "engine": run_engine_bench,
        "pool": run_bench,
        "config3": run_bench,  # historical alias
        "config2": run_config2,
        "config4": run_config4,
        "engine_config4": run_engine_config4,
        "config5": run_config5,
        "engine_config5": run_engine_config5,
        "engine_config5_retained": lambda: run_engine_config5(retain=True),
        "lanes1024": run_lanes1024,
        "engine_lanes1024": run_engine_lanes1024,
        "deepchain": run_deepchain,
        "crypto": run_crypto,
        "validated": run_validated,
        "validated-sweep": run_validated_sweep,
        "validated_sweep": run_validated_sweep,  # shell-friendly alias
        "device-verify": lambda: run_device_verify(smoke=fleet_smoke),
        "device_verify": lambda: run_device_verify(smoke=fleet_smoke),
        "redelivery": run_redelivery,
        "wal": run_wal,
        "fleet": lambda: (
            run_federation(
                n_hosts=int(fleet_hosts), smoke=fleet_smoke
            )
            if fleet_hosts is not None and int(fleet_hosts) > 1
            else run_fleet(smoke=fleet_smoke)
        ),
        "catchup": lambda: run_catchup(smoke=fleet_smoke),
        "gossip": lambda: run_gossip(
            smoke=fleet_smoke,
            stages=gossip_stages,
            reactor_ab=gossip_reactor_ab,
            reactor_only=gossip_reactor_only,
        ),
        "chaos": lambda: run_chaos(smoke=fleet_smoke),
        "liveness": lambda: run_liveness(smoke=fleet_smoke),
        "churn": lambda: run_churn(smoke=fleet_smoke),
        "slo-overhead": lambda: run_slo_overhead(smoke=fleet_smoke),
        "slo_overhead": lambda: run_slo_overhead(smoke=fleet_smoke),
        "profile-overhead": lambda: run_profile_overhead(smoke=fleet_smoke),
        "profile_overhead": lambda: run_profile_overhead(smoke=fleet_smoke),
        "regress": run_regress,
        "default": run_default,
    }
    def _registry_snapshot() -> dict:
        from hashgraph_tpu.obs import registry

        return registry.snapshot()

    def _health_snapshot() -> dict:
        from hashgraph_tpu.obs import health_monitor

        return health_monitor.snapshot()

    def _health_summary(snap: dict) -> dict:
        """Compact alert view for the BENCH json line (the full
        scorecard/evidence snapshot lives in --health-out's file)."""
        firing = snap["alerts"]["firing"]
        grades: dict[str, int] = {}
        for card in snap["peers"].values():
            grades[card["grade"]] = grades.get(card["grade"], 0) + 1
        return {
            "alert_events_total": snap["alerts"]["events_total"],
            "alerts_firing": [a["rule"] for a in firing],
            "evidence_records": len(snap["evidence"]),
            "peer_grades": grades,
        }

    # finally: a run that RAISES is exactly the one whose trace matters —
    # the export (and sidecar shutdown) must survive runner failures.
    try:
        if which == "all":
            results = {}
            for name in (
                "engine",
                "pool",
                "config2",
                "lanes1024",
                "engine_lanes1024",
                "validated",
                "crypto",
                "config4",
                "engine_config4",
                "config5",
                "engine_config5",
                "engine_config5_retained",
            ):
                results[name] = runners[name]()
                print(json.dumps(results[name]))
            if metrics_out is not None:
                with open(metrics_out, "w") as fh:
                    json.dump(
                        {"results": results, "metrics": _registry_snapshot()}, fh
                    )
            if health_out is not None:
                snap = _health_snapshot()
                with open(health_out, "w") as fh:
                    json.dump(snap, fh)
                print(json.dumps({"health": _health_summary(snap)}))
        else:
            result = runners[which]()
            if health_out is not None:
                snap = _health_snapshot()
                with open(health_out, "w") as fh:
                    json.dump(snap, fh)
                result["health"] = _health_summary(snap)
            if metrics_out is not None:
                result["metrics"] = _registry_snapshot()
                with open(metrics_out, "w") as fh:
                    json.dump(result, fh)
            print(json.dumps(result))
    finally:
        # Cleanup steps are independent: a failing trace export must not
        # mask the runner's real exception or skip the sidecar shutdown.
        try:
            if _trace_cm is not None:
                _trace_cm.__exit__(None, None, None)
                from hashgraph_tpu.obs.trace import trace_store

                events = trace_store.export_chrome(trace_out)
                dropped = (
                    f" ({trace_store.dropped} spans dropped at the store cap)"
                    if trace_store.dropped
                    else ""
                )
                print(
                    f"wrote {events} trace events to {trace_out}{dropped}",
                    file=sys.stderr,
                )
        except Exception as exc:
            print(f"trace export failed: {exc!r}", file=sys.stderr)
        finally:
            if sidecar is not None:
                sidecar.stop()
