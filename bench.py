"""Benchmarks over the device pool (BASELINE.md configs).

Default (bare ``python bench.py``) runs config 3 — 10k concurrent proposals
× 64 voters, batched tally, single TPU core — and prints ONE JSON line:
votes ingested/sec vs the 1M/s north-star baseline. Other configs via argv:

  python bench.py config2   # 1 proposal x 1024 voters, P2P: finality latency
  python bench.py config4   # scopes x proposals x 256 voters, 30% absent,
                            # liveness-timeout path (sharded when >1 device)
  python bench.py config5   # streaming mixed Gossipsub+P2P replay
  python bench.py all

Traces are pre-validated replays (signature/hash verification is the
pluggable host stage, benchmarked separately in tests/test_native.py; the
reference's own tests hand-deliver already-validated votes the same way) —
these measure the consensus engine proper: packed transfer → scatter →
arrival-ordered scan → fused decision kernel → status readback, pipelined
the way a streaming embedder would drive it.
"""

from __future__ import annotations

import json
import time

import numpy as np


def run_bench(
    p_count: int = 10_240,
    v_count: int = 64,
    votes_per_dispatch: int = 8,
    cycles: int = 5,
) -> dict:
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import required_votes_np

    rng = np.random.default_rng(7)
    now = 1_700_000_000

    pool = ProposalPool(p_count, v_count)

    def allocate(cycle: int) -> None:
        # Gossipsub mode, threshold 1.0: every vote is accepted (round cap 2
        # admits any count) and no session decides before its last voter, so
        # every dispatch carries only real, accepted votes.
        pool.allocate_batch(
            keys=[(f"bench{cycle}", i) for i in range(p_count)],
            n=np.full(p_count, v_count),
            req=required_votes_np(np.full(p_count, v_count), 1.0),
            cap=np.full(p_count, 2),
            gossip=np.ones(p_count, bool),
            liveness=np.ones(p_count, bool),
            expiry=np.full(p_count, now + 10_000),
            created_at=np.full(p_count, now),
        )

    L = votes_per_dispatch
    dispatches_per_cycle = v_count // L
    slots = np.repeat(np.arange(p_count, dtype=np.int64), L)

    def dispatch(d: int):
        # L votes per proposal per dispatch: lanes d*L..(d+1)*L-1.
        lanes = np.tile(np.arange(d * L, (d + 1) * L, dtype=np.int32), p_count)
        values = rng.random(p_count * L) < 0.5
        return pool.ingest_async(slots, lanes, values, now)

    def run_cycle(check: bool) -> None:
        pendings = [dispatch(d) for d in range(dispatches_per_cycle)]
        results = pool.complete_all(pendings)
        if check:
            for d, (statuses, _) in enumerate(results):
                assert int(statuses[0]) == 0, f"dispatch {d}: {statuses[0]}"

    # Warmup: compile every kernel the timed loop uses (allocate, ingest,
    # release) so the measured window is pure steady-state throughput.
    all_slots = list(range(p_count))
    allocate(0)
    run_cycle(check=True)
    pool.release(all_slots)
    allocate(0)
    run_cycle(check=True)

    jax.block_until_ready(pool._state)
    # Per-cycle timing with a median report: the tunneled link has high
    # run-to-run jitter (2x between identical runs), and one slow RPC
    # shouldn't define the engine's throughput number.
    cycle_votes = p_count * v_count
    rates = []
    for cycle in range(1, cycles + 1):
        start = time.perf_counter()
        pool.release(all_slots)
        allocate(cycle)
        run_cycle(check=False)
        rates.append(cycle_votes / (time.perf_counter() - start))
    rates.sort()
    throughput = rates[len(rates) // 2]
    return {
        "metric": "vote_ingest_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "proposals": p_count,
            "voters": v_count,
            "votes_per_cycle": cycle_votes,
            "cycles": cycles,
            "cycle_rates": [round(r, 1) for r in rates],
            "platform": jax.devices()[0].platform,
        },
    }


def run_config2(voters: int = 1024, repeats: int = 9) -> dict:
    """1 proposal × 1024 voters, P2P dynamic rounds: p50 finality latency.

    The P2P cap is ceil(2n/3) votes; a unanimous YES replay decides at
    req = ceil(2n/3) = 683 votes. The whole chain arrives as one dispatch
    (scan depth = 683), timing first-vote-to-decision wall clock.
    """
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import STATE_REACHED_YES, required_votes_np

    now = 1_700_000_000
    cap = (2 * voters + 2) // 3
    pool = ProposalPool(8, voters)
    latencies = []
    for rep in range(repeats + 1):  # first is compile warmup
        pool.allocate_batch(
            keys=[(rep, 0)],
            n=np.array([voters]),
            req=required_votes_np(np.array([voters]), 2.0 / 3.0),
            cap=np.array([cap]),
            gossip=np.array([False]),
            liveness=np.array([True]),
            expiry=np.array([now + 1000]),
            created_at=np.array([now]),
        )
        slots = np.zeros(cap, np.int64)
        lanes = np.arange(cap, dtype=np.int32)
        values = np.ones(cap, bool)
        start = time.perf_counter()
        statuses, transitions = pool.ingest(slots, lanes, values, now)
        latency = time.perf_counter() - start
        assert transitions and transitions[0][1] == STATE_REACHED_YES
        if rep > 0:
            latencies.append(latency)
        pool.release([0])
    latencies.sort()
    p50 = latencies[len(latencies) // 2]
    return {
        "metric": "p2p_finality_latency_p50",
        "value": round(p50 * 1000, 3),
        "unit": "ms",
        "vs_baseline": None,
        "detail": {
            "voters": voters,
            "votes_to_quorum": cap,
            "latencies_ms": [round(l * 1000, 2) for l in latencies],
            "platform": jax.devices()[0].platform,
        },
    }


def run_config4(
    scopes: int = 64, proposals_per_scope: int = 256, voters: int = 256
) -> dict:
    """Byzantine/absent liveness path: 30% of voters never vote; sessions
    finalize via the timeout sweep. Sharded over all available devices."""
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import (
        STATE_ACTIVE,
        required_votes_np,
    )
    from hashgraph_tpu.parallel import ShardedPool, consensus_mesh

    rng = np.random.default_rng(11)
    now = 1_700_000_000
    p_count = scopes * proposals_per_scope
    n_dev = len(jax.devices())
    if n_dev > 1:
        per_dev = -(-p_count // n_dev)
        pool = ShardedPool(per_dev, voters, consensus_mesh())
    else:
        pool = ProposalPool(p_count, voters)

    pool.allocate_batch(
        keys=[(f"s{i % scopes}", i) for i in range(p_count)],
        n=np.full(p_count, voters),
        req=required_votes_np(np.full(p_count, voters), 2.0 / 3.0),
        cap=np.full(p_count, 2),
        gossip=np.ones(p_count, bool),
        liveness=rng.random(p_count) < 0.5,
        expiry=np.full(p_count, now + 100),
        created_at=np.full(p_count, now),
    )

    # 70% participation, random yes/no, streamed in lane-rounds.
    present = int(voters * 0.7)
    slots = np.repeat(np.arange(p_count, dtype=np.int64), 8)
    start = time.perf_counter()
    total_votes = 0
    pendings = []
    for base_lane in range(0, present, 8):
        width = min(8, present - base_lane)
        sl = np.repeat(np.arange(p_count, dtype=np.int64), width)
        lanes = np.tile(
            np.arange(base_lane, base_lane + width, dtype=np.int32), p_count
        )
        values = rng.random(p_count * width) < 0.5
        pendings.append(pool.ingest_async(sl, lanes, values, now))
        total_votes += p_count * width
    pool.complete_all(pendings)
    # Liveness sweep finalizes everything still active.
    active = [s for s in range(p_count) if pool.state_of(s) == STATE_ACTIVE]
    swept = pool.timeout(active)
    elapsed = time.perf_counter() - start

    undecided = sum(1 for _, st in swept if st == STATE_ACTIVE)
    throughput = total_votes / elapsed
    return {
        "metric": "byzantine_timeout_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "scopes": scopes,
            "proposals": p_count,
            "voters": voters,
            "absent_pct": 30,
            "votes": total_votes,
            "timeout_decisions": len(swept),
            "undecided_after_sweep": undecided,
            "seconds": round(elapsed, 3),
            "devices": n_dev,
        },
    }


def run_config5(p_count: int = 65_536, v_count: int = 48) -> dict:
    """Streaming mixed Gossipsub+P2P replay: a large arrival-ordered trace
    applied through the pipelined ingest path (config-5 scaled to one chip;
    the full 1M-proposal replay is this shape run repeatedly)."""
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import required_votes_np

    rng = np.random.default_rng(23)
    now = 1_700_000_000
    pool = ProposalPool(p_count, v_count)

    gossip = rng.random(p_count) < 0.5
    caps = np.where(gossip, 2, (2 * v_count + 2) // 3)
    pool.allocate_batch(
        keys=[("stream", i) for i in range(p_count)],
        n=np.full(p_count, v_count),
        req=required_votes_np(np.full(p_count, v_count), 2.0 / 3.0),
        cap=caps,
        gossip=gossip,
        liveness=rng.random(p_count) < 0.5,
        expiry=np.full(p_count, now + 10_000),
        created_at=np.full(p_count, now),
    )

    # Stream rounds of one-vote-per-proposal through the full voter set:
    # gossip sessions decide once quorum lands (~vote 32 of 48), P2P
    # sessions hit their ceil(2n/3) caps, and later rounds exercise the
    # ALREADY_REACHED / SESSION_NOT_ACTIVE absorption paths — exactly like
    # a replayed gossip trace.
    rounds = v_count
    total_votes = 0
    start = time.perf_counter()
    pendings = []
    slots = np.arange(p_count, dtype=np.int64)
    for r in range(rounds):
        lanes = np.full(p_count, r, np.int32)
        values = rng.random(p_count) < 0.55
        pendings.append(pool.ingest_async(slots, lanes, values, now))
        total_votes += p_count
        if len(pendings) >= 8:
            pool.complete_all(pendings)
            pendings = []
    if pendings:
        pool.complete_all(pendings)
    elapsed = time.perf_counter() - start

    counts = pool.state_counts()
    throughput = total_votes / elapsed
    return {
        "metric": "streaming_mixed_replay_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "proposals": p_count,
            "voters": v_count,
            "votes": total_votes,
            "seconds": round(elapsed, 3),
            "final_state_counts": {str(k): v for k, v in counts.items()},
            "platform": jax.devices()[0].platform,
        },
    }


if __name__ == "__main__":
    import sys

    which = sys.argv[1] if len(sys.argv) > 1 else "config3"
    runners = {
        "config2": run_config2,
        "config3": run_bench,
        "config4": run_config4,
        "config5": run_config5,
    }
    if which == "all":
        for name, fn in runners.items():
            print(json.dumps(fn()))
    else:
        print(json.dumps(runners[which]()))
