"""Benchmark: batched vote-ingest throughput on the device pool.

BASELINE config 3 shape: 10k concurrent proposals × 64 voters, batched tally
on a single TPU core. The trace is a pre-validated replay (signature/hash
verification is the pluggable host stage, benchmarked separately; the
reference's own tests hand-deliver already-validated votes the same way) —
this measures the consensus engine proper: packed transfer → scatter →
arrival-ordered scan → fused decision kernel → status readback, via the same
ProposalPool ingest path the engine uses in production, pipelined the way a
streaming embedder would drive it (dispatches in flight, one batched
completion).

Prints ONE JSON line: votes ingested/sec vs the 1M/s north-star baseline.
"""

from __future__ import annotations

import json
import time

import numpy as np


def run_bench(
    p_count: int = 10_240,
    v_count: int = 64,
    votes_per_dispatch: int = 8,
    cycles: int = 5,
) -> dict:
    import jax

    from hashgraph_tpu.engine.pool import ProposalPool
    from hashgraph_tpu.ops.decide import required_votes_np

    rng = np.random.default_rng(7)
    now = 1_700_000_000

    pool = ProposalPool(p_count, v_count)

    def allocate(cycle: int) -> None:
        # Gossipsub mode, threshold 1.0: every vote is accepted (round cap 2
        # admits any count) and no session decides before its last voter, so
        # every dispatch carries only real, accepted votes.
        pool.allocate_batch(
            keys=[(f"bench{cycle}", i) for i in range(p_count)],
            n=np.full(p_count, v_count),
            req=required_votes_np(np.full(p_count, v_count), 1.0),
            cap=np.full(p_count, 2),
            gossip=np.ones(p_count, bool),
            liveness=np.ones(p_count, bool),
            expiry=np.full(p_count, now + 10_000),
            created_at=np.full(p_count, now),
        )

    L = votes_per_dispatch
    dispatches_per_cycle = v_count // L
    slots = np.repeat(np.arange(p_count, dtype=np.int64), L)

    def dispatch(d: int):
        # L votes per proposal per dispatch: lanes d*L..(d+1)*L-1.
        lanes = np.tile(np.arange(d * L, (d + 1) * L, dtype=np.int32), p_count)
        values = rng.random(p_count * L) < 0.5
        return pool.ingest_async(slots, lanes, values, now)

    def run_cycle(check: bool) -> None:
        pendings = [dispatch(d) for d in range(dispatches_per_cycle)]
        results = pool.complete_all(pendings)
        if check:
            for d, (statuses, _) in enumerate(results):
                assert int(statuses[0]) == 0, f"dispatch {d}: {statuses[0]}"

    # Warmup: compile every kernel the timed loop uses (allocate, ingest,
    # release) so the measured window is pure steady-state throughput.
    all_slots = list(range(p_count))
    allocate(0)
    run_cycle(check=True)
    pool.release(all_slots)
    allocate(0)
    run_cycle(check=True)

    jax.block_until_ready(pool._state)
    # Per-cycle timing with a median report: the tunneled link has high
    # run-to-run jitter (2x between identical runs), and one slow RPC
    # shouldn't define the engine's throughput number.
    cycle_votes = p_count * v_count
    rates = []
    for cycle in range(1, cycles + 1):
        start = time.perf_counter()
        pool.release(all_slots)
        allocate(cycle)
        run_cycle(check=False)
        rates.append(cycle_votes / (time.perf_counter() - start))
    rates.sort()
    throughput = rates[len(rates) // 2]
    return {
        "metric": "vote_ingest_throughput",
        "value": round(throughput, 1),
        "unit": "votes/sec",
        "vs_baseline": round(throughput / 1_000_000, 4),
        "detail": {
            "proposals": p_count,
            "voters": v_count,
            "votes_per_cycle": cycle_votes,
            "cycles": cycles,
            "cycle_rates": [round(r, 1) for r in rates],
            "platform": jax.devices()[0].platform,
        },
    }


if __name__ == "__main__":
    print(json.dumps(run_bench()))
