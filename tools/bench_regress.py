"""Perf-regression sentry over the checked-in BENCH_*.json trajectory.

Every bench round in this repo ships a machine-readable artifact
(BENCH_rNN.json) carrying its headline number and — since round 6 —
its own paired-A/B rep spread. This tool reconstructs the per-metric
trajectory across those artifacts and issues NOISE-AWARE verdicts: a
drop between two rounds is a regression only when it exceeds the sum of
both rounds' recorded spreads (a claim the rounds themselves could not
have distinguished from noise cannot convict a later round).

Corpus archaeology the loader handles (see the BENCHMARKS.md
"Bench round ↔ BENCH file" table):

- **r01, r06**: driver-wrapped ``{n, cmd, rc, tail, parsed}`` records
  whose ``parsed`` object is the flat bench line;
- **r02–r05**: the same wrapper but ``parsed: null`` and a
  FRONT-truncated ``tail`` — the artifact keeps only the line's end.
  Where the run_default key order preserved the trailing
  ``headline_spread_pct`` / ``value`` pair (r05) the headline is
  regex-recovered and the entry marked ``recovered``; otherwise the
  file is listed under ``skipped`` with the reason;
- **r07+**: flat ``{metric, value, unit, detail}`` lines.

Confidence discipline: only entries that are neither recovered nor
spread-less participate in hard regression verdicts; everything else
still appears in the trajectory but its comparisons are ``advisory``
(reported, never failing). That is what keeps the existing trajectory
free of FALSE regressions — r01's TPU headline vs r05's recovered CPU
line is a hardware story, not a code regression, and neither point
carries the evidence to say otherwise.

The device-apply busy-share trajectory (round 11's 66.8% → round 19's
50.9%) is reconstructed alongside, so the attribution the continuous
profiler now serves live (``obs/attribution.py``) is checkable against
its own history.

Usage: ``python tools/bench_regress.py [repo_root]`` (also reachable as
``python bench.py regress`` / ``make bench-regress``). Prints one JSON
verdict block; exit status 1 iff a non-advisory regression was found.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

REGRESS_SCHEMA = "hashgraph.bench_regress.v1"

# Artifact file ↔ bench round. r01–r05 were numbered by sequential
# driver run; r06–r09 kept that sequence while the ROUNDS jumped with
# the issue numbers (r06 records round 11's gossip+attribution run, r07
# round 13's federation, r08 round 14's churn, r09 round 18's
# liveness). From BENCH_r19 on the artifact number IS the round number,
# which `_round_for` assumes for any file not pinned here.
ROUND_FOR_FILE = {
    "BENCH_r01.json": 1,
    "BENCH_r02.json": 2,
    "BENCH_r03.json": 3,
    "BENCH_r04.json": 4,
    "BENCH_r05.json": 5,
    "BENCH_r06.json": 11,
    "BENCH_r07.json": 13,
    "BENCH_r08.json": 14,
    "BENCH_r09.json": 18,
}

# Metric implied by the driver command line for recovered (truncated)
# wrapped artifacts, whose leading "metric" key did not survive.
_DEFAULT_SWEEP_METRIC = ("vote_ingest_throughput", "votes/sec")


def _round_for(name: str) -> int | None:
    if name in ROUND_FOR_FILE:
        return ROUND_FOR_FILE[name]
    m = re.match(r"BENCH_r(\d+)\.json$", name)
    return int(m.group(1)) if m else None


def _recorded_spreads(body) -> list[float]:
    """Every rep-spread percentage the artifact recorded about itself
    (``headline_spread_pct`` and any ``spread_pct`` scalar or per-arm
    dict, wherever they appear). The MAX becomes the entry's noise
    figure — conservative by construction."""
    out: list[float] = []

    def walk(node) -> None:
        if isinstance(node, dict):
            for key, value in node.items():
                if key == "headline_spread_pct" and isinstance(
                    value, (int, float)
                ):
                    out.append(float(value))
                elif key == "spread_pct":
                    if isinstance(value, dict):
                        out.extend(
                            float(v)
                            for v in value.values()
                            if isinstance(v, (int, float))
                        )
                    elif isinstance(value, (int, float)):
                        out.append(float(value))
                else:
                    walk(value)
        elif isinstance(node, list):
            for value in node:
                walk(value)

    walk(body)
    return out


def _device_apply_shares(body) -> list[dict]:
    """Device-apply busy-share readings in an artifact: round 11's
    ``stage_attribution.stage_share`` block and round 19's per-arm
    ``device_apply_share`` (its ``r06_baseline`` echo excluded — the
    r06 artifact speaks for itself)."""
    found: list[dict] = []

    def walk(node) -> None:
        if isinstance(node, dict):
            share = node.get("stage_share")
            if isinstance(share, dict) and "device_apply_s" in share:
                found.append(
                    {"arm": "headline", "share": float(share["device_apply_s"])}
                )
            share = node.get("device_apply_share")
            if isinstance(share, dict):
                for arm, value in share.items():
                    if arm != "r06_baseline" and isinstance(
                        value, (int, float)
                    ):
                        found.append({"arm": arm, "share": float(value)})
            for value in node.values():
                walk(value)
        elif isinstance(node, list):
            for value in node:
                walk(value)

    walk(body)
    return found


def _recover_from_tail(tail: str) -> tuple[float, float] | None:
    """(value, headline_spread_pct) regex-recovered from a
    front-truncated run_default line — possible exactly because that
    line puts the headline fields LAST (a deliberate choice documented
    in bench.py). None when the trailing pair did not survive."""
    m = re.search(
        r'"headline_spread_pct":\s*([0-9.]+).*?"value":\s*([0-9.eE+-]+)',
        tail[-800:],
        re.DOTALL,
    )
    if m is None:
        return None
    return float(m.group(2)), float(m.group(1))


def load_corpus(root: str) -> tuple[list[dict], list[dict]]:
    """(entries, skipped) from every BENCH_r*.json under ``root``."""
    entries: list[dict] = []
    skipped: list[dict] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        name = os.path.basename(path)
        try:
            with open(path) as fh:
                raw = json.load(fh)
        except (OSError, ValueError) as exc:
            skipped.append({"file": name, "reason": f"unreadable: {exc}"})
            continue
        round_no = _round_for(name)
        body = None
        recovered = False
        if isinstance(raw.get("metric"), str) and "value" in raw:
            body = raw
        elif isinstance(raw.get("parsed"), dict):
            body = raw["parsed"]
        elif isinstance(raw.get("tail"), str):
            got = _recover_from_tail(raw["tail"])
            if got is None:
                skipped.append(
                    {
                        "file": name,
                        "reason": (
                            "truncated artifact: headline fields did not "
                            "survive the tail"
                        ),
                    }
                )
                continue
            value, spread = got
            metric, unit = _DEFAULT_SWEEP_METRIC
            entries.append(
                {
                    "file": name,
                    "round": round_no,
                    "metric": metric,
                    "value": value,
                    "unit": unit,
                    "spread_pct": spread,
                    "recovered": True,
                    "confident": False,
                    "device_apply_shares": [],
                }
            )
            continue
        else:
            skipped.append(
                {"file": name, "reason": "unrecognized artifact shape"}
            )
            continue
        spreads = _recorded_spreads(body)
        spread = max(spreads) if spreads else None
        try:
            value = float(body["value"])
        except (KeyError, TypeError, ValueError):
            skipped.append(
                {"file": name, "reason": "no numeric headline value"}
            )
            continue
        entries.append(
            {
                "file": name,
                "round": round_no,
                "metric": str(body.get("metric", "unknown")),
                "value": value,
                "unit": str(body.get("unit", "")),
                "spread_pct": spread,
                "recovered": recovered,
                "confident": bool(spread is not None and not recovered),
                "device_apply_shares": _device_apply_shares(body),
            }
        )
    return entries, skipped


def _compare(older: dict, newer: dict) -> dict:
    """Noise-aware verdict for one consecutive same-metric pair. All
    headline metrics in this corpus are higher-is-better rates/counts."""
    delta_pct = (
        round(100.0 * (newer["value"] - older["value"]) / older["value"], 2)
        if older["value"]
        else 0.0
    )
    comparison = {
        "metric": older["metric"],
        "from": {"file": older["file"], "round": older["round"]},
        "to": {"file": newer["file"], "round": newer["round"]},
        "delta_pct": delta_pct,
    }
    if not (older["confident"] and newer["confident"]):
        reasons = [
            f"{e['file']}: "
            + ("recovered from truncated tail" if e["recovered"] else "no recorded spread")
            for e in (older, newer)
            if not e["confident"]
        ]
        comparison["verdict"] = "advisory"
        comparison["reason"] = "; ".join(reasons)
        return comparison
    allowance = float(older["spread_pct"]) + float(newer["spread_pct"])
    comparison["allowance_pct"] = round(allowance, 2)
    if delta_pct < -allowance:
        comparison["verdict"] = "regression"
    elif delta_pct > allowance:
        comparison["verdict"] = "improvement"
    else:
        comparison["verdict"] = "stable"
    return comparison


def build_verdict(root: str) -> dict:
    """The machine-readable verdict block: trajectory, per-pair
    comparisons, the device-apply share history, and the hard
    ``regressions`` list (empty == pass)."""
    entries, skipped = load_corpus(root)
    series: dict[str, dict] = {}
    for entry in sorted(
        entries, key=lambda e: (e["round"] is None, e["round"], e["file"])
    ):
        key = entry["metric"]
        if key in series and series[key]["unit"] != entry["unit"]:
            # Same name, different unit = a different measurement; a
            # cross-unit delta would be meaningless.
            key = f"{key} ({entry['unit']})"
        bucket = series.setdefault(
            key, {"unit": entry["unit"], "points": []}
        )
        bucket["points"].append(
            {
                key: entry[key]
                for key in (
                    "file",
                    "round",
                    "value",
                    "spread_pct",
                    "recovered",
                    "confident",
                )
            }
        )
    comparisons: list[dict] = []
    for metric, bucket in series.items():
        points = bucket["points"]
        bucket["comparisons"] = []
        for older, newer in zip(points, points[1:]):
            pair = _compare(
                {**older, "metric": metric}, {**newer, "metric": metric}
            )
            bucket["comparisons"].append(pair)
            comparisons.append(pair)
    shares = [
        {
            "file": entry["file"],
            "round": entry["round"],
            "arm": reading["arm"],
            "share": reading["share"],
        }
        for entry in sorted(
            entries, key=lambda e: (e["round"] is None, e["round"], e["file"])
        )
        for reading in entry["device_apply_shares"]
    ]
    regressions = [c for c in comparisons if c["verdict"] == "regression"]
    return {
        "schema": REGRESS_SCHEMA,
        "files": sorted(e["file"] for e in entries)
        + sorted(s["file"] for s in skipped),
        "entries": len(entries),
        "skipped": skipped,
        "series": series,
        "stage_shares": {"device_apply": shares},
        "regressions": regressions,
        "pass": not regressions,
    }


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    verdict = build_verdict(root)
    print(json.dumps(verdict, indent=2))
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
