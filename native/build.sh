#!/bin/sh
# Build the native host runtime into native/build/libconsensus_native.so.
# The Python wrapper (hashgraph_tpu/native.py) also invokes this lazily when
# the shared object is missing and a compiler is available.
set -e
cd "$(dirname "$0")"
mkdir -p build
# -march=native lets gcc use ADX/BMI2 (mulx/adcx) for the 256-bit field
# arithmetic — a large win for ECDSA. Fall back to portable codegen on
# toolchains that reject the flag.
if ! g++ -O3 -march=native -fPIC -shared -std=c++17 -pthread \
    -o build/libconsensus_native.so consensus_native.cpp 2>/dev/null; then
  g++ -O3 -fPIC -shared -std=c++17 -pthread \
      -o build/libconsensus_native.so consensus_native.cpp
fi
# Stamp the builder's ISA fingerprint: the Python loader rebuilds when a
# shared checkout lands on a host with different CPU extensions (a foreign
# -march=native binary would SIGILL).
grep -m1 '^flags' /proc/cpuinfo 2>/dev/null | sha256sum | cut -c1-16 \
    > build/libconsensus_native.so.cputag 2>/dev/null || true
echo "built build/libconsensus_native.so"
