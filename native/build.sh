#!/bin/sh
# Build the native host runtime into native/build/libconsensus_native.so.
# The Python wrapper (hashgraph_tpu/native.py) also invokes this lazily when
# the shared object is missing and a compiler is available.
set -e
cd "$(dirname "$0")"
mkdir -p build
g++ -O3 -fPIC -shared -std=c++17 -pthread \
    -o build/libconsensus_native.so consensus_native.cpp
echo "built build/libconsensus_native.so"
