// Native host runtime for hashgraph_tpu: batched hashing + secp256k1 ECDSA.
//
// The TPU owns tallies and decisions; the host owns crypto (the reference
// delegates it to alloy's signer stack, src/signing/ethereum.rs:58-97 — here
// it is a from-scratch C++ implementation, no third-party code). Exposed as
// a C ABI consumed via ctypes (hashgraph_tpu/native.py); every batch entry
// point releases the GIL by construction and fans out over std::thread.
//
// Implemented primitives:
//   - SHA-256 (FIPS 180-4) + HMAC-SHA256 (RFC 6979 nonces)
//   - Keccak-256 (pre-NIST padding, Ethereum flavor)
//   - secp256k1 field/scalar arithmetic (4x64 limbs, 2^256-c folding),
//     Jacobian point ops, fixed-base window table for G
//   - ECDSA sign (RFC 6979, low-s) and public-key recovery
//   - EIP-191 verify: prefix-hash -> recover -> keccak address -> compare
//
// Build: native/build.sh (g++ -O3 -shared). The Python wrapper falls back to
// the pure-Python implementations when the shared object is absent.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <random>
#include <sched.h>
#include <thread>
#include <unordered_map>
#include <vector>

// ───────────────────────────── SHA-256 ─────────────────────────────

static const uint32_t SHA_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr32(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static void sha256_compress(uint32_t h[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(block[4 * i]) << 24) | (uint32_t(block[4 * i + 1]) << 16) |
           (uint32_t(block[4 * i + 2]) << 8) | uint32_t(block[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
           g = h[6], hh = h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = hh + S1 + ch + SHA_K[i] + w[i];
    uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

static void sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  size_t off = 0;
  for (; off + 64 <= len; off += 64) sha256_compress(h, data + off);
  uint8_t block[128] = {0};
  size_t tail = len - off;
  memcpy(block, data + off, tail);
  block[tail] = 0x80;
  size_t blocks = (tail + 9 <= 64) ? 1 : 2;
  uint64_t bits = uint64_t(len) * 8;
  for (int i = 0; i < 8; i++)
    block[blocks * 64 - 1 - i] = uint8_t(bits >> (8 * i));
  for (size_t b = 0; b < blocks; b++) sha256_compress(h, block + 64 * b);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(h[i] >> 24);
    out[4 * i + 1] = uint8_t(h[i] >> 16);
    out[4 * i + 2] = uint8_t(h[i] >> 8);
    out[4 * i + 3] = uint8_t(h[i]);
  }
}

static void hmac_sha256(const uint8_t* key, size_t keylen, const uint8_t* m1,
                        size_t l1, const uint8_t* m2, size_t l2,
                        const uint8_t* m3, size_t l3, const uint8_t* m4,
                        size_t l4, uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (keylen > 64) {
    sha256(key, keylen, k);
  } else {
    memcpy(k, key, keylen);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  // inner = sha256(ipad || m1 || m2 || m3 || m4)
  std::vector<uint8_t> buf;
  buf.reserve(64 + l1 + l2 + l3 + l4);
  buf.insert(buf.end(), ipad, ipad + 64);
  buf.insert(buf.end(), m1, m1 + l1);
  buf.insert(buf.end(), m2, m2 + l2);
  buf.insert(buf.end(), m3, m3 + l3);
  buf.insert(buf.end(), m4, m4 + l4);
  uint8_t inner[32];
  sha256(buf.data(), buf.size(), inner);
  uint8_t outer[96];
  memcpy(outer, opad, 64);
  memcpy(outer + 64, inner, 32);
  sha256(outer, 96, out);
}

// ──────────────────────────── Keccak-256 ───────────────────────────

static const uint64_t KECCAK_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static const int KECCAK_ROT[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55,
                                   20, 3,  10, 43, 25, 39, 41, 45, 15,
                                   21, 8,  18, 2,  61, 56, 14};

static inline uint64_t rotl64(uint64_t x, int n) {
  return n == 0 ? x : (x << n) | (x >> (64 - n));
}

static void keccak_f1600(uint64_t A[25]) {
  for (int round = 0; round < 24; round++) {
    uint64_t C[5], D[5], B[25];
    for (int x = 0; x < 5; x++)
      C[x] = A[x] ^ A[x + 5] ^ A[x + 10] ^ A[x + 15] ^ A[x + 20];
    for (int x = 0; x < 5; x++)
      D[x] = C[(x + 4) % 5] ^ rotl64(C[(x + 1) % 5], 1);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 25; y += 5) A[x + y] ^= D[x];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        B[y + 5 * ((2 * x + 3 * y) % 5)] = rotl64(A[x + 5 * y], KECCAK_ROT[x + 5 * y]);
    for (int y = 0; y < 25; y += 5)
      for (int x = 0; x < 5; x++)
        A[x + y] = B[x + y] ^ ((~B[(x + 1) % 5 + y]) & B[(x + 2) % 5 + y]);
    A[0] ^= KECCAK_RC[round];
  }
}

static void keccak256(const uint8_t* data, size_t len, uint8_t out[32]) {
  const size_t rate = 136;
  uint64_t A[25] = {0};
  size_t off = 0;
  while (len - off >= rate) {
    for (size_t i = 0; i < rate / 8; i++) {
      uint64_t lane;
      memcpy(&lane, data + off + 8 * i, 8);
      A[i] ^= lane;  // little-endian host assumed (x86/arm64)
    }
    keccak_f1600(A);
    off += rate;
  }
  uint8_t block[136] = {0};
  memcpy(block, data + off, len - off);
  block[len - off] ^= 0x01;
  block[rate - 1] ^= 0x80;
  for (size_t i = 0; i < rate / 8; i++) {
    uint64_t lane;
    memcpy(&lane, block + 8 * i, 8);
    A[i] ^= lane;
  }
  keccak_f1600(A);
  memcpy(out, A, 32);
}

// ───────────────────── 256-bit modular arithmetic ──────────────────
// Little-endian 4x64 limbs. Moduli are 2^256 - c with small-ish c, so
// reduction is repeated folding: hi * c + lo.

struct U256 {
  uint64_t v[4];
};

static inline bool u256_is_zero(const U256& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static inline int u256_cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; i--) {
    if (a.v[i] < b.v[i]) return -1;
    if (a.v[i] > b.v[i]) return 1;
  }
  return 0;
}

static inline uint64_t u256_add(U256& r, const U256& a, const U256& b) {
  unsigned __int128 carry = 0;
  for (int i = 0; i < 4; i++) {
    carry += (unsigned __int128)a.v[i] + b.v[i];
    r.v[i] = (uint64_t)carry;
    carry >>= 64;
  }
  return (uint64_t)carry;
}

static inline uint64_t u256_sub(U256& r, const U256& a, const U256& b) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 4; i++) {
    unsigned __int128 d = (unsigned __int128)a.v[i] - b.v[i] - borrow;
    r.v[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
  return (uint64_t)borrow;
}

// out[0..7] = a * b
static void u256_mul_full(const U256& a, const U256& b, uint64_t out[8]) {
  memset(out, 0, 8 * sizeof(uint64_t));
  for (int i = 0; i < 4; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 4; j++) {
      carry += (unsigned __int128)a.v[i] * b.v[j] + out[i + j];
      out[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    out[i + 4] = (uint64_t)carry;
  }
}

struct Modulus {
  U256 m;  // 2^256 - c
  U256 c;  // the folding constant (fits in <= 3 limbs)
};

// Reduce an 8-limb value modulo m = 2^256 - c by folding hi*c into lo.
static U256 mod_reduce512(const uint64_t t_in[8], const Modulus& mod) {
  uint64_t t[12];
  memcpy(t, t_in, 8 * sizeof(uint64_t));
  memset(t + 8, 0, 4 * sizeof(uint64_t));
  // Fold until limbs above 3 are clear (terminates: c < 2^130).
  for (int iter = 0; iter < 4; iter++) {
    bool high = false;
    for (int i = 4; i < 12; i++) high |= (t[i] != 0);
    if (!high) break;
    uint64_t hi[8];
    memcpy(hi, t + 4, 8 * sizeof(uint64_t));
    memset(t + 4, 0, 8 * sizeof(uint64_t));
    // t += hi * c   (hi up to 8 limbs but after first fold it is small)
    for (int i = 0; i < 8; i++) {
      if (hi[i] == 0) continue;
      unsigned __int128 carry = 0;
      for (int j = 0; j < 3; j++) {
        if (i + j >= 12) break;
        carry += (unsigned __int128)hi[i] * mod.c.v[j] + t[i + j];
        t[i + j] = (uint64_t)carry;
        carry >>= 64;
      }
      for (int k = i + 3; carry && k < 12; k++) {
        carry += t[k];
        t[k] = (uint64_t)carry;
        carry >>= 64;
      }
    }
  }
  U256 r = {{t[0], t[1], t[2], t[3]}};
  while (u256_cmp(r, mod.m) >= 0) u256_sub(r, r, mod.m);
  return r;
}

static U256 mod_mul(const U256& a, const U256& b, const Modulus& mod) {
  uint64_t t[8];
  u256_mul_full(a, b, t);
  return mod_reduce512(t, mod);
}

static U256 mod_add(const U256& a, const U256& b, const Modulus& mod) {
  U256 r;
  uint64_t carry = u256_add(r, a, b);
  if (carry) {
    // r + 2^256 ≡ r + c (mod m)
    U256 r2;
    uint64_t c2 = u256_add(r2, r, mod.c);
    r = r2;
    if (c2) u256_add(r, r, mod.c);  // cannot carry twice for our c
  }
  while (u256_cmp(r, mod.m) >= 0) u256_sub(r, r, mod.m);
  return r;
}

static U256 mod_sub(const U256& a, const U256& b, const Modulus& mod) {
  U256 r;
  if (u256_sub(r, a, b)) u256_add(r, r, mod.m);
  return r;
}

// 4-bit windowed exponentiation: ~256 squarings + ~64 multiplies. The
// exponents used here (p-2, n-2, (p+1)/4) are dense with set bits, so the
// naive square-and-multiply ladder costs ~250 multiplies on top of the
// squarings — the window cuts that 4x.
static U256 mod_pow(const U256& base, const U256& exp, const Modulus& mod) {
  U256 tbl[16];
  tbl[0] = {{1, 0, 0, 0}};
  tbl[1] = base;
  for (int i = 2; i < 16; i++) tbl[i] = mod_mul(tbl[i - 1], base, mod);
  U256 result = {{1, 0, 0, 0}};
  bool started = false;
  for (int w = 63; w >= 0; w--) {
    int digit = (exp.v[w / 16] >> (4 * (w % 16))) & 0xF;
    if (started) {
      result = mod_mul(result, result, mod);
      result = mod_mul(result, result, mod);
      result = mod_mul(result, result, mod);
      result = mod_mul(result, result, mod);
    }
    if (digit) {
      result = started ? mod_mul(result, tbl[digit], mod) : tbl[digit];
      started = true;
    }
  }
  return started ? result : tbl[0];
}

static U256 u256_from_be(const uint8_t b[32]) {
  U256 r;
  for (int i = 0; i < 4; i++) {
    uint64_t limb = 0;
    for (int j = 0; j < 8; j++) limb = (limb << 8) | b[(3 - i) * 8 + j];
    r.v[i] = limb;
  }
  return r;
}

static void u256_to_be(const U256& a, uint8_t out[32]) {
  for (int i = 0; i < 4; i++) {
    uint64_t limb = a.v[3 - i];
    for (int j = 0; j < 8; j++) out[i * 8 + j] = uint8_t(limb >> (8 * (7 - j)));
  }
}

// secp256k1 constants.
static const Modulus FP = {
    {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL,
      0xFFFFFFFFFFFFFFFFULL}},
    {{0x00000001000003D1ULL, 0, 0, 0}}};
static const Modulus FN = {
    {{0xBFD25E8CD0364141ULL, 0xBAAEDCE6AF48A03BULL, 0xFFFFFFFFFFFFFFFEULL,
      0xFFFFFFFFFFFFFFFFULL}},
    {{0x402DA1732FC9BEBFULL, 0x4551231950B75FC4ULL, 0x1ULL, 0}}};
static const U256 GX = {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL,
                         0x55A06295CE870B07ULL, 0x79BE667EF9DCBBACULL}};
static const U256 GY = {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL,
                         0x5DA4FBFC0E1108A8ULL, 0x483ADA7726A3C465ULL}};

// ─────────── fast field ops for p = 2^256 - 0x1000003D1 ───────────
// The fold constant fits a single limb, so a 512-bit product reduces with
// two single-limb folds — an order of magnitude cheaper than the generic
// mod_reduce512 loop. These carry all point arithmetic; mod-n scalar math
// (a handful of ops per signature) stays on the generic path.

static const uint64_t FP_C = 0x1000003D1ULL;

static inline U256 fp_reduce8(const uint64_t t[8]) {
  unsigned __int128 acc;
  uint64_t r[4];
  acc = (unsigned __int128)t[4] * FP_C + t[0];
  r[0] = (uint64_t)acc; acc >>= 64;
  acc += (unsigned __int128)t[5] * FP_C + t[1];
  r[1] = (uint64_t)acc; acc >>= 64;
  acc += (unsigned __int128)t[6] * FP_C + t[2];
  r[2] = (uint64_t)acc; acc >>= 64;
  acc += (unsigned __int128)t[7] * FP_C + t[3];
  r[3] = (uint64_t)acc; acc >>= 64;
  uint64_t hi = (uint64_t)acc;  // <= ~2^33 after the first fold
  acc = (unsigned __int128)hi * FP_C + r[0];
  r[0] = (uint64_t)acc; acc >>= 64;
  acc += r[1]; r[1] = (uint64_t)acc; acc >>= 64;
  acc += r[2]; r[2] = (uint64_t)acc; acc >>= 64;
  acc += r[3]; r[3] = (uint64_t)acc; acc >>= 64;
  if ((uint64_t)acc) {
    // wrapped past 2^256 once more; the remainder is tiny, += C can't carry
    acc = (unsigned __int128)r[0] + FP_C;
    r[0] = (uint64_t)acc; acc >>= 64;
    for (int i = 1; acc && i < 4; i++) {
      acc += r[i];
      r[i] = (uint64_t)acc; acc >>= 64;
    }
  }
  U256 out = {{r[0], r[1], r[2], r[3]}};
  if (u256_cmp(out, FP.m) >= 0) u256_sub(out, out, FP.m);
  return out;
}

static inline U256 fp_mul(const U256& a, const U256& b) {
  uint64_t t[8];
  u256_mul_full(a, b, t);
  return fp_reduce8(t);
}

// Dedicated squaring: cross products once, doubled, plus the diagonal.
static inline U256 fp_sqr(const U256& a) {
  uint64_t t[8] = {0};
  for (int i = 0; i < 4; i++) {
    unsigned __int128 carry = 0;
    for (int j = i + 1; j < 4; j++) {
      carry += (unsigned __int128)a.v[i] * a.v[j] + t[i + j];
      t[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    if (i < 3) t[i + 4] = (uint64_t)carry;
  }
  uint64_t msb = 0;
  for (int i = 0; i < 8; i++) {
    uint64_t next = t[i] >> 63;
    t[i] = (t[i] << 1) | msb;
    msb = next;
  }
  unsigned __int128 acc = 0;
  for (int i = 0; i < 4; i++) {
    unsigned __int128 sq = (unsigned __int128)a.v[i] * a.v[i];
    acc += (unsigned __int128)t[2 * i] + (uint64_t)sq;
    t[2 * i] = (uint64_t)acc; acc >>= 64;
    acc += (unsigned __int128)t[2 * i + 1] + (uint64_t)(sq >> 64);
    t[2 * i + 1] = (uint64_t)acc; acc >>= 64;
  }
  return fp_reduce8(t);
}

static inline U256 fp_add(const U256& a, const U256& b) {
  U256 r;
  if (u256_add(r, a, b)) {
    // 2^256 ≡ FP_C (mod p); a,b < p bounds the wrap to at most once
    unsigned __int128 acc = (unsigned __int128)r.v[0] + FP_C;
    r.v[0] = (uint64_t)acc; acc >>= 64;
    for (int i = 1; acc && i < 4; i++) {
      acc += r.v[i];
      r.v[i] = (uint64_t)acc; acc >>= 64;
    }
  }
  if (u256_cmp(r, FP.m) >= 0) u256_sub(r, r, FP.m);
  return r;
}

static inline U256 fp_sub(const U256& a, const U256& b) {
  U256 r;
  if (u256_sub(r, a, b)) u256_add(r, r, FP.m);
  return r;
}

// Windowed pow over the fast ops (same shape as mod_pow above).
static U256 fp_pow(const U256& base, const U256& exp) {
  U256 tbl[16];
  tbl[0] = {{1, 0, 0, 0}};
  tbl[1] = base;
  for (int i = 2; i < 16; i++) tbl[i] = fp_mul(tbl[i - 1], base);
  U256 result = {{1, 0, 0, 0}};
  bool started = false;
  for (int w = 63; w >= 0; w--) {
    int digit = (exp.v[w / 16] >> (4 * (w % 16))) & 0xF;
    if (started) result = fp_sqr(fp_sqr(fp_sqr(fp_sqr(result))));
    if (digit) {
      result = started ? fp_mul(result, tbl[digit]) : tbl[digit];
      started = true;
    }
  }
  return started ? result : tbl[0];
}

static U256 fp_inv(const U256& a) {
  U256 e = FP.m;
  U256 two = {{2, 0, 0, 0}};
  u256_sub(e, e, two);
  return fp_pow(a, e);
}

// Square root mod p as a^((p+1)/4) (p ≡ 3 mod 4) on a dedicated addition
// chain: the exponent's binary form is [223 ones][0][22 ones][0000][11][00],
// so runs of ones are built by doubling-and-merging x_k = a^(2^k - 1) —
// ~253 squarings + 13 multiplies vs the generic windowed pow's
// ~256 sq + 62 mul. Callers verify y² == alpha afterwards, so a chain
// defect fails closed instead of mis-recovering.
static U256 fp_sqrt(const U256& a) {
  auto sqn = [](U256 x, int n) {
    for (int i = 0; i < n; i++) x = fp_sqr(x);
    return x;
  };
  U256 x2 = fp_mul(fp_sqr(a), a);
  U256 x3 = fp_mul(fp_sqr(x2), a);
  U256 x6 = fp_mul(sqn(x3, 3), x3);
  U256 x9 = fp_mul(sqn(x6, 3), x3);
  U256 x11 = fp_mul(sqn(x9, 2), x2);
  U256 x22 = fp_mul(sqn(x11, 11), x11);
  U256 x44 = fp_mul(sqn(x22, 22), x22);
  U256 x88 = fp_mul(sqn(x44, 44), x44);
  U256 x176 = fp_mul(sqn(x88, 88), x88);
  U256 x220 = fp_mul(sqn(x176, 44), x44);
  U256 x223 = fp_mul(sqn(x220, 3), x3);
  U256 r = fp_mul(sqn(x223, 23), x22);  // [223 ones][0][22 ones]
  r = fp_mul(sqn(r, 6), x2);            // append 0000 then 11
  return sqn(r, 2);                     // trailing 00
}

// Montgomery batch inversion: one fp_inv amortised over the whole array.
// Zero entries are left untouched (callers use zero as an "absent" marker).
static void fp_batch_inv(U256* vals, int n) {
  std::vector<U256> prefix(n);
  U256 acc = {{1, 0, 0, 0}};
  for (int i = 0; i < n; i++) {
    prefix[i] = acc;
    if (!u256_is_zero(vals[i])) acc = fp_mul(acc, vals[i]);
  }
  U256 inv = fp_inv(acc);
  for (int i = n - 1; i >= 0; i--) {
    if (u256_is_zero(vals[i])) continue;
    U256 orig = vals[i];
    vals[i] = fp_mul(inv, prefix[i]);
    inv = fp_mul(inv, orig);
  }
}

static U256 fn_inv(const U256& a) {
  U256 e = FN.m;
  U256 two = {{2, 0, 0, 0}};
  u256_sub(e, e, two);
  return mod_pow(a, e, FN);
}

// Montgomery batch inversion mod n (zeros skipped, as in fp_batch_inv). The
// batch-verify path uses this to amortise the per-signature r⁻¹ — mod-n
// arithmetic runs on the generic reduction, so one inversion there costs
// ~320 slow multiplies.
static void fn_batch_inv(U256* vals, int n) {
  std::vector<U256> prefix(n);
  U256 acc = {{1, 0, 0, 0}};
  for (int i = 0; i < n; i++) {
    prefix[i] = acc;
    if (!u256_is_zero(vals[i])) acc = mod_mul(acc, vals[i], FN);
  }
  U256 inv = fn_inv(acc);
  for (int i = n - 1; i >= 0; i--) {
    if (u256_is_zero(vals[i])) continue;
    U256 orig = vals[i];
    vals[i] = mod_mul(inv, prefix[i], FN);
    inv = mod_mul(inv, orig, FN);
  }
}

// ─────────────────── Jacobian point arithmetic (mod p) ─────────────

struct Point {
  U256 x, y, z;  // z == 0 encodes infinity
};

static const Point P_INF = {{{0, 0, 0, 0}}, {{1, 0, 0, 0}}, {{0, 0, 0, 0}}};

static inline bool pt_is_inf(const Point& p) { return u256_is_zero(p.z); }

static Point pt_double(const Point& p) {
  if (pt_is_inf(p) || u256_is_zero(p.y)) return P_INF;
  U256 a = fp_sqr(p.x);
  U256 b = fp_sqr(p.y);
  U256 c = fp_sqr(b);
  U256 xb = fp_add(p.x, b);
  U256 d = fp_sub(fp_sub(fp_sqr(xb), a), c);
  d = fp_add(d, d);
  U256 e = fp_add(fp_add(a, a), a);
  U256 f = fp_sqr(e);
  U256 x3 = fp_sub(f, fp_add(d, d));
  U256 c8 = fp_add(c, c);
  c8 = fp_add(c8, c8);
  c8 = fp_add(c8, c8);
  U256 y3 = fp_sub(fp_mul(e, fp_sub(d, x3)), c8);
  U256 z3 = fp_mul(p.y, p.z);
  z3 = fp_add(z3, z3);
  return {x3, y3, z3};
}

static Point pt_add(const Point& p1, const Point& p2) {
  if (pt_is_inf(p1)) return p2;
  if (pt_is_inf(p2)) return p1;
  U256 z1z1 = fp_sqr(p1.z);
  U256 z2z2 = fp_sqr(p2.z);
  U256 u1 = fp_mul(p1.x, z2z2);
  U256 u2 = fp_mul(p2.x, z1z1);
  U256 s1 = fp_mul(fp_mul(p1.y, p2.z), z2z2);
  U256 s2 = fp_mul(fp_mul(p2.y, p1.z), z1z1);
  if (u256_cmp(u1, u2) == 0) {
    if (u256_cmp(s1, s2) != 0) return P_INF;
    return pt_double(p1);
  }
  U256 h = fp_sub(u2, u1);
  U256 h2 = fp_add(h, h);
  U256 i = fp_sqr(h2);
  U256 j = fp_mul(h, i);
  U256 r = fp_sub(s2, s1);
  r = fp_add(r, r);
  U256 v = fp_mul(u1, i);
  U256 x3 = fp_sub(fp_sub(fp_sqr(r), j), fp_add(v, v));
  U256 s1j = fp_mul(s1, j);
  U256 y3 = fp_sub(fp_mul(r, fp_sub(v, x3)), fp_add(s1j, s1j));
  U256 z3 = fp_mul(fp_mul(h, p1.z), p2.z);
  z3 = fp_add(z3, z3);
  return {x3, y3, z3};
}

static Point pt_neg(const Point& p) {
  if (pt_is_inf(p) || u256_is_zero(p.y)) return p;
  U256 ny;
  u256_sub(ny, FP.m, p.y);
  return {p.x, ny, p.z};
}

// Affine second operand (z2 == 1 implicit): saves ~4 multiplies vs pt_add.
struct AffinePoint {
  U256 x, y;
  bool inf;
};

static Point pt_add_affine(const Point& p1, const AffinePoint& p2) {
  if (p2.inf) return p1;
  if (pt_is_inf(p1)) return {p2.x, p2.y, {{1, 0, 0, 0}}};
  U256 z1z1 = fp_sqr(p1.z);
  U256 u2 = fp_mul(p2.x, z1z1);
  U256 s2 = fp_mul(fp_mul(p2.y, p1.z), z1z1);
  if (u256_cmp(p1.x, u2) == 0) {
    if (u256_cmp(p1.y, s2) != 0) return P_INF;
    return pt_double(p1);
  }
  U256 h = fp_sub(u2, p1.x);
  U256 h2 = fp_add(h, h);
  U256 i = fp_sqr(h2);
  U256 j = fp_mul(h, i);
  U256 r = fp_sub(s2, p1.y);
  r = fp_add(r, r);
  U256 v = fp_mul(p1.x, i);
  U256 x3 = fp_sub(fp_sub(fp_sqr(r), j), fp_add(v, v));
  U256 s1j = fp_mul(p1.y, j);
  U256 y3 = fp_sub(fp_mul(r, fp_sub(v, x3)), fp_add(s1j, s1j));
  U256 z3 = fp_mul(p1.z, h);
  z3 = fp_add(z3, z3);
  return {x3, y3, z3};
}

static inline void u256_shr1(U256& a) {
  for (int i = 0; i < 3; i++) a.v[i] = (a.v[i] >> 1) | (a.v[i + 1] << 63);
  a.v[3] >>= 1;
}

// Width-5 NAF: odd digits in [-15, 15], ~1 nonzero per 6 bits.
static int build_wnaf5(const U256& k_in, int8_t out[260]) {
  U256 k = k_in;
  int len = 0;
  while (!u256_is_zero(k)) {
    int8_t d = 0;
    int m = (int)(k.v[0] & 31);
    if (m & 1) {
      if (m > 16) {
        d = (int8_t)(m - 32);
        unsigned __int128 carry = (unsigned)(32 - m);
        for (int i = 0; i < 4 && carry; i++) {
          carry += k.v[i];
          k.v[i] = (uint64_t)carry;
          carry >>= 64;
        }
      } else {
        d = (int8_t)m;
        k.v[0] -= (uint64_t)m;  // low bits of k.v[0] are exactly m
      }
    }
    out[len++] = d;
    u256_shr1(k);
  }
  return len;
}

// Variable-base scalar multiply: wNAF-5 with 8 precomputed odd multiples —
// ~256 doublings + ~51 additions vs double-and-add's ~128 additions.
static Point wnaf_mul(const Point& p, const U256& k) {
  if (pt_is_inf(p) || u256_is_zero(k)) return P_INF;
  int8_t naf[260];
  int len = build_wnaf5(k, naf);
  Point tbl[8];  // 1P, 3P, ..., 15P
  tbl[0] = p;
  Point p2 = pt_double(p);
  for (int i = 1; i < 8; i++) tbl[i] = pt_add(tbl[i - 1], p2);
  Point acc = P_INF;
  for (int i = len - 1; i >= 0; i--) {
    acc = pt_double(acc);
    int d = naf[i];
    if (d > 0) acc = pt_add(acc, tbl[(d - 1) >> 1]);
    else if (d < 0) acc = pt_add(acc, pt_neg(tbl[((-d) - 1) >> 1]));
  }
  return acc;
}

// ───────── GLV endomorphism: k·P with half the doublings ──────────
// secp256k1 has an efficient endomorphism φ(x, y) = (β·x, y) = λ·(x, y).
// Splitting k = k1 + k2·λ (mod n) with |k1|,|k2| ≲ 2^128 turns one 256-bit
// scalar multiply into two interleaved 128-bit ones sharing a doubling
// chain. Constants are the standard curve values; build_g_table_impl
// cross-checks them against plain wNAF at init and clears glv_ok on any
// mismatch, falling back to the single-scalar path.

static const U256 GLV_BETA = {{0xC1396C28719501EEULL, 0x9CF0497512F58995ULL,
                               0x6E64479EAC3434E9ULL, 0x7AE96A2B657C0710ULL}};
static bool glv_ok = false;

// q = round(m2·k / n) for a ≤128-bit multiplier, via the series
// 1/n = 2^-256·(1 + c·2^-256 + ...). Error ≤ 1, which only nudges
// |k1|,|k2| within their headroom.
static void glv_round_div(const U256& k, const uint64_t m2[2], uint64_t q[2]) {
  uint64_t T[6] = {0};
  for (int i = 0; i < 4; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 2; j++) {
      carry += (unsigned __int128)k.v[i] * m2[j] + T[i + j];
      T[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    T[i + 2] = (uint64_t)carry;
  }
  uint64_t P[9] = {0};
  for (int i = 0; i < 6; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 3; j++) {
      carry += (unsigned __int128)T[i] * FN.c.v[j] + P[i + j];
      P[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    P[i + 3] = (uint64_t)carry;
  }
  // U = T + (P >> 256); q = (U + 2^255) >> 256
  unsigned __int128 acc = 0;
  uint64_t U[7];
  for (int i = 0; i < 6; i++) {
    acc += T[i];
    if (i + 4 < 9) acc += P[i + 4];
    U[i] = (uint64_t)acc;
    acc >>= 64;
  }
  U[6] = (uint64_t)acc;
  acc = (unsigned __int128)U[3] + 0x8000000000000000ULL;
  U[3] = (uint64_t)acc;
  acc >>= 64;
  for (int i = 4; acc && i < 7; i++) {
    acc += U[i];
    U[i] = (uint64_t)acc;
    acc >>= 64;
  }
  q[0] = U[4];
  q[1] = U[5];
}

// a(an limbs) * b(bn limbs) truncated to 256 bits.
static U256 mul_trunc256(const uint64_t* a, int an, const uint64_t* b, int bn) {
  uint64_t t[8] = {0};
  for (int i = 0; i < an; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < bn && i + j < 8; j++) {
      carry += (unsigned __int128)a[i] * b[j] + t[i + j];
      t[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    if (i + bn < 8) t[i + bn] = (uint64_t)carry;
  }
  return {{t[0], t[1], t[2], t[3]}};
}

// Split k into signed halves: k ≡ sign1·k1 + sign2·k2·λ (mod n).
static void glv_split(const U256& k, U256& k1, bool& k1_neg, U256& k2,
                      bool& k2_neg) {
  // Lattice basis: v1 = (a1, b1), v2 = (a2, b2) with a + b·λ ≡ 0 (mod n);
  // b1 = -B1N, a2 = a1 + B1N, b2 = a1.
  static const uint64_t A1[2] = {0xE86C90E49284EB15ULL, 0x3086D221A7D46BCDULL};
  static const uint64_t B1N[2] = {0x6F547FA90ABFE4C3ULL, 0xE4437ED6010E8828ULL};
  static const uint64_t A2[3] = {0x57C1108D9D44CFD8ULL, 0x14CA50F7A8E2F3F6ULL,
                                 1ULL};
  uint64_t c1[2], c2[2];
  glv_round_div(k, A1, c1);   // round(b2·k/n)
  glv_round_div(k, B1N, c2);  // round(-b1·k/n)
  U256 c1a1 = mul_trunc256(c1, 2, A1, 2);
  U256 c2a2 = mul_trunc256(c2, 2, A2, 3);
  const U256 zero = {{0, 0, 0, 0}};
  U256 s, t;
  u256_add(s, c1a1, c2a2);  // mod 2^256; |k1| small makes wrap safe
  u256_sub(t, k, s);
  k1_neg = (t.v[3] >> 63) != 0;
  if (k1_neg) u256_sub(k1, zero, t);
  else k1 = t;
  U256 c1b1n = mul_trunc256(c1, 2, B1N, 2);
  U256 c2a1 = mul_trunc256(c2, 2, A1, 2);
  u256_sub(t, c1b1n, c2a1);
  k2_neg = (t.v[3] >> 63) != 0;
  if (k2_neg) u256_sub(k2, zero, t);
  else k2 = t;
}

static Point glv_mul(const Point& p, const U256& u) {
  if (pt_is_inf(p) || u256_is_zero(u)) return P_INF;
  U256 k1, k2;
  bool n1, n2;
  glv_split(u, k1, n1, k2, n2);
  Point p1 = n1 ? pt_neg(p) : p;
  Point p2 = {fp_mul(p.x, GLV_BETA), p.y, p.z};
  if (n2) p2 = pt_neg(p2);
  int8_t naf1[260], naf2[260];
  int len1 = build_wnaf5(k1, naf1);
  int len2 = build_wnaf5(k2, naf2);
  Point tbl1[8], tbl2[8];
  tbl1[0] = p1;
  Point d1 = pt_double(p1);
  for (int i = 1; i < 8; i++) tbl1[i] = pt_add(tbl1[i - 1], d1);
  tbl2[0] = p2;
  Point d2 = pt_double(p2);
  for (int i = 1; i < 8; i++) tbl2[i] = pt_add(tbl2[i - 1], d2);
  Point acc = P_INF;
  int len = len1 > len2 ? len1 : len2;
  for (int i = len - 1; i >= 0; i--) {
    acc = pt_double(acc);
    if (i < len1) {
      int d = naf1[i];
      if (d > 0) acc = pt_add(acc, tbl1[(d - 1) >> 1]);
      else if (d < 0) acc = pt_add(acc, pt_neg(tbl1[((-d) - 1) >> 1]));
    }
    if (i < len2) {
      int d = naf2[i];
      if (d > 0) acc = pt_add(acc, tbl2[(d - 1) >> 1]);
      else if (d < 0) acc = pt_add(acc, pt_neg(tbl2[((-d) - 1) >> 1]));
    }
  }
  return acc;
}

// ── Batched affine-GLV ladder ──────────────────────────────────────
// The verify hot path amortises ONE field inversion across a whole
// chunk's per-item wNAF tables (8 z's per item into a cross-item
// Montgomery batch), so every ladder addition runs on the cheaper mixed
// (affine-operand) formulas, and the φ-table is derived free from the
// affine base table (φ(x, y) = (β·x, y); negation flips y only).
struct GlvPrep {
  int8_t naf1[260], naf2[260];
  int len1, len2;
  Point jtbl[8];       // jacobian odd multiples 1,3,...,15 of ±R
  AffinePoint tbl[8];  // affine conversions (phase B)
  U256 beta_x[8];      // φ-table x coordinates
  bool flip2;          // second scalar's sign differs from the first's
  bool glv;            // affine ladder prepared (else q computed eagerly)
};

// Phase A: split the scalar, build the jacobian odd-multiple table of
// ±R, and export the 8 z coordinates for the cross-item batch inversion.
static void glv_prep_phase(const U256& rx, const U256& ry, const U256& u2,
                           GlvPrep& gp, U256* zs8) {
  U256 k1, k2;
  bool n1, n2;
  glv_split(u2, k1, n1, k2, n2);
  gp.len1 = build_wnaf5(k1, gp.naf1);
  gp.len2 = build_wnaf5(k2, gp.naf2);
  gp.flip2 = (n1 != n2);
  Point p1 = {rx, ry, {{1, 0, 0, 0}}};
  if (n1) p1 = pt_neg(p1);
  gp.jtbl[0] = p1;
  Point d1 = pt_double(p1);
  for (int i = 1; i < 8; i++) gp.jtbl[i] = pt_add(gp.jtbl[i - 1], d1);
  for (int i = 0; i < 8; i++) zs8[i] = gp.jtbl[i].z;
}

// Phase B: finish the affine conversion with the batch-inverted z's and
// run the dual ladder on mixed additions.
static Point glv_ladder_affine(GlvPrep& gp, const U256* zinv8) {
  for (int i = 0; i < 8; i++) {
    const Point& p = gp.jtbl[i];
    AffinePoint& a = gp.tbl[i];
    a.inf = pt_is_inf(p);
    if (a.inf) {
      gp.beta_x[i] = p.x;
      continue;
    }
    U256 zi2 = fp_sqr(zinv8[i]);
    a.x = fp_mul(p.x, zi2);
    a.y = fp_mul(p.y, fp_mul(zi2, zinv8[i]));
    gp.beta_x[i] = fp_mul(a.x, GLV_BETA);
  }
  Point acc = P_INF;
  int len = gp.len1 > gp.len2 ? gp.len1 : gp.len2;
  for (int i = len - 1; i >= 0; i--) {
    acc = pt_double(acc);
    if (i < gp.len1) {
      int d = gp.naf1[i];
      if (d) {
        AffinePoint t = gp.tbl[((d < 0 ? -d : d) - 1) >> 1];
        if (d < 0 && !t.inf) u256_sub(t.y, FP.m, t.y);
        acc = pt_add_affine(acc, t);
      }
    }
    if (i < gp.len2) {
      int d = gp.naf2[i];
      if (d) {
        int idx = ((d < 0 ? -d : d) - 1) >> 1;
        AffinePoint t = {gp.beta_x[idx], gp.tbl[idx].y, gp.tbl[idx].inf};
        if (((d < 0) != gp.flip2) && !t.inf) u256_sub(t.y, FP.m, t.y);
        acc = pt_add_affine(acc, t);
      }
    }
  }
  return acc;
}

// Projective equality: x1·z2² == x2·z1² and y1·z2³ == y2·z1³.
static bool pt_equal(const Point& a, const Point& b) {
  if (pt_is_inf(a) || pt_is_inf(b)) return pt_is_inf(a) == pt_is_inf(b);
  U256 za2 = fp_sqr(a.z), zb2 = fp_sqr(b.z);
  if (u256_cmp(fp_mul(a.x, zb2), fp_mul(b.x, za2)) != 0) return false;
  U256 za3 = fp_mul(za2, a.z), zb3 = fp_mul(zb2, b.z);
  return u256_cmp(fp_mul(a.y, zb3), fp_mul(b.y, za3)) == 0;
}

// Fixed-base 8-bit window table for G: g_table[w][d-1] = (256^w * d) * G,
// stored affine (one batch inversion at init) so g_mul runs on the cheaper
// mixed addition — 32 windows means ~32 mixed adds per fixed-base multiply
// (the earlier 4-bit table paid ~64). ~590 KB of table, built once.
// Callers enter through ctypes with the GIL released, so initialisation
// must be race-free: std::call_once.
static constexpr int GT_WINDOWS = 32;
static constexpr int GT_ENTRIES = 255;
static AffinePoint g_table[GT_WINDOWS][GT_ENTRIES];
static std::once_flag g_table_once;

static void build_g_table_impl() {
  std::vector<Point> jac((size_t)GT_WINDOWS * GT_ENTRIES);
  Point base = {GX, GY, {{1, 0, 0, 0}}};
  for (int w = 0; w < GT_WINDOWS; w++) {
    Point acc = P_INF;
    for (int d = 0; d < GT_ENTRIES; d++) {
      acc = pt_add(acc, base);
      jac[(size_t)w * GT_ENTRIES + d] = acc;
    }
    for (int b = 0; b < 8; b++) base = pt_double(base);
  }
  std::vector<U256> zs((size_t)GT_WINDOWS * GT_ENTRIES);
  for (size_t i = 0; i < zs.size(); i++) zs[i] = jac[i].z;
  fp_batch_inv(zs.data(), (int)zs.size());
  for (int w = 0; w < GT_WINDOWS; w++) {
    for (int d = 0; d < GT_ENTRIES; d++) {
      const Point& p = jac[(size_t)w * GT_ENTRIES + d];
      AffinePoint& a = g_table[w][d];
      a.inf = pt_is_inf(p);  // never true for d*256^w*G, but stay defensive
      if (a.inf) continue;
      U256 zi = zs[(size_t)w * GT_ENTRIES + d];
      U256 zi2 = fp_sqr(zi);
      a.x = fp_mul(p.x, zi2);
      a.y = fp_mul(p.y, fp_mul(zi2, zi));
    }
  }
  // Cross-check the GLV constants once against the plain wNAF ladder; on
  // any disagreement recover_combine silently stays on the slow path.
  Point g = {GX, GY, {{1, 0, 0, 0}}};
  U256 probe = {{0x243F6A8885A308D3ULL, 0x13198A2E03707344ULL,
                 0xA4093822299F31D0ULL, 0x082EFA98EC4E6C89ULL}};
  glv_ok = pt_equal(glv_mul(g, probe), wnaf_mul(g, probe));
}

static void build_g_table() { std::call_once(g_table_once, build_g_table_impl); }

static Point g_mul(const U256& scalar) {
  build_g_table();
  Point result = P_INF;
  for (int w = 0; w < GT_WINDOWS; w++) {
    int digit = (scalar.v[w / 8] >> (8 * (w % 8))) & 0xFF;
    if (digit) result = pt_add_affine(result, g_table[w][digit - 1]);
  }
  return result;
}

static bool pt_to_affine(const Point& p, U256& x, U256& y) {
  if (pt_is_inf(p)) return false;
  U256 zi = fp_inv(p.z);
  U256 zi2 = fp_sqr(zi);
  x = fp_mul(p.x, zi2);
  y = fp_mul(p.y, fp_mul(zi2, zi));
  return true;
}

// ───────────────────────────── ECDSA ───────────────────────────────

// Reconstruct the ephemeral point R = (x, y) from the signature r scalar and
// recovery id. False when x is off-curve or out of range.
static bool recover_r_point(const U256& r, int recid, U256& x_out,
                            U256& y_out) {
  U256 x = r;
  if (recid & 2) {
    uint64_t carry = u256_add(x, x, FN.m);
    if (carry || u256_cmp(x, FP.m) >= 0) return false;
  }
  // alpha = x^3 + 7 mod p
  U256 alpha = fp_add(fp_mul(fp_sqr(x), x), {{7, 0, 0, 0}});
  // y = alpha^((p+1)/4): p ≡ 3 mod 4 (dedicated chain; checked below)
  U256 y = fp_sqrt(alpha);
  if (u256_cmp(fp_sqr(y), alpha) != 0) return false;
  if ((y.v[0] & 1) != (uint64_t)(recid & 1)) {
    U256 ny;
    u256_sub(ny, FP.m, y);
    y = ny;
  }
  x_out = x;
  y_out = y;
  return true;
}

// Q = r⁻¹(sR − zG), computed with r_inv supplied by the caller (batch paths
// amortise the mod-n inversion) as (s·r⁻¹)·R + (−z·r⁻¹)·G: one wNAF
// variable-base multiply plus a fixed-base table multiply instead of the
// naive three scalar multiplies.
static bool recover_combine(const U256& rx, const U256& ry, const U256& s,
                            const U256& z, const U256& r_inv, Point& q_out) {
  U256 u1 = u256_is_zero(z) ? z : mod_mul(mod_sub(FN.m, z, FN), r_inv, FN);
  U256 u2 = mod_mul(s, r_inv, FN);
  Point R = {rx, ry, {{1, 0, 0, 0}}};
  Point sr = glv_ok ? glv_mul(R, u2) : wnaf_mul(R, u2);
  q_out = pt_add(sr, g_mul(u1));
  return !pt_is_inf(q_out);
}

static bool ecdsa_recover_jac(const uint8_t msg_hash[32], const U256& r,
                              const U256& s, int recid, Point& q_out) {
  if (u256_is_zero(r) || u256_is_zero(s)) return false;
  if (u256_cmp(r, FN.m) >= 0 || u256_cmp(s, FN.m) >= 0) return false;
  if (recid < 0 || recid > 3) return false;
  U256 x, y;
  if (!recover_r_point(r, recid, x, y)) return false;
  U256 z = u256_from_be(msg_hash);
  // z mod n (one conditional subtract is enough: z < 2^256 < 2n)
  if (u256_cmp(z, FN.m) >= 0) u256_sub(z, z, FN.m);
  return recover_combine(x, y, s, z, fn_inv(r), q_out);
}

// Recover affine pubkey from (msg_hash, r, s, recid). Returns false on fail.
static bool ecdsa_recover(const uint8_t msg_hash[32], const U256& r,
                          const U256& s, int recid, U256& qx, U256& qy) {
  Point q;
  if (!ecdsa_recover_jac(msg_hash, r, s, recid, q)) return false;
  return pt_to_affine(q, qx, qy);
}

// RFC 6979 deterministic nonce.
static U256 rfc6979_k(const uint8_t msg_hash[32], const uint8_t priv[32]) {
  uint8_t v[32], k[32];
  memset(v, 0x01, 32);
  memset(k, 0x00, 32);
  uint8_t sep0 = 0x00, sep1 = 0x01;
  hmac_sha256(k, 32, v, 32, &sep0, 1, priv, 32, msg_hash, 32, k);
  hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
  hmac_sha256(k, 32, v, 32, &sep1, 1, priv, 32, msg_hash, 32, k);
  hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
  while (true) {
    hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
    U256 cand = u256_from_be(v);
    if (!u256_is_zero(cand) && u256_cmp(cand, FN.m) < 0) return cand;
    hmac_sha256(k, 32, v, 32, &sep0, 1, nullptr, 0, nullptr, 0, k);
    hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
  }
}

// Sign; returns recid in [0,3] with low-s normalisation.
static bool ecdsa_sign(const uint8_t msg_hash[32], const uint8_t priv[32],
                       U256& r_out, U256& s_out, int& recid_out) {
  U256 d = u256_from_be(priv);
  if (u256_is_zero(d) || u256_cmp(d, FN.m) >= 0) return false;
  U256 z = u256_from_be(msg_hash);
  if (u256_cmp(z, FN.m) >= 0) u256_sub(z, z, FN.m);
  for (int attempt = 0; attempt < 64; attempt++) {
    U256 k = rfc6979_k(msg_hash, priv);
    U256 rx, ry;
    if (!pt_to_affine(g_mul(k), rx, ry)) continue;
    U256 r = rx;
    if (u256_cmp(r, FN.m) >= 0) u256_sub(r, r, FN.m);
    if (u256_is_zero(r)) continue;
    U256 s = mod_mul(fn_inv(k), mod_add(z, mod_mul(r, d, FN), FN), FN);
    if (u256_is_zero(s)) continue;
    int recid = int(ry.v[0] & 1) | (u256_cmp(rx, FN.m) >= 0 ? 2 : 0);
    // low-s
    U256 half = FN.m;
    uint64_t carry = 0;
    for (int i = 3; i >= 0; i--) {
      uint64_t next = half.v[i] & 1;
      half.v[i] = (half.v[i] >> 1) | (carry << 63);
      carry = next;
    }
    if (u256_cmp(s, half) > 0) {
      s = mod_sub(FN.m, s, FN);
      recid ^= 1;
    }
    r_out = r;
    s_out = s;
    recid_out = recid;
    return true;
  }
  return false;
}

// ───────────────────────── Ethereum scheme ─────────────────────────

static void eip191_hash(const uint8_t* payload, size_t len, uint8_t out[32]) {
  char prefix[64];
  int plen = snprintf(prefix, sizeof(prefix),
                      "\x19""Ethereum Signed Message:\n%zu", len);
  std::vector<uint8_t> buf(plen + len);
  memcpy(buf.data(), prefix, plen);
  memcpy(buf.data() + plen, payload, len);
  keccak256(buf.data(), buf.size(), out);
}

static void address_from_pub(const U256& qx, const U256& qy, uint8_t out[20]) {
  uint8_t pub[64], digest[32];
  u256_to_be(qx, pub);
  u256_to_be(qy, pub + 32);
  keccak256(pub, 64, digest);
  memcpy(out, digest + 12, 20);
}

// Verify one EIP-191 signature. Returns 1 valid, 0 address mismatch,
// -1 malformed recovery byte, -2 recovery failed (the reference surfaces
// -1/-2 as scheme errors and 0 as InvalidVoteSignature — distinct paths,
// src/signing/ethereum.rs:66-97).
// Per-item state threaded through the batched verify phases.
struct VerifyItem {
  U256 r, s, z, rx, ry;
};

// Phase 1: parse + digest + R-point reconstruction. Returns 1 = ok (r
// pending batch inversion), 255 = malformed recovery byte, 254 = failed.
static uint8_t eth_parse_phase(const uint8_t* payload, size_t len,
                               const uint8_t sig[65], VerifyItem& it) {
  it.r = u256_from_be(sig);
  it.s = u256_from_be(sig + 32);
  int v = sig[64];
  if (v >= 27) v -= 27;
  if (v > 1) return 255;
  if (u256_is_zero(it.r) || u256_is_zero(it.s)) return 254;
  if (u256_cmp(it.r, FN.m) >= 0 || u256_cmp(it.s, FN.m) >= 0) return 254;
  if (!recover_r_point(it.r, v, it.rx, it.ry)) return 254;
  uint8_t digest[32];
  eip191_hash(payload, len, digest);
  it.z = u256_from_be(digest);
  if (u256_cmp(it.z, FN.m) >= 0) u256_sub(it.z, it.z, FN.m);
  return 1;
}

static int eth_verify_one(const uint8_t identity[20], const uint8_t* payload,
                          size_t len, const uint8_t sig[65]) {
  U256 r = u256_from_be(sig);
  U256 s = u256_from_be(sig + 32);
  int v = sig[64];
  if (v >= 27) v -= 27;
  if (v > 1) return -1;
  uint8_t digest[32];
  eip191_hash(payload, len, digest);
  U256 qx, qy;
  if (!ecdsa_recover(digest, r, s, v, qx, qy)) return -2;
  uint8_t addr[20];
  address_from_pub(qx, qy, addr);
  return memcmp(addr, identity, 20) == 0 ? 1 : 0;
}

// ─────────────────── persistent worker pool ────────────────────────
// One process-wide pool of long-lived workers replaces the per-call
// std::thread spawn the batch entry points used to pay (~100µs per
// thread per call — measurable against sub-millisecond verify batches,
// and fatal to pipelining, where submit must return immediately).
// Every batch primitive fans its chunks here; the async submit/collect
// pair (hg_*_submit / hg_pool_wait) additionally lets Python overlap
// host crypto with device work: the workers never touch the GIL, so a
// submitted batch runs while the interpreter drives the engine.

class WorkerPool {
 public:
  static WorkerPool& instance() {
    static WorkerPool* pool = new WorkerPool();  // leaked: workers may
    return *pool;  // outlive static destruction order at process exit
  }

  // (Re)size the pool. Joins idle workers and spawns the new set; safe
  // to call between batches (in-flight tasks finish on the old threads
  // before they exit). n <= 0 restores the hardware default.
  int configure(int n) {
    std::vector<std::thread> old;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (n <= 0) n = default_threads();
      stop_epoch_++;
      old.swap(workers_);
      cv_.notify_all();
    }
    for (auto& th : old) th.join();
    std::lock_guard<std::mutex> lk(mu_);
    target_ = n;
    for (int i = 0; i < n; i++)
      workers_.emplace_back([this, epoch = stop_epoch_] { loop(epoch); });
    return n;
  }

  int size() {
    std::lock_guard<std::mutex> lk(mu_);
    ensure_started_locked();
    return (int)workers_.size();
  }

  // Tasks queued but not yet started, plus tasks currently running.
  int64_t depth() {
    std::lock_guard<std::mutex> lk(mu_);
    return (int64_t)queue_.size() + running_;
  }

  struct Job {
    std::atomic<int64_t> remaining;
    std::mutex mu;
    std::condition_variable cv;
    explicit Job(int64_t n) : remaining(n) {}
  };

  // Enqueue tasks under one shared completion job; returns it.
  std::shared_ptr<Job> submit(std::vector<std::function<void()>> tasks) {
    auto job = std::make_shared<Job>((int64_t)tasks.size());
    if (tasks.empty()) return job;
    {
      std::lock_guard<std::mutex> lk(mu_);
      ensure_started_locked();
      for (auto& t : tasks)
        queue_.emplace_back([this, job, fn = std::move(t)] {
          fn();
          finish(*job);
        });
    }
    cv_.notify_all();
    return job;
  }

  // Block until the job completes. The CALLING thread participates in
  // queue draining while it waits — a pool sized below the chunk count
  // (or busy with another job) can never deadlock the waiter, and the
  // caller's core is never idle while work is queued.
  void wait(Job& job) {
    while (job.remaining.load(std::memory_order_acquire) > 0) {
      std::function<void()> task;
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (!queue_.empty()) {
          task = std::move(queue_.front());
          queue_.pop_front();
          running_++;
        }
      }
      if (task) {
        task();
        std::lock_guard<std::mutex> lk(mu_);
        running_--;
        continue;
      }
      std::unique_lock<std::mutex> lk(job.mu);
      job.cv.wait_for(lk, std::chrono::milliseconds(50), [&] {
        return job.remaining.load(std::memory_order_acquire) <= 0;
      });
    }
  }

  // Async handle registry for the C ABI: ids are stable across the
  // submit/collect round-trip through Python.
  int64_t register_job(std::shared_ptr<Job> job) {
    std::lock_guard<std::mutex> lk(handles_mu_);
    int64_t id = next_handle_++;
    handles_[id] = std::move(job);
    return id;
  }

  int wait_handle(int64_t id) {
    std::shared_ptr<Job> job;
    {
      std::lock_guard<std::mutex> lk(handles_mu_);
      auto it = handles_.find(id);
      if (it == handles_.end()) return 1;
      job = it->second;
      handles_.erase(it);
    }
    wait(*job);
    return 0;
  }

 private:
  WorkerPool() = default;

  static int default_threads() {
#ifdef __linux__
    // Respect the AFFINITY mask, not the host's online-CPU count:
    // hardware_concurrency() reports all online CPUs, so inside a
    // cgroup/affinity-limited container (TPU-VM bench hosts) it would
    // oversubscribe the few runnable cores with dozens of contending
    // workers — the failure mode that capped the old per-call spawn
    // path well below one core's worth of throughput.
    cpu_set_t set;
    if (sched_getaffinity(0, sizeof(set), &set) == 0) {
      int n = CPU_COUNT(&set);
      if (n >= 1) return n;
    }
#endif
    int n = (int)std::thread::hardware_concurrency();
    return n < 1 ? 1 : n;
  }

  void ensure_started_locked() {
    if (workers_.empty() && target_ == 0) {
      target_ = default_threads();
      for (int i = 0; i < target_; i++)
        workers_.emplace_back([this, epoch = stop_epoch_] { loop(epoch); });
    }
  }

  void finish(Job& job) {
    if (job.remaining.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      std::lock_guard<std::mutex> lk(job.mu);
      job.cv.notify_all();
    }
  }

  void loop(uint64_t epoch) {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] {
          return !queue_.empty() || stop_epoch_ != epoch;
        });
        if (queue_.empty()) return;  // epoch rolled: retire this worker
        task = std::move(queue_.front());
        queue_.pop_front();
        running_++;
      }
      task();
      std::lock_guard<std::mutex> lk(mu_);
      running_--;
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::function<void()>> queue_;
  std::vector<std::thread> workers_;
  int64_t running_ = 0;
  int target_ = 0;
  uint64_t stop_epoch_ = 0;

  std::mutex handles_mu_;
  std::unordered_map<int64_t, std::shared_ptr<Job>> handles_;
  int64_t next_handle_ = 1;
};

// Split [0, count) into per-worker chunks on the persistent pool (0 =
// pool width); stay single-threaded below min_parallel items where even
// queue traffic dominates. The calling thread runs the first chunk
// itself and then drains the queue alongside the workers.
template <typename Work>
static void run_parallel(int64_t count, int n_threads, int64_t min_parallel,
                         const Work& work) {
  WorkerPool& pool = WorkerPool::instance();
  if (n_threads <= 0) n_threads = pool.size();
  if (n_threads < 1) n_threads = 1;
  if (n_threads == 1 || count < min_parallel) {
    work(0, count);
    return;
  }
  int64_t chunk = (count + n_threads - 1) / n_threads;
  std::vector<std::function<void()>> tasks;
  for (int t = 1; t < n_threads; t++) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(count, lo + chunk);
    if (lo >= hi) break;
    tasks.emplace_back([&work, lo, hi] { work(lo, hi); });
  }
  auto job = pool.submit(std::move(tasks));
  work(0, std::min<int64_t>(count, chunk));
  pool.wait(*job);
}

// Chunked async fan-out: enqueue [0, count) as pool tasks WITHOUT
// waiting; the returned handle blocks in hg_pool_wait. Chunks are
// smaller than one-per-worker so late chunks load-balance across
// whatever the pool is doing when they run.
template <typename Work>
static int64_t submit_parallel(int64_t count, int64_t min_chunk, Work work) {
  WorkerPool& pool = WorkerPool::instance();
  int64_t width = pool.size();
  int64_t chunk = std::max<int64_t>(min_chunk, count / (4 * width) + 1);
  std::vector<std::function<void()>> tasks;
  for (int64_t lo = 0; lo < count; lo += chunk) {
    int64_t hi = std::min<int64_t>(count, lo + chunk);
    tasks.emplace_back([work, lo, hi] { work(lo, hi); });
  }
  return pool.register_job(pool.submit(std::move(tasks)));
}

// ───────────────────────────── SHA-512 ─────────────────────────────
// Needed by Ed25519 (RFC 8032 hashes everything with SHA-512).

static const uint64_t SHA512_K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL, 0xe9b5dba58189dbbcULL,
    0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL, 0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL,
    0xd807aa98a3030242ULL, 0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL, 0xc19bf174cf692694ULL,
    0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL, 0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL,
    0x2de92c6f592b0275ULL, 0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL, 0xbf597fc7beef0ee4ULL,
    0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL, 0x06ca6351e003826fULL, 0x142929670a0e6e70ULL,
    0x27b70a8546d22ffcULL, 0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL, 0x92722c851482353bULL,
    0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL, 0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL,
    0xd192e819d6ef5218ULL, 0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL, 0x34b0bcb5e19b48a8ULL,
    0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL, 0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL,
    0x748f82ee5defb2fcULL, 0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL, 0xc67178f2e372532bULL,
    0xca273eceea26619cULL, 0xd186b8c721c0c207ULL, 0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL,
    0x06f067aa72176fbaULL, 0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL, 0x431d67c49c100d4cULL,
    0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL, 0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

static void sha512_compress(uint64_t h[8], const uint8_t block[128]) {
  uint64_t w[80];
  for (int i = 0; i < 16; i++) {
    uint64_t v = 0;
    for (int j = 0; j < 8; j++) v = (v << 8) | block[8 * i + j];
    w[i] = v;
  }
  for (int i = 16; i < 80; i++) {
    uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
    uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint64_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
           g = h[6], hh = h[7];
  for (int i = 0; i < 80; i++) {
    uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
    uint64_t ch = (e & f) ^ (~e & g);
    uint64_t t1 = hh + S1 + ch + SHA512_K[i] + w[i];
    uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
    uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint64_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

// Streaming interface: Ed25519 hashes (R || A || M) without materialising
// the concatenation.
struct Sha512 {
  uint64_t h[8] = {0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
                   0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
                   0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
                   0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
  uint8_t buf[128];
  size_t buffered = 0;
  uint64_t total = 0;

  void update(const uint8_t* data, size_t len) {
    total += len;
    if (buffered) {
      size_t take = std::min(len, (size_t)128 - buffered);
      memcpy(buf + buffered, data, take);
      buffered += take;
      data += take;
      len -= take;
      if (buffered == 128) {
        sha512_compress(h, buf);
        buffered = 0;
      }
    }
    while (len >= 128) {
      sha512_compress(h, data);
      data += 128;
      len -= 128;
    }
    if (len) {
      memcpy(buf, data, len);
      buffered = len;
    }
  }

  void final(uint8_t out[64]) {
    uint8_t pad[256] = {0};
    memcpy(pad, buf, buffered);
    pad[buffered] = 0x80;
    size_t blocks = (buffered + 17 <= 128) ? 1 : 2;
    uint64_t bits = total * 8;  // < 2^64 for any realistic payload
    for (int i = 0; i < 8; i++)
      pad[blocks * 128 - 1 - i] = uint8_t(bits >> (8 * i));
    for (size_t b = 0; b < blocks; b++) sha512_compress(h, pad + 128 * b);
    for (int i = 0; i < 8; i++)
      for (int j = 0; j < 8; j++) out[8 * i + j] = uint8_t(h[i] >> (8 * (7 - j)));
  }
};

// ─────────────── curve25519 field: radix-2^51 limbs ────────────────
// p = 2^255 - 19. Unlike the secp256k1 section's canonical-every-op
// 4x64 code, this uses the donna-style 5x51 representation with lazy
// carries: limbs stay < 2^52 between operations, no compare/subtract
// per op, and 2^255 ≡ 19 makes the product fold a single multiply-add
// per limb. The Ed25519 hot path is pure mul/sq chains, so this is
// where the batch-verify throughput comes from.

typedef uint64_t fe25[5];
typedef unsigned __int128 uint128_t;

static const uint64_t M51 = 0x7FFFFFFFFFFFFULL;

static void fe_copy(fe25 r, const fe25 a) { memcpy(r, a, sizeof(fe25)); }

static void fe_0(fe25 r) { memset(r, 0, sizeof(fe25)); }

static void fe_1(fe25 r) {
  fe_0(r);
  r[0] = 1;
}

// One sequential carry pass: limbs < 2^54 in, < 2^51 + tiny out.
static inline void fe_carry(fe25 h) {
  uint64_t c;
  c = h[0] >> 51; h[0] &= M51; h[1] += c;
  c = h[1] >> 51; h[1] &= M51; h[2] += c;
  c = h[2] >> 51; h[2] &= M51; h[3] += c;
  c = h[3] >> 51; h[3] &= M51; h[4] += c;
  c = h[4] >> 51; h[4] &= M51; h[0] += 19 * c;
}

// Lazy (carry-free) add/sub, donna-style: limbs grow to < 2^54, which
// fe_mul/fe_sq/fe_carry/fe_tobytes all tolerate. The point formulas
// below are arranged so no operand ever chains more than two uncarried
// add/subs before re-entering a multiply (which re-reduces), and every
// fe_sub's subtrahend is < 2^53 limb-wise so adding 4p cannot underflow.
static inline void fe_add(fe25 r, const fe25 a, const fe25 b) {
  for (int i = 0; i < 5; i++) r[i] = a[i] + b[i];
}

static inline void fe_sub(fe25 r, const fe25 a, const fe25 b) {
  r[0] = a[0] + 0x1FFFFFFFFFFFB4ULL - b[0];
  r[1] = a[1] + 0x1FFFFFFFFFFFFCULL - b[1];
  r[2] = a[2] + 0x1FFFFFFFFFFFFCULL - b[2];
  r[3] = a[3] + 0x1FFFFFFFFFFFFCULL - b[3];
  r[4] = a[4] + 0x1FFFFFFFFFFFFCULL - b[4];
}

static inline void fe_neg(fe25 r, const fe25 a) {
  fe25 zero;
  fe_0(zero);
  fe_sub(r, zero, a);
}

static void fe_mul(fe25 r, const fe25 f, const fe25 g) {
  uint64_t f0 = f[0], f1 = f[1], f2 = f[2], f3 = f[3], f4 = f[4];
  uint64_t g0 = g[0], g1 = g[1], g2 = g[2], g3 = g[3], g4 = g[4];
  uint64_t g1_19 = 19 * g1, g2_19 = 19 * g2, g3_19 = 19 * g3, g4_19 = 19 * g4;
  uint128_t t0 = (uint128_t)f0 * g0 + (uint128_t)f1 * g4_19 +
                 (uint128_t)f2 * g3_19 + (uint128_t)f3 * g2_19 +
                 (uint128_t)f4 * g1_19;
  uint128_t t1 = (uint128_t)f0 * g1 + (uint128_t)f1 * g0 +
                 (uint128_t)f2 * g4_19 + (uint128_t)f3 * g3_19 +
                 (uint128_t)f4 * g2_19;
  uint128_t t2 = (uint128_t)f0 * g2 + (uint128_t)f1 * g1 +
                 (uint128_t)f2 * g0 + (uint128_t)f3 * g4_19 +
                 (uint128_t)f4 * g3_19;
  uint128_t t3 = (uint128_t)f0 * g3 + (uint128_t)f1 * g2 +
                 (uint128_t)f2 * g1 + (uint128_t)f3 * g0 +
                 (uint128_t)f4 * g4_19;
  uint128_t t4 = (uint128_t)f0 * g4 + (uint128_t)f1 * g3 +
                 (uint128_t)f2 * g2 + (uint128_t)f3 * g1 +
                 (uint128_t)f4 * g0;
  uint64_t r0 = (uint64_t)t0 & M51; t1 += (uint64_t)(t0 >> 51);
  uint64_t r1 = (uint64_t)t1 & M51; t2 += (uint64_t)(t1 >> 51);
  uint64_t r2 = (uint64_t)t2 & M51; t3 += (uint64_t)(t2 >> 51);
  uint64_t r3 = (uint64_t)t3 & M51; t4 += (uint64_t)(t3 >> 51);
  uint64_t r4 = (uint64_t)t4 & M51;
  r0 += 19 * (uint64_t)(t4 >> 51);
  r1 += r0 >> 51; r0 &= M51;
  r[0] = r0; r[1] = r1; r[2] = r2; r[3] = r3; r[4] = r4;
}

static void fe_sq(fe25 r, const fe25 f) {
  uint64_t f0 = f[0], f1 = f[1], f2 = f[2], f3 = f[3], f4 = f[4];
  uint64_t f0_2 = 2 * f0, f1_2 = 2 * f1;
  uint64_t f1_38 = 38 * f1, f2_38 = 38 * f2, f3_38 = 38 * f3;
  uint64_t f3_19 = 19 * f3, f4_19 = 19 * f4;
  uint128_t t0 = (uint128_t)f0 * f0 + (uint128_t)f1_38 * f4 +
                 (uint128_t)f2_38 * f3;
  uint128_t t1 = (uint128_t)f0_2 * f1 + (uint128_t)f2_38 * f4 +
                 (uint128_t)f3_19 * f3;
  uint128_t t2 = (uint128_t)f0_2 * f2 + (uint128_t)f1 * f1 +
                 (uint128_t)f3_38 * f4;
  uint128_t t3 = (uint128_t)f0_2 * f3 + (uint128_t)f1_2 * f2 +
                 (uint128_t)f4_19 * f4;
  uint128_t t4 = (uint128_t)f0_2 * f4 + (uint128_t)f1_2 * f3 +
                 (uint128_t)f2 * f2;
  uint64_t r0 = (uint64_t)t0 & M51; t1 += (uint64_t)(t0 >> 51);
  uint64_t r1 = (uint64_t)t1 & M51; t2 += (uint64_t)(t1 >> 51);
  uint64_t r2 = (uint64_t)t2 & M51; t3 += (uint64_t)(t2 >> 51);
  uint64_t r3 = (uint64_t)t3 & M51; t4 += (uint64_t)(t3 >> 51);
  uint64_t r4 = (uint64_t)t4 & M51;
  r0 += 19 * (uint64_t)(t4 >> 51);
  r1 += r0 >> 51; r0 &= M51;
  r[0] = r0; r[1] = r1; r[2] = r2; r[3] = r3; r[4] = r4;
}

static void fe_sqn(fe25 r, const fe25 a, int n) {
  fe_sq(r, a);
  for (int i = 1; i < n; i++) fe_sq(r, r);
}

static inline uint64_t load64_le(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;  // little-endian host assumed, as in keccak256 above
}

static inline void store64_le(uint8_t* p, uint64_t v) { memcpy(p, &v, 8); }

static void fe_frombytes(fe25 h, const uint8_t s[32]) {
  uint64_t a0 = load64_le(s), a1 = load64_le(s + 8), a2 = load64_le(s + 16),
           a3 = load64_le(s + 24);
  h[0] = a0 & M51;
  h[1] = ((a0 >> 51) | (a1 << 13)) & M51;
  h[2] = ((a1 >> 38) | (a2 << 26)) & M51;
  h[3] = ((a2 >> 25) | (a3 << 39)) & M51;
  h[4] = (a3 >> 12) & M51;  // bit 255 (the sign bit slot) dropped
}

static void fe_tobytes(uint8_t s[32], const fe25 f) {
  fe25 h;
  fe_copy(h, f);
  fe_carry(h);
  fe_carry(h);
  // Canonicalize: add 19 and see whether that wraps past 2^255; if so
  // the value was >= p and needs the fold applied for real.
  uint64_t q = (h[0] + 19) >> 51;
  q = (h[1] + q) >> 51;
  q = (h[2] + q) >> 51;
  q = (h[3] + q) >> 51;
  q = (h[4] + q) >> 51;
  h[0] += 19 * q;
  h[1] += h[0] >> 51; h[0] &= M51;
  h[2] += h[1] >> 51; h[1] &= M51;
  h[3] += h[2] >> 51; h[2] &= M51;
  h[4] += h[3] >> 51; h[3] &= M51;
  h[4] &= M51;
  store64_le(s, h[0] | (h[1] << 51));
  store64_le(s + 8, (h[1] >> 13) | (h[2] << 38));
  store64_le(s + 16, (h[2] >> 26) | (h[3] << 25));
  store64_le(s + 24, (h[3] >> 39) | (h[4] << 12));
}

static bool fe_iszero(const fe25 f) {
  uint8_t s[32];
  fe_tobytes(s, f);
  uint8_t acc = 0;
  for (int i = 0; i < 32; i++) acc |= s[i];
  return acc == 0;
}

static bool fe_eq(const fe25 a, const fe25 b) {
  uint8_t sa[32], sb[32];
  fe_tobytes(sa, a);
  fe_tobytes(sb, b);
  return memcmp(sa, sb, 32) == 0;
}

static int fe_isnegative(const fe25 f) {
  uint8_t s[32];
  fe_tobytes(s, f);
  return s[0] & 1;
}

// Interleaved squaring over a group of independent elements: one fe_sq
// is a ~254-deep dependency chain in the exponent towers below, so
// stepping a group of 4 states per squaring lets the CPU overlap their
// multiply latencies (~1.7x on batched decompression).
static void fe_sq_each(fe25* x, int cnt, int n) {
  for (int s = 0; s < n; s++)
    for (int k = 0; k < cnt; k++) fe_sq(x[k], x[k]);
}

// Batched z^(2^252 - 3) (the sqrt helper exponent): the fe_pow22523
// chain with every step applied to ``cnt`` independent inputs.
static constexpr int FE_POW_GROUP = 4;

static void fe_pow22523_multi(fe25* r, const fe25* z, int cnt) {
  fe25 t0[FE_POW_GROUP], t1[FE_POW_GROUP], t2[FE_POW_GROUP];
  for (int k = 0; k < cnt; k++) {
    fe_sq(t0[k], z[k]);                    // 2
    fe_sq(t1[k], t0[k]);
    fe_sq(t1[k], t1[k]);                   // 8
    fe_mul(t1[k], z[k], t1[k]);            // 9
    fe_mul(t0[k], t0[k], t1[k]);           // 11
    fe_sq(t0[k], t0[k]);                   // 22
    fe_mul(t0[k], t1[k], t0[k]);           // 31 = 2^5 - 1
  }
  for (int k = 0; k < cnt; k++) fe_copy(t1[k], t0[k]);
  fe_sq_each(t1, cnt, 5);
  for (int k = 0; k < cnt; k++) fe_mul(t0[k], t1[k], t0[k]);  // 2^10 - 1
  for (int k = 0; k < cnt; k++) fe_copy(t1[k], t0[k]);
  fe_sq_each(t1, cnt, 10);
  for (int k = 0; k < cnt; k++) fe_mul(t1[k], t1[k], t0[k]);  // 2^20 - 1
  for (int k = 0; k < cnt; k++) fe_copy(t2[k], t1[k]);
  fe_sq_each(t2, cnt, 20);
  for (int k = 0; k < cnt; k++) fe_mul(t1[k], t2[k], t1[k]);  // 2^40 - 1
  fe_sq_each(t1, cnt, 10);
  for (int k = 0; k < cnt; k++) fe_mul(t0[k], t1[k], t0[k]);  // 2^50 - 1
  for (int k = 0; k < cnt; k++) fe_copy(t1[k], t0[k]);
  fe_sq_each(t1, cnt, 50);
  for (int k = 0; k < cnt; k++) fe_mul(t1[k], t1[k], t0[k]);  // 2^100 - 1
  for (int k = 0; k < cnt; k++) fe_copy(t2[k], t1[k]);
  fe_sq_each(t2, cnt, 100);
  for (int k = 0; k < cnt; k++) fe_mul(t1[k], t2[k], t1[k]);  // 2^200 - 1
  fe_sq_each(t1, cnt, 50);
  for (int k = 0; k < cnt; k++) fe_mul(t0[k], t1[k], t0[k]);  // 2^250 - 1
  fe_sq_each(t0, cnt, 2);                                     // 2^252 - 4
  for (int k = 0; k < cnt; k++) fe_mul(r[k], t0[k], z[k]);    // 2^252 - 3
}

// z^(2^250 - 1) — the shared tower of both exponent chains below.
static void fe_pow250_1(fe25 out, fe25 z11_out, const fe25 z) {
  fe25 t0, t1, t2;
  fe_sq(t0, z);                    // 2
  fe_sqn(t1, t0, 2);               // 8
  fe_mul(t1, z, t1);               // 9
  fe_mul(t0, t0, t1);              // 11
  fe_copy(z11_out, t0);
  fe_sq(t0, t0);                   // 22
  fe_mul(t0, t1, t0);              // 31 = 2^5 - 1
  fe_sqn(t1, t0, 5);
  fe_mul(t0, t1, t0);              // 2^10 - 1
  fe_sqn(t1, t0, 10);
  fe_mul(t1, t1, t0);              // 2^20 - 1
  fe_sqn(t2, t1, 20);
  fe_mul(t1, t2, t1);              // 2^40 - 1
  fe_sqn(t1, t1, 10);
  fe_mul(t0, t1, t0);              // 2^50 - 1
  fe_sqn(t1, t0, 50);
  fe_mul(t1, t1, t0);              // 2^100 - 1
  fe_sqn(t2, t1, 100);
  fe_mul(t1, t2, t1);              // 2^200 - 1
  fe_sqn(t1, t1, 50);
  fe_mul(out, t1, t0);             // 2^250 - 1
}

// z^(p - 2) = z^(2^255 - 21): the modular inverse.
static void fe_invert(fe25 r, const fe25 z) {
  fe25 t, z11;
  fe_pow250_1(t, z11, z);
  fe_sqn(t, t, 5);                 // 2^255 - 2^5
  fe_mul(r, t, z11);               // 2^255 - 32 + 11 = 2^255 - 21
}

// z^((p - 5) / 8) = z^(2^252 - 3): the square-root helper exponent.
static void fe_pow22523(fe25 r, const fe25 z) {
  fe25 t, z11;
  fe_pow250_1(t, z11, z);
  fe_sqn(t, t, 2);                 // 2^252 - 4
  fe_mul(r, t, z);                 // 2^252 - 3
}

// Montgomery batch inversion over fe25 (same trick as fp_batch_inv):
// zeros are left untouched.
static void fe_batch_invert(fe25* vals, int n) {
  std::vector<uint64_t> prefix((size_t)n * 5);
  fe25 acc;
  fe_1(acc);
  for (int i = 0; i < n; i++) {
    memcpy(&prefix[(size_t)i * 5], acc, sizeof(fe25));
    if (!fe_iszero(vals[i])) fe_mul(acc, acc, vals[i]);
  }
  fe25 inv;
  fe_invert(inv, acc);
  for (int i = n - 1; i >= 0; i--) {
    if (fe_iszero(vals[i])) continue;
    fe25 orig;
    fe_copy(orig, vals[i]);
    fe_mul(vals[i], inv, (const uint64_t*)&prefix[(size_t)i * 5]);
    fe_mul(inv, inv, orig);
  }
}

// Curve constants (radix-51).
static const fe25 ED_D = {0x34DCA135978A3ULL, 0x1A8283B156EBDULL,
                          0x5E7A26001C029ULL, 0x739C663A03CBBULL,
                          0x52036CEE2B6FFULL};
static const fe25 ED_2D = {0x69B9426B2F159ULL, 0x35050762ADD7AULL,
                           0x3CF44C0038052ULL, 0x6738CC7407977ULL,
                           0x2406D9DC56DFFULL};
static const fe25 ED_SQRTM1 = {0x61B274A0EA0B0ULL, 0x0D5A5FC8F189DULL,
                               0x7EF5E9CBD0C60ULL, 0x78595A6804C9EULL,
                               0x2B8324804FC1DULL};
static const fe25 ED_BX = {0x62D608F25D51AULL, 0x412A4B4F6592AULL,
                           0x75B7171A4B31DULL, 0x1FF60527118FEULL,
                           0x216936D3CD6E5ULL};
static const fe25 ED_BY = {0x6666666666658ULL, 0x4CCCCCCCCCCCCULL,
                           0x1999999999999ULL, 0x3333333333333ULL,
                           0x6666666666666ULL};

// ───────────── Edwards points (extended coordinates) ───────────────

struct GeP3 {
  fe25 X, Y, Z, T;  // x = X/Z, y = Y/Z, T = XY/Z
};

// Affine precomputed form for the fixed-base table: (y+x, y-x, 2d·x·y).
struct GeNiels {
  fe25 ypx, ymx, xy2d;
};

static void ge_identity(GeP3& r) {
  fe_0(r.X);
  fe_1(r.Y);
  fe_1(r.Z);
  fe_0(r.T);
}

// Unified addition (add-2008-hwcd-3 for a = -1): ~8 muls.
static void ge_add(GeP3& r, const GeP3& p, const GeP3& q) {
  fe25 a, b, c, d, e, f, g, h, t1, t2;
  fe_sub(t1, p.Y, p.X);
  fe_sub(t2, q.Y, q.X);
  fe_mul(a, t1, t2);
  fe_add(t1, p.Y, p.X);
  fe_add(t2, q.Y, q.X);
  fe_mul(b, t1, t2);
  fe_mul(c, p.T, q.T);
  fe_mul(c, c, ED_2D);
  fe_mul(d, p.Z, q.Z);
  fe_add(d, d, d);
  fe_sub(e, b, a);
  fe_sub(f, d, c);
  fe_add(g, d, c);
  fe_add(h, b, a);
  fe_mul(r.X, e, f);
  fe_mul(r.Y, g, h);
  fe_mul(r.T, e, h);
  fe_mul(r.Z, f, g);
}

// Mixed addition with an affine Niels point: saves the Z multiply.
static void ge_madd(GeP3& r, const GeP3& p, const GeNiels& q) {
  fe25 a, b, c, d, e, f, g, h, t1;
  fe_sub(t1, p.Y, p.X);
  fe_mul(a, t1, q.ymx);
  fe_add(t1, p.Y, p.X);
  fe_mul(b, t1, q.ypx);
  fe_mul(c, p.T, q.xy2d);
  fe_add(d, p.Z, p.Z);
  fe_sub(e, b, a);
  fe_sub(f, d, c);
  fe_add(g, d, c);
  fe_add(h, b, a);
  fe_mul(r.X, e, f);
  fe_mul(r.Y, g, h);
  fe_mul(r.T, e, h);
  fe_mul(r.Z, f, g);
}

// Doubling (dbl-2008-hwcd, all four outputs negated — an equivalent
// projective representative — so no field negation is needed).
static void ge_dbl(GeP3& r, const GeP3& p) {
  fe25 a, b, c, e, f, g, h, t;
  fe_sq(a, p.X);
  fe_sq(b, p.Y);
  fe_sq(c, p.Z);
  fe_add(c, c, c);
  fe_add(h, a, b);
  fe_add(t, p.X, p.Y);
  fe_sq(t, t);
  fe_sub(e, t, h);     // 2XY (h < 2^53 limb-wise: sum of two squarings)
  fe_sub(g, b, a);     // Y² - X²
  fe_add(t, c, a);     // f = 2Z² - (Y²-X²) computed as (2Z²+X²) - Y² so
  fe_sub(f, t, b);     // the lazy sub's subtrahend stays reduced
  fe_mul(r.X, e, f);
  fe_mul(r.Y, g, h);
  fe_mul(r.T, e, h);
  fe_mul(r.Z, f, g);
}

static void ge_neg(GeP3& r, const GeP3& p) {
  fe_neg(r.X, p.X);
  fe_copy(r.Y, p.Y);
  fe_copy(r.Z, p.Z);
  fe_neg(r.T, p.T);
}

static bool ge_is_identity(const GeP3& p) {
  // x = 0 and y = z (y/z = 1).
  return fe_iszero(p.X) && fe_eq(p.Y, p.Z);
}

static void ge_tobytes(uint8_t s[32], const GeP3& p) {
  fe25 zi, x, y;
  fe_invert(zi, p.Z);
  fe_mul(x, p.X, zi);
  fe_mul(y, p.Y, zi);
  fe_tobytes(s, y);
  s[31] ^= uint8_t(fe_isnegative(x) << 7);
}

// Decompress a point: y from the low 255 bits, x = ±sqrt((y²-1)/(dy²+1)).
// Rejects non-canonical y (>= p), off-curve x, and x = 0 with the sign
// bit set (RFC 8032 §5.1.3 decoding).
static bool ge_frombytes(GeP3& r, const uint8_t s[32]) {
  // Canonical-encoding check: re-serializing the decoded y must give the
  // same 255 bits back.
  fe25 y;
  fe_frombytes(y, s);
  uint8_t canon[32];
  fe_tobytes(canon, y);
  for (int i = 0; i < 31; i++)
    if (canon[i] != s[i]) return false;
  if ((canon[31] & 0x7F) != (s[31] & 0x7F)) return false;

  fe25 yy, u, v, x, xx, t;
  fe_sq(yy, y);
  fe25 one;
  fe_1(one);
  fe_sub(u, yy, one);        // y² - 1
  fe_carry(u);               // u feeds fe_neg below: keep it reduced
  fe_mul(v, yy, ED_D);
  fe_add(v, v, one);         // d·y² + 1
  // x = u·v³·(u·v⁷)^((p-5)/8)
  fe25 v3, v7, p1;
  fe_sq(v3, v);
  fe_mul(v3, v3, v);         // v³
  fe_sq(v7, v3);
  fe_mul(v7, v7, v);         // v⁷
  fe_mul(p1, u, v7);
  fe_pow22523(p1, p1);
  fe_mul(x, u, v3);
  fe_mul(x, x, p1);
  // check v·x² against ±u
  fe_sq(xx, x);
  fe_mul(xx, xx, v);
  fe25 neg_u;
  fe_neg(neg_u, u);
  if (fe_eq(xx, u)) {
    // x is the root
  } else if (fe_eq(xx, neg_u)) {
    fe_mul(x, x, ED_SQRTM1);
  } else {
    return false;
  }
  int sign = (s[31] >> 7) & 1;
  if (fe_iszero(x)) {
    if (sign) return false;  // -0 is not a valid encoding
  } else if (fe_isnegative(x) != sign) {
    fe_neg(x, x);
  }
  fe_copy(r.X, x);
  fe_copy(r.Y, y);
  fe_1(r.Z);
  fe_mul(r.T, x, y);
  (void)t;
  return true;
}

// Batched decompression: identical acceptance rules to ge_frombytes,
// but the ~254-squaring sqrt exponent chains of up to FE_POW_GROUP
// points run interleaved (fe_pow22523_multi) — decompression is the
// single largest per-signature cost of batch verification, and it is
// latency-bound, not throughput-bound.
static void ge_frombytes_multi(GeP3* out, uint8_t* ok,
                               const uint8_t* const* encs, int count) {
  for (int base = 0; base < count; base += FE_POW_GROUP) {
    int cnt = std::min(FE_POW_GROUP, count - base);
    fe25 y[FE_POW_GROUP], u[FE_POW_GROUP], v[FE_POW_GROUP];
    fe25 v3[FE_POW_GROUP], pin[FE_POW_GROUP], p1[FE_POW_GROUP];
    bool pre_ok[FE_POW_GROUP];
    for (int k = 0; k < cnt; k++) {
      const uint8_t* s = encs[base + k];
      fe_frombytes(y[k], s);
      uint8_t canon[32];
      fe_tobytes(canon, y[k]);
      pre_ok[k] = memcmp(canon, s, 31) == 0 &&
                  (canon[31] & 0x7F) == (s[31] & 0x7F);
      fe25 yy, one, v7;
      fe_1(one);
      fe_sq(yy, y[k]);
      fe_sub(u[k], yy, one);
      fe_carry(u[k]);
      fe_mul(v[k], yy, ED_D);
      fe_add(v[k], v[k], one);
      fe_sq(v3[k], v[k]);
      fe_mul(v3[k], v3[k], v[k]);
      fe_sq(v7, v3[k]);
      fe_mul(v7, v7, v[k]);
      fe_mul(pin[k], u[k], v7);
    }
    fe_pow22523_multi(p1, pin, cnt);
    for (int k = 0; k < cnt; k++) {
      ok[base + k] = 0;
      if (!pre_ok[k]) continue;
      const uint8_t* s = encs[base + k];
      fe25 x, xx, neg_u;
      fe_mul(x, u[k], v3[k]);
      fe_mul(x, x, p1[k]);
      fe_sq(xx, x);
      fe_mul(xx, xx, v[k]);
      fe_neg(neg_u, u[k]);
      if (fe_eq(xx, u[k])) {
        // x is the root
      } else if (fe_eq(xx, neg_u)) {
        fe_mul(x, x, ED_SQRTM1);
      } else {
        continue;
      }
      int sign = (s[31] >> 7) & 1;
      if (fe_iszero(x)) {
        if (sign) continue;
      } else if (fe_isnegative(x) != sign) {
        fe_neg(x, x);
      }
      GeP3& r = out[base + k];
      fe_copy(r.X, x);
      fe_copy(r.Y, y[k]);
      fe_1(r.Z);
      fe_mul(r.T, x, y[k]);
      ok[base + k] = 1;
    }
  }
}

// ───────────── scalar field mod L (Montgomery 4x64) ────────────────
// L = 2^252 + 27742317777372353535851937790883648493. L is not of the
// 2^256 - c shape the generic Modulus machinery folds, so scalars use
// CIOS Montgomery multiplication instead.

struct Sc25 {
  uint64_t v[4];
};

static const uint64_t SC_L[4] = {0x5812631A5CF5D3EDULL, 0x14DEF9DEA2F79CD6ULL,
                                 0x0000000000000000ULL, 0x1000000000000000ULL};
static const uint64_t SC_LFACTOR = 0xD2B51DA312547E1BULL;  // -L⁻¹ mod 2^64
static const Sc25 SC_R2 = {{0xA40611E3449C0F01ULL, 0xD00E1BA768859347ULL,
                            0xCEEC73D217F5BE65ULL, 0x0399411B7C309A3DULL}};
static const Sc25 SC_ONE = {{1, 0, 0, 0}};

static bool sc_gte_l(const Sc25& a) {
  for (int i = 3; i >= 0; i--) {
    if (a.v[i] > SC_L[i]) return true;
    if (a.v[i] < SC_L[i]) return false;
  }
  return true;
}

static void sc_sub_l(Sc25& a) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 4; i++) {
    unsigned __int128 d = (unsigned __int128)a.v[i] - SC_L[i] - borrow;
    a.v[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
}

static Sc25 sc_add(const Sc25& a, const Sc25& b) {
  Sc25 r;
  unsigned __int128 carry = 0;
  for (int i = 0; i < 4; i++) {
    carry += (unsigned __int128)a.v[i] + b.v[i];
    r.v[i] = (uint64_t)carry;
    carry >>= 64;
  }
  if (carry || sc_gte_l(r)) sc_sub_l(r);
  return r;
}

// CIOS Montgomery: returns a·b·2^-256 mod L. Valid for a < 2^256, b < L.
static Sc25 sc_montmul(const Sc25& a, const Sc25& b) {
  uint64_t t[5] = {0, 0, 0, 0, 0};
  uint64_t t5 = 0;
  for (int i = 0; i < 4; i++) {
    unsigned __int128 c = 0;
    for (int j = 0; j < 4; j++) {
      c += (unsigned __int128)a.v[i] * b.v[j] + t[j];
      t[j] = (uint64_t)c;
      c >>= 64;
    }
    c += t[4];
    t[4] = (uint64_t)c;
    t5 = (uint64_t)(c >> 64);
    uint64_t m = t[0] * SC_LFACTOR;
    c = (unsigned __int128)m * SC_L[0] + t[0];
    c >>= 64;
    for (int j = 1; j < 4; j++) {
      c += (unsigned __int128)m * SC_L[j] + t[j];
      t[j - 1] = (uint64_t)c;
      c >>= 64;
    }
    c += t[4];
    t[3] = (uint64_t)c;
    t[4] = t5 + (uint64_t)(c >> 64);
  }
  Sc25 r = {{t[0], t[1], t[2], t[3]}};
  if (t[4] || sc_gte_l(r)) sc_sub_l(r);
  return r;
}

// a·b mod L for a, b < 2^256 (b < L).
static Sc25 sc_mulmod(const Sc25& a, const Sc25& b) {
  return sc_montmul(sc_montmul(a, SC_R2), b);
}

static Sc25 sc_frombytes32(const uint8_t s[32]) {
  Sc25 r;
  for (int i = 0; i < 4; i++) r.v[i] = load64_le(s + 8 * i);
  return r;
}

// Reduce a 64-byte little-endian value (SHA-512 output) mod L.
static Sc25 sc_frombytes64(const uint8_t s[64]) {
  Sc25 lo = sc_frombytes32(s);
  Sc25 hi = sc_frombytes32(s + 32);
  // hi·2^256 mod L = montmul(hi, R2); lo mod L = redc(montmul(lo, R2)).
  Sc25 hi_part = sc_montmul(hi, SC_R2);
  Sc25 lo_part = sc_montmul(sc_montmul(lo, SC_R2), SC_ONE);
  return sc_add(hi_part, lo_part);
}

static void sc_tobytes(uint8_t s[32], const Sc25& a) {
  for (int i = 0; i < 4; i++) store64_le(s + 8 * i, a.v[i]);
}

static bool sc_iszero(const Sc25& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

// ───────────────────────── Ed25519 engine ──────────────────────────

// Fixed-base 8-bit window table for B (mirror of the secp g_table):
// ed_b_table[w][d-1] = (256^w · d) · B in affine Niels form, so both
// signing and the batch-verify s·B term cost ~32 mixed additions.
static constexpr int EDT_WINDOWS = 32;
static constexpr int EDT_ENTRIES = 255;
static GeNiels ed_b_table[EDT_WINDOWS][EDT_ENTRIES];
static std::once_flag ed_table_once;

static void build_ed_table_impl() {
  std::vector<GeP3> jac((size_t)EDT_WINDOWS * EDT_ENTRIES);
  GeP3 base;
  fe_copy(base.X, ED_BX);
  fe_copy(base.Y, ED_BY);
  fe_1(base.Z);
  fe_mul(base.T, ED_BX, ED_BY);
  for (int w = 0; w < EDT_WINDOWS; w++) {
    GeP3 acc;
    ge_identity(acc);
    for (int d = 0; d < EDT_ENTRIES; d++) {
      ge_add(acc, acc, base);
      jac[(size_t)w * EDT_ENTRIES + d] = acc;
    }
    for (int b = 0; b < 8; b++) ge_dbl(base, base);
  }
  std::vector<uint64_t> zs((size_t)EDT_WINDOWS * EDT_ENTRIES * 5);
  for (size_t i = 0; i < jac.size(); i++)
    memcpy(&zs[i * 5], jac[i].Z, sizeof(fe25));
  fe_batch_invert((fe25*)zs.data(), (int)jac.size());
  for (size_t i = 0; i < jac.size(); i++) {
    fe25 zi, x, y, xy;
    memcpy(zi, &zs[i * 5], sizeof(fe25));
    fe_mul(x, jac[i].X, zi);
    fe_mul(y, jac[i].Y, zi);
    GeNiels& n = ed_b_table[i / EDT_ENTRIES][i % EDT_ENTRIES];
    fe_add(n.ypx, y, x);
    fe_sub(n.ymx, y, x);
    fe_mul(xy, x, y);
    fe_mul(n.xy2d, xy, ED_2D);
  }
}

static void build_ed_table() { std::call_once(ed_table_once, build_ed_table_impl); }

// scalar · B via the fixed-base window table (scalar as 32 LE bytes).
static void ge_scalarmult_base(GeP3& r, const uint8_t scalar[32]) {
  build_ed_table();
  ge_identity(r);
  for (int w = 0; w < EDT_WINDOWS; w++) {
    int digit = scalar[w];
    if (digit) ge_madd(r, r, ed_b_table[w][digit - 1]);
  }
}

// Variable-base scalar multiply via wNAF-5 (reuses the shared
// build_wnaf5 digit scan; the table holds 1P, 3P, ..., 15P).
struct GeWnafTable {
  GeP3 pts[8];
};

static void ge_wnaf_table(GeWnafTable& t, const GeP3& p) {
  t.pts[0] = p;
  GeP3 p2;
  ge_dbl(p2, p);
  for (int i = 1; i < 8; i++) ge_add(t.pts[i], t.pts[i - 1], p2);
}

// Batched table build: each table is an 8-deep addition chain, so
// interleaving a group of independent points overlaps their latencies
// (same trick as fe_pow22523_multi).
static void ge_wnaf_table_multi(GeWnafTable* tbls, const GeP3* pts,
                                int count) {
  constexpr int G = 4;
  for (int base = 0; base < count; base += G) {
    int cnt = std::min(G, count - base);
    GeP3 p2[G];
    for (int k = 0; k < cnt; k++) tbls[base + k].pts[0] = pts[base + k];
    for (int k = 0; k < cnt; k++) ge_dbl(p2[k], pts[base + k]);
    for (int i = 1; i < 8; i++)
      for (int k = 0; k < cnt; k++)
        ge_add(tbls[base + k].pts[i], tbls[base + k].pts[i - 1], p2[k]);
  }
}

static void ge_wnaf_add_digit(GeP3& acc, const GeWnafTable& t, int d) {
  if (d > 0) {
    ge_add(acc, acc, t.pts[(d - 1) >> 1]);
  } else if (d < 0) {
    GeP3 n;
    ge_neg(n, t.pts[((-d) - 1) >> 1]);
    ge_add(acc, acc, n);
  }
}

static void ge_scalarmult(GeP3& r, const GeP3& p, const Sc25& k) {
  U256 u = {{k.v[0], k.v[1], k.v[2], k.v[3]}};
  int8_t naf[260];
  int len = build_wnaf5(u, naf);
  GeWnafTable t;
  ge_wnaf_table(t, p);
  ge_identity(r);
  for (int i = len - 1; i >= 0; i--) {
    ge_dbl(r, r);
    ge_wnaf_add_digit(r, t, naf[i]);
  }
}

// Derive (a_scalar, prefix, A_bytes) from a 32-byte seed (RFC 8032 §5.1.5).
static void ed_expand_key(const uint8_t seed[32], uint8_t a_clamped[32],
                          uint8_t prefix[32], uint8_t pub[32]) {
  Sha512 h;
  h.update(seed, 32);
  uint8_t digest[64];
  h.final(digest);
  digest[0] &= 248;
  digest[31] &= 127;
  digest[31] |= 64;
  memcpy(a_clamped, digest, 32);
  memcpy(prefix, digest + 32, 32);
  GeP3 A;
  ge_scalarmult_base(A, a_clamped);
  ge_tobytes(pub, A);
}

static void ed_sign(const uint8_t seed[32], const uint8_t* msg, size_t len,
                    uint8_t sig[64]) {
  uint8_t a_clamped[32], prefix[32], pub[32];
  ed_expand_key(seed, a_clamped, prefix, pub);
  Sha512 hr;
  hr.update(prefix, 32);
  hr.update(msg, len);
  uint8_t rdigest[64];
  hr.final(rdigest);
  Sc25 r = sc_frombytes64(rdigest);
  uint8_t rbytes[32];
  sc_tobytes(rbytes, r);
  GeP3 R;
  ge_scalarmult_base(R, rbytes);
  ge_tobytes(sig, R);
  Sha512 hk;
  hk.update(sig, 32);
  hk.update(pub, 32);
  hk.update(msg, len);
  uint8_t kdigest[64];
  hk.final(kdigest);
  Sc25 k = sc_frombytes64(kdigest);
  // a mod L (the clamped scalar is < 2^255 but can exceed L).
  uint8_t awide[64] = {0};
  memcpy(awide, a_clamped, 32);
  Sc25 a = sc_frombytes64(awide);
  Sc25 s = sc_add(sc_mulmod(k, a), r);
  sc_tobytes(sig + 32, s);
}

// Cofactored verification: accept iff 8·(s·B - h·A - R) is the identity.
// Batch verification is only sound for the cofactored equation (the
// random linear combination multiplies the whole sum by 8), so the
// scalar path uses the same criterion — scalar and batch verdicts can
// then never disagree on any input (PARITY.md documents the contrast
// with cofactorless verifiers).
static bool ed_verify_decoded(const GeP3& A, const GeP3& R, const Sc25& s,
                              const Sc25& h) {
  uint8_t sbytes[32];
  sc_tobytes(sbytes, s);
  GeP3 sB, hA, q, t;
  ge_scalarmult_base(sB, sbytes);
  ge_scalarmult(hA, A, h);
  ge_neg(t, hA);
  ge_add(q, sB, t);
  ge_neg(t, R);
  ge_add(q, q, t);
  ge_dbl(q, q);
  ge_dbl(q, q);
  ge_dbl(q, q);
  return ge_is_identity(q);
}

static int ed_verify_one(const uint8_t pub[32], const uint8_t* msg, size_t len,
                         const uint8_t sig[64]) {
  build_ed_table();
  Sc25 s = sc_frombytes32(sig + 32);
  if (sc_gte_l(s)) return 0;  // non-canonical s: malleable, rejected
  GeP3 A, R;
  if (!ge_frombytes(A, pub)) return 0;
  if (!ge_frombytes(R, sig)) return 0;
  Sha512 hh;
  hh.update(sig, 32);
  hh.update(pub, 32);
  hh.update(msg, len);
  uint8_t hdigest[64];
  hh.final(hdigest);
  Sc25 h = sc_frombytes64(hdigest);
  return ed_verify_decoded(A, R, s, h) ? 1 : 0;
}

// 128-bit batch randomizers from a per-thread splitmix64 stream seeded
// by the OS entropy source. Fresh per batch: an attacker cannot grind a
// randomizer they never observe, and 2^-128 bounds the chance a forged
// batch survives the linear combination.
static thread_local uint64_t ed_rng_state = 0;

static uint64_t ed_rand64() {
  if (ed_rng_state == 0) {
    std::random_device rd;
    ed_rng_state = ((uint64_t)rd() << 32) ^ rd() ^ 0x9E3779B97F4A7C15ULL;
  }
  uint64_t z = (ed_rng_state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// Randomized-linear-combination batch verification over one chunk:
// checks 8·Σ zᵢ(sᵢB - hᵢAᵢ - Rᵢ) == O via one Straus multi-scalar
// multiply — per signature, ~21 ladder additions for the 128-bit zᵢ on
// Rᵢ instead of a full double-scalar multiply. Identities repeat
// heavily in consensus traffic, so Aᵢ terms are grouped per unique
// pubkey: one decompression, one wNAF table, and one 253-bit scalar
// (Σ zᵢhᵢ mod L) per SIGNER rather than per signature. On failure the
// chunk falls back to per-item scalar verification for exact verdicts
// (the RLC cannot false-reject: an all-valid chunk always sums to the
// identity after the cofactor multiply).
static void ed_verify_batch_range(const uint8_t* pubs, const uint8_t* msgs,
                                  const uint64_t* offsets, const uint8_t* sigs,
                                  int64_t lo, int64_t hi, uint8_t* results) {
  build_ed_table();
  const int64_t n = hi - lo;
  if (n <= 0) return;
  if (n == 1) {
    results[lo] = (uint8_t)ed_verify_one(
        pubs + 32 * lo, msgs + offsets[lo], offsets[lo + 1] - offsets[lo],
        sigs + 64 * lo);
    return;
  }
  struct Item {
    GeP3 R;
    Sc25 s, h, z;
    int a_slot;
    bool s_ok, ok;
  };
  std::vector<Item> items(n);
  // Unique pubkeys in this chunk -> decoded point + accumulated scalar.
  struct ASlot {
    GeP3 A;
    Sc25 coeff;
    bool decoded, used;
  };
  std::vector<ASlot> aslots;
  std::vector<const uint8_t*> akeys;
  // Pass 1: scalar-range check, identity grouping (linear scan is fine
  // at chunk scale — the signer population per chunk is small by
  // construction), per-item hash and randomizer. Point decompression is
  // deferred so it can run BATCHED below.
  for (int64_t j = 0; j < n; j++) {
    int64_t i = lo + j;
    Item& it = items[j];
    it.ok = false;
    const uint8_t* pub = pubs + 32 * i;
    const uint8_t* sig = sigs + 64 * i;
    it.s = sc_frombytes32(sig + 32);
    it.s_ok = !sc_gte_l(it.s);
    if (!it.s_ok) {
      results[i] = 0;
      continue;
    }
    int slot = -1;
    for (size_t k = 0; k < akeys.size(); k++)
      if (memcmp(akeys[k], pub, 32) == 0) {
        slot = (int)k;
        break;
      }
    if (slot < 0) {
      ASlot as;
      as.decoded = false;
      as.used = false;
      as.coeff = Sc25{{0, 0, 0, 0}};
      slot = (int)aslots.size();
      aslots.push_back(as);
      akeys.push_back(pub);
    }
    it.a_slot = slot;
    Sha512 hh;
    hh.update(sig, 32);
    hh.update(pub, 32);
    hh.update(msgs + offsets[i], offsets[i + 1] - offsets[i]);
    uint8_t hdigest[64];
    hh.final(hdigest);
    it.h = sc_frombytes64(hdigest);
    it.z = Sc25{{ed_rand64(), ed_rand64(), 0, 0}};
  }
  // Batched decompression: all unique A's, then all R's.
  {
    std::vector<GeP3> apts(aslots.size());
    std::vector<uint8_t> aok(aslots.size());
    if (!aslots.empty()) {
      ge_frombytes_multi(apts.data(), aok.data(), akeys.data(),
                         (int)aslots.size());
      for (size_t k = 0; k < aslots.size(); k++) {
        aslots[k].A = apts[k];
        aslots[k].decoded = aok[k] != 0;
      }
    }
    std::vector<const uint8_t*> rencs;
    std::vector<int64_t> rrows;
    rencs.reserve(n);
    rrows.reserve(n);
    for (int64_t j = 0; j < n; j++)
      if (items[j].s_ok && aslots[items[j].a_slot].decoded) {
        rencs.push_back(sigs + 64 * (lo + j));
        rrows.push_back(j);
      }
    std::vector<GeP3> rpts(rencs.size());
    std::vector<uint8_t> rok(rencs.size());
    if (!rencs.empty())
      ge_frombytes_multi(rpts.data(), rok.data(), rencs.data(),
                         (int)rencs.size());
    for (size_t k = 0; k < rrows.size(); k++)
      if (rok[k]) {
        items[rrows[k]].R = rpts[k];
        items[rrows[k]].ok = true;
      }
  }
  // Pass 2: accumulate the linear combination over decodable items.
  Sc25 s_total = {{0, 0, 0, 0}};
  for (int64_t j = 0; j < n; j++) {
    int64_t i = lo + j;
    Item& it = items[j];
    if (!it.ok) {
      results[i] = 0;
      continue;
    }
    ASlot& as = aslots[it.a_slot];
    as.coeff = sc_add(as.coeff, sc_mulmod(it.z, it.h));
    as.used = true;
    s_total = sc_add(s_total, sc_mulmod(it.z, it.s));
    results[i] = 1;  // provisional; confirmed by the combination below
  }
  // Straus MSM: acc = Σ zᵢ·(-Rᵢ) + Σ coeffⱼ·(-Aⱼ), then + s_total·B.
  struct Strand {
    int8_t naf[260];
    int len;
    int lane;  // which accumulator this strand lands on
  };
  // Four independent accumulator lanes: the joint ladder is one long
  // dependency chain per accumulator (each dbl/add waits on the last),
  // so splitting strands across lanes lets the CPU overlap the field
  // multiplies of four chains (~1.4x on the MSM). The short 128-bit zᵢ
  // strands share lanes 0-2 — their lanes only start doubling halfway
  // up the window range — and the full-width per-signer coefficient
  // strands take lane 3.
  std::vector<Strand> strands;
  std::vector<GeP3> neg_pts;
  strands.reserve(items.size() + aslots.size());
  neg_pts.reserve(items.size() + aslots.size());
  int max_len = 0;
  int r_count = 0;
  for (int64_t j = 0; j < n; j++) {
    if (!items[j].ok) continue;
    Strand st;
    U256 u = {{items[j].z.v[0], items[j].z.v[1], 0, 0}};
    st.len = build_wnaf5(u, st.naf);
    st.lane = r_count++ % 3;
    if (st.len > max_len) max_len = st.len;
    strands.push_back(st);
    GeP3 neg;
    ge_neg(neg, items[j].R);
    neg_pts.push_back(neg);
  }
  for (auto& as : aslots) {
    if (!as.used || sc_iszero(as.coeff)) continue;
    Strand st;
    U256 u = {{as.coeff.v[0], as.coeff.v[1], as.coeff.v[2], as.coeff.v[3]}};
    st.len = build_wnaf5(u, st.naf);
    st.lane = 3;
    if (st.len > max_len) max_len = st.len;
    strands.push_back(st);
    GeP3 neg;
    ge_neg(neg, as.A);
    neg_pts.push_back(neg);
  }
  // Per-strand odd-multiple tables, built interleaved (ILP).
  std::vector<GeWnafTable> tbls(strands.size());
  if (!strands.empty())
    ge_wnaf_table_multi(tbls.data(), neg_pts.data(), (int)strands.size());
  GeP3 accs[4];
  bool active[4] = {false, false, false, false};
  for (auto& a : accs) ge_identity(a);
  for (int i = max_len - 1; i >= 0; i--) {
    for (int k = 0; k < 4; k++)
      if (active[k]) ge_dbl(accs[k], accs[k]);
    for (size_t si = 0; si < strands.size(); si++) {
      const Strand& st = strands[si];
      if (i < st.len && st.naf[i]) {
        ge_wnaf_add_digit(accs[st.lane], tbls[si], st.naf[i]);
        active[st.lane] = true;
      }
    }
  }
  GeP3 acc, t01, t23;
  ge_add(t01, accs[0], accs[1]);
  ge_add(t23, accs[2], accs[3]);
  ge_add(acc, t01, t23);
  uint8_t stb[32];
  sc_tobytes(stb, s_total);
  GeP3 sB;
  ge_scalarmult_base(sB, stb);
  ge_add(acc, acc, sB);
  ge_dbl(acc, acc);
  ge_dbl(acc, acc);
  ge_dbl(acc, acc);
  if (ge_is_identity(acc)) return;  // malformed items are already 0
  // Combination failed: at least one bad signature — resolve exactly.
  for (int64_t j = 0; j < n; j++) {
    int64_t i = lo + j;
    if (!items[j].ok) continue;  // already 0
    results[i] = (uint8_t)ed_verify_one(
        pubs + 32 * i, msgs + offsets[i], offsets[i + 1] - offsets[i],
        sigs + 64 * i);
  }
}

// ───────────────────────────── C ABI ───────────────────────────────

extern "C" {

void hg_sha256(const uint8_t* data, uint64_t len, uint8_t* out) {
  sha256(data, len, out);
}

void hg_keccak256(const uint8_t* data, uint64_t len, uint8_t* out) {
  keccak256(data, len, out);
}

// Batched hashing: items are concatenated in `data`, item i spans
// [offsets[i], offsets[i+1]); digests land at out + 32*i.
static void hash_batch(const uint8_t* data, const uint64_t* offsets,
                       int64_t count, uint8_t* out, int n_threads,
                       void (*fn)(const uint8_t*, size_t, uint8_t*)) {
  run_parallel(count, n_threads, 64, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++)
      fn(data + offsets[i], offsets[i + 1] - offsets[i], out + 32 * i);
  });
}

void hg_sha256_batch(const uint8_t* data, const uint64_t* offsets,
                     int64_t count, uint8_t* out, int n_threads) {
  hash_batch(data, offsets, count, out, n_threads, sha256);
}

void hg_keccak256_batch(const uint8_t* data, const uint64_t* offsets,
                        int64_t count, uint8_t* out, int n_threads) {
  hash_batch(data, offsets, count, out, n_threads, keccak256);
}

// Worker body shared by the sync and async EIP-191 batch entry points:
// verify items [lo, hi) into results.
static void eth_verify_range(const uint8_t* identities, const uint8_t* payloads,
                             const uint64_t* offsets, const uint8_t* sigs,
                             int64_t lo, int64_t hi, uint8_t* results) {
  {
    // Chunked so the three Montgomery batch inversions (r⁻¹ mod n before
    // the scalar multiplies, the per-item wNAF-table z's for the affine
    // GLV ladder, and q's z for the final affine conversion) each amortise
    // one real inversion over up to 64 signatures.
    const int64_t CHUNK = 64;
    VerifyItem items[CHUNK];
    U256 rinvs[CHUNK];
    U256 u1s[CHUNK];
    Point qs[CHUNK];
    U256 zs[CHUNK];
    std::vector<GlvPrep> preps(CHUNK);
    std::vector<U256> ztbl(CHUNK * 8);
    const U256 zero = {{0, 0, 0, 0}};
    for (int64_t base = lo; base < hi; base += CHUNK) {
      int64_t m = std::min(CHUNK, hi - base);
      for (int64_t j = 0; j < m; j++) {
        int64_t i = base + j;
        results[i] = eth_parse_phase(payloads + offsets[i],
                                     offsets[i + 1] - offsets[i],
                                     sigs + 65 * i, items[j]);
        rinvs[j] = results[i] == 1 ? items[j].r : zero;
      }
      fn_batch_inv(rinvs, (int)m);
      for (int64_t j = 0; j < m; j++) {
        int64_t i = base + j;
        zs[j] = zero;
        preps[j].glv = false;
        for (int t = 0; t < 8; t++) ztbl[8 * j + t] = zero;
        if (results[i] != 1) continue;
        const U256& z = items[j].z;
        U256 u1 = u256_is_zero(z) ? z
                                  : mod_mul(mod_sub(FN.m, z, FN), rinvs[j], FN);
        U256 u2 = mod_mul(items[j].s, rinvs[j], FN);
        u1s[j] = u1;
        if (glv_ok && !u256_is_zero(u2)) {
          preps[j].glv = true;
          glv_prep_phase(items[j].rx, items[j].ry, u2, preps[j],
                         &ztbl[8 * j]);
        } else if (!recover_combine(items[j].rx, items[j].ry, items[j].s,
                                    items[j].z, rinvs[j], qs[j])) {
          results[i] = 254;
        } else {
          zs[j] = qs[j].z;
        }
      }
      fp_batch_inv(ztbl.data(), (int)(8 * m));
      for (int64_t j = 0; j < m; j++) {
        int64_t i = base + j;
        if (results[i] != 1 || !preps[j].glv) continue;
        Point sr = glv_ladder_affine(preps[j], &ztbl[8 * j]);
        qs[j] = pt_add(sr, g_mul(u1s[j]));
        if (pt_is_inf(qs[j]))
          results[i] = 254;
        else
          zs[j] = qs[j].z;
      }
      fp_batch_inv(zs, (int)m);
      for (int64_t j = 0; j < m; j++) {
        int64_t i = base + j;
        if (results[i] != 1) continue;
        U256 zi2 = fp_sqr(zs[j]);
        U256 qx = fp_mul(qs[j].x, zi2);
        U256 qy = fp_mul(qs[j].y, fp_mul(zi2, zs[j]));
        uint8_t addr[20];
        address_from_pub(qx, qy, addr);
        results[i] = memcmp(addr, identities + 20 * i, 20) == 0 ? 1 : 0;
      }
    }
  }
}

// EIP-191 verify. identities: 20*i, payload spans offsets, sigs: 65*i.
// results[i]: 1 valid, 0 address mismatch, 255 malformed recovery byte,
// 254 recovery failed (the latter two map to scheme errors).
void hg_eth_verify_batch(const uint8_t* identities, const uint8_t* payloads,
                         const uint64_t* offsets, const uint8_t* sigs,
                         int64_t count, uint8_t* results, int n_threads) {
  build_g_table();
  run_parallel(count, n_threads, 4, [&](int64_t lo, int64_t hi) {
    eth_verify_range(identities, payloads, offsets, sigs, lo, hi, results);
  });
}

int hg_eth_verify(const uint8_t* identity, const uint8_t* payload,
                  uint64_t len, const uint8_t* sig) {
  build_g_table();
  return eth_verify_one(identity, payload, len, sig);
}

// Sign payload (EIP-191) with a 32-byte key; writes r||s||v (65 bytes).
// Returns 0 on success.
int hg_eth_sign(const uint8_t* priv, const uint8_t* payload, uint64_t len,
                uint8_t* sig_out) {
  build_g_table();
  uint8_t digest[32];
  eip191_hash(payload, len, digest);
  U256 r, s;
  int recid;
  if (!ecdsa_sign(digest, priv, r, s, recid)) return 1;
  u256_to_be(r, sig_out);
  u256_to_be(s, sig_out + 32);
  sig_out[64] = uint8_t(27 + (recid & 1));
  return 0;
}

// Derive the Ethereum address for a private key. Returns 0 on success.
int hg_eth_address(const uint8_t* priv, uint8_t* addr_out) {
  build_g_table();
  U256 d = u256_from_be(priv);
  if (u256_is_zero(d) || u256_cmp(d, FN.m) >= 0) return 1;
  U256 qx, qy;
  if (!pt_to_affine(g_mul(d), qx, qy)) return 1;
  address_from_pub(qx, qy, addr_out);
  return 0;
}

// Fused open-addressing probe for the engine's proposal-id -> slot hash
// (mirror of hashgraph_tpu.engine.engine._PidLookup: Fibonacci bucketing
// h = (uint64(key) * GOLDEN) >> shift over a power-of-two table with -1
// as the empty sentinel, linear probing). The numpy probe loop pays ~12
// full-array passes per probe iteration; this is one fused pass per
// query at memory bandwidth. Queries equal to -1 (the sentinel) resolve
// to not-found, as in the Python path. Table load factor <= 0.5
// guarantees empty buckets, so probing always terminates.
void hg_pid_lookup(const int64_t* table_keys, const int64_t* table_vals,
                   int64_t size, int shift, const int64_t* queries,
                   int64_t count, uint8_t* found, int64_t* out,
                   int n_threads) {
  const uint64_t GOLDEN = 0x9E3779B97F4A7C15ull;
  const uint64_t mask = uint64_t(size - 1);
  run_parallel(count, n_threads, 4096, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      const int64_t q = queries[i];
      if (q == -1) {
        found[i] = 0;
        out[i] = 0;
        continue;
      }
      uint64_t h = (uint64_t(q) * GOLDEN) >> shift;
      for (;;) {
        const int64_t k = table_keys[h & mask];
        if (k == q) {
          found[i] = 1;
          out[i] = table_vals[h & mask];
          break;
        }
        if (k == -1) {
          found[i] = 0;
          out[i] = 0;
          break;
        }
        h++;
      }
    }
  });
}

// Fused voter-gid liveness check (mirror of ProposalPool.gids_live):
// gid = generation << 32 | index; live iff index in range, the live flag
// is set, and the generation matches. One pass instead of numpy's six
// (range mask, index split, generation split, two gathers, compare).
void hg_gids_live(const int64_t* gids, int64_t count, const uint8_t* live,
                  const int64_t* gen, int64_t n_owners, uint8_t* out,
                  int n_threads) {
  run_parallel(count, n_threads, 8192, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      const int64_t g = gids[i];
      const int64_t idx = g & 0xFFFFFFFFll;
      out[i] = uint8_t(g >= 0 && idx < n_owners && live[idx] &&
                       gen[idx] == (g >> 32));
    }
  });
}

// ── Persistent verify pool ─────────────────────────────────────────

// (Re)size the worker pool; n <= 0 restores the hardware default.
// Returns the resulting thread count. Safe between batches.
int hg_pool_configure(int n_threads) {
  return WorkerPool::instance().configure(n_threads);
}

int hg_pool_size() { return WorkerPool::instance().size(); }

// Tasks queued + running — the /metrics verify-pool queue-depth gauge.
int64_t hg_pool_queue_depth() { return WorkerPool::instance().depth(); }

// Block until an async job (from a *_submit call) completes. Returns 0
// on success, 1 for an unknown/already-collected handle. Results were
// written into the caller's buffers by the workers; the caller must
// keep every buffer passed to submit alive until this returns.
int hg_pool_wait(int64_t job) {
  return WorkerPool::instance().wait_handle(job);
}

// Async hg_eth_verify_batch: returns a job handle immediately; the
// worker pool fills `results` in the background (GIL-free), so Python
// can overlap device work with host ECDSA. Collect via hg_pool_wait.
int64_t hg_eth_verify_batch_submit(const uint8_t* identities,
                                   const uint8_t* payloads,
                                   const uint64_t* offsets,
                                   const uint8_t* sigs, int64_t count,
                                   uint8_t* results) {
  build_g_table();
  return submit_parallel(count, 64, [=](int64_t lo, int64_t hi) {
    eth_verify_range(identities, payloads, offsets, sigs, lo, hi, results);
  });
}

// ── Ed25519 ────────────────────────────────────────────────────────

// Public key for a 32-byte seed (RFC 8032 §5.1.5). Returns 0.
int hg_ed25519_public(const uint8_t* seed, uint8_t* pub_out) {
  build_ed_table();
  uint8_t a[32], prefix[32];
  ed_expand_key(seed, a, prefix, pub_out);
  return 0;
}

// Sign payload with a 32-byte seed; writes R || S (64 bytes). Returns 0.
int hg_ed25519_sign(const uint8_t* seed, const uint8_t* payload, uint64_t len,
                    uint8_t* sig_out) {
  build_ed_table();
  ed_sign(seed, payload, len, sig_out);
  return 0;
}

// Verify one signature (cofactored; see ed_verify_decoded). Returns 1
// valid, 0 invalid (bad point encodings and non-canonical s included).
int hg_ed25519_verify(const uint8_t* pub, const uint8_t* payload, uint64_t len,
                      const uint8_t* sig) {
  return ed_verify_one(pub, payload, len, sig);
}

// Batched Ed25519 verification: pubs at 32·i, payload spans offsets,
// sigs at 64·i. results[i]: 1 valid, 0 invalid. Chunks of <= 64 run the
// randomized-linear-combination batch equation across the worker pool.
void hg_ed25519_verify_batch(const uint8_t* pubs, const uint8_t* payloads,
                             const uint64_t* offsets, const uint8_t* sigs,
                             int64_t count, uint8_t* results, int n_threads) {
  build_ed_table();
  run_parallel(count, n_threads, 4, [&](int64_t lo, int64_t hi) {
    for (int64_t base = lo; base < hi; base += 256)
      ed_verify_batch_range(pubs, payloads, offsets, sigs, base,
                            std::min<int64_t>(hi, base + 256), results);
  });
}

// Async hg_ed25519_verify_batch (collect via hg_pool_wait).
int64_t hg_ed25519_verify_batch_submit(const uint8_t* pubs,
                                       const uint8_t* payloads,
                                       const uint64_t* offsets,
                                       const uint8_t* sigs, int64_t count,
                                       uint8_t* results) {
  build_ed_table();
  return submit_parallel(count, 256, [=](int64_t lo, int64_t hi) {
    for (int64_t base = lo; base < hi; base += 256)
      ed_verify_batch_range(pubs, payloads, offsets, sigs, base,
                            std::min<int64_t>(hi, base + 256), results);
  });
}

// ── Columnar wire-vote parsing (zero-copy bridge ingest) ──────────────
//
// Strict-canonical protobuf Vote parse: exactly the byte form the
// package's own encoder (and the reference's prost codec) produces —
// fields 20..28 in ascending order, each at most once, minimal varints,
// zero/empty fields omitted, bool encoded as 1, no unknown fields, no
// trailing bytes. Rows that match yield flag 1 and a column row; any
// deviation (malformed OR merely non-canonical) yields flag 0 and the
// caller falls back to the Python object decoder for the whole frame,
// which is what makes fast-path and fallback statuses identical by
// construction. The parse never touches the GIL.
//
// Column layout (int64[count][16]):
//   0 vote_id   1 proposal_id   2 timestamp (u64 bits)   3 value
//   4 owner_off  5 owner_len   6 parent_off  7 parent_len
//   8 recv_off   9 recv_len   10 hash_off   11 hash_len
//  12 sig_off   13 sig_len    14 sign_len (signing-payload prefix bytes)
//  15 reserved
// Offsets are absolute into `data`; absent fields report off=row start,
// len=0; sign_len is the whole row when the signature field is absent.

static constexpr int HG_VOTE_COLS = 16;

// Minimal-encoding varint: returns consumed bytes (0 = malformed or
// non-minimal or u64 overflow — all "not canonical" to the caller).
static int read_varint_canonical(const uint8_t* p, int64_t len, int64_t pos,
                                 uint64_t* out) {
  uint64_t v = 0;
  int shift = 0, i = 0;
  while (true) {
    if (pos + i >= len || i >= 10) return 0;
    uint8_t b = p[pos + i];
    if (shift == 63 && (b & 0x7E)) return 0;  // overflows u64
    v |= (uint64_t)(b & 0x7F) << shift;
    i++;
    if (!(b & 0x80)) {
      if (i > 1 && b == 0) return 0;  // non-minimal (trailing zero byte)
      *out = v;
      return i;
    }
    shift += 7;
  }
}

static int parse_vote_canonical(const uint8_t* p, int64_t len, int64_t base,
                                int64_t* col) {
  for (int k = 0; k < HG_VOTE_COLS; k++) col[k] = 0;
  col[4] = col[6] = col[8] = col[10] = col[12] = base;
  col[14] = len;
  int64_t pos = 0;
  int last_field = 0;
  while (pos < len) {
    int64_t tag_start = pos;
    uint64_t key;
    int n = read_varint_canonical(p, len, pos, &key);
    if (n <= 0) return 0;
    pos += n;
    int field = (int)(key >> 3), wt = (int)(key & 7);
    if (field <= last_field || field < 20 || field > 28) return 0;
    last_field = field;
    if (field == 20 || field == 22 || field == 23 || field == 24) {
      if (wt != 0) return 0;
      uint64_t v;
      int m = read_varint_canonical(p, len, pos, &v);
      if (m <= 0) return 0;
      pos += m;
      if (v == 0) return 0;  // canonical encoders omit zero fields
      if ((field == 20 || field == 22) && v > 0xFFFFFFFFull) return 0;
      if (field == 24 && v != 1) return 0;
      if (field == 20) col[0] = (int64_t)v;
      else if (field == 22) col[1] = (int64_t)v;
      else if (field == 23) col[2] = (int64_t)v;
      else col[3] = 1;
    } else {
      if (wt != 2) return 0;
      uint64_t l;
      int m = read_varint_canonical(p, len, pos, &l);
      if (m <= 0) return 0;
      pos += m;
      if (l == 0 || l > (uint64_t)(len - pos)) return 0;
      int idx = field == 21 ? 4 : field == 25 ? 6 : field == 26 ? 8
                : field == 27 ? 10 : 12;
      col[idx] = base + pos;
      col[idx + 1] = (int64_t)l;
      if (field == 28) col[14] = tag_start;
      pos += (int64_t)l;
    }
  }
  return pos == len ? 1 : 0;
}

void hg_parse_vote_columns(const uint8_t* data, const uint64_t* offsets,
                           int64_t count, int64_t* cols, uint8_t* flags,
                           int n_threads) {
  run_parallel(count, n_threads, 256, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      int64_t base = (int64_t)offsets[i];
      flags[i] = (uint8_t)parse_vote_canonical(
          data + base, (int64_t)offsets[i + 1] - base, base,
          cols + HG_VOTE_COLS * i);
    }
  });
}

// Batched compute_vote_hash over parsed columns: SHA-256 of
// u32le(vote_id) | owner | u32le(pid) | u64le(ts) | value | parent |
// received — the engine's protocol.compute_vote_hash byte order.
void hg_vote_hash_columns(const uint8_t* data, const int64_t* cols,
                          int64_t count, uint8_t* out, int n_threads) {
  run_parallel(count, n_threads, 64, [&](int64_t lo, int64_t hi) {
    std::vector<uint8_t> buf;
    for (int64_t i = lo; i < hi; i++) {
      const int64_t* c = cols + HG_VOTE_COLS * i;
      buf.clear();
      for (int k = 0; k < 4; k++)
        buf.push_back((uint8_t)((uint64_t)c[0] >> (8 * k)));
      buf.insert(buf.end(), data + c[4], data + c[4] + c[5]);
      for (int k = 0; k < 4; k++)
        buf.push_back((uint8_t)((uint64_t)c[1] >> (8 * k)));
      for (int k = 0; k < 8; k++)
        buf.push_back((uint8_t)((uint64_t)c[2] >> (8 * k)));
      buf.push_back(c[3] ? 1 : 0);
      buf.insert(buf.end(), data + c[6], data + c[6] + c[7]);
      buf.insert(buf.end(), data + c[8], data + c[8] + c[9]);
      sha256(buf.data(), buf.size(), out + 32 * i);
    }
  });
}

int hg_version() { return 4; }

}  // extern "C"
