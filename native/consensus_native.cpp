// Native host runtime for hashgraph_tpu: batched hashing + secp256k1 ECDSA.
//
// The TPU owns tallies and decisions; the host owns crypto (the reference
// delegates it to alloy's signer stack, src/signing/ethereum.rs:58-97 — here
// it is a from-scratch C++ implementation, no third-party code). Exposed as
// a C ABI consumed via ctypes (hashgraph_tpu/native.py); every batch entry
// point releases the GIL by construction and fans out over std::thread.
//
// Implemented primitives:
//   - SHA-256 (FIPS 180-4) + HMAC-SHA256 (RFC 6979 nonces)
//   - Keccak-256 (pre-NIST padding, Ethereum flavor)
//   - secp256k1 field/scalar arithmetic (4x64 limbs, 2^256-c folding),
//     Jacobian point ops, fixed-base window table for G
//   - ECDSA sign (RFC 6979, low-s) and public-key recovery
//   - EIP-191 verify: prefix-hash -> recover -> keccak address -> compare
//
// Build: native/build.sh (g++ -O3 -shared). The Python wrapper falls back to
// the pure-Python implementations when the shared object is absent.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

// ───────────────────────────── SHA-256 ─────────────────────────────

static const uint32_t SHA_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr32(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static void sha256_compress(uint32_t h[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(block[4 * i]) << 24) | (uint32_t(block[4 * i + 1]) << 16) |
           (uint32_t(block[4 * i + 2]) << 8) | uint32_t(block[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
           g = h[6], hh = h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = hh + S1 + ch + SHA_K[i] + w[i];
    uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

static void sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  size_t off = 0;
  for (; off + 64 <= len; off += 64) sha256_compress(h, data + off);
  uint8_t block[128] = {0};
  size_t tail = len - off;
  memcpy(block, data + off, tail);
  block[tail] = 0x80;
  size_t blocks = (tail + 9 <= 64) ? 1 : 2;
  uint64_t bits = uint64_t(len) * 8;
  for (int i = 0; i < 8; i++)
    block[blocks * 64 - 1 - i] = uint8_t(bits >> (8 * i));
  for (size_t b = 0; b < blocks; b++) sha256_compress(h, block + 64 * b);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(h[i] >> 24);
    out[4 * i + 1] = uint8_t(h[i] >> 16);
    out[4 * i + 2] = uint8_t(h[i] >> 8);
    out[4 * i + 3] = uint8_t(h[i]);
  }
}

static void hmac_sha256(const uint8_t* key, size_t keylen, const uint8_t* m1,
                        size_t l1, const uint8_t* m2, size_t l2,
                        const uint8_t* m3, size_t l3, const uint8_t* m4,
                        size_t l4, uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (keylen > 64) {
    sha256(key, keylen, k);
  } else {
    memcpy(k, key, keylen);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  // inner = sha256(ipad || m1 || m2 || m3 || m4)
  std::vector<uint8_t> buf;
  buf.reserve(64 + l1 + l2 + l3 + l4);
  buf.insert(buf.end(), ipad, ipad + 64);
  buf.insert(buf.end(), m1, m1 + l1);
  buf.insert(buf.end(), m2, m2 + l2);
  buf.insert(buf.end(), m3, m3 + l3);
  buf.insert(buf.end(), m4, m4 + l4);
  uint8_t inner[32];
  sha256(buf.data(), buf.size(), inner);
  uint8_t outer[96];
  memcpy(outer, opad, 64);
  memcpy(outer + 64, inner, 32);
  sha256(outer, 96, out);
}

// ──────────────────────────── Keccak-256 ───────────────────────────

static const uint64_t KECCAK_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static const int KECCAK_ROT[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55,
                                   20, 3,  10, 43, 25, 39, 41, 45, 15,
                                   21, 8,  18, 2,  61, 56, 14};

static inline uint64_t rotl64(uint64_t x, int n) {
  return n == 0 ? x : (x << n) | (x >> (64 - n));
}

static void keccak_f1600(uint64_t A[25]) {
  for (int round = 0; round < 24; round++) {
    uint64_t C[5], D[5], B[25];
    for (int x = 0; x < 5; x++)
      C[x] = A[x] ^ A[x + 5] ^ A[x + 10] ^ A[x + 15] ^ A[x + 20];
    for (int x = 0; x < 5; x++)
      D[x] = C[(x + 4) % 5] ^ rotl64(C[(x + 1) % 5], 1);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 25; y += 5) A[x + y] ^= D[x];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        B[y + 5 * ((2 * x + 3 * y) % 5)] = rotl64(A[x + 5 * y], KECCAK_ROT[x + 5 * y]);
    for (int y = 0; y < 25; y += 5)
      for (int x = 0; x < 5; x++)
        A[x + y] = B[x + y] ^ ((~B[(x + 1) % 5 + y]) & B[(x + 2) % 5 + y]);
    A[0] ^= KECCAK_RC[round];
  }
}

static void keccak256(const uint8_t* data, size_t len, uint8_t out[32]) {
  const size_t rate = 136;
  uint64_t A[25] = {0};
  size_t off = 0;
  while (len - off >= rate) {
    for (size_t i = 0; i < rate / 8; i++) {
      uint64_t lane;
      memcpy(&lane, data + off + 8 * i, 8);
      A[i] ^= lane;  // little-endian host assumed (x86/arm64)
    }
    keccak_f1600(A);
    off += rate;
  }
  uint8_t block[136] = {0};
  memcpy(block, data + off, len - off);
  block[len - off] ^= 0x01;
  block[rate - 1] ^= 0x80;
  for (size_t i = 0; i < rate / 8; i++) {
    uint64_t lane;
    memcpy(&lane, block + 8 * i, 8);
    A[i] ^= lane;
  }
  keccak_f1600(A);
  memcpy(out, A, 32);
}

// ───────────────────── 256-bit modular arithmetic ──────────────────
// Little-endian 4x64 limbs. Moduli are 2^256 - c with small-ish c, so
// reduction is repeated folding: hi * c + lo.

struct U256 {
  uint64_t v[4];
};

static inline bool u256_is_zero(const U256& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static inline int u256_cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; i--) {
    if (a.v[i] < b.v[i]) return -1;
    if (a.v[i] > b.v[i]) return 1;
  }
  return 0;
}

static inline uint64_t u256_add(U256& r, const U256& a, const U256& b) {
  unsigned __int128 carry = 0;
  for (int i = 0; i < 4; i++) {
    carry += (unsigned __int128)a.v[i] + b.v[i];
    r.v[i] = (uint64_t)carry;
    carry >>= 64;
  }
  return (uint64_t)carry;
}

static inline uint64_t u256_sub(U256& r, const U256& a, const U256& b) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 4; i++) {
    unsigned __int128 d = (unsigned __int128)a.v[i] - b.v[i] - borrow;
    r.v[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
  return (uint64_t)borrow;
}

// out[0..7] = a * b
static void u256_mul_full(const U256& a, const U256& b, uint64_t out[8]) {
  memset(out, 0, 8 * sizeof(uint64_t));
  for (int i = 0; i < 4; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 4; j++) {
      carry += (unsigned __int128)a.v[i] * b.v[j] + out[i + j];
      out[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    out[i + 4] = (uint64_t)carry;
  }
}

struct Modulus {
  U256 m;  // 2^256 - c
  U256 c;  // the folding constant (fits in <= 3 limbs)
};

// Reduce an 8-limb value modulo m = 2^256 - c by folding hi*c into lo.
static U256 mod_reduce512(const uint64_t t_in[8], const Modulus& mod) {
  uint64_t t[12];
  memcpy(t, t_in, 8 * sizeof(uint64_t));
  memset(t + 8, 0, 4 * sizeof(uint64_t));
  // Fold until limbs above 3 are clear (terminates: c < 2^130).
  for (int iter = 0; iter < 4; iter++) {
    bool high = false;
    for (int i = 4; i < 12; i++) high |= (t[i] != 0);
    if (!high) break;
    uint64_t hi[8];
    memcpy(hi, t + 4, 8 * sizeof(uint64_t));
    memset(t + 4, 0, 8 * sizeof(uint64_t));
    // t += hi * c   (hi up to 8 limbs but after first fold it is small)
    for (int i = 0; i < 8; i++) {
      if (hi[i] == 0) continue;
      unsigned __int128 carry = 0;
      for (int j = 0; j < 3; j++) {
        if (i + j >= 12) break;
        carry += (unsigned __int128)hi[i] * mod.c.v[j] + t[i + j];
        t[i + j] = (uint64_t)carry;
        carry >>= 64;
      }
      for (int k = i + 3; carry && k < 12; k++) {
        carry += t[k];
        t[k] = (uint64_t)carry;
        carry >>= 64;
      }
    }
  }
  U256 r = {{t[0], t[1], t[2], t[3]}};
  while (u256_cmp(r, mod.m) >= 0) u256_sub(r, r, mod.m);
  return r;
}

static U256 mod_mul(const U256& a, const U256& b, const Modulus& mod) {
  uint64_t t[8];
  u256_mul_full(a, b, t);
  return mod_reduce512(t, mod);
}

static U256 mod_add(const U256& a, const U256& b, const Modulus& mod) {
  U256 r;
  uint64_t carry = u256_add(r, a, b);
  if (carry) {
    // r + 2^256 ≡ r + c (mod m)
    U256 r2;
    uint64_t c2 = u256_add(r2, r, mod.c);
    r = r2;
    if (c2) u256_add(r, r, mod.c);  // cannot carry twice for our c
  }
  while (u256_cmp(r, mod.m) >= 0) u256_sub(r, r, mod.m);
  return r;
}

static U256 mod_sub(const U256& a, const U256& b, const Modulus& mod) {
  U256 r;
  if (u256_sub(r, a, b)) u256_add(r, r, mod.m);
  return r;
}

// 4-bit windowed exponentiation: ~256 squarings + ~64 multiplies. The
// exponents used here (p-2, n-2, (p+1)/4) are dense with set bits, so the
// naive square-and-multiply ladder costs ~250 multiplies on top of the
// squarings — the window cuts that 4x.
static U256 mod_pow(const U256& base, const U256& exp, const Modulus& mod) {
  U256 tbl[16];
  tbl[0] = {{1, 0, 0, 0}};
  tbl[1] = base;
  for (int i = 2; i < 16; i++) tbl[i] = mod_mul(tbl[i - 1], base, mod);
  U256 result = {{1, 0, 0, 0}};
  bool started = false;
  for (int w = 63; w >= 0; w--) {
    int digit = (exp.v[w / 16] >> (4 * (w % 16))) & 0xF;
    if (started) {
      result = mod_mul(result, result, mod);
      result = mod_mul(result, result, mod);
      result = mod_mul(result, result, mod);
      result = mod_mul(result, result, mod);
    }
    if (digit) {
      result = started ? mod_mul(result, tbl[digit], mod) : tbl[digit];
      started = true;
    }
  }
  return started ? result : tbl[0];
}

static U256 u256_from_be(const uint8_t b[32]) {
  U256 r;
  for (int i = 0; i < 4; i++) {
    uint64_t limb = 0;
    for (int j = 0; j < 8; j++) limb = (limb << 8) | b[(3 - i) * 8 + j];
    r.v[i] = limb;
  }
  return r;
}

static void u256_to_be(const U256& a, uint8_t out[32]) {
  for (int i = 0; i < 4; i++) {
    uint64_t limb = a.v[3 - i];
    for (int j = 0; j < 8; j++) out[i * 8 + j] = uint8_t(limb >> (8 * (7 - j)));
  }
}

// secp256k1 constants.
static const Modulus FP = {
    {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL,
      0xFFFFFFFFFFFFFFFFULL}},
    {{0x00000001000003D1ULL, 0, 0, 0}}};
static const Modulus FN = {
    {{0xBFD25E8CD0364141ULL, 0xBAAEDCE6AF48A03BULL, 0xFFFFFFFFFFFFFFFEULL,
      0xFFFFFFFFFFFFFFFFULL}},
    {{0x402DA1732FC9BEBFULL, 0x4551231950B75FC4ULL, 0x1ULL, 0}}};
static const U256 GX = {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL,
                         0x55A06295CE870B07ULL, 0x79BE667EF9DCBBACULL}};
static const U256 GY = {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL,
                         0x5DA4FBFC0E1108A8ULL, 0x483ADA7726A3C465ULL}};

// ─────────── fast field ops for p = 2^256 - 0x1000003D1 ───────────
// The fold constant fits a single limb, so a 512-bit product reduces with
// two single-limb folds — an order of magnitude cheaper than the generic
// mod_reduce512 loop. These carry all point arithmetic; mod-n scalar math
// (a handful of ops per signature) stays on the generic path.

static const uint64_t FP_C = 0x1000003D1ULL;

static inline U256 fp_reduce8(const uint64_t t[8]) {
  unsigned __int128 acc;
  uint64_t r[4];
  acc = (unsigned __int128)t[4] * FP_C + t[0];
  r[0] = (uint64_t)acc; acc >>= 64;
  acc += (unsigned __int128)t[5] * FP_C + t[1];
  r[1] = (uint64_t)acc; acc >>= 64;
  acc += (unsigned __int128)t[6] * FP_C + t[2];
  r[2] = (uint64_t)acc; acc >>= 64;
  acc += (unsigned __int128)t[7] * FP_C + t[3];
  r[3] = (uint64_t)acc; acc >>= 64;
  uint64_t hi = (uint64_t)acc;  // <= ~2^33 after the first fold
  acc = (unsigned __int128)hi * FP_C + r[0];
  r[0] = (uint64_t)acc; acc >>= 64;
  acc += r[1]; r[1] = (uint64_t)acc; acc >>= 64;
  acc += r[2]; r[2] = (uint64_t)acc; acc >>= 64;
  acc += r[3]; r[3] = (uint64_t)acc; acc >>= 64;
  if ((uint64_t)acc) {
    // wrapped past 2^256 once more; the remainder is tiny, += C can't carry
    acc = (unsigned __int128)r[0] + FP_C;
    r[0] = (uint64_t)acc; acc >>= 64;
    for (int i = 1; acc && i < 4; i++) {
      acc += r[i];
      r[i] = (uint64_t)acc; acc >>= 64;
    }
  }
  U256 out = {{r[0], r[1], r[2], r[3]}};
  if (u256_cmp(out, FP.m) >= 0) u256_sub(out, out, FP.m);
  return out;
}

static inline U256 fp_mul(const U256& a, const U256& b) {
  uint64_t t[8];
  u256_mul_full(a, b, t);
  return fp_reduce8(t);
}

// Dedicated squaring: cross products once, doubled, plus the diagonal.
static inline U256 fp_sqr(const U256& a) {
  uint64_t t[8] = {0};
  for (int i = 0; i < 4; i++) {
    unsigned __int128 carry = 0;
    for (int j = i + 1; j < 4; j++) {
      carry += (unsigned __int128)a.v[i] * a.v[j] + t[i + j];
      t[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    if (i < 3) t[i + 4] = (uint64_t)carry;
  }
  uint64_t msb = 0;
  for (int i = 0; i < 8; i++) {
    uint64_t next = t[i] >> 63;
    t[i] = (t[i] << 1) | msb;
    msb = next;
  }
  unsigned __int128 acc = 0;
  for (int i = 0; i < 4; i++) {
    unsigned __int128 sq = (unsigned __int128)a.v[i] * a.v[i];
    acc += (unsigned __int128)t[2 * i] + (uint64_t)sq;
    t[2 * i] = (uint64_t)acc; acc >>= 64;
    acc += (unsigned __int128)t[2 * i + 1] + (uint64_t)(sq >> 64);
    t[2 * i + 1] = (uint64_t)acc; acc >>= 64;
  }
  return fp_reduce8(t);
}

static inline U256 fp_add(const U256& a, const U256& b) {
  U256 r;
  if (u256_add(r, a, b)) {
    // 2^256 ≡ FP_C (mod p); a,b < p bounds the wrap to at most once
    unsigned __int128 acc = (unsigned __int128)r.v[0] + FP_C;
    r.v[0] = (uint64_t)acc; acc >>= 64;
    for (int i = 1; acc && i < 4; i++) {
      acc += r.v[i];
      r.v[i] = (uint64_t)acc; acc >>= 64;
    }
  }
  if (u256_cmp(r, FP.m) >= 0) u256_sub(r, r, FP.m);
  return r;
}

static inline U256 fp_sub(const U256& a, const U256& b) {
  U256 r;
  if (u256_sub(r, a, b)) u256_add(r, r, FP.m);
  return r;
}

// Windowed pow over the fast ops (same shape as mod_pow above).
static U256 fp_pow(const U256& base, const U256& exp) {
  U256 tbl[16];
  tbl[0] = {{1, 0, 0, 0}};
  tbl[1] = base;
  for (int i = 2; i < 16; i++) tbl[i] = fp_mul(tbl[i - 1], base);
  U256 result = {{1, 0, 0, 0}};
  bool started = false;
  for (int w = 63; w >= 0; w--) {
    int digit = (exp.v[w / 16] >> (4 * (w % 16))) & 0xF;
    if (started) result = fp_sqr(fp_sqr(fp_sqr(fp_sqr(result))));
    if (digit) {
      result = started ? fp_mul(result, tbl[digit]) : tbl[digit];
      started = true;
    }
  }
  return started ? result : tbl[0];
}

static U256 fp_inv(const U256& a) {
  U256 e = FP.m;
  U256 two = {{2, 0, 0, 0}};
  u256_sub(e, e, two);
  return fp_pow(a, e);
}

// Square root mod p as a^((p+1)/4) (p ≡ 3 mod 4) on a dedicated addition
// chain: the exponent's binary form is [223 ones][0][22 ones][0000][11][00],
// so runs of ones are built by doubling-and-merging x_k = a^(2^k - 1) —
// ~253 squarings + 13 multiplies vs the generic windowed pow's
// ~256 sq + 62 mul. Callers verify y² == alpha afterwards, so a chain
// defect fails closed instead of mis-recovering.
static U256 fp_sqrt(const U256& a) {
  auto sqn = [](U256 x, int n) {
    for (int i = 0; i < n; i++) x = fp_sqr(x);
    return x;
  };
  U256 x2 = fp_mul(fp_sqr(a), a);
  U256 x3 = fp_mul(fp_sqr(x2), a);
  U256 x6 = fp_mul(sqn(x3, 3), x3);
  U256 x9 = fp_mul(sqn(x6, 3), x3);
  U256 x11 = fp_mul(sqn(x9, 2), x2);
  U256 x22 = fp_mul(sqn(x11, 11), x11);
  U256 x44 = fp_mul(sqn(x22, 22), x22);
  U256 x88 = fp_mul(sqn(x44, 44), x44);
  U256 x176 = fp_mul(sqn(x88, 88), x88);
  U256 x220 = fp_mul(sqn(x176, 44), x44);
  U256 x223 = fp_mul(sqn(x220, 3), x3);
  U256 r = fp_mul(sqn(x223, 23), x22);  // [223 ones][0][22 ones]
  r = fp_mul(sqn(r, 6), x2);            // append 0000 then 11
  return sqn(r, 2);                     // trailing 00
}

// Montgomery batch inversion: one fp_inv amortised over the whole array.
// Zero entries are left untouched (callers use zero as an "absent" marker).
static void fp_batch_inv(U256* vals, int n) {
  std::vector<U256> prefix(n);
  U256 acc = {{1, 0, 0, 0}};
  for (int i = 0; i < n; i++) {
    prefix[i] = acc;
    if (!u256_is_zero(vals[i])) acc = fp_mul(acc, vals[i]);
  }
  U256 inv = fp_inv(acc);
  for (int i = n - 1; i >= 0; i--) {
    if (u256_is_zero(vals[i])) continue;
    U256 orig = vals[i];
    vals[i] = fp_mul(inv, prefix[i]);
    inv = fp_mul(inv, orig);
  }
}

static U256 fn_inv(const U256& a) {
  U256 e = FN.m;
  U256 two = {{2, 0, 0, 0}};
  u256_sub(e, e, two);
  return mod_pow(a, e, FN);
}

// Montgomery batch inversion mod n (zeros skipped, as in fp_batch_inv). The
// batch-verify path uses this to amortise the per-signature r⁻¹ — mod-n
// arithmetic runs on the generic reduction, so one inversion there costs
// ~320 slow multiplies.
static void fn_batch_inv(U256* vals, int n) {
  std::vector<U256> prefix(n);
  U256 acc = {{1, 0, 0, 0}};
  for (int i = 0; i < n; i++) {
    prefix[i] = acc;
    if (!u256_is_zero(vals[i])) acc = mod_mul(acc, vals[i], FN);
  }
  U256 inv = fn_inv(acc);
  for (int i = n - 1; i >= 0; i--) {
    if (u256_is_zero(vals[i])) continue;
    U256 orig = vals[i];
    vals[i] = mod_mul(inv, prefix[i], FN);
    inv = mod_mul(inv, orig, FN);
  }
}

// ─────────────────── Jacobian point arithmetic (mod p) ─────────────

struct Point {
  U256 x, y, z;  // z == 0 encodes infinity
};

static const Point P_INF = {{{0, 0, 0, 0}}, {{1, 0, 0, 0}}, {{0, 0, 0, 0}}};

static inline bool pt_is_inf(const Point& p) { return u256_is_zero(p.z); }

static Point pt_double(const Point& p) {
  if (pt_is_inf(p) || u256_is_zero(p.y)) return P_INF;
  U256 a = fp_sqr(p.x);
  U256 b = fp_sqr(p.y);
  U256 c = fp_sqr(b);
  U256 xb = fp_add(p.x, b);
  U256 d = fp_sub(fp_sub(fp_sqr(xb), a), c);
  d = fp_add(d, d);
  U256 e = fp_add(fp_add(a, a), a);
  U256 f = fp_sqr(e);
  U256 x3 = fp_sub(f, fp_add(d, d));
  U256 c8 = fp_add(c, c);
  c8 = fp_add(c8, c8);
  c8 = fp_add(c8, c8);
  U256 y3 = fp_sub(fp_mul(e, fp_sub(d, x3)), c8);
  U256 z3 = fp_mul(p.y, p.z);
  z3 = fp_add(z3, z3);
  return {x3, y3, z3};
}

static Point pt_add(const Point& p1, const Point& p2) {
  if (pt_is_inf(p1)) return p2;
  if (pt_is_inf(p2)) return p1;
  U256 z1z1 = fp_sqr(p1.z);
  U256 z2z2 = fp_sqr(p2.z);
  U256 u1 = fp_mul(p1.x, z2z2);
  U256 u2 = fp_mul(p2.x, z1z1);
  U256 s1 = fp_mul(fp_mul(p1.y, p2.z), z2z2);
  U256 s2 = fp_mul(fp_mul(p2.y, p1.z), z1z1);
  if (u256_cmp(u1, u2) == 0) {
    if (u256_cmp(s1, s2) != 0) return P_INF;
    return pt_double(p1);
  }
  U256 h = fp_sub(u2, u1);
  U256 h2 = fp_add(h, h);
  U256 i = fp_sqr(h2);
  U256 j = fp_mul(h, i);
  U256 r = fp_sub(s2, s1);
  r = fp_add(r, r);
  U256 v = fp_mul(u1, i);
  U256 x3 = fp_sub(fp_sub(fp_sqr(r), j), fp_add(v, v));
  U256 s1j = fp_mul(s1, j);
  U256 y3 = fp_sub(fp_mul(r, fp_sub(v, x3)), fp_add(s1j, s1j));
  U256 z3 = fp_mul(fp_mul(h, p1.z), p2.z);
  z3 = fp_add(z3, z3);
  return {x3, y3, z3};
}

static Point pt_neg(const Point& p) {
  if (pt_is_inf(p) || u256_is_zero(p.y)) return p;
  U256 ny;
  u256_sub(ny, FP.m, p.y);
  return {p.x, ny, p.z};
}

// Affine second operand (z2 == 1 implicit): saves ~4 multiplies vs pt_add.
struct AffinePoint {
  U256 x, y;
  bool inf;
};

static Point pt_add_affine(const Point& p1, const AffinePoint& p2) {
  if (p2.inf) return p1;
  if (pt_is_inf(p1)) return {p2.x, p2.y, {{1, 0, 0, 0}}};
  U256 z1z1 = fp_sqr(p1.z);
  U256 u2 = fp_mul(p2.x, z1z1);
  U256 s2 = fp_mul(fp_mul(p2.y, p1.z), z1z1);
  if (u256_cmp(p1.x, u2) == 0) {
    if (u256_cmp(p1.y, s2) != 0) return P_INF;
    return pt_double(p1);
  }
  U256 h = fp_sub(u2, p1.x);
  U256 h2 = fp_add(h, h);
  U256 i = fp_sqr(h2);
  U256 j = fp_mul(h, i);
  U256 r = fp_sub(s2, p1.y);
  r = fp_add(r, r);
  U256 v = fp_mul(p1.x, i);
  U256 x3 = fp_sub(fp_sub(fp_sqr(r), j), fp_add(v, v));
  U256 s1j = fp_mul(p1.y, j);
  U256 y3 = fp_sub(fp_mul(r, fp_sub(v, x3)), fp_add(s1j, s1j));
  U256 z3 = fp_mul(p1.z, h);
  z3 = fp_add(z3, z3);
  return {x3, y3, z3};
}

static inline void u256_shr1(U256& a) {
  for (int i = 0; i < 3; i++) a.v[i] = (a.v[i] >> 1) | (a.v[i + 1] << 63);
  a.v[3] >>= 1;
}

// Width-5 NAF: odd digits in [-15, 15], ~1 nonzero per 6 bits.
static int build_wnaf5(const U256& k_in, int8_t out[260]) {
  U256 k = k_in;
  int len = 0;
  while (!u256_is_zero(k)) {
    int8_t d = 0;
    int m = (int)(k.v[0] & 31);
    if (m & 1) {
      if (m > 16) {
        d = (int8_t)(m - 32);
        unsigned __int128 carry = (unsigned)(32 - m);
        for (int i = 0; i < 4 && carry; i++) {
          carry += k.v[i];
          k.v[i] = (uint64_t)carry;
          carry >>= 64;
        }
      } else {
        d = (int8_t)m;
        k.v[0] -= (uint64_t)m;  // low bits of k.v[0] are exactly m
      }
    }
    out[len++] = d;
    u256_shr1(k);
  }
  return len;
}

// Variable-base scalar multiply: wNAF-5 with 8 precomputed odd multiples —
// ~256 doublings + ~51 additions vs double-and-add's ~128 additions.
static Point wnaf_mul(const Point& p, const U256& k) {
  if (pt_is_inf(p) || u256_is_zero(k)) return P_INF;
  int8_t naf[260];
  int len = build_wnaf5(k, naf);
  Point tbl[8];  // 1P, 3P, ..., 15P
  tbl[0] = p;
  Point p2 = pt_double(p);
  for (int i = 1; i < 8; i++) tbl[i] = pt_add(tbl[i - 1], p2);
  Point acc = P_INF;
  for (int i = len - 1; i >= 0; i--) {
    acc = pt_double(acc);
    int d = naf[i];
    if (d > 0) acc = pt_add(acc, tbl[(d - 1) >> 1]);
    else if (d < 0) acc = pt_add(acc, pt_neg(tbl[((-d) - 1) >> 1]));
  }
  return acc;
}

// ───────── GLV endomorphism: k·P with half the doublings ──────────
// secp256k1 has an efficient endomorphism φ(x, y) = (β·x, y) = λ·(x, y).
// Splitting k = k1 + k2·λ (mod n) with |k1|,|k2| ≲ 2^128 turns one 256-bit
// scalar multiply into two interleaved 128-bit ones sharing a doubling
// chain. Constants are the standard curve values; build_g_table_impl
// cross-checks them against plain wNAF at init and clears glv_ok on any
// mismatch, falling back to the single-scalar path.

static const U256 GLV_BETA = {{0xC1396C28719501EEULL, 0x9CF0497512F58995ULL,
                               0x6E64479EAC3434E9ULL, 0x7AE96A2B657C0710ULL}};
static bool glv_ok = false;

// q = round(m2·k / n) for a ≤128-bit multiplier, via the series
// 1/n = 2^-256·(1 + c·2^-256 + ...). Error ≤ 1, which only nudges
// |k1|,|k2| within their headroom.
static void glv_round_div(const U256& k, const uint64_t m2[2], uint64_t q[2]) {
  uint64_t T[6] = {0};
  for (int i = 0; i < 4; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 2; j++) {
      carry += (unsigned __int128)k.v[i] * m2[j] + T[i + j];
      T[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    T[i + 2] = (uint64_t)carry;
  }
  uint64_t P[9] = {0};
  for (int i = 0; i < 6; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 3; j++) {
      carry += (unsigned __int128)T[i] * FN.c.v[j] + P[i + j];
      P[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    P[i + 3] = (uint64_t)carry;
  }
  // U = T + (P >> 256); q = (U + 2^255) >> 256
  unsigned __int128 acc = 0;
  uint64_t U[7];
  for (int i = 0; i < 6; i++) {
    acc += T[i];
    if (i + 4 < 9) acc += P[i + 4];
    U[i] = (uint64_t)acc;
    acc >>= 64;
  }
  U[6] = (uint64_t)acc;
  acc = (unsigned __int128)U[3] + 0x8000000000000000ULL;
  U[3] = (uint64_t)acc;
  acc >>= 64;
  for (int i = 4; acc && i < 7; i++) {
    acc += U[i];
    U[i] = (uint64_t)acc;
    acc >>= 64;
  }
  q[0] = U[4];
  q[1] = U[5];
}

// a(an limbs) * b(bn limbs) truncated to 256 bits.
static U256 mul_trunc256(const uint64_t* a, int an, const uint64_t* b, int bn) {
  uint64_t t[8] = {0};
  for (int i = 0; i < an; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < bn && i + j < 8; j++) {
      carry += (unsigned __int128)a[i] * b[j] + t[i + j];
      t[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    if (i + bn < 8) t[i + bn] = (uint64_t)carry;
  }
  return {{t[0], t[1], t[2], t[3]}};
}

// Split k into signed halves: k ≡ sign1·k1 + sign2·k2·λ (mod n).
static void glv_split(const U256& k, U256& k1, bool& k1_neg, U256& k2,
                      bool& k2_neg) {
  // Lattice basis: v1 = (a1, b1), v2 = (a2, b2) with a + b·λ ≡ 0 (mod n);
  // b1 = -B1N, a2 = a1 + B1N, b2 = a1.
  static const uint64_t A1[2] = {0xE86C90E49284EB15ULL, 0x3086D221A7D46BCDULL};
  static const uint64_t B1N[2] = {0x6F547FA90ABFE4C3ULL, 0xE4437ED6010E8828ULL};
  static const uint64_t A2[3] = {0x57C1108D9D44CFD8ULL, 0x14CA50F7A8E2F3F6ULL,
                                 1ULL};
  uint64_t c1[2], c2[2];
  glv_round_div(k, A1, c1);   // round(b2·k/n)
  glv_round_div(k, B1N, c2);  // round(-b1·k/n)
  U256 c1a1 = mul_trunc256(c1, 2, A1, 2);
  U256 c2a2 = mul_trunc256(c2, 2, A2, 3);
  const U256 zero = {{0, 0, 0, 0}};
  U256 s, t;
  u256_add(s, c1a1, c2a2);  // mod 2^256; |k1| small makes wrap safe
  u256_sub(t, k, s);
  k1_neg = (t.v[3] >> 63) != 0;
  if (k1_neg) u256_sub(k1, zero, t);
  else k1 = t;
  U256 c1b1n = mul_trunc256(c1, 2, B1N, 2);
  U256 c2a1 = mul_trunc256(c2, 2, A1, 2);
  u256_sub(t, c1b1n, c2a1);
  k2_neg = (t.v[3] >> 63) != 0;
  if (k2_neg) u256_sub(k2, zero, t);
  else k2 = t;
}

static Point glv_mul(const Point& p, const U256& u) {
  if (pt_is_inf(p) || u256_is_zero(u)) return P_INF;
  U256 k1, k2;
  bool n1, n2;
  glv_split(u, k1, n1, k2, n2);
  Point p1 = n1 ? pt_neg(p) : p;
  Point p2 = {fp_mul(p.x, GLV_BETA), p.y, p.z};
  if (n2) p2 = pt_neg(p2);
  int8_t naf1[260], naf2[260];
  int len1 = build_wnaf5(k1, naf1);
  int len2 = build_wnaf5(k2, naf2);
  Point tbl1[8], tbl2[8];
  tbl1[0] = p1;
  Point d1 = pt_double(p1);
  for (int i = 1; i < 8; i++) tbl1[i] = pt_add(tbl1[i - 1], d1);
  tbl2[0] = p2;
  Point d2 = pt_double(p2);
  for (int i = 1; i < 8; i++) tbl2[i] = pt_add(tbl2[i - 1], d2);
  Point acc = P_INF;
  int len = len1 > len2 ? len1 : len2;
  for (int i = len - 1; i >= 0; i--) {
    acc = pt_double(acc);
    if (i < len1) {
      int d = naf1[i];
      if (d > 0) acc = pt_add(acc, tbl1[(d - 1) >> 1]);
      else if (d < 0) acc = pt_add(acc, pt_neg(tbl1[((-d) - 1) >> 1]));
    }
    if (i < len2) {
      int d = naf2[i];
      if (d > 0) acc = pt_add(acc, tbl2[(d - 1) >> 1]);
      else if (d < 0) acc = pt_add(acc, pt_neg(tbl2[((-d) - 1) >> 1]));
    }
  }
  return acc;
}

// ── Batched affine-GLV ladder ──────────────────────────────────────
// The verify hot path amortises ONE field inversion across a whole
// chunk's per-item wNAF tables (8 z's per item into a cross-item
// Montgomery batch), so every ladder addition runs on the cheaper mixed
// (affine-operand) formulas, and the φ-table is derived free from the
// affine base table (φ(x, y) = (β·x, y); negation flips y only).
struct GlvPrep {
  int8_t naf1[260], naf2[260];
  int len1, len2;
  Point jtbl[8];       // jacobian odd multiples 1,3,...,15 of ±R
  AffinePoint tbl[8];  // affine conversions (phase B)
  U256 beta_x[8];      // φ-table x coordinates
  bool flip2;          // second scalar's sign differs from the first's
  bool glv;            // affine ladder prepared (else q computed eagerly)
};

// Phase A: split the scalar, build the jacobian odd-multiple table of
// ±R, and export the 8 z coordinates for the cross-item batch inversion.
static void glv_prep_phase(const U256& rx, const U256& ry, const U256& u2,
                           GlvPrep& gp, U256* zs8) {
  U256 k1, k2;
  bool n1, n2;
  glv_split(u2, k1, n1, k2, n2);
  gp.len1 = build_wnaf5(k1, gp.naf1);
  gp.len2 = build_wnaf5(k2, gp.naf2);
  gp.flip2 = (n1 != n2);
  Point p1 = {rx, ry, {{1, 0, 0, 0}}};
  if (n1) p1 = pt_neg(p1);
  gp.jtbl[0] = p1;
  Point d1 = pt_double(p1);
  for (int i = 1; i < 8; i++) gp.jtbl[i] = pt_add(gp.jtbl[i - 1], d1);
  for (int i = 0; i < 8; i++) zs8[i] = gp.jtbl[i].z;
}

// Phase B: finish the affine conversion with the batch-inverted z's and
// run the dual ladder on mixed additions.
static Point glv_ladder_affine(GlvPrep& gp, const U256* zinv8) {
  for (int i = 0; i < 8; i++) {
    const Point& p = gp.jtbl[i];
    AffinePoint& a = gp.tbl[i];
    a.inf = pt_is_inf(p);
    if (a.inf) {
      gp.beta_x[i] = p.x;
      continue;
    }
    U256 zi2 = fp_sqr(zinv8[i]);
    a.x = fp_mul(p.x, zi2);
    a.y = fp_mul(p.y, fp_mul(zi2, zinv8[i]));
    gp.beta_x[i] = fp_mul(a.x, GLV_BETA);
  }
  Point acc = P_INF;
  int len = gp.len1 > gp.len2 ? gp.len1 : gp.len2;
  for (int i = len - 1; i >= 0; i--) {
    acc = pt_double(acc);
    if (i < gp.len1) {
      int d = gp.naf1[i];
      if (d) {
        AffinePoint t = gp.tbl[((d < 0 ? -d : d) - 1) >> 1];
        if (d < 0 && !t.inf) u256_sub(t.y, FP.m, t.y);
        acc = pt_add_affine(acc, t);
      }
    }
    if (i < gp.len2) {
      int d = gp.naf2[i];
      if (d) {
        int idx = ((d < 0 ? -d : d) - 1) >> 1;
        AffinePoint t = {gp.beta_x[idx], gp.tbl[idx].y, gp.tbl[idx].inf};
        if (((d < 0) != gp.flip2) && !t.inf) u256_sub(t.y, FP.m, t.y);
        acc = pt_add_affine(acc, t);
      }
    }
  }
  return acc;
}

// Projective equality: x1·z2² == x2·z1² and y1·z2³ == y2·z1³.
static bool pt_equal(const Point& a, const Point& b) {
  if (pt_is_inf(a) || pt_is_inf(b)) return pt_is_inf(a) == pt_is_inf(b);
  U256 za2 = fp_sqr(a.z), zb2 = fp_sqr(b.z);
  if (u256_cmp(fp_mul(a.x, zb2), fp_mul(b.x, za2)) != 0) return false;
  U256 za3 = fp_mul(za2, a.z), zb3 = fp_mul(zb2, b.z);
  return u256_cmp(fp_mul(a.y, zb3), fp_mul(b.y, za3)) == 0;
}

// Fixed-base 8-bit window table for G: g_table[w][d-1] = (256^w * d) * G,
// stored affine (one batch inversion at init) so g_mul runs on the cheaper
// mixed addition — 32 windows means ~32 mixed adds per fixed-base multiply
// (the earlier 4-bit table paid ~64). ~590 KB of table, built once.
// Callers enter through ctypes with the GIL released, so initialisation
// must be race-free: std::call_once.
static constexpr int GT_WINDOWS = 32;
static constexpr int GT_ENTRIES = 255;
static AffinePoint g_table[GT_WINDOWS][GT_ENTRIES];
static std::once_flag g_table_once;

static void build_g_table_impl() {
  std::vector<Point> jac((size_t)GT_WINDOWS * GT_ENTRIES);
  Point base = {GX, GY, {{1, 0, 0, 0}}};
  for (int w = 0; w < GT_WINDOWS; w++) {
    Point acc = P_INF;
    for (int d = 0; d < GT_ENTRIES; d++) {
      acc = pt_add(acc, base);
      jac[(size_t)w * GT_ENTRIES + d] = acc;
    }
    for (int b = 0; b < 8; b++) base = pt_double(base);
  }
  std::vector<U256> zs((size_t)GT_WINDOWS * GT_ENTRIES);
  for (size_t i = 0; i < zs.size(); i++) zs[i] = jac[i].z;
  fp_batch_inv(zs.data(), (int)zs.size());
  for (int w = 0; w < GT_WINDOWS; w++) {
    for (int d = 0; d < GT_ENTRIES; d++) {
      const Point& p = jac[(size_t)w * GT_ENTRIES + d];
      AffinePoint& a = g_table[w][d];
      a.inf = pt_is_inf(p);  // never true for d*256^w*G, but stay defensive
      if (a.inf) continue;
      U256 zi = zs[(size_t)w * GT_ENTRIES + d];
      U256 zi2 = fp_sqr(zi);
      a.x = fp_mul(p.x, zi2);
      a.y = fp_mul(p.y, fp_mul(zi2, zi));
    }
  }
  // Cross-check the GLV constants once against the plain wNAF ladder; on
  // any disagreement recover_combine silently stays on the slow path.
  Point g = {GX, GY, {{1, 0, 0, 0}}};
  U256 probe = {{0x243F6A8885A308D3ULL, 0x13198A2E03707344ULL,
                 0xA4093822299F31D0ULL, 0x082EFA98EC4E6C89ULL}};
  glv_ok = pt_equal(glv_mul(g, probe), wnaf_mul(g, probe));
}

static void build_g_table() { std::call_once(g_table_once, build_g_table_impl); }

static Point g_mul(const U256& scalar) {
  build_g_table();
  Point result = P_INF;
  for (int w = 0; w < GT_WINDOWS; w++) {
    int digit = (scalar.v[w / 8] >> (8 * (w % 8))) & 0xFF;
    if (digit) result = pt_add_affine(result, g_table[w][digit - 1]);
  }
  return result;
}

static bool pt_to_affine(const Point& p, U256& x, U256& y) {
  if (pt_is_inf(p)) return false;
  U256 zi = fp_inv(p.z);
  U256 zi2 = fp_sqr(zi);
  x = fp_mul(p.x, zi2);
  y = fp_mul(p.y, fp_mul(zi2, zi));
  return true;
}

// ───────────────────────────── ECDSA ───────────────────────────────

// Reconstruct the ephemeral point R = (x, y) from the signature r scalar and
// recovery id. False when x is off-curve or out of range.
static bool recover_r_point(const U256& r, int recid, U256& x_out,
                            U256& y_out) {
  U256 x = r;
  if (recid & 2) {
    uint64_t carry = u256_add(x, x, FN.m);
    if (carry || u256_cmp(x, FP.m) >= 0) return false;
  }
  // alpha = x^3 + 7 mod p
  U256 alpha = fp_add(fp_mul(fp_sqr(x), x), {{7, 0, 0, 0}});
  // y = alpha^((p+1)/4): p ≡ 3 mod 4 (dedicated chain; checked below)
  U256 y = fp_sqrt(alpha);
  if (u256_cmp(fp_sqr(y), alpha) != 0) return false;
  if ((y.v[0] & 1) != (uint64_t)(recid & 1)) {
    U256 ny;
    u256_sub(ny, FP.m, y);
    y = ny;
  }
  x_out = x;
  y_out = y;
  return true;
}

// Q = r⁻¹(sR − zG), computed with r_inv supplied by the caller (batch paths
// amortise the mod-n inversion) as (s·r⁻¹)·R + (−z·r⁻¹)·G: one wNAF
// variable-base multiply plus a fixed-base table multiply instead of the
// naive three scalar multiplies.
static bool recover_combine(const U256& rx, const U256& ry, const U256& s,
                            const U256& z, const U256& r_inv, Point& q_out) {
  U256 u1 = u256_is_zero(z) ? z : mod_mul(mod_sub(FN.m, z, FN), r_inv, FN);
  U256 u2 = mod_mul(s, r_inv, FN);
  Point R = {rx, ry, {{1, 0, 0, 0}}};
  Point sr = glv_ok ? glv_mul(R, u2) : wnaf_mul(R, u2);
  q_out = pt_add(sr, g_mul(u1));
  return !pt_is_inf(q_out);
}

static bool ecdsa_recover_jac(const uint8_t msg_hash[32], const U256& r,
                              const U256& s, int recid, Point& q_out) {
  if (u256_is_zero(r) || u256_is_zero(s)) return false;
  if (u256_cmp(r, FN.m) >= 0 || u256_cmp(s, FN.m) >= 0) return false;
  if (recid < 0 || recid > 3) return false;
  U256 x, y;
  if (!recover_r_point(r, recid, x, y)) return false;
  U256 z = u256_from_be(msg_hash);
  // z mod n (one conditional subtract is enough: z < 2^256 < 2n)
  if (u256_cmp(z, FN.m) >= 0) u256_sub(z, z, FN.m);
  return recover_combine(x, y, s, z, fn_inv(r), q_out);
}

// Recover affine pubkey from (msg_hash, r, s, recid). Returns false on fail.
static bool ecdsa_recover(const uint8_t msg_hash[32], const U256& r,
                          const U256& s, int recid, U256& qx, U256& qy) {
  Point q;
  if (!ecdsa_recover_jac(msg_hash, r, s, recid, q)) return false;
  return pt_to_affine(q, qx, qy);
}

// RFC 6979 deterministic nonce.
static U256 rfc6979_k(const uint8_t msg_hash[32], const uint8_t priv[32]) {
  uint8_t v[32], k[32];
  memset(v, 0x01, 32);
  memset(k, 0x00, 32);
  uint8_t sep0 = 0x00, sep1 = 0x01;
  hmac_sha256(k, 32, v, 32, &sep0, 1, priv, 32, msg_hash, 32, k);
  hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
  hmac_sha256(k, 32, v, 32, &sep1, 1, priv, 32, msg_hash, 32, k);
  hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
  while (true) {
    hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
    U256 cand = u256_from_be(v);
    if (!u256_is_zero(cand) && u256_cmp(cand, FN.m) < 0) return cand;
    hmac_sha256(k, 32, v, 32, &sep0, 1, nullptr, 0, nullptr, 0, k);
    hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
  }
}

// Sign; returns recid in [0,3] with low-s normalisation.
static bool ecdsa_sign(const uint8_t msg_hash[32], const uint8_t priv[32],
                       U256& r_out, U256& s_out, int& recid_out) {
  U256 d = u256_from_be(priv);
  if (u256_is_zero(d) || u256_cmp(d, FN.m) >= 0) return false;
  U256 z = u256_from_be(msg_hash);
  if (u256_cmp(z, FN.m) >= 0) u256_sub(z, z, FN.m);
  for (int attempt = 0; attempt < 64; attempt++) {
    U256 k = rfc6979_k(msg_hash, priv);
    U256 rx, ry;
    if (!pt_to_affine(g_mul(k), rx, ry)) continue;
    U256 r = rx;
    if (u256_cmp(r, FN.m) >= 0) u256_sub(r, r, FN.m);
    if (u256_is_zero(r)) continue;
    U256 s = mod_mul(fn_inv(k), mod_add(z, mod_mul(r, d, FN), FN), FN);
    if (u256_is_zero(s)) continue;
    int recid = int(ry.v[0] & 1) | (u256_cmp(rx, FN.m) >= 0 ? 2 : 0);
    // low-s
    U256 half = FN.m;
    uint64_t carry = 0;
    for (int i = 3; i >= 0; i--) {
      uint64_t next = half.v[i] & 1;
      half.v[i] = (half.v[i] >> 1) | (carry << 63);
      carry = next;
    }
    if (u256_cmp(s, half) > 0) {
      s = mod_sub(FN.m, s, FN);
      recid ^= 1;
    }
    r_out = r;
    s_out = s;
    recid_out = recid;
    return true;
  }
  return false;
}

// ───────────────────────── Ethereum scheme ─────────────────────────

static void eip191_hash(const uint8_t* payload, size_t len, uint8_t out[32]) {
  char prefix[64];
  int plen = snprintf(prefix, sizeof(prefix),
                      "\x19""Ethereum Signed Message:\n%zu", len);
  std::vector<uint8_t> buf(plen + len);
  memcpy(buf.data(), prefix, plen);
  memcpy(buf.data() + plen, payload, len);
  keccak256(buf.data(), buf.size(), out);
}

static void address_from_pub(const U256& qx, const U256& qy, uint8_t out[20]) {
  uint8_t pub[64], digest[32];
  u256_to_be(qx, pub);
  u256_to_be(qy, pub + 32);
  keccak256(pub, 64, digest);
  memcpy(out, digest + 12, 20);
}

// Verify one EIP-191 signature. Returns 1 valid, 0 address mismatch,
// -1 malformed recovery byte, -2 recovery failed (the reference surfaces
// -1/-2 as scheme errors and 0 as InvalidVoteSignature — distinct paths,
// src/signing/ethereum.rs:66-97).
// Per-item state threaded through the batched verify phases.
struct VerifyItem {
  U256 r, s, z, rx, ry;
};

// Phase 1: parse + digest + R-point reconstruction. Returns 1 = ok (r
// pending batch inversion), 255 = malformed recovery byte, 254 = failed.
static uint8_t eth_parse_phase(const uint8_t* payload, size_t len,
                               const uint8_t sig[65], VerifyItem& it) {
  it.r = u256_from_be(sig);
  it.s = u256_from_be(sig + 32);
  int v = sig[64];
  if (v >= 27) v -= 27;
  if (v > 1) return 255;
  if (u256_is_zero(it.r) || u256_is_zero(it.s)) return 254;
  if (u256_cmp(it.r, FN.m) >= 0 || u256_cmp(it.s, FN.m) >= 0) return 254;
  if (!recover_r_point(it.r, v, it.rx, it.ry)) return 254;
  uint8_t digest[32];
  eip191_hash(payload, len, digest);
  it.z = u256_from_be(digest);
  if (u256_cmp(it.z, FN.m) >= 0) u256_sub(it.z, it.z, FN.m);
  return 1;
}

static int eth_verify_one(const uint8_t identity[20], const uint8_t* payload,
                          size_t len, const uint8_t sig[65]) {
  U256 r = u256_from_be(sig);
  U256 s = u256_from_be(sig + 32);
  int v = sig[64];
  if (v >= 27) v -= 27;
  if (v > 1) return -1;
  uint8_t digest[32];
  eip191_hash(payload, len, digest);
  U256 qx, qy;
  if (!ecdsa_recover(digest, r, s, v, qx, qy)) return -2;
  uint8_t addr[20];
  address_from_pub(qx, qy, addr);
  return memcmp(addr, identity, 20) == 0 ? 1 : 0;
}

// ─────────────────────── batch fan-out helper ──────────────────────

// Split [0, count) across n_threads (0 = hardware concurrency); stay
// single-threaded below min_parallel items where spawn cost dominates.
template <typename Work>
static void run_parallel(int64_t count, int n_threads, int64_t min_parallel,
                         const Work& work) {
  if (n_threads <= 0) n_threads = (int)std::thread::hardware_concurrency();
  if (n_threads < 1) n_threads = 1;
  if (n_threads == 1 || count < min_parallel) {
    work(0, count);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (count + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(count, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back(work, lo, hi);
  }
  for (auto& th : threads) th.join();
}

// ───────────────────────────── C ABI ───────────────────────────────

extern "C" {

void hg_sha256(const uint8_t* data, uint64_t len, uint8_t* out) {
  sha256(data, len, out);
}

void hg_keccak256(const uint8_t* data, uint64_t len, uint8_t* out) {
  keccak256(data, len, out);
}

// Batched hashing: items are concatenated in `data`, item i spans
// [offsets[i], offsets[i+1]); digests land at out + 32*i.
static void hash_batch(const uint8_t* data, const uint64_t* offsets,
                       int64_t count, uint8_t* out, int n_threads,
                       void (*fn)(const uint8_t*, size_t, uint8_t*)) {
  run_parallel(count, n_threads, 64, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++)
      fn(data + offsets[i], offsets[i + 1] - offsets[i], out + 32 * i);
  });
}

void hg_sha256_batch(const uint8_t* data, const uint64_t* offsets,
                     int64_t count, uint8_t* out, int n_threads) {
  hash_batch(data, offsets, count, out, n_threads, sha256);
}

void hg_keccak256_batch(const uint8_t* data, const uint64_t* offsets,
                        int64_t count, uint8_t* out, int n_threads) {
  hash_batch(data, offsets, count, out, n_threads, keccak256);
}

// EIP-191 verify. identities: 20*i, payload spans offsets, sigs: 65*i.
// results[i]: 1 valid, 0 address mismatch, 255 malformed recovery byte,
// 254 recovery failed (the latter two map to scheme errors).
void hg_eth_verify_batch(const uint8_t* identities, const uint8_t* payloads,
                         const uint64_t* offsets, const uint8_t* sigs,
                         int64_t count, uint8_t* results, int n_threads) {
  build_g_table();
  run_parallel(count, n_threads, 4, [&](int64_t lo, int64_t hi) {
    // Chunked so the three Montgomery batch inversions (r⁻¹ mod n before
    // the scalar multiplies, the per-item wNAF-table z's for the affine
    // GLV ladder, and q's z for the final affine conversion) each amortise
    // one real inversion over up to 64 signatures.
    const int64_t CHUNK = 64;
    VerifyItem items[CHUNK];
    U256 rinvs[CHUNK];
    U256 u1s[CHUNK];
    Point qs[CHUNK];
    U256 zs[CHUNK];
    std::vector<GlvPrep> preps(CHUNK);
    std::vector<U256> ztbl(CHUNK * 8);
    const U256 zero = {{0, 0, 0, 0}};
    for (int64_t base = lo; base < hi; base += CHUNK) {
      int64_t m = std::min(CHUNK, hi - base);
      for (int64_t j = 0; j < m; j++) {
        int64_t i = base + j;
        results[i] = eth_parse_phase(payloads + offsets[i],
                                     offsets[i + 1] - offsets[i],
                                     sigs + 65 * i, items[j]);
        rinvs[j] = results[i] == 1 ? items[j].r : zero;
      }
      fn_batch_inv(rinvs, (int)m);
      for (int64_t j = 0; j < m; j++) {
        int64_t i = base + j;
        zs[j] = zero;
        preps[j].glv = false;
        for (int t = 0; t < 8; t++) ztbl[8 * j + t] = zero;
        if (results[i] != 1) continue;
        const U256& z = items[j].z;
        U256 u1 = u256_is_zero(z) ? z
                                  : mod_mul(mod_sub(FN.m, z, FN), rinvs[j], FN);
        U256 u2 = mod_mul(items[j].s, rinvs[j], FN);
        u1s[j] = u1;
        if (glv_ok && !u256_is_zero(u2)) {
          preps[j].glv = true;
          glv_prep_phase(items[j].rx, items[j].ry, u2, preps[j],
                         &ztbl[8 * j]);
        } else if (!recover_combine(items[j].rx, items[j].ry, items[j].s,
                                    items[j].z, rinvs[j], qs[j])) {
          results[i] = 254;
        } else {
          zs[j] = qs[j].z;
        }
      }
      fp_batch_inv(ztbl.data(), (int)(8 * m));
      for (int64_t j = 0; j < m; j++) {
        int64_t i = base + j;
        if (results[i] != 1 || !preps[j].glv) continue;
        Point sr = glv_ladder_affine(preps[j], &ztbl[8 * j]);
        qs[j] = pt_add(sr, g_mul(u1s[j]));
        if (pt_is_inf(qs[j]))
          results[i] = 254;
        else
          zs[j] = qs[j].z;
      }
      fp_batch_inv(zs, (int)m);
      for (int64_t j = 0; j < m; j++) {
        int64_t i = base + j;
        if (results[i] != 1) continue;
        U256 zi2 = fp_sqr(zs[j]);
        U256 qx = fp_mul(qs[j].x, zi2);
        U256 qy = fp_mul(qs[j].y, fp_mul(zi2, zs[j]));
        uint8_t addr[20];
        address_from_pub(qx, qy, addr);
        results[i] = memcmp(addr, identities + 20 * i, 20) == 0 ? 1 : 0;
      }
    }
  });
}

int hg_eth_verify(const uint8_t* identity, const uint8_t* payload,
                  uint64_t len, const uint8_t* sig) {
  build_g_table();
  return eth_verify_one(identity, payload, len, sig);
}

// Sign payload (EIP-191) with a 32-byte key; writes r||s||v (65 bytes).
// Returns 0 on success.
int hg_eth_sign(const uint8_t* priv, const uint8_t* payload, uint64_t len,
                uint8_t* sig_out) {
  build_g_table();
  uint8_t digest[32];
  eip191_hash(payload, len, digest);
  U256 r, s;
  int recid;
  if (!ecdsa_sign(digest, priv, r, s, recid)) return 1;
  u256_to_be(r, sig_out);
  u256_to_be(s, sig_out + 32);
  sig_out[64] = uint8_t(27 + (recid & 1));
  return 0;
}

// Derive the Ethereum address for a private key. Returns 0 on success.
int hg_eth_address(const uint8_t* priv, uint8_t* addr_out) {
  build_g_table();
  U256 d = u256_from_be(priv);
  if (u256_is_zero(d) || u256_cmp(d, FN.m) >= 0) return 1;
  U256 qx, qy;
  if (!pt_to_affine(g_mul(d), qx, qy)) return 1;
  address_from_pub(qx, qy, addr_out);
  return 0;
}

// Fused open-addressing probe for the engine's proposal-id -> slot hash
// (mirror of hashgraph_tpu.engine.engine._PidLookup: Fibonacci bucketing
// h = (uint64(key) * GOLDEN) >> shift over a power-of-two table with -1
// as the empty sentinel, linear probing). The numpy probe loop pays ~12
// full-array passes per probe iteration; this is one fused pass per
// query at memory bandwidth. Queries equal to -1 (the sentinel) resolve
// to not-found, as in the Python path. Table load factor <= 0.5
// guarantees empty buckets, so probing always terminates.
void hg_pid_lookup(const int64_t* table_keys, const int64_t* table_vals,
                   int64_t size, int shift, const int64_t* queries,
                   int64_t count, uint8_t* found, int64_t* out,
                   int n_threads) {
  const uint64_t GOLDEN = 0x9E3779B97F4A7C15ull;
  const uint64_t mask = uint64_t(size - 1);
  run_parallel(count, n_threads, 4096, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      const int64_t q = queries[i];
      if (q == -1) {
        found[i] = 0;
        out[i] = 0;
        continue;
      }
      uint64_t h = (uint64_t(q) * GOLDEN) >> shift;
      for (;;) {
        const int64_t k = table_keys[h & mask];
        if (k == q) {
          found[i] = 1;
          out[i] = table_vals[h & mask];
          break;
        }
        if (k == -1) {
          found[i] = 0;
          out[i] = 0;
          break;
        }
        h++;
      }
    }
  });
}

// Fused voter-gid liveness check (mirror of ProposalPool.gids_live):
// gid = generation << 32 | index; live iff index in range, the live flag
// is set, and the generation matches. One pass instead of numpy's six
// (range mask, index split, generation split, two gathers, compare).
void hg_gids_live(const int64_t* gids, int64_t count, const uint8_t* live,
                  const int64_t* gen, int64_t n_owners, uint8_t* out,
                  int n_threads) {
  run_parallel(count, n_threads, 8192, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      const int64_t g = gids[i];
      const int64_t idx = g & 0xFFFFFFFFll;
      out[i] = uint8_t(g >= 0 && idx < n_owners && live[idx] &&
                       gen[idx] == (g >> 32));
    }
  });
}

int hg_version() { return 2; }

}  // extern "C"
