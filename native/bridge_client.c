/* C reference embedder for the hashgraph_tpu bridge.
 *
 * Demonstrates that a non-Python process can drive the full consensus
 * surface — create a proposal, cast votes, ferry Proposal/Vote protobuf
 * bytes between peers, receive events — over the framed TCP protocol
 * documented in hashgraph_tpu/bridge/protocol.py. The scenario is the
 * reference library's 3-voter quick-start (reference: README.md:41-82):
 * alice proposes, everyone votes YES, all three peers observe
 * ConsensusReached(true).
 *
 * Build:  gcc -O2 -o bridge_demo native/bridge_client.c
 * Run:    ./bridge_demo <host> <port>     (exit 0 = scenario passed)
 *
 * The first ~150 lines are a reusable mini client library (hgb_*); the
 * quick-start itself is the few dozen lines of main().
 */

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

/* ───────────────────────── wire primitives ───────────────────────── */

enum {
  OP_PING = 0,
  OP_ADD_PEER = 1,
  OP_CREATE_PROPOSAL = 2,
  OP_CAST_VOTE = 3,
  OP_PROCESS_PROPOSAL = 4,
  OP_PROCESS_VOTE = 5,
  OP_HANDLE_TIMEOUT = 6,
  OP_GET_RESULT = 7,
  OP_POLL_EVENTS = 8,
  OP_GET_PROPOSAL = 9,
  OP_GET_STATS = 10,
  OP_PROCESS_VOTES = 11, /* batch: u32 count + count blobs -> u8 statuses */
};

#define STATUS_OK 0
#define RESULT_YES 1
#define EVENT_REACHED 1
#define HGB_MAX_FRAME (1 << 20)

typedef struct {
  uint8_t buf[HGB_MAX_FRAME];
  uint32_t len;
} hgb_buf;

static void put_u8(hgb_buf* b, uint8_t v) { b->buf[b->len++] = v; }
static void put_u16(hgb_buf* b, uint16_t v) {
  b->buf[b->len++] = (uint8_t)v;
  b->buf[b->len++] = (uint8_t)(v >> 8);
}
static void put_u32(hgb_buf* b, uint32_t v) {
  for (int i = 0; i < 4; i++) b->buf[b->len++] = (uint8_t)(v >> (8 * i));
}
static void put_u64(hgb_buf* b, uint64_t v) {
  for (int i = 0; i < 8; i++) b->buf[b->len++] = (uint8_t)(v >> (8 * i));
}
static void put_str(hgb_buf* b, const char* s) {
  uint16_t n = (uint16_t)strlen(s);
  put_u16(b, n);
  memcpy(b->buf + b->len, s, n);
  b->len += n;
}
static void put_blob(hgb_buf* b, const uint8_t* data, uint32_t n) {
  put_u32(b, n);
  memcpy(b->buf + b->len, data, n);
  b->len += n;
}

typedef struct {
  const uint8_t* p;
  uint32_t len, pos;
} hgb_cur;

static uint8_t get_u8(hgb_cur* c) { return c->p[c->pos++]; }
static uint16_t get_u16(hgb_cur* c) {
  uint16_t v = (uint16_t)(c->p[c->pos] | (c->p[c->pos + 1] << 8));
  c->pos += 2;
  return v;
}
static uint32_t get_u32(hgb_cur* c) {
  uint32_t v = 0;
  for (int i = 0; i < 4; i++) v |= (uint32_t)c->p[c->pos + i] << (8 * i);
  c->pos += 4;
  return v;
}
static uint64_t get_u64(hgb_cur* c) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v |= (uint64_t)c->p[c->pos + i] << (8 * i);
  c->pos += 8;
  return v;
}

/* ───────────────────────── connection + call ─────────────────────── */

static int hgb_connect(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      connect(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

static int io_all(int fd, uint8_t* buf, uint32_t n, int writing) {
  while (n > 0) {
    ssize_t k = writing ? write(fd, buf, n) : read(fd, buf, n);
    if (k <= 0) return -1;
    buf += k;
    n -= (uint32_t)k;
  }
  return 0;
}

/* Sends opcode+payload, receives the response into resp (payload only).
 * Returns the wire status byte, or -1 on transport failure. */
static int hgb_call(int fd, uint8_t op, const hgb_buf* req, hgb_buf* resp) {
  uint8_t head[5];
  uint32_t len = 1 + (req ? req->len : 0);
  for (int i = 0; i < 4; i++) head[i] = (uint8_t)(len >> (8 * i));
  head[4] = op;
  if (io_all(fd, head, 5, 1) != 0) return -1;
  if (req && req->len && io_all(fd, (uint8_t*)req->buf, req->len, 1) != 0)
    return -1;
  uint8_t rhead[4];
  if (io_all(fd, rhead, 4, 0) != 0) return -1;
  uint32_t rlen = 0;
  for (int i = 0; i < 4; i++) rlen |= (uint32_t)rhead[i] << (8 * i);
  if (rlen < 1 || rlen > HGB_MAX_FRAME) return -1;
  uint8_t status;
  if (io_all(fd, &status, 1, 0) != 0) return -1;
  resp->len = rlen - 1;
  if (resp->len && io_all(fd, resp->buf, resp->len, 0) != 0) return -1;
  return status;
}

/* ─────────────────────────── quick-start ─────────────────────────── */

#define CHECK(cond, what)                                   \
  do {                                                      \
    if (!(cond)) {                                          \
      fprintf(stderr, "FAIL: %s (line %d)\n", what, __LINE__); \
      return 1;                                             \
    }                                                       \
  } while (0)

int main(int argc, char** argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: %s <host> <port>\n", argv[0]);
    return 2;
  }
  int fd = hgb_connect(argv[1], atoi(argv[2]));
  CHECK(fd >= 0, "connect");

  static hgb_buf req, resp;
  hgb_cur cur;

  /* handshake */
  req.len = 0;
  CHECK(hgb_call(fd, OP_PING, &req, &resp) == STATUS_OK, "ping");
  cur = (hgb_cur){resp.buf, resp.len, 0};
  printf("bridge protocol v%u\n", get_u32(&cur));

  /* three peers: alice, bob, carol (each its own engine + signer) */
  uint32_t peers[3];
  const char* names[3] = {"alice", "bob", "carol"};
  for (int i = 0; i < 3; i++) {
    req.len = 0;
    put_u8(&req, 0); /* server-generated key */
    CHECK(hgb_call(fd, OP_ADD_PEER, &req, &resp) == STATUS_OK, "add_peer");
    cur = (hgb_cur){resp.buf, resp.len, 0};
    peers[i] = get_u32(&cur);
    uint8_t idlen = get_u8(&cur);
    printf("%s: peer %u, identity %u bytes\n", names[i], peers[i], idlen);
  }
  const char* scope = "quickstart";
  uint64_t now = 1000000;

  /* alice proposes to 3 voters, 600 s expiry, liveness YES */
  req.len = 0;
  put_u32(&req, peers[0]);
  put_str(&req, scope);
  put_u64(&req, now);
  put_str(&req, "genesis-upgrade");
  put_blob(&req, (const uint8_t*)"ship it", 7);
  put_u32(&req, 3);
  put_u64(&req, 600);
  put_u8(&req, 1);
  CHECK(hgb_call(fd, OP_CREATE_PROPOSAL, &req, &resp) == STATUS_OK,
        "create_proposal");
  cur = (hgb_cur){resp.buf, resp.len, 0};
  uint32_t pid = get_u32(&cur);
  printf("proposal %u created\n", pid);

  /* alice votes YES, then gossips the proposal (with her vote embedded) */
  req.len = 0;
  put_u32(&req, peers[0]);
  put_str(&req, scope);
  put_u32(&req, pid);
  put_u8(&req, 1);
  put_u64(&req, now + 1);
  CHECK(hgb_call(fd, OP_CAST_VOTE, &req, &resp) == STATUS_OK, "alice votes");

  req.len = 0;
  put_u32(&req, peers[0]);
  put_str(&req, scope);
  put_u32(&req, pid);
  CHECK(hgb_call(fd, OP_GET_PROPOSAL, &req, &resp) == STATUS_OK,
        "get_proposal");
  cur = (hgb_cur){resp.buf, resp.len, 0};
  uint32_t plen = get_u32(&cur);
  static uint8_t proposal[HGB_MAX_FRAME];
  CHECK(plen <= sizeof(proposal) && cur.pos + plen <= resp.len,
        "proposal length sane");
  memcpy(proposal, resp.buf + cur.pos, plen);

  for (int i = 1; i < 3; i++) { /* bob + carol receive the proposal */
    req.len = 0;
    put_u32(&req, peers[i]);
    put_str(&req, scope);
    put_u64(&req, now + 2);
    put_blob(&req, proposal, plen);
    CHECK(hgb_call(fd, OP_PROCESS_PROPOSAL, &req, &resp) == STATUS_OK,
          "process_proposal");
  }

  /* bob and carol vote YES. Each vote goes to the OTHER voter via the
   * scalar opcode; alice receives BOTH in one PROCESS_VOTES batch frame
   * (the embedder throughput path: one round trip for the whole batch). */
  static uint8_t votes[2][4096];
  uint32_t vlens[2];
  for (int voter = 1; voter < 3; voter++) {
    req.len = 0;
    put_u32(&req, peers[voter]);
    put_str(&req, scope);
    put_u32(&req, pid);
    put_u8(&req, 1);
    put_u64(&req, now + 3 + (uint64_t)voter);
    CHECK(hgb_call(fd, OP_CAST_VOTE, &req, &resp) == STATUS_OK, "cast_vote");
    cur = (hgb_cur){resp.buf, resp.len, 0};
    uint32_t vlen = get_u32(&cur);
    CHECK(vlen <= sizeof(votes[0]) && cur.pos + vlen <= resp.len,
          "vote length sane");
    memcpy(votes[voter - 1], resp.buf + cur.pos, vlen);
    vlens[voter - 1] = vlen;
    int other = voter == 1 ? 2 : 1;
    req.len = 0;
    put_u32(&req, peers[other]);
    put_str(&req, scope);
    put_u64(&req, now + 4 + (uint64_t)voter);
    put_blob(&req, votes[voter - 1], vlen);
    CHECK(hgb_call(fd, OP_PROCESS_VOTE, &req, &resp) == STATUS_OK,
          "process_vote");
  }
  req.len = 0;
  put_u32(&req, peers[0]);
  put_str(&req, scope);
  put_u64(&req, now + 6);
  put_u32(&req, 2);
  put_blob(&req, votes[0], vlens[0]);
  put_blob(&req, votes[1], vlens[1]);
  CHECK(hgb_call(fd, OP_PROCESS_VOTES, &req, &resp) == STATUS_OK,
        "process_votes batch");
  cur = (hgb_cur){resp.buf, resp.len, 0};
  CHECK(get_u32(&cur) == 2, "batch status count");
  for (int i = 0; i < 2; i++) {
    uint8_t st = get_u8(&cur);
    CHECK(st == 0 || st == 28, "batch vote accepted"); /* OK / ALREADY_REACHED */
  }

  /* every peer must now report YES and have emitted ConsensusReached */
  for (int i = 0; i < 3; i++) {
    req.len = 0;
    put_u32(&req, peers[i]);
    put_str(&req, scope);
    put_u32(&req, pid);
    CHECK(hgb_call(fd, OP_GET_RESULT, &req, &resp) == STATUS_OK, "get_result");
    cur = (hgb_cur){resp.buf, resp.len, 0};
    CHECK(get_u8(&cur) == RESULT_YES, "consensus must be YES");

    req.len = 0;
    put_u32(&req, peers[i]);
    CHECK(hgb_call(fd, OP_POLL_EVENTS, &req, &resp) == STATUS_OK,
          "poll_events");
    cur = (hgb_cur){resp.buf, resp.len, 0};
    uint32_t count = get_u32(&cur);
    int reached = 0;
    for (uint32_t e = 0; e < count; e++) {
      uint16_t slen = get_u16(&cur);
      cur.pos += slen; /* scope */
      uint8_t kind = get_u8(&cur);
      uint32_t epid = get_u32(&cur);
      uint8_t eresult = get_u8(&cur);
      get_u64(&cur); /* timestamp */
      if (kind == EVENT_REACHED && epid == pid && eresult) reached = 1;
    }
    CHECK(reached, "ConsensusReached(true) event");
    printf("%s: consensus YES, %u event(s)\n", names[i], count);
  }

  close(fd);
  printf("QUICKSTART PASS\n");
  return 0;
}
