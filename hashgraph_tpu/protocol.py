"""Scalar protocol kernels: hashing, vote building, validation, consensus math.

This is the host-side *oracle* layer: pure functions that reproduce the
reference's protocol semantics bit-exactly (reference: src/utils.rs). The
vectorized JAX kernels in :mod:`hashgraph_tpu.ops` are validated against these
functions case-by-case, and the integer threshold values shipped to the device
are computed here (in IEEE-754 double precision, matching Rust f64).
"""

from __future__ import annotations

import hashlib
import math
import sys
import uuid
from typing import TYPE_CHECKING, Iterable, Mapping

from .errors import (
    EmptySignature,
    EmptyVoteHash,
    EmptyVoteOwner,
    InvalidConsensusThreshold,
    InvalidExpectedVotersCount,
    InvalidTimeout,
    InvalidVoteHash,
    InvalidVoteSignature,
    ParentHashMismatch,
    ProposalExpired,
    ReceivedHashMismatch,
    TimestampOlderThanCreationTime,
    VoteExpired,
    VoteProposalIdMismatch,
)
from .wire import Proposal, Vote

if TYPE_CHECKING:
    from .signing import ConsensusSignatureScheme

_U32_MASK = 0xFFFFFFFF
_U32_MAX = 0xFFFFFFFF
_F64_EPSILON = sys.float_info.epsilon  # == Rust f64::EPSILON
_TWO_THIRDS = 2.0 / 3.0


def fold_u128_to_u32(n: int) -> int:
    """Fold a 128-bit value into 32 bits via XOR so every bit contributes
    (reference: src/utils.rs:19-21)."""
    return ((n >> 96) ^ (n >> 64) ^ (n >> 32) ^ n) & _U32_MASK


# Entropy seam for deterministic simulation: when set, generate_id draws
# its 128-bit value from this callable instead of uuid4. The seeded
# cluster simulator (hashgraph_tpu.sim) installs a scenario-rng source so
# every minted proposal/vote id — and therefore every signed byte and
# state fingerprint — is a pure function of the scenario seed. Production
# and tests leave it None (uuid4, the reference's behavior).
_id_entropy = None


def set_id_entropy(source) -> None:
    """Install (or with ``None`` remove) a ``() -> int`` 128-bit entropy
    source backing :func:`generate_id`. Simulation-only seam; not
    thread-scoped — callers own the install/restore discipline."""
    global _id_entropy
    _id_entropy = source


def generate_id() -> int:
    """Generate a unique 32-bit ID from a UUIDv4 (reference: src/utils.rs:27-30).

    Under :func:`set_id_entropy` the 128 bits come from the installed
    source instead, making id minting deterministic per scenario seed."""
    if _id_entropy is not None:
        return fold_u128_to_u32(_id_entropy() & ((1 << 128) - 1))
    return fold_u128_to_u32(uuid.uuid4().int)


def regenerate_until_unique(proposal, is_taken) -> int:
    """Regenerate a locally-generated proposal id while ``is_taken(pid)``.

    u32 ids birthday-collide at realistic populations (~1.2% per 10k-proposal
    wave); the reference's HashMap insert silently overwrites the incumbent
    session (reference: src/storage.rs:225-230). Regenerating before the
    fresh (vote-free) proposal becomes visible is semantically free and
    strictly safer than overwrite. Incoming network proposals must NOT be
    rewritten — their id is signed into vote chains — so their paths raise
    ProposalAlreadyExist instead. Returns the number of collisions resolved.
    """
    collisions = 0
    while is_taken(proposal.proposal_id):
        collisions += 1
        proposal.proposal_id = generate_id()
    return collisions


def compute_vote_hash(vote: Vote) -> bytes:
    """SHA-256 over the vote's identifying fields in a fixed byte order
    (reference: src/utils.rs:37-47). The signature field is excluded.
    One join + one hash call: the seven-update form paid ~2x in
    per-call dispatch on the validated ingest hot path (this runs once
    per vote there), for identical digests."""
    return hashlib.sha256(
        b"".join(
            (
                (vote.vote_id & _U32_MASK).to_bytes(4, "little"),
                vote.vote_owner,
                (vote.proposal_id & _U32_MASK).to_bytes(4, "little"),
                (vote.timestamp & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little"),
                b"\x01" if vote.vote else b"\x00",
                vote.parent_hash,
                vote.received_hash,
            )
        )
    ).digest()


def build_vote(
    proposal: Proposal,
    user_vote: bool,
    signer: "ConsensusSignatureScheme",
    now: int,
) -> Vote:
    """Create a new signed vote with hashgraph chain linking.

    ``received_hash`` links to the last vote in the proposal's list;
    ``parent_hash`` links to this voter's own most recent vote
    (reference: src/utils.rs:55-98).
    """
    voter_identity = signer.identity()

    if proposal.votes:
        latest_vote = proposal.votes[-1]
        own_last_vote = next(
            (v for v in reversed(proposal.votes) if v.vote_owner == voter_identity),
            None,
        )
        if own_last_vote is not None:
            parent_hash, received_hash = own_last_vote.vote_hash, latest_vote.vote_hash
        else:
            parent_hash, received_hash = b"", latest_vote.vote_hash
    else:
        parent_hash, received_hash = b"", b""

    vote = Vote(
        vote_id=generate_id(),
        vote_owner=bytes(voter_identity),
        proposal_id=proposal.proposal_id,
        timestamp=now,
        vote=user_vote,
        parent_hash=parent_hash,
        received_hash=received_hash,
        vote_hash=b"",
        signature=b"",
    )
    vote.vote_hash = compute_vote_hash(vote)
    vote.signature = signer.sign(vote.encode())
    return vote


# Sentinel: "compute the chain check here" (vs an injected device result).
COMPUTE_CHAIN = object()


def validate_proposal(
    proposal: Proposal,
    scheme,
    now: int,
    sig_verdicts=None,
    chain_error=COMPUTE_CHAIN,
    computed_hashes=None,
) -> None:
    """Validate a proposal and all its votes (reference: src/utils.rs:106-120).

    ``sig_verdicts``/``chain_error``/``computed_hashes`` optionally inject
    precomputed results from the batched paths (scheme.verify_batch / the
    device chain kernel / a prior ``compute_vote_hash`` pass):
    ``sig_verdicts`` is one verdict per vote in order; ``chain_error`` is
    None (chain valid) or the exception to raise at the chain-check
    position; ``computed_hashes`` is one digest per vote in order.
    Injection changes where the work happens, not the semantics.
    """
    validate_proposal_timestamp(proposal.expiration_timestamp, now)
    for i, vote in enumerate(proposal.votes):
        if vote.proposal_id != proposal.proposal_id:
            raise VoteProposalIdMismatch()
        validate_vote(
            vote,
            scheme,
            proposal.expiration_timestamp,
            proposal.timestamp,
            now,
            sig_verdict=sig_verdicts[i] if sig_verdicts is not None else None,
            computed_hash=(
                computed_hashes[i] if computed_hashes is not None else None
            ),
        )
    if chain_error is COMPUTE_CHAIN:
        validate_vote_chain(proposal.votes)
    elif chain_error is not None:
        raise chain_error


def validate_vote(
    vote: Vote,
    scheme,
    expiration_timestamp: int,
    creation_time: int,
    now: int,
    sig_verdict=None,
    computed_hash=None,
) -> None:
    """Validate a single vote: structure, hash, signature, replay, expiry.

    Check order matters and mirrors the reference exactly
    (reference: src/utils.rs:127-171).

    ``sig_verdict`` optionally injects a precomputed signature result from
    the scheme's batched verification (bool, or the ConsensusSchemeError
    ``verify`` would have raised) — the batch ingest path verifies all
    signatures in one native call, then replays this check sequence per
    vote. ``computed_hash`` optionally injects the caller's own
    ``compute_vote_hash(vote)`` result (the verify-cache prepass hashes
    every vote to build its keys; recomputing here would double the SHA
    work per vote). Semantics are identical to the inline computations.
    """
    if not vote.vote_owner:
        raise EmptyVoteOwner()
    if not vote.vote_hash:
        raise EmptyVoteHash()
    if not vote.signature:
        raise EmptySignature()

    expected_hash = (
        computed_hash if computed_hash is not None else compute_vote_hash(vote)
    )
    if vote.vote_hash != expected_hash:
        raise InvalidVoteHash()

    if sig_verdict is None:
        sig_verdict = scheme.verify(
            vote.vote_owner, vote.signing_payload(), vote.signature
        )
    if isinstance(sig_verdict, Exception):
        raise sig_verdict
    if not sig_verdict:
        raise InvalidVoteSignature()

    # Replay guard: the vote cannot predate the proposal
    # (reference: src/utils.rs:160-164).
    if vote.timestamp < creation_time:
        raise TimestampOlderThanCreationTime()

    if vote.timestamp > expiration_timestamp or now > expiration_timestamp:
        raise VoteExpired()


def validate_vote_chain(votes: list[Vote], start: int = 0) -> None:
    """Validate the hashgraph chain structure over an ordered vote list
    (reference: src/utils.rs:175-215).

    Rules:
    - a non-empty ``received_hash`` must equal the immediately previous vote's
      ``vote_hash``, with non-decreasing timestamps;
    - a non-empty ``parent_hash`` must resolve to an earlier-indexed vote by
      the same owner with timestamp <= this vote's.

    ``start`` restricts WHICH indices are checked (the hash map still spans
    the full list, preserving last-occurrence-wins): the engine's
    validated-chain watermark passes the accepted prefix + suffix with
    ``start`` at the watermark, so the suffix is checked against the full
    chain without re-checking links the prefix already passed. The rules
    themselves have exactly one home — this function.
    """
    if len(votes) <= 1:
        return

    hash_index: dict[bytes, tuple[bytes, int, int]] = {}
    for idx, vote in enumerate(votes):
        hash_index[vote.vote_hash] = (vote.vote_owner, vote.timestamp, idx)

    for idx in range(start, len(votes)):
        vote = votes[idx]
        if idx > 0 and vote.received_hash:
            prev_vote = votes[idx - 1]
            if vote.received_hash != prev_vote.vote_hash:
                raise ReceivedHashMismatch()
            if prev_vote.timestamp > vote.timestamp:
                raise ReceivedHashMismatch()

        if vote.parent_hash:
            entry = hash_index.get(vote.parent_hash)
            if entry is None:
                raise ParentHashMismatch()
            owner, ts, parent_idx = entry
            if not (owner == vote.vote_owner and ts <= vote.timestamp and parent_idx < idx):
                raise ParentHashMismatch()


def calculate_consensus_result(
    votes: Mapping[bytes, Vote] | Iterable[Vote],
    expected_voters: int,
    consensus_threshold: float,
    liveness_criteria_yes: bool,
    is_timeout: bool,
) -> bool | None:
    """THE decision kernel (scalar form). Reference: src/utils.rs:227-286.

    Accepts either an owner->Vote mapping or an iterable of votes (each owner
    assumed distinct). Returns True (YES), False (NO), or None (undecided).
    """
    if isinstance(votes, Mapping):
        vote_values = [v.vote for v in votes.values()]
    else:
        vote_values = [v.vote for v in votes]
    total_votes = len(vote_values)
    yes_votes = sum(1 for v in vote_values if v)
    return decide(
        yes_votes,
        total_votes,
        expected_voters,
        consensus_threshold,
        liveness_criteria_yes,
        is_timeout,
    )


def decide(
    yes_votes: int,
    total_votes: int,
    expected_voters: int,
    consensus_threshold: float,
    liveness_criteria_yes: bool,
    is_timeout: bool,
) -> bool | None:
    """Count-level form of the decision kernel — the exact scalar rules the
    vectorized device kernel must match (reference: src/utils.rs:227-286)."""
    no_votes = max(total_votes - yes_votes, 0)
    silent_votes = max(expected_voters - total_votes, 0)

    # n <= 2: unanimity rule (reference: src/utils.rs:239-244).
    if expected_voters <= 2:
        if total_votes < expected_voters:
            return None
        return yes_votes == expected_voters

    required_votes = calculate_required_votes(expected_voters, consensus_threshold)
    # At timeout, silent peers count toward quorum (reference: src/utils.rs:249-253).
    effective_total = expected_voters if is_timeout else total_votes
    if effective_total < required_votes:
        return None

    required_choice_votes = calculate_threshold_based_value(
        expected_voters, consensus_threshold
    )
    yes_weight = yes_votes + (silent_votes if liveness_criteria_yes else 0)
    no_weight = no_votes + (0 if liveness_criteria_yes else silent_votes)

    if yes_weight >= required_choice_votes and yes_weight > no_weight:
        return True
    if no_weight >= required_choice_votes and no_weight > yes_weight:
        return False
    if total_votes == expected_voters and yes_weight == no_weight:
        return liveness_criteria_yes
    return None


def calculate_required_votes(expected_voters: int, consensus_threshold: float) -> int:
    """Minimum participation to potentially reach consensus
    (reference: src/utils.rs:292-299)."""
    if expected_voters <= 2:
        return expected_voters
    return calculate_threshold_based_value(expected_voters, consensus_threshold)


def calculate_max_rounds(expected_voters: int, consensus_threshold: float) -> int:
    """Dynamic P2P round cap, ceil(2n/3) by default (reference: src/utils.rs:302-304)."""
    return calculate_threshold_based_value(expected_voters, consensus_threshold)


def calculate_threshold_based_value(expected_voters: int, consensus_threshold: float) -> int:
    """Precision-critical threshold math (reference: src/utils.rs:307-313).

    The default 2/3 threshold takes an exact integer path — ``ceil(2n/3)`` via
    integer division — to avoid f64 rounding; other thresholds use
    ``ceil(n * t)`` in f64 (Python floats are IEEE-754 doubles, matching Rust).
    The final ``as u32`` cast saturates like Rust's.
    """
    if abs(consensus_threshold - _TWO_THIRDS) < _F64_EPSILON:
        return (2 * expected_voters + 2) // 3  # div_ceil(2n, 3)
    value = math.ceil((expected_voters * 1.0) * consensus_threshold)
    if value < 0:
        return 0
    return min(int(value), _U32_MAX)


def validate_proposal_timestamp(expiration_timestamp: int, now: int) -> None:
    """Reject expired proposals (reference: src/utils.rs:320-328)."""
    if now >= expiration_timestamp:
        raise ProposalExpired()


def validate_threshold(threshold: float) -> None:
    """Threshold must be within [0.0, 1.0] (reference: src/utils.rs:331-336)."""
    if not (0.0 <= threshold <= 1.0):
        raise InvalidConsensusThreshold()


def validate_timeout(timeout_seconds: float) -> None:
    """Timeout must be > 0 (reference: src/utils.rs:339-344)."""
    if timeout_seconds <= 0:
        raise InvalidTimeout()


def validate_expected_voters_count(expected_voters_count: int) -> None:
    """expected_voters_count must be a valid nonzero u32
    (reference: src/utils.rs:347-354; values outside u32 range are
    unrepresentable in the reference's wire type)."""
    if not (1 <= expected_voters_count <= _U32_MAX):
        raise InvalidExpectedVotersCount()


def has_sufficient_votes(
    total_votes: int, expected_voters: int, consensus_threshold: float
) -> bool:
    """Quick participation check (reference: src/utils.rs:360-367)."""
    return total_votes >= calculate_required_votes(expected_voters, consensus_threshold)
