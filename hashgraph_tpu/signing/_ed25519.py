"""Pure-Python Ed25519 (RFC 8032) fallback for the native core.

Mirrors ``native/consensus_native.cpp``'s Ed25519 engine bit for bit on
the wire: same key derivation, same signatures, and the same *cofactored*
verification criterion — accept iff ``8·(s·B - h·A - R)`` is the
identity — so a native verifier and this fallback can never disagree on
any input (the batch randomized-linear-combination check is only sound
for the cofactored equation, and scalar-vs-batch verdict equivalence is
part of the scheme conformance contract). Decoding enforces RFC 8032
§5.1.3: non-canonical field encodings (y >= p) and a non-canonical
scalar (s >= L) are rejected.

Python-int arithmetic: correct and slow (~1k verifies/sec) — the native
runtime carries production traffic; this keeps the framework dependency
free and the conformance suite runnable everywhere.
"""

from __future__ import annotations

import hashlib

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

_B_Y = (4 * pow(5, P - 2, P)) % P
_B_X = 15112221349535400772501151409588531511454012693041857206046113283949847762202
# Extended coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z.
_BASE = (_B_X, _B_Y, 1, (_B_X * _B_Y) % P)
_IDENTITY = (0, 1, 1, 0)


def _add(p1, q):
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = t1 * (2 * D) % P * t2 % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _dbl(p1):
    return _add(p1, p1)


def _mul(point, k: int):
    acc = _IDENTITY
    while k:
        if k & 1:
            acc = _add(acc, point)
        point = _dbl(point)
        k >>= 1
    return acc


def _neg(p1):
    x, y, z, t = p1
    return ((-x) % P, y, z, (-t) % P)


def _is_identity(p1) -> bool:
    x, y, z, _ = p1
    return x % P == 0 and (y - z) % P == 0


def _encode(p1) -> bytes:
    x, y, z, _ = p1
    zi = pow(z, P - 2, P)
    x, y = x * zi % P, y * zi % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decode(s: bytes):
    """Decoded point, or None (RFC 8032 §5.1.3 rejections)."""
    if len(s) != 32:
        return None
    val = int.from_bytes(s, "little")
    sign = val >> 255
    y = val & ((1 << 255) - 1)
    if y >= P:
        return None  # non-canonical field encoding
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    x = u * pow(v, 3, P) % P * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    if v * x * x % P == u:
        pass
    elif v * x * x % P == (-u) % P:
        x = x * SQRT_M1 % P
    else:
        return None
    if x == 0 and sign:
        return None
    if (x & 1) != sign:
        x = P - x
    return (x, y, 1, x * y % P)


def _clamp(h: bytes) -> int:
    a = bytearray(h[:32])
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(a, "little")


def public_key(seed: bytes) -> bytes:
    a = _clamp(hashlib.sha512(seed).digest())
    return _encode(_mul(_BASE, a))


def sign(seed: bytes, message: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    prefix = h[32:]
    pub = _encode(_mul(_BASE, a))
    r = int.from_bytes(hashlib.sha512(prefix + message).digest(), "little") % L
    r_enc = _encode(_mul(_BASE, r))
    k = int.from_bytes(
        hashlib.sha512(r_enc + pub + message).digest(), "little"
    ) % L
    s = (r + k * a) % L
    return r_enc + int.to_bytes(s, 32, "little")


def verify(pub: bytes, message: bytes, signature: bytes) -> bool:
    if len(signature) != 64:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:
        return False  # non-canonical scalar (malleable form)
    a_pt = _decode(pub)
    if a_pt is None:
        return False
    r_pt = _decode(signature[:32])
    if r_pt is None:
        return False
    k = int.from_bytes(
        hashlib.sha512(signature[:32] + pub + message).digest(), "little"
    ) % L
    q = _add(_mul(_BASE, s), _neg(_add(_mul(a_pt, k), r_pt)))
    return _is_identity(_dbl(_dbl(_dbl(q))))
