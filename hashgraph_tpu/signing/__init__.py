"""Pluggable signature schemes for vote authentication.

Mirrors the reference's scheme abstraction (reference: src/signing.rs:46-74):
a scheme instance carries private state and produces signatures via
``identity()`` / ``sign()``; the scheme *type* verifies incoming signatures via
the class-level ``verify()``. All peers on a network must use the same scheme.

Signature verification always runs on the host — ECDSA does not map to the
MXU — and is batched across worker threads (or the native runtime) by the
ingest pipeline; only the vote tally/decision state lives on device.
"""

from __future__ import annotations

import abc

from ..errors import ConsensusSchemeError

__all__ = [
    "ConsensusSignatureScheme",
    "ConsensusSchemeError",
    "Ed25519ConsensusSigner",
    "Ed25519DeviceConsensusSigner",
    "EthereumConsensusSigner",
    "PendingVerdicts",
    "StubConsensusSigner",
]


class PendingVerdicts:
    """Handle for an in-flight :meth:`~ConsensusSignatureScheme.verify_batch`.

    ``collect()`` blocks until the batch resolves and returns exactly what
    the synchronous call would have: one ``bool | ConsensusSchemeError``
    per item. The default implementation simply defers the synchronous
    batch to collect time; schemes with a native worker pool (Ethereum,
    Ed25519) wrap an async submission instead, so the crypto runs on
    background threads — GIL-free — between submit and collect. Collect
    is idempotent; the first call does the waiting.
    """

    def __init__(self, collect_fn):
        self._collect_fn = collect_fn
        self._result = None

    def collect(self) -> "list[bool | ConsensusSchemeError]":
        if self._collect_fn is not None:
            self._result = self._collect_fn()
            self._collect_fn = None
        return self._result


class ConsensusSignatureScheme(abc.ABC):
    """A signature scheme the consensus service uses to sign and verify votes
    (reference: src/signing.rs:46-74)."""

    @abc.abstractmethod
    def identity(self) -> bytes:
        """Stable identity bytes for this signer (address / public key / id).
        Written into ``Vote.vote_owner`` when casting."""

    @abc.abstractmethod
    def sign(self, payload: bytes) -> bytes:
        """Sign ``payload`` and return raw signature bytes."""

    @classmethod
    @abc.abstractmethod
    def verify(cls, identity: bytes, payload: bytes, signature: bytes) -> bool:
        """Verify ``signature`` over ``payload`` against ``identity``.

        Returns True/False for well-formed inputs; raises
        :class:`ConsensusSchemeError` for malformed ones (wrong lengths etc.).
        """

    @classmethod
    def verify_batch(
        cls,
        identities: list[bytes],
        payloads: list[bytes],
        signatures: list[bytes],
    ) -> list[bool | ConsensusSchemeError]:
        """Bulk verification for the ingest pipeline: one entry per item,
        either the boolean verdict or the scheme error that ``verify`` would
        have raised. Default is a scalar loop; schemes with a native batched
        path (Ethereum) override this."""
        out: list[bool | ConsensusSchemeError] = []
        for identity, payload, signature in zip(identities, payloads, signatures):
            try:
                out.append(cls.verify(identity, payload, signature))
            except ConsensusSchemeError as exc:
                out.append(exc)
        return out

    @classmethod
    def verify_batch_submit(
        cls,
        identities: list[bytes],
        payloads: list[bytes],
        signatures: list[bytes],
    ) -> PendingVerdicts:
        """Asynchronous :meth:`verify_batch` for the pipelined ingest
        path: returns immediately; ``collect()`` yields the identical
        verdict list. The default defers the synchronous batch to
        collect time (observationally identical — verdicts are values,
        never raises), so every scheme is pipeline-compatible; schemes
        backed by the native worker pool override this to start the
        crypto NOW and overlap it with device work."""
        return PendingVerdicts(
            lambda: cls.verify_batch(identities, payloads, signatures)
        )


from .ed25519 import (  # noqa: E402
    Ed25519ConsensusSigner,
    Ed25519DeviceConsensusSigner,
)
from .ethereum import EthereumConsensusSigner  # noqa: E402
from .stub import StubConsensusSigner  # noqa: E402
