"""Pure-Python secp256k1 ECDSA with public-key recovery.

Provides deterministic (RFC 6979) signing and recovery-based verification —
the primitive the Ethereum scheme needs (65-byte r||s||v signatures, address
recovery). Jacobian-coordinate arithmetic with a fixed-base window table for
the generator keeps host signing fast enough for tests; bulk verification is
the job of the optional native runtime.
"""

from __future__ import annotations

import hashlib
import hmac

# Curve parameters (SEC 2).
P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

_HALF_N = N // 2

# Points are (X, Y, Z) Jacobian triples; Z == 0 encodes infinity.
_INF = (0, 1, 0)


def _jacobian_double(point):
    x1, y1, z1 = point
    if z1 == 0 or y1 == 0:
        return _INF
    a = (x1 * x1) % P
    b = (y1 * y1) % P
    c = (b * b) % P
    d = (2 * ((x1 + b) * (x1 + b) - a - c)) % P
    e = (3 * a) % P
    f = (e * e) % P
    x3 = (f - 2 * d) % P
    y3 = (e * (d - x3) - 8 * c) % P
    z3 = (2 * y1 * z1) % P
    return (x3, y3, z3)


def _jacobian_add(p1, p2):
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    if z1 == 0:
        return p2
    if z2 == 0:
        return p1
    z1z1 = (z1 * z1) % P
    z2z2 = (z2 * z2) % P
    u1 = (x1 * z2z2) % P
    u2 = (x2 * z1z1) % P
    s1 = (y1 * z2 * z2z2) % P
    s2 = (y2 * z1 * z1z1) % P
    if u1 == u2:
        if s1 != s2:
            return _INF
        return _jacobian_double(p1)
    h = (u2 - u1) % P
    i = (4 * h * h) % P
    j = (h * i) % P
    r = (2 * (s2 - s1)) % P
    v = (u1 * i) % P
    x3 = (r * r - j - 2 * v) % P
    y3 = (r * (v - x3) - 2 * s1 * j) % P
    z3 = (2 * h * z1 * z2) % P
    return (x3, y3, z3)


def _to_affine(point):
    x, y, z = point
    if z == 0:
        return None
    z_inv = pow(z, P - 2, P)
    z_inv2 = (z_inv * z_inv) % P
    return ((x * z_inv2) % P, (y * z_inv2 * z_inv) % P)


def _jacobian_mul(point, scalar):
    scalar %= N
    if scalar == 0:
        return _INF
    result = _INF
    addend = point
    while scalar:
        if scalar & 1:
            result = _jacobian_add(result, addend)
        addend = _jacobian_double(addend)
        scalar >>= 1
    return result


# Fixed-base 4-bit window table for G: _G_WINDOWS[w][d] = (16^w * d) * G.
_WINDOW_BITS = 4
_NUM_WINDOWS = 64


def _build_g_table():
    table = []
    base = (GX, GY, 1)
    for _ in range(_NUM_WINDOWS):
        row = [_INF]
        acc = _INF
        for _ in range(15):
            acc = _jacobian_add(acc, base)
            row.append(acc)
        table.append(row)
        for _ in range(_WINDOW_BITS):
            base = _jacobian_double(base)
    return table


_G_TABLE = _build_g_table()


def _g_mul(scalar):
    """Fixed-base multiply scalar * G using the precomputed window table."""
    scalar %= N
    result = _INF
    for w in range(_NUM_WINDOWS):
        digit = (scalar >> (w * _WINDOW_BITS)) & 0xF
        if digit:
            result = _jacobian_add(result, _G_TABLE[w][digit])
    return result


def pubkey_from_private(private_key: int) -> tuple[int, int]:
    """Affine public key point for a private scalar."""
    point = _to_affine(_g_mul(private_key))
    if point is None:
        raise ValueError("invalid private key")
    return point


def _rfc6979_k(msg_hash: bytes, private_key: int) -> int:
    """Deterministic nonce per RFC 6979 with HMAC-SHA256."""
    holen = 32
    x = private_key.to_bytes(32, "big")
    h1 = msg_hash
    v = b"\x01" * holen
    k = b"\x00" * holen
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        candidate = int.from_bytes(v, "big")
        if 1 <= candidate < N:
            return candidate
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign_recoverable(msg_hash: bytes, private_key: int) -> tuple[int, int, int]:
    """ECDSA-sign a 32-byte digest; returns (r, s, recovery_id) with low-s."""
    z = int.from_bytes(msg_hash, "big")
    while True:
        k = _rfc6979_k(msg_hash, private_key)
        point = _to_affine(_g_mul(k))
        if point is None:
            continue
        rx, ry = point
        r = rx % N
        if r == 0:
            continue
        s = (pow(k, N - 2, N) * (z + r * private_key)) % N
        if s == 0:
            continue
        recovery_id = (ry & 1) | (2 if rx >= N else 0)
        if s > _HALF_N:
            s = N - s
            recovery_id ^= 1
        return r, s, recovery_id


def recover_pubkey(msg_hash: bytes, r: int, s: int, recovery_id: int) -> tuple[int, int] | None:
    """Recover the affine public key from a recoverable signature, or None."""
    if not (1 <= r < N and 1 <= s < N) or not (0 <= recovery_id <= 3):
        return None
    x = r + (recovery_id >> 1) * N
    if x >= P:
        return None
    # Lift x to a curve point: y^2 = x^3 + 7.
    alpha = (pow(x, 3, P) + 7) % P
    y = pow(alpha, (P + 1) // 4, P)
    if (y * y) % P != alpha:
        return None
    if (y & 1) != (recovery_id & 1):
        y = P - y
    z = int.from_bytes(msg_hash, "big")
    r_inv = pow(r, N - 2, N)
    # Q = r^-1 (s*R - z*G)
    sr = _jacobian_mul((x, y, 1), s)
    zg = _g_mul((-z) % N)
    q = _jacobian_mul(_jacobian_add(sr, zg), r_inv)
    return _to_affine(q)
