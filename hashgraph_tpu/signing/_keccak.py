"""Pure-Python Keccak-256 (the pre-NIST padding Ethereum uses).

Implemented from the Keccak specification; used for Ethereum address
derivation and EIP-191 message hashing. Distinct from SHA3-256 only in the
domain-separation/padding byte (0x01 here vs 0x06 for SHA3).

Host-side only; the TPU path never hashes on device. The optional native
runtime (hashgraph_tpu.native) provides a batched C++ implementation.
"""

from __future__ import annotations

_MASK64 = (1 << 64) - 1

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

# Rotation offsets r[x][y] laid out per lane index (x + 5*y).
_ROTATIONS = (
    0, 1, 62, 28, 27,
    36, 44, 6, 55, 20,
    3, 10, 43, 25, 39,
    41, 45, 15, 21, 8,
    18, 2, 61, 56, 14,
)


def _rotl(value: int, shift: int) -> int:
    if shift == 0:
        return value
    return ((value << shift) | (value >> (64 - shift))) & _MASK64


def _keccak_f1600(lanes: list[int]) -> None:
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [
            lanes[x] ^ lanes[x + 5] ^ lanes[x + 10] ^ lanes[x + 15] ^ lanes[x + 20]
            for x in range(5)
        ]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            dx = d[x]
            for y in range(0, 25, 5):
                lanes[x + y] ^= dx
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                # B[y, 2x+3y] = rot(A[x, y])
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(
                    lanes[x + 5 * y], _ROTATIONS[x + 5 * y]
                )
        # chi
        for y in range(0, 25, 5):
            row = b[y : y + 5]
            for x in range(5):
                lanes[x + y] = row[x] ^ ((~row[(x + 1) % 5]) & row[(x + 2) % 5])
        # iota
        lanes[0] ^= rc


def keccak256(data: bytes) -> bytes:
    """Keccak-256 digest of ``data`` (32 bytes)."""
    rate = 136  # bytes, for 256-bit output
    lanes = [0] * 25

    # Absorb full blocks.
    offset = 0
    length = len(data)
    while length - offset >= rate:
        block = data[offset : offset + rate]
        for i in range(rate // 8):
            lanes[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        _keccak_f1600(lanes)
        offset += rate

    # Pad final block: Keccak pad10*1 with domain byte 0x01.
    block = bytearray(rate)
    tail = data[offset:]
    block[: len(tail)] = tail
    block[len(tail)] ^= 0x01
    block[rate - 1] ^= 0x80
    for i in range(rate // 8):
        lanes[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
    _keccak_f1600(lanes)

    out = bytearray()
    for i in range(4):  # 4 lanes = 32 bytes
        out += lanes[i].to_bytes(8, "little")
    return bytes(out)
