"""Ed25519 signing scheme: the batch-verification-native alternative.

A second production scheme alongside :class:`EthereumConsensusSigner`,
added under the reference's pluggable-scheme contract (reference:
src/signing.rs:46-74) — identity is the 32-byte public key, signatures
are 64-byte ``R || S`` over the raw payload (RFC 8032, no EIP-191-style
envelope; the payload is already the canonical signed-fields encoding).

Why a second scheme: recover-and-compare ECDSA verification is
inherently scalar — each signature costs a full double-scalar multiply
and there is no sound way to merge checks — while Ed25519 verification
equations combine algebraically: a random linear combination verifies a
whole batch with one multi-scalar multiply (Bernstein et al., "Batch
binary Edwards" lineage), which is what `bench.py validated-sweep`
exercises. The native core (``native/consensus_native.cpp``) implements
that batch path over the persistent verify pool; this module falls back
to the pure-Python RFC 8032 code in :mod:`._ed25519` when the native
runtime is absent.

Verification is *cofactored* (accept iff ``8·(s·B - h·A - R)`` is the
identity) with RFC 8032 canonical-encoding rejections — the only
criterion under which scalar and batch verdicts provably agree on every
input. See PARITY.md.
"""

from __future__ import annotations

import os
import secrets

from ..errors import ConsensusSchemeError
from .. import native
from . import ConsensusSignatureScheme, PendingVerdicts
from . import _ed25519 as _py

ED25519_SIGNATURE_LENGTH = 64
ED25519_IDENTITY_LENGTH = 32

# Backend selector for batch verification: instances resolve
# device_verify=None against this env at construction. "1"/"on"/"true"
# routes verify_batch/_submit through hashgraph_tpu.crypto_device (the
# JAX pipeline — TPU/GPU/CPU alike); anything else keeps the native
# pool / pure-Python host path. The env seam means a bridge server, the
# sim cluster, and the engine's ingest_wire_columnar crypto prepass all
# reach the device path with zero caller changes.
DEVICE_VERIFY_ENV = "HASHGRAPH_TPU_DEVICE_VERIFY"


def _device_verify_default() -> bool:
    return os.environ.get(DEVICE_VERIFY_ENV, "").lower() in ("1", "on", "true")


class Ed25519ConsensusSigner(ConsensusSignatureScheme):
    """Holds a 32-byte seed; identity is the derived public key.

    ``device_verify`` selects the batch-verification backend:

    - ``None`` (default): consult ``HASHGRAPH_TPU_DEVICE_VERIFY``;
    - ``True``: the instance is constructed as
      :class:`Ed25519DeviceConsensusSigner`, whose class-level batch
      verifiers run the JAX device pipeline (engines resolve scheme
      methods through ``type(signer)``, so the choice rides the
      instance into every ``verify_batch_submit`` call site, and the
      per-scheme metric label / admission-cache namespace pick up the
      distinct subclass identity);
    - ``False``: force the host path even when the env is set.

    Signing and scalar ``verify`` are host-side in every case; the
    backends differ only in who executes the batch equation, never in
    verdicts (PARITY.md "Device-resident verification").
    """

    def __new__(cls, seed: bytes = b"", device_verify: "bool | None" = None):
        if cls is Ed25519ConsensusSigner:
            enabled = (
                _device_verify_default()
                if device_verify is None
                else bool(device_verify)
            )
            if enabled and _device_backend_usable():
                cls = Ed25519DeviceConsensusSigner
        return super().__new__(cls)

    def __init__(self, seed: bytes, device_verify: "bool | None" = None):
        del device_verify  # consumed by __new__ (class identity carries it)
        if len(seed) != 32:
            raise ValueError("ed25519 seed must be 32 bytes")
        self._seed = bytes(seed)
        pub = native.ed25519_public(self._seed)
        self._public = pub if pub is not None else _py.public_key(self._seed)

    @classmethod
    def random(cls) -> "Ed25519ConsensusSigner":
        return cls(secrets.token_bytes(32))

    def identity(self) -> bytes:
        return self._public

    def private_key_bytes(self) -> bytes:
        """Expose the seed for interop/tests (inner() equivalent)."""
        return self._seed

    def sign(self, payload: bytes) -> bytes:
        signature = native.ed25519_sign(self._seed, payload)
        if signature is not None:
            return signature
        return _py.sign(self._seed, payload)

    @classmethod
    def _check_lengths(cls, identity: bytes, signature: bytes) -> None:
        if len(signature) != ED25519_SIGNATURE_LENGTH:
            raise ConsensusSchemeError.verify(
                f"expected {ED25519_SIGNATURE_LENGTH}-byte signature, "
                f"got {len(signature)}"
            )
        if len(identity) != ED25519_IDENTITY_LENGTH:
            raise ConsensusSchemeError.verify(
                f"expected {ED25519_IDENTITY_LENGTH}-byte public key, "
                f"got {len(identity)}"
            )

    @classmethod
    def verify(cls, identity: bytes, payload: bytes, signature: bytes) -> bool:
        # Wrong lengths are scheme errors (the Ethereum convention);
        # length-valid but undecodable points and non-canonical scalars
        # are False — on the wire they are indistinguishable from forged
        # signatures, and the batch path reports them the same way.
        cls._check_lengths(identity, signature)
        verdict = native.ed25519_verify(
            bytes(identity), payload, bytes(signature)
        )
        if verdict is not None:
            return verdict == 1
        return _py.verify(bytes(identity), payload, bytes(signature))

    @classmethod
    def _precheck(
        cls,
        identities: list[bytes],
        payloads: list[bytes],
        signatures: list[bytes],
    ) -> "tuple[list, list[int]]":
        """Length gauntlet shared by the sync and async batch paths:
        returns (out list with scheme errors pre-filled, well-formed
        row indices). zip() truncation keeps the ragged-input contract."""
        out: list = []
        well_formed: list[int] = []
        for i, (identity, _payload, signature) in enumerate(
            zip(identities, payloads, signatures)
        ):
            try:
                cls._check_lengths(identity, signature)
            except ConsensusSchemeError as exc:
                out.append(exc)
                continue
            out.append(False)  # placeholder
            well_formed.append(i)
        return out, well_formed

    @classmethod
    def verify_batch(
        cls,
        identities: list[bytes],
        payloads: list[bytes],
        signatures: list[bytes],
    ) -> list:
        """Native batched verification: chunks verify as ONE randomized
        linear combination (a single multi-scalar multiply) on the
        persistent worker pool; falls back to the scalar loop without
        the native runtime."""
        out, well_formed = cls._precheck(identities, payloads, signatures)
        if not well_formed:
            return out
        results = native.ed25519_verify_batch(
            [bytes(identities[i]) for i in well_formed],
            [payloads[i] for i in well_formed],
            [bytes(signatures[i]) for i in well_formed],
        )
        if results is None:
            for i in well_formed:
                out[i] = _py.verify(
                    bytes(identities[i]), payloads[i], bytes(signatures[i])
                )
            return out
        for i, code in zip(well_formed, results):
            out[i] = bool(code == 1)
        return out

    @classmethod
    def verify_batch_submit(
        cls,
        identities: list[bytes],
        payloads: list[bytes],
        signatures: list[bytes],
    ) -> PendingVerdicts:
        """Start the batch on the native pool NOW; collect() fans the
        codes out exactly as :meth:`verify_batch` would. Without the
        native runtime this degrades to the deferred-sync default."""
        out, well_formed = cls._precheck(identities, payloads, signatures)
        job = (
            native.ed25519_verify_batch_submit(
                [bytes(identities[i]) for i in well_formed],
                [payloads[i] for i in well_formed],
                [bytes(signatures[i]) for i in well_formed],
            )
            if well_formed
            else None
        )
        if well_formed and job is None:
            return super().verify_batch_submit(identities, payloads, signatures)

        def _collect():
            if job is not None:
                for i, code in zip(well_formed, job.collect()):
                    out[i] = bool(code == 1)
            return out

        return PendingVerdicts(_collect)


def _device_backend_usable() -> bool:
    """Probe (memoized in crypto_device) that the JAX pipeline can run;
    selection quietly degrades to the host path when it cannot, so
    setting the env on a jax-less box never breaks verification."""
    try:
        from .. import crypto_device

        return crypto_device.available()
    except Exception:
        return False


class Ed25519DeviceConsensusSigner(Ed25519ConsensusSigner):
    """Ed25519 with device-resident batch verification.

    Same wire format, same seed handling, same scalar ``verify``, same
    *cofactored* acceptance criterion — a backend, not a divergence:
    ``verify_batch``/``verify_batch_submit`` run the whole batch
    equation (decompression, SHA-512 challenge hashes, the randomized
    Straus MSM) on the JAX backend via :mod:`hashgraph_tpu.crypto_device`,
    with host blame for exact per-item verdicts when the combination
    fails. Constructed via ``Ed25519ConsensusSigner(seed,
    device_verify=True)`` or the ``HASHGRAPH_TPU_DEVICE_VERIFY`` env;
    the distinct class name labels the per-scheme verified-signatures
    counter and namespaces the admission cache."""

    @classmethod
    def device_phase_seconds(cls) -> "dict[str, float]":
        """Per-phase wall seconds of the backend's most recent batch
        (decompress / hash / msm / fallback / total) — the engine's
        wire-path stage attribution and the bench's timing block both
        read this instead of re-instrumenting the pipeline."""
        from .. import crypto_device

        return crypto_device.last_phase_seconds()

    @classmethod
    def verify_batch(
        cls,
        identities: "list[bytes]",
        payloads: "list[bytes]",
        signatures: "list[bytes]",
    ) -> list:
        return cls.verify_batch_submit(
            identities, payloads, signatures
        ).collect()

    @classmethod
    def verify_batch_submit(
        cls,
        identities: "list[bytes]",
        payloads: "list[bytes]",
        signatures: "list[bytes]",
    ) -> PendingVerdicts:
        """Dispatch decompression + challenge hashing to the device NOW;
        ``collect()`` finishes the MSM and fans out verdicts (falling
        back to the host verifiers for per-item blame on batch
        failure). Scheme errors and ragged truncation are handled by
        the shared precheck, byte-compatible with the host path."""
        from .. import crypto_device

        out, well_formed = cls._precheck(identities, payloads, signatures)
        if not well_formed:
            return PendingVerdicts(lambda: out)
        collect_device = crypto_device.verify_batch_begin(
            [bytes(identities[i]) for i in well_formed],
            [payloads[i] for i in well_formed],
            [bytes(signatures[i]) for i in well_formed],
        )

        def _collect():
            for i, verdict in zip(well_formed, collect_device()):
                out[i] = bool(verdict)
            return out

        return PendingVerdicts(_collect)
