"""ECDSA-secp256k1 signing scheme with Ethereum conventions.

Matches the reference's default scheme (reference: src/signing/ethereum.rs):
identity is the 20-byte Ethereum address, signatures are 65-byte recoverable
``r || s || v`` over the EIP-191 prefixed message, and verification recovers
the address and compares. Implemented on pure-Python secp256k1 + Keccak so the
framework has zero non-baked dependencies; the native runtime accelerates bulk
verification.
"""

from __future__ import annotations

import secrets

from ..errors import ConsensusSchemeError
from .. import native
from . import ConsensusSignatureScheme, PendingVerdicts
from ._keccak import keccak256
from ._secp256k1 import N, pubkey_from_private, recover_pubkey, sign_recoverable

ETHEREUM_SIGNATURE_LENGTH = 65
ETHEREUM_ADDRESS_LENGTH = 20


def eip191_hash(payload: bytes) -> bytes:
    """Keccak-256 of the EIP-191 personal-message envelope.

    The reference signs via alloy's ``sign_message_sync`` which applies the
    same ``"\\x19Ethereum Signed Message:\\n" + len`` prefix
    (reference: src/signing/ethereum.rs:58-64).
    """
    prefix = b"\x19Ethereum Signed Message:\n" + str(len(payload)).encode("ascii")
    return keccak256(prefix + payload)


def address_from_pubkey(pubkey: tuple[int, int]) -> bytes:
    """Last 20 bytes of keccak256(uncompressed public key sans 0x04 prefix)."""
    x, y = pubkey
    return keccak256(x.to_bytes(32, "big") + y.to_bytes(32, "big"))[-20:]


class EthereumConsensusSigner(ConsensusSignatureScheme):
    """Holds a 32-byte private key; identity is the derived 20-byte address."""

    def __init__(self, private_key: bytes | int):
        if isinstance(private_key, bytes):
            if len(private_key) != 32:
                raise ValueError("private key must be 32 bytes")
            private_key = int.from_bytes(private_key, "big")
        if not (1 <= private_key < N):
            raise ValueError("private key out of range for secp256k1")
        self._private_key = private_key
        self._address = address_from_pubkey(pubkey_from_private(private_key))

    @classmethod
    def random(cls) -> "EthereumConsensusSigner":
        """Generate a fresh random signer (PrivateKeySigner::random equivalent)."""
        while True:
            candidate = secrets.randbits(256)
            if 1 <= candidate < N:
                return cls(candidate)

    def identity(self) -> bytes:
        return self._address

    def private_key_bytes(self) -> bytes:
        """Expose key material for interop/tests (inner() equivalent)."""
        return self._private_key.to_bytes(32, "big")

    def sign(self, payload: bytes) -> bytes:
        signature = native.eth_sign(self.private_key_bytes(), payload)
        if signature is not None:
            return signature
        try:
            r, s, v = sign_recoverable(eip191_hash(payload), self._private_key)
        except Exception as exc:  # pragma: no cover - curve math never fails in practice
            raise ConsensusSchemeError.sign(str(exc)) from exc
        return r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([27 + (v & 1)])

    @classmethod
    def verify(cls, identity: bytes, payload: bytes, signature: bytes) -> bool:
        # Length checks raise scheme errors, mirroring the reference
        # (reference: src/signing/ethereum.rs:71-82).
        if len(signature) != ETHEREUM_SIGNATURE_LENGTH:
            raise ConsensusSchemeError.verify(
                f"expected {ETHEREUM_SIGNATURE_LENGTH}-byte signature, got {len(signature)}"
            )
        if len(identity) != ETHEREUM_ADDRESS_LENGTH:
            raise ConsensusSchemeError.verify(
                f"expected {ETHEREUM_ADDRESS_LENGTH}-byte address, got {len(identity)}"
            )

        r = int.from_bytes(signature[0:32], "big")
        s = int.from_bytes(signature[32:64], "big")
        v = signature[64]
        if v >= 27:
            v -= 27
        if v > 1:
            raise ConsensusSchemeError.verify(f"invalid recovery id byte: {signature[64]}")

        verdict = native.eth_verify(bytes(identity), payload, signature)
        if verdict is not None:
            if verdict == -2:
                raise ConsensusSchemeError.verify("signature recovery failed")
            return verdict == 1

        pubkey = recover_pubkey(eip191_hash(payload), r, s, v)
        if pubkey is None:
            raise ConsensusSchemeError.verify("signature recovery failed")
        return address_from_pubkey(pubkey) == bytes(identity)

    @classmethod
    def _precheck(
        cls,
        identities: list[bytes],
        payloads: list[bytes],
        signatures: list[bytes],
    ) -> "tuple[list, list[int]]":
        """Length gauntlet shared by the sync and async batch paths:
        returns (out list with scheme errors pre-filled, well-formed row
        indices). zip() truncation keeps the base-class contract for
        ragged inputs."""
        well_formed: list[int] = []
        out: list[bool | ConsensusSchemeError] = []
        for i, (identity, _payload, signature) in enumerate(
            zip(identities, payloads, signatures)
        ):
            if len(signature) != ETHEREUM_SIGNATURE_LENGTH:
                out.append(
                    ConsensusSchemeError.verify(
                        f"expected {ETHEREUM_SIGNATURE_LENGTH}-byte signature, "
                        f"got {len(signature)}"
                    )
                )
            elif len(identity) != ETHEREUM_ADDRESS_LENGTH:
                out.append(
                    ConsensusSchemeError.verify(
                        f"expected {ETHEREUM_ADDRESS_LENGTH}-byte address, "
                        f"got {len(identity)}"
                    )
                )
            else:
                out.append(False)  # placeholder
                well_formed.append(i)
        return out, well_formed

    @classmethod
    def verify_batch(
        cls,
        identities: list[bytes],
        payloads: list[bytes],
        signatures: list[bytes],
    ) -> list[bool | ConsensusSchemeError]:
        """Native threaded batch verification (GIL released for the whole
        batch); falls back to the scalar loop without the native runtime."""
        out, well_formed = cls._precheck(identities, payloads, signatures)
        if not well_formed:
            return out
        results = native.eth_verify_batch(
            [bytes(identities[i]) for i in well_formed],
            [payloads[i] for i in well_formed],
            [signatures[i] for i in well_formed],
        )
        if results is None:
            for i in well_formed:
                try:
                    out[i] = cls.verify(identities[i], payloads[i], signatures[i])
                except ConsensusSchemeError as exc:
                    out[i] = exc
            return out
        cls._fan_out_codes(out, well_formed, results, signatures)
        return out

    @staticmethod
    def _fan_out_codes(out, well_formed, results, signatures) -> None:
        """Map native result codes onto the verdict list (shared by the
        sync and async batch paths)."""
        for i, code in zip(well_formed, results):
            if code == 1:
                out[i] = True
            elif code == 0:
                out[i] = False
            elif code == 254:
                out[i] = ConsensusSchemeError.verify("signature recovery failed")
            else:
                out[i] = ConsensusSchemeError.verify(
                    f"invalid recovery id byte: {signatures[i][64]}"
                )

    @classmethod
    def verify_batch_submit(
        cls,
        identities: list[bytes],
        payloads: list[bytes],
        signatures: list[bytes],
    ) -> PendingVerdicts:
        """Async :meth:`verify_batch` on the persistent native pool:
        returns immediately, the ECDSA runs GIL-free on worker threads,
        and ``collect()`` fans out the identical verdicts. Degrades to
        the deferred-sync default without the native runtime."""
        out, well_formed = cls._precheck(identities, payloads, signatures)
        job = (
            native.eth_verify_batch_submit(
                [bytes(identities[i]) for i in well_formed],
                [payloads[i] for i in well_formed],
                [signatures[i] for i in well_formed],
            )
            if well_formed
            else None
        )
        if well_formed and job is None:
            return super().verify_batch_submit(identities, payloads, signatures)

        def _collect():
            if job is not None:
                cls._fan_out_codes(out, well_formed, job.collect(), signatures)
            return out

        return PendingVerdicts(_collect)
