"""Deterministic hash-based stub scheme for tests and benchmarks.

Equivalent in role to the reference's test ``StubSigner``
(reference: tests/custom_scheme_tests.rs:32-72): the "signature" is
SHA-256(identity || payload), so any holder of the identity bytes can produce
it. Proves the service is scheme-agnostic; also used by throughput benchmarks
where ECDSA cost would measure the signer, not the engine.
"""

from __future__ import annotations

import hashlib

from . import ConsensusSignatureScheme


class StubConsensusSigner(ConsensusSignatureScheme):
    def __init__(self, identity: bytes):
        if not identity:
            raise ValueError("stub identity must be non-empty")
        self._identity = bytes(identity)

    def identity(self) -> bytes:
        return self._identity

    def sign(self, payload: bytes) -> bytes:
        return hashlib.sha256(self._identity + payload).digest()

    @classmethod
    def verify(cls, identity: bytes, payload: bytes, signature: bytes) -> bool:
        return hashlib.sha256(bytes(identity) + payload).digest() == signature
