"""Test helpers exported by the library itself
(reference: src/test_utils.rs:5-10).

The reference exposes a wall-clock helper for doctests and downstream test
suites; everything else in this framework takes caller-supplied ``now``
values, so tests can (and should) drive time arithmetically instead.
"""

from __future__ import annotations

import time

__all__ = ["now_ts"]


def now_ts() -> int:
    """Current Unix timestamp in seconds (reference: src/test_utils.rs:5-10)."""
    return int(time.time())
