"""Vectorized hashgraph vote-chain validation.

The vote chain is this framework's "long context": an append-only
hash-linked sequence per proposal (reference: src/utils.rs:175-215). The
scalar rules only reference index ``i-1`` (received link) and one
hash-indexed earlier vote (parent link), so validation needs no sequential
scan — it becomes a shifted row-compare plus an O(V²) equality matrix, both
embarrassingly parallel and vmappable over a proposal batch (SURVEY §5
long-context row).

Exact reference semantics reproduced:
- received rule (``idx > 0`` only — index 0 is never checked): a non-empty
  ``received_hash`` must equal the previous vote's ``vote_hash`` and the
  previous timestamp must be ≤ this one's (utils.rs:188-198);
- parent rule: a non-empty ``parent_hash`` is looked up in a hash→index map
  built with LAST-occurrence-wins over the full list (utils.rs:181-184);
  that single entry must be an earlier index, same owner, timestamp ≤
  (utils.rs:200-211) — existence of *some* matching earlier vote is NOT
  sufficient if a later vote shadows it in the map;
- fail-fast order: first offending index wins; within one index the
  received check precedes the parent check.

Device encoding (host packs via :func:`pack_chain`):
- hashes → ``int32[V, 9]``: 8 little-endian 4-byte words + a length column
  (length participates in equality; hashes over 32 bytes are canonicalised
  through SHA-256 first, preserving equality with cryptographic certainty);
- u64 timestamps → two bias-encoded int32 columns (hi, lo) compared
  lexicographically (TPU kernels run without x64);
- owners → dict-encoded int32 ids (exact bytes equality, no hash collisions).
"""

from __future__ import annotations

import hashlib

import numpy as np

import jax
import jax.numpy as jnp

from ..errors import StatusCode
from ..wire import Vote

__all__ = [
    "pack_chain",
    "chain_body",
    "chain_kernel",
    "chain_kernel_batch",
    "first_chain_error",
]

HASH_WORDS = 8
_BIAS = np.int64(-0x80000000)  # maps u32 order onto i32 order

_OK = int(StatusCode.OK)
_RECV = int(StatusCode.RECEIVED_HASH_MISMATCH)
_PARENT = int(StatusCode.PARENT_HASH_MISMATCH)


def _pack_hashes(hashes: list[bytes]) -> np.ndarray:
    """[V] bytes -> int32[V, 9] (8 words + length; empty = all-zero row)."""
    v = len(hashes)
    out = np.zeros((v, HASH_WORDS + 1), np.int32)
    for i, h in enumerate(hashes):
        if len(h) > 32:
            h = hashlib.sha256(h).digest()
            length = 33  # sentinel: "canonicalised long hash"
        else:
            length = len(h)
        padded = h + b"\x00" * (32 - len(h))
        out[i, :HASH_WORDS] = np.frombuffer(padded, np.uint32).view(np.int32)
        out[i, HASH_WORDS] = length
    return out


def _pack_ts(ts: list[int]) -> np.ndarray:
    """u64 timestamps -> bias-encoded int32[V, 2] (hi, lo), order-preserving
    under lexicographic signed comparison."""
    arr = np.array(ts, np.uint64)
    hi = ((arr >> np.uint64(32)).astype(np.int64) + _BIAS).astype(np.int32)
    lo = ((arr & np.uint64(0xFFFFFFFF)).astype(np.int64) + _BIAS).astype(np.int32)
    return np.stack([hi, lo], axis=1)


def pack_chain(
    votes: list[Vote], pad_to: int | None = None
) -> dict[str, np.ndarray]:
    """Encode a proposal's ordered vote list for the device kernel."""
    v = len(votes)
    width = pad_to if pad_to is not None else v
    if width < v:
        raise ValueError("pad_to smaller than vote count")

    owners: dict[bytes, int] = {}
    owner_ids = np.zeros(width, np.int32)
    for i, vote in enumerate(votes):
        owner_ids[i] = owners.setdefault(vote.vote_owner, len(owners))

    def field(hashes: list[bytes]) -> np.ndarray:
        packed = _pack_hashes(hashes)
        out = np.zeros((width, HASH_WORDS + 1), np.int32)
        out[:v] = packed
        return out

    ts = np.zeros((width, 2), np.int32)
    ts[:v] = _pack_ts([vote.timestamp for vote in votes])
    valid = np.zeros(width, bool)
    valid[:v] = True
    return dict(
        vote_hash=field([vote.vote_hash for vote in votes]),
        received_hash=field([vote.received_hash for vote in votes]),
        parent_hash=field([vote.parent_hash for vote in votes]),
        owner=owner_ids,
        ts=ts,
        valid=valid,
    )


def _ts_le(a, b):
    """Lexicographic ≤ over bias-encoded (hi, lo) int32 pairs."""
    return (a[..., 0] < b[..., 0]) | (
        (a[..., 0] == b[..., 0]) & (a[..., 1] <= b[..., 1])
    )


def chain_body(vote_hash, received_hash, parent_hash, owner, ts, valid):
    """Per-vote chain statuses for one proposal's ordered votes.

    Args (device arrays, V = padded vote count):
      vote_hash / received_hash / parent_hash: int32[V, 9]
      owner: int32[V] dict-encoded owner ids
      ts: int32[V, 2] bias-encoded timestamps
      valid: bool[V] real-vote mask (pad rows always pass)

    Returns int32[V]: OK / RECEIVED_HASH_MISMATCH / PARENT_HASH_MISMATCH per
    vote, with the reference's intra-vote precedence (received first).
    """
    v = vote_hash.shape[0]
    idx = jnp.arange(v)
    empty_recv = received_hash[:, HASH_WORDS] == 0
    empty_parent = parent_hash[:, HASH_WORDS] == 0

    # Received rule: row i vs row i-1 (row 0 exempt).
    prev_hash = jnp.roll(vote_hash, 1, axis=0)
    prev_ts = jnp.roll(ts, 1, axis=0)
    recv_eq = jnp.all(received_hash == prev_hash, axis=1)
    recv_ok = (
        (idx == 0)
        | empty_recv
        | (recv_eq & _ts_le(prev_ts, ts))
    )

    # Parent rule: last-occurrence hash index. eq[i, j] = parent i matches
    # vote-hash j (pad rows excluded); j* = max matching j.
    eq = jnp.all(
        parent_hash[:, None, :] == vote_hash[None, :, :], axis=2
    ) & valid[None, :]
    j_star = jnp.max(jnp.where(eq, idx[None, :], -1), axis=1)
    found = j_star >= 0
    j_clip = jnp.maximum(j_star, 0)
    parent_ok = empty_parent | (
        found
        & (jnp.take(owner, j_clip) == owner)
        & _ts_le(jnp.take(ts, j_clip, axis=0), ts)
        & (j_star < idx)
    )

    status = jnp.where(
        ~recv_ok,
        _RECV,
        jnp.where(~parent_ok, _PARENT, _OK),
    ).astype(jnp.int32)
    return jnp.where(valid, status, _OK)


chain_kernel = jax.jit(chain_body)
# Batched over a [B, V, ...] proposal axis — config-5-style bulk replay.
chain_kernel_batch = jax.jit(jax.vmap(chain_body))


def first_chain_error(statuses: np.ndarray) -> int:
    """Reduce per-vote statuses to the reference's fail-fast result: the
    status of the first offending vote, or OK. Lists of length ≤ 1 are
    trivially valid (utils.rs:176-178) — callers skip the kernel for those.
    """
    statuses = np.asarray(statuses)
    bad = np.nonzero(statuses != _OK)[0]
    return int(statuses[bad[0]]) if bad.size else _OK
