"""Batched vote ingest over the dense proposal pool.

Applies a batch of (already host-validated) votes to the device-resident pool
with semantics bit-identical to repeated ``ConsensusSession::add_vote``
(reference: src/session.rs:225-249): per-proposal votes apply in arrival
order with the exact precedence chain — already-reached (no-op success) →
session-not-active → proposal-expired → round-cap (fails the session) →
duplicate-owner → accept, then the consensus check runs on the updated tally.

Layout: the host groups the batch by proposal slot into an ``[S, L]`` grid
(S touched slots, L = max votes per slot in this batch, padded). The kernel
gathers each touched slot's state, runs a ``lax.scan`` of length L — one vote
per slot per step, vectorized across all S slots — and scatters results back.
Wall-clock scales with the *deepest* per-proposal vote chain in the batch,
not the batch size: breadth-heavy workloads (many proposals, few votes each)
are nearly fully parallel; depth-heavy replays serialize only within a
proposal, exactly like the protocol itself does.

Padding contract: pad rows carry ``slot_id == P`` (out of range). Gathers
clip (values unused), scatters drop — so pad rows can never corrupt slot 0.
Pad cells within a real row have ``valid == False``.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..errors import StatusCode
from .decide import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
    decide_kernel,
)

# Status emitted for padding cells (no vote present).
PAD_STATUS = -1

def group_batch(slot_idx: np.ndarray):
    """Host-side: group a flat vote batch by proposal slot into grid
    coordinates, preserving arrival order within each slot.

    Returns ``(uniq_slots[S], row[B], col[B], L)`` where batch item ``b``
    lands at grid cell ``(row[b], col[b])`` and ``L`` is the deepest
    per-slot chain. Stable sort keeps the protocol's order-sensitivity
    (round caps, mid-batch consensus cuts) intact.
    """
    b_count = len(slot_idx)
    if b_count == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64), np.empty(0, np.int64), 0
    order = np.argsort(slot_idx, kind="stable")
    sorted_slots = slot_idx[order]
    uniq, inverse_sorted, counts = np.unique(
        sorted_slots, return_inverse=True, return_counts=True
    )
    starts = np.cumsum(counts) - counts
    pos_sorted = np.arange(b_count) - starts[inverse_sorted]
    row = np.empty(b_count, dtype=np.int64)
    col = np.empty(b_count, dtype=np.int64)
    row[order] = inverse_sorted
    col[order] = pos_sorted
    return uniq, row, col, int(counts.max())


_OK = int(StatusCode.OK)
_ALREADY_REACHED = int(StatusCode.ALREADY_REACHED)
_SESSION_NOT_ACTIVE = int(StatusCode.SESSION_NOT_ACTIVE)
_PROPOSAL_EXPIRED = int(StatusCode.PROPOSAL_EXPIRED)
_MAX_ROUNDS_EXCEEDED = int(StatusCode.MAX_ROUNDS_EXCEEDED)
_DUPLICATE_VOTE = int(StatusCode.DUPLICATE_VOTE)


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
def ingest_kernel(
    state,  # int32[P] slot lifecycle
    yes,  # int32[P] YES tally
    tot,  # int32[P] total tally
    vote_mask,  # bool[P, V] who has voted
    vote_val,  # bool[P, V] their choice
    n,  # int32[P] expected voters
    req,  # int32[P] precomputed required votes
    cap,  # int32[P] max round limit (max_round_limit semantics)
    gossipsub,  # bool[P] gossipsub round semantics flag
    liveness,  # bool[P] silent-peers-as-YES flag
    slot_ids,  # int32[S] touched slots (P = pad sentinel)
    expired,  # bool[S] host-computed `now >= expiration` per touched slot
    voter_grid,  # int32[S, L] voter index within [0, V)
    val_grid,  # bool[S, L] vote choice
    valid_grid,  # bool[S, L] cell-is-a-real-vote mask
):
    """Returns (updated pool arrays..., statuses int32[S, L], final row state
    int32[S])."""
    s_count = slot_ids.shape[0]
    rows = jnp.arange(s_count)

    gather = lambda arr: jnp.take(arr, slot_ids, axis=0, mode="clip")
    row_state = gather(state)
    row_yes = gather(yes)
    row_tot = gather(tot)
    row_mask = gather(vote_mask)
    row_val = gather(vote_val)
    row_n = gather(n)
    row_req = gather(req)
    row_cap = gather(cap)
    row_gossip = gather(gossipsub)
    row_live = gather(liveness)

    def step(carry, xs):
        st, ys, tt, mask, vals = carry
        voter, val, valid = xs

        reached = (st == STATE_REACHED_YES) | (st == STATE_REACHED_NO)
        active = st == STATE_ACTIVE
        # Round projection (reference: src/session.rs:306-344): gossipsub
        # always projects round 2 when adding a vote; P2P projects
        # accepted-votes + 1 (round == tot + 1 invariant).
        projected = jnp.where(row_gossip, 2, tt + 1)
        exceeded = projected > row_cap
        dup = mask[rows, voter]

        ok = valid & active & ~expired & ~exceeded & ~dup
        status = jnp.where(
            ~valid,
            PAD_STATUS,
            jnp.where(
                reached,
                _ALREADY_REACHED,
                jnp.where(
                    ~active,
                    _SESSION_NOT_ACTIVE,
                    jnp.where(
                        expired,
                        _PROPOSAL_EXPIRED,
                        jnp.where(
                            exceeded,
                            _MAX_ROUNDS_EXCEEDED,
                            jnp.where(dup, _DUPLICATE_VOTE, _OK),
                        ),
                    ),
                ),
            ),
        ).astype(jnp.int32)

        # A cap violation moves the session to Failed even though the vote is
        # rejected (reference: src/session.rs:334-341).
        st = jnp.where(valid & active & ~expired & exceeded, STATE_FAILED, st)

        tt = tt + ok.astype(tt.dtype)
        ys = ys + (ok & val).astype(ys.dtype)
        mask = mask.at[rows, voter].set(dup | ok)
        vals = vals.at[rows, voter].set(jnp.where(ok, val, vals[rows, voter]))

        # Consensus check on the updated tally (is_timeout=False).
        decided, result = decide_kernel(ys, tt, row_n, row_req, row_live, False)
        newly = ok & decided
        reached_state = jnp.where(result, STATE_REACHED_YES, STATE_REACHED_NO)
        st = jnp.where(newly, reached_state.astype(st.dtype), st)

        return (st, ys, tt, mask, vals), status

    carry0 = (row_state, row_yes, row_tot, row_mask, row_val)
    # Scan over vote positions: xs steps through columns of the [S, L] grids.
    (row_state, row_yes, row_tot, row_mask, row_val), statuses = lax.scan(
        step,
        carry0,
        (voter_grid.T, val_grid.T, valid_grid.T),
    )
    statuses = statuses.T  # [L, S] -> [S, L]

    scatter = lambda arr, rows_val: arr.at[slot_ids].set(rows_val, mode="drop")
    state = scatter(state, row_state)
    yes = scatter(yes, row_yes)
    tot = scatter(tot, row_tot)
    vote_mask = scatter(vote_mask, row_mask)
    vote_val = scatter(vote_val, row_val)

    return state, yes, tot, vote_mask, vote_val, statuses, row_state
