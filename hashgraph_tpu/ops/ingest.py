"""Batched vote ingest over the dense proposal pool.

Applies a batch of (already host-validated) votes to the device-resident pool
with semantics bit-identical to repeated ``ConsensusSession::add_vote``
(reference: src/session.rs:225-249): per-proposal votes apply in arrival
order with the exact precedence chain — already-reached (no-op success) →
session-not-active → proposal-expired → round-cap (fails the session) →
duplicate-owner → accept, then the consensus check runs on the updated tally.

Layout: the host groups the batch by proposal slot into an ``[S, L]`` grid
(S touched slots, L = max votes per slot in this batch, padded). The kernel
gathers each touched slot's state, runs a ``lax.scan`` of length L — one vote
per slot per step, vectorized across all S slots — and scatters results back.
Wall-clock scales with the *deepest* per-proposal vote chain in the batch,
not the batch size: breadth-heavy workloads (many proposals, few votes each)
are nearly fully parallel; depth-heavy replays serialize only within a
proposal, exactly like the protocol itself does.

Transfer format (the host↔device link is latency-bound — a tunneled TPU pays
~100ms per round-trip, so the batch crosses in TWO packed arrays and returns
in ONE):
- ``slot_pack`` int32[S]: slot id in bits 0-29, ``expired`` flag in bit 30.
  Pad rows carry slot id == P (out of range): gathers clip (values unused),
  scatters drop — so pad rows can never corrupt slot 0.
- ``grid_pack`` [S, L]: voter lane in the low bits, vote value and
  cell-valid above them. The dtype is the narrowest that fits the pool's
  lane range (uint8 for voter_capacity <= 64, uint16 <= 16384, else int32
  with lane bits 0-15 / value bit 16 / valid bit 17 — see
  :func:`grid_layout`); the grid is the dominant upload, so narrowing it
  cuts the per-dispatch wire bytes 4x/2x. Pad cells have valid == 0.
- output int8[S, L+1]: per-vote statuses in columns [0, L), the row's final
  lifecycle state in column L.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..errors import StatusCode
from .decide import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
    decide_kernel,
)

# Status emitted for padding cells (no vote present).
PAD_STATUS = -1

_SLOT_MASK = (1 << 30) - 1
_EXPIRED_BIT = 30
_LANE_MASK = (1 << 16) - 1
_VAL_BIT = 16
_VALID_BIT = 17


def pack_slots(slot_ids: np.ndarray, expired: np.ndarray) -> np.ndarray:
    """Host-side: fuse slot ids + expiry flags into one int32 transfer."""
    return (
        np.asarray(slot_ids, np.int32) | (np.asarray(expired, np.int32) << _EXPIRED_BIT)
    ).astype(np.int32)


def unpack_slots(slot_pack: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host-side inverse of :func:`pack_slots` (used by shard routing)."""
    packed = np.asarray(slot_pack, np.int32)
    return packed & _SLOT_MASK, ((packed >> _EXPIRED_BIT) & 1).astype(bool)


def grid_dtype(voter_capacity: int):
    """Narrowest packed-grid dtype that fits lane + value + valid bits.
    The grid is the big host->device transfer of every ingest dispatch
    (uploads dominate on a tunneled link), so capacity <= 64 pools ship
    uint8 cells and capacity <= 16384 ship uint16 — 4x / 2x less wire
    than the general int32 layout."""
    if voter_capacity <= 64:
        return np.uint8
    if voter_capacity <= 16384:
        return np.uint16
    return np.int32


def grid_layout(dtype) -> tuple[int, int, int]:
    """(lane_mask, val_bit, valid_bit) for a packed-grid dtype. Kernels
    derive the layout from the traced array's dtype, so host pack and
    device unpack can never disagree."""
    dt = np.dtype(dtype)
    if dt == np.uint8:
        return (1 << 6) - 1, 6, 7
    if dt == np.uint16:
        return (1 << 14) - 1, 14, 15
    return _LANE_MASK, _VAL_BIT, _VALID_BIT


def pack_grid(
    voter_grid: np.ndarray,
    val_grid: np.ndarray,
    valid_grid: np.ndarray,
    voter_capacity: int | None = None,
) -> np.ndarray:
    """Host-side: fuse lane/value/valid grids into one packed transfer.
    ``voter_capacity`` (when given) selects the narrowest dtype whose lane
    field still holds capacity-1; None keeps the original int32 layout
    (direct callers, and the Pallas kernel's fixed int32 unpack)."""
    dt = np.int32 if voter_capacity is None else grid_dtype(voter_capacity)
    _, val_bit, valid_bit = grid_layout(dt)
    return (
        np.asarray(voter_grid, dt)
        | (np.asarray(val_grid, dt) << val_bit)
        | (np.asarray(valid_grid, dt) << valid_bit)
    ).astype(dt)


def group_batch(slot_idx: np.ndarray):
    """Host-side: group a flat vote batch by proposal slot into grid
    coordinates, preserving arrival order within each slot.

    Returns ``(uniq_slots[S], row[B], col[B], L)`` where batch item ``b``
    lands at grid cell ``(row[b], col[b])`` and ``L`` is the deepest
    per-slot chain. Stable sort keeps the protocol's order-sensitivity
    (round caps, mid-batch consensus cuts) intact.
    """
    b_count = len(slot_idx)
    if b_count == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64), np.empty(0, np.int64), 0
    order = np.argsort(slot_idx, kind="stable")
    sorted_slots = slot_idx[order]
    # Group boundaries straight from the sorted run (np.unique would sort a
    # second time — this path sits on the ingest hot loop).
    is_start = np.empty(b_count, bool)
    is_start[0] = True
    np.not_equal(sorted_slots[1:], sorted_slots[:-1], out=is_start[1:])
    starts_idx = np.nonzero(is_start)[0]
    uniq = sorted_slots[starts_idx]
    inverse_sorted = np.cumsum(is_start) - 1
    starts = starts_idx[inverse_sorted]
    pos_sorted = np.arange(b_count) - starts
    counts_max = int(np.max(np.diff(np.append(starts_idx, b_count))))
    row = np.empty(b_count, dtype=np.int64)
    col = np.empty(b_count, dtype=np.int64)
    row[order] = inverse_sorted
    col[order] = pos_sorted
    return uniq, row, col, counts_max


_OK = int(StatusCode.OK)
_ALREADY_REACHED = int(StatusCode.ALREADY_REACHED)
_SESSION_NOT_ACTIVE = int(StatusCode.SESSION_NOT_ACTIVE)
_PROPOSAL_EXPIRED = int(StatusCode.PROPOSAL_EXPIRED)
_MAX_ROUNDS_EXCEEDED = int(StatusCode.MAX_ROUNDS_EXCEEDED)
_DUPLICATE_VOTE = int(StatusCode.DUPLICATE_VOTE)


def ingest_body(
    state,  # int32[P] slot lifecycle
    yes,  # int32[P] YES tally
    tot,  # int32[P] total tally
    vote_mask,  # bool[P, V] who has voted
    vote_val,  # bool[P, V] their choice
    n,  # int32[P] expected voters
    req,  # int32[P] precomputed required votes
    cap,  # int32[P] max round limit (max_round_limit semantics)
    gossipsub,  # bool[P] gossipsub round semantics flag
    liveness,  # bool[P] silent-peers-as-YES flag
    slot_pack,  # int32[S] packed slot ids + expired flags (see module doc)
    grid_pack,  # int32[S, L] packed voter/value/valid cells
):
    """Returns (updated pool arrays..., out int32[S, L+1]) where out carries
    per-vote statuses plus the final row state in the last column."""
    s_count = slot_pack.shape[0]
    rows = jnp.arange(s_count)

    slot_ids = slot_pack & _SLOT_MASK
    expired = ((slot_pack >> _EXPIRED_BIT) & 1).astype(bool)
    lane_mask, val_bit, valid_bit = grid_layout(grid_pack.dtype)
    voter_grid = (grid_pack & lane_mask).astype(jnp.int32)
    val_grid = ((grid_pack >> val_bit) & 1).astype(bool)
    valid_grid = ((grid_pack >> valid_bit) & 1).astype(bool)

    gather = lambda arr: jnp.take(arr, slot_ids, axis=0, mode="clip")
    row_state = gather(state)
    row_yes = gather(yes)
    row_tot = gather(tot)
    row_mask = gather(vote_mask)
    row_val = gather(vote_val)
    row_n = gather(n)
    row_req = gather(req)
    row_cap = gather(cap)
    row_gossip = gather(gossipsub)
    row_live = gather(liveness)

    def step(carry, xs):
        st, ys, tt, mask, vals = carry
        voter, val, valid = xs

        reached = (st == STATE_REACHED_YES) | (st == STATE_REACHED_NO)
        active = st == STATE_ACTIVE
        # Round projection (reference: src/session.rs:306-344): gossipsub
        # always projects round 2 when adding a vote; P2P projects
        # accepted-votes + 1 (round == tot + 1 invariant).
        projected = jnp.where(row_gossip, 2, tt + 1)
        exceeded = projected > row_cap
        dup = mask[rows, voter]

        ok = valid & active & ~expired & ~exceeded & ~dup
        status = jnp.where(
            ~valid,
            PAD_STATUS,
            jnp.where(
                reached,
                _ALREADY_REACHED,
                jnp.where(
                    ~active,
                    _SESSION_NOT_ACTIVE,
                    jnp.where(
                        expired,
                        _PROPOSAL_EXPIRED,
                        jnp.where(
                            exceeded,
                            _MAX_ROUNDS_EXCEEDED,
                            jnp.where(dup, _DUPLICATE_VOTE, _OK),
                        ),
                    ),
                ),
            ),
        ).astype(jnp.int32)

        # A cap violation moves the session to Failed even though the vote is
        # rejected (reference: src/session.rs:334-341).
        st = jnp.where(valid & active & ~expired & exceeded, STATE_FAILED, st)

        tt = tt + ok.astype(tt.dtype)
        ys = ys + (ok & val).astype(ys.dtype)
        mask = mask.at[rows, voter].set(dup | ok)
        vals = vals.at[rows, voter].set(jnp.where(ok, val, vals[rows, voter]))

        # Consensus check on the updated tally (is_timeout=False).
        decided, result = decide_kernel(ys, tt, row_n, row_req, row_live, False)
        newly = ok & decided
        reached_state = jnp.where(result, STATE_REACHED_YES, STATE_REACHED_NO)
        st = jnp.where(newly, reached_state.astype(st.dtype), st)

        return (st, ys, tt, mask, vals), status

    carry0 = (row_state, row_yes, row_tot, row_mask, row_val)
    # Scan over vote positions: xs steps through columns of the [S, L] grids.
    (row_state, row_yes, row_tot, row_mask, row_val), statuses = lax.scan(
        step,
        carry0,
        (voter_grid.T, val_grid.T, valid_grid.T),
    )
    statuses = statuses.T  # [L, S] -> [S, L]

    scatter = lambda arr, rows_val: arr.at[slot_ids].set(rows_val, mode="drop")
    state = scatter(state, row_state)
    yes = scatter(yes, row_yes)
    tot = scatter(tot, row_tot)
    vote_mask = scatter(vote_mask, row_mask)
    vote_val = scatter(vote_val, row_val)

    # int8 readback: status codes fit a byte, and the device->host link is
    # the bottleneck — 4x less transfer than int32.
    out = jnp.concatenate([statuses, row_state[:, None]], axis=1).astype(jnp.int8)
    return state, yes, tot, vote_mask, vote_val, out


# Jitted single-device entry point; the raw body is reused inside shard_map
# blocks by the multi-device pool (hashgraph_tpu.parallel).
ingest_kernel = partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))(ingest_body)


def fresh_ingest_body(
    state,
    yes,
    tot,
    vote_mask,
    vote_val,
    n,
    req,
    cap,
    gossipsub,
    liveness,
    slot_pack,  # int32[S] packed slot ids + expired flags
    grid_pack,  # packed cells: see `laneless` below
    *,
    laneless: bool = False,
):
    """Closed-form ingest for FRESH slots: the whole per-slot vote chain in
    one dispatch with NO sequential scan.

    ``laneless=True``: the grid carries only value (bit 0) and valid
    (bit 1) per cell (uint8); voter lanes are reconstructed on device as
    the within-slot arrival index — which is exactly what the fresh-path
    lane assignment rule produces — halving the dominant upload for pools
    whose lane range doesn't fit uint8 anyway (voter_capacity > 64).

    The serial scan in :func:`ingest_body` exists because a vote's fate
    depends on the running state. For a batch the engine has already
    resolved on its fast path — every touched slot freshly ACTIVE with zero
    prior tallies, and no repeated (slot, voter) pair — that dependency has
    a closed form: every valid vote before the terminal event is accepted,
    so the running tallies are prefix sums (XLA's log-depth parallel
    cumsum, not an L-step scan), the round-cap violation index and the
    decision index are first-true reductions over elementwise
    :func:`decide_kernel`, and statuses fall out of index-vs-terminal
    comparisons. Semantics are bit-identical to replaying the scan on a
    fresh slot (randomized parity-tested); per-slot wall clock drops from
    O(depth) scan steps to O(log depth), which is the difference between
    ~16 ms and ~1 ms for a 683-deep P2P quorum chain.

    PRECONDITIONS (engine-enforced): touched slots are ACTIVE with
    tot == yes == 0 and cleared mask/val rows; the batch has no duplicate
    (slot, voter) pair. Pad rows/cells follow the scan kernel's contract.
    Returns the same (updated arrays..., out int8[S, L+1]) shape.
    """
    s_count, depth = grid_pack.shape

    slot_ids = slot_pack & _SLOT_MASK
    expired = ((slot_pack >> _EXPIRED_BIT) & 1).astype(bool)
    if laneless:
        val_grid = (grid_pack & 1).astype(bool)
        valid = ((grid_pack >> 1) & 1).astype(bool)
        voter_grid = jnp.broadcast_to(
            jnp.arange(depth, dtype=jnp.int32), (s_count, depth)
        )
    else:
        lane_mask, val_bit, valid_bit = grid_layout(grid_pack.dtype)
        voter_grid = (grid_pack & lane_mask).astype(jnp.int32)
        val_grid = ((grid_pack >> val_bit) & 1).astype(bool)
        valid = ((grid_pack >> valid_bit) & 1).astype(bool)

    gather = lambda arr: jnp.take(arr, slot_ids, axis=0, mode="clip")
    row_n = gather(n)[:, None]
    row_req = gather(req)[:, None]
    row_cap = gather(cap)[:, None]
    row_gossip = gather(gossipsub)[:, None]
    row_live = gather(liveness)[:, None]

    live = valid & ~expired[:, None]
    T = jnp.cumsum(live.astype(jnp.int32), axis=1)
    Y = jnp.cumsum((live & val_grid).astype(jnp.int32), axis=1)

    # Round-cap check per vote, pre-accept (reference: src/session.rs:306-344):
    # gossipsub projects round 2; P2P projects accepted-before + 1 == T_i for
    # a valid vote on a fresh slot.
    projected = jnp.where(row_gossip, 2, T)
    exceeded = live & (projected > row_cap)
    decided_i, result_i = decide_kernel(Y, T, row_n, row_req, row_live, False)
    dec = live & decided_i

    idxs = jnp.arange(depth, dtype=jnp.int32)[None, :]
    c_has = dec.any(axis=1)
    c = jnp.where(c_has, jnp.argmax(dec, axis=1).astype(jnp.int32), depth)
    f_has = exceeded.any(axis=1)
    f = jnp.where(f_has, jnp.argmax(exceeded, axis=1).astype(jnp.int32), depth)
    # A vote that violates the cap is rejected before it could decide, so
    # the cap-fail terminal wins ties.
    dec_term = c < f
    fail_term = f_has & ~dec_term
    t_idx = jnp.where(dec_term, c, f)[:, None]

    # Statuses by region relative to the terminal index (the innermost
    # else-branch is the post-terminal region; with no terminal, t == depth
    # and every cell is "pre").
    pre = idxs < t_idx
    at = idxs == t_idx
    status = jnp.where(
        pre,
        _OK,
        jnp.where(
            at,
            jnp.where(dec_term[:, None], _OK, _MAX_ROUNDS_EXCEEDED),
            jnp.where(
                dec_term[:, None], _ALREADY_REACHED, _SESSION_NOT_ACTIVE
            ),
        ),
    )
    status = jnp.where(expired[:, None], _PROPOSAL_EXPIRED, status)
    status = jnp.where(valid, status, PAD_STATUS).astype(jnp.int32)

    # Accepted set: valid live votes up to the terminal (inclusive for a
    # decision — the deciding vote is accepted; exclusive for a cap fail).
    acc = live & (pre | (at & dec_term[:, None]))

    # Final per-row tallies/state.
    take_at = lambda M, i: jnp.take_along_axis(M, i[:, None], axis=1)[:, 0]
    last_T = T[:, -1] if depth else jnp.zeros(s_count, jnp.int32)
    last_Y = Y[:, -1] if depth else jnp.zeros(s_count, jnp.int32)
    cc = jnp.minimum(c, depth - 1)
    ff = jnp.minimum(f, depth - 1)
    tot_new = jnp.where(
        dec_term,
        take_at(T, cc),
        jnp.where(fail_term, take_at(T, ff) - 1, last_T),
    )
    yes_new = jnp.where(
        dec_term,
        take_at(Y, cc),
        jnp.where(
            fail_term,
            take_at(Y, ff) - (take_at(val_grid, ff) & take_at(live, ff)),
            last_Y,
        ),
    )
    result_c = take_at(result_i, cc)
    prev_state = gather(state)
    row_state = jnp.where(
        dec_term,
        jnp.where(result_c, STATE_REACHED_YES, STATE_REACHED_NO),
        jnp.where(fail_term, STATE_FAILED, prev_state),
    ).astype(prev_state.dtype)

    scatter = lambda arr, rows_val: arr.at[slot_ids].set(rows_val, mode="drop")
    state = scatter(state, row_state)
    yes = scatter(yes, yes_new.astype(yes.dtype))
    tot = scatter(tot, tot_new.astype(tot.dtype))
    rows_flat = jnp.repeat(slot_ids, depth)
    lanes_flat = voter_grid.reshape(-1)
    # Fresh rows start all-False, and each (slot, lane) cell is touched at
    # most once (no duplicate voters on this path), so scatter-max writes
    # exactly the accepted cells.
    vote_mask = vote_mask.at[rows_flat, lanes_flat].max(
        acc.reshape(-1), mode="drop"
    )
    vote_val = vote_val.at[rows_flat, lanes_flat].max(
        (acc & val_grid).reshape(-1), mode="drop"
    )

    out = jnp.concatenate(
        [status, row_state[:, None].astype(jnp.int32)], axis=1
    ).astype(jnp.int8)
    return state, yes, tot, vote_mask, vote_val, out


fresh_ingest_kernel = partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))(
    fresh_ingest_body
)
fresh_ingest_laneless_kernel = partial(
    jax.jit, donate_argnums=(0, 1, 2, 3, 4)
)(partial(fresh_ingest_body, laneless=True))
