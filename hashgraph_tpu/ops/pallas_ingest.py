"""Pallas TPU kernel for the ingest vote-scan (the hot loop).

The XLA path (:func:`hashgraph_tpu.ops.ingest.ingest_body`) expresses the
arrival-ordered vote replay as ``lax.scan`` whose carry — the ``[S, V]``
mask/value rows plus tallies — may round-trip HBM between steps. This Pallas
version keeps each block's carry resident in VMEM for all ``L`` steps: the
grid tiles the touched-slot axis, each program loads its rows once, loops
votes with a ``fori_loop`` entirely on-chip (VPU; the per-row lane update is
a one-hot compare against an iota, not a scatter), and writes back once.

Layout notes (TPU tiling):
- per-row scalars (state/yes/tot/n/req/cap/gossip/liveness/expired) pack
  into one ``int32[S, 16]`` array → a single VMEM block per program;
- masks/values are ``int32[S, V]`` (bool semantics; int32 keeps the 8×128
  tile layout);
- the semantics are bit-identical to the XLA scan — enforced by the parity
  suite which runs both on identical inputs.

Used by the pool when ``HASHGRAPH_TPU_PALLAS=1`` (or ``use_pallas=True``);
falls back to the XLA path automatically if lowering fails. On non-TPU
backends tests run it with ``interpret=True``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..errors import StatusCode
from .decide import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
)
from .ingest import PAD_STATUS

# Packed per-row scalar columns.
_C_STATE, _C_YES, _C_TOT, _C_N, _C_REQ, _C_CAP, _C_GOSSIP, _C_LIVE, _C_EXPIRED = range(9)
SCALAR_COLS = 16  # padded for tiling friendliness

_OK = int(StatusCode.OK)
_ALREADY = int(StatusCode.ALREADY_REACHED)
_NOT_ACTIVE = int(StatusCode.SESSION_NOT_ACTIVE)
_EXPIRED = int(StatusCode.PROPOSAL_EXPIRED)
_MAX_ROUNDS = int(StatusCode.MAX_ROUNDS_EXCEEDED)
_DUP = int(StatusCode.DUPLICATE_VOTE)

_LANE_MASK = (1 << 16) - 1
_VAL_BIT = 16
_VALID_BIT = 17


def _decide_vec(yes, tot, n, req, live):
    """Vectorized calculate_consensus_result with is_timeout=False
    (mirrors ops.decide.decide_kernel; kernel-local form with int32 truth
    values throughout — Mosaic cannot select over packed-bool vectors, so
    no jnp.where may carry boolean branches)."""
    i32 = jnp.int32
    no = tot - yes
    silent = jnp.maximum(n - tot, 0)
    small = (n <= 2).astype(i32)
    small_decided = (tot >= n).astype(i32)
    small_result = (yes == n).astype(i32)
    gate = (tot >= req).astype(i32)
    live_i = live.astype(i32)
    yes_w = yes + silent * live_i
    no_w = no + silent * (1 - live_i)
    yes_win = ((yes_w >= req) & (yes_w > no_w)).astype(i32)
    no_win = ((no_w >= req) & (no_w > yes_w)).astype(i32)
    tie = ((tot == n) & (yes_w == no_w)).astype(i32)
    big_decided = gate * jnp.minimum(yes_win + no_win + tie, 1)
    big_result = jnp.minimum(yes_win + (1 - no_win) * (1 - yes_win) * live_i, 1)
    decided = small * small_decided + (1 - small) * big_decided
    result = small * small_result + (1 - small) * big_result
    return decided, result


def _ingest_block_kernel(scal_ref, mask_ref, val_ref, grid_ref,
                         out_scal_ref, out_mask_ref, out_val_ref, out_status_ref):
    scal = scal_ref[...]  # [B, 16] int32
    mask = mask_ref[...]  # [B, V] int32 (0/1)
    vals = val_ref[...]
    grid = grid_ref[...]  # [B, L] packed votes
    b, v_cap = mask.shape
    l_depth = grid.shape[1]

    state = scal[:, _C_STATE]
    yes = scal[:, _C_YES]
    tot = scal[:, _C_TOT]
    n = scal[:, _C_N]
    req = scal[:, _C_REQ]
    cap = scal[:, _C_CAP]
    gossip = scal[:, _C_GOSSIP] != 0
    live = scal[:, _C_LIVE] != 0
    expired = scal[:, _C_EXPIRED] != 0

    lane_iota = lax.broadcasted_iota(jnp.int32, (b, v_cap), 1)
    col_iota = lax.broadcasted_iota(jnp.int32, (b, l_depth), 1)
    statuses0 = jnp.full((b, l_depth), PAD_STATUS, jnp.int32)

    def step(l, carry):
        state, yes, tot, mask, vals, statuses = carry
        # Column l of the grid via one-hot select (Pallas TPU lowers no
        # dynamic_slice; L is small so the O(L) select is free on the VPU).
        cell = jnp.sum(jnp.where(col_iota == l, grid, 0), axis=1)  # [B]
        voter = cell & _LANE_MASK
        val = ((cell >> _VAL_BIT) & 1) != 0
        valid = ((cell >> _VALID_BIT) & 1) != 0

        reached = (state == STATE_REACHED_YES) | (state == STATE_REACHED_NO)
        active = state == STATE_ACTIVE
        projected = jnp.where(gossip, 2, tot + 1)
        exceeded = projected > cap
        onehot = lane_iota == voter[:, None]  # [B, V]
        dup = jnp.sum(jnp.where(onehot, mask, 0), axis=1) != 0

        ok = valid & active & ~expired & ~exceeded & ~dup
        status = jnp.where(
            ~valid,
            PAD_STATUS,
            jnp.where(
                reached,
                _ALREADY,
                jnp.where(
                    ~active,
                    _NOT_ACTIVE,
                    jnp.where(
                        expired,
                        _EXPIRED,
                        jnp.where(
                            exceeded,
                            _MAX_ROUNDS,
                            jnp.where(dup, _DUP, _OK),
                        ),
                    ),
                ),
            ),
        ).astype(jnp.int32)

        state = jnp.where(valid & active & ~expired & exceeded, STATE_FAILED, state)
        tot = tot + ok.astype(tot.dtype)
        yes = yes + (ok & val).astype(yes.dtype)
        set_mask = onehot & ok[:, None]
        mask = jnp.where(set_mask, 1, mask)
        vals = jnp.where(set_mask & val[:, None], 1, jnp.where(set_mask, 0, vals))

        decided, result = _decide_vec(yes, tot, n, req, live)  # int32 0/1
        newly = ok & (decided != 0)
        reached_state = jnp.where(result != 0, STATE_REACHED_YES, STATE_REACHED_NO)
        state = jnp.where(newly, reached_state.astype(state.dtype), state)

        statuses = jnp.where(col_iota == l, status[:, None], statuses)
        return state, yes, tot, mask, vals, statuses

    state, yes, tot, mask, vals, statuses = lax.fori_loop(
        0, l_depth, step, (state, yes, tot, mask, vals, statuses0)
    )

    # Column-wise writeback via one-hot selects (no scatter in Pallas TPU).
    scol = lax.broadcasted_iota(jnp.int32, (b, SCALAR_COLS), 1)
    out = jnp.where(scol == _C_STATE, state[:, None], scal)
    out = jnp.where(scol == _C_YES, yes[:, None], out)
    out = jnp.where(scol == _C_TOT, tot[:, None], out)
    out_scal_ref[...] = out
    out_mask_ref[...] = mask
    out_val_ref[...] = vals
    out_status_ref[...] = statuses


def pallas_ingest_body(
    state, yes, tot, vote_mask, vote_val, n, req, cap, gossipsub, liveness,
    slot_pack, grid_pack, *, block: int = 128, interpret: bool = False,
):
    """Drop-in alternative to :func:`hashgraph_tpu.ops.ingest.ingest_body`:
    identical signature and outputs, with the vote scan running in the
    Pallas kernel (gather/pack and unpack/scatter stay XLA and fuse around
    the pallas_call)."""
    s_count = slot_pack.shape[0]
    slot_ids = slot_pack & ((1 << 30) - 1)
    expired = (slot_pack >> 30) & 1

    gather = lambda arr: jnp.take(arr, slot_ids, axis=0, mode="clip")
    i32 = lambda arr: arr.astype(jnp.int32)
    cols = [
        i32(gather(state)),
        i32(gather(yes)),
        i32(gather(tot)),
        i32(gather(n)),
        i32(gather(req)),
        i32(gather(cap)),
        i32(gather(gossipsub)),
        i32(gather(liveness)),
        i32(expired),
    ]
    scal = jnp.zeros((s_count, SCALAR_COLS), jnp.int32)
    for c, col in enumerate(cols):
        scal = scal.at[:, c].set(col)
    mask_rows = i32(gather(vote_mask))
    val_rows = i32(gather(vote_val))

    out_scal, out_mask, out_val, statuses = pallas_ingest_rows(
        scal, mask_rows, val_rows, grid_pack, block=block, interpret=interpret
    )

    row_state = out_scal[:, _C_STATE]
    scatter = lambda arr, rows: arr.at[slot_ids].set(
        rows.astype(arr.dtype), mode="drop"
    )
    state = scatter(state, row_state)
    yes = scatter(yes, out_scal[:, _C_YES])
    tot = scatter(tot, out_scal[:, _C_TOT])
    vote_mask = scatter(vote_mask, out_mask != 0)
    vote_val = scatter(vote_val, out_val != 0)
    out = jnp.concatenate([statuses, row_state[:, None]], axis=1).astype(jnp.int8)
    return state, yes, tot, vote_mask, vote_val, out


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def pallas_ingest_rows(scal, mask, vals, grid, block: int = 128,
                       interpret: bool = False):
    """Run the VMEM-resident vote scan over gathered rows.

    Args:
      scal: int32[S, 16] packed per-row scalars (see column constants).
      mask/vals: int32[S, V] voter masks/choices (0/1).
      grid: int32[S, L] packed votes (lane | value<<16 | valid<<17).
      block: rows per Pallas program (S must be a multiple, callers bucket).

    Returns (scal', mask', vals', statuses int32[S, L]).
    """
    s_count, v_cap = mask.shape
    l_depth = grid.shape[1]
    block = min(block, s_count)  # pool buckets are powers of two
    if s_count % block:
        raise ValueError(f"S={s_count} not a multiple of block={block}")
    grid_size = s_count // block

    return pl.pallas_call(
        _ingest_block_kernel,
        grid=(grid_size,),
        in_specs=[
            pl.BlockSpec((block, SCALAR_COLS), lambda i: (i, 0)),
            pl.BlockSpec((block, v_cap), lambda i: (i, 0)),
            pl.BlockSpec((block, v_cap), lambda i: (i, 0)),
            pl.BlockSpec((block, l_depth), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, SCALAR_COLS), lambda i: (i, 0)),
            pl.BlockSpec((block, v_cap), lambda i: (i, 0)),
            pl.BlockSpec((block, v_cap), lambda i: (i, 0)),
            pl.BlockSpec((block, l_depth), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s_count, SCALAR_COLS), jnp.int32),
            jax.ShapeDtypeStruct((s_count, v_cap), jnp.int32),
            jax.ShapeDtypeStruct((s_count, v_cap), jnp.int32),
            jax.ShapeDtypeStruct((s_count, l_depth), jnp.int32),
        ],
        interpret=interpret,
    )(scal, mask, vals, grid)
