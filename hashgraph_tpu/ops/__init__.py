"""Device kernels: vectorized consensus math over dense proposal batches.

Everything here is jit/vmap/shard_map-friendly JAX with static shapes and no
data-dependent Python control flow. The scalar oracle these kernels must match
bit-for-bit lives in :mod:`hashgraph_tpu.protocol`.
"""

from .decide import (
    STATE_ACTIVE,
    STATE_FAILED,
    STATE_FREE,
    STATE_REACHED_NO,
    STATE_REACHED_YES,
    decide_kernel,
    decide_update,
    required_votes_np,
    state_result,
    timeout_update,
)
from .chain import (
    chain_kernel,
    chain_kernel_batch,
    first_chain_error,
    pack_chain,
)
from .ingest import ingest_kernel

__all__ = [
    "chain_kernel",
    "chain_kernel_batch",
    "first_chain_error",
    "pack_chain",
    "STATE_FREE",
    "STATE_ACTIVE",
    "STATE_FAILED",
    "STATE_REACHED_NO",
    "STATE_REACHED_YES",
    "decide_kernel",
    "decide_update",
    "timeout_update",
    "required_votes_np",
    "state_result",
    "ingest_kernel",
]
