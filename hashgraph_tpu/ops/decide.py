"""The consensus decision kernel, vectorized over a dense proposal batch.

Reproduces ``calculate_consensus_result`` (reference: src/utils.rs:227-286)
elementwise over ``[P]`` arrays of vote tallies. All inputs are int32/bool;
the only floating-point step — converting a threshold to an integer required
vote count — happens once per proposal on the host in IEEE-754 f64
(:func:`required_votes_np`), exactly matching the reference's Rust f64 math,
so the device kernel is pure integer arithmetic and bit-exact by construction.

Design notes (TPU):
- branch-free ``where`` ladders instead of control flow, so XLA fuses the
  whole decision into one elementwise kernel over HBM-resident state;
- int32 tallies (voter counts are bounded by the pool's voter capacity);
  the u32-extreme cases stay on the scalar host path;
- no cross-proposal communication: the kernel shards trivially over the
  proposal axis of a device mesh.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

# Proposal slot lifecycle states (dense int8 codes).
STATE_FREE = 0  # unallocated pool slot
STATE_ACTIVE = 1  # accepting votes
STATE_FAILED = 2  # ConsensusState::Failed
STATE_REACHED_NO = 3  # ConsensusReached(false)
STATE_REACHED_YES = 4  # ConsensusReached(true)

_F64_EPS = float(np.finfo(np.float64).eps)  # == Rust f64::EPSILON
_TWO_THIRDS = 2.0 / 3.0
_U32_MAX = 0xFFFFFFFF


def required_votes_np(
    expected_voters: np.ndarray, consensus_threshold: np.ndarray | float
) -> np.ndarray:
    """Host-side ``calculate_threshold_based_value`` over arrays
    (reference: src/utils.rs:307-313).

    The 2/3 default takes the exact-integer ``div_ceil(2n, 3)`` path; any
    other threshold uses ``ceil(n * t)`` in f64 (numpy float64 == Rust f64),
    with the final u32-saturating cast mirrored. Returns int64 (values are
    bounded by n, so they fit whatever the device needs).
    """
    n = np.asarray(expected_voters, dtype=np.int64)
    t = np.broadcast_to(np.asarray(consensus_threshold, dtype=np.float64), n.shape)
    exact_path = np.abs(t - _TWO_THIRDS) < _F64_EPS
    exact = (2 * n + 2) // 3
    general = np.ceil(n.astype(np.float64) * t)
    general = np.clip(general, 0, _U32_MAX).astype(np.int64)
    return np.where(exact_path, exact, general)


def decide_kernel(yes, tot, n, req, liveness, is_timeout):
    """Elementwise decision over ``[P]`` tallies.

    Args:
      yes: int32[P] YES votes recorded.
      tot: int32[P] total votes recorded.
      n: int32[P] expected voters.
      req: int32[P] precomputed ceil(n*threshold) (see required_votes_np).
      liveness: bool[P] silent-peers-count-as-YES flag.
      is_timeout: bool[P] (or scalar) timeout-path flag.

    Returns:
      (decided, result): bool[P] pair; ``result`` is meaningful only where
      ``decided`` is True. Mirrors reference src/utils.rs:227-286 exactly:
      n<=2 unanimity, quorum gate (silent peers join at timeout), silent-peer
      weighting, strict-majority wins, full-participation tie-break.
    """
    no = tot - yes
    silent = jnp.maximum(n - tot, 0)

    # n <= 2 unanimity branch (utils.rs:239-244) — unaffected by is_timeout.
    small = n <= 2
    small_decided = tot >= n
    small_result = yes == n

    # Quorum gate (utils.rs:246-255): at timeout, silent peers count.
    eff = jnp.where(is_timeout, n, tot)
    gate = eff >= req

    # Silent-peer weighting (utils.rs:258-271).
    zeros = jnp.zeros_like(silent)
    yes_w = yes + jnp.where(liveness, silent, zeros)
    no_w = no + jnp.where(liveness, zeros, silent)

    yes_win = (yes_w >= req) & (yes_w > no_w)
    no_win = (no_w >= req) & (no_w > yes_w)
    # Tie-break only at full participation (utils.rs:281-283).
    tie = (tot == n) & (yes_w == no_w)

    big_decided = gate & (yes_win | no_win | tie)
    big_result = jnp.where(yes_win, True, jnp.where(no_win, False, liveness))

    decided = jnp.where(small, small_decided, big_decided)
    result = jnp.where(small, small_result, big_result)
    return decided, result


def decide_update(state, yes, tot, n, req, liveness):
    """Post-ingest consensus check (is_timeout=False) applied to ACTIVE slots.

    Mirrors ``ConsensusSession::check_consensus`` (reference:
    src/session.rs:372-387): undecided slots stay ACTIVE.
    """
    decided, result = decide_kernel(yes, tot, n, req, liveness, False)
    active = state == STATE_ACTIVE
    reached = jnp.where(result, STATE_REACHED_YES, STATE_REACHED_NO).astype(state.dtype)
    return jnp.where(active & decided, reached, state)


def timeout_update(state, yes, tot, n, req, liveness, timeout_mask):
    """Timeout decision for masked slots (is_timeout=True).

    Mirrors ``handle_consensus_timeout`` (reference: src/service.rs:329-348):
    REACHED slots are untouched (idempotent); ACTIVE *and* FAILED slots are
    recomputed — the reference mutator only short-circuits on ConsensusReached,
    so a Failed session whose timeout fires again gets a fresh decision —
    and transition to FAILED when undecidable.
    """
    decided, result = decide_kernel(yes, tot, n, req, liveness, True)
    fires = ((state == STATE_ACTIVE) | (state == STATE_FAILED)) & timeout_mask
    reached = jnp.where(result, STATE_REACHED_YES, STATE_REACHED_NO).astype(state.dtype)
    outcome = jnp.where(decided, reached, jnp.asarray(STATE_FAILED, state.dtype))
    return jnp.where(fires, outcome, state)


def state_result(state):
    """Map slot states to (has_result, result) pairs for host readback."""
    has_result = (state == STATE_REACHED_YES) | (state == STATE_REACHED_NO)
    return has_result, state == STATE_REACHED_YES


def timeout_body(state, yes, tot, n, req, liveness, slot_ids):
    """Fire the timeout decision for the given slots and return their new
    states.

    ``slot_ids`` uses the same pad contract as the ingest kernel: ids ``== P``
    are out-of-range sentinels whose scatter drops and whose gather clips (the
    clipped row's returned state is unused by the host). Mirrors
    ``handle_consensus_timeout`` (reference: src/service.rs:329-348): REACHED
    slots are untouched; ACTIVE/FAILED slots get a fresh timeout decision.
    """
    fires = jnp.zeros(state.shape, bool).at[slot_ids].set(True, mode="drop")
    new_state = timeout_update(state, yes, tot, n, req, liveness, fires)
    return new_state, jnp.take(new_state, slot_ids, mode="clip")


# Jitted single-device entry point; the raw body is reused inside shard_map
# blocks by the multi-device pool (hashgraph_tpu.parallel).
timeout_kernel = partial(jax.jit, donate_argnums=(0,))(timeout_body)
