"""GossipNode: sampled fan-out + anti-entropy over the bridge fabric.

One node owns (optionally) a local consensus engine and a set of remote
peers reached through a :class:`~hashgraph_tpu.gossip.transport.
GossipTransport`. Deliveries follow Baird's gossip-about-gossip shape in
two tiers:

- **hot path**: :meth:`submit_votes` applies locally, then fans each
  vote out to a *sampled* subset of peers (``fanout``) through the
  :class:`~hashgraph_tpu.gossip.coalescer.VoteCoalescer` — coalesced
  columnar frames, pipelined on the wire, bounded queues throughout;
- **repair path**: :meth:`anti_entropy` periodically pushes full
  proposals (their whole retained vote chains) to peers via
  ``OP_DELIVER_PROPOSALS``. The receiving engine's validated-chain
  watermark makes this cheap: an already-known chain settles with ONE
  tail-hash compare and zero crypto, a lagging peer verifies only the
  suffix it was missing, an unknown session is created whole. Scopes
  whose hot-path frames were *shed* (slow peer, queue at cap) are
  pushed first — backpressure degrades to deferred repair, never to
  unbounded buffering or silent loss.

A peer that is TOO far behind for incremental repair — a fresh joiner,
or a node whose whole history was lost — escalates to the state-sync
path: :meth:`anti_entropy` probes a sampled peer's snapshot manifest
and, when the local engine is fresh and the gap exceeds
``escalate_sessions``, runs a full
:class:`~hashgraph_tpu.sync.CatchUpClient` catch-up (snapshot + WAL
tail) instead of absorbing thousands of deliver frames.
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout

import numpy as np

from ..bridge import columnar as WC
from ..bridge import protocol as P
from ..bridge.reactor import ApplyReactor, reactor_enabled
from ..bridge.client import (
    BridgeConnectionLost,
    BridgeError,
    parse_status_list,
    parse_sync_manifest,
)
from ..errors import ConsensusError, StatusCode
from ..obs import (
    GOSSIP_ANTI_ENTROPY_ROUNDS_TOTAL,
    GOSSIP_ANTI_ENTROPY_SESSIONS_TOTAL,
    GOSSIP_CATCHUP_ESCALATIONS_TOTAL,
    GOSSIP_FRAMES_DEFERRED_TOTAL,
    flight_recorder,
)
from ..obs import registry as default_registry
from ..wire import Vote
from .coalescer import VoteCoalescer
from .transport import ChannelBusy, GossipTransport

_OK = int(StatusCode.OK)
_ALREADY = int(StatusCode.PROPOSAL_ALREADY_EXIST)


class _PeerInfo:
    __slots__ = ("name", "host", "port", "peer_id")

    def __init__(self, name: str, host: str, port: int, peer_id: int):
        self.name = name
        self.host = host
        self.port = port
        self.peer_id = peer_id


class GossipNode:
    """Fan-out + anti-entropy façade over one transport.

    ``engine=None`` builds a pure driver (fan-out only; anti-entropy and
    escalation need a local engine to read proposals from / install
    into). ``fanout=None`` targets every peer; an integer samples that
    many per submit (deterministic under ``seed``). ``flusher=True``
    runs a small background thread that closes coalescer windows on
    ``flush_interval`` expiry — leave it off when a driving loop calls
    :meth:`pump` itself (the benches do)."""

    def __init__(
        self,
        name: str,
        *,
        engine=None,
        transport: GossipTransport | None = None,
        fanout: int | None = None,
        seed: int | None = None,
        flush_votes: int = 256,
        flush_bytes: int = 512 * 1024,
        flush_interval: float = 0.005,
        escalate_sessions: int = 64,
        flusher: bool = False,
        catchup_factory=None,
        shm_ring_bytes: int | None = None,
        apply_reactor: "bool | ApplyReactor | None" = None,
    ):
        self.name = name
        self._engine = engine
        # Escalation seam: ``catchup_factory(host, port, peer_id)`` must
        # return a CatchUpClient-shaped object (catch_up + close). The
        # default dials a real bridge over TCP; the deterministic
        # simulator injects one that rides its in-process fabric instead,
        # so the far-behind escalation path itself stays the live code.
        self._catchup_factory = catchup_factory
        # shm_ring_bytes opts co-located peers into the shared-memory
        # ring lane (loopback endpoints whose server grants
        # FEATURE_SHM_RING); None keeps pure TCP.
        self._transport = (
            transport
            if transport is not None
            else GossipTransport(shm_ring_bytes=shm_ring_bytes)
        )
        self._owns_transport = transport is None
        self._fanout = fanout
        self._rng = random.Random(seed)
        self._coalescer = VoteCoalescer(
            flush_votes=flush_votes,
            flush_bytes=flush_bytes,
            flush_interval=flush_interval,
        )
        self._escalate_sessions = escalate_sessions
        # Local-apply reactor seam: pass the embedding BridgeServer's
        # ApplyReactor instance so this node's local applies merge into
        # the SAME per-engine windows as wire frames; True builds a
        # private (manual-mode) one; None defers to the env default.
        if isinstance(apply_reactor, ApplyReactor):
            self._reactor: "ApplyReactor | None" = apply_reactor
            self._owns_reactor = False
        elif reactor_enabled(apply_reactor):
            self._reactor = ApplyReactor()
            self._owns_reactor = True
        else:
            self._reactor = None
            self._owns_reactor = False
        self._lock = threading.Lock()
        self._peers: dict[str, _PeerInfo] = {}
        # scope -> ordered pid list; peer -> scopes owed a repair push;
        # peer -> rotation cursor into the non-dirty session list, so
        # successive anti-entropy rounds cover EVERY session even when
        # one round's max_sessions can't (the cursor advances by what
        # each round actually pushed).
        self._sessions: dict[str, list[int]] = {}
        self._dirty: dict[str, set[str]] = {}
        self._rotation: dict[str, int] = {}
        # (scope, pid) -> the session's STICKY fan-out sample. Sampling
        # is per SESSION, not per submit call: if consecutive chunks of
        # one session went to different subsets, every peer would hold a
        # different interleaved fragment — and a fragment that is not a
        # positional prefix of the pusher's chain settles as a benign
        # redelivery under the watermark, so anti-entropy could never
        # repair the fabric to byte-identical state. With a sticky
        # sample, a non-sampled peer misses the WHOLE session, which
        # repair creates wholesale.
        self._session_targets: dict[tuple[str, int], list[str]] = {}
        self._tracked = 0  # total (scope, pid) pairs in _sessions
        # In-flight hot-path frames: (peer, meta, future). Reaped
        # opportunistically (pump/_send_frame) so a long-lived node that
        # never calls drain() doesn't accumulate resolved futures; the
        # reaped tallies feed the next drain() report.
        self._outstanding: list = []
        # Serializes reap-and-tally against drain()'s read-and-reset:
        # _reap pops entries under _lock but tallies them outside it, so
        # without this barrier a background pump() could land a frame's
        # acked counts AFTER drain() zeroed the window — the votes would
        # vanish from every report. Held only across already-completed
        # futures (or drain's own bounded waits), never across sends.
        self._reap_lock = threading.Lock()
        self._acked = 0
        self._rejected = 0
        self._failed_frames = 0
        self._deferred_frames = 0
        # peer -> wall deadline of a server-hinted backoff window
        # (STATUS_RETRY_AFTER): until it passes, hot-path frames to that
        # peer defer straight to anti-entropy instead of re-offering
        # load the peer just said it cannot admit.
        self._retry_after: dict[str, float] = {}
        self._m_rounds = default_registry.counter(
            GOSSIP_ANTI_ENTROPY_ROUNDS_TOTAL
        )
        self._m_sessions = default_registry.counter(
            GOSSIP_ANTI_ENTROPY_SESSIONS_TOTAL
        )
        self._m_escalations = default_registry.counter(
            GOSSIP_CATCHUP_ESCALATIONS_TOTAL
        )
        self._m_deferred = default_registry.counter(
            GOSSIP_FRAMES_DEFERRED_TOTAL
        )
        self._running = True
        self._flusher: threading.Thread | None = None
        if flusher:
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name=f"gossip-flusher-{name}",
            )
            self._flusher.start()

    # ── membership ─────────────────────────────────────────────────────

    @property
    def engine(self):
        return self._engine

    @property
    def transport(self) -> GossipTransport:
        return self._transport

    def add_peer(self, name: str, host: str, port: int, peer_id: int) -> None:
        """Connect to a peer's bridge server (blocking HELLO) and join it
        to the fan-out set. ``peer_id`` is the peer's id ON THAT server
        (from its embedder's ADD_PEER)."""
        self._transport.connect(name, host, port)
        with self._lock:
            self._peers[name] = _PeerInfo(name, host, port, peer_id)
            self._dirty.setdefault(name, set())

    def peers(self) -> list[str]:
        with self._lock:
            return list(self._peers)

    # Bookkeeping cap: a pure fan-out driver (engine=None) never runs
    # anti-entropy, so nothing would ever prune its session/sticky-sample
    # maps — bound them and evict oldest-first (the ScopePlacement memo
    # precedent from the fleet). Engine-backed nodes lose only repair
    # coverage for sessions beyond the cap, which the cap makes explicit
    # instead of OOM-implicit.
    _MAX_TRACKED_SESSIONS = 65536

    def note_session(self, scope: str, pid: int) -> None:
        """Register a session for anti-entropy bookkeeping (call for
        locally created proposals; :meth:`submit_votes` calls it for the
        sessions it touches)."""
        with self._lock:
            pids = self._sessions.setdefault(scope, [])
            if pid not in pids:
                pids.append(pid)
                self._tracked += 1
            while self._tracked > self._MAX_TRACKED_SESSIONS:
                oldest_scope = next(iter(self._sessions))
                for old_pid in self._sessions.pop(oldest_scope):
                    self._session_targets.pop((oldest_scope, old_pid), None)
                    self._tracked -= 1
                for dirty in self._dirty.values():
                    dirty.discard(oldest_scope)

    # ── hot path: sampled fan-out through the coalescer ────────────────

    def submit_votes(
        self,
        scope: str,
        pid: int,
        votes: "list[bytes]",
        now: int,
        *,
        local: bool = True,
    ):
        """Deliver signed votes (wire bytes) for one session: apply to
        the local engine (when present and ``local``), then coalesce
        toward the session's sampled ``fanout`` subset of peers — the
        sample is drawn ONCE per (scope, pid) and reused for every
        subsequent chunk, so a peer either receives a session's votes in
        submission order or misses the session entirely (which
        anti-entropy repairs wholesale; interleaved fragments could
        not be). Returns the local ingest statuses (or None for a pure
        driver). Frames that trip a coalescer size threshold go on the
        wire immediately; call :meth:`pump` (or run the background
        flusher) to close trickle windows on the latency bound."""
        self.note_session(scope, pid)
        statuses = None
        if local and self._engine is not None:
            statuses = self._apply_local(scope, votes, now)
        with self._lock:
            names = self._session_targets.get((scope, pid))
            if names is None:
                names = list(self._peers)
                if self._fanout is not None and self._fanout < len(names):
                    names = self._rng.sample(names, self._fanout)
                self._session_targets[(scope, pid)] = names
        for name in names:
            info = self._peers[name]
            for vote in votes:
                ready = self._coalescer.add(name, info.peer_id, scope, vote, now)
                if ready is not None:
                    self._send_frame(name, *ready)
        return statuses

    def _apply_local(self, scope: str, votes: "list[bytes]", now: int):
        """Apply one session's vote blobs to the local engine. With a
        reactor and a columnar-capable engine, canonical rows enqueue as
        ONE columnar frame-entry into the engine's open window — merging
        with whatever wire frames the window already holds — then flush
        the engine's window and wait (this caller needs its statuses
        synchronously). Any non-canonical row falls the whole call back
        to the object path, preserving exact per-row statuses."""
        engine = self._engine
        reactor = self._reactor
        if (
            reactor is not None
            and votes
            and hasattr(engine, "ingest_wire_columnar")
        ):
            offsets = np.zeros(len(votes) + 1, np.int64)
            np.cumsum([len(v) for v in votes], out=offsets[1:])
            data = np.frombuffer(b"".join(votes), np.uint8)
            cols, flags = WC.parse_vote_columns(data, offsets)
            if flags.all():
                handle = reactor.submit(
                    engine,
                    [scope],
                    np.zeros(len(votes), np.int64),
                    cols,
                    data,
                    offsets,
                    now,
                )
                reactor.flush(engine)
                return np.asarray(handle.wait(30.0), np.int32)
        return engine.ingest_votes(
            [(scope, Vote.decode(v)) for v in votes], now
        )

    def pump(self) -> None:
        """Close coalescer windows past their latency bound and reap
        completed hot-path frames."""
        for name in self._coalescer.due():
            ready = self._coalescer.flush(name)
            if ready is not None:
                self._send_frame(name, *ready)
        self._reap()

    def flush_all(self) -> None:
        with self._lock:
            names = list(self._peers)
        for name in names:
            ready = self._coalescer.flush(name)
            if ready is not None:
                self._send_frame(name, *ready)

    def _defer_frame(self, name: str, meta) -> None:
        """Book one hot-path frame as deferred-to-repair (server-hinted
        overload): counted separately from failures, scopes dirty."""
        self._m_deferred.inc()
        with self._lock:
            self._deferred_frames += 1
            dirty = self._dirty.setdefault(name, set())
            for _, scope, _count in meta:
                dirty.add(scope)

    def _send_frame(self, name: str, payload: bytes, meta) -> None:
        with self._lock:
            until = self._retry_after.get(name)
        if until is not None:
            if time.monotonic() < until:
                # The peer's backoff window is still open: don't re-offer
                # load it just shed — anti-entropy repairs these scopes.
                self._defer_frame(name, meta)
                return
            with self._lock:
                self._retry_after.pop(name, None)
        future = self._transport.try_request(name, P.OP_VOTE_BATCH, payload)
        if future is None:
            # Shed under backpressure: the peer owes these scopes an
            # anti-entropy push; memory stays bounded either way.
            with self._lock:
                dirty = self._dirty.setdefault(name, set())
                for _, scope, _count in meta:
                    dirty.add(scope)
            return
        with self._lock:
            self._outstanding.append((name, meta, future))
            backlog = len(self._outstanding)
        if backlog > 64:  # opportunistic trim on the hot path
            self._reap()

    def _harvest(self, name: str, meta, future, budget: float | None) -> None:
        """Tally one completed (or awaited) frame into the cumulative
        counters; failures mark the frame's scopes dirty for repair."""
        try:
            statuses = parse_status_list(
                future.result(budget if budget is not None else 0)
            )
        except BridgeError as exc:
            if exc.status == P.STATUS_RETRY_AFTER:
                # Typed overload shed: nothing was applied. Honor the
                # server-computed hint (bounded — a garbled payload
                # falls back to a short fixed window) and stop offering
                # this peer hot-path load until it passes.
                try:
                    hint = min(5.0, max(0.0, float(exc.message)))
                except (TypeError, ValueError):
                    hint = 0.05
                with self._lock:
                    self._retry_after[name] = time.monotonic() + hint
                self._defer_frame(name, meta)
                return
            with self._lock:
                self._failed_frames += 1
                dirty = self._dirty.setdefault(name, set())
                for _, scope, _count in meta:
                    dirty.add(scope)
            return
        except (BridgeConnectionLost, TimeoutError, _FutureTimeout, OSError):
            with self._lock:
                self._failed_frames += 1
                dirty = self._dirty.setdefault(name, set())
                for _, scope, _count in meta:
                    dirty.add(scope)
            return
        acked = rejected = 0
        for code in statuses:
            if code in (_OK, int(StatusCode.ALREADY_REACHED)):
                acked += 1
            else:
                rejected += 1
        with self._lock:
            self._acked += acked
            self._rejected += rejected

    def _reap(self) -> None:
        """Harvest every already-completed hot-path frame (non-blocking);
        unresolved futures stay outstanding."""
        with self._reap_lock:
            with self._lock:
                # ONE done() probe per entry: futures resolve on the
                # transport's reader thread, so probing once for a
                # "done" list and again for the remainder would drop
                # any frame that completes between the two passes —
                # harvested by neither, its acks vanish from every
                # report.
                done: list = []
                remaining: list = []
                for entry in self._outstanding:
                    (done if entry[2].done() else remaining).append(entry)
                if not done:
                    return
                self._outstanding = remaining
            for name, meta, future in done:
                self._harvest(name, meta, future, None)

    def drain(self, timeout: float = 30.0) -> dict:
        """Flush everything pending and await every in-flight hot-path
        frame. Returns the delivery counts accumulated since the last
        drain (opportunistic reaps included); failed frames (peer died
        mid-flight) mark their scopes dirty for anti-entropy."""
        self.flush_all()
        deadline = time.monotonic() + timeout
        # _reap_lock: a background pump()'s reap may have popped frames
        # it has not tallied yet — taking the lock here waits for those
        # tallies to land before this window is read and reset, so no
        # frame's counts ever fall between two reports. flush_all stays
        # OUTSIDE the lock (its _send_frame path can reap on backlog).
        with self._reap_lock:
            with self._lock:
                outstanding = self._outstanding
                self._outstanding = []
            for name, meta, future in outstanding:
                self._harvest(name, meta, future,
                              max(0.0, deadline - time.monotonic()))
            shed = sum(
                ch["shed_total"] for ch in self._transport.stats().values()
            )
            with self._lock:
                report = {
                    "acked": self._acked,
                    "rejected": self._rejected,
                    "failed_frames": self._failed_frames,
                    "deferred_frames": self._deferred_frames,
                    "shed_total": shed,
                }
                self._acked = self._rejected = self._failed_frames = 0
                self._deferred_frames = 0
        return report

    # ── repair path: anti-entropy + catch-up escalation ────────────────

    def anti_entropy(
        self,
        now: int,
        *,
        peers: "list[str] | None" = None,
        max_sessions: int = 128,
        window: int = 16,
        timeout: float = 30.0,
    ) -> dict:
        """One push round: deliver full proposals (whole retained vote
        chains) to each target peer — shed-dirty scopes first, then a
        rotating slice of all known sessions up to ``max_sessions`` per
        peer. Frames are windowed (``window`` sessions each) and awaited
        one at a time, so repair traffic can never trip its own
        backpressure shed. Requires a local engine.

        If the local engine is FRESH (no live sessions) and a probed
        peer serves state sync with at least ``escalate_sessions``
        sessions, the round escalates to a full snapshot+tail catch-up
        from that peer before pushing anything."""
        if self._engine is None:
            raise RuntimeError("anti-entropy needs a local engine")
        self._m_rounds.inc()
        report: dict = {
            "pushed_sessions": 0, "created_or_extended": 0,
            "redelivered": 0, "rejected": 0, "failed": 0,
            "escalated": None,
        }
        escalation = self._maybe_escalate(report)
        if escalation is not None:
            return report
        with self._lock:
            targets = [
                self._peers[name]
                for name in (peers if peers is not None else list(self._peers))
                if name in self._peers
            ]
        for info in targets:
            self._push_to_peer(info, now, max_sessions, window, timeout, report)
        flight_recorder.record(
            "gossip.anti_entropy", node=self.name,
            pushed=report["pushed_sessions"],
            redelivered=report["redelivered"], failed=report["failed"],
        )
        return report

    def _session_batch(self, name: str, max_sessions: int) -> list[tuple[str, int]]:
        """(scope, pid) batch for one peer: dirty scopes first, then a
        ROTATING slice of everything else — the per-peer cursor advances
        by what each round takes, so rounds eventually cover every
        session even when one round's budget can't. The engine is the
        source of truth for live sessions — evicted pids drop out of the
        bookkeeping in `_push_to_peer`."""
        with self._lock:
            dirty_scopes = self._dirty.get(name, set())
            out: list[tuple[str, int]] = []
            for scope in dirty_scopes:
                for pid in self._sessions.get(scope, ()):
                    out.append((scope, pid))
                    if len(out) >= max_sessions:
                        return out
            rest = [
                (scope, pid)
                for scope in self._sessions
                if scope not in dirty_scopes
                for pid in self._sessions[scope]
            ]
            room = max_sessions - len(out)
            if room > 0 and rest:
                start = self._rotation.get(name, 0) % len(rest)
                take = min(room, len(rest))
                out.extend(rest[(start + i) % len(rest)] for i in range(take))
                self._rotation[name] = (start + take) % len(rest)
        return out

    def _push_to_peer(
        self, info: _PeerInfo, now: int, max_sessions: int, window: int,
        timeout: float, report: dict,
    ) -> None:
        batch = self._session_batch(info.name, max_sessions)
        pushed_scopes: set[str] = set()
        items: list[tuple[str, bytes]] = []
        frames: list[tuple[list[tuple[str, bytes]], set[str]]] = []
        scopes_in_frame: set[str] = set()
        for scope, pid in batch:
            try:
                proposal = self._engine.get_proposal(scope, pid)
            except ConsensusError:
                with self._lock:  # evicted locally: stop tracking it
                    pids = self._sessions.get(scope)
                    if pids and pid in pids:
                        pids.remove(pid)
                        self._tracked -= 1
                    self._session_targets.pop((scope, pid), None)
                continue
            items.append((scope, proposal.encode()))
            scopes_in_frame.add(scope)
            if len(items) >= window:
                frames.append((items, scopes_in_frame))
                items, scopes_in_frame = [], set()
        if items:
            frames.append((items, scopes_in_frame))
        for frame_items, frame_scopes in frames:
            try:
                future = self._transport.request(
                    info.name,
                    P.OP_DELIVER_PROPOSALS,
                    P.encode_deliver_proposals(info.peer_id, frame_items, now),
                )
                statuses = parse_status_list(future.result(timeout))
            except (ChannelBusy, BridgeError, BridgeConnectionLost,
                    TimeoutError, _FutureTimeout, OSError, KeyError):
                report["failed"] += len(frame_items)
                continue  # scopes stay dirty; next round retries
            report["pushed_sessions"] += len(frame_items)
            self._m_sessions.inc(len(frame_items))
            for code in statuses:
                if code == _OK:
                    report["created_or_extended"] += 1
                elif code == _ALREADY:
                    report["redelivered"] += 1
                else:
                    report["rejected"] += 1
            pushed_scopes |= frame_scopes
        with self._lock:
            self._dirty.setdefault(info.name, set()).difference_update(
                pushed_scopes
            )

    def _maybe_escalate(self, report: dict):
        """Fresh local engine + a peer far ahead = snapshot catch-up, not
        thousands of deliver frames. Probes ONE sampled peer's sync
        manifest (undurable peers reject the probe; that just skips
        escalation this round)."""
        occupancy = getattr(self._engine, "occupancy", None)
        if occupancy is None or occupancy().get("live_sessions", 0):
            return None
        with self._lock:
            infos = list(self._peers.values())
        if not infos:
            return None
        info = self._rng.choice(infos)
        try:
            future = self._transport.request(
                info.name, P.OP_SYNC_MANIFEST,
                P.u32(info.peer_id) + P.u32(0),
            )
            manifest = parse_sync_manifest(future.result(30.0))
        except (ChannelBusy, BridgeError, BridgeConnectionLost,
                TimeoutError, _FutureTimeout, OSError, KeyError, ValueError):
            return None  # undurable / unreachable: incremental repair only
        if manifest["session_count"] < self._escalate_sessions:
            return None
        if self._catchup_factory is not None:
            client_factory = self._catchup_factory
        else:
            from ..sync import CatchUpClient

            client_factory = CatchUpClient
        with client_factory(info.host, info.port, info.peer_id) as client:
            catchup = client.catch_up(self._engine)
        self._m_escalations.inc()
        flight_recorder.record(
            "gossip.escalate", node=self.name, source=info.name,
            sessions=catchup.sessions_installed,
            tail_records=catchup.tail_records, seconds=catchup.seconds,
        )
        # The installed sessions join the anti-entropy bookkeeping so
        # this node can serve repair pushes for them too.
        session_keys = getattr(self._engine, "session_keys", None)
        if session_keys is not None:
            for scope, pid in session_keys():
                self.note_session(scope, pid)
        report["escalated"] = {
            "source": info.name,
            "sessions_installed": catchup.sessions_installed,
            "votes_verified": catchup.votes_verified,
            "tail_records": catchup.tail_records,
            "seconds": catchup.seconds,
        }
        return report["escalated"]

    # ── lifecycle ──────────────────────────────────────────────────────

    def _flush_loop(self) -> None:
        while self._running:
            self.pump()
            time.sleep(self._coalescer.flush_interval / 2)

    def close(self) -> None:
        self._running = False
        if self._flusher is not None:
            self._flusher.join(timeout=5)
        if self._owns_reactor and self._reactor is not None:
            self._reactor.stop()
        if self._owns_transport:
            self._transport.close()
