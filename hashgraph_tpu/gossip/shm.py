"""Shared-memory ring transport for co-located bridge peers.

Same-host shards used to speak loopback TCP: every coalesced frame paid
two syscalls, two kernel copies, and the socket wakeup path. This module
replaces that hop with a pair of single-producer/single-consumer byte
rings in POSIX shared memory (``multiprocessing.shared_memory``), one
per direction. A frame is then ONE userspace memcpy each way, and the
byte stream inside the ring is exactly the bridge's tagged frame stream
— the same incremental parser both ends already run over TCP consumes
it unchanged.

Negotiation (see :mod:`hashgraph_tpu.bridge.protocol`): the client
offers ``FEATURE_SHM_RING`` at HELLO; on grant — and only for loopback
endpoints — it creates the two rings and sends ``OP_SHM_ATTACH`` with
their names over the still-blocking socket. Any failure (feature not
granted, old server, ``/dev/shm`` unavailable, cross-container peer
that cannot map the name) falls back to TCP silently: the socket stays
open as the control lane either way, and its close tears the rings
down on both sides.

Ring layout (``HEADER_BYTES`` header + data):

    [0:8)  head — total bytes ever written (u64 LE, producer-owned)
    [8:16) tail — total bytes ever read    (u64 LE, consumer-owned)
    [16:16+capacity) data, addressed modulo capacity

Head is stored only AFTER the frame bytes are in place and tail only
after they are consumed, so the single producer and single consumer
never read a torn frame. That publish ordering is a TOTAL-STORE-ORDER
property: plain stores through a shared mapping are only guaranteed to
become visible in program order on x86/TSO machines, so
:func:`shm_available` reports False on weakly-ordered architectures
(aarch64 & co) and those hosts keep the TCP lane — correct, just
without the shm shortcut — until the ring grows real barriers. Writes
are all-or-nothing: a frame that does not fit reports False and the
caller falls back (bounded backpressure, never a partial frame).
"""

from __future__ import annotations

import platform
import struct
import time

HEADER_BYTES = 16
_U64 = struct.Struct("<Q")

# Architectures whose plain aligned stores publish in program order
# (total store order) — the property the head-after-payload commit
# protocol depends on. Everything else degrades to TCP.
_TSO_MACHINES = {"x86_64", "amd64", "i686", "i386"}

try:  # pragma: no cover - platform gate
    from multiprocessing import shared_memory as _shm
except ImportError:  # pragma: no cover
    _shm = None


def shm_available() -> bool:
    return _shm is not None and platform.machine().lower() in _TSO_MACHINES


def _untrack(shm) -> None:
    """Detach an ATTACHED mapping from the resource tracker: the creator
    owns unlink; without this, the attaching process's tracker would
    destroy the segment at exit and warn about a leak it caused."""
    try:  # pragma: no cover - stdlib internals, best effort
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


class ShmRing:
    """One SPSC byte ring over a shared-memory segment."""

    __slots__ = ("shm", "capacity", "_buf", "_owner")

    # Names created by THIS process: a same-process attach (tests, the
    # in-process gossip smoke) must not untrack them — the creator's
    # registration is the one the unlink path balances.
    _created: "set[str]" = set()

    def __init__(self, shm, owner: bool):
        self.shm = shm
        self.capacity = shm.size - HEADER_BYTES
        self._buf = shm.buf
        self._owner = owner

    @classmethod
    def create(cls, capacity: int) -> "ShmRing":
        if _shm is None:
            raise RuntimeError("shared_memory unavailable on this platform")
        shm = _shm.SharedMemory(create=True, size=HEADER_BYTES + capacity)
        shm.buf[:HEADER_BYTES] = bytes(HEADER_BYTES)
        cls._created.add(shm.name)
        return cls(shm, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        if _shm is None:
            raise RuntimeError("shared_memory unavailable on this platform")
        shm = _shm.SharedMemory(name=name)
        if name not in cls._created:
            _untrack(shm)
        return cls(shm, owner=False)

    @property
    def name(self) -> str:
        return self.shm.name

    def _live_buf(self):
        """The mapping, snapshotted ONCE per operation; raises ValueError
        once :meth:`close` swapped it out. A snapshot taken before a
        concurrent close stays valid — the exported view keeps the
        mapping alive (``SharedMemory.close`` defers to it)."""
        buf = self._buf
        if len(buf) < HEADER_BYTES:
            raise ValueError("shm ring is closed")
        return buf

    def try_write(self, segments: "list[bytes]", total: int) -> bool:
        """Append ``segments`` (``total`` bytes) as one atomic unit;
        False when the ring lacks space (caller sheds or falls back).
        Single producer: callers serialize writers themselves. Raises
        ValueError on a closed ring (channel died under the caller)."""
        buf = self._live_buf()
        head = _U64.unpack_from(buf, 0)[0]
        if total > self.capacity - (head - _U64.unpack_from(buf, 8)[0]):
            return False
        cap = self.capacity
        pos = head % cap
        for seg in segments:
            view = memoryview(seg)
            n = len(view)
            first = min(n, cap - pos)
            buf[HEADER_BYTES + pos:HEADER_BYTES + pos + first] = view[:first]
            if first < n:
                buf[HEADER_BYTES:HEADER_BYTES + n - first] = view[first:]
            pos = (pos + n) % cap
        _U64.pack_into(buf, 0, head + total)
        return True

    def pending_bytes(self) -> int:
        """Bytes written but not yet read (0 = the consumer has drained
        everything). Raises ValueError on a closed ring."""
        buf = self._live_buf()
        return _U64.unpack_from(buf, 0)[0] - _U64.unpack_from(buf, 8)[0]

    def read_available(self, limit: int = 1 << 20) -> bytes | None:
        """Drain up to ``limit`` buffered bytes (None when empty). The
        stream is frame-structured by the caller's parser, so partial
        frames across calls are fine. Raises ValueError on a closed
        ring (channel died under the caller)."""
        buf = self._live_buf()
        tail = _U64.unpack_from(buf, 8)[0]
        n = _U64.unpack_from(buf, 0)[0] - tail
        if n <= 0:
            return None
        n = min(n, limit)
        cap = self.capacity
        pos = tail % cap
        first = min(n, cap - pos)
        out = bytes(buf[HEADER_BYTES + pos:HEADER_BYTES + pos + first])
        if first < n:
            out += bytes(buf[HEADER_BYTES:HEADER_BYTES + n - first])
        _U64.pack_into(buf, 8, tail + n)
        return out

    def close(self) -> None:
        self._buf = memoryview(b"")
        try:
            self.shm.close()
        except (OSError, BufferError):
            pass
        if self._owner:
            ShmRing._created.discard(self.shm.name)
            try:
                self.shm.unlink()
            except OSError:
                pass


class ShmSpin:
    """Adaptive poll pacing for ring consumers: spin a little while the
    stream is hot, back off to short sleeps when idle — latency stays
    in the microseconds under load without burning a core at rest."""

    __slots__ = ("_misses",)

    def __init__(self):
        self._misses = 0

    def hit(self) -> None:
        self._misses = 0

    def wait(self) -> None:
        self._misses += 1
        if self._misses < 200:
            return  # hot spin
        time.sleep(0.0002 if self._misses < 2000 else 0.002)
