"""Send-side vote coalescing: many small votes, one columnar frame.

The engine ingests hundreds of thousands of votes per second, but a
gossip arrival is tiny — one signed vote is ~200 bytes — and both the
wire AND the engine charge a fixed cost per frame/dispatch. The
:class:`VoteCoalescer` closes that gap: votes destined for one peer
accumulate into (peer_id, scope)-keyed groups and flush as ONE
``OP_VOTE_BATCH`` frame per (peer, window), where a window closes on
whichever trips first:

- ``flush_votes`` — enough votes to amortize the dispatch,
- ``flush_bytes`` — keep frames well under the wire cap,
- ``flush_interval`` — latency bound; a trickle never waits longer.

Order is preserved end to end (groups keep insertion order, votes keep
append order, the server's pipelined vote lane applies frames in receive
order), so coalescing never reorders a vote chain.
"""

from __future__ import annotations

import threading
import time

from ..bridge import protocol as P
from ..obs import GOSSIP_VOTES_COALESCED_TOTAL
from ..obs import registry as default_registry


class _Window:
    __slots__ = ("groups", "votes", "bytes", "opened", "now")

    def __init__(self, opened: float):
        # (peer_id, scope) -> list[vote bytes]; insertion-ordered.
        self.groups: dict[tuple[int, str], list[bytes]] = {}
        self.votes = 0
        self.bytes = 0
        self.opened = opened
        self.now = 0  # logical consensus time for the frame (max of adds)


class VoteCoalescer:
    """Per-peer vote packing with bounded windows. Thread-safe."""

    def __init__(
        self,
        *,
        flush_votes: int = 256,
        flush_bytes: int = 512 * 1024,
        flush_interval: float = 0.005,
        clock=time.monotonic,
    ):
        self.flush_votes = flush_votes
        self.flush_bytes = flush_bytes
        self.flush_interval = flush_interval
        self._clock = clock
        self._lock = threading.Lock()
        self._windows: dict[str, _Window] = {}
        self._m_votes = default_registry.counter(GOSSIP_VOTES_COALESCED_TOTAL)

    def add(
        self,
        peer_name: str,
        peer_id: int,
        scope: str,
        vote: bytes,
        now: int,
    ) -> "tuple[bytes, list[tuple[int, str, int]]] | None":
        """Buffer one vote for ``peer_name``. Returns a ready frame —
        ``(payload, meta)`` as :meth:`flush` — when this add trips a
        size threshold, else None (the window stays open for more)."""
        with self._lock:
            window = self._windows.get(peer_name)
            if window is None:
                window = self._windows[peer_name] = _Window(self._clock())
            window.groups.setdefault((peer_id, scope), []).append(vote)
            window.votes += 1
            window.bytes += len(vote)
            window.now = max(window.now, now)
            if (
                window.votes >= self.flush_votes
                or window.bytes >= self.flush_bytes
            ):
                return self._seal(peer_name, window)
            return None

    def flush(
        self, peer_name: str
    ) -> "tuple[bytes, list[tuple[int, str, int]]] | None":
        """Seal ``peer_name``'s open window now (interval expiry, drain,
        shutdown). Returns ``(payload, meta)`` — the encoded
        ``OP_VOTE_BATCH`` payload and its ``(peer_id, scope, count)``
        meta, which the sender uses to mark scopes dirty if the frame
        sheds — or None when nothing is buffered."""
        with self._lock:
            window = self._windows.get(peer_name)
            if window is None or not window.votes:
                return None
            return self._seal(peer_name, window)

    def due(self) -> list[str]:
        """Peers whose open window exceeded ``flush_interval``."""
        deadline = self._clock() - self.flush_interval
        with self._lock:
            return [
                name
                for name, window in self._windows.items()
                if window.votes and window.opened <= deadline
            ]

    def pending(self, peer_name: str) -> int:
        with self._lock:
            window = self._windows.get(peer_name)
            return window.votes if window is not None else 0

    def extract(
        self, peer_name: str, predicate
    ) -> "list[tuple[int, str, list[bytes], int]]":
        """Surgically remove the groups whose scope satisfies
        ``predicate(scope)`` from ``peer_name``'s open window, returning
        ``(peer_id, scope, votes, window_now)`` tuples (insertion
        order). The federation driver drains a migrating shard's queued
        votes into its migration tail this way — the rest of the window
        stays queued for its original destination."""
        with self._lock:
            window = self._windows.get(peer_name)
            if window is None:
                return []
            out = []
            for key in [k for k in window.groups if predicate(k[1])]:
                votes = window.groups.pop(key)
                window.votes -= len(votes)
                window.bytes -= sum(len(v) for v in votes)
                out.append((key[0], key[1], votes, window.now))
            if not window.groups:
                del self._windows[peer_name]
            return out

    def _seal(self, peer_name: str, window: _Window):
        # Caller holds the lock. The payload is a SEGMENT LIST (frame
        # head + the buffered vote bytes objects, un-joined): the
        # transport scatter-gathers it to the socket or shm ring, so the
        # votes are never concatenated on the send side
        # (protocol.encode_vote_batch_segments).
        del self._windows[peer_name]
        groups = [
            (peer_id, scope, votes)
            for (peer_id, scope), votes in window.groups.items()
        ]
        self._m_votes.inc(window.votes)
        payload, _nbytes = P.encode_vote_batch_segments(window.now, groups)
        meta = [(peer_id, scope, len(votes)) for peer_id, scope, votes in groups]
        return payload, meta
