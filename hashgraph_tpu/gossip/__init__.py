"""Gossip fabric, phase 1: multiplexed pipelined transport.

The bridge (:mod:`hashgraph_tpu.bridge`) is the FFI boundary — strictly
request/response, one frame at a time. This package is the throughput
layer on top of the SAME wire protocol: a selectors-based event-loop
transport with connection multiplexing and frame pipelining
(:class:`GossipTransport`), send-side vote coalescing into columnar
batch frames (:class:`VoteCoalescer`), and a :class:`GossipNode` that
fans deliveries to a sampled peer subset and repairs divergence with
periodic anti-entropy over the engine's validated-chain watermark,
escalating far-behind peers to the state-sync catch-up path.

Feature negotiation (``OP_HELLO``) keeps old and new peers
interoperable in both directions; see
:mod:`hashgraph_tpu.bridge.protocol` for the wire additions.
"""

from .coalescer import VoteCoalescer
from .node import GossipNode
from .transport import ChannelBusy, GossipTransport, PeerChannel

__all__ = [
    "ChannelBusy",
    "GossipNode",
    "GossipTransport",
    "PeerChannel",
    "VoteCoalescer",
]
