"""Selectors-based multiplexed transport for the gossip fabric.

One :class:`GossipTransport` drives MANY peer connections from ONE
event-loop thread (`selectors`, non-blocking sockets — no asyncio in the
library core, matching the reference's no-I/O embedder contract: the
loop is plain stdlib an embedder can reason about and replace). Each
:class:`PeerChannel` speaks the bridge wire protocol with the features
its server granted at HELLO:

- against a new server (``FEATURE_PIPELINING``): tagged frames, many in
  flight, responses matched by correlation id;
- against an OLD server: the one-at-a-time framing with a FIFO response
  match and an in-flight window of 1 — same API, interop preserved.

**Backpressure is explicit and bounded.** Every channel has a byte-capped
send queue and a credit window (``max_inflight`` unanswered requests).
Credits gate *sending* — queued frames wait; once the queue's byte cap
would be exceeded, :meth:`GossipTransport.try_request` refuses the frame
(*sheds*) instead of buffering without bound. The caller — the
:class:`~hashgraph_tpu.gossip.node.GossipNode` — records what it shed
and repairs via anti-entropy later, so a slow peer costs a bounded queue
plus deferred repair, never ballooning memory.

A dropped connection fails every queued and in-flight future with
:class:`~hashgraph_tpu.bridge.client.BridgeConnectionLost` — a typed,
per-request signal, never a silent hang.
"""

from __future__ import annotations

import selectors
import socket
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future

from ..bridge import protocol as P
from ..bridge.client import BridgeConnectionLost, BridgeError, ReconnectPolicy
from ..obs import (
    GOSSIP_FRAMES_SENT_TOTAL,
    GOSSIP_FRAMES_SHED_TOTAL,
    GOSSIP_INFLIGHT_REQUESTS,
    GOSSIP_SEND_QUEUE_BYTES,
    flight_recorder,
)
from ..obs import registry as default_registry

_RECV_CHUNK = 256 * 1024


def _weak_sample(ref, method_name):
    """Gauge provider over a weakly-referenced transport (0 once dead)."""

    def sample():
        transport = ref()
        return 0 if transport is None else getattr(transport, method_name)()

    return sample


class PeerChannel:
    """One multiplexed connection to a peer's bridge server. Owned by a
    :class:`GossipTransport`; all socket I/O happens on the transport's
    event-loop thread, callers only enqueue frames and await futures."""

    def __init__(self, name: str, sock: socket.socket, features: int,
                 max_inflight: int, max_queue_bytes: int):
        self.name = name
        self.sock = sock
        self.features = features
        self.pipelined = bool(features & P.FEATURE_PIPELINING)
        self.max_inflight = max_inflight if self.pipelined else 1
        self.max_queue_bytes = max_queue_bytes
        self.alive = True
        self.error: Exception | None = None
        # Guarded by the channel lock: send queue + accounting. Frames
        # are fully encoded at enqueue time (the loop thread only moves
        # bytes).
        self.lock = threading.Lock()
        self.sendq: deque[tuple[bytes, Future]] = deque()
        self.queue_bytes = 0
        self.shed_total = 0
        # Loop-thread-only state: the frame currently being written and
        # the unanswered requests. Tagged channels match by correlation
        # id; untagged channels complete FIFO.
        self.outbuf: memoryview | None = None
        self.outfut: Future | None = None
        self.inflight: dict[int, Future] = {}
        self.fifo: deque[Future] = deque()
        self.next_corr = 0
        self.rbuf = bytearray()

    # ── accounting (any thread) ────────────────────────────────────────

    def inflight_count(self) -> int:
        return len(self.inflight) + len(self.fifo) + (
            1 if self.outfut is not None else 0
        )

    def stats(self) -> dict:
        with self.lock:
            return {
                "alive": self.alive,
                "pipelined": self.pipelined,
                "queue_frames": len(self.sendq),
                "queue_bytes": self.queue_bytes,
                "inflight": self.inflight_count(),
                "shed_total": self.shed_total,
            }


class GossipTransport:
    """Multiplexed, pipelined fan-out over many bridge connections.

    ``connect`` performs the blocking HELLO handshake, then hands the
    socket to the event loop. ``try_request`` enqueues one frame for a
    peer and returns a future resolving to the response payload cursor
    (or raising :class:`BridgeError` / :class:`BridgeConnectionLost`) —
    or returns ``None`` when the peer's send queue is at its byte cap
    (the shed signal). All sockets run ``TCP_NODELAY``; pass ``sndbuf``/
    ``rcvbuf`` for high-BDP links (see :func:`bridge.protocol.tune_socket`).
    """

    def __init__(
        self,
        *,
        max_inflight: int = 128,
        max_queue_bytes: int = 4 * 1024 * 1024,
        connect_timeout: float = 5.0,
        features: int = P.SUPPORTED_FEATURES,
        sndbuf: int | None = None,
        rcvbuf: int | None = None,
        reconnect: "ReconnectPolicy | None" = None,
    ):
        self._max_inflight = max_inflight
        self._max_queue_bytes = max_queue_bytes
        self._connect_timeout = connect_timeout
        self._features = features
        self._sndbuf = sndbuf
        self._rcvbuf = rcvbuf
        # Opt-in channel healing: when a peer's channel dies (and the
        # transport itself is not closing), re-dial it with capped
        # jittered backoff and a fresh HELLO. In-flight and queued
        # futures on the dead channel still fail typed — only the
        # CHANNEL heals; lost frames are the anti-entropy layer's job.
        self._reconnect = reconnect
        self._endpoints: dict[str, tuple[str, int]] = {}
        self._reconnecting: set[str] = set()
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._channels: dict[str, PeerChannel] = {}
        self._pending_register: list[PeerChannel] = []
        self._lock = threading.Lock()
        self._running = True
        self._m_sent = default_registry.counter(GOSSIP_FRAMES_SENT_TOTAL)
        self._m_shed = default_registry.counter(GOSSIP_FRAMES_SHED_TOTAL)
        # Providers close over a WEAK ref (the engine/WAL convention): a
        # bound method's __self__ would strongly pin every transport ever
        # created into the process-global registry — the owner weakref
        # only prunes the entry once the owner can actually die.
        ref = weakref.ref(self)
        default_registry.gauge(GOSSIP_SEND_QUEUE_BYTES).add_provider(
            _weak_sample(ref, "_total_queue_bytes"), owner=self
        )
        default_registry.gauge(GOSSIP_INFLIGHT_REQUESTS).add_provider(
            _weak_sample(ref, "_total_inflight"), owner=self
        )
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="gossip-transport"
        )
        self._thread.start()

    # ── lifecycle ──────────────────────────────────────────────────────

    def close(self) -> None:
        self._running = False
        self._wake()
        self._thread.join(timeout=5)
        with self._lock:
            channels = list(self._channels.values())
        for ch in channels:
            self._kill_channel(
                ch, BridgeConnectionLost("transport closed"), record=False
            )
        try:
            self._wake_r.close()
            self._wake_w.close()
        except OSError:
            pass
        self._sel.close()

    def __enter__(self) -> "GossipTransport":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    # ── connections ────────────────────────────────────────────────────

    def connect(self, name: str, host: str, port: int) -> PeerChannel:
        """Open (blocking) a channel to a peer's bridge server and
        negotiate features; the socket then joins the event loop. A
        ``name`` can be reconnected after its channel died — the dead
        channel is replaced."""
        if not self._running:
            raise RuntimeError("transport is closed")
        sock = socket.create_connection(
            (host, port), timeout=self._connect_timeout
        )
        P.tune_socket(sock, sndbuf=self._sndbuf, rcvbuf=self._rcvbuf)
        features = 0
        try:
            sock.sendall(P.encode_frame(
                P.OP_HELLO,
                P.u32(P.PROTOCOL_VERSION) + P.u32(self._features),
            ))
            status, cursor = P.read_frame(sock)
            if status == P.STATUS_OK:
                cursor.u32()  # server protocol version
                features = cursor.u32()
            elif status != P.STATUS_UNKNOWN_OPCODE:
                raise BridgeError(status)
        except BaseException:
            sock.close()
            raise
        sock.setblocking(False)
        channel = PeerChannel(
            name, sock, features, self._max_inflight, self._max_queue_bytes
        )
        with self._lock:
            # Re-checked at registration time: a reconnect attempt's
            # blocking dial can race close() past the entry check, and a
            # channel registered after the loop thread exited would
            # never be serviced — its futures would hang instead of
            # failing typed.
            if not self._running:
                sock.close()
                raise RuntimeError("transport is closed")
            old = self._channels.get(name)
            if old is not None and old.alive:
                sock.close()
                raise ValueError(f"peer {name!r} already connected")
            self._channels[name] = channel
            self._pending_register.append(channel)
            self._endpoints[name] = (host, port)
        self._wake()
        return channel

    def channel(self, name: str) -> PeerChannel | None:
        with self._lock:
            return self._channels.get(name)

    def stats(self) -> dict:
        with self._lock:
            channels = dict(self._channels)
        return {name: ch.stats() for name, ch in channels.items()}

    # ── requests ───────────────────────────────────────────────────────

    def try_request(
        self, name: str, opcode: int, payload: bytes = b""
    ) -> Future | None:
        """Enqueue one request for ``name``; None = shed (queue at its
        byte cap — bounded backpressure, the caller repairs later)."""
        with self._lock:
            channel = self._channels.get(name)
        if channel is None:
            raise KeyError(f"unknown peer {name!r}")
        if not channel.alive:
            future: Future = Future()
            future.set_exception(
                channel.error
                or BridgeConnectionLost(f"peer {name!r} disconnected")
            )
            return future
        if channel.pipelined:
            with channel.lock:
                corr = channel.next_corr
                channel.next_corr = (corr + 1) & 0xFFFFFFFF
            frame = P.encode_tagged_frame(opcode, corr, payload)
        else:
            frame = P.encode_frame(opcode, payload)
        future = Future()
        with channel.lock:
            # Re-checked under the SAME lock _kill_channel drains the
            # queue with: without this, a frame enqueued between the
            # loop thread's kill-drain and our append would sit on a
            # dead channel with its future never resolved.
            if not channel.alive:
                future.set_exception(
                    channel.error
                    or BridgeConnectionLost(f"peer {name!r} disconnected")
                )
                return future
            if channel.queue_bytes + len(frame) > channel.max_queue_bytes:
                channel.shed_total += 1
                self._m_shed.inc()
                flight_recorder.record(
                    "gossip.shed", peer=name, opcode=opcode,
                    queue_bytes=channel.queue_bytes,
                )
                return None
            channel.sendq.append((frame, future))
            channel.queue_bytes += len(frame)
        self._wake()
        return future

    def request(self, name: str, opcode: int, payload: bytes = b"") -> Future:
        """:meth:`try_request` that raises :class:`ChannelBusy` instead
        of returning None — for control traffic the caller windows
        itself (anti-entropy sends one frame and awaits it)."""
        future = self.try_request(name, opcode, payload)
        if future is None:
            raise ChannelBusy(f"peer {name!r} send queue is full")
        return future

    # ── gauge providers ────────────────────────────────────────────────

    def _total_queue_bytes(self) -> int:
        with self._lock:
            channels = list(self._channels.values())
        return sum(ch.queue_bytes for ch in channels)

    def _total_inflight(self) -> int:
        with self._lock:
            channels = list(self._channels.values())
        return sum(ch.inflight_count() for ch in channels)

    # ── event loop (loop thread only below) ────────────────────────────

    def _loop(self) -> None:
        while self._running:
            self._register_pending()
            self._refresh_interest()
            for key, mask in self._sel.select(timeout=0.1):
                if key.data is None:  # wake pipe
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                channel: PeerChannel = key.data
                try:
                    if mask & selectors.EVENT_WRITE:
                        self._on_writable(channel)
                    if mask & selectors.EVENT_READ:
                        self._on_readable(channel)
                except (ConnectionError, OSError, ValueError) as exc:
                    self._kill_channel(channel, BridgeConnectionLost(
                        f"peer {channel.name!r} connection lost: {exc}"
                    ))

    def _register_pending(self) -> None:
        with self._lock:
            fresh = self._pending_register
            self._pending_register = []
        for channel in fresh:
            if channel.alive:
                self._sel.register(channel.sock, selectors.EVENT_READ, channel)

    def _refresh_interest(self) -> None:
        with self._lock:
            channels = list(self._channels.values())
        for channel in channels:
            if not channel.alive:
                continue
            want = selectors.EVENT_READ
            credits = channel.max_inflight - channel.inflight_count()
            with channel.lock:
                has_frames = bool(channel.sendq) or channel.outbuf is not None
            if has_frames and (credits > 0 or channel.outbuf is not None):
                want |= selectors.EVENT_WRITE
            try:
                self._sel.modify(channel.sock, want, channel)
            except (KeyError, ValueError):
                pass  # not registered yet / already unregistered

    def _on_writable(self, channel: PeerChannel) -> None:
        while True:
            if channel.outbuf is None:
                credits = channel.max_inflight - channel.inflight_count()
                if credits <= 0:
                    return
                with channel.lock:
                    if not channel.sendq:
                        return
                    frame, future = channel.sendq.popleft()
                    channel.queue_bytes -= len(frame)
                channel.outbuf = memoryview(frame)
                channel.outfut = future
            sent = channel.sock.send(channel.outbuf)
            if sent < len(channel.outbuf):
                channel.outbuf = channel.outbuf[sent:]
                return  # kernel buffer full; resume on next writable
            # Frame fully handed to the kernel: it is now in flight.
            frame_bytes = channel.outbuf.obj
            future = channel.outfut
            channel.outbuf = None
            channel.outfut = None
            self._m_sent.inc()
            if channel.pipelined:
                corr = P._U32.unpack_from(frame_bytes, 5)[0]
                channel.inflight[corr] = future
            else:
                channel.fifo.append(future)

    def _on_readable(self, channel: PeerChannel) -> None:
        chunk = channel.sock.recv(_RECV_CHUNK)
        if not chunk:
            raise ConnectionError("peer closed the connection")
        channel.rbuf += chunk
        buf = channel.rbuf
        pos = 0
        while True:
            if len(buf) - pos < 4:
                break
            (length,) = P._U32.unpack_from(buf, pos)
            if length < 1 or length > P.MAX_FRAME:
                raise ValueError(f"bad frame length {length}")
            if len(buf) - pos < 4 + length:
                break
            body = bytes(buf[pos + 4 : pos + 4 + length])
            pos += 4 + length
            self._complete(channel, body)
        if pos:
            del buf[:pos]

    def _complete(self, channel: PeerChannel, body: bytes) -> None:
        status, corr, cursor = P.parse_frame(body, channel.pipelined)
        if channel.pipelined:
            future = channel.inflight.pop(corr, None)
        else:
            future = channel.fifo.popleft() if channel.fifo else None
        if future is None:
            return  # response to nothing we sent; drop
        if status == P.STATUS_OK:
            future.set_result(cursor)
        else:
            message = ""
            try:
                message = cursor.string()
            except ValueError:
                pass
            future.set_exception(BridgeError(status, message))

    def _kill_channel(
        self, channel: PeerChannel, error: Exception, record: bool = True
    ) -> None:
        if not channel.alive:
            return
        channel.alive = False
        channel.error = error
        try:
            self._sel.unregister(channel.sock)
        except (KeyError, ValueError):
            pass
        try:
            channel.sock.close()
        except OSError:
            pass
        with channel.lock:
            queued = [future for _, future in channel.sendq]
            channel.sendq.clear()
            channel.queue_bytes = 0
        pending = list(channel.inflight.values()) + list(channel.fifo)
        channel.inflight.clear()
        channel.fifo.clear()
        if channel.outfut is not None:
            pending.append(channel.outfut)
            channel.outbuf = None
            channel.outfut = None
        if record:
            flight_recorder.record(
                "gossip.peer_lost", peer=channel.name,
                pending=len(pending) + len(queued), error=str(error),
            )
        for future in pending + queued:
            if not future.done():
                future.set_exception(error)
        if record and self._running:
            self._maybe_reconnect(channel.name)

    def _maybe_reconnect(self, name: str) -> None:
        """Spawn (at most one per peer) the bounded backoff re-dial loop,
        when the transport opted into a :class:`ReconnectPolicy`."""
        if self._reconnect is None:
            return
        with self._lock:
            endpoint = self._endpoints.get(name)
            if endpoint is None or name in self._reconnecting:
                return
            self._reconnecting.add(name)
        threading.Thread(
            target=self._reconnect_loop, args=(name, *endpoint),
            daemon=True, name=f"gossip-reconnect-{name}",
        ).start()

    def _reconnect_loop(self, name: str, host: str, port: int) -> None:
        policy = self._reconnect
        try:
            for attempt in range(policy.max_attempts):
                time.sleep(policy.delay(attempt))
                if not self._running:
                    return
                try:
                    self.connect(name, host, port)
                except (ConnectionError, OSError, BridgeError, ValueError,
                        RuntimeError):
                    continue
                flight_recorder.record(
                    "gossip.reconnected", peer=name, attempt=attempt + 1,
                )
                return
            flight_recorder.record(
                "gossip.reconnect_failed", peer=name,
                attempts=policy.max_attempts,
            )
        finally:
            with self._lock:
                self._reconnecting.discard(name)


class ChannelBusy(RuntimeError):
    """``request`` refused a frame because the peer's bounded send queue
    is full — the explicit backpressure signal for callers that must not
    shed silently."""
