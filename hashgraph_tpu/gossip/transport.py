"""Selectors-based multiplexed transport for the gossip fabric.

One :class:`GossipTransport` drives MANY peer connections from ONE
event-loop thread (`selectors`, non-blocking sockets — no asyncio in the
library core, matching the reference's no-I/O embedder contract: the
loop is plain stdlib an embedder can reason about and replace). Each
:class:`PeerChannel` speaks the bridge wire protocol with the features
its server granted at HELLO:

- against a new server (``FEATURE_PIPELINING``): tagged frames, many in
  flight, responses matched by correlation id;
- against an OLD server: the one-at-a-time framing with a FIFO response
  match and an in-flight window of 1 — same API, interop preserved.

**Backpressure is explicit and bounded.** Every channel has a byte-capped
send queue and a credit window (``max_inflight`` unanswered requests).
Credits gate *sending* — queued frames wait; once the queue's byte cap
would be exceeded, :meth:`GossipTransport.try_request` refuses the frame
(*sheds*) instead of buffering without bound. The caller — the
:class:`~hashgraph_tpu.gossip.node.GossipNode` — records what it shed
and repairs via anti-entropy later, so a slow peer costs a bounded queue
plus deferred repair, never ballooning memory.

A dropped connection fails every queued and in-flight future with
:class:`~hashgraph_tpu.bridge.client.BridgeConnectionLost` — a typed,
per-request signal, never a silent hang.
"""

from __future__ import annotations

import os
import selectors
import socket
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future

from ..bridge import protocol as P
from ..bridge.client import BridgeConnectionLost, BridgeError, ReconnectPolicy
from ..obs import (
    GOSSIP_DRAIN_PRESSURE,
    GOSSIP_FRAMES_SENT_TOTAL,
    GOSSIP_FRAMES_SHED_TOTAL,
    GOSSIP_INFLIGHT_REQUESTS,
    GOSSIP_SEND_QUEUE_BYTES,
    flight_recorder,
)
from ..obs import registry as default_registry

_RECV_CHUNK = 256 * 1024

# Most iovecs one sendmsg accepts (UIO_MAXIOV; EINVAL past it). A frame
# coalesced from more vote segments than this is written in capped
# scatter-gather passes via the partial-send resume path.
try:  # pragma: no cover - platform probe
    _IOV_MAX = os.sysconf("SC_IOV_MAX")
    if _IOV_MAX <= 0:
        _IOV_MAX = 1024
except (AttributeError, ValueError, OSError):  # pragma: no cover
    _IOV_MAX = 1024


def _weak_sample(ref, method_name):
    """Gauge provider over a weakly-referenced transport (0 once dead)."""

    def sample():
        transport = ref()
        return 0 if transport is None else getattr(transport, method_name)()

    return sample


class PeerChannel:
    """One multiplexed connection to a peer's bridge server. Owned by a
    :class:`GossipTransport`; all socket I/O happens on the transport's
    event-loop thread, callers only enqueue frames and await futures.
    Channels that negotiated ``FEATURE_SHM_RING`` additionally carry a
    shared-memory ring pair (``shm_tx``/``shm_rx``): requests write
    straight into the tx ring at enqueue time (one memcpy, no syscall)
    and a per-channel reader thread completes futures from the rx ring;
    the socket stays the control/fallback lane."""

    def __init__(self, name: str, sock: socket.socket, features: int,
                 max_inflight: int, max_queue_bytes: int):
        self.name = name
        self.sock = sock
        self.features = features
        self.pipelined = bool(features & P.FEATURE_PIPELINING)
        self.max_inflight = max_inflight if self.pipelined else 1
        self.max_queue_bytes = max_queue_bytes
        self.alive = True
        self.error: Exception | None = None
        # Guarded by the channel lock: send queue + accounting. Frames
        # are fully encoded at enqueue time (the loop thread only moves
        # bytes). Queue entries are (segments, nbytes, corr, future):
        # segments lists ride to sendmsg un-joined (send-side zero-copy).
        self.lock = threading.Lock()
        self.sendq: deque[tuple[list, int, int, Future]] = deque()
        self.queue_bytes = 0
        self.shed_total = 0
        # Loop-thread-only state: the frame currently being written and
        # the unanswered requests. Tagged channels match by correlation
        # id; untagged channels complete FIFO.
        self.outbuf: "list[memoryview] | None" = None
        self.outfut: Future | None = None
        self.outcorr = 0
        self.inflight: dict[int, Future] = {}
        self.fifo: deque[Future] = deque()
        self.next_corr = 0
        self.rbuf = bytearray()
        # Shared-memory lane (None until an attach succeeds). shm
        # futures are guarded by the channel lock (the rx thread and the
        # kill path both touch them).
        self.shm_tx = None
        self.shm_rx = None
        self.shm_inflight: dict[int, Future] = {}
        self.shm_thread: "threading.Thread | None" = None
        # Corr ids of MUTATING frames routed to the TCP lane (queued or
        # awaiting response), guarded by the channel lock. While any are
        # outstanding, later mutating frames also ride TCP so one
        # ordered opcode stream never splits across lanes (the server
        # serializes per lane, not across them).
        self.tcp_mutating: set[int] = set()

    # ── accounting (any thread) ────────────────────────────────────────

    def inflight_count(self) -> int:
        return len(self.inflight) + len(self.fifo) + (
            1 if self.outfut is not None else 0
        )

    def stats(self) -> dict:
        with self.lock:
            return {
                "alive": self.alive,
                "pipelined": self.pipelined,
                "shm": self.shm_tx is not None,
                "queue_frames": len(self.sendq),
                "queue_bytes": self.queue_bytes,
                "inflight": self.inflight_count() + len(self.shm_inflight),
                "shed_total": self.shed_total,
            }


class GossipTransport:
    """Multiplexed, pipelined fan-out over many bridge connections.

    ``connect`` performs the blocking HELLO handshake, then hands the
    socket to the event loop. ``try_request`` enqueues one frame for a
    peer and returns a future resolving to the response payload cursor
    (or raising :class:`BridgeError` / :class:`BridgeConnectionLost`) —
    or returns ``None`` when the peer's send queue is at its byte cap
    (the shed signal). All sockets run ``TCP_NODELAY``; pass ``sndbuf``/
    ``rcvbuf`` for high-BDP links (see :func:`bridge.protocol.tune_socket`).
    """

    def __init__(
        self,
        *,
        max_inflight: int = 128,
        max_queue_bytes: int = 4 * 1024 * 1024,
        connect_timeout: float = 5.0,
        features: int = P.SUPPORTED_FEATURES,
        sndbuf: int | None = None,
        rcvbuf: int | None = None,
        reconnect: "ReconnectPolicy | None" = None,
        shm_ring_bytes: int | None = None,
    ):
        self._max_inflight = max_inflight
        self._max_queue_bytes = max_queue_bytes
        self._connect_timeout = connect_timeout
        self._features = features
        self._sndbuf = sndbuf
        self._rcvbuf = rcvbuf
        # Shared-memory rings for co-located peers: when set (ring bytes
        # per direction) AND the server grants FEATURE_SHM_RING AND the
        # endpoint is loopback, requests bypass the kernel socket path
        # entirely (gossip.shm). Any attach failure silently keeps TCP.
        self._shm_ring_bytes = shm_ring_bytes
        # Opt-in channel healing: when a peer's channel dies (and the
        # transport itself is not closing), re-dial it with capped
        # jittered backoff and a fresh HELLO. In-flight and queued
        # futures on the dead channel still fail typed — only the
        # CHANNEL heals; lost frames are the anti-entropy layer's job.
        self._reconnect = reconnect
        self._endpoints: dict[str, tuple[str, int]] = {}
        self._reconnecting: set[str] = set()
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._channels: dict[str, PeerChannel] = {}
        self._pending_register: list[PeerChannel] = []
        self._lock = threading.Lock()
        self._running = True
        self._m_sent = default_registry.counter(GOSSIP_FRAMES_SENT_TOTAL)
        self._m_shed = default_registry.counter(GOSSIP_FRAMES_SHED_TOTAL)
        # Providers close over a WEAK ref (the engine/WAL convention): a
        # bound method's __self__ would strongly pin every transport ever
        # created into the process-global registry — the owner weakref
        # only prunes the entry once the owner can actually die.
        ref = weakref.ref(self)
        default_registry.gauge(GOSSIP_SEND_QUEUE_BYTES).add_provider(
            _weak_sample(ref, "_total_queue_bytes"), owner=self
        )
        default_registry.gauge(GOSSIP_INFLIGHT_REQUESTS).add_provider(
            _weak_sample(ref, "_total_inflight"), owner=self
        )
        default_registry.gauge(GOSSIP_DRAIN_PRESSURE).add_provider(
            _weak_sample(ref, "_drain_pressure"), owner=self
        )
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="gossip-transport"
        )
        self._thread.start()

    # ── lifecycle ──────────────────────────────────────────────────────

    def close(self) -> None:
        self._running = False
        self._wake()
        self._thread.join(timeout=5)
        with self._lock:
            channels = list(self._channels.values())
        for ch in channels:
            self._kill_channel(
                ch, BridgeConnectionLost("transport closed"), record=False
            )
        try:
            self._wake_r.close()
            self._wake_w.close()
        except OSError:
            pass
        self._sel.close()

    def __enter__(self) -> "GossipTransport":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    # ── connections ────────────────────────────────────────────────────

    def connect(self, name: str, host: str, port: int) -> PeerChannel:
        """Open (blocking) a channel to a peer's bridge server and
        negotiate features; the socket then joins the event loop. A
        ``name`` can be reconnected after its channel died — the dead
        channel is replaced."""
        if not self._running:
            raise RuntimeError("transport is closed")
        sock = socket.create_connection(
            (host, port), timeout=self._connect_timeout
        )
        P.tune_socket(sock, sndbuf=self._sndbuf, rcvbuf=self._rcvbuf)
        features = 0
        try:
            sock.sendall(P.encode_frame(
                P.OP_HELLO,
                P.u32(P.PROTOCOL_VERSION) + P.u32(self._features),
            ))
            status, cursor = P.read_frame(sock)
            if status == P.STATUS_OK:
                cursor.u32()  # server protocol version
                features = cursor.u32()
            elif status != P.STATUS_UNKNOWN_OPCODE:
                raise BridgeError(status)
        except BaseException:
            sock.close()
            raise
        channel = PeerChannel(
            name, sock, features, self._max_inflight, self._max_queue_bytes
        )
        if (
            self._shm_ring_bytes
            and features & P.FEATURE_SHM_RING
            and features & P.FEATURE_PIPELINING
            and host in ("127.0.0.1", "localhost", "::1")
        ):
            self._try_attach_shm(channel)  # still blocking; pre-loop
        sock.setblocking(False)
        with self._lock:
            # Re-checked at registration time: a reconnect attempt's
            # blocking dial can race close() past the entry check, and a
            # channel registered after the loop thread exited would
            # never be serviced — its futures would hang instead of
            # failing typed.
            if not self._running:
                sock.close()
                raise RuntimeError("transport is closed")
            old = self._channels.get(name)
            if old is not None and old.alive:
                sock.close()
                raise ValueError(f"peer {name!r} already connected")
            self._channels[name] = channel
            self._pending_register.append(channel)
            self._endpoints[name] = (host, port)
        self._wake()
        return channel

    def channel(self, name: str) -> PeerChannel | None:
        with self._lock:
            return self._channels.get(name)

    def stats(self) -> dict:
        with self._lock:
            channels = dict(self._channels)
        return {name: ch.stats() for name, ch in channels.items()}

    # ── requests ───────────────────────────────────────────────────────

    def try_request(
        self, name: str, opcode: int, payload: "bytes | list" = b""
    ) -> Future | None:
        """Enqueue one request for ``name``; None = shed (queue at its
        byte cap / shm ring full — bounded backpressure, the caller
        repairs later). ``payload`` may be a LIST of byte segments
        (see :func:`bridge.protocol.encode_vote_batch_segments`): the
        segments ride to ``sendmsg`` — or into the shm ring — without
        ever being joined into one contiguous copy."""
        with self._lock:
            channel = self._channels.get(name)
        if channel is None:
            raise KeyError(f"unknown peer {name!r}")
        if not channel.alive:
            future: Future = Future()
            future.set_exception(
                channel.error
                or BridgeConnectionLost(f"peer {name!r} disconnected")
            )
            return future
        if isinstance(payload, (bytes, bytearray, memoryview)):
            psegs, pbytes = [payload], len(payload)
        else:
            psegs, pbytes = list(payload), sum(len(s) for s in payload)
        future = Future()
        with channel.lock:
            # Re-checked under the SAME lock _kill_channel drains the
            # queue with: without this, a frame enqueued between the
            # loop thread's kill-drain and our append would sit on a
            # dead channel with its future never resolved.
            if not channel.alive:
                future.set_exception(
                    channel.error
                    or BridgeConnectionLost(f"peer {name!r} disconnected")
                )
                return future
            if channel.pipelined:
                corr = channel.next_corr
                channel.next_corr = (corr + 1) & 0xFFFFFFFF
                header = P._TAGGED_HEADER.pack(5 + pbytes, opcode, corr)
            else:
                corr = 0
                header = P._FRAME_HEADER.pack(1 + pbytes, opcode)
            segments = [header, *psegs]
            nbytes = len(header) + pbytes
            mutating = opcode in P.MUTATING_OPCODES
            if (
                channel.shm_tx is not None
                and nbytes <= channel.shm_tx.capacity
                and not (mutating and channel.tcp_mutating)
            ):
                # Shared-memory lane: ONE memcpy into the ring, future
                # completed by the rx thread. Ring full = the same shed
                # signal as the byte cap (never split a stream across
                # lanes — reordering a chained vote stream is worse
                # than a deferred repair). A frame larger than the ring
                # can EVER hold rides TCP below (shedding it would retry
                # the same un-sendable frame forever), and while any
                # mutating frame is on the TCP lane, later mutating
                # frames follow it there — the server only preserves
                # order WITHIN a lane, so admitting them to the ring
                # would let them overtake the TCP frame.
                try:
                    written = channel.shm_tx.try_write(segments, nbytes)
                except ValueError:  # ring closed under us: channel dying
                    future.set_exception(
                        channel.error
                        or BridgeConnectionLost(f"peer {name!r} disconnected")
                    )
                    return future
                if written:
                    channel.shm_inflight[corr] = future
                    self._m_sent.inc()
                    return future
                channel.shed_total += 1
                self._m_shed.inc()
                flight_recorder.record(
                    "gossip.shed", peer=name, opcode=opcode, shm=True,
                )
                return None
            if (
                mutating
                and channel.shm_tx is not None
                and not channel.tcp_mutating
            ):
                # First mutating frame to leave the ring for TCP (it is
                # oversize, or it arrives as the set drains to empty):
                # admit it only once the server has consumed every frame
                # already in the ring — earlier ring frames still queued
                # could otherwise be APPLIED after this newer one (an
                # older shorter chain landing late reads as truncation
                # to the redelivery health probe). Shed until drained;
                # the ring clears in microseconds and the caller's
                # anti-entropy retry resends.
                try:
                    if channel.shm_tx.pending_bytes() > 0:
                        channel.shed_total += 1
                        self._m_shed.inc()
                        flight_recorder.record(
                            "gossip.shed", peer=name, opcode=opcode,
                            shm=True, draining=True,
                        )
                        return None
                except ValueError:  # ring closed under us: channel dying
                    future.set_exception(
                        channel.error
                        or BridgeConnectionLost(f"peer {name!r} disconnected")
                    )
                    return future
            # Byte cap applies only while frames are already queued: an
            # empty queue always admits ONE frame (cap effectively
            # cap + one frame), so a frame bigger than the cap itself
            # degrades to serialized sends instead of shedding forever.
            if channel.sendq and (
                channel.queue_bytes + nbytes > channel.max_queue_bytes
            ):
                channel.shed_total += 1
                self._m_shed.inc()
                flight_recorder.record(
                    "gossip.shed", peer=name, opcode=opcode,
                    queue_bytes=channel.queue_bytes,
                )
                return None
            channel.sendq.append((segments, nbytes, corr, future))
            channel.queue_bytes += nbytes
            if mutating and channel.pipelined:
                channel.tcp_mutating.add(corr)
        self._wake()
        return future

    def request(self, name: str, opcode: int, payload: bytes = b"") -> Future:
        """:meth:`try_request` that raises :class:`ChannelBusy` instead
        of returning None — for control traffic the caller windows
        itself (anti-entropy sends one frame and awaits it)."""
        future = self.try_request(name, opcode, payload)
        if future is None:
            raise ChannelBusy(f"peer {name!r} send queue is full")
        return future

    # ── gauge providers ────────────────────────────────────────────────

    def _total_queue_bytes(self) -> int:
        with self._lock:
            channels = list(self._channels.values())
        return sum(ch.queue_bytes for ch in channels)

    def _total_inflight(self) -> int:
        with self._lock:
            channels = list(self._channels.values())
        return sum(ch.inflight_count() for ch in channels)

    def _drain_pressure(self) -> float:
        """Worst per-channel send-queue fill fraction in [0, 1] — how
        close the slowest peer is to tripping the backpressure shed."""
        with self._lock:
            channels = list(self._channels.values())
        return max(
            (
                ch.queue_bytes / ch.max_queue_bytes
                for ch in channels
                if ch.max_queue_bytes > 0
            ),
            default=0.0,
        )

    # ── event loop (loop thread only below) ────────────────────────────

    def _loop(self) -> None:
        while self._running:
            self._register_pending()
            self._refresh_interest()
            for key, mask in self._sel.select(timeout=0.1):
                if key.data is None:  # wake pipe
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                channel: PeerChannel = key.data
                try:
                    if mask & selectors.EVENT_WRITE:
                        self._on_writable(channel)
                    if mask & selectors.EVENT_READ:
                        self._on_readable(channel)
                except (ConnectionError, OSError, ValueError) as exc:
                    self._kill_channel(channel, BridgeConnectionLost(
                        f"peer {channel.name!r} connection lost: {exc}"
                    ))

    def _register_pending(self) -> None:
        with self._lock:
            fresh = self._pending_register
            self._pending_register = []
        for channel in fresh:
            if channel.alive:
                self._sel.register(channel.sock, selectors.EVENT_READ, channel)

    def _refresh_interest(self) -> None:
        with self._lock:
            channels = list(self._channels.values())
        for channel in channels:
            if not channel.alive:
                continue
            want = selectors.EVENT_READ
            credits = channel.max_inflight - channel.inflight_count()
            with channel.lock:
                has_frames = bool(channel.sendq) or channel.outbuf is not None
            if has_frames and (credits > 0 or channel.outbuf is not None):
                want |= selectors.EVENT_WRITE
            try:
                self._sel.modify(channel.sock, want, channel)
            except (KeyError, ValueError):
                pass  # not registered yet / already unregistered

    def _on_writable(self, channel: PeerChannel) -> None:
        while True:
            if channel.outbuf is None:
                credits = channel.max_inflight - channel.inflight_count()
                if credits <= 0:
                    return
                with channel.lock:
                    if not channel.sendq:
                        return
                    segments, nbytes, corr, future = channel.sendq.popleft()
                    channel.queue_bytes -= nbytes
                channel.outbuf = [memoryview(s) for s in segments]
                channel.outfut = future
                channel.outcorr = corr
            # Scatter-gather write: the frame's segments (header + the
            # coalescer's original vote bytes) go to the kernel in one
            # syscall without ever being joined. Capped at IOV_MAX
            # iovecs per call (sendmsg fails whole with EINVAL past it);
            # the partial-send resume below picks up the remainder.
            if hasattr(channel.sock, "sendmsg"):
                sent = channel.sock.sendmsg(channel.outbuf[:_IOV_MAX])
            else:  # pragma: no cover - platforms without sendmsg
                sent = channel.sock.send(b"".join(channel.outbuf))
            remaining: list[memoryview] = []
            for seg in channel.outbuf:
                if sent >= len(seg):
                    sent -= len(seg)
                    continue
                remaining.append(seg[sent:] if sent else seg)
                sent = 0
            if remaining:
                channel.outbuf = remaining
                return  # kernel buffer full; resume on next writable
            # Frame fully handed to the kernel: it is now in flight.
            future = channel.outfut
            corr = channel.outcorr
            channel.outbuf = None
            channel.outfut = None
            self._m_sent.inc()
            if channel.pipelined:
                channel.inflight[corr] = future
            else:
                channel.fifo.append(future)

    def _on_readable(self, channel: PeerChannel) -> None:
        chunk = channel.sock.recv(_RECV_CHUNK)
        if not chunk:
            raise ConnectionError("peer closed the connection")
        channel.rbuf += chunk
        for body in P.split_frames(channel.rbuf):
            self._complete(channel, body)

    def _complete(self, channel: PeerChannel, body: bytes) -> None:
        status, corr, cursor = P.parse_frame(body, channel.pipelined)
        if channel.pipelined:
            future = channel.inflight.pop(corr, None)
            with channel.lock:
                channel.tcp_mutating.discard(corr)
                if future is None:
                    # A ring-sent request whose response outgrew the
                    # ring comes back on the TCP control lane (corr ids
                    # are shared across lanes; the server falls back
                    # rather than wedge the response ring).
                    future = channel.shm_inflight.pop(corr, None)
        else:
            future = channel.fifo.popleft() if channel.fifo else None
        if future is None:
            return  # response to nothing we sent; drop
        if status == P.STATUS_OK:
            future.set_result(cursor)
        else:
            message = ""
            try:
                message = cursor.string()
            except ValueError:
                pass
            future.set_exception(BridgeError(status, message))

    def _kill_channel(
        self, channel: PeerChannel, error: Exception, record: bool = True
    ) -> None:
        if not channel.alive:
            return
        channel.alive = False
        channel.error = error
        try:
            self._sel.unregister(channel.sock)
        except (KeyError, ValueError):
            pass
        try:
            channel.sock.close()
        except OSError:
            pass
        with channel.lock:
            queued = [entry[3] for entry in channel.sendq]
            channel.sendq.clear()
            channel.queue_bytes = 0
            queued.extend(channel.shm_inflight.values())
            channel.shm_inflight.clear()
            channel.tcp_mutating.clear()
            shm_rings = (channel.shm_tx, channel.shm_rx)
            channel.shm_tx = None
            channel.shm_rx = None
        for ring in shm_rings:
            if ring is not None:
                ring.close()
        pending = list(channel.inflight.values()) + list(channel.fifo)
        channel.inflight.clear()
        channel.fifo.clear()
        if channel.outfut is not None:
            pending.append(channel.outfut)
            channel.outbuf = None
            channel.outfut = None
        if record:
            flight_recorder.record(
                "gossip.peer_lost", peer=channel.name,
                pending=len(pending) + len(queued), error=str(error),
            )
        for future in pending + queued:
            if not future.done():
                future.set_exception(error)
        if record and self._running:
            self._maybe_reconnect(channel.name)

    # ── shared-memory lane ─────────────────────────────────────────────

    def _try_attach_shm(self, channel: PeerChannel) -> None:
        """Create a ring pair and offer it to the server (blocking; runs
        during connect, before the socket joins the event loop). Any
        failure keeps the TCP lane silently — old servers, containers
        without a shared /dev/shm, and platform gaps all degrade to
        exactly the pre-shm behavior."""
        tx = rx = None
        try:
            from .shm import ShmRing, shm_available

            if not shm_available():
                return
            tx = ShmRing.create(self._shm_ring_bytes)  # client -> server
            rx = ShmRing.create(self._shm_ring_bytes)  # server -> client
            with channel.lock:
                corr = channel.next_corr
                channel.next_corr = (corr + 1) & 0xFFFFFFFF
            channel.sock.sendall(P.encode_tagged_frame(
                P.OP_SHM_ATTACH,
                corr,
                P.u32(self._shm_ring_bytes)
                + P.string(tx.name)
                + P.string(rx.name),
            ))
            status, _rcorr, _cursor = P.read_tagged_frame(channel.sock)
            if status != P.STATUS_OK:
                raise ValueError(f"shm attach refused (status {status})")
        except (OSError, ValueError, RuntimeError, ConnectionError):
            for ring in (tx, rx):
                if ring is not None:
                    ring.close()
            return
        channel.shm_tx = tx
        channel.shm_rx = rx
        channel.shm_thread = threading.Thread(
            target=self._shm_rx_loop, args=(channel,), daemon=True,
            name=f"gossip-shm-{channel.name}",
        )
        channel.shm_thread.start()
        flight_recorder.record("gossip.shm_attached", peer=channel.name)

    def _shm_rx_loop(self, channel: PeerChannel) -> None:
        """Per-channel response drain for the shm lane: the ring carries
        the same tagged frame stream as the socket; futures complete by
        correlation id."""
        from .shm import ShmSpin

        spin = ShmSpin()
        buf = bytearray()
        while channel.alive and self._running:
            rx = channel.shm_rx
            if rx is None:
                return
            try:
                chunk = rx.read_available()
            except (OSError, ValueError):
                return  # ring closed/unmapped under us (channel died)
            if chunk is None:
                spin.wait()
                continue
            spin.hit()
            buf += chunk
            try:
                frames = P.split_frames(buf, min_len=5)
            except ValueError:
                self._kill_channel(channel, BridgeConnectionLost(
                    f"peer {channel.name!r} shm stream corrupt"
                ))
                return
            for body in frames:
                self._complete_shm(channel, body)

    def _complete_shm(self, channel: PeerChannel, body: bytes) -> None:
        status, corr, cursor = P.parse_frame(body, tagged=True)
        with channel.lock:
            future = channel.shm_inflight.pop(corr, None)
        if future is None:
            return
        if status == P.STATUS_OK:
            future.set_result(cursor)
        else:
            message = ""
            try:
                message = cursor.string()
            except ValueError:
                pass
            future.set_exception(BridgeError(status, message))

    def _maybe_reconnect(self, name: str) -> None:
        """Spawn (at most one per peer) the bounded backoff re-dial loop,
        when the transport opted into a :class:`ReconnectPolicy`."""
        if self._reconnect is None:
            return
        with self._lock:
            endpoint = self._endpoints.get(name)
            if endpoint is None or name in self._reconnecting:
                return
            self._reconnecting.add(name)
        threading.Thread(
            target=self._reconnect_loop, args=(name, *endpoint),
            daemon=True, name=f"gossip-reconnect-{name}",
        ).start()

    def _reconnect_loop(self, name: str, host: str, port: int) -> None:
        policy = self._reconnect
        try:
            for attempt in range(policy.max_attempts):
                time.sleep(policy.delay(attempt))
                if not self._running:
                    return
                try:
                    self.connect(name, host, port)
                except (ConnectionError, OSError, BridgeError, ValueError,
                        RuntimeError):
                    continue
                flight_recorder.record(
                    "gossip.reconnected", peer=name, attempt=attempt + 1,
                )
                return
            flight_recorder.record(
                "gossip.reconnect_failed", peer=name,
                attempts=policy.max_attempts,
            )
        finally:
            with self._lock:
                self._reconnecting.discard(name)


class ChannelBusy(RuntimeError):
    """``request`` refused a frame because the peer's bounded send queue
    is full — the explicit backpressure signal for callers that must not
    shed silently."""
