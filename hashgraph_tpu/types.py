"""Core request and event types (reference: src/types.rs)."""

from __future__ import annotations

from dataclasses import dataclass

from .protocol import (
    generate_id,
    validate_expected_voters_count,
    validate_timeout,
)
from .wire import Proposal

_U64_MAX = 0xFFFFFFFFFFFFFFFF


@dataclass(frozen=True)
class ConsensusReached:
    """Consensus was reached with a final yes/no result
    (reference: src/types.rs:17-22)."""

    proposal_id: int
    result: bool
    timestamp: int


@dataclass(frozen=True)
class ConsensusFailedEvent:
    """Consensus failed — insufficient votes before timeout
    (reference: src/types.rs:23-24)."""

    proposal_id: int
    timestamp: int


# A ConsensusEvent is one of the two dataclasses above.
ConsensusEvent = ConsensusReached | ConsensusFailedEvent


@dataclass(frozen=True)
class SessionTransition:
    """Result of adding votes to a session (reference: src/types.rs:29-34).

    ``reached is None`` means still active; otherwise the boolean result.
    """

    reached: bool | None = None

    @classmethod
    def still_active(cls) -> "SessionTransition":
        return cls(None)

    @classmethod
    def consensus_reached(cls, result: bool) -> "SessionTransition":
        return cls(result)

    @property
    def is_reached(self) -> bool:
        return self.reached is not None


STILL_ACTIVE = SessionTransition.still_active()


@dataclass
class CreateProposalRequest:
    """Validated parameters for creating a new proposal
    (reference: src/types.rs:42-83).

    ``expiration_timestamp`` is a *relative* duration in seconds, converted to
    an absolute timestamp at creation time.
    """

    name: str
    payload: bytes
    proposal_owner: bytes
    expected_voters_count: int
    expiration_timestamp: int
    liveness_criteria_yes: bool

    def __post_init__(self):
        validate_expected_voters_count(self.expected_voters_count)
        validate_timeout(self.expiration_timestamp)

    def into_proposal(self, now: int, pid: int | None = None) -> Proposal:
        """Stamp ``now``, generate an id, derive absolute expiration with
        saturating add (reference: src/types.rs:90-105). ``pid`` lets batch
        creators supply a pre-drawn id (same id space, one urandom read for
        the whole batch) instead of paying a uuid4 per proposal."""
        return Proposal(
            name=self.name,
            payload=self.payload,
            proposal_id=generate_id() if pid is None else pid,
            proposal_owner=self.proposal_owner,
            votes=[],
            expected_voters_count=self.expected_voters_count,
            round=1,
            timestamp=now,
            expiration_timestamp=min(now + self.expiration_timestamp, _U64_MAX),
            liveness_criteria_yes=self.liveness_criteria_yes,
        )
