"""Apply reactor: cross-connection continuous batching for the wire path.

The round-11 stage attribution put device-apply at ~2/3 of server busy
time on the networked path: every connection lands its own small
``ingest_wire_columnar`` dispatch, so the engine pays the fixed XLA
launch + readback cost per *frame* instead of per *window*. The reactor
is the continuous-batching scheduler (the Orca insight from inference
serving, applied to consensus ingest — PAPERS.md "Serving & dispatch
amortization") that closes the gap: validated columnar frame-entries
from all connections, peers, and lanes enqueue into per-engine
micro-windows, one fused device dispatch flushes each window, and the
per-row statuses scatter back to every pending frame.

Ordering contract (unchanged from the reactor-off wire):

- A connection's mutating frames join windows in receive order (the
  serial lane enqueues them in order, and an engine's windows dispatch
  strictly in creation order with at most one dispatch in flight per
  engine), so a vote stream's chain links never reorder.
- Rows from *different* connections inside one window are order-free —
  exactly as today's concurrent per-connection dispatches are.
- Windows merge only frames that share the same logical ``now``: the
  scalar drives expiry/decide timestamps, so merging across differing
  clocks could change per-row verdicts. A differing-``now`` enqueue
  closes the open window first (flush reason ``now_change``), which
  keeps reactor-on byte-identical to reactor-off unconditionally.

Windowing: flush on rows, bytes, or deadline (sub-millisecond default).
The deadline adapts — deadline-flushes at occupancy 1 shrink it toward
``min_delay`` so light-load p99 decision latency does not regress;
rows/bytes-flushes grow it back toward ``max_delay``.

Determinism: a reactor that was never ``start()``-ed runs no thread and
dispatches nothing on its own — ``submit()`` only queues, and
``flush()`` dispatches inline on the caller's thread, in enqueue order.
That is the embedded/sim mode (``BridgeServer.start_embedded``): every
frame flushes on the scheduler's own tick, so a chaos run stays a pure
function of its seed.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..obs import (
    DEFAULT_SIZE_BUCKETS,
    REACTOR_FLUSH_BYTES_TOTAL,
    REACTOR_FLUSH_DEADLINE_TOTAL,
    REACTOR_FLUSH_FORCED_TOTAL,
    REACTOR_FLUSH_NOW_CHANGE_TOTAL,
    REACTOR_FLUSH_ROWS_TOTAL,
    REACTOR_ROWS_PER_DISPATCH,
    REACTOR_ROWS_TOTAL,
    REACTOR_WINDOW_OCCUPANCY,
    REACTOR_WINDOWS_TOTAL,
)
from ..obs import registry as default_registry


class ReactorHandle:
    """One enqueued frame-entry's pending per-row statuses. ``wait()``
    blocks for the fused dispatch carrying the entry and returns its
    status slice (``np.int32``, one code per row, engine order); a
    dispatch failure re-raises the engine's exception here so the wire
    error contract is applied where the response is written."""

    __slots__ = ("rows", "_event", "_codes", "_error", "_on_done")

    def __init__(self, rows: int, on_done=None):
        self.rows = rows
        self._event = threading.Event()
        self._codes = None
        self._error = None
        self._on_done = on_done

    @property
    def done(self) -> bool:
        return self._event.is_set()

    @property
    def codes(self):
        """Per-row status codes once done (None before, or on error)."""
        return self._codes

    @property
    def error(self):
        """The dispatch's exception once done, else None."""
        return self._error

    def _finish(self, codes, error=None) -> None:
        self._codes = codes
        self._error = error
        self._event.set()
        on_done, self._on_done = self._on_done, None
        if on_done is not None:
            try:
                on_done(self)
            except Exception:  # pragma: no cover - callback owns errors
                pass

    def wait(self, timeout: "float | None" = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("reactor dispatch did not complete")
        if self._error is not None:
            raise self._error
        return self._codes


class _Entry:
    """One validated columnar frame-entry queued for a fused dispatch."""

    __slots__ = (
        "scopes", "sidx", "cols", "data", "offsets", "prepass", "handle",
        "nbytes", "mergeable",
    )

    def __init__(self, scopes, sidx, cols, data, offsets, prepass, handle):
        self.scopes = scopes
        self.sidx = sidx
        self.cols = cols
        self.data = data
        self.offsets = offsets
        self.prepass = prepass
        self.handle = handle
        self.nbytes = int(len(data))
        # Concatenation assumes the offsets span the data exactly (true
        # for decode_vote_batch_views and pack_rows outputs); an entry
        # that doesn't gets its own single-entry window instead of a
        # byte-shifted merge.
        offs = offsets
        self.mergeable = bool(
            len(offs) > 0 and int(offs[0]) == 0 and int(offs[-1]) == self.nbytes
        )


class _Window:
    """One open or flush-pending micro-window: entries for ONE engine at
    ONE logical ``now``, dispatched as a single fused device call."""

    __slots__ = ("engine", "now", "entries", "rows", "nbytes", "deadline", "reason")

    def __init__(self, engine, now, deadline: float):
        self.engine = engine
        self.now = now
        self.entries: list[_Entry] = []
        self.rows = 0
        self.nbytes = 0
        self.deadline = deadline
        self.reason = None  # set when the window closes

    def add(self, entry: _Entry) -> None:
        self.entries.append(entry)
        self.rows += entry.handle.rows
        self.nbytes += entry.nbytes


class _EngineQ:
    """Per-engine scheduling state: at most one OPEN window, a FIFO of
    closed windows awaiting dispatch, and a single-dispatch-in-flight
    flag — windows dispatch strictly in creation order, which is what
    preserves a connection's receive order across windows."""

    __slots__ = ("engine", "open", "ready", "busy")

    def __init__(self, engine):
        self.engine = engine
        self.open: "_Window | None" = None
        self.ready: deque = deque()
        self.busy = False


# The five absolute byte-offset columns a row carries into its data
# region — the exact set ``columnar.pack_rows`` rebases when gathering
# rows, shifted here by each entry's base instead.
def _offset_columns():
    from . import columnar as C

    return (
        C.COL_OWNER_OFF, C.COL_PARENT_OFF, C.COL_RECV_OFF,
        C.COL_HASH_OFF, C.COL_SIG_OFF,
    )


def merge_entries(entries: "list[_Entry]"):
    """Concatenate queued frame-entries into ONE ``ingest_wire_columnar``
    call's arguments: data regions concatenate, the per-row offsets and
    the five byte-offset columns shift by each entry's data base, scope
    indices shift by each entry's scope base (duplicate scope strings
    across entries are harmless — each index group resolves the same
    session), and the in-flight prepasses merge into one whose
    ``collect()`` chains the originals in entry order. Returns
    ``(scopes, sidx, cols, data, offsets, prepass)``."""
    from ..engine.engine import WireVotePrepass

    scopes: list = []
    sidx_parts: list[np.ndarray] = []
    cols_parts: list[np.ndarray] = []
    off_parts: list[np.ndarray] = []
    pre_parts: list[np.ndarray] = []
    crypto_parts: list[np.ndarray] = []
    sources: list = []
    bufs: list[bytes] = []
    have_prepass = entries[0].prepass is not None
    offset_cols = _offset_columns()
    data_base = 0
    row_base = 0
    for entry in entries:
        scope_base = len(scopes)
        scopes.extend(entry.scopes)
        sidx_parts.append(np.asarray(entry.sidx, np.int64) + scope_base)
        cols = np.array(entry.cols, np.int64, copy=True)
        if data_base:
            for col in offset_cols:
                cols[:, col] += data_base
        cols_parts.append(cols)
        offs = np.asarray(entry.offsets, np.int64)
        off_parts.append(offs[:-1] + data_base)
        if have_prepass:
            prepass = entry.prepass
            pre_parts.append(np.asarray(prepass.pre_status, np.int32))
            crypto_parts.append(
                np.asarray(prepass.crypto_rows, np.int64) + row_base
            )
            sources.append(prepass)
            bufs.append(
                prepass.buf if prepass.buf is not None
                else entry.data.tobytes()
            )
        data_base += entry.nbytes
        row_base += len(entry.cols)
    off_parts.append(np.asarray([data_base], np.int64))
    data = np.concatenate([entry.data for entry in entries])
    merged_prepass = None
    if have_prepass:

        def _collect():
            out: list = []
            for source in sources:
                out.extend(source.collect())
            return out

        merged_prepass = WireVotePrepass(
            np.concatenate(pre_parts),
            np.concatenate(crypto_parts),
            _collect,
            buf=b"".join(bufs),
        )
    return (
        scopes,
        np.concatenate(sidx_parts),
        np.vstack(cols_parts),
        data,
        np.concatenate(off_parts),
        merged_prepass,
    )


class ApplyReactor:
    """Per-server micro-batching scheduler for the columnar wire path.

    ``submit()`` queues one validated frame-entry for its engine's open
    window and returns a :class:`ReactorHandle`; windows close on rows /
    bytes / deadline / ``now``-change / forced flush and dispatch as ONE
    fused ``ingest_wire_columnar`` call each, scattering status slices
    back to every handle.

    Two modes, one code path:

    - ``start()``-ed (the TCP server): a flusher thread enforces the
      adaptive deadline and a small executor runs the fused dispatches;
      at most one dispatch in flight per engine, windows in creation
      order.
    - never started (embedded/sim, unit tests): no threads exist;
      ``flush()`` closes and dispatches inline on the caller's thread —
      fully deterministic, the simulator's "flush on the scheduler
      tick".

    ``on_stage`` (optional) receives each dispatch's ``stage_seconds``
    dict — the bridge server feeds its wire crypto/apply counters from
    it so stage attribution stays correct with the reactor on.
    """

    def __init__(
        self,
        *,
        max_rows: int = 1024,
        max_bytes: int = 1 << 20,
        max_delay: float = 0.0005,
        min_delay: float = 0.00005,
        adaptive: bool = True,
        dispatch_workers: int = 2,
        on_stage=None,
    ):
        self.max_rows = max(1, int(max_rows))
        self.max_bytes = max(1, int(max_bytes))
        self.max_delay = float(max_delay)
        self.min_delay = min(float(min_delay), self.max_delay)
        self.adaptive = bool(adaptive)
        self._delay = self.max_delay
        self._on_stage = on_stage
        self._dispatch_workers = max(1, int(dispatch_workers))
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._queues: dict[int, _EngineQ] = {}
        self._pool: ThreadPoolExecutor | None = None
        self._flusher: threading.Thread | None = None
        self._running = False
        self._m_windows = default_registry.counter(REACTOR_WINDOWS_TOTAL)
        self._m_rows = default_registry.counter(REACTOR_ROWS_TOTAL)
        self._m_flush = {
            "rows": default_registry.counter(REACTOR_FLUSH_ROWS_TOTAL),
            "bytes": default_registry.counter(REACTOR_FLUSH_BYTES_TOTAL),
            "deadline": default_registry.counter(REACTOR_FLUSH_DEADLINE_TOTAL),
            "now_change": default_registry.counter(
                REACTOR_FLUSH_NOW_CHANGE_TOTAL
            ),
            "forced": default_registry.counter(REACTOR_FLUSH_FORCED_TOTAL),
        }
        self._m_occupancy = default_registry.histogram(
            REACTOR_WINDOW_OCCUPANCY, DEFAULT_SIZE_BUCKETS
        )
        self._m_rows_per_dispatch = default_registry.histogram(
            REACTOR_ROWS_PER_DISPATCH, DEFAULT_SIZE_BUCKETS
        )

    # ── lifecycle ──────────────────────────────────────────────────────

    @property
    def started(self) -> bool:
        return self._running

    def start(self) -> None:
        """Start the deadline flusher + dispatch executor (idempotent)."""
        with self._lock:
            if self._running:
                return
            self._running = True
            self._pool = ThreadPoolExecutor(
                max_workers=self._dispatch_workers,
                thread_name_prefix="apply-reactor",
            )
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True, name="reactor-flusher"
            )
            self._flusher.start()

    def stop(self) -> None:
        """Flush and dispatch everything still queued, then join the
        threads. Pending handles always finish — a caller blocked in
        ``wait()`` is never stranded by shutdown."""
        with self._lock:
            was_running = self._running
            self._running = False
            self._wake.notify_all()
        flusher, self._flusher = self._flusher, None
        if flusher is not None:
            flusher.join(timeout=5)
        self.flush()
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)
        if was_running:
            # Late closes that raced the executor shutdown drain inline.
            self._drain_inline()

    # ── enqueue / flush ────────────────────────────────────────────────

    def submit(
        self,
        engine,
        scopes,
        scope_idx,
        cols,
        data,
        offsets,
        now,
        prepass=None,
        on_done=None,
    ) -> ReactorHandle:
        """Queue one validated columnar frame-entry for ``engine``'s
        window at logical time ``now``. Starts the engine's crypto
        prepass if the caller didn't (reader threads already do). In
        started mode the entry dispatches on rows/bytes/deadline; in
        manual mode nothing dispatches until :meth:`flush`."""
        if prepass is None and hasattr(engine, "wire_verify_begin"):
            prepass = engine.wire_verify_begin(data, cols, offsets)
        handle = ReactorHandle(len(cols), on_done)
        entry = _Entry(scopes, scope_idx, cols, data, offsets, prepass, handle)
        with self._lock:
            q = self._queues.get(id(engine))
            if q is None:
                q = self._queues[id(engine)] = _EngineQ(engine)
            window = q.open
            if window is not None and (
                window.now != now or not entry.mergeable
            ):
                self._close(q, "now_change" if window.now != now else "forced")
                window = None
            if window is None:
                window = q.open = _Window(
                    engine, now, time.monotonic() + self._delay
                )
            window.add(entry)
            if not entry.mergeable or window.rows >= self.max_rows:
                self._close(q, "forced" if not entry.mergeable else "rows")
            elif window.nbytes >= self.max_bytes:
                self._close(q, "bytes")
            if self._running:
                self._pump_locked()
                self._wake.notify_all()
        return handle

    def flush(self, engine=None) -> None:
        """Close the open window(s) — ``engine``'s, or every engine's —
        and dispatch. Started mode hands the windows to the executor
        (callers wait on their handles); manual mode dispatches inline,
        in enqueue order, before returning."""
        with self._lock:
            targets = (
                [q for q in self._queues.values() if q.engine is engine]
                if engine is not None
                else list(self._queues.values())
            )
            for q in targets:
                if q.open is not None and q.open.entries:
                    self._close(q, "forced")
            if self._running:
                self._pump_locked()
                return
        self._drain_inline(engine)

    def pending(self, engine=None) -> tuple[int, int]:
        """(frames, rows) queued or dispatching — the admission-control
        signal: a full window is still *unapplied* work the sender is
        stacking up, so overload shedding must see it (ISSUE 19's
        serial-lane shed fix counts these rows, not just lane jobs)."""
        frames = rows = 0
        with self._lock:
            for q in self._queues.values():
                if engine is not None and q.engine is not engine:
                    continue
                windows = list(q.ready)
                if q.open is not None:
                    windows.append(q.open)
                for window in windows:
                    frames += len(window.entries)
                    rows += window.rows
        return frames, rows

    # ── internals ──────────────────────────────────────────────────────

    def _close(self, q: _EngineQ, reason: str) -> None:
        """Move the open window to the dispatch FIFO (lock held)."""
        window = q.open
        if window is None or not window.entries:
            q.open = None
            return
        window.reason = reason
        q.open = None
        q.ready.append(window)
        if self.adaptive:
            if reason == "deadline" and len(window.entries) <= 1:
                # Light load: the window waited its whole deadline for
                # nothing — stop adding latency.
                self._delay = max(self.min_delay, self._delay * 0.5)
            elif reason in ("rows", "bytes"):
                # Saturated before the deadline: let windows grow back.
                self._delay = min(self.max_delay, self._delay * 1.5)

    def _pump_locked(self) -> None:
        """Start a dispatch worker for every engine with ready windows
        and no dispatch in flight (lock held, started mode)."""
        pool = self._pool
        if pool is None:
            return
        for q in self._queues.values():
            if q.ready and not q.busy:
                q.busy = True
                try:
                    pool.submit(self._run_queue, q)
                except RuntimeError:  # executor shutting down
                    q.busy = False

    def _run_queue(self, q: _EngineQ) -> None:
        """Dispatch ``q``'s ready windows one at a time, in creation
        order (executor thread) — the per-engine ordering guarantee."""
        while True:
            with self._lock:
                if not q.ready:
                    q.busy = False
                    if q.open is None:
                        self._queues.pop(id(q.engine), None)
                    return
                window = q.ready.popleft()
            self._dispatch(window)

    def _drain_inline(self, engine=None) -> None:
        """Manual-mode dispatch: run every ready window inline, engines
        in insertion order, windows in creation order (deterministic)."""
        while True:
            window = None
            with self._lock:
                for q in list(self._queues.values()):
                    if engine is not None and q.engine is not engine:
                        continue
                    if q.busy:
                        # A started-mode worker owns this queue's order;
                        # never interleave with it.
                        continue
                    if q.ready:
                        window = q.ready.popleft()
                        break
                    if q.open is None:
                        self._queues.pop(id(q.engine), None)
            if window is None:
                return
            self._dispatch(window)

    def _dispatch(self, window: _Window) -> None:
        """One fused device dispatch for one closed window; scatters the
        status slices (or the failure) back to every entry's handle."""
        entries = window.entries
        try:
            stage: dict = {}
            if len(entries) == 1:
                entry = entries[0]
                codes = window.engine.ingest_wire_columnar(
                    entry.scopes,
                    entry.sidx,
                    entry.cols,
                    entry.data,
                    entry.offsets,
                    window.now,
                    stage_seconds=stage,
                    _prepass=entry.prepass,
                )
                slices = [np.asarray(codes, np.int64)]
            else:
                scopes, sidx, cols, data, offsets, prepass = merge_entries(
                    entries
                )
                codes = np.asarray(
                    window.engine.ingest_wire_columnar(
                        scopes,
                        sidx,
                        cols,
                        data,
                        offsets,
                        window.now,
                        stage_seconds=stage,
                        _prepass=prepass,
                    ),
                    np.int64,
                )
                slices = []
                base = 0
                for entry in entries:
                    slices.append(codes[base:base + entry.handle.rows])
                    base += entry.handle.rows
            self._m_windows.inc()
            self._m_rows.inc(window.rows)
            self._m_flush[window.reason or "forced"].inc()
            self._m_occupancy.observe(len(entries))
            self._m_rows_per_dispatch.observe(max(1, window.rows))
            if self._on_stage is not None and stage:
                try:
                    self._on_stage(stage)
                except Exception:  # pragma: no cover - observer owns errors
                    pass
            for entry, sub in zip(entries, slices):
                entry.handle._finish(sub)
        except Exception as exc:
            for entry in entries:
                if not entry.handle.done:
                    entry.handle._finish(None, exc)

    def _flush_loop(self) -> None:
        """Deadline enforcement (started mode): close expired open
        windows and pump their dispatches."""
        while True:
            with self._wake:
                if not self._running:
                    return
                now = time.monotonic()
                next_deadline = None
                for q in self._queues.values():
                    window = q.open
                    if window is None or not window.entries:
                        continue
                    if window.deadline <= now:
                        self._close(q, "deadline")
                    elif next_deadline is None or window.deadline < next_deadline:
                        next_deadline = window.deadline
                self._pump_locked()
                timeout = (
                    0.05 if next_deadline is None
                    else max(0.0, next_deadline - now)
                )
                self._wake.wait(timeout)


def reactor_enabled(explicit: "bool | None" = None) -> bool:
    """Resolve the construction-default/escape-hatch contract: an
    explicit constructor argument wins; otherwise the
    ``HASHGRAPH_TPU_APPLY_REACTOR`` env var (``1`` = on), defaulting to
    OFF — the reactor is opt-in while the decision-identity suite and
    the chaos corpus gate it."""
    if explicit is not None:
        return bool(explicit)
    import os

    return os.environ.get("HASHGRAPH_TPU_APPLY_REACTOR", "0") == "1"
