"""Columnar wire-vote parsing: the zero-copy half of ``OP_VOTE_BATCH``.

The coalesced vote frame already ships a columnar layout — length
columns plus one contiguous vote-bytes region — but the server used to
decode every vote back into a Python ``Vote`` object before dispatch,
paying object construction, per-field attribute stores, and a full
re-encode (``signing_payload``) per vote. This module keeps the frame
columnar all the way to the engine: one batched parse pass produces
int64 *columns* (ids, timestamps, values, field offsets into the frame
buffer) and a per-row canonicality flag.

**Strict-canonical contract.** The fast path only accepts rows whose
bytes are exactly what the package's own encoder (and the reference's
prost codec) produces: fields 20..28 ascending, each at most once,
minimal varints, zero/empty fields omitted, bool encoded as 1, no
unknown fields, no trailing bytes. Canonical bytes have two load-bearing
properties the columns exploit:

- the *signing payload* (``Vote.signing_payload()``) is a **prefix** of
  the wire bytes (everything before the signature field), so signature
  verification needs no re-encode;
- ``compute_vote_hash``'s input is reconstructible from fixed-width
  fields plus three wire slices, so hashing is one batched native call.

Any row that deviates — malformed *or* merely non-canonical — flags 0,
and the server falls back to the object-path decoder for the whole
frame. That makes fast-path and fallback statuses identical by
construction: the fast path never guesses at bytes the object decoder
would read differently.

Column layout (``int64[N, VOTE_COLS]``, offsets absolute into the data
buffer; absent fields report len 0; ``sign_len`` is the whole row when
the signature field is absent):

    0 vote_id     1 proposal_id  2 timestamp(u64 bits)  3 value
    4 owner_off   5 owner_len    6 parent_off   7 parent_len
    8 recv_off    9 recv_len    10 hash_off    11 hash_len
   12 sig_off    13 sig_len    14 sign_len    15 reserved

``parse_vote_columns`` dispatches to the native runtime
(``hg_parse_vote_columns``, GIL-free, pool-fanned) when present and to
the pure-Python twin below otherwise — same outputs byte for byte
(asserted by tests/test_wire_columnar.py), same fallback discipline as
the fused pid probe.
"""

from __future__ import annotations

import hashlib

import numpy as np

from .. import native

# One home on the Python side (native.py mirrors the C++ HG_VOTE_COLS);
# a stale local copy would silently mis-stride rows against the native
# parser's output instead of failing loudly.
VOTE_COLS = native.VOTE_COLS

# Column indices (keep in sync with native/consensus_native.cpp).
COL_VOTE_ID = 0
COL_PID = 1
COL_TS = 2
COL_VALUE = 3
COL_OWNER_OFF, COL_OWNER_LEN = 4, 5
COL_PARENT_OFF, COL_PARENT_LEN = 6, 7
COL_RECV_OFF, COL_RECV_LEN = 8, 9
COL_HASH_OFF, COL_HASH_LEN = 10, 11
COL_SIG_OFF, COL_SIG_LEN = 12, 13
COL_SIGN_LEN = 14

_U32_MAX = 0xFFFFFFFF

# field -> (owner_off column index) for the LEN-typed fields.
_LEN_FIELD_COL = {21: 4, 25: 6, 26: 8, 27: 10, 28: 12}


def _read_varint_canonical(buf, pos: int, end: int):
    """Minimal-encoding varint; returns (value, new_pos) or None when
    malformed / non-minimal / u64-overflowing (all 'not canonical')."""
    value = 0
    shift = 0
    i = pos
    while True:
        if i >= end or i - pos >= 10:
            return None
        b = buf[i]
        if shift == 63 and b & 0x7E:
            return None
        value |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            if i - pos > 1 and b == 0:
                return None  # non-minimal (trailing zero byte)
            return value, i
        shift += 7


def _parse_one(buf, start: int, end: int, col: "list[int]") -> bool:
    """Python twin of the native ``parse_vote_canonical``."""
    col[COL_OWNER_OFF] = col[COL_PARENT_OFF] = col[COL_RECV_OFF] = start
    col[COL_HASH_OFF] = col[COL_SIG_OFF] = start
    col[COL_SIGN_LEN] = end - start
    pos = start
    last_field = 0
    while pos < end:
        tag_start = pos
        got = _read_varint_canonical(buf, pos, end)
        if got is None:
            return False
        key, pos = got
        field, wt = key >> 3, key & 7
        if field <= last_field or field < 20 or field > 28:
            return False
        last_field = field
        if field in (20, 22, 23, 24):
            if wt != 0:
                return False
            got = _read_varint_canonical(buf, pos, end)
            if got is None:
                return False
            value, pos = got
            if value == 0:
                return False  # canonical encoders omit zero fields
            if field in (20, 22) and value > _U32_MAX:
                return False
            if field == 24 and value != 1:
                return False
            if field == 20:
                col[COL_VOTE_ID] = value
            elif field == 22:
                col[COL_PID] = value
            elif field == 23:
                col[COL_TS] = value
            else:
                col[COL_VALUE] = 1
        else:
            if wt != 2:
                return False
            got = _read_varint_canonical(buf, pos, end)
            if got is None:
                return False
            length, pos = got
            if length == 0 or length > end - pos:
                return False
            idx = _LEN_FIELD_COL[field]
            col[idx] = pos
            col[idx + 1] = length
            if field == 28:
                col[COL_SIGN_LEN] = tag_start - start
            pos += length
    return pos == end


def parse_vote_columns_py(
    data, offsets: np.ndarray
) -> "tuple[np.ndarray, np.ndarray]":
    """Pure-Python strict-canonical parse: (cols int64[N, VOTE_COLS],
    flags uint8[N]) — output-identical to the native path."""
    buf = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
    n = len(offsets) - 1
    cols = np.zeros((n, VOTE_COLS), np.int64)
    flags = np.zeros(n, np.uint8)
    col_scratch = [0] * VOTE_COLS
    for i in range(n):
        for k in range(VOTE_COLS):
            col_scratch[k] = 0
        # Timestamps ride as raw u64 bits inside the int64 column (the
        # native side does the same); reinterpret on the way out.
        if _parse_one(buf, int(offsets[i]), int(offsets[i + 1]), col_scratch):
            flags[i] = 1
            ts = col_scratch[COL_TS]
            if ts > 0x7FFFFFFFFFFFFFFF:
                ts -= 1 << 64
            col_scratch[COL_TS] = ts
            cols[i] = col_scratch
    return cols, flags


def parse_vote_columns(
    data, offsets: np.ndarray
) -> "tuple[np.ndarray, np.ndarray]":
    """Batched strict-canonical Vote parse: native runtime when present
    (GIL-free), pure-Python twin otherwise. Same outputs either way."""
    out = native.parse_vote_columns(data, offsets)
    if out is not None:
        return out
    return parse_vote_columns_py(data, offsets)


def pack_rows(
    data: np.ndarray, offsets: np.ndarray, cols: np.ndarray, rows: np.ndarray
) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
    """Gather ``rows`` (possibly non-contiguous) of a parsed frame into
    one contiguous ``(data, offsets, cols)`` triple, the absolute offset
    columns rebased — vectorized, no per-row Python slicing. One home
    for the bridge server's per-peer packing and the federation
    adapter's per-shard packing."""
    starts = offsets[rows]
    lens = offsets[rows + 1] - starts
    sub_offsets = np.zeros(len(rows) + 1, np.int64)
    np.cumsum(lens, out=sub_offsets[1:])
    total = int(sub_offsets[-1])
    gather = (
        np.arange(total, dtype=np.int64)
        - np.repeat(sub_offsets[:-1], lens)
        + np.repeat(starts, lens)
    )
    sub_data = data[gather]
    sub_cols = cols[rows].copy()
    delta = sub_offsets[:-1] - starts
    for col in (
        COL_OWNER_OFF, COL_PARENT_OFF, COL_RECV_OFF, COL_HASH_OFF,
        COL_SIG_OFF,
    ):
        sub_cols[:, col] += delta
    return sub_data, sub_offsets, sub_cols


def vote_hash_columns(data, cols: np.ndarray) -> np.ndarray:
    """Batched ``compute_vote_hash`` over parsed columns: uint8[N, 32].
    Native when present; the Python twin rebuilds each hash input from
    the same fixed fields + wire slices (no Vote objects)."""
    out = native.vote_hash_columns(data, cols)
    if out is not None:
        return out
    buf = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
    n = len(cols)
    digests = np.empty((n, 32), np.uint8)
    for i in range(n):
        c = cols[i]
        digests[i] = np.frombuffer(
            hashlib.sha256(
                b"".join(
                    (
                        (int(c[COL_VOTE_ID]) & _U32_MAX).to_bytes(4, "little"),
                        buf[c[COL_OWNER_OFF]:c[COL_OWNER_OFF] + c[COL_OWNER_LEN]],
                        (int(c[COL_PID]) & _U32_MAX).to_bytes(4, "little"),
                        (int(c[COL_TS]) & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little"),
                        b"\x01" if c[COL_VALUE] else b"\x00",
                        buf[c[COL_PARENT_OFF]:c[COL_PARENT_OFF] + c[COL_PARENT_LEN]],
                        buf[c[COL_RECV_OFF]:c[COL_RECV_OFF] + c[COL_RECV_LEN]],
                    )
                )
            ).digest(),
            np.uint8,
        )
    return digests
