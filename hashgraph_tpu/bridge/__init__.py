"""Embedder/FFI bridge: the consensus surface for non-Python processes.

See :mod:`hashgraph_tpu.bridge.protocol` for the wire format,
:class:`~hashgraph_tpu.bridge.server.BridgeServer` for the host side,
``native/bridge_client.c`` for the C reference embedder, and
:class:`~hashgraph_tpu.bridge.client.PipelinedBridgeClient` for the
feature-negotiated many-in-flight client the gossip fabric builds on.
"""

from .client import (
    BridgeClient,
    BridgeConnectionLost,
    BridgeError,
    BridgeEvent,
    PipelinedBridgeClient,
)
from .server import BridgeServer

__all__ = [
    "BridgeClient",
    "BridgeConnectionLost",
    "BridgeError",
    "BridgeEvent",
    "BridgeServer",
    "PipelinedBridgeClient",
]
