"""Embedder/FFI bridge: the consensus surface for non-Python processes.

See :mod:`hashgraph_tpu.bridge.protocol` for the wire format,
:class:`~hashgraph_tpu.bridge.server.BridgeServer` for the host side, and
``native/bridge_client.c`` for the C reference embedder.
"""

from .client import BridgeClient, BridgeError, BridgeEvent
from .server import BridgeServer

__all__ = ["BridgeClient", "BridgeError", "BridgeEvent", "BridgeServer"]
