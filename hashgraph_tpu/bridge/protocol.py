"""Embedder bridge wire protocol: framing, opcodes, and field codecs.

The reference is a library an application embeds in-process
(reference: README.md:183-197, src/lib.rs:15-34); its FFI story is "link the
crate". This framework's compute engine lives in a Python/JAX process, so the
embedder boundary is a byte protocol instead: any language opens a TCP
connection to :class:`~hashgraph_tpu.bridge.server.BridgeServer` and drives
the full ConsensusService surface (create_proposal, cast_vote,
process_incoming_{proposal,vote}, handle_consensus_timeout, events out) with
`Proposal`/`Vote` payloads as the exact protobuf bytes of
``protos/messages/v1/consensus.proto`` — the same bytes the reference's prost
codec produces, so a Rust embedder can decode them with its own generated
types. ``native/bridge_client.c`` is the C reference client.

Frame layout (all integers little-endian):

    request:  u32 length | u8 opcode | payload
    response: u32 length | u8 status | payload

``length`` counts the opcode/status byte plus the payload. Field codecs:
strings are ``u16 len + UTF-8``; byte blobs are ``u32 len + bytes``. Every
opcode except PING and ADD_PEER starts its payload with the ``u32 peer_id``
returned by ADD_PEER (a bridge server hosts many independent peers, mirroring
the reference's one-service-per-peer deployment, src/service.rs:26-29).

Statuses: 0 = OK; 1..29 mirror :class:`hashgraph_tpu.errors.StatusCode`;
240+ are bridge-level (unknown peer / malformed frame / unknown opcode /
internal error). Error responses carry the message as a string payload.

**Trace context (optional, backward compatible).** Proposal-lifecycle
requests (CREATE_PROPOSAL, CAST_VOTE, PROCESS_PROPOSAL, PROCESS_VOTE,
PROCESS_VOTES, HANDLE_TIMEOUT) may append a 26-byte trace-context suffix
after their last field: ``u8 version (0)`` + the 25-byte
:class:`~hashgraph_tpu.obs.trace.TraceContext` wire form (16-byte
trace_id, 8-byte parent span_id, u8 flags). CREATE_PROPOSAL and
CAST_VOTE responses append the same suffix carrying the proposal's bound
context, so the embedder can ferry it to the peers it gossips to.
Handlers never require the suffix (frames without it decode exactly as
before) and never read past their declared fields, so old and new peers
interoperate in both directions: an old server ignores the trailing
bytes, an old client ignores the suffixed response tail.

**Feature negotiation + pipelining (optional, backward compatible).**
``OP_HELLO`` (``u32 protocol_version + u32 offered feature bits`` ->
``u32 protocol_version + u32 granted feature bits``) lets a connection
upgrade itself. A peer that never sends HELLO gets exactly the old wire;
an old server answers HELLO with ``STATUS_UNKNOWN_OPCODE`` — the
canonical "no features" reply, after which the connection continues in
the old one-at-a-time framing. When ``FEATURE_PIPELINING`` is granted,
every subsequent frame on that connection (both directions) switches to
the *tagged* layout:

    request:  u32 length | u8 opcode | u32 correlation_id | payload
    response: u32 length | u8 status | u32 correlation_id | payload

(``length`` counts the lead byte, the 4-byte correlation id, and the
payload.) Many requests may be in flight; the server answers each with
its request's correlation id, and responses MAY complete out of order —
read-only opcodes dispatch concurrently, while state-mutating opcodes
(create/cast/process/deliver/timeout) from one connection execute in
receive order, so a pipelined vote stream keeps its chain order without
waiting a round trip per frame. Correlation ids are opaque to the
server; clients allocate them (wrapping u32 counters).

``FEATURE_VOTE_BATCH`` grants ``OP_VOTE_BATCH`` — the coalesced columnar
vote frame (see :func:`encode_vote_batch`) landing many small votes for
many (peer, scope) targets in one frame and one pipelined engine
dispatch per peer. ``FEATURE_DELIVER`` grants ``OP_DELIVER_PROPOSALS`` —
gossip anti-entropy delivery riding the engine's validated-chain
watermark (redelivered chains verify only their suffix).
``FEATURE_EVENT_BOUND`` grants the bounded ``OP_POLL_EVENTS`` request
form (trailing ``u32 max_events``; the response then carries a trailing
``u8 more`` flag).
"""

from __future__ import annotations

import socket as _socket
import struct

import numpy as np

from ..obs.trace import TRACE_WIRE_BYTES, TraceContext

PROTOCOL_VERSION = 1

# Opcodes.
OP_PING = 0
OP_ADD_PEER = 1
OP_CREATE_PROPOSAL = 2
OP_CAST_VOTE = 3
OP_PROCESS_PROPOSAL = 4
OP_PROCESS_VOTE = 5
OP_HANDLE_TIMEOUT = 6
OP_GET_RESULT = 7
OP_POLL_EVENTS = 8
OP_GET_PROPOSAL = 9
OP_GET_STATS = 10
OP_PROCESS_VOTES = 11  # batch: u32 count + count vote blobs -> u8 statuses
# Server-wide observability scrape (no peer_id prefix, like PING): returns
# the process metrics registry rendered in Prometheus text format as one
# byte blob — remote embedders scrape over the wire they already hold
# instead of needing the HTTP sidecar reachable.
OP_GET_METRICS = 12
# Decision provenance: u32 peer_id + string scope + u32 proposal_id ->
# one JSON blob (TpuConsensusEngine.explain_decision: vote chain, quorum
# arithmetic, timeline phases, trace identity, WAL watermark).
OP_EXPLAIN = 13
# Consensus health observatory: u32 peer_id + u64 now (0 = the monitor's
# latest observed logical tick) -> one JSON blob
# (TpuConsensusEngine.health_report: per-peer scorecards with derived
# grades, self-authenticating equivocation/fork evidence, liveness
# watchdog, firing alert rules; durable peers overlay the WAL watermark).
OP_HEALTH = 14
# ── State sync (snapshot shipping + WAL tailing; durable peers only) ──
# SYNC_MANIFEST: u32 peer_id + u32 max_chunk_bytes (0 = server default)
# -> u64 snapshot_id | u64 watermark_lsn | u64 total_bytes |
#    u32 chunk_bytes | u32 session_count | u32 config_count |
#    u32 chunk_count | chunk_count × 32-byte SHA-256 chunk digests.
# The server captures (or reuses, when the WAL position is unchanged) a
# consistent snapshot of the peer's state at its WAL watermark; chunks
# are byte ranges of the serialized snapshot (sync.snapshot format).
OP_SYNC_MANIFEST = 15
# SYNC_CHUNK: u32 peer_id + u64 snapshot_id + u32 chunk_index -> one
# byte blob (that chunk of the snapshot). STATUS_SYNC_STALE means the
# identified snapshot is no longer served (the source's state moved on
# and the snapshot was rebuilt) — re-fetch the manifest and resume.
OP_SYNC_CHUNK = 16
# WAL_TAIL: u32 peer_id + u64 after_lsn + u32 max_bytes ->
# u32 count | count × (u64 lsn | u8 kind | u32 len | record payload) |
# u8 more. Streams the peer's WAL records after ``after_lsn`` in log
# order, resumable by advancing after_lsn to the last received LSN;
# ``more`` = 1 when the byte budget stopped the read short.
OP_WAL_TAIL = 17
# ── Gossip fabric (feature-negotiated; see the module docstring) ──────
# HELLO: u32 protocol_version + u32 offered feature bits ->
# u32 protocol_version + u32 granted bits (offered ∩ supported). No
# peer_id prefix (like PING). Old servers answer STATUS_UNKNOWN_OPCODE,
# which clients treat as "zero features granted".
OP_HELLO = 18
# VOTE_BATCH: the coalesced columnar vote frame (encode_vote_batch) —
# many (peer_id, scope) groups of small vote payloads in ONE frame,
# landed via ingest_votes_pipelined per peer. Response: u32 total |
# one status byte per vote in flattened batch order. No peer_id prefix
# (groups carry their own).
OP_VOTE_BATCH = 19
# DELIVER_PROPOSALS: u32 peer_id | u64 now | u32 count |
# count × (string scope | blob proposal) -> u32 count | count status
# bytes. Lands on TpuConsensusEngine.deliver_proposals: unknown
# sessions are created, known ones EXTEND along the validated-chain
# watermark (suffix-only crypto), redeliveries settle crypto-free —
# the anti-entropy primitive.
OP_DELIVER_PROPOSALS = 20

# STATE_FINGERPRINT: u32 peer_id -> string (hex). The peer engine's
# order-insensitive content digest (sync.state_fingerprint) — the
# convergence check the gossip bench/smoke asserts across peers that
# live in DIFFERENT processes (in-process tests can reach the engine;
# networked peers cannot).
OP_STATE_FINGERPRINT = 21

# SHM_ATTACH (FEATURE_SHM_RING; pipelined connections only): u32
# ring_bytes | string c2s shm name | string s2c shm name -> empty OK.
# The client creates two single-producer single-consumer shared-memory
# byte rings (hashgraph_tpu.gossip.shm layout) and the server maps them;
# from the OK on, the client MAY send any tagged request frame through
# the c2s ring and the server answers through the s2c ring. The TCP
# socket stays open as the control/fallback lane and its close tears the
# rings down. Co-located peers skip the kernel socket path entirely —
# a frame is one memcpy each way.
OP_SHM_ATTACH = 22

# FLEET_TALLY: u32 peer_id -> u32 n | n x (u32 state_code, u64 count).
# The peer engine's slot-state histogram — for a federation host whose
# peer engine is a fleet adapter this is the host's ONE-psum
# fleet_state_counts; a plain engine answers its pool's local counts.
# This is the fabric half of the cross-host tally contract: where the
# backend implements cross-process collectives
# (parallel.multihost.collectives_available) the fleet psums instead;
# where it doesn't, a driver sums these frames across hosts.
OP_FLEET_TALLY = 23

# Federated metrics pull (server-wide, no peer_id — like GET_METRICS):
# returns one JSON blob {"host": <label>, "state": <registry
# export_state>, "slo": <SloEngine.state>}. GET_METRICS ships *rendered*
# Prometheus text, which cannot be merged; this ships the raw mergeable
# registry state (non-cumulative histogram buckets + exemplars) that
# parallel.rollup.merge_metric_states sums into a single fleet-wide
# /metrics + /slo view with per-host labels.
OP_METRICS_PULL = 24

# Server-wide (no peer_id) -> JSON blob {"host": <label>, "profile":
# <obs.attribution.attribution_report()>}: the wall-clock attribution
# readout (per-stage busy shares, reactor dispatch counters, continuous
# profiler sample summary). Host-labelled like OP_METRICS_PULL so
# parallel.rollup.merge_profile_states federates frames into one fleet
# view. Old servers answer STATUS_UNKNOWN_OPCODE — callers treat that
# as "no profile plane", the HELLO interop discipline.
OP_PROFILE = 25

# Opcodes that mutate server-side state (plus POLL_EVENTS, whose read is
# DESTRUCTIVE — it drains the peer's event queue). On a pipelined
# connection the server executes these in receive order per connection;
# read-only opcodes dispatch concurrently and may complete out of order.
# The client transport uses the same set to keep an ordered stream on
# ONE lane when a connection carries both a shm ring and the TCP
# control/fallback lane (see gossip.transport.GossipTransport).
MUTATING_OPCODES = frozenset({
    OP_ADD_PEER,
    OP_CREATE_PROPOSAL,
    OP_CAST_VOTE,
    OP_PROCESS_PROPOSAL,
    OP_PROCESS_VOTE,
    OP_PROCESS_VOTES,
    OP_VOTE_BATCH,
    OP_DELIVER_PROPOSALS,
    OP_HANDLE_TIMEOUT,
    OP_POLL_EVENTS,
})

# HELLO feature bits.
FEATURE_PIPELINING = 1 << 0
FEATURE_VOTE_BATCH = 1 << 1
FEATURE_DELIVER = 1 << 2
FEATURE_EVENT_BOUND = 1 << 3
FEATURE_SHM_RING = 1 << 4
SUPPORTED_FEATURES = (
    FEATURE_PIPELINING | FEATURE_VOTE_BATCH | FEATURE_DELIVER
    | FEATURE_EVENT_BOUND | FEATURE_SHM_RING
)

# Bridge-level statuses (protocol StatusCode values occupy 0..29).
STATUS_OK = 0
STATUS_UNKNOWN_PEER = 240
STATUS_BAD_REQUEST = 241
STATUS_UNKNOWN_OPCODE = 242
STATUS_SYNC_STALE = 245  # requested snapshot_id no longer served
# The scope's owning shard is frozen mid-migration to another host; the
# response payload is the retry-after hint (seconds, decimal string).
# Back off and retry — the placement flips within the window; votes are
# never dropped, only deferred.
STATUS_SHARD_MIGRATING = 246
# Overload admission: the connection's in-order dispatch lane is too
# deep to accept another state-mutating frame. The response payload is a
# server-computed backoff hint (seconds, decimal string) derived from
# the lane's queue depth. Semantics mirror STATUS_SHARD_MIGRATING:
# nothing was applied, back off for the hinted window and let
# anti-entropy repair the deferred scopes — shed, never silently lost.
STATUS_RETRY_AFTER = 247
STATUS_INTERNAL = 250

# GET_RESULT payload byte.
RESULT_NO = 0
RESULT_YES = 1
RESULT_FAILED = 2
RESULT_UNDECIDED = 255

# POLL_EVENTS event kinds.
EVENT_REACHED = 1
EVENT_FAILED = 2

MAX_FRAME = 64 * 1024 * 1024  # hard cap against garbage length prefixes

# Precompiled header/field structs: encode_frame and the Cursor integer
# reads are the per-frame hot path (the coalesced fabric moves hundreds
# of thousands of frames and fields per second), and `struct.pack("<I",
# v)` re-parses its format string and allocates an intermediate on every
# call. One compiled Struct per width, reused for the process lifetime.
_U8 = struct.Struct("<B")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_FRAME_HEADER = struct.Struct("<IB")  # length | lead
_TAGGED_HEADER = struct.Struct("<IBI")  # length | lead | correlation id


class Cursor:
    """Sequential reader over one frame's payload. ``start`` lets framed
    readers hand the body over without slicing off the already-consumed
    header bytes (one allocation saved per frame)."""

    __slots__ = ("_data", "_pos")

    def __init__(self, data: bytes, start: int = 0):
        self._data = data
        self._pos = start

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise ValueError("frame truncated")
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def u8(self) -> int:
        pos = self._pos
        if pos + 1 > len(self._data):
            raise ValueError("frame truncated")
        self._pos = pos + 1
        return self._data[pos]

    def u16(self) -> int:
        pos = self._pos
        if pos + 2 > len(self._data):
            raise ValueError("frame truncated")
        self._pos = pos + 2
        return _U16.unpack_from(self._data, pos)[0]

    def u32(self) -> int:
        pos = self._pos
        if pos + 4 > len(self._data):
            raise ValueError("frame truncated")
        self._pos = pos + 4
        return _U32.unpack_from(self._data, pos)[0]

    def u64(self) -> int:
        pos = self._pos
        if pos + 8 > len(self._data):
            raise ValueError("frame truncated")
        self._pos = pos + 8
        return _U64.unpack_from(self._data, pos)[0]

    def string(self) -> str:
        return self._take(self.u16()).decode("utf-8")

    def blob(self) -> bytes:
        return self._take(self.u32())

    def skip(self, n: int) -> None:
        if self._pos + n > len(self._data):
            raise ValueError("frame truncated")
        self._pos += n

    def fork(self) -> "Cursor":
        """Independent cursor at the current position over the same
        buffer — lets a fast path consume the frame and still hand the
        untouched bytes to the fallback decoder."""
        return Cursor(self._data, self._pos)

    def done(self) -> bool:
        return self._pos == len(self._data)

    def remaining(self) -> int:
        return len(self._data) - self._pos


# Field encoders: the compiled Structs' bound ``pack`` methods ARE the
# functions (same signatures, same struct.error on out-of-range values,
# no per-call format parse).
u8 = _U8.pack
u16 = _U16.pack
u32 = _U32.pack
u64 = _U64.pack


def string(s: str) -> bytes:
    raw = s.encode("utf-8")
    return _U16.pack(len(raw)) + raw


def blob(b: bytes) -> bytes:
    return _U32.pack(len(b)) + b


def encode_frame(lead: int, payload: bytes = b"") -> bytes:
    """``lead`` is the opcode (requests) or status (responses)."""
    return _FRAME_HEADER.pack(1 + len(payload), lead) + payload


def encode_tagged_frame(lead: int, corr_id: int, payload: bytes = b"") -> bytes:
    """Pipelined-mode frame: ``lead`` + correlation id + payload (only
    valid on a connection that negotiated ``FEATURE_PIPELINING``)."""
    return _TAGGED_HEADER.pack(5 + len(payload), lead, corr_id) + payload


# ── Optional trace-context suffix ──────────────────────────────────────

TRACE_SUFFIX_VERSION = 0


def encode_trace_context(ctx: TraceContext | None) -> bytes:
    """The 26-byte optional frame suffix (empty bytes for None, so call
    sites can append unconditionally)."""
    if ctx is None:
        return b""
    return u8(TRACE_SUFFIX_VERSION) + ctx.to_wire()


def read_trace_context(c: Cursor) -> TraceContext | None:
    """Consume a trailing trace-context suffix, if present. Returns None
    for frames without one (old peers), with an unknown suffix version,
    or with a short/odd-sized tail (future peers, foreign embedders
    appending their own trailers — the bytes are consumed and ignored,
    never an error, matching the pre-suffix server's tolerance)."""
    if c.done():
        return None
    if c.remaining() < 1 + TRACE_WIRE_BYTES:
        c.raw(c.remaining())
        return None
    version = c.u8()
    raw = c.raw(TRACE_WIRE_BYTES)
    if version != TRACE_SUFFIX_VERSION:
        return None
    return TraceContext.from_wire(raw)


def read_exact(sock, n: int) -> bytes:
    """Read exactly n bytes from a socket; raises ConnectionError on EOF.
    Reads into one preallocated buffer (``recv_into``) instead of
    accumulating chunk objects and joining — one allocation per frame
    body regardless of how the kernel segments it."""
    buf = bytearray(n)
    view = memoryview(buf)
    pos = 0
    while pos < n:
        got = sock.recv_into(view[pos:])
        if not got:
            raise ConnectionError("bridge peer closed the connection")
        pos += got
    return bytes(buf)


def read_frame(sock) -> tuple[int, Cursor]:
    """Returns (opcode-or-status, payload cursor)."""
    (length,) = _U32.unpack(read_exact(sock, 4))
    if length < 1 or length > MAX_FRAME:
        raise ValueError(f"bad frame length {length}")
    body = read_exact(sock, length)
    return body[0], Cursor(body, 1)


def read_tagged_frame(sock) -> tuple[int, int, Cursor]:
    """Pipelined-mode :func:`read_frame`: returns (opcode-or-status,
    correlation id, payload cursor)."""
    (length,) = _U32.unpack(read_exact(sock, 4))
    if length < 5 or length > MAX_FRAME:
        raise ValueError(f"bad tagged frame length {length}")
    body = read_exact(sock, length)
    return body[0], _U32.unpack_from(body, 1)[0], Cursor(body, 5)


def split_frames(buf: bytearray, min_len: int = 1) -> "list[bytes]":
    """Split every COMPLETE length-prefixed frame body off the front of
    ``buf`` (mutated in place; a trailing partial frame stays buffered
    for the next feed). One home for the accumulate/length-check/slice
    loop every buffered lane runs — the TCP reader and both shm ring
    readers stay provably consistent. Raises ValueError on a
    structurally impossible length: the stream has lost framing and the
    caller must kill it (frames split earlier in the same feed are
    dropped with it — their futures fail typed when the lane dies)."""
    frames: list[bytes] = []
    pos = 0
    n = len(buf)
    while n - pos >= 4:
        (length,) = _U32.unpack_from(buf, pos)
        if length < min_len or length > MAX_FRAME:
            raise ValueError(f"bad frame length {length}")
        if n - pos < 4 + length:
            break
        frames.append(bytes(buf[pos + 4 : pos + 4 + length]))
        pos += 4 + length
    if pos:
        del buf[:pos]
    return frames


def parse_frame(body: bytes, tagged: bool) -> tuple[int, int, Cursor]:
    """Parse one already-read frame body (the length prefix stripped):
    returns (lead, correlation id — 0 when untagged, payload cursor).
    The non-blocking transport reads socket bytes into its own buffer
    and hands complete bodies here."""
    if tagged:
        if len(body) < 5:
            raise ValueError("tagged frame truncated")
        return body[0], _U32.unpack_from(body, 1)[0], Cursor(body, 5)
    if len(body) < 1:
        raise ValueError("frame truncated")
    return body[0], 0, Cursor(body, 1)


# ── Coalesced columnar vote frames (OP_VOTE_BATCH) ─────────────────────
#
# Layout: u64 now | u32 group_count
#         | group_count × (u32 peer_id | string scope | u32 vote_count)
#         | Σvote_count × u32 vote_len        (columnar lengths)
#         | concatenated vote payload bytes    (same flattened order)
# Response: u32 total | total × u8 status (flattened batch order; the
# per-vote codes mirror OP_PROCESS_VOTES: StatusCode values, 241 for an
# undecodable blob, STATUS_UNKNOWN_PEER for a group naming no peer).


def encode_vote_batch(
    now: int, groups: "list[tuple[int, str, list[bytes]]]"
) -> bytes:
    """One coalesced frame payload from ``(peer_id, scope, votes)``
    groups (votes as wire bytes). Order inside a group — and across
    groups — is preserved end to end, so chained votes coalesced in
    submission order land in submission order."""
    head = [u64(now), u32(len(groups))]
    lens: list[bytes] = []
    bodies: list[bytes] = []
    for peer_id, scope, votes in groups:
        head.append(u32(peer_id) + string(scope) + u32(len(votes)))
        for v in votes:
            lens.append(u32(len(v)))
            bodies.append(v)
    return b"".join(head) + b"".join(lens) + b"".join(bodies)


def encode_vote_batch_segments(
    now: int, groups: "list[tuple[int, str, list[bytes]]]"
) -> "tuple[list[bytes], int]":
    """Scatter-gather :func:`encode_vote_batch`: returns ``(segments,
    total_bytes)`` where the segments are the frame head (header fields +
    length columns, one joined blob) followed by the vote payloads AS THE
    CALLER'S OWN bytes objects — no concatenation copy of the vote
    region. ``b"".join(segments)`` equals :func:`encode_vote_batch`'s
    output byte for byte; the transport hands the list to
    ``socket.sendmsg`` (or writes it segment-wise into a shm ring)."""
    head = [u64(now), u32(len(groups))]
    lens: list[bytes] = []
    bodies: list[bytes] = []
    body_bytes = 0
    for peer_id, scope, votes in groups:
        head.append(u32(peer_id) + string(scope) + u32(len(votes)))
        for v in votes:
            lens.append(u32(len(v)))
            bodies.append(v)
            body_bytes += len(v)
    lead = b"".join(head) + b"".join(lens)
    return [lead, *bodies], len(lead) + body_bytes


class VoteBatchView:
    """Zero-copy columnar view of one decoded ``OP_VOTE_BATCH`` payload:
    group metadata plus numpy views (no per-vote slicing) over the
    length column and the contiguous vote-bytes region."""

    __slots__ = ("now", "groups", "offsets", "data", "total")

    def __init__(self, now, groups, offsets, data, total):
        self.now = now
        self.groups = groups  # [(peer_id, scope, vote_count)]
        self.offsets = offsets  # int64[total+1], absolute into `data`
        self.data = data  # uint8 view over the frame's vote region
        self.total = total


def decode_vote_batch_views(c: Cursor) -> VoteBatchView:
    """Columnar :func:`decode_vote_batch`: same header walk (so
    malformed frames raise the same ``ValueError`` the object decoder
    would), but the length column becomes one u32 numpy view and the
    vote bytes stay one contiguous uint8 view — zero per-vote Python
    objects. Trailing bytes past the vote region are tolerated exactly
    as the object decoder tolerates them."""
    now = c.u64()
    groups: list[tuple[int, str, int]] = []
    for _ in range(c.u32()):
        peer_id = c.u32()
        scope = c.string()
        groups.append((peer_id, scope, c.u32()))
    total = sum(g[2] for g in groups)
    if c.remaining() < 4 * total:
        raise ValueError("frame truncated")
    lens = np.frombuffer(c._data, np.dtype("<u4"), count=total, offset=c._pos)
    c.skip(4 * total)
    offsets = np.zeros(total + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    need = int(offsets[-1])
    if c.remaining() < need:
        raise ValueError("frame truncated")
    data = np.frombuffer(c._data, np.uint8, count=need, offset=c._pos)
    c.skip(need)
    return VoteBatchView(now, groups, offsets, data, total)


def decode_vote_batch(
    c: Cursor,
) -> "tuple[int, list[tuple[int, str, list[bytes]]]]":
    """Inverse of :func:`encode_vote_batch`: (now, groups)."""
    now = c.u64()
    metas: list[tuple[int, str, int]] = []
    for _ in range(c.u32()):
        peer_id = c.u32()
        scope = c.string()
        metas.append((peer_id, scope, c.u32()))
    lens: list[int] = [c.u32() for _ in range(sum(m[2] for m in metas))]
    groups: list[tuple[int, str, list[bytes]]] = []
    k = 0
    for peer_id, scope, count in metas:
        votes = []
        for _ in range(count):
            votes.append(c.raw(lens[k]))
            k += 1
        groups.append((peer_id, scope, votes))
    return now, groups


def encode_deliver_proposals(
    peer_id: int, items: "list[tuple[str, bytes]]", now: int
) -> bytes:
    """``OP_DELIVER_PROPOSALS`` request payload: one home for the field
    walk (serial client, pipelined client, gossip node all send it)."""
    out = [u32(peer_id), u64(now), u32(len(items))]
    for scope, proposal in items:
        out.append(string(scope))
        out.append(blob(proposal))
    return b"".join(out)


def encode_fleet_tally(counts: "dict[int, int]") -> bytes:
    """``OP_FLEET_TALLY`` response payload: the slot-state histogram as
    (state_code, count) pairs, code-sorted for a stable wire image."""
    out = [u32(len(counts))]
    for code in sorted(counts):
        out.append(u32(int(code)) + u64(int(counts[code])))
    return b"".join(out)


def parse_fleet_tally(c: Cursor) -> "dict[int, int]":
    """Decode an ``OP_FLEET_TALLY`` response into {state_code: count}."""
    return {c.u32(): c.u64() for _ in range(c.u32())}


# ── Socket tuning ──────────────────────────────────────────────────────


def tune_socket(sock, *, nodelay: bool = True,
                sndbuf: int | None = None, rcvbuf: int | None = None) -> None:
    """Apply the bridge's socket defaults. ``TCP_NODELAY`` is ON for
    every bridge socket (both ends): the wire is dominated by small
    request/response frames, and Nagle coalescing would serialize each
    one behind the peer's delayed ACK (~40 ms stalls on the serial
    path). ``SO_SNDBUF``/``SO_RCVBUF`` default to the OS autotuned
    sizes, which are right for loopback and LAN; set them explicitly
    (e.g. 1–4 MiB) only for high-BDP WAN links where the pipelined
    fabric must keep a full window in flight — note Linux doubles the
    requested value and caps it at ``net.core.{w,r}mem_max``, so a
    silently clamped setsockopt is worth checking with getsockopt when
    tuning."""
    if nodelay:
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    if sndbuf is not None:
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF, sndbuf)
    if rcvbuf is not None:
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, rcvbuf)
