"""Embedder bridge wire protocol: framing, opcodes, and field codecs.

The reference is a library an application embeds in-process
(reference: README.md:183-197, src/lib.rs:15-34); its FFI story is "link the
crate". This framework's compute engine lives in a Python/JAX process, so the
embedder boundary is a byte protocol instead: any language opens a TCP
connection to :class:`~hashgraph_tpu.bridge.server.BridgeServer` and drives
the full ConsensusService surface (create_proposal, cast_vote,
process_incoming_{proposal,vote}, handle_consensus_timeout, events out) with
`Proposal`/`Vote` payloads as the exact protobuf bytes of
``protos/messages/v1/consensus.proto`` — the same bytes the reference's prost
codec produces, so a Rust embedder can decode them with its own generated
types. ``native/bridge_client.c`` is the C reference client.

Frame layout (all integers little-endian):

    request:  u32 length | u8 opcode | payload
    response: u32 length | u8 status | payload

``length`` counts the opcode/status byte plus the payload. Field codecs:
strings are ``u16 len + UTF-8``; byte blobs are ``u32 len + bytes``. Every
opcode except PING and ADD_PEER starts its payload with the ``u32 peer_id``
returned by ADD_PEER (a bridge server hosts many independent peers, mirroring
the reference's one-service-per-peer deployment, src/service.rs:26-29).

Statuses: 0 = OK; 1..29 mirror :class:`hashgraph_tpu.errors.StatusCode`;
240+ are bridge-level (unknown peer / malformed frame / unknown opcode /
internal error). Error responses carry the message as a string payload.

**Trace context (optional, backward compatible).** Proposal-lifecycle
requests (CREATE_PROPOSAL, CAST_VOTE, PROCESS_PROPOSAL, PROCESS_VOTE,
PROCESS_VOTES, HANDLE_TIMEOUT) may append a 26-byte trace-context suffix
after their last field: ``u8 version (0)`` + the 25-byte
:class:`~hashgraph_tpu.obs.trace.TraceContext` wire form (16-byte
trace_id, 8-byte parent span_id, u8 flags). CREATE_PROPOSAL and
CAST_VOTE responses append the same suffix carrying the proposal's bound
context, so the embedder can ferry it to the peers it gossips to.
Handlers never require the suffix (frames without it decode exactly as
before) and never read past their declared fields, so old and new peers
interoperate in both directions: an old server ignores the trailing
bytes, an old client ignores the suffixed response tail.
"""

from __future__ import annotations

import struct

from ..obs.trace import TRACE_WIRE_BYTES, TraceContext

PROTOCOL_VERSION = 1

# Opcodes.
OP_PING = 0
OP_ADD_PEER = 1
OP_CREATE_PROPOSAL = 2
OP_CAST_VOTE = 3
OP_PROCESS_PROPOSAL = 4
OP_PROCESS_VOTE = 5
OP_HANDLE_TIMEOUT = 6
OP_GET_RESULT = 7
OP_POLL_EVENTS = 8
OP_GET_PROPOSAL = 9
OP_GET_STATS = 10
OP_PROCESS_VOTES = 11  # batch: u32 count + count vote blobs -> u8 statuses
# Server-wide observability scrape (no peer_id prefix, like PING): returns
# the process metrics registry rendered in Prometheus text format as one
# byte blob — remote embedders scrape over the wire they already hold
# instead of needing the HTTP sidecar reachable.
OP_GET_METRICS = 12
# Decision provenance: u32 peer_id + string scope + u32 proposal_id ->
# one JSON blob (TpuConsensusEngine.explain_decision: vote chain, quorum
# arithmetic, timeline phases, trace identity, WAL watermark).
OP_EXPLAIN = 13
# Consensus health observatory: u32 peer_id + u64 now (0 = the monitor's
# latest observed logical tick) -> one JSON blob
# (TpuConsensusEngine.health_report: per-peer scorecards with derived
# grades, self-authenticating equivocation/fork evidence, liveness
# watchdog, firing alert rules; durable peers overlay the WAL watermark).
OP_HEALTH = 14
# ── State sync (snapshot shipping + WAL tailing; durable peers only) ──
# SYNC_MANIFEST: u32 peer_id + u32 max_chunk_bytes (0 = server default)
# -> u64 snapshot_id | u64 watermark_lsn | u64 total_bytes |
#    u32 chunk_bytes | u32 session_count | u32 config_count |
#    u32 chunk_count | chunk_count × 32-byte SHA-256 chunk digests.
# The server captures (or reuses, when the WAL position is unchanged) a
# consistent snapshot of the peer's state at its WAL watermark; chunks
# are byte ranges of the serialized snapshot (sync.snapshot format).
OP_SYNC_MANIFEST = 15
# SYNC_CHUNK: u32 peer_id + u64 snapshot_id + u32 chunk_index -> one
# byte blob (that chunk of the snapshot). STATUS_SYNC_STALE means the
# identified snapshot is no longer served (the source's state moved on
# and the snapshot was rebuilt) — re-fetch the manifest and resume.
OP_SYNC_CHUNK = 16
# WAL_TAIL: u32 peer_id + u64 after_lsn + u32 max_bytes ->
# u32 count | count × (u64 lsn | u8 kind | u32 len | record payload) |
# u8 more. Streams the peer's WAL records after ``after_lsn`` in log
# order, resumable by advancing after_lsn to the last received LSN;
# ``more`` = 1 when the byte budget stopped the read short.
OP_WAL_TAIL = 17

# Bridge-level statuses (protocol StatusCode values occupy 0..29).
STATUS_OK = 0
STATUS_UNKNOWN_PEER = 240
STATUS_BAD_REQUEST = 241
STATUS_UNKNOWN_OPCODE = 242
STATUS_SYNC_STALE = 245  # requested snapshot_id no longer served
STATUS_INTERNAL = 250

# GET_RESULT payload byte.
RESULT_NO = 0
RESULT_YES = 1
RESULT_FAILED = 2
RESULT_UNDECIDED = 255

# POLL_EVENTS event kinds.
EVENT_REACHED = 1
EVENT_FAILED = 2

MAX_FRAME = 64 * 1024 * 1024  # hard cap against garbage length prefixes


class Cursor:
    """Sequential reader over one frame's payload."""

    def __init__(self, data: bytes):
        self._data = data
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise ValueError("frame truncated")
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def string(self) -> str:
        return self._take(self.u16()).decode("utf-8")

    def blob(self) -> bytes:
        return self._take(self.u32())

    def done(self) -> bool:
        return self._pos == len(self._data)

    def remaining(self) -> int:
        return len(self._data) - self._pos


def u8(v: int) -> bytes:
    return struct.pack("<B", v)


def u16(v: int) -> bytes:
    return struct.pack("<H", v)


def u32(v: int) -> bytes:
    return struct.pack("<I", v)


def u64(v: int) -> bytes:
    return struct.pack("<Q", v)


def string(s: str) -> bytes:
    raw = s.encode("utf-8")
    return u16(len(raw)) + raw


def blob(b: bytes) -> bytes:
    return u32(len(b)) + b


def encode_frame(lead: int, payload: bytes = b"") -> bytes:
    """``lead`` is the opcode (requests) or status (responses)."""
    return u32(1 + len(payload)) + u8(lead) + payload


# ── Optional trace-context suffix ──────────────────────────────────────

TRACE_SUFFIX_VERSION = 0


def encode_trace_context(ctx: TraceContext | None) -> bytes:
    """The 26-byte optional frame suffix (empty bytes for None, so call
    sites can append unconditionally)."""
    if ctx is None:
        return b""
    return u8(TRACE_SUFFIX_VERSION) + ctx.to_wire()


def read_trace_context(c: Cursor) -> TraceContext | None:
    """Consume a trailing trace-context suffix, if present. Returns None
    for frames without one (old peers), with an unknown suffix version,
    or with a short/odd-sized tail (future peers, foreign embedders
    appending their own trailers — the bytes are consumed and ignored,
    never an error, matching the pre-suffix server's tolerance)."""
    if c.done():
        return None
    if c.remaining() < 1 + TRACE_WIRE_BYTES:
        c.raw(c.remaining())
        return None
    version = c.u8()
    raw = c.raw(TRACE_WIRE_BYTES)
    if version != TRACE_SUFFIX_VERSION:
        return None
    return TraceContext.from_wire(raw)


def read_exact(sock, n: int) -> bytes:
    """Read exactly n bytes from a socket; raises ConnectionError on EOF."""
    chunks = []
    while n > 0:
        chunk = sock.recv(n)
        if not chunk:
            raise ConnectionError("bridge peer closed the connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def read_frame(sock) -> tuple[int, Cursor]:
    """Returns (opcode-or-status, payload cursor)."""
    (length,) = struct.unpack("<I", read_exact(sock, 4))
    if length < 1 or length > MAX_FRAME:
        raise ValueError(f"bad frame length {length}")
    body = read_exact(sock, length)
    return body[0], Cursor(body[1:])
