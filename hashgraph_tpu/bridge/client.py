"""Python reference client for the embedder bridge.

Mirrors ``native/bridge_client.c`` one call per opcode; used by the test
suite and as executable documentation of the wire protocol. An embedder in
any language reproduces exactly these byte sequences.
"""

from __future__ import annotations

import json
import socket
from dataclasses import dataclass

from ..errors import StatusCode, error_for_code
from ..obs.trace import TraceContext, current_context
from . import protocol as P


class BridgeError(Exception):
    """Non-OK response from the bridge, carrying the wire status."""

    def __init__(self, status: int, message: str = ""):
        self.status = status
        try:
            name = StatusCode(status).name
        except ValueError:
            name = f"bridge status {status}"
        super().__init__(f"{name}: {message}" if message else name)


@dataclass(frozen=True)
class BridgeEvent:
    scope: str
    kind: int  # P.EVENT_REACHED / P.EVENT_FAILED
    proposal_id: int
    result: bool
    timestamp: int


class BridgeClient:
    """One bridge connection.

    Distributed tracing: proposal-lifecycle calls accept an optional
    ``trace=`` :class:`~hashgraph_tpu.obs.trace.TraceContext` (falling
    back to the ambient :func:`~hashgraph_tpu.obs.trace.current_context`)
    appended as the protocol's backward-compatible frame suffix.
    ``create_proposal``/``cast_vote`` store the proposal's server-bound
    context in :attr:`last_trace_context` — pass it as ``trace=`` when
    ferrying the returned bytes to other peers so every peer's spans
    stitch into one trace."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        #: Trace context returned by the last create_proposal/cast_vote.
        self.last_trace_context: TraceContext | None = None

    def close(self) -> None:
        self._sock.close()

    def __enter__(self) -> "BridgeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ── plumbing ───────────────────────────────────────────────────────

    def _call(self, opcode: int, payload: bytes = b"") -> P.Cursor:
        self._sock.sendall(P.encode_frame(opcode, payload))
        status, cursor = P.read_frame(self._sock)
        if status != P.STATUS_OK:
            message = ""
            try:
                message = cursor.string()
            except ValueError:
                pass
            raise BridgeError(status, message)
        return cursor

    # ── API ────────────────────────────────────────────────────────────

    @staticmethod
    def _suffix(trace: TraceContext | None) -> bytes:
        """Explicit ``trace=`` wins; otherwise the ambient context (if
        any); empty bytes keep the frame byte-identical to the old wire."""
        return P.encode_trace_context(
            trace if trace is not None else current_context()
        )

    def ping(self) -> int:
        return self._call(P.OP_PING).u32()

    def add_peer(self, private_key: bytes | None = None) -> tuple[int, bytes]:
        """Returns (peer_id, identity bytes)."""
        key = private_key or b""
        cursor = self._call(P.OP_ADD_PEER, P.u8(len(key)) + key)
        peer_id = cursor.u32()
        identity = cursor.raw(cursor.u8())
        return peer_id, identity

    def create_proposal(
        self,
        peer: int,
        scope: str,
        now: int,
        name: str,
        payload: bytes,
        expected_voters: int,
        rel_expiration: int,
        liveness_yes: bool = True,
        trace: TraceContext | None = None,
    ) -> tuple[int, bytes]:
        """Returns (proposal_id, proposal protobuf bytes); the proposal's
        bound trace context lands in :attr:`last_trace_context`."""
        cursor = self._call(
            P.OP_CREATE_PROPOSAL,
            P.u32(peer)
            + P.string(scope)
            + P.u64(now)
            + P.string(name)
            + P.blob(payload)
            + P.u32(expected_voters)
            + P.u64(rel_expiration)
            + P.u8(1 if liveness_yes else 0)
            + self._suffix(trace),
        )
        pid, blob = cursor.u32(), cursor.blob()
        self.last_trace_context = P.read_trace_context(cursor)
        return pid, blob

    def cast_vote(
        self,
        peer: int,
        scope: str,
        pid: int,
        choice: bool,
        now: int,
        trace: TraceContext | None = None,
    ) -> bytes:
        """Returns the signed Vote protobuf bytes for gossiping; the
        proposal's bound trace context lands in :attr:`last_trace_context`."""
        cursor = self._call(
            P.OP_CAST_VOTE,
            P.u32(peer)
            + P.string(scope)
            + P.u32(pid)
            + P.u8(1 if choice else 0)
            + P.u64(now)
            + self._suffix(trace),
        )
        blob = cursor.blob()
        self.last_trace_context = P.read_trace_context(cursor)
        return blob

    def process_proposal(
        self,
        peer: int,
        scope: str,
        proposal: bytes,
        now: int,
        trace: TraceContext | None = None,
    ) -> None:
        self._call(
            P.OP_PROCESS_PROPOSAL,
            P.u32(peer)
            + P.string(scope)
            + P.u64(now)
            + P.blob(proposal)
            + self._suffix(trace),
        )

    def process_vote(
        self,
        peer: int,
        scope: str,
        vote: bytes,
        now: int,
        trace: TraceContext | None = None,
    ) -> None:
        self._call(
            P.OP_PROCESS_VOTE,
            P.u32(peer)
            + P.string(scope)
            + P.u64(now)
            + P.blob(vote)
            + self._suffix(trace),
        )

    # Soft ceiling per PROCESS_VOTES frame, comfortably under the server's
    # 64 MiB MAX_FRAME; larger batches are chunked transparently.
    _VOTE_FRAME_BUDGET = 8 * 1024 * 1024

    def process_votes(
        self,
        peer: int,
        scope: str,
        votes: list[bytes],
        now: int,
        trace: TraceContext | None = None,
    ) -> list[int]:
        """Batch delivery: one frame (chunked past ~8 MiB), per-vote
        StatusCode list back in batch order (0 OK / 28 ALREADY_REACHED are
        successes; 241 marks an undecodable blob; others are rejections)."""
        statuses: list[int] = []
        start = 0
        while start < len(votes):
            size = 0
            stop = start
            while stop < len(votes) and (
                size + len(votes[stop]) + 4 <= self._VOTE_FRAME_BUDGET
                or stop == start
            ):
                size += len(votes[stop]) + 4
                stop += 1
            chunk = votes[start:stop]
            payload = [P.u32(peer), P.string(scope), P.u64(now), P.u32(len(chunk))]
            payload.extend(P.blob(v) for v in chunk)
            payload.append(self._suffix(trace))
            cursor = self._call(P.OP_PROCESS_VOTES, b"".join(payload))
            statuses.extend(cursor.raw(cursor.u32()))
            start = stop
        return statuses

    def handle_timeout(
        self,
        peer: int,
        scope: str,
        pid: int,
        now: int,
        trace: TraceContext | None = None,
    ) -> bool:
        cursor = self._call(
            P.OP_HANDLE_TIMEOUT,
            P.u32(peer)
            + P.string(scope)
            + P.u32(pid)
            + P.u64(now)
            + self._suffix(trace),
        )
        return bool(cursor.u8())

    def get_result(self, peer: int, scope: str, pid: int) -> bool | None:
        """True/False once decided, None while active; raises on failed."""
        cursor = self._call(P.OP_GET_RESULT, P.u32(peer) + P.string(scope) + P.u32(pid))
        value = cursor.u8()
        if value == P.RESULT_UNDECIDED:
            return None
        if value == P.RESULT_FAILED:
            raise error_for_code(int(StatusCode.CONSENSUS_FAILED))()
        return value == P.RESULT_YES

    def poll_events(self, peer: int) -> list[BridgeEvent]:
        cursor = self._call(P.OP_POLL_EVENTS, P.u32(peer))
        events = []
        for _ in range(cursor.u32()):
            scope = cursor.string()
            kind = cursor.u8()
            pid = cursor.u32()
            result = bool(cursor.u8())
            ts = cursor.u64()
            events.append(BridgeEvent(scope, kind, pid, result, ts))
        return events

    def get_proposal(self, peer: int, scope: str, pid: int) -> bytes:
        return self._call(
            P.OP_GET_PROPOSAL, P.u32(peer) + P.string(scope) + P.u32(pid)
        ).blob()

    def get_stats(self, peer: int, scope: str) -> tuple[int, int, int, int]:
        """(total, active, failed, reached)."""
        cursor = self._call(P.OP_GET_STATS, P.u32(peer) + P.string(scope))
        return cursor.u32(), cursor.u32(), cursor.u32(), cursor.u32()

    def explain(self, peer: int, scope: str, pid: int) -> dict:
        """Decision provenance for one proposal (``OP_EXPLAIN``): the
        accepted vote chain with per-peer contributions, the quorum
        arithmetic (required votes, yes/no/silent counts, decision rule),
        lifecycle timeline, distributed-trace identity, and — for durable
        peers — the WAL LSN watermark. Raises the usual wire-mapped
        errors (e.g. SESSION_NOT_FOUND) for unknown proposals."""
        cursor = self._call(
            P.OP_EXPLAIN, P.u32(peer) + P.string(scope) + P.u32(pid)
        )
        return json.loads(cursor.blob().decode("utf-8"))

    def health(self, peer: int, now: int | None = None) -> dict:
        """Consensus-health snapshot for one peer (``OP_HEALTH``):
        per-peer scorecards with derived ``healthy | suspect | faulty``
        grades, the retained self-authenticating equivocation/fork
        evidence (verbatim signed vote bytes, hex), liveness-watchdog
        state, and the firing alert rules — plus the WAL watermark for
        durable peers. ``now`` is the embedder's logical tick for
        staleness grading (omit to use the server monitor's latest)."""
        cursor = self._call(
            P.OP_HEALTH, P.u32(peer) + P.u64(now if now is not None else 0)
        )
        return json.loads(cursor.blob().decode("utf-8"))

    def sync_manifest(self, peer: int, max_chunk_bytes: int = 0) -> dict:
        """State-sync snapshot manifest for a durable peer
        (``OP_SYNC_MANIFEST``): the snapshot's identity (``snapshot_id``),
        its WAL ``watermark`` LSN, transfer geometry (``total_bytes``,
        ``chunk_bytes``, ``chunk_count``), item counts, and per-chunk
        SHA-256 ``digests``. ``max_chunk_bytes`` caps the server's chunk
        size (0 = server default). Raises BridgeError(241) for
        undurable peers."""
        cursor = self._call(
            P.OP_SYNC_MANIFEST, P.u32(peer) + P.u32(max_chunk_bytes)
        )
        manifest = {
            "snapshot_id": cursor.u64(),
            "watermark": cursor.u64(),
            "total_bytes": cursor.u64(),
            "chunk_bytes": cursor.u32(),
            "session_count": cursor.u32(),
            "config_count": cursor.u32(),
        }
        count = cursor.u32()
        manifest["chunk_count"] = count
        manifest["digests"] = [cursor.raw(32) for _ in range(count)]
        return manifest

    def sync_chunk(self, peer: int, snapshot_id: int, index: int) -> bytes:
        """One snapshot chunk (``OP_SYNC_CHUNK``). Raises
        BridgeError(``P.STATUS_SYNC_STALE``) when the identified snapshot
        is no longer served — re-fetch the manifest and resume from the
        chunks already verified."""
        return self._call(
            P.OP_SYNC_CHUNK, P.u32(peer) + P.u64(snapshot_id) + P.u32(index)
        ).blob()

    def wal_tail(
        self, peer: int, after_lsn: int, max_bytes: int = 0
    ) -> "tuple[list[tuple[int, int, bytes]], bool]":
        """WAL records after ``after_lsn`` (``OP_WAL_TAIL``): returns
        ``(records, more)`` with records as ``(lsn, kind, payload)`` in
        log order; ``more`` means the server's byte budget stopped the
        read short — loop with ``after_lsn`` advanced to the last
        received LSN."""
        cursor = self._call(
            P.OP_WAL_TAIL, P.u32(peer) + P.u64(after_lsn) + P.u32(max_bytes)
        )
        records = []
        for _ in range(cursor.u32()):
            lsn = cursor.u64()
            kind = cursor.u8()
            records.append((lsn, kind, cursor.blob()))
        return records, bool(cursor.u8())

    def get_metrics(self) -> str:
        """Prometheus text-format scrape of the server process's metrics
        registry (server-wide — no peer id). The same text the HTTP
        sidecar's ``/metrics`` serves, for embedders that only hold the
        bridge wire."""
        return self._call(P.OP_GET_METRICS).blob().decode("utf-8")
