"""Python reference client for the embedder bridge.

Mirrors ``native/bridge_client.c`` one call per opcode; used by the test
suite and as executable documentation of the wire protocol. An embedder in
any language reproduces exactly these byte sequences.
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass

from ..errors import StatusCode, error_for_code
from ..obs import flight_recorder
from ..obs.trace import TraceContext, current_context
from . import protocol as P


class BridgeError(Exception):
    """Non-OK response from the bridge, carrying the wire status."""

    def __init__(self, status: int, message: str = ""):
        self.status = status
        # Raw payload string, pre-formatting: typed statuses
        # (STATUS_SHARD_MIGRATING, STATUS_RETRY_AFTER) carry their
        # retry-after hint here as a decimal-seconds string.
        self.message = message
        try:
            name = StatusCode(status).name
        except ValueError:
            name = f"bridge status {status}"
        super().__init__(f"{name}: {message}" if message else name)


class BridgeConnectionLost(ConnectionError):
    """The bridge connection died with requests still in flight. Every
    pending future of a :class:`PipelinedBridgeClient` (and of the gossip
    transport's channels) resolves to this — a typed, per-request signal
    that the response will never arrive, distinct from a server-side
    rejection (:class:`BridgeError`)."""


@dataclass(frozen=True)
class ReconnectPolicy:
    """Bounded, jittered exponential backoff for opt-in channel
    auto-reconnect (:class:`PipelinedBridgeClient` and the gossip
    :class:`~hashgraph_tpu.gossip.transport.GossipTransport` both take
    one). The contract is deliberately narrow: in-flight requests on a
    dying channel STILL fail typed (``BridgeConnectionLost`` — a lost
    frame cannot be replayed safely by a generic layer), but the channel
    itself comes back — fresh socket, fresh HELLO feature negotiation —
    so a crash-restarting peer heals without embedder plumbing. Jitter
    (a random fraction shaved off each delay) keeps a fleet of clients
    from stampeding a peer the moment it returns."""

    max_attempts: int = 6
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: float = 0.5  # fraction of each delay randomized away

    def __post_init__(self):
        if self.max_attempts <= 0:
            raise ValueError("max_attempts must be positive")
        if not (0.0 <= self.jitter <= 1.0):
            raise ValueError("jitter must be in [0, 1]")

    def delay(self, attempt: int, rng=random) -> float:
        """Backoff before attempt ``attempt`` (0-based): exponential from
        ``base_delay``, capped at ``max_delay``, minus a random slice up
        to ``jitter`` of itself."""
        full = min(self.max_delay, self.base_delay * (2.0 ** attempt))
        return full * (1.0 - self.jitter * rng.random())


@dataclass(frozen=True)
class BridgeEvent:
    scope: str
    kind: int  # P.EVENT_REACHED / P.EVENT_FAILED
    proposal_id: int
    result: bool
    timestamp: int


class BridgeClient:
    """One bridge connection.

    Distributed tracing: proposal-lifecycle calls accept an optional
    ``trace=`` :class:`~hashgraph_tpu.obs.trace.TraceContext` (falling
    back to the ambient :func:`~hashgraph_tpu.obs.trace.current_context`)
    appended as the protocol's backward-compatible frame suffix.
    ``create_proposal``/``cast_vote`` store the proposal's server-bound
    context in :attr:`last_trace_context` — pass it as ``trace=`` when
    ferrying the returned bytes to other peers so every peer's spans
    stitch into one trace."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        P.tune_socket(self._sock)  # TCP_NODELAY on: small-frame wire
        #: Trace context returned by the last create_proposal/cast_vote.
        self.last_trace_context: TraceContext | None = None

    def close(self) -> None:
        self._sock.close()

    def __enter__(self) -> "BridgeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ── plumbing ───────────────────────────────────────────────────────

    def _call(self, opcode: int, payload: bytes = b"") -> P.Cursor:
        self._sock.sendall(P.encode_frame(opcode, payload))
        status, cursor = P.read_frame(self._sock)
        if status != P.STATUS_OK:
            message = ""
            try:
                message = cursor.string()
            except ValueError:
                pass
            raise BridgeError(status, message)
        return cursor

    # ── API ────────────────────────────────────────────────────────────

    @staticmethod
    def _suffix(trace: TraceContext | None) -> bytes:
        """Explicit ``trace=`` wins; otherwise the ambient context (if
        any); empty bytes keep the frame byte-identical to the old wire."""
        return P.encode_trace_context(
            trace if trace is not None else current_context()
        )

    def ping(self) -> int:
        return self._call(P.OP_PING).u32()

    def add_peer(self, private_key: bytes | None = None) -> tuple[int, bytes]:
        """Returns (peer_id, identity bytes)."""
        key = private_key or b""
        cursor = self._call(P.OP_ADD_PEER, P.u8(len(key)) + key)
        peer_id = cursor.u32()
        identity = cursor.raw(cursor.u8())
        return peer_id, identity

    def create_proposal(
        self,
        peer: int,
        scope: str,
        now: int,
        name: str,
        payload: bytes,
        expected_voters: int,
        rel_expiration: int,
        liveness_yes: bool = True,
        trace: TraceContext | None = None,
    ) -> tuple[int, bytes]:
        """Returns (proposal_id, proposal protobuf bytes); the proposal's
        bound trace context lands in :attr:`last_trace_context`."""
        cursor = self._call(
            P.OP_CREATE_PROPOSAL,
            P.u32(peer)
            + P.string(scope)
            + P.u64(now)
            + P.string(name)
            + P.blob(payload)
            + P.u32(expected_voters)
            + P.u64(rel_expiration)
            + P.u8(1 if liveness_yes else 0)
            + self._suffix(trace),
        )
        pid, blob = cursor.u32(), cursor.blob()
        self.last_trace_context = P.read_trace_context(cursor)
        return pid, blob

    def cast_vote(
        self,
        peer: int,
        scope: str,
        pid: int,
        choice: bool,
        now: int,
        trace: TraceContext | None = None,
    ) -> bytes:
        """Returns the signed Vote protobuf bytes for gossiping; the
        proposal's bound trace context lands in :attr:`last_trace_context`."""
        cursor = self._call(
            P.OP_CAST_VOTE,
            P.u32(peer)
            + P.string(scope)
            + P.u32(pid)
            + P.u8(1 if choice else 0)
            + P.u64(now)
            + self._suffix(trace),
        )
        blob = cursor.blob()
        self.last_trace_context = P.read_trace_context(cursor)
        return blob

    def process_proposal(
        self,
        peer: int,
        scope: str,
        proposal: bytes,
        now: int,
        trace: TraceContext | None = None,
    ) -> None:
        self._call(
            P.OP_PROCESS_PROPOSAL,
            P.u32(peer)
            + P.string(scope)
            + P.u64(now)
            + P.blob(proposal)
            + self._suffix(trace),
        )

    def process_vote(
        self,
        peer: int,
        scope: str,
        vote: bytes,
        now: int,
        trace: TraceContext | None = None,
    ) -> None:
        self._call(
            P.OP_PROCESS_VOTE,
            P.u32(peer)
            + P.string(scope)
            + P.u64(now)
            + P.blob(vote)
            + self._suffix(trace),
        )

    # Soft ceiling per PROCESS_VOTES frame, comfortably under the server's
    # 64 MiB MAX_FRAME; larger batches are chunked transparently.
    _VOTE_FRAME_BUDGET = 8 * 1024 * 1024

    def process_votes(
        self,
        peer: int,
        scope: str,
        votes: list[bytes],
        now: int,
        trace: TraceContext | None = None,
    ) -> list[int]:
        """Batch delivery: one frame (chunked past ~8 MiB), per-vote
        StatusCode list back in batch order (0 OK / 28 ALREADY_REACHED are
        successes; 241 marks an undecodable blob; others are rejections)."""
        statuses: list[int] = []
        start = 0
        while start < len(votes):
            size = 0
            stop = start
            while stop < len(votes) and (
                size + len(votes[stop]) + 4 <= self._VOTE_FRAME_BUDGET
                or stop == start
            ):
                size += len(votes[stop]) + 4
                stop += 1
            chunk = votes[start:stop]
            payload = [P.u32(peer), P.string(scope), P.u64(now), P.u32(len(chunk))]
            payload.extend(P.blob(v) for v in chunk)
            payload.append(self._suffix(trace))
            cursor = self._call(P.OP_PROCESS_VOTES, b"".join(payload))
            statuses.extend(cursor.raw(cursor.u32()))
            start = stop
        return statuses

    def handle_timeout(
        self,
        peer: int,
        scope: str,
        pid: int,
        now: int,
        trace: TraceContext | None = None,
    ) -> bool:
        cursor = self._call(
            P.OP_HANDLE_TIMEOUT,
            P.u32(peer)
            + P.string(scope)
            + P.u32(pid)
            + P.u64(now)
            + self._suffix(trace),
        )
        return bool(cursor.u8())

    def get_result(self, peer: int, scope: str, pid: int) -> bool | None:
        """True/False once decided, None while active; raises on failed."""
        cursor = self._call(P.OP_GET_RESULT, P.u32(peer) + P.string(scope) + P.u32(pid))
        value = cursor.u8()
        if value == P.RESULT_UNDECIDED:
            return None
        if value == P.RESULT_FAILED:
            raise error_for_code(int(StatusCode.CONSENSUS_FAILED))()
        return value == P.RESULT_YES

    def poll_events(self, peer: int, max_events: int | None = None):
        """Drain the peer's pending consensus events in ONE frame.

        ``max_events=None`` (the old wire form) returns the full drained
        ``list[BridgeEvent]``. With a bound — the gossip fabric's event
        pump, which must not let one hot peer monopolize a poll window —
        the request carries a trailing ``u32`` and the reply a trailing
        ``more`` flag: returns ``(events, more)``, where ``more`` means
        the bound stopped the drain and another poll should follow
        immediately (requires a ``FEATURE_EVENT_BOUND`` server; old
        servers ignore the extra bytes and drain fully, so the caller
        sees ``more=False`` with a possibly over-bound list)."""
        payload = P.u32(peer)
        if max_events is not None:
            payload += P.u32(max_events)
        cursor = self._call(P.OP_POLL_EVENTS, payload)
        events = []
        for _ in range(cursor.u32()):
            scope = cursor.string()
            kind = cursor.u8()
            pid = cursor.u32()
            result = bool(cursor.u8())
            ts = cursor.u64()
            events.append(BridgeEvent(scope, kind, pid, result, ts))
        if max_events is None:
            return events
        more = bool(cursor.u8()) if cursor.remaining() >= 1 else False
        return events, more

    def get_proposal(self, peer: int, scope: str, pid: int) -> bytes:
        return self._call(
            P.OP_GET_PROPOSAL, P.u32(peer) + P.string(scope) + P.u32(pid)
        ).blob()

    def get_stats(self, peer: int, scope: str) -> tuple[int, int, int, int]:
        """(total, active, failed, reached)."""
        cursor = self._call(P.OP_GET_STATS, P.u32(peer) + P.string(scope))
        return cursor.u32(), cursor.u32(), cursor.u32(), cursor.u32()

    def explain(self, peer: int, scope: str, pid: int) -> dict:
        """Decision provenance for one proposal (``OP_EXPLAIN``): the
        accepted vote chain with per-peer contributions, the quorum
        arithmetic (required votes, yes/no/silent counts, decision rule),
        lifecycle timeline, distributed-trace identity, and — for durable
        peers — the WAL LSN watermark. Raises the usual wire-mapped
        errors (e.g. SESSION_NOT_FOUND) for unknown proposals."""
        cursor = self._call(
            P.OP_EXPLAIN, P.u32(peer) + P.string(scope) + P.u32(pid)
        )
        return json.loads(cursor.blob().decode("utf-8"))

    def health(self, peer: int, now: int | None = None) -> dict:
        """Consensus-health snapshot for one peer (``OP_HEALTH``):
        per-peer scorecards with derived ``healthy | suspect | faulty``
        grades, the retained self-authenticating equivocation/fork
        evidence (verbatim signed vote bytes, hex), liveness-watchdog
        state, and the firing alert rules — plus the WAL watermark for
        durable peers. ``now`` is the embedder's logical tick for
        staleness grading (omit to use the server monitor's latest)."""
        cursor = self._call(
            P.OP_HEALTH, P.u32(peer) + P.u64(now if now is not None else 0)
        )
        return json.loads(cursor.blob().decode("utf-8"))

    def sync_manifest(self, peer: int, max_chunk_bytes: int = 0) -> dict:
        """State-sync snapshot manifest for a durable peer
        (``OP_SYNC_MANIFEST``): the snapshot's identity (``snapshot_id``),
        its WAL ``watermark`` LSN, transfer geometry (``total_bytes``,
        ``chunk_bytes``, ``chunk_count``), item counts, and per-chunk
        SHA-256 ``digests``. ``max_chunk_bytes`` caps the server's chunk
        size (0 = server default). Raises BridgeError(241) for
        undurable peers."""
        return parse_sync_manifest(
            self._call(P.OP_SYNC_MANIFEST, P.u32(peer) + P.u32(max_chunk_bytes))
        )

    def sync_chunk(self, peer: int, snapshot_id: int, index: int) -> bytes:
        """One snapshot chunk (``OP_SYNC_CHUNK``). Raises
        BridgeError(``P.STATUS_SYNC_STALE``) when the identified snapshot
        is no longer served — re-fetch the manifest and resume from the
        chunks already verified."""
        return self._call(
            P.OP_SYNC_CHUNK, P.u32(peer) + P.u64(snapshot_id) + P.u32(index)
        ).blob()

    def wal_tail(
        self, peer: int, after_lsn: int, max_bytes: int = 0
    ) -> "tuple[list[tuple[int, int, bytes]], bool]":
        """WAL records after ``after_lsn`` (``OP_WAL_TAIL``): returns
        ``(records, more)`` with records as ``(lsn, kind, payload)`` in
        log order; ``more`` means the server's byte budget stopped the
        read short — loop with ``after_lsn`` advanced to the last
        received LSN."""
        cursor = self._call(
            P.OP_WAL_TAIL, P.u32(peer) + P.u64(after_lsn) + P.u32(max_bytes)
        )
        records = []
        for _ in range(cursor.u32()):
            lsn = cursor.u64()
            kind = cursor.u8()
            records.append((lsn, kind, cursor.blob()))
        return records, bool(cursor.u8())

    def get_metrics(self) -> str:
        """Prometheus text-format scrape of the server process's metrics
        registry (server-wide — no peer id). The same text the HTTP
        sidecar's ``/metrics`` serves, for embedders that only hold the
        bridge wire."""
        return self._call(P.OP_GET_METRICS).blob().decode("utf-8")

    def metrics_pull(self) -> dict:
        """Raw metric-federation frame (``OP_METRICS_PULL``, server-wide):
        ``{"host": <label>, "state": <mergeable registry state>, "slo":
        <SLO engine state>}``. Unlike :meth:`get_metrics` this is the
        UNRENDERED registry (non-cumulative histogram buckets, exemplars)
        — the input ``parallel.rollup.merge_metric_states`` sums across
        hosts into one fleet-wide scrape."""
        return json.loads(self._call(P.OP_METRICS_PULL).blob().decode("utf-8"))

    def profile(self) -> "dict | None":
        """Wall-clock attribution frame (``OP_PROFILE``, server-wide):
        ``{"host": <label>, "profile": <attribution report>}`` — stage
        busy shares, reactor dispatch counters, and the continuous
        profiler's sampled per-role stack summary. Host-labelled so
        ``parallel.rollup.merge_profile_states`` can federate frames.
        Returns None against an old peer (STATUS_UNKNOWN_OPCODE — the
        HELLO interop discipline: absence of the plane, not a fault)."""
        try:
            return json.loads(self._call(P.OP_PROFILE).blob().decode("utf-8"))
        except BridgeError as exc:
            if exc.status == P.STATUS_UNKNOWN_OPCODE:
                return None
            raise

    def state_fingerprint(self, peer: int) -> str:
        """The peer engine's order-insensitive content digest
        (``OP_STATE_FINGERPRINT``; see ``sync.state_fingerprint``) — two
        peers are state-identical iff their fingerprints match."""
        return self._call(P.OP_STATE_FINGERPRINT, P.u32(peer)).string()

    def fleet_tally(self, peer: int) -> "dict[int, int]":
        """The peer engine's slot-state histogram (``OP_FLEET_TALLY``) as
        {state_code: count}. Against a federation host this is the whole
        local fleet's tally — the frame a driver sums across hosts when
        the backend lacks cross-process collectives."""
        return P.parse_fleet_tally(self._call(P.OP_FLEET_TALLY, P.u32(peer)))

    def hello(self, features: int | None = None) -> int:
        """Feature negotiation (``OP_HELLO``); returns the granted bits.
        The default offer deliberately EXCLUDES ``FEATURE_PIPELINING``:
        this client reads one response per request, and a granted
        pipelining bit switches the connection to tagged frames it does
        not speak — use :class:`PipelinedBridgeClient` for that. An old
        server answers UNKNOWN_OPCODE, reported here as 0 (no features),
        after which this connection continues exactly as before."""
        if features is None:
            features = P.SUPPORTED_FEATURES & ~P.FEATURE_PIPELINING
        if features & P.FEATURE_PIPELINING:
            raise ValueError(
                "BridgeClient cannot negotiate FEATURE_PIPELINING "
                "(tagged frames); use PipelinedBridgeClient"
            )
        try:
            cursor = self._call(
                P.OP_HELLO, P.u32(P.PROTOCOL_VERSION) + P.u32(features)
            )
        except BridgeError as exc:
            if exc.status == P.STATUS_UNKNOWN_OPCODE:
                return 0
            raise
        cursor.u32()  # server protocol version (1)
        return cursor.u32()

    def deliver_proposals(
        self, peer: int, items: "list[tuple[str, bytes]]", now: int
    ) -> list[int]:
        """Anti-entropy delivery (``OP_DELIVER_PROPOSALS``): create-or-
        extend each ``(scope, proposal wire bytes)`` along the engine's
        validated-chain watermark. Returns per-item StatusCode values
        (0 OK = created or suffix-extended; 21 PROPOSAL_ALREADY_EXIST =
        benign redelivery; 241 = undecodable blob). Requires a
        ``FEATURE_DELIVER`` server."""
        cursor = self._call(
            P.OP_DELIVER_PROPOSALS,
            P.encode_deliver_proposals(peer, items, now),
        )
        return list(cursor.raw(cursor.u32()))


# ── Shared response parsers (serial client, pipelined client, gossip
#    transport — one home for each payload's field walk) ───────────────


def parse_sync_manifest(cursor: P.Cursor) -> dict:
    """Field walk of an ``OP_SYNC_MANIFEST`` OK response."""
    manifest = {
        "snapshot_id": cursor.u64(),
        "watermark": cursor.u64(),
        "total_bytes": cursor.u64(),
        "chunk_bytes": cursor.u32(),
        "session_count": cursor.u32(),
        "config_count": cursor.u32(),
    }
    count = cursor.u32()
    manifest["chunk_count"] = count
    manifest["digests"] = [cursor.raw(32) for _ in range(count)]
    return manifest


def parse_status_list(cursor: P.Cursor) -> list[int]:
    """``u32 count + count status bytes`` (PROCESS_VOTES / VOTE_BATCH /
    DELIVER_PROPOSALS responses)."""
    return list(cursor.raw(cursor.u32()))


class MappedFuture:
    """A :class:`concurrent.futures.Future` view whose ``result()``
    applies a parse function to the resolved cursor. The underlying
    future resolves to the response payload cursor (or raises
    :class:`BridgeError` / :class:`BridgeConnectionLost`)."""

    __slots__ = ("_future", "_fn")

    def __init__(self, future: Future, fn):
        self._future = future
        self._fn = fn

    def result(self, timeout: float | None = None):
        return self._fn(self._future.result(timeout))

    def exception(self, timeout: float | None = None):
        return self._future.exception(timeout)

    def done(self) -> bool:
        return self._future.done()

    def add_done_callback(self, fn) -> None:
        self._future.add_done_callback(lambda _f: fn(self))


class PipelinedBridgeClient:
    """A bridge connection with many requests in flight.

    On connect it sends ``OP_HELLO``; a new server grants
    ``FEATURE_PIPELINING`` and the connection switches to tagged frames —
    :meth:`submit` then returns immediately with a future, a background
    reader matches responses to futures by correlation id (responses may
    complete out of order), and ``max_inflight`` bounds the outstanding
    window (submit blocks — natural backpressure — when the server falls
    behind). Against an OLD server (HELLO answered UNKNOWN_OPCODE) every
    call degrades to the serial one-frame-at-a-time exchange and
    :meth:`submit` returns an already-resolved future, so callers write
    one code path and interoperate both ways; :attr:`pipelined` says
    which mode the connection landed in.

    If the connection drops with requests in flight, every pending
    future raises :class:`BridgeConnectionLost`.

    ``reconnect`` (a :class:`ReconnectPolicy`; default None = the old
    stay-dead behavior) opts into auto-reconnect: when the connection
    dies, pending futures still fail typed, but a background thread
    re-dials with capped, jittered exponential backoff and re-runs the
    HELLO negotiation, after which new submits flow again — the healing
    a crash-restarting server needs without embedder plumbing. Submits
    issued while the channel is down fail fast with
    :class:`BridgeConnectionLost` (callers retry; nothing queues against
    a dead peer).

    Not thread-safe for concurrent submitters by design EXCEPT
    :meth:`submit`/the async helpers, which take the writer lock; the
    sync convenience wrappers just await their own future.
    """

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 10.0,
        *,
        max_inflight: int = 256,
        features: int = P.SUPPORTED_FEATURES,
        reconnect: "ReconnectPolicy | None" = None,
    ):
        self._host = host
        self._port = port
        self._timeout = timeout
        self._offered = features
        self._reconnect = reconnect
        self._shutdown = False  # user called close(); never resurrect
        self._closed = True
        self._features = 0
        self._write_lock = threading.Lock()
        self._pending: dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._next_corr = 0
        # ONE window for the client's lifetime: credits released by the
        # old connection's cleanup must be the same tokens new submits
        # acquire, or a reconnect could over-release the semaphore.
        self._window = threading.BoundedSemaphore(max_inflight)
        self._reader: threading.Thread | None = None
        self._reconnector: threading.Thread | None = None
        self._establish()

    def _establish(self) -> None:
        """Dial + HELLO + (when granted) start the reader — the shared
        path of the constructor and every reconnect attempt."""
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        )
        P.tune_socket(sock)
        features = 0
        try:
            # HELLO handshake runs in the plain one-frame framing; only a
            # granted pipelining bit switches the connection.
            sock.sendall(
                P.encode_frame(
                    P.OP_HELLO,
                    P.u32(P.PROTOCOL_VERSION) + P.u32(self._offered),
                )
            )
            status, cursor = P.read_frame(sock)
            if status == P.STATUS_OK:
                cursor.u32()  # server protocol version
                features = cursor.u32()
            elif status != P.STATUS_UNKNOWN_OPCODE:
                message = ""
                try:
                    message = cursor.string()
                except ValueError:
                    pass
                raise BridgeError(status, message)
        except BaseException:
            sock.close()
            raise
        with self._pending_lock:
            # A close() racing a reconnect attempt must not be undone by
            # a late _establish: once shutdown is set, refuse the fresh
            # socket instead of resurrecting the client.
            if self._shutdown:
                sock.close()
                raise BridgeConnectionLost("client closed during reconnect")
            self._sock = sock
            self._features = features
            self.pipelined = bool(features & P.FEATURE_PIPELINING)
            if self.pipelined:
                # The reader blocks in recv for the connection's
                # lifetime; close() unblocks it by shutting the socket
                # down.
                self._sock.settimeout(None)
                self._reader = threading.Thread(
                    target=self._read_loop, daemon=True,
                    name="bridge-pipelined-reader",
                )
                self._reader.start()
            # Open for submits only once the connection is fully set up.
            self._closed = False

    @property
    def features(self) -> int:
        """Feature bits the server granted (0 against an old server)."""
        return self._features

    def _spawn_reconnector(self) -> None:
        """Start (at most one) background reconnect loop, if opted in and
        the death was not a user close()."""
        if self._reconnect is None or self._shutdown:
            return
        with self._pending_lock:
            if self._reconnector is not None and self._reconnector.is_alive():
                return
            thread = threading.Thread(
                target=self._reconnect_loop, daemon=True,
                name="bridge-reconnector",
            )
            self._reconnector = thread
        thread.start()

    def _reconnect_loop(self) -> None:
        policy = self._reconnect
        for attempt in range(policy.max_attempts):
            time.sleep(policy.delay(attempt))
            if self._shutdown:
                return
            try:
                self._establish()
            except (ConnectionError, OSError, BridgeError):
                continue
            flight_recorder.record(
                "bridge.reconnected",
                host=self._host, port=self._port, attempt=attempt + 1,
            )
            return
        flight_recorder.record(
            "bridge.reconnect_failed",
            host=self._host, port=self._port, attempts=policy.max_attempts,
        )

    def close(self) -> None:
        self._shutdown = True
        self._closed = True
        # Two sweeps: the first closes the current socket and waits out
        # the reconnector; a reconnect attempt that raced the shutdown
        # flag may have installed a fresh socket/reader in between, so
        # the second sweep (after the reconnector is provably done —
        # _establish refuses once _shutdown is set) closes that one too.
        for _ in range(2):
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            if self._reader is not None:
                self._reader.join(timeout=5)
            if self._reconnector is not None:
                self._reconnector.join(timeout=5)

    def __enter__(self) -> "PipelinedBridgeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ── plumbing ───────────────────────────────────────────────────────

    def submit(self, opcode: int, payload: bytes = b"") -> Future:
        """Send one request; the future resolves to the response payload
        cursor on STATUS_OK, or raises :class:`BridgeError` (non-OK) /
        :class:`BridgeConnectionLost` (connection died first). In serial
        fallback mode the exchange happens inline and the returned
        future is already resolved."""
        future: Future = Future()
        if not self.pipelined:
            if self._closed:
                future.set_exception(
                    BridgeConnectionLost("bridge connection is down")
                )
                return future
            try:
                with self._write_lock:
                    self._sock.sendall(P.encode_frame(opcode, payload))
                    status, cursor = P.read_frame(self._sock)
            except (ConnectionError, OSError) as exc:
                self._closed = True
                future.set_exception(
                    BridgeConnectionLost(f"bridge connection lost: {exc}")
                )
                self._spawn_reconnector()
                return future
            if status == P.STATUS_OK:
                future.set_result(cursor)
            else:
                future.set_exception(BridgeError(status, _error_message(cursor)))
            return future
        # Window credit: bounds client-side memory AND stops a runaway
        # submitter from ballooning the server's per-connection queue.
        self._window.acquire()
        with self._pending_lock:
            if self._closed:
                self._window.release()
                future.set_exception(
                    BridgeConnectionLost("client closed with request unsent")
                )
                return future
            corr = self._next_corr
            self._next_corr = (corr + 1) & 0xFFFFFFFF
            self._pending[corr] = future
        try:
            with self._write_lock:
                self._sock.sendall(P.encode_tagged_frame(opcode, corr, payload))
        except (ConnectionError, OSError) as exc:
            # The reader may have noticed the death first and already
            # failed (and released the window for) every pending future,
            # this one included — only the side that POPS the entry owns
            # its release + exception, so neither is ever doubled.
            with self._pending_lock:
                owned = self._pending.pop(corr, None) is not None
            if owned:
                self._window.release()
                future.set_exception(
                    BridgeConnectionLost(f"bridge connection lost: {exc}")
                )
        return future

    def _read_loop(self) -> None:
        try:
            while True:
                status, corr, cursor = P.read_tagged_frame(self._sock)
                with self._pending_lock:
                    future = self._pending.pop(corr, None)
                if future is None:
                    continue  # cancelled/unknown id: drop, keep reading
                self._window.release()
                if status == P.STATUS_OK:
                    future.set_result(cursor)
                else:
                    future.set_exception(
                        BridgeError(status, _error_message(cursor))
                    )
        except (ConnectionError, OSError, ValueError) as exc:
            with self._pending_lock:
                pending = list(self._pending.values())
                self._pending.clear()
                self._closed = True
            lost = BridgeConnectionLost(
                "bridge connection lost with "
                f"{len(pending)} requests in flight: {exc}"
            )
            for future in pending:
                self._window.release()
                future.set_exception(lost)
            self._spawn_reconnector()

    def call(self, opcode: int, payload: bytes = b"") -> P.Cursor:
        """Blocking :meth:`submit` (one round trip in either mode)."""
        return self.submit(opcode, payload).result(self._timeout)

    # ── async API (futures) ────────────────────────────────────────────

    def ping_async(self) -> MappedFuture:
        return MappedFuture(self.submit(P.OP_PING), lambda c: c.u32())

    def process_votes_async(
        self, peer: int, scope: str, votes: list[bytes], now: int
    ) -> MappedFuture:
        """One OP_PROCESS_VOTES frame in flight; resolves to the per-vote
        status list (no transparent chunking — the coalescer owns frame
        sizing on the fabric path)."""
        payload = [P.u32(peer), P.string(scope), P.u64(now), P.u32(len(votes))]
        payload.extend(P.blob(v) for v in votes)
        return MappedFuture(
            self.submit(P.OP_PROCESS_VOTES, b"".join(payload)),
            parse_status_list,
        )

    def vote_batch_async(
        self, now: int, groups: "list[tuple[int, str, list[bytes]]]"
    ) -> MappedFuture:
        """One coalesced columnar ``OP_VOTE_BATCH`` frame (requires
        ``FEATURE_VOTE_BATCH``); resolves to the flattened status list."""
        return MappedFuture(
            self.submit(P.OP_VOTE_BATCH, P.encode_vote_batch(now, groups)),
            parse_status_list,
        )

    def deliver_proposals_async(
        self, peer: int, items: "list[tuple[str, bytes]]", now: int
    ) -> MappedFuture:
        return MappedFuture(
            self.submit(
                P.OP_DELIVER_PROPOSALS,
                P.encode_deliver_proposals(peer, items, now),
            ),
            parse_status_list,
        )

    # ── sync conveniences (setup traffic; same wire as BridgeClient) ───

    def ping(self) -> int:
        return self.ping_async().result(self._timeout)

    def add_peer(self, private_key: bytes | None = None) -> tuple[int, bytes]:
        key = private_key or b""
        cursor = self.call(P.OP_ADD_PEER, P.u8(len(key)) + key)
        peer_id = cursor.u32()
        return peer_id, cursor.raw(cursor.u8())

    def create_proposal(
        self,
        peer: int,
        scope: str,
        now: int,
        name: str,
        payload: bytes,
        expected_voters: int,
        rel_expiration: int,
        liveness_yes: bool = True,
    ) -> tuple[int, bytes]:
        cursor = self.call(
            P.OP_CREATE_PROPOSAL,
            P.u32(peer)
            + P.string(scope)
            + P.u64(now)
            + P.string(name)
            + P.blob(payload)
            + P.u32(expected_voters)
            + P.u64(rel_expiration)
            + P.u8(1 if liveness_yes else 0),
        )
        return cursor.u32(), cursor.blob()

    def process_proposal(
        self, peer: int, scope: str, proposal: bytes, now: int
    ) -> None:
        self.call(
            P.OP_PROCESS_PROPOSAL,
            P.u32(peer) + P.string(scope) + P.u64(now) + P.blob(proposal),
        )

    def process_votes(
        self, peer: int, scope: str, votes: list[bytes], now: int
    ) -> list[int]:
        return self.process_votes_async(peer, scope, votes, now).result(
            self._timeout
        )

    def deliver_proposals(
        self, peer: int, items: "list[tuple[str, bytes]]", now: int
    ) -> list[int]:
        return self.deliver_proposals_async(peer, items, now).result(
            self._timeout
        )

    def sync_manifest(self, peer: int, max_chunk_bytes: int = 0) -> dict:
        return parse_sync_manifest(
            self.call(P.OP_SYNC_MANIFEST, P.u32(peer) + P.u32(max_chunk_bytes))
        )


def _error_message(cursor: P.Cursor) -> str:
    try:
        return cursor.string()
    except ValueError:
        return ""
