"""Embedder bridge server: the framework's consensus surface over TCP.

One :class:`BridgeServer` hosts many independent *peers*; each peer is a
:class:`~hashgraph_tpu.engine.TpuConsensusEngine` with its own signer and
event subscription — the same one-service-per-peer unit the reference
deploys (reference: src/service.rs:26-29, README.md:120-171). A non-Python
embedder (see ``native/bridge_client.c``) ferries the protobuf
``Proposal``/``Vote`` bytes between peers exactly the way the reference's
host application ferries prost messages between its services
(reference: README.md:183-197, tests/network_gossip_tests.rs:20-152).

The server binds loopback by default: it is an in-machine FFI boundary, not
a network service — transport security is the embedder's job, as in the
reference's no-I/O contract (reference: src/lib.rs:15-34).
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from struct import error as struct_error

import numpy as np

from ..engine import TpuConsensusEngine, VerifiedVoteCache
from ..errors import ConsensusError
from ..events import BroadcastEventBus, EventReceiver
from ..obs import (
    BRIDGE_ERRORS_TOTAL,
    BRIDGE_REQUESTS_TOTAL,
    BRIDGE_RETRY_AFTER_TOTAL,
    SHM_RINGS_ATTACHED_TOTAL,
    SYNC_CHUNKS_SENT_TOTAL,
    WIRE_APPLY_SECONDS_TOTAL,
    WIRE_COLUMNAR_FRAMES_TOTAL,
    WIRE_CRYPTO_SECONDS_TOTAL,
    WIRE_DECODE_SECONDS_TOTAL,
    WIRE_FALLBACK_FRAMES_TOTAL,
    HealthMonitor,
    MetricsSidecar,
    flight_recorder,
)
from ..obs import registry as default_registry
from ..obs import slo_engine as default_slo_engine
from ..obs.profiler import maybe_start_default as maybe_start_profiler
from ..obs.trace import trace_store, use_context
from ..parallel.fleet import ShardRecoveringError
from ..signing import ConsensusSignatureScheme
from ..signing.ethereum import EthereumConsensusSigner
from ..types import (
    ConsensusEvent,
    ConsensusFailedEvent,
    ConsensusReached,
    CreateProposalRequest,
)
from ..wire import Proposal, Vote
from . import protocol as P
from .reactor import ApplyReactor, reactor_enabled


class _Peer:
    def __init__(self, peer_id: int, engine: TpuConsensusEngine, receiver: EventReceiver):
        self.peer_id = peer_id
        self.engine = engine
        self.receiver = receiver


class _SerialLane:
    """Per-connection in-order execution lane over a shared pool: jobs
    run one at a time in submission order, but on pool threads so the
    connection's reader keeps draining frames. State-mutating opcodes on
    a pipelined connection go through this — pipelining removes the
    round-trip stall WITHOUT reordering a vote stream's chain links."""

    __slots__ = ("_pool", "_jobs", "_lock", "_active")

    def __init__(self, pool: ThreadPoolExecutor):
        self._pool = pool
        self._jobs: deque = deque()
        self._lock = threading.Lock()
        self._active = False

    def depth(self) -> int:
        """Queued jobs plus the one running — the overload-admission
        signal (server answers STATUS_RETRY_AFTER past its limit)."""
        with self._lock:
            return len(self._jobs) + (1 if self._active else 0)

    def submit(self, job) -> None:
        with self._lock:
            self._jobs.append(job)
            if self._active:
                return
            self._active = True
        try:
            self._pool.submit(self._drain)
        except RuntimeError:
            # Pool shutting down (server stop): run inline on the
            # connection thread — jobs still execute exactly once, in
            # order, before the connection unwinds.
            self._drain()

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._jobs:
                    self._active = False
                    return
                job = self._jobs.popleft()
            try:
                job()
            except Exception:  # pragma: no cover - job() handles its own
                pass


class _WireFramePrep:
    """One prepared OP_VOTE_BATCH frame on the columnar fast path: the
    decoded views plus per-peer row groups, each with its validation
    prepass already in flight on the verify pool."""

    __slots__ = ("view", "per_peer")

    def __init__(self, view, per_peer):
        self.view = view
        self.per_peer = per_peer


class _ConnState:
    """Per-connection pipelining state (created on HELLO upgrade)."""

    __slots__ = (
        "write_lock", "inflight", "ordered", "shm_running",
        "reactor_lock", "reactor_frames", "reactor_rows", "reactor_handles",
    )

    def __init__(self, pool: ThreadPoolExecutor, max_inflight: int):
        self.write_lock = threading.Lock()
        # Bounds concurrently-dispatched frames per connection: when the
        # window is full the reader blocks HERE instead of queueing
        # unboundedly — TCP backpressure does the rest.
        self.inflight = threading.BoundedSemaphore(max_inflight)
        self.ordered = _SerialLane(pool)
        # Flipped off when the owning TCP connection unwinds: the shm
        # serving thread (if any) watches it and exits.
        self.shm_running = True
        # Apply-reactor bookkeeping: frames/rows this connection has
        # queued into reactor windows but not yet had applied — the
        # overload-admission shed counts them (a full window must not
        # bypass admission control), and the handle deque is the
        # ordering barrier other mutating opcodes wait on.
        self.reactor_lock = threading.Lock()
        self.reactor_frames = 0
        self.reactor_rows = 0
        self.reactor_handles: deque = deque()


# Opcodes that execute in receive order on a pipelined connection; the
# set lives in protocol.py because the client transport's lane routing
# must agree with it (see MUTATING_OPCODES there for the rationale).
_ORDERED_OPCODES = P.MUTATING_OPCODES

# Reader-thread verdict: "_vote_batch_prepare already ran and chose the
# object fallback (a non-canonical row)" — the serial lane goes straight
# to the object path instead of re-decoding + re-parsing the frame just
# to reach the same conclusion. Distinct from None, which means "not
# attempted" (no reader prepass) or "prepare raised" (the lane re-runs
# the decode so the wire error contract answers with the exact message).
_PREP_FALLBACK = object()


@contextlib.contextmanager
def _traced(name: str, ctx, peer_id: int):
    """Activate a frame's trace context around its engine call and record
    the bridge dispatch itself as a child span (no-op for untraced
    frames, so the old wire stays zero-cost)."""
    if ctx is None or not trace_store.enabled:
        yield
        return
    start = time.time()
    with use_context(ctx):
        try:
            yield
        finally:
            trace_store.record(
                name,
                ctx.child(),
                start,
                time.time() - start,
                parent=ctx.span_id,
                peer=f"bridge:{peer_id}",
            )


class BridgeServer:
    """Threaded TCP front-end over per-peer consensus engines.

    ``port=0`` binds an ephemeral port (read it back from :attr:`address`).
    ``engine_factory(signer)`` swaps the backing engine, e.g. one over a
    sharded device-mesh pool; the default builds a small single-chip engine
    per peer.

    ``metrics_port`` (None = off, 0 = ephemeral) attaches an HTTP sidecar
    serving ``/metrics`` (Prometheus text format over the process-wide
    registry) and ``/healthz`` (JSON: running + peer count) for the
    server's lifetime; read the bound port from :attr:`metrics_address`.
    The ``GET_METRICS`` opcode serves the identical text over the bridge
    wire itself, sidecar or not.

    ``verify_cache`` ("shared" default) gives every default-built peer
    engine ONE :class:`~hashgraph_tpu.engine.VerifiedVoteCache`, so a vote
    gossiped to N co-hosted peers is signature-verified once per process;
    its hit/miss/evict counters land on the registry above.

    ``health_monitor`` (default: one fresh
    :class:`~hashgraph_tpu.obs.HealthMonitor` per server) collects every
    default-built peer engine's scorecards/evidence/alerts; firing
    critical rules flip ``/healthz`` to 503 and the ``OP_HEALTH`` opcode
    serves the full snapshot (``BridgeClient.health``).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        capacity: int = 256,
        voter_capacity: int = 16,
        engine_factory=None,
        wal_dir: str | None = None,
        wal_fsync: str = "batch",
        metrics_port: int | None = None,
        metrics_host: str = "127.0.0.1",
        verify_cache: "VerifiedVoteCache | None | str" = "shared",
        health_monitor: "HealthMonitor | None" = None,
        signer_factory: type | None = None,
        pipeline_workers: int | None = None,
        max_inflight_per_connection: int = 256,
        ordered_admission_limit: int | None = None,
        wire_columnar: "bool | None" = None,
        apply_reactor: "bool | ApplyReactor | None" = None,
        host_label: str | None = None,
    ):
        self._host = host
        self._port = port
        # Identity stamped on OP_METRICS_PULL frames: federation merges
        # per-host registry states under this label (default: the bound
        # host:port once the listener is up).
        self.host_label = host_label
        self._capacity = capacity
        self._voter_capacity = voter_capacity
        self._engine_factory = engine_factory
        # Scheme the ADD_PEER opcode mints signers from (all peers on a
        # network must share one scheme, reference src/signing.rs:46-74):
        # any ConsensusSignatureScheme class with ``random()`` and a
        # 32-byte-key constructor works — EthereumConsensusSigner
        # (default, the reference's scheme) or Ed25519ConsensusSigner
        # (batch-verified; the state-sync/catch-up benches use it).
        self._signer_factory = (
            signer_factory if signer_factory is not None
            else EthereumConsensusSigner
        )
        # ONE admission cache for every peer engine this server builds
        # ("shared", the default): co-hosted peers receive the same
        # gossiped votes, so a vote is ECDSA-verified once per server
        # process instead of once per peer. Pass an instance to share it
        # wider (or size it), or None to disable caching. Engines from
        # ``engine_factory`` manage their own cache.
        if isinstance(verify_cache, str) and verify_cache != "shared":
            # An unknown string would propagate into every peer engine and
            # crash each one at its first ingest — reject it here.
            raise ValueError(
                'verify_cache must be "shared", a VerifiedVoteCache, or None'
            )
        self._verify_cache = (
            VerifiedVoteCache() if verify_cache == "shared" else verify_cache
        )
        # ONE health monitor for every default-built peer engine: the
        # scorecards, evidence log, and /healthz verdict describe THIS
        # server's peers, not whatever other engines share the process
        # (the engine's process-wide default monitor would bleed an
        # unrelated engine's faulty peer into this server's 503). Anomaly
        # counters still land on the process-wide registry. Engines from
        # ``engine_factory`` keep whatever monitor they were built with.
        # Gauges are registered only for a monitor this server built —
        # a caller-passed monitor owns its own registration (it may
        # already be registered; providers are additive, so a second
        # registration would double its gauge contributions).
        if health_monitor is not None:
            self._health_monitor = health_monitor
        else:
            self._health_monitor = HealthMonitor(registry=default_registry)
            self._health_monitor.register_gauges(default_registry)
        # Durability: with a wal_dir every peer's engine is wrapped in a
        # DurableEngine logging each incoming wire message BEFORE its ack
        # frame is sent (the response is only written after the handler —
        # and therefore the WAL append — returns). Peer logs are keyed by
        # signer identity, which is stable across restarts for key-carrying
        # ADD_PEER calls, so re-adding the same key replays the peer's log.
        self._wal_dir = wal_dir
        self._wal_fsync = wal_fsync
        # identity -> live DurableEngine for this run: one WalWriter per
        # directory, ever. Re-adding a key reuses the open engine instead
        # of opening a second writer on the same segment files (which
        # would interleave duplicate LSNs and corrupt watermark skipping
        # on the next restart). _durable_gates serializes same-identity
        # creation without holding the server-wide lock through recovery;
        # _recovery keeps each identity's ReplayStats for the embedder.
        self._durable: dict[bytes, object] = {}
        self._durable_gates: dict[bytes, threading.Lock] = {}
        self._recovery: dict[bytes, object] = {}
        self._peers: dict[int, _Peer] = {}
        self._next_peer = 1
        self._lock = threading.Lock()
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._connections: set[socket.socket] = set()
        self._handlers: set[threading.Thread] = set()
        self._running = False
        # Observability: /metrics + /healthz HTTP sidecar (metrics_port
        # 0 = ephemeral, None = no sidecar; the GET_METRICS opcode serves
        # the same text over the bridge wire regardless).
        self._metrics_port = metrics_port
        self._metrics_host = metrics_host
        self._sidecar: MetricsSidecar | None = None
        self._m_requests = default_registry.counter(BRIDGE_REQUESTS_TOTAL)
        self._m_errors = default_registry.counter(BRIDGE_ERRORS_TOTAL)
        self._m_retry_after = default_registry.counter(BRIDGE_RETRY_AFTER_TOTAL)
        # State sync: per-peer cached snapshot (manifest, file path),
        # rebuilt when the peer's WAL position (or the requested chunk
        # geometry) moves. ``_sync_lock`` guards only the cache dict and
        # the id counter; per-peer gates serialize builds so one peer's
        # multi-second snapshot capture never stalls another peer's
        # manifest or chunk traffic. Snapshot ids are unique PER BUILD
        # (never reused across rebuilds, even at an unchanged watermark),
        # so a client holding a stale manifest always gets
        # STATUS_SYNC_STALE rather than chunks from a different artifact.
        self._sync_cache: dict[int, tuple[object, str]] = {}
        self._sync_gates: dict[int, threading.Lock] = {}
        self._sync_lock = threading.Lock()
        self._sync_seq = 0
        self._m_sync_chunks = default_registry.counter(SYNC_CHUNKS_SENT_TOTAL)
        # Pipelined dispatch: one shared worker pool for every upgraded
        # connection (HELLO + FEATURE_PIPELINING). Read-only frames run
        # concurrently on it; mutating frames run through a per-connection
        # _SerialLane so a pipelined vote stream applies in receive order.
        # max_inflight_per_connection bounds dispatched-but-unanswered
        # frames per connection (the reader blocks past it).
        if pipeline_workers is None:
            pipeline_workers = min(8, (os.cpu_count() or 2) + 2)
        self._pipeline_workers = max(1, pipeline_workers)
        self._max_inflight = max(1, max_inflight_per_connection)
        # Overload admission for mutating frames on pipelined/shm
        # connections: past this serial-lane depth the server answers
        # STATUS_RETRY_AFTER (depth-derived backoff hint) instead of
        # queueing deeper. Defaults just under the inflight window so
        # shedding fires BEFORE the semaphore wedges the reader thread.
        self._admission_limit = max(
            1,
            ordered_admission_limit
            if ordered_admission_limit is not None
            else self._max_inflight * 3 // 4,
        )
        self._pipeline_pool: ThreadPoolExecutor | None = None
        # Zero-copy wire ingest: OP_VOTE_BATCH frames whose rows all parse
        # strict-canonical land as numpy columns on ingest_wire_columnar
        # (full validation, no per-vote Python objects); anything else —
        # and engines without the columnar entry point — takes the object
        # path, which stays the parity oracle. Default on; force off with
        # wire_columnar=False or HASHGRAPH_TPU_WIRE_COLUMNAR=0 (the CI
        # fallback leg runs the smoke that way).
        if wire_columnar is None:
            wire_columnar = os.environ.get(
                "HASHGRAPH_TPU_WIRE_COLUMNAR", "1"
            ) != "0"
        self._wire_columnar = bool(wire_columnar)
        self._m_wire_columnar = default_registry.counter(
            WIRE_COLUMNAR_FRAMES_TOTAL
        )
        self._m_wire_fallback = default_registry.counter(
            WIRE_FALLBACK_FRAMES_TOTAL
        )
        self._m_wire_decode_s = default_registry.counter(
            WIRE_DECODE_SECONDS_TOTAL
        )
        self._m_wire_crypto_s = default_registry.counter(
            WIRE_CRYPTO_SECONDS_TOTAL
        )
        self._m_wire_apply_s = default_registry.counter(
            WIRE_APPLY_SECONDS_TOTAL
        )
        self._m_shm_attached = default_registry.counter(
            SHM_RINGS_ATTACHED_TOTAL
        )
        # Apply reactor (cross-connection continuous batching): validated
        # columnar vote frames from ALL connections and lanes merge into
        # per-engine micro-windows, one fused device dispatch each —
        # amortizing the fixed XLA launch + readback cost the per-frame
        # dispatches pay. Off by default (construction-compatible escape
        # hatch); turn on with apply_reactor=True, an ApplyReactor
        # instance (custom windowing), or HASHGRAPH_TPU_APPLY_REACTOR=1.
        # start() runs its flusher thread; an embedded server leaves it
        # in manual mode (inline, deterministic flush per dispatch).
        if isinstance(apply_reactor, ApplyReactor):
            self._reactor: "ApplyReactor | None" = apply_reactor
        elif reactor_enabled(apply_reactor):
            self._reactor = ApplyReactor()
        else:
            self._reactor = None
        if self._reactor is not None and self._reactor._on_stage is None:
            self._reactor._on_stage = self._note_reactor_stage
        # Live shm ring pairs: (rx, tx) per serving thread, torn down on
        # stop() and when the owning TCP connection closes.
        self._shm_rings: "set[tuple[object, object]]" = set()

    # ── lifecycle ──────────────────────────────────────────────────────

    @property
    def address(self) -> tuple[str, int]:
        if self._listener is None:
            raise RuntimeError("server not started")
        return self._listener.getsockname()[:2]

    @property
    def metrics_address(self) -> tuple[str, int]:
        """(host, port) of the HTTP metrics sidecar (requires
        ``metrics_port`` and a started server)."""
        if self._sidecar is None:
            raise RuntimeError("metrics sidecar not running")
        return self._sidecar.address

    def _health(self) -> dict:
        """``/healthz`` body: liveness plus the consensus-health verdict.
        Every distinct health monitor behind the peer engines (one, when
        the default process-wide monitor is shared; several, when an
        engine_factory supplies private ones) is evaluated; firing
        CRITICAL rules — signed misbehavior like an equivocating peer —
        flip ``ok`` to false, which the sidecar serves as 503, with the
        machine-readable reasons alongside so the balancer's operator
        sees *why* without a second query. Warnings ride along in
        ``alerts`` without degrading."""
        with self._lock:
            peers = len(self._peers)
            # The server's own monitor always participates (it exists
            # before the first ADD_PEER); engine_factory-built engines
            # may carry different monitors — aggregate the distinct set.
            monitors = {id(self._health_monitor): self._health_monitor}
            for peer in self._peers.values():
                monitor = getattr(peer.engine, "health", None)
                if monitor is not None:
                    monitors[id(monitor)] = monitor
        alerts: list[dict] = []
        for monitor in monitors.values():
            try:
                alerts.extend(monitor.evaluate_alerts())
            except Exception:
                # A broken rule must degrade the report, not the scrape.
                continue
        reasons = [
            {
                "rule": alert["rule"],
                "severity": alert["severity"],
                "description": alert.get("description", ""),
                "details": alert.get("details", []),
            }
            for alert in alerts
            if alert.get("severity") == "critical"
        ]
        out = {
            "ok": self._running and not reasons,
            "peers": peers,
            "alerts": alerts,
        }
        if reasons:
            out["reasons"] = reasons
        return out

    def start_embedded(self) -> None:
        """Serve frames in-process through :meth:`dispatch_frame` without
        binding a listener or starting any thread. Same dispatch table,
        same per-peer engines, same WAL/recovery machinery as the TCP
        front-end — this is the deterministic cluster simulator's mode
        (:mod:`hashgraph_tpu.sim`): every byte still crosses the wire
        codec and the live validation paths, but scheduling is entirely
        the caller's, so a run can be a pure function of its seed.
        ``stop()`` quiesces an embedded server exactly as a started one
        (durable peer WALs flushed and closed, peers evicted)."""
        if self._running:
            raise RuntimeError("server already started")
        self._running = True

    def dispatch_frame(self, opcode: int, payload: bytes = b"") -> tuple[int, bytes]:
        """Dispatch ONE decoded frame (opcode + payload bytes) through
        the live handler table and return ``(status, response payload)``
        — the socketless request/response unit the embedded mode serves.
        The wire's error contract applies (ConsensusError -> status code,
        malformed payloads -> STATUS_BAD_REQUEST), identical to what a
        TCP client would read back."""
        if not self._running:
            raise RuntimeError("server not started")
        self._m_requests.inc()
        flight_recorder.record("bridge.op", opcode=opcode)
        status, out = self._safe_dispatch(opcode, P.Cursor(payload))
        if status >= P.STATUS_UNKNOWN_PEER:
            self._m_errors.inc()
        return status, out

    def start(self) -> tuple[str, int]:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self._host, self._port))
        listener.listen(16)
        self._listener = listener
        self._running = True
        if self._metrics_port is not None:
            try:
                self._sidecar = MetricsSidecar(
                    default_registry,
                    host=self._metrics_host,
                    port=self._metrics_port,
                    health_fn=self._health,
                )
                self._sidecar.start()
            except Exception:
                # A sidecar bind failure (port in use) must not leave a
                # half-started server holding the bridge listener: in the
                # `with BridgeServer(...)` pattern a raising __enter__
                # never reaches __exit__/stop().
                self._sidecar = None
                self._running = False
                self._listener = None
                try:
                    listener.close()
                except OSError:
                    pass
                raise
        self._pipeline_pool = ThreadPoolExecutor(
            max_workers=self._pipeline_workers,
            thread_name_prefix="bridge-pipeline",
        )
        if self._reactor is not None:
            self._reactor.start()
        # Always-on stack sampling, $HASHGRAPH_TPU_PROFILE=1 opt-in (the
        # reactor's env-gate pattern): every serving process gets the
        # continuous-profiling loop without per-embedder wiring. The
        # process-wide instance is idempotent across servers.
        maybe_start_profiler()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()
        return self.address

    def stop(self) -> None:
        """Quiesce the bridge: no new connections, live connections closed.
        After stop() returns no further frames mutate the peer engines."""
        self._running = False
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            connections = list(self._connections)
        for conn in connections:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
        self._teardown_shm(None)
        # Join in-flight handlers: a dispatch that was already running keeps
        # the engine lock until it finishes; only after this loop is the
        # "no further frames mutate the peer engines" guarantee true.
        with self._lock:
            handlers = list(self._handlers)
        for thread in handlers:
            thread.join(timeout=5)
        # Pipelined frames that were already dispatched finish on the pool
        # before the engines are considered quiesced (their responses go
        # to closed sockets, which is fine — sendall just fails).
        if self._pipeline_pool is not None:
            self._pipeline_pool.shutdown(wait=True)
            self._pipeline_pool = None
        # Reactor drains AFTER the lanes (no new enqueues) and BEFORE the
        # durable engines close: every queued window either applies or
        # finishes its handles with the shutdown error — nothing mutates
        # a closed WAL, and no waiter is stranded.
        if self._reactor is not None:
            self._reactor.stop()
        # Flush + close the per-identity WALs, then evict those engines and
        # the peers built on them: a closed WalWriter can never append
        # again, so a restarted server must rebuild each durable engine
        # (re-recovering from its log on the next ADD_PEER) rather than
        # hand out the closed one. Undecorated engines hold no file
        # handles; their peers survive a stop()/start() cycle unchanged.
        with self._lock:
            durable = list(self._durable.values())
            self._durable.clear()
            # Stats and gates die with the engines they described: a stale
            # ReplayStats surviving into the next start() would report a
            # previous incarnation's recovery as the current one's.
            self._recovery.clear()
            self._durable_gates.clear()
            closed = {id(engine) for engine in durable}
            for peer_id in [
                pid for pid, p in self._peers.items() if id(p.engine) in closed
            ]:
                del self._peers[peer_id]
        for engine in durable:
            engine.close()
        # Served snapshots die with the server: the files live under the
        # peers' WAL directories and would otherwise accumulate one stale
        # artifact per incarnation.
        with self._sync_lock:
            sync_paths = [path for _, path in self._sync_cache.values()]
            self._sync_cache.clear()
            self._sync_gates.clear()
        for path in sync_paths:
            try:
                os.remove(path)
            except OSError:
                pass
        if self._sidecar is not None:
            self._sidecar.stop()
            self._sidecar = None

    def __enter__(self) -> "BridgeServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # ── connection handling ────────────────────────────────────────────

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(
                target=self._serve_connection,
                args=(conn,),
                # Named so the continuous profiler's role table can
                # attribute reader-thread samples (obs.profiler).
                name="bridge-reader",
                daemon=True,
            ).start()

    def _serve_connection(self, conn: socket.socket) -> None:
        with self._lock:
            self._connections.add(conn)
            self._handlers.add(threading.current_thread())
        try:
            self._serve_frames(conn)
        finally:
            self._teardown_shm(conn)
            with self._lock:
                self._connections.discard(conn)
                self._handlers.discard(threading.current_thread())
            try:
                conn.close()
            except OSError:
                pass

    def _serve_frames(self, conn: socket.socket) -> None:
        P.tune_socket(conn)  # TCP_NODELAY: small-frame request wire
        state: _ConnState | None = None  # non-None once pipelining upgraded
        while self._running:
            try:
                if state is None:
                    opcode, cursor = P.read_frame(conn)
                    corr = 0
                else:
                    opcode, corr, cursor = P.read_tagged_frame(conn)
            except (ConnectionError, OSError):
                return
            except ValueError:
                try:
                    conn.sendall(P.encode_frame(P.STATUS_BAD_REQUEST))
                except OSError:
                    pass
                return
            if not self._running:
                return
            self._m_requests.inc()
            flight_recorder.record("bridge.op", opcode=opcode)
            if opcode == P.OP_HELLO:
                granted = self._handle_hello(conn, cursor, state, corr)
                if granted is None:
                    return  # write failed; connection is dead
                if state is None and granted & P.FEATURE_PIPELINING:
                    pool = self._pipeline_pool
                    if pool is not None:
                        state = _ConnState(pool, self._max_inflight)
                continue
            if state is not None and opcode == P.OP_SHM_ATTACH:
                if not self._handle_shm_attach(conn, state, corr, cursor):
                    return  # write failed; connection is dead
                continue
            if state is None:
                status, payload = self._safe_dispatch(opcode, cursor)
                if status >= P.STATUS_UNKNOWN_PEER:
                    self._m_errors.inc()
                try:
                    conn.sendall(P.encode_frame(status, payload))
                except OSError:
                    return
            else:
                self._dispatch_pipelined(conn, state, opcode, corr, cursor)

    def _handle_hello(
        self, conn, cursor: P.Cursor, state: "_ConnState | None", corr: int
    ) -> int | None:
        """Negotiate features; answer in the connection's CURRENT framing
        (the mode only switches after the grant is on the wire). Returns
        the granted bits, or None when the response write failed."""
        try:
            cursor.u32()  # client protocol version (1; reserved)
            offered = cursor.u32()
        except ValueError:
            offered = 0
        granted = offered & P.SUPPORTED_FEATURES
        if self._pipeline_pool is None:
            granted &= ~P.FEATURE_PIPELINING  # not started / stopping
        payload = P.u32(P.PROTOCOL_VERSION) + P.u32(granted)
        try:
            if state is None:
                conn.sendall(P.encode_frame(P.STATUS_OK, payload))
            else:
                # Re-HELLO on an upgraded connection: answer tagged; the
                # connection stays pipelined (no downgrade path).
                with state.write_lock:
                    conn.sendall(
                        P.encode_tagged_frame(P.STATUS_OK, corr, payload)
                    )
        except OSError:
            return None
        return granted

    def _handle_shm_attach(
        self, conn, state: _ConnState, corr: int, cursor: P.Cursor
    ) -> bool:
        """Map the client's ring pair and serve tagged frames from it on
        a dedicated thread (``OP_SHM_ATTACH``; pipelined connections
        only). Any failure answers a typed error — the client keeps the
        TCP lane and simply never upgrades. Returns False only when the
        response write failed (connection dead)."""
        status, message = P.STATUS_OK, b""
        rings = None
        rx = None
        try:
            cursor.u32()  # ring_bytes (informative)
            c2s = cursor.string()
            s2c = cursor.string()
            from ..gossip.shm import ShmRing, shm_available

            if not shm_available():
                raise ValueError("shared memory unavailable on this host")
            rx = ShmRing.attach(c2s)
            tx = ShmRing.attach(s2c)
            rings = (rx, tx)
        except (ValueError, OSError) as exc:
            if rx is not None:  # c2s attached but s2c failed: unmap it
                rx.close()
            status, message = P.STATUS_BAD_REQUEST, P.string(str(exc))
        try:
            with state.write_lock:
                conn.sendall(P.encode_tagged_frame(status, corr, message))
        except OSError:
            if rings is not None:
                for ring in rings:
                    ring.close()
            return False
        if rings is None:
            return True
        thread = threading.Thread(
            target=self._serve_shm_ring,
            args=(conn, state, rings[0], rings[1]),
            daemon=True,
            name="bridge-shm",
        )
        with self._lock:
            self._shm_rings.add((conn, state, rings[0], rings[1], thread))
        self._m_shm_attached.inc()
        flight_recorder.record("bridge.shm_attach", c2s=c2s, s2c=s2c)
        thread.start()
        return True

    def _serve_shm_ring(self, conn, state: _ConnState, rx, tx) -> None:
        """Reader loop for one attached ring pair: the byte stream is
        the same tagged frame stream TCP carries, parsed incrementally
        and dispatched through the connection's pipelining state (same
        serial lane — vote order is preserved across lanes per opcode
        stream; the client routes each request to exactly one lane).
        Responses go back through the tx ring."""
        from ..gossip.shm import ShmSpin

        spin = ShmSpin()
        tx_lock = threading.Lock()
        buf = bytearray()
        while self._running and state.shm_running:
            try:
                chunk = rx.read_available()
            except (OSError, ValueError):
                return  # ring closed under us (teardown)
            if chunk is None:
                spin.wait()
                continue
            spin.hit()
            buf += chunk
            try:
                frames = P.split_frames(buf, min_len=5)
            except ValueError:
                # Stream integrity gone: the ring can never recover its
                # framing, so kill the WHOLE connection — the TCP reader
                # unblocks, its cleanup tears the rings down, and the
                # client sees a typed connection loss (then falls back /
                # reconnects). Stopping just this reader would leave the
                # client writing into a ring nobody drains.
                flight_recorder.record("bridge.shm_bad_frame")
                state.shm_running = False
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return
            for body in frames:
                self._dispatch_shm_frame(body, conn, state, tx, tx_lock)

    def _dispatch_shm_frame(
        self, body: bytes, conn, state: _ConnState, tx, tx_lock
    ) -> None:
        opcode, corr, cursor = P.parse_frame(body, tagged=True)
        self._m_requests.inc()
        flight_recorder.record("bridge.op", opcode=opcode)
        if self._shed_retry_after(conn, state, opcode, corr):
            return
        state.inflight.acquire()
        prep = self._try_vote_batch_prepare(opcode, cursor)

        def send(status: int, payload: bytes) -> None:
            frame = P.encode_tagged_frame(status, corr, payload)
            if len(frame) > tx.capacity:
                # The ring can NEVER carry this response: answer on
                # the TCP control lane instead (the client matches
                # responses by corr id across lanes). Spinning on
                # try_write would hold tx_lock forever and wedge
                # every later response on the connection.
                try:
                    with state.write_lock:
                        conn.sendall(frame)
                except OSError:
                    pass  # connection died; nothing to answer to
                return
            with tx_lock:
                # Response ring full: the client is the sole drainer
                # and responses are small — wait briefly rather than
                # drop a response (a lost response hangs a future).
                try:
                    while not tx.try_write([frame], len(frame)):
                        if not (self._running and state.shm_running):
                            return
                        time.sleep(0.0005)
                except ValueError:
                    return  # ring closed under us (teardown race)

        if self._reactor_eligible(opcode, prep):
            state.ordered.submit(
                lambda: self._vote_batch_enqueue(prep, state, send)
            )
            return

        def run() -> None:
            try:
                status, payload = self._safe_dispatch(opcode, cursor, prep)
                if status >= P.STATUS_UNKNOWN_PEER:
                    self._m_errors.inc()
                send(status, payload)
            finally:
                state.inflight.release()

        if opcode in _ORDERED_OPCODES:
            state.ordered.submit(self._barriered(state, run))
        else:
            pool = self._pipeline_pool
            if pool is None:
                run()
                return
            try:
                pool.submit(run)
            except RuntimeError:
                run()

    def _teardown_shm(self, conn) -> None:
        """Stop and unmap every ring pair attached to ``conn`` (or all
        of them when ``conn`` is None — server stop)."""
        with self._lock:
            mine = [
                entry for entry in self._shm_rings
                if conn is None or entry[0] is conn
            ]
            self._shm_rings.difference_update(mine)
        for _conn, state, rx, tx, thread in mine:
            state.shm_running = False
            thread.join(timeout=2)
            rx.close()
            tx.close()

    def _safe_dispatch(
        self, opcode: int, cursor: P.Cursor, vote_prep=None
    ) -> tuple[int, bytes]:
        """_dispatch with the wire's error contract applied (one home for
        the serial loop and the pipelined workers)."""
        try:
            return self._dispatch(opcode, cursor, vote_prep)
        except Exception as exc:
            return self._map_dispatch_error(opcode, exc)

    def _map_dispatch_error(self, opcode: int, exc: Exception) -> tuple[int, bytes]:
        """The wire's error contract as a value mapping: also applied to
        engine failures surfacing from a reactor dispatch, whose response
        is written by a completion callback instead of _safe_dispatch."""
        if isinstance(exc, ConsensusError):
            return int(exc.code), P.string(str(exc))
        if isinstance(exc, ShardRecoveringError):
            # A federation host's shard frozen mid-migration (or mid-
            # recovery): typed retry-after on the wire instead of an
            # internal error — the sender backs off and replays, so a
            # migration window never drops votes.
            retry = getattr(exc, "retry_after", 1.0)
            return P.STATUS_SHARD_MIGRATING, P.string(f"{retry}")
        if isinstance(exc, (ValueError, KeyError, struct_error)):
            flight_recorder.record(
                "bridge.bad_request", opcode=opcode, error=str(exc)
            )
            return P.STATUS_BAD_REQUEST, P.string(str(exc))
        # Dispatch blew up unexpectedly (a peer engine died, a bug):
        # preserve the ring for the postmortem before answering.
        flight_recorder.record(
            "bridge.dispatch_error", opcode=opcode, error=repr(exc)
        )
        flight_recorder.dump("bridge-dispatch-error")
        return P.STATUS_INTERNAL, P.string(repr(exc))

    def _shed_retry_after(
        self, conn, state: _ConnState, opcode: int, corr: int
    ) -> bool:
        """Overload admission for one mutating frame: when the
        connection's serial lane is at the admission limit, answer
        STATUS_RETRY_AFTER (backoff hint in seconds, scaled to the depth
        the sender would be queueing behind) and drop the frame —
        nothing is applied, so the sender defers the scopes to
        anti-entropy instead of stacking work the lane cannot reach.
        The answer rides the TCP control lane even for shm frames
        (clients match responses by corr id across lanes). Returns True
        when the frame was shed.

        With the apply reactor on, frames the lane already handed to a
        window are *queued work the sender is stacking up* even though
        the lane itself is empty — they (and their rows) count toward
        the depth signal, so a full window cannot silently bypass
        admission control."""
        if opcode not in _ORDERED_OPCODES:
            return False
        depth = state.ordered.depth()
        reactor_rows = 0
        if self._reactor is not None:
            with state.reactor_lock:
                depth += state.reactor_frames
                reactor_rows = state.reactor_rows
        if depth < self._admission_limit:
            return False
        self._m_retry_after.inc()
        flight_recorder.record(
            "bridge.retry_after", opcode=opcode, depth=depth
        )
        # ~1ms of lane work per queued frame is the drain-time model
        # (queued reactor rows drain vectorized — ~64 rows per frame-
        # equivalent); bounded so a backlog never hints minutes.
        retry = min(1.0, depth / 1000.0 + reactor_rows / 64000.0)
        try:
            with state.write_lock:
                conn.sendall(
                    P.encode_tagged_frame(
                        P.STATUS_RETRY_AFTER, corr, P.string(f"{retry}")
                    )
                )
        except OSError:
            pass  # connection died; nothing to answer to
        return True

    def _try_vote_batch_prepare(self, opcode: int, cursor: P.Cursor):
        """3-stage wire pipeline, stage 1: vote-batch frames parse AND
        submit their crypto on the calling (reader) thread — GIL-free
        native parse, async verify-pool submit — so by the time the
        serial lane reaches the frame, its signatures are already
        verified or in flight while the previous frame's device apply
        runs. Returns the prepass, ``_PREP_FALLBACK`` when the parse
        chose the object path (a non-canonical row), or ``None`` when
        the lane should re-decode from scratch (not a vote batch /
        columnar off / parse raised — the lane answers the exact wire
        error). One home for both the TCP and shm reader threads."""
        if opcode != P.OP_VOTE_BATCH or not self._wire_columnar:
            return None
        try:
            return self._vote_batch_prepare(cursor.fork()) or _PREP_FALLBACK
        except Exception:
            return None  # lane re-decodes and answers the exact error

    # ── Apply reactor (cross-connection continuous batching) ───────────

    @property
    def reactor(self) -> "ApplyReactor | None":
        """The server's apply reactor, or None when disabled."""
        return self._reactor

    def _note_reactor_stage(self, stage: dict) -> None:
        """Stage-attribution hook a reactor dispatch reports through —
        the same wire crypto/apply counters the reactor-off path feeds,
        so GET_METRICS attribution stays comparable either way."""
        crypto = stage.get("crypto", 0.0)
        if crypto:
            self._m_wire_crypto_s.inc(crypto)
        apply_s = stage.get("apply", 0.0)
        if apply_s:
            self._m_wire_apply_s.inc(apply_s)

    def _reactor_eligible(self, opcode: int, prep) -> bool:
        """True when a pipelined/shm frame takes the asynchronous
        reactor path: a columnar-prepared OP_VOTE_BATCH on a server with
        the reactor on. Everything else keeps today's lane semantics."""
        return (
            self._reactor is not None
            and opcode == P.OP_VOTE_BATCH
            and prep is not None
            and prep is not _PREP_FALLBACK
        )

    def _barriered(self, state: _ConnState, run):
        """Wrap a serial-lane job so it waits for the connection's
        pending reactor windows first. With the reactor on, a lane job
        that mutates engine state directly (ADD_PEER, object-path vote
        frames, POLL_EVENTS, ...) must not run ahead of vote frames the
        lane already handed to a window — receive order is the
        contract. No-op (and no wrapper) with the reactor off."""
        if self._reactor is None:
            return run

        def job() -> None:
            self._reactor_barrier(state)
            run()

        return job

    def _reactor_barrier(self, state: _ConnState) -> None:
        """Flush and wait out every reactor window holding this
        connection's enqueued frames (serial lane only, so the deque
        holds exactly the frames received before the barrier)."""
        if self._reactor is None:
            return
        with state.reactor_lock:
            if not state.reactor_handles:
                return
            handles = list(state.reactor_handles)
            state.reactor_handles.clear()
        self._reactor.flush()
        for handle in handles:
            try:
                handle.wait(30.0)
            except Exception:
                pass  # the frame's own response carries its error

    def _vote_batch_enqueue(self, prep, state: _ConnState, send) -> None:
        """Serial-lane half of the reactor path for ONE pipelined/shm
        OP_VOTE_BATCH frame: re-resolve peers in receive order, enqueue
        each columnar entry into its engine's open window, and RETURN —
        the lane moves on while windows accumulate frames from every
        connection. The last entry's completion callback assembles the
        per-row statuses and writes the response; unknown peers and
        object-path engines resolve inline exactly as the reactor-off
        apply does."""
        reactor = self._reactor
        view = prep.view
        statuses = bytearray(view.total)
        out = np.frombuffer(statuses, np.uint8)
        pending: list = []
        try:
            for entry in prep.per_peer:
                rows = entry["rows"]
                peer = self._peers.get(entry["peer_id"])
                if peer is None:
                    out[rows] = P.STATUS_UNKNOWN_PEER
                    continue
                engine = peer.engine
                if not hasattr(engine, "ingest_wire_columnar"):
                    self._apply_rows_objects(engine, entry, view, out)
                    continue
                prepass = (
                    entry["prepass"] if engine is entry["engine"] else None
                )
                pending.append((engine, entry, prepass))
        except Exception as exc:
            status, payload = self._map_dispatch_error(P.OP_VOTE_BATCH, exc)
            self._m_errors.inc()
            send(status, payload)
            state.inflight.release()
            return
        if not pending:
            self._m_wire_columnar.inc()
            send(P.STATUS_OK, P.u32(view.total) + bytes(statuses))
            state.inflight.release()
            return
        join = {"left": len(pending), "error": None}
        join_lock = threading.Lock()
        frame_rows = int(view.total)
        with state.reactor_lock:
            state.reactor_frames += 1
            state.reactor_rows += frame_rows

        def finish(handle, rows) -> None:
            error = handle.error
            if error is None:
                out[rows] = (
                    np.asarray(handle.codes, np.int64) & 0xFF
                ).astype(np.uint8)
            with join_lock:
                if error is not None and join["error"] is None:
                    join["error"] = error
                join["left"] -= 1
                if join["left"]:
                    return
            with state.reactor_lock:
                state.reactor_frames -= 1
                state.reactor_rows -= frame_rows
            error = join["error"]
            if error is None:
                self._m_wire_columnar.inc()
                send(P.STATUS_OK, P.u32(view.total) + bytes(statuses))
            else:
                status, payload = self._map_dispatch_error(
                    P.OP_VOTE_BATCH, error
                )
                self._m_errors.inc()
                send(status, payload)
            state.inflight.release()

        for engine, entry, prepass in pending:
            handle = reactor.submit(
                engine,
                entry["scopes"],
                entry["sidx"],
                entry["cols"],
                entry["data"],
                entry["offsets"],
                view.now,
                prepass=prepass,
                on_done=(lambda h, r=entry["rows"]: finish(h, r)),
            )
            with state.reactor_lock:
                # The deque is the barrier other mutating opcodes wait
                # on; prune settled handles so a vote-only connection
                # never accumulates them unboundedly.
                while (
                    state.reactor_handles and state.reactor_handles[0].done
                ):
                    state.reactor_handles.popleft()
                state.reactor_handles.append(handle)

    def _dispatch_pipelined(
        self,
        conn: socket.socket,
        state: _ConnState,
        opcode: int,
        corr: int,
        cursor: P.Cursor,
    ) -> None:
        """Hand one tagged frame to the worker pool and return to the
        read loop. Mutating opcodes run on the connection's serial lane
        (receive order); read-only opcodes run concurrently, so their
        responses can overtake — the client matches by correlation id."""
        if self._shed_retry_after(conn, state, opcode, corr):
            return
        state.inflight.acquire()  # reader blocks when the window is full
        prep = self._try_vote_batch_prepare(opcode, cursor)

        def send(status: int, payload: bytes) -> None:
            try:
                with state.write_lock:
                    conn.sendall(
                        P.encode_tagged_frame(status, corr, payload)
                    )
            except OSError:
                pass  # connection died; nothing to answer to

        if self._reactor_eligible(opcode, prep):
            # Reactor path: the lane job only ENQUEUES the frame's
            # entries into their engines' open windows and returns — the
            # lane drains ahead while validated work from many
            # connections merges into one fused dispatch. The completion
            # callback writes the response and releases the inflight
            # permit.
            state.ordered.submit(
                lambda: self._vote_batch_enqueue(prep, state, send)
            )
            return

        def run() -> None:
            try:
                status, payload = self._safe_dispatch(opcode, cursor, prep)
                if status >= P.STATUS_UNKNOWN_PEER:
                    self._m_errors.inc()
                send(status, payload)
            finally:
                state.inflight.release()

        if opcode in _ORDERED_OPCODES:
            state.ordered.submit(self._barriered(state, run))
        else:
            pool = self._pipeline_pool
            if pool is None:
                run()
                return
            try:
                pool.submit(run)
            except RuntimeError:
                run()  # pool shut down mid-flight: answer inline

    # ── dispatch ───────────────────────────────────────────────────────

    def _dispatch(
        self, opcode: int, c: P.Cursor, vote_prep=None
    ) -> tuple[int, bytes]:
        if opcode == P.OP_PING:
            return P.STATUS_OK, P.u32(P.PROTOCOL_VERSION)
        if opcode == P.OP_ADD_PEER:
            return self._op_add_peer(c)
        if opcode == P.OP_GET_METRICS:
            # Server-wide (no peer_id): the registry is process-global, so
            # one scrape covers every peer engine plus WAL and bridge.
            return P.STATUS_OK, P.blob(
                default_registry.render_prometheus().encode("utf-8")
            )
        if opcode == P.OP_METRICS_PULL:
            # Server-wide raw metric federation frame: the mergeable
            # registry state + SLO state under this host's label — what a
            # federation driver sums (parallel.rollup.merge_metric_states)
            # into one fleet /metrics + /slo view.
            label = self.host_label
            if label is None:
                try:
                    label = "%s:%d" % (self._host, self.address[1])
                except Exception:
                    label = self._host
            payload = {
                "host": label,
                "state": default_registry.export_state(),
                "slo": default_slo_engine.state(),
            }
            return P.STATUS_OK, P.blob(json.dumps(payload).encode("utf-8"))
        if opcode == P.OP_PROFILE:
            # Server-wide attribution readout (stage busy shares +
            # sampled stacks), host-labelled like OP_METRICS_PULL so
            # merge_profile_states can federate frames across hosts.
            from ..obs.attribution import attribution_report

            label = self.host_label
            if label is None:
                try:
                    label = "%s:%d" % (self._host, self.address[1])
                except Exception:
                    label = self._host
            payload = {"host": label, "profile": attribution_report()}
            return P.STATUS_OK, P.blob(json.dumps(payload).encode("utf-8"))
        if opcode == P.OP_VOTE_BATCH:
            # Multi-peer frame: groups carry their own peer ids.
            return self._op_vote_batch(c, vote_prep)
        handler = _HANDLERS.get(opcode)
        if handler is None:
            return P.STATUS_UNKNOWN_OPCODE, b""
        peer = self._peers.get(c.u32())
        if peer is None:
            return P.STATUS_UNKNOWN_PEER, b""
        return handler(self, peer, c)

    def _op_add_peer(self, c: P.Cursor) -> tuple[int, bytes]:
        keylen = c.u8()
        if keylen == 0:
            signer: ConsensusSignatureScheme = self._signer_factory.random()
        elif keylen == 32:
            signer = self._signer_factory(c.raw(32))
        else:
            return P.STATUS_BAD_REQUEST, P.string("key must be absent or 32 bytes")
        identity = signer.identity()
        # Durability only for key-carrying peers: a keyless ADD_PEER mints a
        # random signer whose identity can never be presented again, so its
        # WAL could never be replayed — wrapping it would only accumulate
        # one dead per-identity directory (plus fsync cost) per ephemeral
        # peer. Keyless peers run undurable by construction.
        if self._wal_dir is not None and keylen == 32:
            engine = self._durable_engine(signer, identity)
        else:
            engine = self._build_engine(signer)
        receiver = engine.event_bus().subscribe()
        with self._lock:
            # stop()'s sweep only evicts peers it can SEE: a registration
            # that lands after the sweep would pin a closed durable engine
            # into the next start(). Refuse instead — the engine itself is
            # either undurable (no handles) or still published in _durable,
            # where the sweep closes it.
            if not self._running:
                raise ValueError("server is stopping")
            peer_id = self._next_peer
            self._next_peer += 1
            self._peers[peer_id] = _Peer(peer_id, engine, receiver)
        return P.STATUS_OK, P.u32(peer_id) + P.u8(len(identity)) + identity

    def _build_engine(self, signer):
        if self._engine_factory is not None:
            return self._engine_factory(signer)
        return TpuConsensusEngine(
            signer,
            event_bus=BroadcastEventBus(),
            capacity=self._capacity,
            voter_capacity=self._voter_capacity,
            verify_cache=self._verify_cache,
            health_monitor=self._health_monitor,
        )

    def _durable_engine(self, signer, identity: bytes):
        """Create-or-reuse the durable engine for ``identity``. A
        per-identity gate serializes concurrent ADD_PEERs with the same key
        (two WalWriters on one directory would interleave duplicate LSNs)
        while keeping WAL replay — potentially seconds for a large log —
        off the server-wide lock, so other connections and ADD_PEERs
        proceed during one peer's recovery."""
        import os

        from ..wal import DurableEngine

        with self._lock:
            gate = self._durable_gates.setdefault(identity, threading.Lock())
        with gate:
            with self._lock:
                # Same guard as the publish below: once stop() begins, its
                # sweep owns every published durable engine (and closes
                # it); handing one out here would let a racing ADD_PEER
                # register a peer on an engine that is about to close.
                if not self._running:
                    raise ValueError("server is stopping")
                engine = self._durable.get(identity)
            if engine is not None:
                return engine
            engine = DurableEngine(
                self._build_engine(signer),
                os.path.join(self._wal_dir, "peer-" + identity.hex()),
                fsync_policy=self._wal_fsync,
            )
            # Crash recovery before the peer serves traffic: replay any
            # surviving log from a previous run of this identity. The event
            # subscription happens after, so replayed transitions don't
            # re-surface through OP_POLL_EVENTS. The stats are retained
            # (see recovery_stats) because nonzero segments_dropped /
            # errors means acknowledged records could not be replayed —
            # the embedder should be told, not served silently partial
            # state; replay() itself emits the wal.recover.* counters.
            stats = engine.recover()
            with self._lock:
                # A handler that outlived stop()'s join (recovery of a big
                # log can exceed the 5s timeout) must not publish after the
                # shutdown sweep already cleared _durable — the engine
                # would leak an open WalWriter (flock held until process
                # exit) and its peer could still mutate state after stop()
                # returned. Close and refuse instead.
                if not self._running:
                    engine.close()
                    raise ValueError("server is stopping")
                self._recovery[identity] = stats
                self._durable[identity] = engine
            return engine

    def durable_engine(self, identity: bytes):
        """The live :class:`~hashgraph_tpu.wal.DurableEngine` backing
        ``identity``'s peer (None = identity unknown or not durable).
        Embedders use it for checkpoint scheduling and state-sync
        bookkeeping; tests use it to reach the source engine behind a
        bridged peer."""
        with self._lock:
            return self._durable.get(identity)

    def peer_engine(self, peer_id: int):
        """The engine serving ``peer_id`` (None = unknown peer). Benches
        and fabric smoke tests use it to fingerprint a bridged peer's
        state without going through a durable identity."""
        with self._lock:
            peer = self._peers.get(peer_id)
            return None if peer is None else peer.engine

    def remove_peer(self, peer_id: int) -> None:
        """Unregister a peer WITHOUT closing its engine (the caller owns
        it — the federation's migration source registers a shard engine
        as a temporary sync peer and retires it after the placement
        flip). In-flight requests racing the removal answer
        STATUS_UNKNOWN_PEER, the same as any never-registered id; the
        peer's cached snapshot artifacts (if any) are dropped."""
        with self._lock:
            if self._peers.pop(peer_id, None) is None:
                raise ValueError(f"unknown peer {peer_id}")
        with self._sync_lock:
            cached = self._sync_cache.pop(peer_id, None)
            self._sync_gates.pop(peer_id, None)
        if cached is not None:
            try:
                os.remove(cached[1])
            except OSError:
                pass

    def recovery_stats(self, identity: bytes):
        """:class:`~hashgraph_tpu.wal.ReplayStats` from the WAL recovery
        that backed ``identity``'s engine (None = identity unknown or not
        durable). Nonzero ``segments_dropped`` or ``errors`` means mid-log
        corruption: acknowledged records exist that replay could not
        reproduce."""
        with self._lock:
            return self._recovery.get(identity)

    def _op_create_proposal(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        scope = c.string()
        now = c.u64()
        name = c.string()
        payload = c.blob()
        expected_voters = c.u32()
        rel_expiration = c.u64()
        liveness = bool(c.u8())
        ctx = P.read_trace_context(c)
        request = CreateProposalRequest(
            name=name,
            payload=payload,
            proposal_owner=peer.engine.signer().identity(),
            expected_voters_count=expected_voters,
            expiration_timestamp=rel_expiration,
            liveness_criteria_yes=liveness,
        )
        with _traced("bridge.create_proposal", ctx, peer.peer_id):
            proposal = peer.engine.create_proposal(scope, request, now)
        # Response suffix: the trace the engine bound (root, or child of
        # the request's ctx) — the embedder ferries it with the gossip.
        bound = peer.engine.trace_context_of(scope, proposal.proposal_id)
        return P.STATUS_OK, (
            P.u32(proposal.proposal_id)
            + P.blob(proposal.encode())
            + P.encode_trace_context(bound)
        )

    def _op_cast_vote(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        scope = c.string()
        pid = c.u32()
        choice = bool(c.u8())
        now = c.u64()
        ctx = P.read_trace_context(c)
        with _traced("bridge.cast_vote", ctx, peer.peer_id):
            vote = peer.engine.cast_vote(scope, pid, choice, now)
        bound = peer.engine.trace_context_of(scope, pid)
        return P.STATUS_OK, P.blob(vote.encode()) + P.encode_trace_context(bound)

    def _op_process_proposal(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        scope = c.string()
        now = c.u64()
        proposal = Proposal.decode(c.blob())
        ctx = P.read_trace_context(c)
        with _traced("bridge.process_proposal", ctx, peer.peer_id):
            peer.engine.process_incoming_proposal(scope, proposal, now)
        return P.STATUS_OK, b""

    def _op_process_vote(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        scope = c.string()
        now = c.u64()
        vote = Vote.decode(c.blob())
        ctx = P.read_trace_context(c)
        with _traced("bridge.process_vote", ctx, peer.peer_id):
            peer.engine.process_incoming_vote(scope, vote, now)
        return P.STATUS_OK, b""

    def _op_process_votes(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        """Batch vote delivery: one frame, one engine dispatch, one status
        byte per vote (StatusCode values; OK/ALREADY_REACHED are successes;
        STATUS_BAD_REQUEST marks an undecodable blob without poisoning the
        rest of the batch). This is the embedder's throughput path — the
        scalar opcode costs one round trip per vote."""
        scope = c.string()
        now = c.u64()
        count = c.u32()
        statuses = [P.STATUS_BAD_REQUEST] * count
        decodable: list[tuple[int, Vote]] = []
        for i in range(count):
            blob = c.blob()
            try:
                decodable.append((i, Vote.decode(blob)))
            except (ValueError, IndexError):
                pass  # per-vote 241 already set; the batch proceeds
        ctx = P.read_trace_context(c)
        if decodable:
            with _traced("bridge.process_votes", ctx, peer.peer_id):
                engine_statuses = peer.engine.ingest_votes(
                    [(scope, vote) for _, vote in decodable], now
                )
            for (i, _), status in zip(decodable, engine_statuses):
                statuses[i] = int(status) & 0xFF
        return P.STATUS_OK, P.u32(count) + bytes(statuses)

    # Stage size for a coalesced frame's pipelined ingest: big enough to
    # amortize the per-dispatch fixed cost, small enough that multi-stage
    # frames overlap crypto with apply.
    _PIPELINE_SPLIT = 256

    def _op_vote_batch(
        self, c: P.Cursor, prep: "_WireFramePrep | None" = None
    ) -> tuple[int, bytes]:
        """Coalesced columnar vote frame (``OP_VOTE_BATCH``), two paths:

        - **columnar fast path** (default): the frame decodes to numpy
          views (:func:`protocol.decode_vote_batch_views`), every vote
          row parses strict-canonical into columns
          (:mod:`bridge.columnar` — native, GIL-free when the runtime is
          present), and each peer's rows land on
          :meth:`TpuConsensusEngine.ingest_wire_columnar` — full
          validation, zero per-vote Python objects. A pipelined
          connection's reader thread hands in ``prep`` with the crypto
          already in flight (the 3-stage wire pipeline: transport read,
          verify-pool crypto, serial-lane device apply).
        - **object path** (fallback + parity oracle): any row that is
          malformed or non-canonical, or an engine without the columnar
          entry point, sends the WHOLE frame through the per-vote
          ``Vote.decode`` + ``ingest_votes_pipelined`` path — statuses
          are byte-identical by construction (fuzz-asserted in
          tests/test_wire_fuzz.py).

        Per-vote statuses return in flattened batch order; an
        undecodable blob marks its row 241 and an unknown peer_id marks
        its group's rows STATUS_UNKNOWN_PEER, neither poisoning the
        rest of the frame."""
        if self._wire_columnar:
            if prep is None:
                fallback = c.fork()
                prep = self._vote_batch_prepare(c)
                if prep is None:
                    c = fallback
            if prep is not None and prep is not _PREP_FALLBACK:
                return self._vote_batch_apply(prep)
            self._m_wire_fallback.inc()
        return self._op_vote_batch_objects(c)

    def _op_vote_batch_objects(self, c: P.Cursor) -> tuple[int, bytes]:
        """The object-path ``OP_VOTE_BATCH`` body: per-vote decode into
        ``Vote`` objects, one pipelined engine dispatch per peer
        (:meth:`TpuConsensusEngine.ingest_votes_pipelined` overlaps
        group k+1's signature prepass with group k's apply)."""
        now, groups = P.decode_vote_batch(c)
        total = sum(len(votes) for _, _, votes in groups)
        statuses = bytearray([P.STATUS_BAD_REQUEST]) * total
        # Per engine: ONE flattened batch across all of the peer's groups
        # (ingest_votes handles heterogeneous scopes in one dispatch, and
        # the fixed dispatch cost dominates small batches — merging is a
        # ~3x server-side win over per-group dispatches at 64-vote
        # groups), split into _PIPELINE_SPLIT-vote stages so big frames
        # still overlap stage k+1's signature prepass with stage k's
        # apply. Flattened-in-group-order ≡ per-group sequential calls
        # (ingest_votes applies items strictly in order), so coalescing
        # never reorders a chain. Row indices ride along so statuses land
        # back in flattened frame order.
        per_peer: dict[int, tuple[list[int], list[tuple[str, Vote]]]] = {}
        offset = 0
        for peer_id, scope, votes in groups:
            rows, batch = per_peer.setdefault(peer_id, ([], []))
            for j, blob in enumerate(votes):
                try:
                    batch.append((scope, Vote.decode(blob)))
                    rows.append(offset + j)
                except (ValueError, IndexError):
                    pass  # row already 241
            offset += len(votes)
        for peer_id, (rows, batch) in per_peer.items():
            peer = self._peers.get(peer_id)
            if peer is None:
                for row in rows:
                    statuses[row] = P.STATUS_UNKNOWN_PEER
                continue
            stages = [
                batch[i : i + self._PIPELINE_SPLIT]
                for i in range(0, len(batch), self._PIPELINE_SPLIT)
            ]
            results = peer.engine.ingest_votes_pipelined(stages, now)
            codes = [code for stage in results for code in stage]
            for row, code in zip(rows, codes):
                statuses[row] = int(code) & 0xFF
        return P.STATUS_OK, P.u32(total) + bytes(statuses)

    # ── Zero-copy columnar wire path ───────────────────────────────────

    def _vote_batch_prepare(self, c: P.Cursor) -> "_WireFramePrep | None":
        """Stage 1+2 of the wire pipeline, safe on the READER thread:
        decode the frame to views, parse vote columns (native, GIL-free),
        group rows per peer, and start each peer engine's session-
        independent validation prepass — hash pass + ONE cache-aware
        signature batch submit, running on the verify pool while earlier
        frames still apply on the serial lane. Returns None when any row
        is non-canonical (whole-frame object fallback) and raises the
        object decoder's ``ValueError`` for structurally bad frames (the
        wire contract stays identical). Peer resolution here is only a
        prepass hint — the apply stage re-resolves in receive order, so
        an ADD_PEER queued ahead of this frame still lands first."""
        from . import columnar as WC

        t0 = time.monotonic()
        view = P.decode_vote_batch_views(c)
        cols, flags = WC.parse_vote_columns(view.data, view.offsets)
        if not bool(flags.all()):
            return None
        per_peer: list[dict] = []
        by_peer: dict[int, dict] = {}
        row = 0
        for peer_id, scope, count in view.groups:
            entry = by_peer.get(peer_id)
            if entry is None:
                entry = by_peer[peer_id] = {
                    "peer_id": peer_id,
                    "scopes": [],
                    "scope_of": {},
                    "rows": [],
                    "sidx": [],
                }
                per_peer.append(entry)
            k = entry["scope_of"].get(scope)
            if k is None:
                k = entry["scope_of"][scope] = len(entry["scopes"])
                entry["scopes"].append(scope)
            entry["rows"].extend(range(row, row + count))
            entry["sidx"].extend([k] * count)
            row += count
        single = len(per_peer) == 1
        for entry in per_peer:
            rows = np.asarray(entry["rows"], np.int64)
            entry["rows"] = rows
            entry["sidx"] = np.asarray(entry["sidx"], np.int64)
            if single:
                entry["data"] = view.data
                entry["offsets"] = view.offsets
                entry["cols"] = cols
            else:
                entry["data"], entry["offsets"], entry["cols"] = (
                    self._pack_rows(view, cols, rows)
                )
        self._m_wire_decode_s.inc(time.monotonic() - t0)
        # Prepass start is CRYPTO time (hash pass + cache + batch
        # submit), attributed separately from the wire decode above.
        t1 = time.monotonic()
        for entry in per_peer:
            peer = self._peers.get(entry["peer_id"])
            engine = None if peer is None else peer.engine
            entry["engine"] = engine
            entry["prepass"] = None
            if (
                engine is not None
                and hasattr(engine, "ingest_wire_columnar")
                and hasattr(engine, "wire_verify_begin")
            ):
                entry["prepass"] = engine.wire_verify_begin(
                    entry["data"], entry["cols"], entry["offsets"]
                )
        self._m_wire_crypto_s.inc(time.monotonic() - t1)
        return _WireFramePrep(view, per_peer)

    @staticmethod
    def _pack_rows(view, cols, rows: np.ndarray):
        """Pack a peer's (possibly non-contiguous) rows into one
        contiguous (data, offsets, cols) triple (``columnar.pack_rows``,
        shared with the federation adapter's per-shard packing).
        Multi-peer frames only; a single-peer frame reuses the original
        views copy-free."""
        from . import columnar as WC

        return WC.pack_rows(view.data, view.offsets, cols, rows)

    def _vote_batch_apply(self, prep: "_WireFramePrep") -> tuple[int, bytes]:
        """Stage 3 of the wire pipeline (serial lane, receive order):
        re-resolve each peer and land its rows on
        ``ingest_wire_columnar`` with the prepass the reader started —
        the crypto has been running since. Unknown peers mark their rows
        STATUS_UNKNOWN_PEER; an engine without the columnar entry point
        (custom engine_factory) takes the object path for just its rows
        — peers are independent, so statuses stay per-row exact."""
        view = prep.view
        statuses = bytearray(view.total)
        out = np.frombuffer(statuses, np.uint8)
        stage: dict = {}
        reactor = self._reactor
        waits: list = []
        for entry in prep.per_peer:
            rows = entry["rows"]
            peer = self._peers.get(entry["peer_id"])
            if peer is None:
                out[rows] = P.STATUS_UNKNOWN_PEER
                continue
            engine = peer.engine
            if not hasattr(engine, "ingest_wire_columnar"):
                self._apply_rows_objects(engine, entry, view, out)
                continue
            prepass = (
                entry["prepass"] if engine is entry["engine"] else None
            )
            if reactor is not None:
                # Synchronous reactor path (non-pipelined connections,
                # embedded dispatch_frame): enqueue so rows can merge
                # with whatever the window already holds, flush the
                # engine's window, and wait here. Stage seconds flow
                # through the reactor's on_stage hook instead of the
                # local dict.
                handle = reactor.submit(
                    engine,
                    entry["scopes"],
                    entry["sidx"],
                    entry["cols"],
                    entry["data"],
                    entry["offsets"],
                    view.now,
                    prepass=prepass,
                )
                reactor.flush(engine)
                waits.append((handle, rows))
                continue
            codes = engine.ingest_wire_columnar(
                entry["scopes"],
                entry["sidx"],
                entry["cols"],
                entry["data"],
                entry["offsets"],
                view.now,
                stage_seconds=stage,
                _prepass=prepass,
            )
            out[rows] = (np.asarray(codes, np.int64) & 0xFF).astype(np.uint8)
        for handle, rows in waits:
            codes = handle.wait(30.0)  # engine errors re-raise here
            out[rows] = (np.asarray(codes, np.int64) & 0xFF).astype(np.uint8)
        self._m_wire_columnar.inc()
        self._m_wire_crypto_s.inc(stage.get("crypto", 0.0))
        self._m_wire_apply_s.inc(stage.get("apply", 0.0))
        return P.STATUS_OK, P.u32(view.total) + bytes(statuses)

    def _apply_rows_objects(self, engine, entry, view, out) -> None:
        """Object-path escape hatch for ONE peer's rows inside an
        otherwise-columnar frame (engine_factory engines without the
        columnar entry point). Rows are canonical by construction here,
        so every blob decodes."""
        from ..wire import Vote as _Vote

        data_b = entry["data"].tobytes()
        offsets = entry["offsets"]
        scopes = entry["scopes"]
        sidx = entry["sidx"]
        batch = [
            (
                scopes[int(sidx[j])],
                _Vote.decode(data_b[int(offsets[j]):int(offsets[j + 1])]),
            )
            for j in range(len(entry["rows"]))
        ]
        stages = [
            batch[i:i + self._PIPELINE_SPLIT]
            for i in range(0, len(batch), self._PIPELINE_SPLIT)
        ]
        results = engine.ingest_votes_pipelined(stages, view.now)
        codes = [int(code) & 0xFF for stage in results for code in stage]
        out[entry["rows"]] = np.asarray(codes, np.uint8)

    def _op_deliver_proposals(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        """Anti-entropy delivery (``OP_DELIVER_PROPOSALS``): lands on
        :meth:`TpuConsensusEngine.deliver_proposals` — unknown sessions
        are created, known ones extend along the validated-chain
        watermark (suffix-only crypto), redeliveries settle crypto-free
        as PROPOSAL_ALREADY_EXIST. Per-item statuses in batch order;
        an undecodable blob marks its row 241."""
        now = c.u64()
        count = c.u32()
        statuses = bytearray([P.STATUS_BAD_REQUEST]) * count
        items: list[tuple[int, str, Proposal]] = []
        for i in range(count):
            scope = c.string()
            blob = c.blob()
            try:
                items.append((i, scope, Proposal.decode(blob)))
            except (ValueError, IndexError):
                pass
        if items:
            codes = peer.engine.deliver_proposals(
                [(scope, proposal) for _, scope, proposal in items], now
            )
            for (i, _, _), code in zip(items, codes):
                statuses[i] = int(code) & 0xFF
        return P.STATUS_OK, P.u32(count) + bytes(statuses)

    def _op_handle_timeout(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        scope = c.string()
        pid = c.u32()
        now = c.u64()
        ctx = P.read_trace_context(c)
        with _traced("bridge.handle_timeout", ctx, peer.peer_id):
            result = peer.engine.handle_consensus_timeout(scope, pid, now)
        return P.STATUS_OK, P.u8(1 if result else 0)

    def _op_get_result(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        scope = c.string()
        pid = c.u32()
        try:
            result = peer.engine.get_consensus_result(scope, pid)
        except ConsensusError as exc:
            from ..errors import StatusCode

            if exc.code == StatusCode.CONSENSUS_FAILED:
                return P.STATUS_OK, P.u8(P.RESULT_FAILED)
            raise
        if result is None:
            return P.STATUS_OK, P.u8(P.RESULT_UNDECIDED)
        return P.STATUS_OK, P.u8(P.RESULT_YES if result else P.RESULT_NO)

    def _op_poll_events(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        # Optional trailing u32 bound (FEATURE_EVENT_BOUND): a fabric
        # event pump polling many peers caps each drain so one hot peer
        # cannot monopolize the window. Bounded requests get a trailing
        # u8 ``more`` flag (conservative: set when the bound stopped the
        # drain, so the pump polls again immediately; an empty receiver
        # on the next poll costs one frame, not a missed event).
        max_events = c.u32() if c.remaining() >= 4 else None
        events: list[tuple[str, ConsensusEvent]] = []
        more = False
        while True:
            if max_events is not None and len(events) >= max_events:
                more = True
                break
            item = peer.receiver.try_recv()
            if item is None:
                break
            # Filter to the encodable kinds BEFORE counting so the leading
            # u32 always matches the records that follow.
            if isinstance(item[1], (ConsensusReached, ConsensusFailedEvent)):
                events.append(item)
        out = [P.u32(len(events))]
        for scope, event in events:
            if isinstance(event, ConsensusReached):
                out.append(
                    P.string(str(scope))
                    + P.u8(P.EVENT_REACHED)
                    + P.u32(event.proposal_id)
                    + P.u8(1 if event.result else 0)
                    + P.u64(event.timestamp)
                )
            else:
                out.append(
                    P.string(str(scope))
                    + P.u8(P.EVENT_FAILED)
                    + P.u32(event.proposal_id)
                    + P.u8(0)
                    + P.u64(event.timestamp)
                )
        if max_events is not None:
            out.append(P.u8(1 if more else 0))
        return P.STATUS_OK, b"".join(out)

    def _op_get_proposal(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        scope = c.string()
        pid = c.u32()
        proposal = peer.engine.get_proposal(scope, pid)
        return P.STATUS_OK, P.blob(proposal.encode())

    def _op_get_stats(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        scope = c.string()
        stats = peer.engine.get_scope_stats(scope)
        return P.STATUS_OK, (
            P.u32(stats.total_sessions)
            + P.u32(stats.active_sessions)
            + P.u32(stats.failed_sessions)
            + P.u32(stats.consensus_reached)
        )

    def _op_health(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        """Consensus-health snapshot as one JSON blob (see
        ``TpuConsensusEngine.health_report``): scorecards, evidence,
        watchdog, firing alerts; durable peers overlay their WAL
        watermark. The trailing u64 is the embedder's logical tick (0 =
        use the monitor's latest — remote dashboards have no embedder
        clock)."""
        now = c.u64()
        report = peer.engine.health_report(now if now else None)
        return P.STATUS_OK, P.blob(json.dumps(report).encode("utf-8"))

    # ── State sync: snapshot shipping + WAL tailing ────────────────────

    # Server-side bounds: a chunk must fit one response frame with room
    # to spare; the tail budget caps how much log one response carries.
    _SYNC_MAX_CHUNK = 32 * 1024 * 1024
    _TAIL_DEFAULT_BYTES = 4 * 1024 * 1024
    _TAIL_MAX_BYTES = 16 * 1024 * 1024

    @staticmethod
    def _sync_source(peer: _Peer):
        """The peer's DurableEngine, or None when the peer cannot serve
        state sync (keyless/undurable peers have no WAL watermark to tail
        from — a snapshot without one could never be caught up past)."""
        engine = peer.engine
        if hasattr(engine, "capture_consistent") and hasattr(engine, "wal"):
            return engine
        return None

    def _op_sync_manifest(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        """Serve (building if stale) the snapshot manifest for a durable
        peer. The snapshot file lives under the peer's WAL directory
        (``<wal>/sync/snapshot.bin``) and is rebuilt only when the peer's
        WAL position moved since the cached build — repeated manifest
        requests against a quiet peer are free."""
        from ..sync.snapshot import build_snapshot

        max_chunk = c.u32()
        engine = self._sync_source(peer)
        if engine is None:
            return P.STATUS_BAD_REQUEST, P.string(
                "peer is not durable (no WAL): state sync needs a "
                "watermark to tail from"
            )
        chunk_bytes = self._SYNC_MAX_CHUNK if max_chunk == 0 else max_chunk
        chunk_bytes = min(chunk_bytes, self._SYNC_MAX_CHUNK)
        with self._sync_lock:
            gate = self._sync_gates.setdefault(peer.peer_id, threading.Lock())
        with gate:  # serializes builds for THIS peer only
            with self._sync_lock:
                cached = self._sync_cache.get(peer.peer_id)
            current = engine.wal.last_lsn
            if (
                cached is not None
                and cached[0].watermark == current
                and cached[0].chunk_bytes == chunk_bytes
            ):
                manifest, _path = cached
            else:
                with self._sync_lock:
                    self._sync_seq += 1
                    snapshot_id = self._sync_seq
                path = os.path.join(
                    engine.wal.directory, "sync", f"snapshot-{snapshot_id}.bin"
                )
                manifest = build_snapshot(
                    engine, path,
                    chunk_bytes=chunk_bytes, snapshot_id=snapshot_id,
                )
                with self._sync_lock:
                    self._sync_cache[peer.peer_id] = (manifest, path)
                # The superseded artifact is dead: chunk requests against
                # its id already resolve to STATUS_SYNC_STALE (the cache
                # holds only the new id), so the file can go.
                if cached is not None:
                    try:
                        os.remove(cached[1])
                    except OSError:
                        pass
                flight_recorder.record(
                    "sync.snapshot_built",
                    peer=peer.peer_id,
                    snapshot_id=manifest.snapshot_id,
                    watermark=manifest.watermark,
                    bytes=manifest.total_bytes,
                    sessions=manifest.session_count,
                )
        return P.STATUS_OK, (
            P.u64(manifest.snapshot_id)
            + P.u64(manifest.watermark)
            + P.u64(manifest.total_bytes)
            + P.u32(manifest.chunk_bytes)
            + P.u32(manifest.session_count)
            + P.u32(manifest.config_count)
            + P.u32(manifest.chunk_count)
            + b"".join(manifest.digests)
        )

    def _op_sync_chunk(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        snapshot_id = c.u64()
        index = c.u32()
        with self._sync_lock:
            cached = self._sync_cache.get(peer.peer_id)
        if cached is None or cached[0].snapshot_id != snapshot_id:
            return P.STATUS_SYNC_STALE, P.string(
                f"snapshot {snapshot_id} is no longer served; re-fetch "
                "the manifest"
            )
        manifest, path = cached
        if index >= manifest.chunk_count:
            return P.STATUS_BAD_REQUEST, P.string(
                f"chunk {index} out of range (snapshot has "
                f"{manifest.chunk_count})"
            )
        try:
            with open(path, "rb") as fh:
                fh.seek(index * manifest.chunk_bytes)
                data = fh.read(manifest.chunk_bytes)
        except OSError:
            # Lost the race with a rebuild that removed this artifact
            # between the cache read and the open: same signal as an id
            # mismatch — refresh and resume.
            return P.STATUS_SYNC_STALE, P.string(
                f"snapshot {snapshot_id} was rebuilt; re-fetch the manifest"
            )
        self._m_sync_chunks.inc()
        return P.STATUS_OK, P.blob(data)

    def _op_wal_tail(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        from ..wal.recovery import read_tail

        after_lsn = c.u64()
        max_bytes = c.u32()
        engine = self._sync_source(peer)
        if engine is None:
            return P.STATUS_BAD_REQUEST, P.string(
                "peer is not durable (no WAL): nothing to tail"
            )
        budget = self._TAIL_DEFAULT_BYTES if max_bytes == 0 else max_bytes
        budget = min(budget, self._TAIL_MAX_BYTES)
        records, more = read_tail(engine.wal.directory, after_lsn, budget)
        out = [P.u32(len(records))]
        for lsn, kind, payload in records:
            out.append(P.u64(lsn) + P.u8(kind) + P.blob(payload))
        out.append(P.u8(1 if more else 0))
        return P.STATUS_OK, b"".join(out)

    def _op_state_fingerprint(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        """Order-insensitive content digest of the peer's full tracked
        state (``sync.state_fingerprint``) — lets a remote driver assert
        cross-peer convergence without reaching into the process."""
        from ..sync.snapshot import state_fingerprint

        return P.STATUS_OK, P.string(state_fingerprint(peer.engine))

    def _op_fleet_tally(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        """Slot-state histogram of the peer's engine. A federation host
        (peer engine = fleet adapter) answers its whole local fleet's
        ONE-psum tally; a plain engine answers its pool's counts. This is
        the fabric arm of the cross-host tally contract — the psum arm
        needs cross-process collectives the backend may not implement
        (parallel.multihost.collectives_available)."""
        tally = getattr(peer.engine, "fleet_state_counts", None)
        counts = tally() if tally is not None else peer.engine.pool().state_counts()
        return P.STATUS_OK, P.encode_fleet_tally(
            {int(code): int(count) for code, count in counts.items()}
        )

    def _op_explain(self, peer: _Peer, c: P.Cursor) -> tuple[int, bytes]:
        """Decision provenance as one JSON blob (see
        ``TpuConsensusEngine.explain_decision``); durable peers overlay
        their WAL watermark. SessionNotFound maps to the usual wire
        status through the dispatch loop."""
        scope = c.string()
        pid = c.u32()
        verdict = peer.engine.explain_decision(scope, pid)
        return P.STATUS_OK, P.blob(json.dumps(verdict).encode("utf-8"))


_HANDLERS = {
    P.OP_CREATE_PROPOSAL: BridgeServer._op_create_proposal,
    P.OP_CAST_VOTE: BridgeServer._op_cast_vote,
    P.OP_PROCESS_PROPOSAL: BridgeServer._op_process_proposal,
    P.OP_PROCESS_VOTE: BridgeServer._op_process_vote,
    P.OP_PROCESS_VOTES: BridgeServer._op_process_votes,
    P.OP_HANDLE_TIMEOUT: BridgeServer._op_handle_timeout,
    P.OP_GET_RESULT: BridgeServer._op_get_result,
    P.OP_POLL_EVENTS: BridgeServer._op_poll_events,
    P.OP_GET_PROPOSAL: BridgeServer._op_get_proposal,
    P.OP_GET_STATS: BridgeServer._op_get_stats,
    P.OP_EXPLAIN: BridgeServer._op_explain,
    P.OP_HEALTH: BridgeServer._op_health,
    P.OP_SYNC_MANIFEST: BridgeServer._op_sync_manifest,
    P.OP_SYNC_CHUNK: BridgeServer._op_sync_chunk,
    P.OP_WAL_TAIL: BridgeServer._op_wal_tail,
    P.OP_DELIVER_PROPOSALS: BridgeServer._op_deliver_proposals,
    P.OP_STATE_FINGERPRINT: BridgeServer._op_state_fingerprint,
    P.OP_FLEET_TALLY: BridgeServer._op_fleet_tally,
}
