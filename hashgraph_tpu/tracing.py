"""Structured tracing and metrics for the consensus engine.

The reference declares a ``tracing`` dependency but never emits a single
event (SURVEY §5 — zero macro invocations); this module is the real thing:
near-zero-overhead counters and spans on the host side, JSON-lines export for
offline analysis, and a bridge to ``jax.profiler`` for device-side traces.

Usage::

    from hashgraph_tpu.tracing import tracer

    with tracer.span("ingest", votes=128):
        ...
    tracer.count("votes_accepted", 120)
    tracer.export_jsonl("/tmp/trace.jsonl")

Disabled by default: a disabled tracer's ``span`` is a no-op context manager
and ``count``/``event`` return immediately (one attribute check), so the hot
path pays nothing until someone calls ``tracer.enable()``.

For the always-on production layer — Prometheus-style metrics families,
decision-latency histograms, scrape endpoints, and the flight recorder —
see :mod:`hashgraph_tpu.obs`; it layers on this tracer
(:func:`~hashgraph_tpu.obs.observed_span` feeds both) rather than
replacing it. For *distributed* tracing — trace context on the wire,
cross-peer span stitching into one Perfetto timeline, and the
``explain_decision`` provenance readout — see
:mod:`hashgraph_tpu.obs.trace`; ``observed_span`` tags its spans with
the active :class:`~hashgraph_tpu.obs.trace.TraceContext` automatically.

Well-known counter families (all emitted through the process-wide default
tracer unless a component was given its own):

- ``engine.*`` — votes_in / votes_accepted / transitions / host_spills /
  pid_collisions / timeout_sweeps / timeouts_fired / fresh_dispatches;
- ``wal.*`` — the durability subsystem (:mod:`hashgraph_tpu.wal`):
  ``wal.append_records`` and ``wal.append_bytes`` (log growth),
  ``wal.fsync`` (durability syscalls — the throughput/durability dial),
  ``wal.rotate`` (segment seals), ``wal.recover.records`` (replayed on
  restart), ``wal.compact.segments`` (dropped behind snapshots),
  ``wal.repair.truncated_bytes`` (torn tail removed at open), and the
  recovery-loss counters ``wal.recover.torn_bytes`` /
  ``wal.recover.dropped_segments`` / ``wal.recover.decode_errors``
  (nonzero dropped_segments/decode_errors = mid-log corruption, not a
  crash tail — acknowledged records were lost).
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field


# Process umask, probed ONCE at import (imports run before worker threads
# exist): export_jsonl needs it to restore normal file modes on its mkstemp
# temp files, and toggling the process-global umask per export would race
# with concurrent file creation elsewhere (WAL segments, flight dumps).
_UMASK = os.umask(0)
os.umask(_UMASK)


def atomic_write_text(path: str, text: str) -> None:
    """Crash-safe text export: write to an mkstemp temp file in the
    destination directory, widen the 0600 temp mode back to what a plain
    open() would create (so log shippers under another uid keep access),
    and ``os.replace`` into place — ``path`` either holds its previous
    content or the complete new text, never a torn file. Shared by
    :meth:`Tracer.export_jsonl` and the distributed-tracing exports
    (:mod:`hashgraph_tpu.obs.trace`)."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".", dir=directory)
    try:
        os.chmod(tmp, 0o666 & ~_UMASK)
        with os.fdopen(fd, "w") as fh:
            fh.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


@dataclass
class SpanRecord:
    name: str
    start: float
    duration: float
    attrs: dict = field(default_factory=dict)


class Tracer:
    """Thread-safe span/counter/event collector."""

    def __init__(self, enabled: bool = False, max_records: int = 100_000):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counters: defaultdict[str, int] = defaultdict(int)
        self._spans: list[SpanRecord] = []
        self._events: list[dict] = []
        self._max_records = max_records

    # ── Control ────────────────────────────────────────────────────────

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._spans.clear()
            self._events.clear()

    # ── Recording ──────────────────────────────────────────────────────

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Time a block. Records wall duration; attrs are free-form.

        At most ``max_records`` span records are retained; past the cap the
        per-span record is dropped (the ``span.dropped`` counter says how
        many) while the ``span.<name>.calls`` / ``.ns`` counters keep
        aggregating, so totals stay exact even when the record list is
        full."""
        if not self.enabled:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            self.record_span(name, start, time.perf_counter() - start, attrs)

    def record_span(
        self, name: str, start: float, duration: float, attrs: dict
    ) -> None:
        """Record an externally-timed span (the body of :meth:`span`;
        also used by :func:`hashgraph_tpu.obs.observed_span`, which times
        once and feeds both the metrics registry and this tracer)."""
        with self._lock:
            if len(self._spans) < self._max_records:
                self._spans.append(SpanRecord(name, start, duration, attrs))
            else:
                self._counters["span.dropped"] += 1
            self._counters[f"span.{name}.calls"] += 1
            self._counters[f"span.{name}.ns"] += int(duration * 1e9)

    def count(self, name: str, n: int = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] += n

    def event(self, name: str, **attrs) -> None:
        if not self.enabled:
            return
        with self._lock:
            if len(self._events) < self._max_records:
                self._events.append(
                    {"name": name, "ts": time.time(), **attrs}
                )

    # ── Readout ────────────────────────────────────────────────────────

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def spans(self, name: str | None = None) -> list[SpanRecord]:
        with self._lock:
            if name is None:
                return list(self._spans)
            return [s for s in self._spans if s.name == name]

    def span_stats(self, name: str) -> dict[str, float]:
        """count / total / mean / max seconds for one span name."""
        durations = [s.duration for s in self.spans(name)]
        if not durations:
            return {"count": 0, "total": 0.0, "mean": 0.0, "max": 0.0}
        return {
            "count": len(durations),
            "total": sum(durations),
            "mean": sum(durations) / len(durations),
            "max": max(durations),
        }

    def export_jsonl(self, path: str) -> None:
        """Write counters, spans, and events as JSON lines, atomically
        (see :func:`atomic_write_text`): a crash or serialization error
        mid-export can never leave a torn trace file."""
        with self._lock:
            lines = [
                json.dumps(
                    {"type": "counters", "values": dict(self._counters)}
                )
            ]
            lines.extend(
                json.dumps(
                    {
                        "type": "span",
                        "name": s.name,
                        "start": s.start,
                        "duration": s.duration,
                        **s.attrs,
                    }
                )
                for s in self._spans
            )
            lines.extend(
                json.dumps({"type": "event", **e}) for e in self._events
            )
            atomic_write_text(path, "".join(line + "\n" for line in lines))


# Process-wide default tracer; engine instances use this unless given one.
tracer = Tracer()


@contextlib.contextmanager
def device_profile(log_dir: str):
    """Capture a jax.profiler device trace (XLA timelines, HBM, fusion view
    in TensorBoard/Perfetto) around a block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
