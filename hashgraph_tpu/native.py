"""ctypes bindings for the native C++ host runtime (hashing + ECDSA).

Loads ``native/build/libconsensus_native.so``, building it on first use when
a compiler is available (the library is ~1s to compile and has zero
dependencies). Every entry point has a pure-Python fallback elsewhere in the
package, so the framework works without it — the native path exists for host
throughput: EIP-191 verification is ~20-40x faster per core than the
pure-Python curve math, and the batch calls release the GIL and fan out over
hardware threads.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DEFAULT_SO = os.path.join(_REPO_ROOT, "native", "build", "libconsensus_native.so")
_SOURCE = os.path.join(_REPO_ROOT, "native", "consensus_native.cpp")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_attempted = False


def _cpu_tag() -> str:
    """Fingerprint of this host's ISA extensions: a -march=native build from
    a different host would SIGILL at the first AVX/ADX instruction, so the
    artifact is stamped with the builder's tag and rebuilt on mismatch."""
    import hashlib
    import platform

    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()[:16]
    except OSError:
        pass
    return platform.machine()


def _build() -> bool:
    base = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread"]
    # -march=native enables ADX/BMI2 (mulx/adcx) codegen for the 256-bit
    # field arithmetic — a large ECDSA win; retry portable if rejected.
    for flags in ([*base, "-march=native"], base):
        try:
            os.makedirs(os.path.dirname(_DEFAULT_SO), exist_ok=True)
            subprocess.run(
                [*flags, "-o", _DEFAULT_SO, _SOURCE],
                check=True,
                capture_output=True,
                timeout=120,
            )
            try:
                with open(_DEFAULT_SO + ".cputag", "w") as fh:
                    fh.write(_cpu_tag())
            except OSError:
                pass
            return True
        except Exception:
            continue
    return False


def _host_mismatch(path: str) -> bool:
    """True when the cached artifact was built on a host with different ISA
    extensions (shared/copied checkout on a heterogeneous fleet)."""
    try:
        with open(path + ".cputag") as fh:
            return fh.read().strip() != _cpu_tag()
    except OSError:
        return False  # untagged artifact: assume portable (pre-tag builds)


def _load() -> ctypes.CDLL | None:
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        path = os.environ.get("HASHGRAPH_TPU_NATIVE", _DEFAULT_SO)
        if not os.path.exists(path) or (
            path == _DEFAULT_SO and _host_mismatch(path)
        ):
            # Only auto-(re)build the default artifact; an explicit env
            # override pointing at a missing or foreign file is the
            # caller's mistake to surface.
            if path != _DEFAULT_SO or not os.path.exists(_SOURCE) or not _build():
                return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        _NEWEST_SYMBOL = "hg_parse_vote_columns"  # bump when the ABI grows
        if not hasattr(lib, _NEWEST_SYMBOL):
            # Stale artifact (e.g. a cached build from an older checkout):
            # rebuild the default path once, else give up.
            if path != _DEFAULT_SO or not _build():
                return None
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                return None
            if not hasattr(lib, _NEWEST_SYMBOL):
                # dlopen caches by path, so the reload may return the
                # SAME stale handle; the rebuilt artifact then only takes
                # effect in a fresh process — degrade, don't crash.
                return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.hg_version.restype = ctypes.c_int
        lib.hg_sha256.argtypes = [u8p, ctypes.c_uint64, u8p]
        lib.hg_keccak256.argtypes = [u8p, ctypes.c_uint64, u8p]
        for fn in (lib.hg_sha256_batch, lib.hg_keccak256_batch):
            fn.argtypes = [u8p, u64p, ctypes.c_int64, u8p, ctypes.c_int]
        lib.hg_eth_verify.restype = ctypes.c_int
        lib.hg_eth_verify.argtypes = [u8p, u8p, ctypes.c_uint64, u8p]
        lib.hg_eth_verify_batch.argtypes = [
            u8p, u8p, u64p, u8p, ctypes.c_int64, u8p, ctypes.c_int,
        ]
        lib.hg_eth_sign.restype = ctypes.c_int
        lib.hg_eth_sign.argtypes = [u8p, u8p, ctypes.c_uint64, u8p]
        lib.hg_eth_address.restype = ctypes.c_int
        lib.hg_eth_address.argtypes = [u8p, u8p]
        lib.hg_pid_lookup.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int, i64p,
            ctypes.c_int64, u8p, i64p, ctypes.c_int,
        ]
        lib.hg_gids_live.argtypes = [
            i64p, ctypes.c_int64, u8p, i64p,
            ctypes.c_int64, u8p, ctypes.c_int,
        ]
        # Persistent verify pool (v3 ABI).
        lib.hg_pool_configure.restype = ctypes.c_int
        lib.hg_pool_configure.argtypes = [ctypes.c_int]
        lib.hg_pool_size.restype = ctypes.c_int
        lib.hg_pool_queue_depth.restype = ctypes.c_int64
        lib.hg_pool_wait.restype = ctypes.c_int
        lib.hg_pool_wait.argtypes = [ctypes.c_int64]
        lib.hg_eth_verify_batch_submit.restype = ctypes.c_int64
        lib.hg_eth_verify_batch_submit.argtypes = [
            u8p, u8p, u64p, u8p, ctypes.c_int64, u8p,
        ]
        # Ed25519 (v3 ABI).
        lib.hg_ed25519_public.restype = ctypes.c_int
        lib.hg_ed25519_public.argtypes = [u8p, u8p]
        lib.hg_ed25519_sign.restype = ctypes.c_int
        lib.hg_ed25519_sign.argtypes = [u8p, u8p, ctypes.c_uint64, u8p]
        lib.hg_ed25519_verify.restype = ctypes.c_int
        lib.hg_ed25519_verify.argtypes = [u8p, u8p, ctypes.c_uint64, u8p]
        lib.hg_ed25519_verify_batch.argtypes = [
            u8p, u8p, u64p, u8p, ctypes.c_int64, u8p, ctypes.c_int,
        ]
        lib.hg_ed25519_verify_batch_submit.restype = ctypes.c_int64
        lib.hg_ed25519_verify_batch_submit.argtypes = [
            u8p, u8p, u64p, u8p, ctypes.c_int64, u8p,
        ]
        # Columnar wire parse (v4 ABI).
        lib.hg_parse_vote_columns.argtypes = [
            u8p, u64p, ctypes.c_int64, i64p, u8p, ctypes.c_int,
        ]
        lib.hg_vote_hash_columns.argtypes = [
            u8p, i64p, ctypes.c_int64, u8p, ctypes.c_int,
        ]
        if lib.hg_version() < 4:
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _u8(buf) -> ctypes.POINTER(ctypes.c_uint8):
    return ctypes.cast(
        (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf), ctypes.POINTER(ctypes.c_uint8)
    )


def _np_u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _joined_u8(items: "list[bytes]") -> np.ndarray:
    """Concatenate byte strings into one uint8 view WITHOUT a second
    copy: ``b"".join`` already materializes a fresh buffer, and the C
    side never writes these, so a read-only ``frombuffer`` view over the
    joined bytes is enough (the array keeps the bytes object alive)."""
    return np.frombuffer(b"".join(items) or b"\x00", np.uint8)


def keccak256(data: bytes) -> bytes | None:
    lib = _load()
    if lib is None:
        return None
    out = np.empty(32, np.uint8)
    lib.hg_keccak256(_u8(data), len(data), _np_u8p(out))
    return out.tobytes()


def pid_lookup(
    table_keys: np.ndarray,
    table_vals: np.ndarray,
    shift: int,
    queries: np.ndarray,
    n_threads: int = 0,
) -> "tuple[np.ndarray, np.ndarray] | None":
    """Fused open-addressing probe (engine._PidLookup layout: power-of-two
    table, Fibonacci bucketing with the given shift, -1 empty sentinel).
    Returns (found bool[B], slots int64[B]; 0 where not found), or None
    when the native runtime is absent. The call releases the GIL."""
    lib = _load()
    if lib is None:
        return None
    keys = np.ascontiguousarray(table_keys, np.int64)
    vals = np.ascontiguousarray(table_vals, np.int64)
    q = np.ascontiguousarray(queries, np.int64)
    if len(keys) < 2:
        # Defensive only — unreachable from the engine: _PidLookup always
        # builds a table of size >= 2 (n = max(len(pids), 1), size doubles
        # until >= 2n). Kept for direct callers of this binding: a size-1
        # table would make shift == 64, a UB shift width in C — and a
        # sentinel-only table can't match anything anyway.
        return np.zeros(len(q), bool), np.zeros(len(q), np.int64)
    found = np.empty(len(q), np.uint8)
    out = np.empty(len(q), np.int64)
    i64 = ctypes.POINTER(ctypes.c_int64)
    lib.hg_pid_lookup(
        keys.ctypes.data_as(i64),
        vals.ctypes.data_as(i64),
        len(keys),
        int(shift),
        q.ctypes.data_as(i64),
        len(q),
        _np_u8p(found),
        out.ctypes.data_as(i64),
        n_threads,
    )
    return found.view(bool), out


def gids_live(
    gids: np.ndarray,
    live: np.ndarray,
    gen: np.ndarray,
    n_threads: int = 0,
) -> "np.ndarray | None":
    """Fused generation-tagged gid liveness check (pool.gids_live layout):
    bool[B], or None when the runtime is absent."""
    lib = _load()
    if lib is None:
        return None
    g = np.ascontiguousarray(gids, np.int64)
    # bool and uint8 share layout: view, don't copy the whole registry.
    lv = (
        live.view(np.uint8)
        if live.dtype == np.bool_ and live.flags.c_contiguous
        else np.ascontiguousarray(live, np.uint8)
    )
    gn = np.ascontiguousarray(gen, np.int64)
    out = np.empty(len(g), np.uint8)
    i64 = ctypes.POINTER(ctypes.c_int64)
    lib.hg_gids_live(
        g.ctypes.data_as(i64),
        len(g),
        _np_u8p(lv),
        gn.ctypes.data_as(i64),
        len(gn),
        _np_u8p(out),
        n_threads,
    )
    return out.view(bool)


def sha256_batch(items: list[bytes], n_threads: int = 0) -> np.ndarray | None:
    """[K] digests as uint8[K, 32], or None when the runtime is absent."""
    return _hash_batch(items, n_threads, "hg_sha256_batch")


def keccak256_batch(items: list[bytes], n_threads: int = 0) -> np.ndarray | None:
    return _hash_batch(items, n_threads, "hg_keccak256_batch")


def _hash_batch(items: list[bytes], n_threads: int, fn_name: str) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    data = _joined_u8(items)
    offsets = np.zeros(len(items) + 1, np.uint64)
    np.cumsum([len(b) for b in items], out=offsets[1:])
    out = np.empty((len(items), 32), np.uint8)
    getattr(lib, fn_name)(
        _np_u8p(data),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(items),
        _np_u8p(out),
        n_threads,
    )
    return out


def eth_verify(identity: bytes, payload: bytes, signature: bytes) -> int | None:
    """1 valid, 0 address mismatch, -1 malformed recovery byte, -2 recovery
    failed; None if the native runtime is unavailable."""
    lib = _load()
    if lib is None:
        return None
    return lib.hg_eth_verify(_u8(identity), _u8(payload), len(payload), _u8(signature))


def eth_verify_batch(
    identities: list[bytes],
    payloads: list[bytes],
    signatures: list[bytes],
    n_threads: int = 0,
) -> np.ndarray | None:
    """uint8[K]: 1 valid, 0 address mismatch, 255 malformed recovery byte,
    254 recovery failed; None if unavailable. Caller guarantees 20-byte
    identities and 65-byte signatures."""
    lib = _load()
    if lib is None:
        return None
    k = len(identities)
    ids = _joined_u8(identities)
    sigs = _joined_u8(signatures)
    data = _joined_u8(payloads)
    offsets = np.zeros(k + 1, np.uint64)
    np.cumsum([len(b) for b in payloads], out=offsets[1:])
    out = np.empty(k, np.uint8)
    lib.hg_eth_verify_batch(
        _np_u8p(ids),
        _np_u8p(data),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        _np_u8p(sigs),
        k,
        _np_u8p(out),
        n_threads,
    )
    return out


def eth_sign(private_key: bytes, payload: bytes) -> bytes | None:
    lib = _load()
    if lib is None:
        return None
    out = np.empty(65, np.uint8)
    rc = lib.hg_eth_sign(_u8(private_key), _u8(payload), len(payload), _np_u8p(out))
    return out.tobytes() if rc == 0 else None


def eth_address(private_key: bytes) -> bytes | None:
    lib = _load()
    if lib is None:
        return None
    out = np.empty(20, np.uint8)
    rc = lib.hg_eth_address(_u8(private_key), _np_u8p(out))
    return out.tobytes() if rc == 0 else None


# ── Persistent verify pool ─────────────────────────────────────────────


class VerifyJob:
    """Handle for an in-flight native verify batch.

    The worker pool fills ``out`` in the background with no GIL
    involvement; :meth:`collect` blocks until every chunk completed and
    returns the result codes. The job object keeps every marshalled
    buffer alive until collection — the C side borrows the pointers, so
    the buffers must outlive the workers: a job dropped UNCOLLECTED
    waits for its chunks in ``__del__`` before the buffers can be freed
    (the crypto is already running; the wait is bounded by work that was
    going to happen anyway — never let the GC race a worker's writes).
    """

    __slots__ = ("_lib", "_handle", "out", "_keepalive", "_collected")

    def __init__(self, lib, handle: int, out: np.ndarray, keepalive: tuple):
        self._lib = lib
        self._handle = handle
        self.out = out
        self._keepalive = keepalive
        self._collected = False

    def collect(self) -> np.ndarray:
        """Wait for the batch and return its result codes (uint8[K])."""
        if not self._collected:
            self._lib.hg_pool_wait(self._handle)
            self._collected = True
        return self.out

    def __del__(self):
        try:
            self.collect()
        except Exception:
            pass  # interpreter teardown: the process outlives the pool


def pool_configure(n_threads: int) -> int | None:
    """(Re)size the persistent verify pool (<= 0 restores the hardware
    default). Returns the resulting worker count, or None when the
    native runtime is absent. Call between batches, not mid-flight."""
    lib = _load()
    if lib is None:
        return None
    return lib.hg_pool_configure(n_threads)


def pool_size() -> int | None:
    lib = _load()
    if lib is None:
        return None
    return lib.hg_pool_size()


def pool_queue_depth() -> int | None:
    """Verify-pool tasks queued + running, or None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    return lib.hg_pool_queue_depth()


def pool_queue_depth_if_loaded() -> int:
    """Metrics-safe queue depth: 0 unless the runtime is ALREADY loaded.
    Scrape paths use this — naming the gauge must never be the thing
    that compiles or dlopens the native library."""
    lib = _lib
    return int(lib.hg_pool_queue_depth()) if lib is not None else 0


def _submit_batch(lib, fn, fixed_arrays: tuple, payloads: "list[bytes]",
                  count: int) -> VerifyJob:
    data = _joined_u8(payloads)
    offsets = np.zeros(count + 1, np.uint64)
    np.cumsum([len(b) for b in payloads], out=offsets[1:])
    out = np.empty(count, np.uint8)
    handle = fn(
        _np_u8p(fixed_arrays[0]),
        _np_u8p(data),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        _np_u8p(fixed_arrays[1]),
        count,
        _np_u8p(out),
    )
    return VerifyJob(lib, handle, out, (fixed_arrays, data, offsets))


def eth_verify_batch_submit(
    identities: list[bytes],
    payloads: list[bytes],
    signatures: list[bytes],
) -> VerifyJob | None:
    """Async :func:`eth_verify_batch`: returns immediately with a
    :class:`VerifyJob` whose ``collect()`` yields the same uint8 codes
    (1 valid, 0 mismatch, 255 malformed recovery byte, 254 recovery
    failed), or None if the runtime is unavailable. Caller guarantees
    20-byte identities and 65-byte signatures."""
    lib = _load()
    if lib is None:
        return None
    return _submit_batch(
        lib,
        lib.hg_eth_verify_batch_submit,
        (_joined_u8(identities), _joined_u8(signatures)),
        payloads,
        len(identities),
    )


# ── Ed25519 ────────────────────────────────────────────────────────────


def ed25519_public(seed: bytes) -> bytes | None:
    """32-byte public key for a 32-byte seed, or None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    out = np.empty(32, np.uint8)
    lib.hg_ed25519_public(_u8(seed), _np_u8p(out))
    return out.tobytes()


def ed25519_sign(seed: bytes, payload: bytes) -> bytes | None:
    lib = _load()
    if lib is None:
        return None
    out = np.empty(64, np.uint8)
    lib.hg_ed25519_sign(_u8(seed), _u8(payload), len(payload), _np_u8p(out))
    return out.tobytes()


def ed25519_verify(pub: bytes, payload: bytes, signature: bytes) -> int | None:
    """1 valid, 0 invalid (cofactored verification; bad encodings and a
    non-canonical s also report 0); None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    return lib.hg_ed25519_verify(_u8(pub), _u8(payload), len(payload), _u8(signature))


def ed25519_verify_batch(
    pubs: list[bytes],
    payloads: list[bytes],
    signatures: list[bytes],
    n_threads: int = 0,
) -> np.ndarray | None:
    """uint8[K]: 1 valid, 0 invalid; None if unavailable. Caller
    guarantees 32-byte pubs and 64-byte signatures. Chunks verify as one
    randomized linear combination across the worker pool."""
    lib = _load()
    if lib is None:
        return None
    k = len(pubs)
    ids = _joined_u8(pubs)
    sigs = _joined_u8(signatures)
    data = _joined_u8(payloads)
    offsets = np.zeros(k + 1, np.uint64)
    np.cumsum([len(b) for b in payloads], out=offsets[1:])
    out = np.empty(k, np.uint8)
    lib.hg_ed25519_verify_batch(
        _np_u8p(ids),
        _np_u8p(data),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        _np_u8p(sigs),
        k,
        _np_u8p(out),
        n_threads,
    )
    return out


# ── Columnar wire-vote parsing ─────────────────────────────────────────

VOTE_COLS = 16  # int64 columns per parsed vote (see consensus_native.cpp)


def parse_vote_columns(
    data: np.ndarray, offsets: np.ndarray, n_threads: int = 0
) -> "tuple[np.ndarray, np.ndarray] | None":
    """Strict-canonical batched Vote parse straight off the wire buffer:
    returns (cols int64[N, VOTE_COLS], flags uint8[N]) — flag 1 rows are
    canonical and fully columnized, flag 0 rows need the Python object
    decoder. None when the native runtime is absent. GIL-free."""
    lib = _load()
    if lib is None:
        return None
    d = (
        data
        if isinstance(data, np.ndarray) and data.dtype == np.uint8
        and data.flags.c_contiguous
        else np.ascontiguousarray(np.frombuffer(bytes(data), np.uint8))
    )
    offs = np.ascontiguousarray(offsets, np.uint64)
    n = len(offs) - 1
    cols = np.zeros((n, VOTE_COLS), np.int64)
    flags = np.zeros(n, np.uint8)
    lib.hg_parse_vote_columns(
        _np_u8p(d),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n,
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        _np_u8p(flags),
        n_threads,
    )
    return cols, flags


def vote_hash_columns(
    data: np.ndarray, cols: np.ndarray, n_threads: int = 0
) -> "np.ndarray | None":
    """Batched ``protocol.compute_vote_hash`` over parsed columns:
    uint8[N, 32] digests, or None when the runtime is absent."""
    lib = _load()
    if lib is None:
        return None
    d = (
        data
        if isinstance(data, np.ndarray) and data.dtype == np.uint8
        and data.flags.c_contiguous
        else np.ascontiguousarray(np.frombuffer(bytes(data), np.uint8))
    )
    c = np.ascontiguousarray(cols, np.int64)
    n = len(c)
    out = np.empty((n, 32), np.uint8)
    lib.hg_vote_hash_columns(
        _np_u8p(d),
        c.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        _np_u8p(out),
        n_threads,
    )
    return out


def ed25519_verify_batch_submit(
    pubs: list[bytes],
    payloads: list[bytes],
    signatures: list[bytes],
) -> VerifyJob | None:
    """Async :func:`ed25519_verify_batch` (collect() -> uint8 codes)."""
    lib = _load()
    if lib is None:
        return None
    return _submit_batch(
        lib,
        lib.hg_ed25519_verify_batch_submit,
        (_joined_u8(pubs), _joined_u8(signatures)),
        payloads,
        len(pubs),
    )
