"""The chaos scenario corpus: composed, reproducible adversity.

Every scenario drives a :class:`~hashgraph_tpu.sim.cluster.SimCluster`
through real traffic while injecting one family of faults, then hands
the cluster to the four machine-checked verdicts
(:mod:`hashgraph_tpu.sim.verdicts`): convergence, exact-culprit
accountability, honest-decision safety, and bounded-decide /
zero-stale-conviction liveness. ``run_scenario(name, seed)`` is
a pure function of its arguments — same seed, byte-identical verdict
JSON — which is what makes the corpus a regression harness rather than
a demo: `bench.py chaos` and `make chaos-smoke` run it at pinned seeds,
and any future PR that breaks a failure path breaks a deterministic
assert, not a flake.

The corpus (≥ the ISSUE's eight):

- ``partition-heal``        — symmetric split, per-side progress, heal
- ``asymmetric-partition``  — requests deliver, responses die (one-way)
- ``storm``                 — drop + duplicate + reorder on every link
- ``crash-restart-wal``     — kill -9 mid-append (torn tail), WAL recovery
- ``crash-restart-catchup`` — disk loss, snapshot+tail catch-up escalation
- ``equivocator``           — signed double-voting, faulty + verified evidence
- ``forker``                — divergent chain delivery, fork evidence
- ``expired-spam-burst``    — expired gossip + in-flight signature corruption
- ``columnar-wire-storm``   — mutated OP_VOTE_BATCH frames convicted by the
  COLUMNAR wire validator (zero-copy server path, wire_columnar pinned on)
- ``timeout-liveness``      — embedder timeouts decide identically everywhere
- ``tiered-crash-recovery`` — kill-9 with demoted sessions (WAL recovery) +
  lost-disk catch-up from tiered sources, fingerprint equality throughout
- ``slo-burn``              — hot-shard overload against a declared decide
  objective: burn-rate alert fires, clears on heal, ONE incident dump
- ``flapping-links``        — a peer's links flap far outside its heartbeat
  cadence but far under the binary stale floor: only φ-accrual can see it,
  and the suspicion must clear itself on heal
- ``slow-never-dead``       — a slow-but-alive peer whose adapted φ history
  tolerates a silence that convicts its metronome-cadence neighbours
- ``stale-partial-synchrony`` — a stall past BOTH detectors (φ and the
  binary floor); after GST the convictions must clear with zero operator
  action — the liveness verdict at full strength

A corpus run can also prove the harness is not blind to itself:
``blind=True`` disables the health/evidence layer (the deliberately
broken injector-run of the acceptance criteria) and the accountability
verdict MUST fail.
"""

from __future__ import annotations

import shutil
import tempfile

from ..obs.health import GRADE_FAULTY, GRADE_SUSPECT
from ..wal import scan
from .byzantine import ByzantineActor
from .cluster import SimCluster
from .verdicts import (
    accountability_verdict,
    convergence_verdict,
    liveness_verdict,
    safety_verdict,
)


def _blind(cluster: SimCluster) -> None:
    """The deliberately-broken run: replay mode pauses every engine's
    health accounting, so injected misbehavior leaves no scorecard or
    evidence trail — the accountability verdict must catch the silence."""
    for peer in cluster.peers:
        peer.engine.set_replay_mode(True)


def _finish(
    cluster: SimCluster,
    culprits: "dict[str, str]",
    checks: "dict[str, bool] | None" = None,
    detail: "dict | None" = None,
) -> dict:
    traffic = cluster.drain_all()
    convergence = convergence_verdict(cluster)
    accountability = accountability_verdict(cluster, culprits)
    safety = safety_verdict(cluster)
    # Liveness runs LAST: "the network has stabilized" means after
    # convergence's repair rounds have run.
    liveness = liveness_verdict(cluster)
    checks = dict(checks or {})
    passed = (
        convergence["ok"]
        and accountability["ok"]
        and safety["ok"]
        and liveness["ok"]
        and all(checks.values())
    )
    return {
        "passed": passed,
        "verdicts": {
            "convergence": convergence,
            "accountability": accountability,
            "safety": safety,
            "liveness": liveness,
        },
        "checks": checks,
        "network": cluster.network.stats.as_dict(),
        "traffic": traffic,
        "detail": dict(detail or {}),
    }


# ── scenario bodies: (cluster) -> (culprits, checks, detail) ───────────


def _partition_heal(c: SimCluster):
    pre = c.create_session(c.peer(0), "pre")
    c.vote_all(pre)
    c.network.partition(["p0", "p1"], ["p2", "p3"])
    left = c.create_session(c.peer(0), "left")
    for i in (0, 1):
        c.cast_vote(left, c.peer(i), True)
    right = c.create_session(c.peer(2), "right")
    for i in (2, 3):
        c.cast_vote(right, c.peer(i), True)
    blocked_mid = c.network.stats.blocked
    c.network.heal_partition()
    c.anti_entropy_round()
    for i in (2, 3):
        c.cast_vote(left, c.peer(i), True)
    for i in (0, 1):
        c.cast_vote(right, c.peer(i), True)
    return {}, {"partition_dropped_frames": blocked_mid > 0}, {
        "blocked_during_partition": blocked_mid
    }


def _asymmetric_partition(c: SimCluster):
    pre = c.create_session(c.peer(0), "pre")
    c.vote_all(pre)
    # One-way: frames FROM p1/p2/p3 TO p0 die. p0's own requests still
    # EXECUTE on the others — only the answers are lost, so p0 mutates
    # the world while believing every call failed.
    c.network.partition(["p1", "p2", "p3"], ["p0"], bidirectional=False)
    outbound = c.create_session(c.peer(0), "outbound")
    c.vote_all(outbound)
    hidden = c.create_session(c.peer(1), "hidden")
    for i in (1, 2, 3):
        c.cast_vote(hidden, c.peer(i), True)
    lost_mid = c.network.stats.response_lost + c.network.stats.blocked
    c.network.heal_partition()
    c.anti_entropy_round()
    for session in (outbound, hidden):
        c.vote_all(session)
    return {}, {"asymmetric_loss_observed": lost_mid > 0}, {
        "lost_during_partition": lost_mid
    }


def _storm(c: SimCluster):
    names = [p.name for p in c.peers]
    pre = c.create_session(c.peer(0), "pre")
    c.vote_all(pre)
    c.network.set_all_links(names, drop_p=0.2, dup_p=0.25, jitter=3)
    for k in range(3):
        session = c.create_session(c.peer(k % len(c.peers)), f"storm-{k}")
        c.vote_all(session, values=[True, True, True, False])
    stats = c.network.stats
    dropped, duplicated = stats.dropped, stats.duplicated
    c.network.clear_faults()
    for session in c.sessions:
        c.vote_all(session)  # finish the turns the storm ate
    return {}, {
        "storm_dropped_frames": dropped > 0,
        "storm_duplicated_frames": duplicated > 0,
    }, {"dropped": dropped, "duplicated": duplicated}


def _crash_restart_wal(c: SimCluster):
    pre = c.create_session(c.peer(0), "pre")
    c.vote_all(pre)
    victim = c.peer(1)
    target = c.create_session(c.peer(0), "crashy")
    for i in (0, 2):
        c.cast_vote(target, c.peer(i), True)
    # kill -9 mid-append: the victim's own vote tears on disk.
    wal_directory = victim.durable.wal.directory
    victim.crash_mid_append(target, torn_bytes=9)
    torn = scan(wal_directory).torn_bytes
    while_down = c.create_session(c.peer(2), "while-down")
    c.vote_all(while_down)
    victim.restart()
    recovery = victim.last_recovery
    c.cast_vote(target, victim, True)
    c.cast_vote(while_down, victim, True)
    return {}, {
        "torn_write_on_disk": torn > 0,
        "recovery_replayed_records": recovery.records_applied > 0,
        "recovery_clean": not recovery.errors
        and recovery.segments_dropped == 0,
    }, {
        "torn_bytes": torn,
        "records_replayed": recovery.records_applied,
        "votes_replayed": recovery.votes_replayed,
    }


def _crash_restart_catchup(c: SimCluster):
    for k in range(5):
        session = c.create_session(c.peer(k % 3), f"hist-{k}")
        c.vote_all(session)
    victim = c.peer(3)
    victim.crash()
    while_down = c.create_session(c.peer(0), "while-down")
    c.vote_all(while_down)
    victim.restart(wipe=True)  # the disk is gone: rejoin as a fresh peer
    # The fresh node's first repair round must escalate to a full
    # snapshot+tail catch-up (CatchUpClient over the sim fabric) instead
    # of absorbing the history as thousands of deliver frames.
    victim.node.anti_entropy(c.now)
    c.run_network()
    occupancy = victim.engine.occupancy()
    return {}, {
        "catchup_escalated": c.catchups >= 1,
        "sessions_installed": occupancy.get("live_sessions", 0) >= 5,
    }, {
        "catchups": c.catchups,
        "sessions_after_catchup": occupancy.get("live_sessions", 0),
    }


def _equivocator(c: SimCluster):
    byz = ByzantineActor(c)
    pre = c.create_session(c.peer(0), "pre")
    c.vote_all(pre)
    target = c.create_session(c.peer(0), "target")
    c.cast_vote(target, c.peer(0), True)
    byz.equivocate(target)
    for i in (1, 2):
        c.cast_vote(target, c.peer(i), True)
    culprit = byz.identity.hex()
    alert_everywhere = all(
        any(
            alert["rule"] == "peer-faulty"
            for alert in peer.monitor.evaluate_alerts(now=c.now)
        )
        for peer in c.live_peers()
    )
    evidence_everywhere = all(
        peer.monitor.evidence_count() >= 1 for peer in c.live_peers()
    )
    return {culprit: GRADE_FAULTY}, {
        "peer_faulty_alert_everywhere": alert_everywhere,
        "evidence_everywhere": evidence_everywhere,
    }, {"culprit": culprit}


def _forker(c: SimCluster):
    byz = ByzantineActor(c)
    target = c.create_session(c.peer(0), "forked")
    for i in (0, 1):
        c.cast_vote(target, c.peer(i), True)
    byz.join(target)  # the forker's legitimate vote — its fork replaces it
    c.cast_vote(target, c.peer(2), True)
    byz.fork_deliver(target)
    culprit = byz.identity.hex()
    evidence_everywhere = all(
        peer.monitor.evidence_count() >= 1 for peer in c.live_peers()
    )
    return {culprit: GRADE_SUSPECT}, {
        "fork_evidence_everywhere": evidence_everywhere,
    }, {"culprit": culprit}


def _expired_spam_burst(c: SimCluster):
    byz = ByzantineActor(c)
    live = c.create_session(c.peer(0), "live")
    for i in (0, 1):
        c.cast_vote(live, c.peer(i), True)
    byz.arm_frame_mutation()
    byz.signature_burst(live, count=5)
    byz.expired_spam("junk", count=4)
    culprit = byz.identity.hex()
    cards = [
        peer.monitor.scorecard(byz.identity) or {}
        for peer in c.live_peers()
    ]
    burst_alert = all(
        any(
            alert["rule"] == "invalid-signature-burst"
            for alert in peer.monitor.evaluate_alerts(now=c.now)
        )
        for peer in c.live_peers()
    )
    for i in (2, 3):
        c.cast_vote(live, c.peer(i), True)
    return {culprit: GRADE_SUSPECT}, {
        "invalid_signatures_scored": all(
            card.get("invalid_signatures", 0) >= 4 for card in cards
        ),
        "expired_gossip_scored": all(
            card.get("expired_gossip", 0) >= 1 for card in cards
        ),
        "signature_burst_alert": burst_alert,
        "frames_mutated": c.network.stats.mutated > 0,
    }, {"culprit": culprit, "mutated_frames": c.network.stats.mutated}


def _columnar_wire_storm(c: SimCluster):
    """OP_VOTE_BATCH frames through the COLUMNAR server path with link
    mutation armed: the byte-mutation injector's corrupted signatures
    must be convicted by the columnar validator (native parser or its
    Python twin — the cluster pins wire_columnar=True), not the object
    path, and all three verdicts must still hold."""
    from ..obs import WIRE_COLUMNAR_FRAMES_TOTAL, WIRE_FALLBACK_FRAMES_TOTAL
    from ..obs import registry as _registry

    frames0 = _registry.counter(WIRE_COLUMNAR_FRAMES_TOTAL).value
    fallback0 = _registry.counter(WIRE_FALLBACK_FRAMES_TOTAL).value
    byz = ByzantineActor(c)
    pre = c.create_session(c.peer(0), "pre")
    c.vote_all(pre)
    live = c.create_session(c.peer(0), "live")
    for i in (0, 1):
        c.cast_vote(live, c.peer(i), True)
    byz.arm_frame_mutation()
    byz.signature_burst(live, count=5)
    culprit = byz.identity.hex()
    cards = [
        peer.monitor.scorecard(byz.identity) or {} for peer in c.live_peers()
    ]
    burst_alert = all(
        any(
            alert["rule"] == "invalid-signature-burst"
            for alert in peer.monitor.evaluate_alerts(now=c.now)
        )
        for peer in c.live_peers()
    )
    for i in (2, 3):
        c.cast_vote(live, c.peer(i), True)
    columnar = (
        _registry.counter(WIRE_COLUMNAR_FRAMES_TOTAL).value - frames0
    )
    fallback = (
        _registry.counter(WIRE_FALLBACK_FRAMES_TOTAL).value - fallback0
    )
    return {culprit: GRADE_SUSPECT}, {
        # The point of the scenario: the mutated frames went through the
        # columnar decode+validate path (mutated signatures stay
        # canonical bytes, so nothing should have fallen back), and the
        # rejects were scored against the claimed signer.
        "columnar_path_decoded_frames": columnar > 0,
        "no_object_path_fallbacks": fallback == 0,
        "frames_mutated": c.network.stats.mutated > 0,
        "invalid_signatures_scored": all(
            card.get("invalid_signatures", 0) >= 4 for card in cards
        ),
        "signature_burst_alert": burst_alert,
    }, {
        "culprit": culprit,
        "columnar_frames": columnar,
        "mutated_frames": c.network.stats.mutated,
    }


def _roll_deploy(c: SimCluster):
    """Rolling deploy: restart every host ONE AT A TIME under sustained
    traffic — each peer in turn is kill-9'd (WAL handles abandoned, no
    final fsync) and brought back through real WAL recovery while the
    survivors keep creating and deciding sessions. The federation
    acceptance shape: zero lost decisions (every session decides, and
    identically, on every peer) and cross-host fingerprint equality
    after the LAST heal."""
    pre = c.create_session(c.peer(0), "pre")
    c.vote_all(pre)
    n = len(c.peers)
    recoveries = []
    for k in range(n):
        victim = c.peer(k)
        # Traffic DURING the roll: a session created before the restart
        # reaches quorum among the other peers (ceil(2n/3) of n needs no
        # single fixed voter), one created while the victim is down is
        # ferried around it, and both must repair onto the restarted
        # peer afterwards.
        rolling = c.create_session(c.peer((k + 1) % n), f"roll-{k}")
        for i, peer in enumerate(c.peers):
            if peer is victim or peer.crashed:
                continue
            c.cast_vote(rolling, peer, True)
        victim.crash()
        while_down = c.create_session(c.peer((k + 1) % n), f"down-{k}")
        c.vote_all(while_down)
        victim.restart()  # the real ADD_PEER -> recover() replay path
        recoveries.append(victim.last_recovery)
        c.anti_entropy_round()
    heal = c.converge()
    # Zero lost decisions: every session created during the roll is
    # DECIDED, identically, on every (now live) peer.
    lost = []
    for session in c.sessions:
        results = c.results(session)
        values = set(results.values())
        if len(values) != 1 or not isinstance(next(iter(values)), bool):
            lost.append({session.scope: results})
    return {}, {
        "every_host_restarted": all(p.restarts >= 1 for p in c.peers),
        "recoveries_clean": all(
            r is not None and not r.errors and r.segments_dropped == 0
            for r in recoveries
        ),
        "zero_lost_decisions": not lost,
        "healed_after_last_restart": heal["ok"],
    }, {
        "restarts": [p.restarts for p in c.peers],
        "sessions": len(c.sessions),
        "heal_rounds": heal["rounds"],
        "lost": lost[:4],
    }


def _tiered_crash_recovery(c: SimCluster):
    """Storage tiering under crashes: a peer DEMOTES decided history to
    its serialized tier, demand-pages one session back under live
    traffic, is kill-9'd with the rest still demoted, and WAL-recovers
    to fingerprint equality (the tier is a rebuildable cache — recovery
    legitimately rebuilds demoted sessions as live, and the
    order-insensitive fingerprint cannot tell). A second victim then
    loses its DISK and rejoins through snapshot+tail catch-up served
    from tiered sources — the snapshot build must read straight through
    the tier."""
    history = [c.create_session(c.peer(k % 3), f"hist-{k}") for k in range(5)]
    for session in history:
        c.vote_all(session)
    victim = c.peer(1)
    demoted = sum(
        bool(victim.engine.demote_session(s.scope, s.pid)) for s in history
    )
    # Demand-page under traffic: a live session is demoted mid-vote and
    # the next votes (incl. the victim's own cast) must promote + apply
    # exactly as if it had never left.
    live = c.create_session(c.peer(0), "live")
    for i in (0, 2):
        c.cast_vote(live, c.peer(i), True)
    victim.engine.demote_session(live.scope, live.pid)
    promotions0 = victim.engine.occupancy()["tier_promotions_total"]
    for i in (1, 3):
        c.cast_vote(live, c.peer(i), True)
    promotions = victim.engine.occupancy()["tier_promotions_total"] - promotions0
    tier_at_crash = victim.engine.occupancy()["tier_sessions"]
    victim.crash()
    while_down = c.create_session(c.peer(2), "while-down")
    c.vote_all(while_down)
    victim.restart()  # WAL recovery with demoted history in the log
    recovery = victim.last_recovery
    c.cast_vote(while_down, victim, True)
    # Lost-disk joiner: every surviving source demotes the history, so
    # the catch-up snapshot is built from tiered engines.
    joiner = c.peer(3)
    for peer in c.live_peers():
        if peer is joiner:
            continue
        for session in history:
            try:
                peer.engine.demote_session(session.scope, session.pid)
            except Exception:
                pass  # already demoted / evicted — the tier is policy
    joiner.crash()
    joiner.restart(wipe=True)
    joiner.node.anti_entropy(c.now)
    c.run_network()
    occupancy = joiner.engine.occupancy()
    return {}, {
        "history_demoted": demoted >= 4,
        "demand_page_promoted": promotions >= 1,
        "demoted_at_crash": tier_at_crash >= 1,
        "recovery_clean": not recovery.errors and recovery.segments_dropped == 0,
        "recovery_replayed_records": recovery.records_applied > 0,
        "catchup_escalated": c.catchups >= 1,
        "joiner_reinstalled_history": occupancy.get("live_sessions", 0)
        + occupancy.get("tier_sessions", 0) >= 5,
    }, {
        "demoted": demoted,
        "tier_at_crash": tier_at_crash,
        "promotions": promotions,
        "records_replayed": recovery.records_applied,
        "catchups": c.catchups,
    }


def _timeout_liveness(c: SimCluster):
    # expected_voters past the live peer count: the session can only
    # decide through the embedder's timeout duty.
    target = c.create_session(c.peer(0), "needs-timeout", voters=8)
    c.vote_all(target)
    c.converge()  # every peer must time out on the same view
    fired = c.fire_timeout(target)
    results = c.results(target)
    decided = {
        name: value for name, value in results.items()
        if isinstance(value, bool)
    }
    return {}, {
        "every_peer_decided_at_timeout": len(decided) == len(c.live_peers()),
        "timeout_decisions_agree": len(set(decided.values())) <= 1,
    }, {"fired": fired, "results_after_timeout": {
        k: results[k] for k in sorted(results)
    }}


def _slo_burn(c: SimCluster):
    """Deterministic hot-shard overload against a declared decide-latency
    objective: a private :class:`~hashgraph_tpu.obs.slo.SloEngine` rides
    the cluster's VIRTUAL clock (ticks as seconds — no wall time, so the
    alert trajectory is a pure function of the seed) while real consensus
    traffic supplies the trace ids. Healthy baseline -> injected slowdown
    (every decision breaches) -> the multi-window burn-rate alert MUST
    fire; heal -> the fast window recovers and the alert MUST clear; and
    the breach storm collapses into exactly ONE incident dump whose
    ``incident.json`` links the breaching decision's trace id."""
    import json as _json
    import os

    from ..obs.slo import IncidentCapture, SloEngine

    clock = lambda: float(c.now)  # noqa: E731 — the cluster's virtual clock
    incident_root = os.path.join(c.root, "incidents")
    slo = SloEngine(
        clock=clock,
        capture=IncidentCapture(
            incident_root, cooldown_s=10**9, clock=clock
        ),
    )
    hot_scope = "chaos/hot"
    objective_s = 0.05  # a 50ms decide p99 objective on the hot scope

    def decide(tag: str) -> "str | None":
        session = c.create_session(c.peer(0), tag)
        c.vote_all(session)
        ctx = session.origin.engine.trace_context_of(
            session.scope, session.pid
        )
        return ctx.trace_id.hex() if ctx is not None else None

    # Phase 1 — healthy baseline: 30 decisions at 5ms over 900 virtual
    # seconds fill the slow window with in-objective traffic.
    for k in range(30):
        trace = decide(f"warm-{k}")
        slo.observe(
            hot_scope, 0.005, shard="hot", objective_s=objective_s,
            trace_hex=trace, now=clock(),
        )
        c.advance_clock(30)

    # Phase 2 — overload: every decision takes 500ms (10x the
    # objective). Both burn windows must cross the threshold.
    breach_trace = None
    for k in range(10):
        trace = decide(f"slow-{k}")
        if breach_trace is None:
            breach_trace = trace
        slo.observe(
            hot_scope, 0.5, shard="hot", objective_s=objective_s,
            trace_hex=trace, now=clock(),
        )
        c.advance_clock(10)
    overload_state = slo.state(now=clock())
    fired_during_overload = hot_scope in overload_state["alerts_firing"]

    # Phase 3 — heal: jump past the fast window, resume healthy traffic;
    # the fast-window burn collapses and the alert clears.
    c.advance_clock(400)
    for k in range(10):
        trace = decide(f"heal-{k}")
        slo.observe(
            hot_scope, 0.005, shard="hot", objective_s=objective_s,
            trace_hex=trace, now=clock(),
        )
        c.advance_clock(10)
    healed_state = slo.state(now=clock())
    hot = healed_state["scopes"][hot_scope]

    incidents = slo.capture.incidents()
    incident_meta = {}
    trace_doc = {}
    if len(incidents) == 1:
        inc_dir = os.path.join(incident_root, incidents[0])
        with open(os.path.join(inc_dir, "incident.json")) as fh:
            incident_meta = _json.load(fh)
        with open(os.path.join(inc_dir, "trace.json")) as fh:
            trace_doc = _json.load(fh)
    return {}, {
        "alert_fired_during_overload": fired_during_overload,
        "alert_cleared_after_heal": hot["alert_firing"] is False,
        "exactly_one_alert_episode": hot["alerts_total"] == 1,
        "exactly_one_incident_dump": len(incidents) == 1,
        "incident_links_breaching_trace": (
            breach_trace is not None
            and incident_meta.get("trace_id") == breach_trace
        ),
        "incident_trace_perfetto_loadable": "traceEvents" in trace_doc,
        "incident_flight_ring_dumped": bool(incidents)
        and os.path.exists(
            os.path.join(incident_root, incidents[0], "flight.jsonl")
        ),
    }, {
        "burn_fast_overload": round(
            overload_state["scopes"][hot_scope]["burn_fast"], 3
        ),
        "burn_fast_healed": round(hot["burn_fast"], 3),
        "breaches_total": hot["breaches_total"],
        "incidents": incidents,
    }


def _flapping_links(c: SimCluster):
    """Link flapping: a healthy peer's links die for a stretch that is
    ~12x its observed heartbeat cadence but four orders of magnitude
    UNDER the binary stale floor (the sessions' 500_000-tick timeout
    hint) — only the φ-accrual detector can see the silence. The
    suspicion must cross the threshold while the links flap (the
    ``peer-suspect-phi`` alert fires on every survivor), the binary
    floor must stay untouched, and the conviction must clear ITSELF the
    moment the links heal and heartbeats resume — zero stale
    convictions survive into the liveness verdict."""
    flappy = c.peer(3)
    others = [c.peer(i) for i in (0, 1, 2)]
    order = [c.peer(0), c.peer(1), c.peer(2), flappy]
    # Warm cadence: rotation-cast 3 of 4 voters per round (a session
    # decides at the 3rd vote — quorum — so a 4th cast would be
    # absorbed unadmitted and earn NO heartbeat) at 10-tick steps;
    # every peer accrues >= min_samples inter-arrival history.
    for k in range(12):
        session = c.create_session(others[k % 3], f"warm-{k}")
        rot = order[k % 4:] + order[: k % 4]
        for voter in rot[:3]:
            c.cast_vote(session, voter, True)
        c.advance_clock(10)
    # Carrier sessions, created FULL-MESH before the flap with
    # expected_voters past the peer count (undecidable by votes):
    # partition-era traffic must be vote-EXTENDS, whose canonical
    # tick-stamped bytes repair byte-identically at any later tick —
    # creating sessions behind a partition and advancing the clock
    # would stamp the repaired copies at repair time and break
    # fingerprint equality (the sim's no-wall-clock contract).
    carriers = [
        c.create_session(c.peer(0), f"carrier-{k}", voters=8)
        for k in range(3)
    ]
    # Flap: flappy's links die both ways. The survivors keep
    # heartbeating (one vote each per carrier round) while flappy's
    # silence grows to ~10x its observed mean inter-arrival.
    c.network.partition(["p0", "p1", "p2"], [flappy.name])
    for carrier in carriers:
        for peer in others:
            c.cast_vote(carrier, peer, True)
        c.advance_clock(40)
    flap_now = c.now
    cards = [
        peer.monitor.snapshot(now=flap_now)["peers"].get(
            flappy.identity.hex(), {}
        )
        for peer in others
    ]
    phi_alert = all(
        any(
            alert["rule"] == "peer-suspect-phi"
            for alert in peer.monitor.evaluate_alerts(now=flap_now)
        )
        for peer in others
    )
    suspected = all(
        card.get("phi", 0.0) >= (card.get("phi_threshold") or float("inf"))
        for card in cards
    )
    # The scenario's point: the silence is invisible to the binary
    # detector (silence << the per-peer floor), yet phi convicted.
    floor_quiet = all(
        (flap_now - card.get("last_seen", 0)) <= card.get("stale_after", 0)
        for card in cards
    )
    # Heal: links return, anti-entropy extends the carrier chains onto
    # flappy, and fresh traffic — flappy casting FIRST, before quorum —
    # resumes its heartbeats; read-time grading clears the suspicion
    # with zero operator action.
    c.network.heal_partition()
    c.anti_entropy_round()
    for k in range(3):
        session = c.create_session(flappy, f"heal-{k}")
        for voter in (flappy, others[k % 3], others[(k + 1) % 3]):
            c.cast_vote(session, voter, True)
        c.advance_clock(10)
    # Settle the carriers: timeout them on a converged view so every
    # peer decides them identically at one tick (the timeout-liveness
    # precedent) — the liveness verdict then sees them decided, not
    # dangling.
    c.converge()
    for carrier in carriers:
        c.fire_timeout(carrier)
    healed = [
        peer.monitor.snapshot(now=c.now)["peers"].get(
            flappy.identity.hex(), {}
        )
        for peer in others
    ]
    cleared = all(
        card.get("phi", 0.0) < (card.get("phi_threshold") or float("inf"))
        and card.get("grade") == "healthy"
        for card in healed
    )
    return {}, {
        "phi_suspected_during_flap": suspected,
        "phi_alert_during_flap": phi_alert,
        "binary_floor_untouched": floor_quiet,
        "suspicion_cleared_after_heal": cleared,
    }, {
        "phi_during_flap": [card.get("phi") for card in cards],
        "phi_after_heal": [card.get("phi") for card in healed],
        "silence_during_flap": [
            flap_now - card.get("last_seen", 0) for card in cards
        ],
    }


def _slow_never_dead(c: SimCluster):
    """A slow-but-alive peer: its heartbeat cadence is ~4-5x the dense
    peers', with genuine jitter, so its φ-accrual history ADAPTS — a
    60-tick probe silence that maxes phi for a 10-tick-metronome peer
    stays unremarkable for it. The slow peer must never be suspected
    (by phi or the floor) while the same probe silence flags its dense
    neighbours — per-peer learned tolerance is the whole point of
    accrual over a global timeout."""
    from ..obs.accrual import phi_from_deviation

    slow = c.peer(3)
    dense = [c.peer(0), c.peer(1)]
    threshold = c.peer(0).monitor.phi_threshold
    # 37 rounds at 10 ticks: p0/p1 vote every round (metronome); the
    # third voting slot alternates p2 / the slow peer, the slow peer on
    # a jittered 40/50-tick schedule (8 intervals, mean 45, std 5 —
    # past min_samples, with real variance). The slow peer votes FIRST
    # in its rounds so its cast is admitted before the session decides.
    slow_rounds = {0, 4, 9, 13, 18, 22, 27, 31, 36}
    for k in range(37):
        session = c.create_session(dense[k % 2], f"cadence-{k}")
        third = slow if k in slow_rounds else c.peer(2)
        for voter in (third, dense[0], dense[1]):
            c.cast_vote(session, voter, True)
        c.advance_clock(10)
    # Probe: a global 60-tick silence. For the slow peer that is
    # (60-45)/5 = 3 standard deviations (phi ~2.9 < threshold); for a
    # metronome peer it is 50 deviations (phi clamps at max).
    c.advance_clock(60)
    probe_now = c.now
    slow_flagged = any(
        slow.identity.hex() in peer.monitor.watchdog(now=probe_now)
        for peer in dense + [c.peer(2)]
    )
    dense_flagged = all(
        dense[1 - i].identity.hex()
        in dense[i].monitor.watchdog(now=probe_now)
        for i in (0, 1)
    )
    slow_phi = max(
        peer.monitor.snapshot(now=probe_now)["peers"]
        .get(slow.identity.hex(), {})
        .get("phi", 0.0)
        for peer in dense
    )
    # The counterfactual, computed not simulated: the slow peer's exact
    # silence at a metronome cadence (mean 10, floor std 1.0) would
    # convict outright.
    counterfactual = phi_from_deviation((60 - 10) / 1.0)
    # Resume: rotation-cast so EVERY peer (the slow one included) gets
    # an admitted vote — vote_all would absorb the 4th cast on an
    # already-decided session and leave one peer heartbeat-less — and
    # the probe-induced suspicion clears before the verdicts read the
    # cluster.
    order = [c.peer(0), c.peer(1), c.peer(2), slow]
    for k in range(4):
        session = c.create_session(dense[k % 2], f"resume-{k}")
        rot = order[k:] + order[:k]
        for voter in rot[:3]:
            c.cast_vote(session, voter, True)
        c.advance_clock(10)
    return {}, {
        "slow_peer_never_suspected": not slow_flagged,
        "dense_cadence_flagged_at_probe": dense_flagged,
        "slow_phi_below_threshold": threshold is not None
        and slow_phi < threshold,
        "metronome_counterfactual_convicts": threshold is not None
        and counterfactual >= threshold,
    }, {
        "slow_phi_at_probe": round(slow_phi, 3),
        "metronome_phi_counterfactual": round(counterfactual, 3),
        "phi_threshold": threshold,
    }


def _stale_partial_synchrony(c: SimCluster):
    """Partial synchrony's pathological stretch: the WHOLE fabric
    stalls past both detectors at once — the logical clock jumps beyond
    the binary floor (the sessions' 500_000-tick timeout hint; the
    cluster pins ``stale_after`` under it so the hint genuinely IS the
    floor) while φ maxes everywhere — so every monitor convicts every
    other peer as stale while the stall lasts. Then GST passes: traffic
    resumes, and BOTH convictions must clear on every monitor with zero
    operator action. A silence-driven conviction that survives GST is
    exactly what the liveness verdict's ``stale_convictions`` list
    exists to catch. (No partition is needed: a global stall is just
    the clock — which also keeps every session's repair tick equal to
    its creation tick, the fingerprint-equality contract.)"""
    order = [c.peer(i) for i in range(4)]
    hexes = [p.identity.hex() for p in order]
    # Warm cadence: rotation-cast (see _flapping_links) — every peer
    # accrues phi history and a fresh last_seen before the stall.
    for k in range(12):
        session = c.create_session(order[k % 4], f"warm-{k}")
        rot = order[k % 4:] + order[: k % 4]
        for voter in rot[:3]:
            c.cast_vote(session, voter, True)
        c.advance_clock(10)
    # The stall: no frames, no votes, and the logical clock jumps past
    # the 500_000-tick floor. Every warm session is already decided, so
    # nothing expires under the jump.
    c.advance_clock(600_001)
    stall_now = c.now
    views = {
        peer.name: peer.monitor.snapshot(now=stall_now)["peers"]
        for peer in order
    }
    cross_cards = [
        views[peer.name].get(hexid, {})
        for peer in order
        for hexid in hexes
        if hexid != peer.identity.hex()
    ]
    floor_tripped = all(
        card.get("stale") is True
        and card.get("stale_after", 0) >= 500_000
        and (stall_now - card.get("last_seen", 0)) > card.get("stale_after", 0)
        for card in cross_cards
    )
    phi_maxed = all(
        card.get("phi", 0.0) >= (card.get("phi_threshold") or float("inf"))
        for card in cross_cards
    )
    convicted_everywhere = all(
        set(hexes) - {peer.identity.hex()}
        <= set(peer.monitor.watchdog(now=stall_now))
        for peer in order
    )
    # GST: traffic resumes (rotation so every peer's cast is admitted
    # somewhere before quorum) — heartbeats land everywhere and
    # read-time grading clears both detectors at once.
    for k in range(4):
        session = c.create_session(order[k % 4], f"gst-{k}")
        rot = order[k:] + order[:k]
        for voter in rot[:3]:
            c.cast_vote(session, voter, True)
        c.advance_clock(10)
    honest = set(hexes)
    lingering = sorted(
        set().union(
            *(
                set(peer.monitor.watchdog(now=c.now)) & honest
                for peer in c.live_peers()
            )
        )
    )
    return {}, {
        "floor_tripped_during_stall": floor_tripped,
        "phi_maxed_during_stall": phi_maxed,
        "stale_convicted_during_stall": convicted_everywhere,
        "convictions_cleared_after_gst": not lingering,
    }, {
        "silence_at_stall": sorted(
            {stall_now - card.get("last_seen", 0) for card in cross_cards}
        ),
        "floor_at_stall": sorted(
            {card.get("stale_after", 0) for card in cross_cards}
        ),
        "lingering_convictions": lingering,
    }


class _Spec:
    __slots__ = ("body", "cluster_kwargs")

    def __init__(self, body, **cluster_kwargs):
        self.body = body
        self.cluster_kwargs = cluster_kwargs


SCENARIOS: "dict[str, _Spec]" = {
    # fanout=2: the sticky per-session sampled fan-out path — peers
    # outside a session's sample miss it wholly and anti-entropy must
    # create it wholesale (the repairable-by-design divergence).
    "partition-heal": _Spec(_partition_heal, fanout=2),
    "asymmetric-partition": _Spec(_asymmetric_partition),
    "storm": _Spec(_storm),
    "crash-restart-wal": _Spec(_crash_restart_wal),
    "crash-restart-catchup": _Spec(_crash_restart_catchup, escalate_sessions=4),
    "equivocator": _Spec(_equivocator),
    "forker": _Spec(_forker),
    "expired-spam-burst": _Spec(_expired_spam_burst),
    # wire_columnar pinned True: the scenario asserts the columnar wire
    # path itself, so the HASHGRAPH_TPU_WIRE_COLUMNAR env override must
    # not be able to change what it measures.
    "columnar-wire-storm": _Spec(_columnar_wire_storm, wire_columnar=True),
    # Rolling restart of every host, one at a time, under traffic — the
    # federation roll-deploy acceptance: zero lost decisions plus
    # cross-host fingerprint equality after the last heal.
    "roll-deploy": _Spec(_roll_deploy),
    "timeout-liveness": _Spec(_timeout_liveness),
    # Kill-9 of a peer holding DEMOTED sessions (WAL recovery), plus a
    # lost-disk joiner catching up from tiered sources — the storage-
    # tiering acceptance: the tier is a cache, fingerprints cannot tell.
    "tiered-crash-recovery": _Spec(
        _tiered_crash_recovery, escalate_sessions=4
    ),
    # Hot-shard SLO overload on the virtual clock: burn-rate alert fires
    # during the slowdown, clears after the heal, exactly one
    # exemplar-linked incident dump — the observability-plane acceptance.
    "slo-burn": _Spec(_slo_burn),
    # φ-accrual liveness battery (ISSUE 18): suspicion that only the
    # accrual detector can see, per-peer learned tolerance, and a stall
    # past BOTH detectors — all three must end with zero stale
    # convictions under the fourth (liveness) verdict.
    "flapping-links": _Spec(_flapping_links),
    "slow-never-dead": _Spec(_slow_never_dead),
    # stale_after pinned UNDER the sessions' timeout hint so the binary
    # floor sits at the hint (500_000 ticks) and the 600_001-tick stall
    # genuinely trips it.
    "stale-partial-synchrony": _Spec(
        _stale_partial_synchrony, stale_after=100_000.0
    ),
}


def run_scenario(
    name: str,
    seed: int,
    *,
    root: "str | None" = None,
    blind: bool = False,
    signer_factory: "type | None" = None,
    overrides: "dict | None" = None,
) -> dict:
    """One scenario at one seed -> the verdict JSON (a dict; serialize
    with ``sort_keys=True`` for the byte-identical determinism check).
    ``blind=True`` disables the health/evidence layer first — the
    harness's self-test that a broken injector run FAILS.
    ``signer_factory`` overrides the cluster's scheme (default stub):
    the device-crypto battery re-runs the signature scenarios with
    ``Ed25519DeviceConsensusSigner`` to prove all four verdicts hold
    when rejects come from the device backend. ``overrides`` merges
    extra SimCluster kwargs over the spec's own — the liveness A/B in
    ``bench.py`` uses ``{"phi_threshold": None}`` to run the
    binary-watchdog-only baseline arm of the same scenario."""
    spec = SCENARIOS[name]
    kwargs = dict(spec.cluster_kwargs)
    if overrides:
        kwargs.update(overrides)
    if signer_factory is not None:
        kwargs["signer_factory"] = signer_factory
    owns_root = root is None
    if owns_root:
        root = tempfile.mkdtemp(prefix=f"hashgraph-chaos-{name}-")
    try:
        with SimCluster(root, seed, **kwargs) as cluster:
            if blind:
                _blind(cluster)
            culprits, checks, detail = spec.body(cluster)
            result = _finish(cluster, culprits, checks, detail)
    finally:
        if owns_root:
            shutil.rmtree(root, ignore_errors=True)
    result["scenario"] = name
    result["seed"] = seed
    result["blind"] = blind
    return result


def run_corpus(
    seeds: "list[int]",
    names: "list[str] | None" = None,
    *,
    blind: bool = False,
) -> dict:
    """The whole corpus × seeds -> the machine-readable summary block
    ``bench.py chaos`` emits: {scenarios: {passed, failed, seeds},
    results, failures}."""
    names = list(SCENARIOS) if names is None else list(names)
    results: dict[str, dict] = {}
    failures: list[dict] = []
    passed = failed = 0
    for name in names:
        per_seed = {}
        for seed in seeds:
            outcome = run_scenario(name, seed, blind=blind)
            per_seed[str(seed)] = outcome["passed"]
            if outcome["passed"]:
                passed += 1
            else:
                failed += 1
                failures.append(
                    {
                        "scenario": name,
                        "seed": seed,
                        "verdicts": {
                            key: verdict["ok"]
                            for key, verdict in outcome["verdicts"].items()
                        },
                        "checks": outcome["checks"],
                    }
                )
        results[name] = per_seed
    return {
        "scenarios": {"passed": passed, "failed": failed, "seeds": seeds},
        "results": results,
        "failures": failures,
    }
