"""SimNetwork + SimTransport: the gossip fabric without sockets.

:class:`SimTransport` implements the :class:`~hashgraph_tpu.gossip.
transport.GossipTransport` surface a :class:`~hashgraph_tpu.gossip.node.
GossipNode` drives — ``connect`` / ``try_request`` / ``request`` /
``channel`` / ``stats`` / ``close`` — but every frame crosses a
:class:`SimNetwork` instead of TCP: delivery is an event on the shared
:class:`~hashgraph_tpu.sim.core.SimScheduler`, and the scenario's fault
injectors act on the link the frame crosses:

- **partitions** (symmetric or one-way): the frame is lost in flight and
  its future fails typed (:class:`BridgeConnectionLost`) at delivery
  time — exactly what a sender observes, while an ASYMMETRIC partition
  still executes the request on the target and loses only the response,
  the hardest case for exactly-once assumptions;
- **drop**: same typed loss, by seeded coin-flip;
- **duplicate**: the frame dispatches twice (the receiving engine must
  settle the duplicate benignly); the second response is discarded;
- **delay / reorder**: seeded jitter on the delivery tick — same-tick
  frames keep scheduling order, jittered frames genuinely reorder;
- **mutation**: a per-link ``mutate(opcode, payload) -> payload`` hook
  rewrites request bytes in flight (the Byzantine signature-burst rides
  this).

Backpressure mirrors the real transport: per-channel byte-capped send
accounting, ``try_request`` *sheds* (returns None) at the cap, and
``request`` raises :class:`~hashgraph_tpu.gossip.transport.ChannelBusy`.

Futures are :class:`SimFuture`: ``result()`` pumps the scheduler instead
of blocking a thread, so the GossipNode's synchronous await-style repair
path (anti-entropy windows, drain) runs unmodified on virtual time.
"""

from __future__ import annotations

from concurrent.futures import Future
from dataclasses import dataclass

from ..bridge import protocol as P
from ..bridge.client import BridgeConnectionLost, BridgeError
from ..gossip.transport import ChannelBusy
from .core import SimScheduler, derived_rng


class SimFuture(Future):
    """A future whose ``result()`` advances VIRTUAL time: it pumps the
    scheduler until resolved, and raises ``TimeoutError`` if the network
    goes idle first (the sim's equivalent of a wall-clock timeout — the
    response provably can never arrive)."""

    def __init__(self, scheduler: SimScheduler):
        super().__init__()
        self._scheduler = scheduler

    def result(self, timeout: float | None = None):
        while not self.done():
            if not self._scheduler.step():
                raise TimeoutError(
                    "sim future unresolved with the network idle"
                )
        return super().result(0)


@dataclass
class LinkFaults:
    """Injected behavior of one directed link (src -> dst). A missing
    entry means a clean link: delivery after ``SimNetwork.base_delay``
    ticks, in order, exactly once."""

    drop_p: float = 0.0
    dup_p: float = 0.0
    jitter: int = 0  # extra delivery ticks drawn uniformly from [0, jitter]
    extra_delay: int = 0
    mutate: object = None  # fn(opcode, payload) -> payload


@dataclass
class NetStats:
    delivered: int = 0
    dropped: int = 0
    blocked: int = 0
    duplicated: int = 0
    response_lost: int = 0
    mutated: int = 0

    def as_dict(self) -> dict:
        return {
            "delivered": self.delivered,
            "dropped": self.dropped,
            "blocked": self.blocked,
            "duplicated": self.duplicated,
            "response_lost": self.response_lost,
            "mutated": self.mutated,
        }


class SimNetwork:
    """Shared fabric: named endpoints + directed-link fault state."""

    def __init__(self, scheduler: SimScheduler, base_delay: int = 1):
        self.scheduler = scheduler
        self.base_delay = base_delay
        self._rng = derived_rng(scheduler.seed, "network")
        self._endpoints: dict[str, object] = {}  # name -> dispatch fn
        self._down: set[str] = set()
        self._blocked: set[tuple[str, str]] = set()
        self._links: dict[tuple[str, str], LinkFaults] = {}
        self.stats = NetStats()

    # ── membership ─────────────────────────────────────────────────────

    def register(self, name: str, dispatch) -> None:
        """Attach an endpoint: ``dispatch(opcode, payload) -> (status,
        payload)`` — a BridgeServer's ``dispatch_frame`` in embedded
        mode."""
        self._endpoints[name] = dispatch
        self._down.discard(name)

    def mark_down(self, name: str) -> None:
        """The endpoint crashed: frames addressed to it are lost (typed)
        until a re-``register``."""
        self._down.add(name)

    def is_up(self, name: str) -> bool:
        return name in self._endpoints and name not in self._down

    # ── fault injection ────────────────────────────────────────────────

    def partition(self, side_a, side_b, *, bidirectional: bool = True) -> None:
        """Block every (a -> b) link; with ``bidirectional`` also every
        (b -> a). One-way blocking is the asymmetric-partition injector."""
        for a in side_a:
            for b in side_b:
                self._blocked.add((a, b))
                if bidirectional:
                    self._blocked.add((b, a))

    def heal_partition(self) -> None:
        self._blocked.clear()

    def set_link(self, src: str, dst: str, **faults) -> None:
        self._links[(src, dst)] = LinkFaults(**faults)

    def set_all_links(self, names, **faults) -> None:
        for src in names:
            for dst in names:
                if src != dst:
                    self.set_link(src, dst, **faults)

    def clear_faults(self) -> None:
        self._links.clear()
        self._blocked.clear()

    def link(self, src: str, dst: str) -> LinkFaults:
        return self._links.get((src, dst)) or _CLEAN

    def blocked(self, src: str, dst: str) -> bool:
        return (src, dst) in self._blocked

    # ── traffic ────────────────────────────────────────────────────────

    def call_direct(self, target: str, opcode: int, payload: bytes):
        """Synchronous fault-free dispatch (a dedicated connection, e.g.
        the catch-up client's): raises ConnectionError when the target is
        down, else returns ``(status, payload)`` immediately."""
        if not self.is_up(target):
            raise ConnectionError(f"sim endpoint {target!r} is down")
        return self._endpoints[target](opcode, payload)

    def send(self, src: str, dst: str, opcode: int, payload: bytes, on_done) -> None:
        """Route one request frame src -> dst under the current fault
        state. ``on_done(result=None, error=None)`` fires EXACTLY ONCE,
        at a scheduled virtual tick; ``result`` is the ``(status,
        payload)`` pair of the FIRST delivery's response."""
        rng = self._rng
        fwd = self.link(src, dst)
        delay = self.base_delay + fwd.extra_delay
        if fwd.jitter:
            delay += rng.randrange(fwd.jitter + 1)
        settled = [False]

        def settle(result=None, error=None):
            if settled[0]:
                return
            settled[0] = True
            on_done(result=result, error=error)

        def lose(counter: str):
            setattr(self.stats, counter, getattr(self.stats, counter) + 1)
            self.scheduler.at(
                delay,
                lambda: settle(error=BridgeConnectionLost(
                    f"frame {src}->{dst} lost ({counter})"
                )),
            )

        if self.blocked(src, dst):
            lose("blocked")
            return
        if fwd.drop_p and rng.random() < fwd.drop_p:
            lose("dropped")
            return
        body = payload
        if fwd.mutate is not None:
            mutated = fwd.mutate(opcode, payload)
            if mutated is not None and mutated != payload:
                self.stats.mutated += 1
                body = mutated
        copies = 1
        if fwd.dup_p and rng.random() < fwd.dup_p:
            copies = 2
            self.stats.duplicated += 1

        def deliver():
            if not self.is_up(dst):
                settle(error=BridgeConnectionLost(
                    f"peer {dst!r} is down"
                ))
                return
            status, out = self._endpoints[dst](opcode, body)
            self.stats.delivered += 1
            rev = self.link(dst, src)
            rdelay = self.base_delay + rev.extra_delay
            if rev.jitter:
                rdelay += rng.randrange(rev.jitter + 1)
            # Response-path faults: the request EXECUTED, only the answer
            # is lost — the asymmetric-partition signature.
            if self.blocked(dst, src) or (
                rev.drop_p and rng.random() < rev.drop_p
            ):
                self.stats.response_lost += 1
                self.scheduler.at(
                    rdelay,
                    lambda: settle(error=BridgeConnectionLost(
                        f"response {dst}->{src} lost"
                    )),
                )
                return
            self.scheduler.at(rdelay, lambda: settle(result=(status, out)))

        for copy in range(copies):
            # A duplicate trails its original by one tick: the receiver
            # must settle the replay benignly (and does — that's the
            # duplicate-rejection path under test).
            self.scheduler.at(delay + copy, deliver)


_CLEAN = LinkFaults()


@dataclass
class _SimChannel:
    name: str
    alive: bool = True
    error: Exception | None = None
    queue_bytes: int = 0
    max_queue_bytes: int = 256 * 1024
    shed_total: int = 0
    inflight: int = 0
    sent: int = 0

    def stats(self) -> dict:
        return {
            "alive": self.alive,
            "pipelined": True,
            "queue_frames": 0,
            "queue_bytes": self.queue_bytes,
            "inflight": self.inflight,
            "shed_total": self.shed_total,
        }


class SimTransport:
    """GossipTransport look-alike over a :class:`SimNetwork`. One per
    node; ``connect`` targets endpoints by NAME (the host argument — the
    sim cluster registers peers under their names and passes
    ``host=name, port=0`` to ``GossipNode.add_peer``)."""

    def __init__(
        self,
        network: SimNetwork,
        owner: str,
        *,
        max_queue_bytes: int = 256 * 1024,
    ):
        self._network = network
        self.owner = owner
        self._max_queue_bytes = max_queue_bytes
        self._channels: dict[str, _SimChannel] = {}
        self._closed = False

    # ── GossipTransport surface ────────────────────────────────────────

    def connect(self, name: str, host: str, port: int) -> _SimChannel:
        if self._closed:
            raise RuntimeError("transport is closed")
        target = host or name
        if not self._network.is_up(target):
            raise ConnectionError(f"sim endpoint {target!r} is not up")
        old = self._channels.get(name)
        if old is not None and old.alive:
            raise ValueError(f"peer {name!r} already connected")
        channel = _SimChannel(name, max_queue_bytes=self._max_queue_bytes)
        self._channels[name] = channel
        return channel

    def channel(self, name: str) -> _SimChannel | None:
        return self._channels.get(name)

    def stats(self) -> dict:
        return {name: ch.stats() for name, ch in self._channels.items()}

    def try_request(
        self, name: str, opcode: int, payload: "bytes | list" = b""
    ) -> "SimFuture | None":
        channel = self._channels.get(name)
        if channel is None:
            raise KeyError(f"unknown peer {name!r}")
        if not isinstance(payload, (bytes, bytearray, memoryview)):
            # Segment lists (send-side zero-copy on the real transport)
            # join here: the sim ships whole payloads through its
            # virtual-time network, and the mutation/digest hooks want
            # one contiguous byte string.
            payload = b"".join(payload)
        future = SimFuture(self._network.scheduler)
        if not channel.alive:
            future.set_exception(
                channel.error
                or BridgeConnectionLost(f"peer {name!r} disconnected")
            )
            return future
        size = len(payload) + 9
        if channel.queue_bytes + size > channel.max_queue_bytes:
            channel.shed_total += 1
            return None
        channel.queue_bytes += size
        channel.inflight += 1
        channel.sent += 1

        def on_done(result=None, error=None):
            channel.queue_bytes -= size
            channel.inflight -= 1
            if future.done():
                return
            if error is not None:
                future.set_exception(error)
                return
            status, out = result
            if status == P.STATUS_OK:
                future.set_result(P.Cursor(out))
            else:
                message = ""
                try:
                    message = P.Cursor(out).string()
                except ValueError:
                    pass
                future.set_exception(BridgeError(status, message))

        self._network.send(self.owner, name, opcode, payload, on_done)
        return future

    def request(self, name: str, opcode: int, payload: bytes = b"") -> SimFuture:
        future = self.try_request(name, opcode, payload)
        if future is None:
            raise ChannelBusy(f"peer {name!r} send queue is full")
        return future

    def kill_channel(self, name: str, reason: str = "peer crashed") -> None:
        """Mark one channel dead (new requests fail typed until the
        harness reconnects it) — the sim-side analogue of a TCP reset."""
        channel = self._channels.get(name)
        if channel is not None:
            channel.alive = False
            channel.error = BridgeConnectionLost(reason)

    def reconnect(self, name: str) -> None:
        """Replace a dead channel (the harness's explicit heal, mirroring
        the real transport's ReconnectPolicy re-dial)."""
        channel = self._channels.get(name)
        if channel is not None and channel.alive:
            return
        self._channels.pop(name, None)
        self.connect(name, name, 0)

    def close(self) -> None:
        self._closed = True
        for channel in self._channels.values():
            channel.alive = False
            channel.error = BridgeConnectionLost("transport closed")


class SimBridgeAdapter:
    """BridgeClient-shaped state-sync surface over the sim network: the
    injectable ``bridge`` a :class:`~hashgraph_tpu.sync.CatchUpClient`
    rides so the snapshot/tail catch-up path itself — manifests, chunk
    digests, LSN continuity — runs live inside a deterministic scenario.
    Dedicated connection semantics: synchronous, fault-free, but a down
    endpoint still raises ``ConnectionError``."""

    def __init__(self, network: SimNetwork, target: str):
        self._network = network
        self._target = target

    def _call(self, opcode: int, payload: bytes) -> P.Cursor:
        status, out = self._network.call_direct(self._target, opcode, payload)
        if status != P.STATUS_OK:
            message = ""
            try:
                message = P.Cursor(out).string()
            except ValueError:
                pass
            raise BridgeError(status, message)
        return P.Cursor(out)

    def sync_manifest(self, peer: int, max_chunk_bytes: int = 0) -> dict:
        from ..bridge.client import parse_sync_manifest

        return parse_sync_manifest(
            self._call(P.OP_SYNC_MANIFEST, P.u32(peer) + P.u32(max_chunk_bytes))
        )

    def sync_chunk(self, peer: int, snapshot_id: int, index: int) -> bytes:
        return self._call(
            P.OP_SYNC_CHUNK, P.u32(peer) + P.u64(snapshot_id) + P.u32(index)
        ).blob()

    def wal_tail(self, peer: int, after_lsn: int, max_bytes: int = 0):
        cursor = self._call(
            P.OP_WAL_TAIL, P.u32(peer) + P.u64(after_lsn) + P.u32(max_bytes)
        )
        records = []
        for _ in range(cursor.u32()):
            lsn = cursor.u64()
            kind = cursor.u8()
            records.append((lsn, kind, cursor.blob()))
        return records, bool(cursor.u8())

    def close(self) -> None:
        pass
