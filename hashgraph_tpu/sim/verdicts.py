"""The four machine-checked verdicts every chaos scenario must pass.

1. **Convergence** — every honest live peer reaches state-fingerprint
   equality (order-insensitive digest over the exact canonical session
   bytes, read over ``OP_STATE_FINGERPRINT``).
2. **Accountability** — the union of the honest peers' health
   convictions (:meth:`HealthMonitor.convicted_peers`) names EXACTLY the
   injected culprits, each at (or past) the grade its misbehavior
   earns, with every retained :class:`EvidenceRecord` verifying OFFLINE
   (:func:`verify_evidence_record` re-checks the signed byte pairs with
   nothing but the scheme — the Polygraph property), and ZERO honest
   peers convicted.
3. **Safety** — no two honest peers decide the same session differently
   (True on one, False on another). Undecided / failed-by-local-timeout
   states are liveness, not safety, and are reported but not violations.
4. **Liveness** — once the network has stabilized (the verdicts run
   after convergence's repair rounds), every session that decided
   ANYWHERE is decided EVERYWHERE, every decision landed within a
   seed-deterministic tick bound of its creation
   (:attr:`SimCluster.decision_ticks`), and ZERO honest peers remain
   under a watchdog conviction — φ-accrual or binary-floor — at verdict
   time (a silence-driven suspicion that survives the heal is a stale
   conviction, the exact failure the read-time grading exists to
   prevent).

A harness must be able to detect its own blindness: a run whose
injectors fired but whose evidence layer was disabled FAILS verdict 2
(culprits uncovered), which is exactly what the corpus's
``blind``-mode self-test asserts.
"""

from __future__ import annotations

from ..obs.health import GRADE_FAULTY, _GRADE_RANK
from ..protocol import compute_vote_hash
from ..wire import Vote
from .cluster import SimCluster


def verify_evidence_record(record: dict, scheme) -> "tuple[bool, str]":
    """Offline re-verification of one evidence record (as_dict form):
    decode the retained byte pair and check it proves what it claims,
    holding nothing but the signature scheme. Returns (ok, reason)."""
    try:
        a = Vote.decode(bytes.fromhex(record["vote_a"]))
        b = Vote.decode(bytes.fromhex(record["vote_b"]))
    except (ValueError, IndexError) as exc:
        return False, f"undecodable evidence bytes: {exc!r}"
    offender = record["offender"]
    if a.vote_hash == b.vote_hash:
        return False, "retained pair does not conflict (equal hashes)"
    # Both kinds meet the double-sign bar: equivocations pair the two
    # conflicting votes the vote path admitted; fork records pair the
    # offender's ACCEPTED vote with its divergent one. Either way the
    # pair proves misbehavior only if both sides are the offender's own
    # validly-signed votes for one proposal.
    if a.vote_owner.hex() != offender or b.vote_owner.hex() != offender:
        return False, f"{record['kind']} pair not owned by the offender"
    if a.proposal_id != b.proposal_id:
        return False, f"{record['kind']} pair spans proposals"
    for side, vote in (("a", a), ("b", b)):
        if compute_vote_hash(vote) != vote.vote_hash:
            return False, f"vote_{side} hash does not recompute"
        if not scheme.verify(
            vote.vote_owner, vote.signing_payload(), vote.signature
        ):
            return False, f"vote_{side} signature fails offline verify"
    return True, "ok"


def accountability_verdict(
    cluster: SimCluster, culprits: "dict[str, str]"
) -> dict:
    """``culprits``: identity-hex -> minimum grade the injection must
    earn (``suspect`` or ``faulty``). Convictions are read from every
    honest live peer's monitor; exactness is two-sided — every culprit
    convicted somewhere at (>=) its grade, and NOBODY else convicted
    anywhere."""
    scheme = cluster.signer_factory
    convicted: dict[str, dict] = {}
    convicting: dict[str, list[str]] = {}
    evidence_total = 0
    evidence_failures: list[str] = []
    for peer in cluster.live_peers():
        for hexid, info in sorted(
            peer.monitor.convicted_peers(now=cluster.now).items()
        ):
            prior = convicted.get(hexid)
            if prior is None or (
                _GRADE_RANK[info["grade"]] > _GRADE_RANK[prior["grade"]]
            ):
                convicted[hexid] = {
                    "grade": info["grade"], "evidence": info["evidence"]
                }
            convicting.setdefault(hexid, []).append(peer.name)
        for record in peer.monitor.evidence():
            evidence_total += 1
            if record["offender"] not in culprits:
                evidence_failures.append(
                    f"{peer.name}: evidence names non-culprit "
                    f"{record['offender'][:12]}"
                )
                continue
            ok, reason = verify_evidence_record(record, scheme)
            if not ok:
                evidence_failures.append(f"{peer.name}: {reason}")
    honest = {p.identity.hex() for p in cluster.peers}
    false_convictions = sorted(set(convicted) & honest)
    missed = sorted(set(culprits) - set(convicted))
    unexpected = sorted(set(convicted) - set(culprits))
    undergraded = sorted(
        hexid
        for hexid, grade in culprits.items()
        if hexid in convicted
        and _GRADE_RANK[convicted[hexid]["grade"]] < _GRADE_RANK[grade]
    )
    missing_evidence = sorted(
        hexid for hexid, grade in culprits.items()
        if grade == GRADE_FAULTY
        and convicted.get(hexid, {}).get("evidence", 0) == 0
    )
    ok = not (
        missed
        or unexpected
        or undergraded
        or false_convictions
        or evidence_failures
        or missing_evidence
    )
    return {
        "ok": ok,
        "expected": dict(sorted(culprits.items())),
        "convicted": {k: convicted[k] for k in sorted(convicted)},
        "convicting_peers": {
            k: sorted(set(v)) for k, v in sorted(convicting.items())
        },
        "false_convictions": false_convictions,
        "missed_culprits": missed,
        "unexpected_convictions": unexpected,
        "undergraded": undergraded,
        "evidence_records": evidence_total,
        "evidence_failures": evidence_failures,
        "culprits_without_evidence": missing_evidence,
    }


def safety_verdict(cluster: SimCluster) -> dict:
    """Cross-peer decision agreement over every session the workload
    created. ``True`` vs ``False`` on two honest peers is the violation;
    None/'failed'/'missing' are liveness states, reported only."""
    violations: list[dict] = []
    decided_sessions = 0
    undecided = 0
    for session in cluster.sessions:
        results = cluster.results(session)
        values = {v for v in results.values() if isinstance(v, bool)}
        if values:
            decided_sessions += 1
        if len(values) > 1:
            violations.append(
                {
                    "scope": session.scope,
                    "proposal_id": session.pid,
                    "results": {k: results[k] for k in sorted(results)},
                }
            )
        undecided += sum(1 for v in results.values() if v is None)
    return {
        "ok": not violations,
        "sessions": len(cluster.sessions),
        "decided_sessions": decided_sessions,
        "undecided_reads": undecided,
        "violations": violations,
    }


def liveness_verdict(
    cluster: SimCluster, *, decide_bound: int = 1_000_000
) -> dict:
    """Decidability, decide latency, and zero stale convictions — run
    LAST, after convergence's repair rounds, so "the network has
    stabilized" is literally true when it reads the cluster.

    Violations:

    - a session decided on some live peer but not on all of them
      (``stuck_sessions`` — decisions must propagate once repair runs);
    - a decision that took more than ``decide_bound`` logical ticks from
      the session's creation (``late_decisions`` — the bound is generous
      but fixed, so a determinism regression that stalls deciding trips
      a hard assert instead of drifting silently);
    - any honest peer still flagged by any live peer's liveness watchdog
      (φ-accrual or binary silence floor) at verdict time
      (``stale_convictions`` — suspicion is graded at read time exactly
      so heal clears it; one surviving is a bug, not a judgment call).

    Sessions no peer decided are ``undecidable`` (quorum genuinely out
    of reach — e.g. expected_voters past the live set with no timeout
    fired) and are reported, not violations: decidability is the
    scenario's claim to make, propagation and promptness are this
    verdict's.
    """
    cluster.note_decisions()
    stuck: "list[dict]" = []
    late: "list[dict]" = []
    undecidable = 0
    max_ticks = 0
    for session in cluster.sessions:
        results = cluster.results(session)
        decided = [v for v in results.values() if isinstance(v, bool)]
        if not decided:
            undecidable += 1
            continue
        if len(decided) != len(results):
            stuck.append(
                {
                    "scope": session.scope,
                    "proposal_id": session.pid,
                    "results": {k: results[k] for k in sorted(results)},
                }
            )
        tick = cluster.decision_ticks.get((session.scope, session.pid))
        if tick is None:
            continue
        took = tick - session.created_tick
        if took > max_ticks:
            max_ticks = took
        if took > decide_bound:
            late.append(
                {
                    "scope": session.scope,
                    "proposal_id": session.pid,
                    "ticks": took,
                }
            )
    honest = {p.identity.hex() for p in cluster.peers}
    stale_convictions: "dict[str, list[str]]" = {}
    for peer in cluster.live_peers():
        flagged = set(peer.monitor.watchdog(now=cluster.now)) & honest
        for hexid in sorted(flagged):
            stale_convictions.setdefault(hexid, []).append(peer.name)
    ok = not (stuck or late or stale_convictions)
    return {
        "ok": ok,
        "sessions": len(cluster.sessions),
        "decided_sessions": len(cluster.sessions) - undecidable,
        "undecidable_sessions": undecidable,
        "stuck_sessions": stuck,
        "decide_bound_ticks": decide_bound,
        "max_decide_ticks": max_ticks,
        "late_decisions": late,
        "stale_convictions": {
            k: sorted(v) for k, v in sorted(stale_convictions.items())
        },
    }


def convergence_verdict(cluster: SimCluster, max_rounds: int = 8) -> dict:
    report = cluster.converge(max_rounds=max_rounds)
    return {
        "ok": report["ok"],
        "repair_rounds": report["rounds"],
        "fingerprints": {
            k: report["fingerprints"][k] for k in sorted(report["fingerprints"])
        },
    }
