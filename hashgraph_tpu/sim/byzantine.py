"""Byzantine actors: genuinely-keyed adversaries on the sim fabric.

Each injector owns a REAL signing key (seed-derived), so everything it
emits is *validly signed conflicting bytes* flowing through the live
validation paths — exactly what the accountability layer must convict
with self-authenticating evidence (Polygraph / BFT-forensics framing,
PAPERS.md), never stub markers a test could cheat on:

- :meth:`ByzantineActor.equivocate` — two validly-signed conflicting
  votes for one (scope, proposal), both fanned to every peer: the
  engines' duplicate-shaped admission statuses trip the equivocation
  probe and retain the signed pair (``verified=True`` evidence, grade
  ``faulty``);
- :meth:`ByzantineActor.fork_deliver` — a chain that diverges before
  the validated watermark, pushed over ``OP_DELIVER_PROPOSALS``:
  settles crypto-free as a redelivery while the fork evidence names the
  divergent vote's signer (grade ``suspect``);
- :meth:`ByzantineActor.expired_spam` — stale self-signed proposals
  and votes: zero crypto bought (the expiry fail-fasts), expired-gossip
  attribution to the spammer's identity;
- :meth:`ByzantineActor.signature_burst` — well-formed votes whose
  signatures a LINK MUTATOR corrupts in flight
  (:func:`corrupt_vote_batch_signatures` rides the
  ``LinkFaults.mutate`` hook — injector-driven frame mutation at the
  bridge codec layer): every frame claims the actor's own identity, so
  the invalid-signature burst lands on its scorecard and trips the
  stock ``invalid-signature-burst`` alert.
"""

from __future__ import annotations

from ..bridge import protocol as P
from ..protocol import build_vote, generate_id
from ..wire import Proposal, Vote
from .cluster import SimCluster, SimSession
from .core import derived_rng
from .transport import SimTransport


def corrupt_vote_batch_signatures(opcode: int, payload: bytes):
    """Link mutator: rewrite every vote in an ``OP_VOTE_BATCH`` frame
    with a flipped signature (decode through the public codecs, corrupt
    the signature field, re-encode). Non-vote frames pass untouched.
    The vote hashes stay valid, so the engines reject on exactly
    INVALID_VOTE_SIGNATURE and attribute the claimed signer."""
    if opcode != P.OP_VOTE_BATCH:
        return None
    now, groups = P.decode_vote_batch(P.Cursor(payload))
    mutated = []
    for peer_id, scope, votes in groups:
        out = []
        for blob in votes:
            vote = Vote.decode(blob)
            vote.signature = bytes(b ^ 0xFF for b in vote.signature)
            out.append(vote.encode())
        mutated.append((peer_id, scope, out))
    return P.encode_vote_batch(now, mutated)


class ByzantineActor:
    """A keyed adversary with its own transport (a pure sender: it
    serves nothing, so honest peers only ever see its signed bytes)."""

    def __init__(self, cluster: SimCluster, name: str = "byz"):
        self.cluster = cluster
        self.name = name
        key = derived_rng(cluster.seed, f"byz-key:{name}").randbytes(32)
        self.signer = cluster.signer_factory(key)
        self.identity = bytes(self.signer.identity())
        self.transport = SimTransport(cluster.network, name)
        for peer in cluster.live_peers():
            self.transport.connect(peer.name, peer.name, 0)

    # ── delivery plumbing ──────────────────────────────────────────────

    def send_votes(
        self, scope: str, vote_bytes_list: "list[bytes]", targets=None
    ) -> None:
        """One coalesced ``OP_VOTE_BATCH`` frame per target peer."""
        cluster = self.cluster
        for peer in targets if targets is not None else cluster.live_peers():
            self.transport.try_request(
                peer.name,
                P.OP_VOTE_BATCH,
                P.encode_vote_batch(
                    cluster.now, [(peer.peer_id, scope, vote_bytes_list)]
                ),
            )
        cluster.run_network()

    def deliver(self, scope: str, proposal: Proposal, targets=None) -> None:
        cluster = self.cluster
        wire = proposal.encode()
        for peer in targets if targets is not None else cluster.live_peers():
            self.transport.try_request(
                peer.name,
                P.OP_DELIVER_PROPOSALS,
                P.encode_deliver_proposals(
                    peer.peer_id, [(scope, wire)], cluster.now
                ),
            )
        cluster.run_network()

    # ── injectors ──────────────────────────────────────────────────────

    def join(self, session: SimSession):
        """Cast ONE legitimate vote on the canonical chain (an attacker's
        first vote IS valid traffic) and fan it to every peer. The vote
        joins the canonical chain; later injections conflict with it."""
        cluster = self.cluster
        vote = build_vote(session.proposal, True, self.signer, cluster.now)
        session.proposal.votes.append(vote)
        self.send_votes(session.scope, [vote.encode()])
        return vote

    def equivocate(self, session: SimSession) -> "tuple[bytes, bytes]":
        """Sign two conflicting votes for ``session``: a legitimate chain
        extension (:meth:`join`), then a conflicting one (same signer,
        opposite value, new chain position) fanned to every peer — each
        engine rejects it duplicate-shaped and retains the verified
        evidence pair."""
        first = self.join(session)
        second = build_vote(
            session.proposal, False, self.signer, self.cluster.now
        )
        self.send_votes(session.scope, [second.encode()])
        return first.encode(), second.encode()

    def fork_deliver(self, session: SimSession) -> Proposal:
        """Push a chain in which the actor's OWN accepted vote is
        replaced by a different one it signed — the double-sign shape the
        fork detector convicts on (a divergence at an honest peer's
        position is not attributable and is deliberately NOT evidence) —
        and that claims to extend past the receivers' heads, forcing the
        positional prefix walk instead of the benign equal-length tail
        compare. Requires a prior :meth:`join`; the watermark still
        settles the delivery crypto-free."""
        cluster = self.cluster
        position = next(
            i
            for i, vote in enumerate(session.proposal.votes)
            if vote.vote_owner == self.identity
        )
        fork = session.proposal.clone()
        fork.votes = [v.clone() for v in session.proposal.votes]
        prefix = fork.clone()
        prefix.votes = fork.votes[:position]
        fork.votes[position] = build_vote(
            prefix, False, self.signer, cluster.now
        )
        fork.votes.append(build_vote(fork, True, self.signer, cluster.now))
        self.deliver(session.scope, fork)
        return fork

    def expired_spam(self, scope: str, count: int = 4) -> int:
        """Stale self-signed sessions thrown at every peer: each is
        expired on arrival, so the engines reject without buying any
        crypto and score ``expired_gossip`` against this actor (the
        chain's most recent — here only — signer)."""
        cluster = self.cluster
        now = cluster.now
        for i in range(count):
            stale = Proposal(
                name=f"stale-{i}",
                payload=b"expired",
                proposal_id=generate_id(),
                proposal_owner=self.identity,
                expected_voters_count=3,
                timestamp=max(0, now - 1000),
                expiration_timestamp=max(1, now - 10),
                liveness_criteria_yes=True,
            )
            stale.votes.append(build_vote(stale, True, self.signer, stale.timestamp))
            wire = stale.encode()
            for peer in cluster.live_peers():
                self.transport.try_request(
                    peer.name,
                    P.OP_PROCESS_PROPOSAL,
                    P.u32(peer.peer_id)
                    + P.string(scope)
                    + P.u64(now)
                    + P.blob(wire),
                )
            cluster.run_network()
        return count

    def signature_burst(self, session: SimSession, count: int = 5) -> int:
        """``count`` well-formed votes for a live session whose
        signatures the link mutator corrupts in flight (install
        :func:`corrupt_vote_batch_signatures` on this actor's links
        first): each rejects as INVALID_VOTE_SIGNATURE on the claimed
        signer — this actor — and past 3 the stock
        ``invalid-signature-burst`` alert fires."""
        cluster = self.cluster
        votes = []
        base = session.proposal.clone()
        base.votes = [v.clone() for v in session.proposal.votes]
        for i in range(count):
            vote = build_vote(base, bool(i % 2), self.signer, cluster.now + i)
            votes.append(vote.encode())
            base.votes.append(vote)
        self.send_votes(session.scope, votes)
        return count

    def arm_frame_mutation(self) -> None:
        """Install the signature-corrupting mutator on every link leaving
        this actor (the injector-driven frame mutation seam)."""
        for peer in self.cluster.live_peers():
            self.cluster.network.set_link(
                self.name, peer.name, mutate=corrupt_vote_batch_signatures
            )
