"""hashgraph_tpu.sim — deterministic chaos harness.

A seeded discrete-event cluster simulator (FoundationDB lineage — see
PAPERS.md) driving N in-process peers — real engines, real WALs, the
real bridge dispatch table in embedded (socketless) mode, the real
gossip node — through every public entry point on VIRTUAL time, with a
composable fault-injector layer: partitions (incl. asymmetric), message
drop/duplicate/reorder/delay, in-flight frame mutation, kill-9
crash-restart through live WAL recovery (torn tails included), lost-disk
rejoin through snapshot+tail catch-up, and genuinely-keyed Byzantine
actors (equivocators, chain forkers, expired-gossip spammers,
signature-burst senders).

Every scenario run is a pure function of its seed and ends with three
machine-checked verdicts: **convergence** (honest state-fingerprint
equality), **accountability** (the health observatory convicts exactly
the injected culprits, with offline-verifiable evidence and zero honest
convictions — the Polygraph/BFT-forensics bar), and **safety** (no two
honest peers decide one session differently). ``run_corpus`` is the
regression harness every future robustness/perf PR runs against
(`bench.py chaos`, `make chaos-smoke`).
"""

from .byzantine import ByzantineActor, corrupt_vote_batch_signatures
from .cluster import SimCluster, SimPeer, SimSession
from .core import SimScheduler, derived_rng, deterministic_ids
from .scenarios import SCENARIOS, run_corpus, run_scenario
from .transport import (
    LinkFaults,
    SimBridgeAdapter,
    SimFuture,
    SimNetwork,
    SimTransport,
)
from .verdicts import (
    accountability_verdict,
    convergence_verdict,
    safety_verdict,
    verify_evidence_record,
)

__all__ = [
    "ByzantineActor",
    "LinkFaults",
    "SCENARIOS",
    "SimBridgeAdapter",
    "SimCluster",
    "SimFuture",
    "SimNetwork",
    "SimPeer",
    "SimScheduler",
    "SimSession",
    "SimTransport",
    "accountability_verdict",
    "convergence_verdict",
    "corrupt_vote_batch_signatures",
    "derived_rng",
    "deterministic_ids",
    "run_corpus",
    "run_scenario",
    "safety_verdict",
    "verify_evidence_record",
]
