"""Deterministic discrete-event core: virtual time, seeded entropy.

The chaos harness's whole claim is that a scenario run is a **pure
function of its seed**: no wall clock, no real sockets, no thread
scheduling. This module supplies the two primitives that make it true:

- :class:`SimScheduler` — a single-threaded event queue over integer
  *virtual* time. Events fire in (time, insertion-order) order, so two
  events scheduled for the same tick run in the order they were
  scheduled; "blocking" callers (futures awaiting a gossip response)
  advance virtual time by pumping this queue instead of sleeping.
- :func:`derived_rng` — named sub-generators off the scenario seed.
  Seeding ``random.Random`` with a *string* uses SHA-512 internally, so
  the streams are stable across processes and PYTHONHASHSEED values
  (tuple seeds would not be).
- :class:`deterministic_ids` — installs a scenario-rng entropy source
  behind :func:`hashgraph_tpu.protocol.generate_id` for the run, so
  every minted proposal id and vote id — and therefore every signed
  byte, every WAL record, and every state fingerprint — derives from
  the seed.
"""

from __future__ import annotations

import heapq
import random

from .. import protocol


def derived_rng(seed: int, label: str) -> random.Random:
    """A named deterministic sub-generator of the scenario seed. String
    seeding is hashed with SHA-512 inside ``random.Random`` — stable
    across interpreter runs, unlike hash()-based tuple seeding."""
    return random.Random(f"hashgraph-sim:{seed}:{label}")


class SimScheduler:
    """Single-threaded discrete-event loop on integer virtual time."""

    def __init__(self, seed: int):
        self.seed = seed
        self.now = 0
        self._queue: list[tuple[int, int, object]] = []
        self._seq = 0
        self.events_run = 0

    def at(self, delay: int, fn) -> None:
        """Schedule ``fn()`` ``delay`` ticks from now (>= 0). Ties run in
        scheduling order — the determinism backbone."""
        if delay < 0:
            raise ValueError("delay must be >= 0")
        self._seq += 1
        heapq.heappush(self._queue, (self.now + int(delay), self._seq, fn))

    def step(self) -> bool:
        """Run the next pending event (advancing ``now`` to its time).
        Returns False when the queue is empty — the idle signal a
        sim future's ``result()`` turns into a typed timeout."""
        if not self._queue:
            return False
        time, _seq, fn = heapq.heappop(self._queue)
        if time > self.now:
            self.now = time
        self.events_run += 1
        fn()
        return True

    def run_until_idle(self, max_events: int = 1_000_000) -> int:
        """Drain the queue; returns the number of events run. The cap is
        a runaway guard (a scenario bug scheduling events from events
        forever), not a tuning knob."""
        ran = 0
        while ran < max_events and self.step():
            ran += 1
        if ran >= max_events:
            raise RuntimeError(
                f"simulation did not quiesce within {max_events} events"
            )
        return ran

    def advance(self, ticks: int) -> None:
        """Move virtual time forward ``ticks`` with the queue idle (e.g.
        to expire sessions or age the liveness watchdog)."""
        if self._queue:
            raise RuntimeError("advance() requires an idle event queue")
        self.now += int(ticks)


class deterministic_ids:
    """Context manager installing seed-derived entropy behind
    ``protocol.generate_id`` (and restoring the previous source on exit,
    even when the scenario raises)."""

    def __init__(self, seed: int):
        self._rng = derived_rng(seed, "ids")

    def __enter__(self) -> "deterministic_ids":
        self._prior = protocol._id_entropy
        protocol.set_id_entropy(lambda: self._rng.getrandbits(128))
        return self

    def __exit__(self, *exc) -> None:
        protocol.set_id_entropy(self._prior)
