"""SimCluster: N in-process peers (engine + WAL + gossip) on virtual time.

Each :class:`SimPeer` is the full production stack of one node:

- a :class:`~hashgraph_tpu.bridge.BridgeServer` in **embedded** mode —
  same dispatch table, opcodes, and per-peer engine construction as the
  TCP front-end, no sockets;
- a durable engine (``wal_dir`` per identity): every mutation is WAL-
  logged exactly as in production, so crash-restart scenarios replay a
  REAL log through the REAL ``recover()`` path (the embedded server's
  ``ADD_PEER`` with the peer's key runs recovery, the same code a
  restarted bridge runs);
- a private :class:`~hashgraph_tpu.obs.HealthMonitor` whose scorecards,
  evidence log, and ``convicted_peers()`` readout the accountability
  verdict interrogates;
- a :class:`~hashgraph_tpu.gossip.node.GossipNode` over a
  :class:`~hashgraph_tpu.sim.transport.SimTransport` — sampled fan-out,
  vote coalescing, anti-entropy repair, and far-behind catch-up
  escalation all run the live gossip code, on virtual time.

The cluster is also the **workload driver** (the reference's "app
supplies the network" embedder): it creates sessions over the wire
(``OP_CREATE_PROPOSAL``), ferries proposal bytes (``OP_PROCESS_PROPOSAL``
/ ``OP_DELIVER_PROPOSALS``), has peers vote (``OP_CAST_VOTE``) and fans
the signed votes out through the coalesced ``OP_VOTE_BATCH`` hot path,
fires timeouts (``OP_HANDLE_TIMEOUT``), drains events
(``OP_POLL_EVENTS``), and reads decisions (``OP_GET_RESULT``) and
fingerprints — every public entry point, every byte through the wire
codec. Vote chains stay canonical (each voter is synced over the network
before casting; an unreachable voter simply skips its turn), so honest
peers can only ever hold positional prefixes of one chain — any fork in
the fabric is, by construction, the work of an injected Byzantine actor.
"""

from __future__ import annotations

import os
import shutil

from ..bridge import protocol as P
from ..bridge.server import BridgeServer
from ..obs import HealthMonitor, MetricsRegistry
from ..obs.health import DEFAULT_PHI_THRESHOLD
from ..signing.stub import StubConsensusSigner
from ..sync import CatchUpClient
from ..wire import Proposal, Vote
from .core import SimScheduler, derived_rng, deterministic_ids
from .transport import SimBridgeAdapter, SimNetwork, SimTransport

_OK = P.STATUS_OK


class SimSession:
    """Sim-side bookkeeping of one consensus session: the CANONICAL vote
    chain (the embedder's ferry copy — each accepted vote appends here,
    every honest peer's chain is a positional prefix of it)."""

    __slots__ = ("scope", "pid", "origin", "proposal", "created_tick")

    def __init__(
        self,
        scope: str,
        pid: int,
        origin: "SimPeer",
        proposal: Proposal,
        created_tick: int = 0,
    ):
        self.scope = scope
        self.pid = pid
        self.origin = origin
        self.proposal = proposal
        # Logical tick at creation — the liveness verdict measures each
        # session's decide latency against this.
        self.created_tick = created_tick


class SimPeer:
    """One simulated node. ``start()`` builds the embedded server +
    durable engine + gossip node; ``crash()`` kills it kill-9 style
    (WAL handles abandoned, endpoint down); ``restart()`` brings the
    same identity back through real WAL recovery (or, after
    ``wipe=True``, as a fresh joiner that must catch up)."""

    def __init__(self, cluster: "SimCluster", index: int):
        self.cluster = cluster
        self.index = index
        self.name = f"p{index}"
        self.key = derived_rng(cluster.seed, f"peer-key:{index}").randbytes(32)
        self.wal_dir = os.path.join(cluster.root, self.name)
        self.server: BridgeServer | None = None
        self.node = None
        self.transport: SimTransport | None = None
        self.monitor: HealthMonitor | None = None
        self.peer_id = 0
        self.identity = b""
        self.crashed = False
        self.restarts = 0
        self.last_recovery = None

    # ── lifecycle ──────────────────────────────────────────────────────

    def start(self) -> None:
        from ..gossip.node import GossipNode

        cluster = self.cluster
        self.monitor = HealthMonitor(
            registry=MetricsRegistry(),
            stale_after=cluster.stale_after,
            phi_threshold=cluster.phi_threshold,
        )
        self.server = BridgeServer(
            capacity=cluster.capacity,
            voter_capacity=cluster.voter_capacity,
            wal_dir=self.wal_dir,
            wal_fsync="batch",
            signer_factory=cluster.signer_factory,
            health_monitor=self.monitor,
            wire_columnar=cluster.wire_columnar,
            apply_reactor=cluster.apply_reactor,
        )
        self.server.start_embedded()
        status, out = self.server.dispatch_frame(
            P.OP_ADD_PEER, P.u8(len(self.key)) + self.key
        )
        if status != _OK:
            raise RuntimeError(f"ADD_PEER failed for {self.name}: {status}")
        cursor = P.Cursor(out)
        self.peer_id = cursor.u32()
        self.identity = cursor.raw(cursor.u8())
        self.last_recovery = self.server.recovery_stats(self.identity)
        self.transport = SimTransport(cluster.network, self.name)
        self.node = GossipNode(
            self.name,
            engine=self.engine,
            transport=self.transport,
            fanout=cluster.fanout,
            seed=derived_rng(
                cluster.seed, f"node:{self.name}:{self.restarts}"
            ).getrandbits(64),
            escalate_sessions=cluster.escalate_sessions,
            catchup_factory=cluster._catchup_factory,
        )
        cluster.network.register(self.name, self.server.dispatch_frame)
        self.crashed = False

    @property
    def engine(self):
        """The peer's engine behind the bridge (a DurableEngine)."""
        return self.server.peer_engine(self.peer_id)

    @property
    def durable(self):
        return self.server.durable_engine(self.identity)

    def crash(self) -> None:
        """kill -9: abandon the WAL (handles + flock released, NO final
        fsync), take the endpoint off the network, discard the process
        state. In-flight frames addressed here fail typed; other peers'
        channels stay up and heal the moment the identity returns."""
        durable = self.durable
        if durable is not None:
            durable.abandon()
        self.cluster.network.mark_down(self.name)
        if self.transport is not None:
            self.transport.close()
        if self.server is not None:
            self.server.stop()
        self.server = None
        self.node = None
        self.transport = None
        self.crashed = True

    def crash_mid_append(
        self, session: "SimSession", *, torn_bytes: int = 7, choice: bool = True
    ) -> None:
        """kill -9 *mid-WAL-append*: arm the writer's crash hook so the
        peer's next mutator (a locally-cast vote) dies after ``torn_bytes``
        of its record hit the disk — the torn tail the recovery scan must
        truncate. The engine had applied the vote (the documented
        crash window for locally-minted data); the restart recovers the
        surviving prefix."""
        from ..wal.writer import SimulatedCrash

        durable = self.durable

        def hook(point: str) -> None:
            if point == "append":
                raise SimulatedCrash(point, torn_bytes=torn_bytes)

        durable.wal.set_crash_hook(hook)
        try:
            durable.cast_vote(
                session.scope, session.pid, choice, self.cluster.now
            )
        except SimulatedCrash:
            pass
        else:
            raise RuntimeError("crash hook did not fire")
        self.crash()

    def restart(self, wipe: bool = False) -> None:
        """Bring the identity back: with its WAL (``ADD_PEER`` replays
        the surviving log through ``recover()``), or — ``wipe=True``, the
        lost-disk case — fresh, relying on the gossip node's catch-up
        escalation to rejoin. Reconnects this node's channels to every
        live peer (the real transport's ReconnectPolicy analogue)."""
        if not self.crashed:
            raise RuntimeError(f"{self.name} is not crashed")
        if wipe:
            shutil.rmtree(self.wal_dir, ignore_errors=True)
        self.restarts += 1
        self.start()
        for other in self.cluster.live_peers():
            if other is not self:
                self.node.add_peer(other.name, other.name, 0, other.peer_id)

    def shutdown(self) -> None:
        """Clean stop (WALs flushed + closed) — end-of-scenario teardown."""
        if self.crashed:
            return
        if self.transport is not None:
            self.transport.close()
        if self.server is not None:
            self.server.stop()
        self.crashed = True

    def note_known_sessions(self) -> None:
        """Sync the gossip node's anti-entropy bookkeeping with the
        engine's live sessions (the embedder wiring ``note_session``
        documents) so repair rounds push everything the peer holds."""
        for scope, pid in self.engine.session_keys():
            self.node.note_session(scope, pid)


class SimCluster:
    """N peers + the network + the workload driver. Use as a context
    manager; every run with the same ``seed`` (and scenario script) is
    byte-identical — ids, signatures, WAL bytes, fingerprints included
    (:class:`~hashgraph_tpu.sim.core.deterministic_ids`)."""

    def __init__(
        self,
        root: str,
        seed: int,
        n_peers: int = 4,
        *,
        fanout: int | None = None,
        stale_after: float = 10**9,
        phi_threshold: "float | None" = DEFAULT_PHI_THRESHOLD,
        capacity: int = 64,
        voter_capacity: int = 8,
        escalate_sessions: int = 8,
        signer_factory: type = StubConsensusSigner,
        base_delay: int = 1,
        wire_columnar: "bool | None" = None,
        apply_reactor: "bool | None" = None,
    ):
        self.root = root
        self.seed = seed
        self.fanout = fanout
        self.stale_after = stale_after
        # φ-accrual suspicion bar for every peer's HealthMonitor (None =
        # binary-threshold-only watchdog — the liveness A/B baseline arm).
        self.phi_threshold = phi_threshold
        self.capacity = capacity
        self.voter_capacity = voter_capacity
        self.escalate_sessions = escalate_sessions
        self.signer_factory = signer_factory
        # Per-cluster override of the bridge's columnar wire path (None =
        # the server's env-driven default): scenario runs must be pure
        # functions of their arguments, and the columnar-wire scenario
        # pins this True so the env cannot change what it asserts.
        self.wire_columnar = wire_columnar
        # Apply-reactor override, same contract as wire_columnar. In the
        # sim the server stays embedded (never start()ed), so the
        # reactor runs in manual mode: submit + flush inline on the
        # dispatching tick — windows merge deterministically, no threads
        # and no wall-clock deadlines enter the simulation.
        self.apply_reactor = apply_reactor
        self.scheduler = SimScheduler(seed)
        self.network = SimNetwork(self.scheduler, base_delay=base_delay)
        # The CONSENSUS clock: the logical `now` every engine call gets.
        # Deliberately decoupled from the scheduler's event tick and
        # piecewise-constant (advance_clock() moves it at phase
        # boundaries only): per-peer lifecycle fields like a session's
        # created_at are stamped with the embedder-supplied now, so
        # convergence to state-fingerprint EQUALITY requires every peer
        # to learn a session at the same logical tick no matter how late
        # repair delivered it — exactly the no-wall-clock contract the
        # library already imposes on embedders.
        self.clock = 1_000
        self.rng = derived_rng(seed, "workload")
        self._ids = deterministic_ids(seed)
        self._ids.__enter__()
        self.sessions: list[SimSession] = []
        # (scope, pid) -> logical tick at which the session FIRST read
        # decided on any peer (the liveness verdict's decide-latency
        # numerator). Stamped eagerly on the acting peer after each cast
        # / timeout, with a late-discovery sweep (note_decisions) for
        # sessions that decided through repair instead.
        self.decision_ticks: "dict[tuple[str, int], int]" = {}
        self.catchups = 0
        self.peers = [SimPeer(self, i) for i in range(n_peers)]
        try:
            for peer in self.peers:
                peer.start()
            self.wire_full_mesh()
        except BaseException:
            # A constructor failure escapes before the context manager
            # exists: the process-global id-entropy install (and any
            # started peers' WAL handles) must not leak past it.
            self.close()
            raise

    def close(self) -> None:
        for peer in self.peers:
            peer.shutdown()
        self._ids.__exit__(None, None, None)

    def __enter__(self) -> "SimCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ── topology ───────────────────────────────────────────────────────

    def live_peers(self) -> "list[SimPeer]":
        return [p for p in self.peers if not p.crashed]

    def peer(self, index: int) -> SimPeer:
        return self.peers[index]

    def wire_full_mesh(self) -> None:
        for a in self.live_peers():
            for b in self.live_peers():
                if a is not b and a.transport.channel(b.name) is None:
                    a.node.add_peer(b.name, b.name, 0, b.peer_id)

    def _catchup_factory(self, host: str, port: int, peer_id: int):
        """GossipNode escalation seam: a CatchUpClient whose transport is
        the sim fabric (``host`` carries the target's NAME — see
        SimTransport.connect). The live snapshot/digest/tail code runs
        unchanged."""
        self.catchups += 1
        return CatchUpClient(
            host, port, peer_id,
            bridge=SimBridgeAdapter(self.network, host),
        )

    # ── workload: the embedder loop over public entry points ───────────

    @property
    def now(self) -> int:
        return self.clock

    def advance_clock(self, ticks: int) -> None:
        self.clock += int(ticks)

    def run_network(self) -> None:
        self.scheduler.run_until_idle()

    def create_session(
        self,
        origin: SimPeer,
        scope: str,
        *,
        voters: int | None = None,
        rel_expiry: int = 500_000,
        liveness: bool = True,
        payload: bytes = b"chaos",
    ) -> SimSession:
        """OP_CREATE_PROPOSAL on the origin, then ferry the proposal to
        every live peer over the (faultable) network via
        OP_PROCESS_PROPOSAL — peers a partition hides miss it and must be
        repaired by anti-entropy later."""
        now = self.now
        if voters is None:
            voters = len(self.peers)
        status, out = origin.server.dispatch_frame(
            P.OP_CREATE_PROPOSAL,
            P.u32(origin.peer_id)
            + P.string(scope)
            + P.u64(now)
            + P.string(f"chaos-{scope}")
            + P.blob(payload)
            + P.u32(voters)
            + P.u64(rel_expiry)
            + P.u8(1 if liveness else 0),
        )
        if status != _OK:
            raise RuntimeError(f"create_proposal failed: status {status}")
        cursor = P.Cursor(out)
        pid = cursor.u32()
        proposal = Proposal.decode(cursor.blob())
        session = SimSession(scope, pid, origin, proposal, created_tick=now)
        self.sessions.append(session)
        origin.node.note_session(scope, pid)
        wire = proposal.encode()
        for peer in self.live_peers():
            if peer is origin:
                continue
            origin.transport.try_request(
                peer.name,
                P.OP_PROCESS_PROPOSAL,
                P.u32(peer.peer_id) + P.string(scope) + P.u64(now) + P.blob(wire),
            )
        self.run_network()
        return session

    def cast_vote(
        self, session: SimSession, voter: SimPeer, choice: bool
    ) -> "bytes | None":
        """One canonical-chain vote: sync the voter to the canonical
        chain OVER THE NETWORK (an unreachable voter cannot see the
        chain and skips its turn — returns None), OP_CAST_VOTE on the
        voter's engine, append the signed bytes to the canonical chain,
        fan out through the voter's gossip node (coalesced
        OP_VOTE_BATCH, sampled fan-out)."""
        now = self.now
        if voter.crashed:
            return None
        deliver = P.encode_deliver_proposals(
            voter.peer_id,
            [(session.scope, session.proposal.encode())],
            now,
        )
        if voter is session.origin:
            # The canonical chain IS the origin's embedder ledger: feeding
            # it back into the origin's own engine is a local embedder
            # action (no network), and keeps the origin from signing a
            # vote against a stale view when fan-out frames to it were
            # dropped — which would put a broken link into the canonical
            # chain and manufacture an honest "fork".
            voter.server.dispatch_frame(P.OP_DELIVER_PROPOSALS, deliver)
        else:
            if session.origin.crashed:
                return None
            future = session.origin.transport.try_request(
                voter.name, P.OP_DELIVER_PROPOSALS, deliver
            )
            if future is None:
                return None
            try:
                future.result(30)
            except Exception:
                return None  # unreachable this turn; the chain moves on
        status, out = voter.server.dispatch_frame(
            P.OP_CAST_VOTE,
            P.u32(voter.peer_id)
            + P.string(session.scope)
            + P.u32(session.pid)
            + P.u8(1 if choice else 0)
            + P.u64(now),
        )
        if status != _OK:
            return None  # already voted / expired — skip
        vote_bytes = P.Cursor(out).blob()
        vote = Vote.decode(vote_bytes)
        # Post-decision casts return a signed vote WITHOUT applying it
        # (ALREADY_REACHED absorbed — reference semantics). Gossiping
        # such a vote would put an unapplied signature into the fabric
        # (and a retry would mint a CONFLICTING one), so only a cast
        # that actually extended the voter's chain joins the canonical
        # chain and fans out.
        status, out = voter.server.dispatch_frame(
            P.OP_GET_PROPOSAL,
            P.u32(voter.peer_id) + P.string(session.scope) + P.u32(session.pid),
        )
        if status != _OK:
            return None
        applied = Proposal.decode(P.Cursor(out).blob())
        if (
            len(applied.votes) != len(session.proposal.votes) + 1
            or applied.votes[-1].vote_hash != vote.vote_hash
        ):
            return None  # absorbed without applying (decided session)
        session.proposal.votes.append(vote)
        self._record_decision(session, voter)
        voter.node.note_session(session.scope, session.pid)
        voter.node.submit_votes(
            session.scope, session.pid, [vote_bytes], now, local=False
        )
        voter.node.flush_all()
        self.run_network()
        return vote_bytes

    def vote_all(self, session: SimSession, values: "list[bool] | None" = None):
        """Every live peer votes once, in peer order (deterministic)."""
        cast = 0
        for i, peer in enumerate(self.peers):
            if peer.crashed:
                continue
            value = True if values is None else values[i % len(values)]
            if self.cast_vote(session, peer, value) is not None:
                cast += 1
        return cast

    def drain_all(self) -> dict:
        """Flush + await every node's in-flight hot-path frames (virtual
        blocking) and drain bridge events (OP_POLL_EVENTS coverage)."""
        report = {"acked": 0, "rejected": 0, "failed_frames": 0, "events": 0}
        for peer in self.live_peers():
            out = peer.node.drain()
            report["acked"] += out["acked"]
            report["rejected"] += out["rejected"]
            report["failed_frames"] += out["failed_frames"]
            status, payload = peer.server.dispatch_frame(
                P.OP_POLL_EVENTS, P.u32(peer.peer_id)
            )
            if status == _OK:
                report["events"] += P.Cursor(payload).u32()
        return report

    def anti_entropy_round(self, max_sessions: int = 256) -> dict:
        """One repair round from every live peer (shed-dirty scopes
        first, rotation after — the live GossipNode code)."""
        total = {"pushed": 0, "created_or_extended": 0, "failed": 0,
                 "escalated": 0}
        for peer in self.live_peers():
            peer.note_known_sessions()
        for peer in self.live_peers():
            report = peer.node.anti_entropy(
                self.now, max_sessions=max_sessions
            )
            total["pushed"] += report["pushed_sessions"]
            total["created_or_extended"] += report["created_or_extended"]
            total["failed"] += report["failed"]
            if report["escalated"] is not None:
                total["escalated"] += 1
            self.run_network()
        return total

    def fingerprints(self) -> "dict[str, str]":
        """Per-peer state fingerprint via OP_STATE_FINGERPRINT — the
        convergence criterion, read over the wire."""
        out = {}
        for peer in self.live_peers():
            status, payload = peer.server.dispatch_frame(
                P.OP_STATE_FINGERPRINT, P.u32(peer.peer_id)
            )
            if status != _OK:
                raise RuntimeError(f"fingerprint failed on {peer.name}")
            out[peer.name] = P.Cursor(payload).string()
        return out

    def converge(self, max_rounds: int = 8) -> dict:
        """Anti-entropy until all live peers fingerprint-equal (or the
        round cap). Returns {'ok', 'rounds', 'fingerprints'}."""
        rounds = 0
        prints = self.fingerprints()
        while len(set(prints.values())) > 1 and rounds < max_rounds:
            self.anti_entropy_round()
            rounds += 1
            prints = self.fingerprints()
        return {
            "ok": len(set(prints.values())) == 1,
            "rounds": rounds,
            "fingerprints": prints,
        }

    def results(self, session: SimSession) -> "dict[str, object]":
        """OP_GET_RESULT per live peer: True/False decided, None
        undecided, 'failed' consensus-failed, 'missing' unknown."""
        out: dict[str, object] = {}
        for peer in self.live_peers():
            status, payload = peer.server.dispatch_frame(
                P.OP_GET_RESULT,
                P.u32(peer.peer_id)
                + P.string(session.scope)
                + P.u32(session.pid),
            )
            if status != _OK:
                out[peer.name] = "missing"
                continue
            value = P.Cursor(payload).u8()
            out[peer.name] = {
                P.RESULT_UNDECIDED: None,
                P.RESULT_FAILED: "failed",
                P.RESULT_YES: True,
                P.RESULT_NO: False,
            }[value]
        return out

    def fire_timeout(self, session: SimSession) -> dict:
        """OP_HANDLE_TIMEOUT on every live peer (the embedder's timer
        duty) — exercised after reconvergence so peers time out on the
        same view."""
        out = {}
        for peer in self.live_peers():
            status, payload = peer.server.dispatch_frame(
                P.OP_HANDLE_TIMEOUT,
                P.u32(peer.peer_id)
                + P.string(session.scope)
                + P.u32(session.pid)
                + P.u64(self.now),
            )
            out[peer.name] = (
                bool(P.Cursor(payload).u8()) if status == _OK else f"status {status}"
            )
            self._record_decision(session, peer)
        return out

    # ── decision-tick bookkeeping (liveness verdict) ───────────────────

    def _record_decision(self, session: SimSession, peer: SimPeer) -> None:
        """Stamp the logical tick at which ``session`` first reads
        decided on any peer (first stamp wins; read-only OP_GET_RESULT,
        so the extra dispatch cannot perturb the run)."""
        key = (session.scope, session.pid)
        if key in self.decision_ticks or peer.crashed:
            return
        status, payload = peer.server.dispatch_frame(
            P.OP_GET_RESULT,
            P.u32(peer.peer_id)
            + P.string(session.scope)
            + P.u32(session.pid),
        )
        if status != _OK:
            return
        if P.Cursor(payload).u8() in (P.RESULT_YES, P.RESULT_NO):
            self.decision_ticks[key] = self.now

    def note_decisions(self) -> None:
        """Late-discovery sweep: sessions that decided through gossip
        fan-out or anti-entropy repair (no locally-observed cast) get
        stamped at the CURRENT tick — an upper bound on their decide
        latency, which is all the liveness bound needs."""
        for session in self.sessions:
            key = (session.scope, session.pid)
            if key in self.decision_ticks:
                continue
            for peer in self.live_peers():
                self._record_decision(session, peer)
                if key in self.decision_ticks:
                    break
