"""Wire data model: ``Proposal`` and ``Vote`` messages with a protobuf codec.

Byte-compatible with the reference schema
(reference: src/protos/messages/v1/consensus.proto:5-29) as encoded by prost:
proto3 semantics, fields emitted in ascending field-number order, and
default-valued scalar fields (0 / false / empty) omitted. The vote signature is
computed over exactly this encoding with the ``signature`` field blanked
(reference: src/utils.rs:93-97, 150-153), so encoding fidelity is
load-bearing for cross-implementation signature verification.

The codec is hand-rolled (no generated code) so the framework controls every
byte; it is a few hundred lines and covers only the two message types the
protocol uses.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

__all__ = ["Vote", "Proposal"]


def normalize_wire_votes(wire_votes, count: int) -> "tuple[bytes, np.ndarray]":
    """Normalize a columnar ``wire_votes`` argument — a list of encoded
    Vote bytes, or an already-packed ``(data, offsets)`` pair — to one
    packed blob plus validated int64 row offsets. Shared by the engine's
    columnar ingest (which views the blob as uint8) and the WAL's columnar
    records (which store it verbatim), so the two layers cannot drift on
    what a well-formed batch is."""
    if isinstance(wire_votes, tuple):
        data, offsets = wire_votes
        blob = (
            bytes(data)
            if isinstance(data, (bytes, bytearray, memoryview))
            else np.asarray(data, np.uint8).tobytes()
        )
        offsets = np.asarray(offsets, np.int64)
    else:
        blob = b"".join(wire_votes)
        offsets = np.zeros(len(wire_votes) + 1, np.int64)
        np.cumsum([len(b) for b in wire_votes], out=offsets[1:])
    if len(offsets) != count + 1:
        raise ValueError("wire_votes must supply one entry per batch row")
    if len(offsets) and int(offsets[-1]) > len(blob):
        raise ValueError("wire_votes offsets exceed the packed data")
    if len(offsets) and (int(offsets[0]) < 0 or (np.diff(offsets) < 0).any()):
        raise ValueError(
            "wire_votes offsets must be non-negative and non-decreasing"
        )
    return blob, offsets

_U32_MASK = 0xFFFFFFFF
_U64_MASK = 0xFFFFFFFFFFFFFFFF

# Wire types
_VARINT = 0
_LEN = 2


def _encode_varint(out: bytearray, value: int) -> None:
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _encode_tag(out: bytearray, field_number: int, wire_type: int) -> None:
    _encode_varint(out, (field_number << 3) | wire_type)


def _encode_uint_field(out: bytearray, field_number: int, value: int) -> None:
    if value:
        _encode_tag(out, field_number, _VARINT)
        _encode_varint(out, value)


def _encode_bool_field(out: bytearray, field_number: int, value: bool) -> None:
    if value:
        _encode_tag(out, field_number, _VARINT)
        out.append(1)


def _encode_bytes_field(out: bytearray, field_number: int, value: bytes) -> None:
    if value:
        _encode_tag(out, field_number, _LEN)
        _encode_varint(out, len(value))
        out += value


def _decode_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _checked_end(data: bytes, pos: int, length: int) -> int:
    end = pos + length
    if end > len(data):
        raise ValueError("truncated length-delimited field")
    return end


def _skip_field(data: bytes, pos: int, wire_type: int) -> int:
    if wire_type == _VARINT:
        _, pos = _decode_varint(data, pos)
        return pos
    if wire_type == 1:  # fixed64
        return _checked_end(data, pos, 8)
    if wire_type == _LEN:
        length, pos = _decode_varint(data, pos)
        return _checked_end(data, pos, length)
    if wire_type == 5:  # fixed32
        return _checked_end(data, pos, 4)
    raise ValueError(f"unsupported wire type {wire_type}")


@dataclass(slots=True)
class Vote:
    """A single vote in a consensus proposal.

    Field numbers match the reference schema
    (reference: src/protos/messages/v1/consensus.proto:19-29).
    """

    vote_id: int = 0  # field 20, uint32
    vote_owner: bytes = b""  # field 21
    proposal_id: int = 0  # field 22, uint32
    timestamp: int = 0  # field 23, uint64
    vote: bool = False  # field 24
    parent_hash: bytes = b""  # field 25
    received_hash: bytes = b""  # field 26
    vote_hash: bytes = b""  # field 27
    signature: bytes = b""  # field 28

    def _encode_signed_fields(self, out: bytearray) -> None:
        """Fields 20-27 — everything the signature covers. Shared between
        ``encode`` and ``signing_payload`` so the signed bytes can never
        drift from the wire bytes.

        Specialized by hand (precomputed two-byte tags, inlined varints,
        single-append length prefixes): this runs once per vote on the
        validated ingest hot path, and the generic per-field helper
        stack measured ~11µs/vote of pure interpreter dispatch — more
        than the amortized signature verify it feeds. Byte output is
        identical to the generic encoding (asserted by the wire tests).
        """
        vid = self.vote_id & _U32_MASK
        if vid:
            out += b"\xa0\x01"  # tag(20, varint)
            while vid > 0x7F:
                out.append((vid & 0x7F) | 0x80)
                vid >>= 7
            out.append(vid)
        owner = self.vote_owner
        if owner:
            out += b"\xaa\x01"  # tag(21, len)
            n = len(owner)
            if n > 0x7F:
                _encode_varint(out, n)
            else:
                out.append(n)
            out += owner
        pid = self.proposal_id & _U32_MASK
        if pid:
            out += b"\xb0\x01"  # tag(22, varint)
            while pid > 0x7F:
                out.append((pid & 0x7F) | 0x80)
                pid >>= 7
            out.append(pid)
        ts = self.timestamp & _U64_MASK
        if ts:
            out += b"\xb8\x01"  # tag(23, varint)
            while ts > 0x7F:
                out.append((ts & 0x7F) | 0x80)
                ts >>= 7
            out.append(ts)
        if self.vote:
            out += b"\xc0\x01\x01"  # tag(24, varint) + true
        for tag, value in (
            (b"\xca\x01", self.parent_hash),    # 25
            (b"\xd2\x01", self.received_hash),  # 26
            (b"\xda\x01", self.vote_hash),      # 27
        ):
            if value:
                out += tag
                n = len(value)
                if n > 0x7F:
                    _encode_varint(out, n)
                else:
                    out.append(n)
                out += value

    def encode(self) -> bytes:
        out = bytearray()
        self._encode_signed_fields(out)
        _encode_bytes_field(out, 28, self.signature)
        return bytes(out)

    def signing_payload(self) -> bytes:
        """Encoding with the signature field blanked — the bytes that get
        signed (reference: src/utils.rs:93-95, 150-153)."""
        out = bytearray()
        self._encode_signed_fields(out)
        return bytes(out)

    @classmethod
    def decode(cls, data: bytes) -> "Vote":
        vote = cls()
        pos = 0
        n = len(data)
        while pos < n:
            key, pos = _decode_varint(data, pos)
            field_number, wire_type = key >> 3, key & 7
            if field_number == 20 and wire_type == _VARINT:
                v, pos = _decode_varint(data, pos)
                vote.vote_id = v & _U32_MASK
            elif field_number == 22 and wire_type == _VARINT:
                v, pos = _decode_varint(data, pos)
                vote.proposal_id = v & _U32_MASK
            elif field_number == 23 and wire_type == _VARINT:
                v, pos = _decode_varint(data, pos)
                vote.timestamp = v & _U64_MASK
            elif field_number == 24 and wire_type == _VARINT:
                v, pos = _decode_varint(data, pos)
                vote.vote = bool(v)
            elif wire_type == _LEN and field_number in (21, 25, 26, 27, 28):
                length, pos = _decode_varint(data, pos)
                end = _checked_end(data, pos, length)
                value = data[pos:end]
                pos = end
                if field_number == 21:
                    vote.vote_owner = value
                elif field_number == 25:
                    vote.parent_hash = value
                elif field_number == 26:
                    vote.received_hash = value
                elif field_number == 27:
                    vote.vote_hash = value
                else:
                    vote.signature = value
            else:
                pos = _skip_field(data, pos, wire_type)
        return vote

    def clone(self) -> "Vote":
        # Direct slot copies, not a kwargs __init__: this runs once per vote
        # on every export/retention decode, and the constructor's keyword
        # dispatch is ~2.5x the cost of nine attribute stores.
        new = Vote.__new__(Vote)
        new.vote_id = self.vote_id
        new.vote_owner = self.vote_owner
        new.proposal_id = self.proposal_id
        new.timestamp = self.timestamp
        new.vote = self.vote
        new.parent_hash = self.parent_hash
        new.received_hash = self.received_hash
        new.vote_hash = self.vote_hash
        new.signature = self.signature
        return new


@dataclass(slots=True)
class Proposal:
    """A consensus proposal that needs voting.

    Field numbers match the reference schema
    (reference: src/protos/messages/v1/consensus.proto:5-16).
    """

    name: str = ""  # field 10
    payload: bytes = b""  # field 11
    proposal_id: int = 0  # field 12, uint32
    proposal_owner: bytes = b""  # field 13
    votes: list[Vote] = field(default_factory=list)  # field 14
    expected_voters_count: int = 0  # field 15, uint32
    round: int = 0  # field 16, uint32
    timestamp: int = 0  # field 17, uint64
    expiration_timestamp: int = 0  # field 18, uint64
    liveness_criteria_yes: bool = False  # field 19

    def encode(self) -> bytes:
        out = bytearray()
        if self.name:
            name_bytes = self.name.encode("utf-8")
            _encode_tag(out, 10, _LEN)
            _encode_varint(out, len(name_bytes))
            out += name_bytes
        _encode_bytes_field(out, 11, self.payload)
        _encode_uint_field(out, 12, self.proposal_id & _U32_MASK)
        _encode_bytes_field(out, 13, self.proposal_owner)
        for vote in self.votes:
            encoded = vote.encode()
            _encode_tag(out, 14, _LEN)
            _encode_varint(out, len(encoded))
            out += encoded
        _encode_uint_field(out, 15, self.expected_voters_count & _U32_MASK)
        _encode_uint_field(out, 16, self.round & _U32_MASK)
        _encode_uint_field(out, 17, self.timestamp & _U64_MASK)
        _encode_uint_field(out, 18, self.expiration_timestamp & _U64_MASK)
        _encode_bool_field(out, 19, self.liveness_criteria_yes)
        return bytes(out)

    def encode_split(self) -> tuple[bytes, bytes]:
        """``(head, tail)`` such that ``head + <field 12: proposal_id> +
        tail`` equals :meth:`encode` byte for byte, for a VOTE-FREE
        proposal (field 14 sits between the id and the tail; embedded
        votes make the split ambiguous and raise). Bulk serializers (the
        engine's session-demotion path) cache the two constant parts per
        distinct (name, payload, owner, n, round, timestamps, liveness)
        shape and splice only the id varint per proposal — the canonical
        bytes without re-walking nine fields per item. Parity with
        ``encode`` is pinned by tests/test_wire.py."""
        if self.votes:
            raise ValueError("encode_split requires a vote-free proposal")
        head = bytearray()
        if self.name:
            name_bytes = self.name.encode("utf-8")
            _encode_tag(head, 10, _LEN)
            _encode_varint(head, len(name_bytes))
            head += name_bytes
        _encode_bytes_field(head, 11, self.payload)
        tail = bytearray()
        _encode_bytes_field(tail, 13, self.proposal_owner)
        _encode_uint_field(tail, 15, self.expected_voters_count & _U32_MASK)
        _encode_uint_field(tail, 16, self.round & _U32_MASK)
        _encode_uint_field(tail, 17, self.timestamp & _U64_MASK)
        _encode_uint_field(tail, 18, self.expiration_timestamp & _U64_MASK)
        _encode_bool_field(tail, 19, self.liveness_criteria_yes)
        return bytes(head), bytes(tail)

    @classmethod
    def decode(cls, data: bytes) -> "Proposal":
        proposal = cls()
        pos = 0
        n = len(data)
        while pos < n:
            key, pos = _decode_varint(data, pos)
            field_number, wire_type = key >> 3, key & 7
            if wire_type == _LEN and field_number in (10, 11, 13, 14):
                length, pos = _decode_varint(data, pos)
                end = _checked_end(data, pos, length)
                value = data[pos:end]
                pos = end
                if field_number == 10:
                    proposal.name = value.decode("utf-8")
                elif field_number == 11:
                    proposal.payload = value
                elif field_number == 13:
                    proposal.proposal_owner = value
                else:
                    proposal.votes.append(Vote.decode(value))
            elif field_number == 12 and wire_type == _VARINT:
                v, pos = _decode_varint(data, pos)
                proposal.proposal_id = v & _U32_MASK
            elif field_number == 15 and wire_type == _VARINT:
                v, pos = _decode_varint(data, pos)
                proposal.expected_voters_count = v & _U32_MASK
            elif field_number == 16 and wire_type == _VARINT:
                v, pos = _decode_varint(data, pos)
                proposal.round = v & _U32_MASK
            elif field_number == 17 and wire_type == _VARINT:
                v, pos = _decode_varint(data, pos)
                proposal.timestamp = v & _U64_MASK
            elif field_number == 18 and wire_type == _VARINT:
                v, pos = _decode_varint(data, pos)
                proposal.expiration_timestamp = v & _U64_MASK
            elif field_number == 19 and wire_type == _VARINT:
                v, pos = _decode_varint(data, pos)
                proposal.liveness_criteria_yes = bool(v)
            else:
                pos = _skip_field(data, pos, wire_type)
        return proposal

    def clone(self) -> "Proposal":
        # Direct slot copies (see Vote.clone): batch creation clones every
        # minted proposal on return, so this is on the registration hot path.
        new = Proposal.__new__(Proposal)
        new.name = self.name
        new.payload = self.payload
        new.proposal_id = self.proposal_id
        new.proposal_owner = self.proposal_owner
        new.votes = [v.clone() for v in self.votes]
        new.expected_voters_count = self.expected_voters_count
        new.round = self.round
        new.timestamp = self.timestamp
        new.expiration_timestamp = self.expiration_timestamp
        new.liveness_criteria_yes = self.liveness_criteria_yes
        return new
