"""Event bus abstraction and default in-process broadcast implementation.

Mirrors the reference semantics (reference: src/events.rs): every event goes
to all current subscribers; a subscriber with a full buffer silently misses
the event (no blocking); closed subscribers are pruned on publish.
"""

from __future__ import annotations

import queue
import threading
from typing import Generic, Hashable, TypeVar

from .types import ConsensusEvent

Scope = TypeVar("Scope", bound=Hashable)

DEFAULT_MAX_QUEUED_EVENTS = 1000  # reference: src/events.rs:59-66


class ConsensusEventBus(Generic[Scope]):
    """Interface for broadcasting consensus events (reference: src/events.rs:15-26)."""

    def subscribe(self):
        """Subscribe to events from all scopes; returns a receiver."""
        raise NotImplementedError

    def publish(self, scope: Scope, event: ConsensusEvent) -> None:
        raise NotImplementedError


class EventReceiver(Generic[Scope]):
    """Receiving end of a broadcast subscription.

    ``recv`` blocks (optionally with timeout); ``try_recv`` is non-blocking;
    ``close`` disconnects, after which the bus prunes this subscriber.
    """

    def __init__(self, capacity: int):
        self._queue: queue.Queue[tuple[Scope, ConsensusEvent]] = queue.Queue(capacity)
        self._closed = False

    def recv(self, timeout: float | None = None) -> tuple[Scope, ConsensusEvent]:
        """Blocking receive; raises queue.Empty on timeout."""
        return self._queue.get(timeout=timeout)

    def try_recv(self) -> tuple[Scope, ConsensusEvent] | None:
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            return None

    def close(self) -> None:
        self._closed = True

    # bus-side API
    def _offer(self, item: tuple[Scope, ConsensusEvent]) -> bool:
        """Returns False iff this receiver is closed (prune me). A full
        buffer silently drops the event but keeps the subscription
        (reference: src/events.rs:84-90)."""
        if self._closed:
            return False
        try:
            self._queue.put_nowait(item)
        except queue.Full:
            pass
        return True


class BroadcastEventBus(ConsensusEventBus[Scope]):
    """Fan-out to every live subscriber, in-process
    (reference: src/events.rs:35-92)."""

    def __init__(self, max_queued_events: int = DEFAULT_MAX_QUEUED_EVENTS):
        self._capacity = max_queued_events
        self._lock = threading.Lock()
        self._subscribers: list[EventReceiver[Scope]] = []

    def subscribe(self) -> EventReceiver[Scope]:
        receiver: EventReceiver[Scope] = EventReceiver(self._capacity)
        with self._lock:
            self._subscribers.append(receiver)
        return receiver

    def publish(self, scope: Scope, event: ConsensusEvent) -> None:
        with self._lock:
            self._subscribers = [
                r for r in self._subscribers if r._offer((scope, event))
            ]
