"""Error types for the TPU-native hashgraph consensus framework.

Mirrors the reference error surface (reference: src/error.rs:11-74) as a Python
exception hierarchy plus an integer ``StatusCode`` enum. The integer codes exist
because the TPU batch-ingest path reports per-vote outcomes as dense ``int32``
status vectors from device kernels; host code maps codes back to exceptions via
:func:`error_for_code`.
"""

from __future__ import annotations

import enum


class StatusCode(enum.IntEnum):
    """Dense per-vote / per-proposal status codes used by device kernels.

    ``OK`` (0) means the operation succeeded. Codes are stable: they are part of
    the batch API surface (``ingest_votes`` returns one code per vote).
    """

    OK = 0

    # Configuration validation (reference: src/error.rs:13-20)
    INVALID_CONSENSUS_THRESHOLD = 1
    INVALID_TIMEOUT = 2
    INVALID_EXPECTED_VOTERS_COUNT = 3
    INVALID_MAX_ROUNDS = 4

    # Vote / proposal validation (reference: src/error.rs:23-50)
    INVALID_VOTE_SIGNATURE = 5
    EMPTY_SIGNATURE = 6
    DUPLICATE_VOTE = 7
    USER_ALREADY_VOTED = 8
    VOTE_EXPIRED = 9
    EMPTY_VOTE_OWNER = 10
    INVALID_VOTE_HASH = 11
    EMPTY_VOTE_HASH = 12
    PROPOSAL_EXPIRED = 13
    VOTE_PROPOSAL_ID_MISMATCH = 14
    RECEIVED_HASH_MISMATCH = 15
    PARENT_HASH_MISMATCH = 16
    INVALID_VOTE_TIMESTAMP = 17
    TIMESTAMP_OLDER_THAN_CREATION_TIME = 18

    # Session / state (reference: src/error.rs:53-60)
    SESSION_NOT_ACTIVE = 19
    SESSION_NOT_FOUND = 20
    PROPOSAL_ALREADY_EXIST = 21
    SCOPE_NOT_FOUND = 22

    # Consensus results (reference: src/error.rs:63-70)
    INSUFFICIENT_VOTES_AT_TIMEOUT = 23
    MAX_ROUNDS_EXCEEDED = 24
    CONSENSUS_NOT_REACHED = 25
    CONSENSUS_FAILED = 26

    # Signature scheme failure (reference: src/error.rs:72-73)
    SIGNATURE_SCHEME = 27

    # Batch-engine specific: the vote was accepted by a session that had already
    # reached consensus — the reference returns Ok(ConsensusReached) without
    # inserting the vote (reference: src/session.rs:246). Not an error.
    ALREADY_REACHED = 28

    # Batch-engine specific (no reference analogue): the proposal's device
    # voter lanes are exhausted — more than voter_capacity distinct owners
    # voted on one proposal. Only possible in Gossipsub mode, which accepts
    # any number of distinct voters; size voter_capacity accordingly.
    VOTER_CAPACITY_EXCEEDED = 29


class ConsensusError(Exception):
    """Base class for everything that can go wrong during consensus operations.

    Each variant of the reference's error enum (src/error.rs:11-74) is a
    subclass carrying a :class:`StatusCode`.
    """

    code: StatusCode = StatusCode.SIGNATURE_SCHEME
    default_message: str = "consensus error"

    def __init__(self, message: str | None = None):
        super().__init__(message if message is not None else self.default_message)


# ── Configuration validation ─────────────────────────────────────────────


class InvalidConsensusThreshold(ConsensusError):
    code = StatusCode.INVALID_CONSENSUS_THRESHOLD
    default_message = "consensus_threshold must be between 0.0 and 1.0"


class InvalidTimeout(ConsensusError):
    code = StatusCode.INVALID_TIMEOUT
    default_message = "timeout must be greater than 0"


class InvalidExpectedVotersCount(ConsensusError):
    code = StatusCode.INVALID_EXPECTED_VOTERS_COUNT
    default_message = "expected_voters_count must be greater than 0"


class InvalidMaxRounds(ConsensusError):
    code = StatusCode.INVALID_MAX_ROUNDS
    default_message = "max_rounds must be greater than 0"


# ── Vote and proposal validation ─────────────────────────────────────────


class InvalidVoteSignature(ConsensusError):
    code = StatusCode.INVALID_VOTE_SIGNATURE
    default_message = "Invalid vote signature"


class EmptySignature(ConsensusError):
    code = StatusCode.EMPTY_SIGNATURE
    default_message = "Empty signature"


class DuplicateVote(ConsensusError):
    code = StatusCode.DUPLICATE_VOTE
    default_message = "Duplicate vote"


class UserAlreadyVoted(ConsensusError):
    code = StatusCode.USER_ALREADY_VOTED
    default_message = "User already voted"


class VoteExpired(ConsensusError):
    code = StatusCode.VOTE_EXPIRED
    default_message = "Vote expired"


class EmptyVoteOwner(ConsensusError):
    code = StatusCode.EMPTY_VOTE_OWNER
    default_message = "Empty vote owner"


class InvalidVoteHash(ConsensusError):
    code = StatusCode.INVALID_VOTE_HASH
    default_message = "Invalid vote hash"


class EmptyVoteHash(ConsensusError):
    code = StatusCode.EMPTY_VOTE_HASH
    default_message = "Empty vote hash"


class ProposalExpired(ConsensusError):
    code = StatusCode.PROPOSAL_EXPIRED
    default_message = "Proposal expired"


class VoteProposalIdMismatch(ConsensusError):
    code = StatusCode.VOTE_PROPOSAL_ID_MISMATCH
    default_message = "Vote proposal_id mismatch: vote belongs to different proposal"


class ReceivedHashMismatch(ConsensusError):
    code = StatusCode.RECEIVED_HASH_MISMATCH
    default_message = "Received hash mismatch"


class ParentHashMismatch(ConsensusError):
    code = StatusCode.PARENT_HASH_MISMATCH
    default_message = "Parent hash mismatch"


class InvalidVoteTimestamp(ConsensusError):
    code = StatusCode.INVALID_VOTE_TIMESTAMP
    default_message = "Invalid vote timestamp"


class TimestampOlderThanCreationTime(ConsensusError):
    code = StatusCode.TIMESTAMP_OLDER_THAN_CREATION_TIME
    default_message = "Vote timestamp is older than creation time"


# ── Session / state ──────────────────────────────────────────────────────


class SessionNotActive(ConsensusError):
    code = StatusCode.SESSION_NOT_ACTIVE
    default_message = "Session not active"


class SessionNotFound(ConsensusError):
    code = StatusCode.SESSION_NOT_FOUND
    default_message = "Session not found"


class ProposalAlreadyExist(ConsensusError):
    code = StatusCode.PROPOSAL_ALREADY_EXIST
    default_message = "Proposal already exist in consensus service"


class ScopeNotFound(ConsensusError):
    code = StatusCode.SCOPE_NOT_FOUND
    default_message = "Scope not found"


# ── Consensus results ────────────────────────────────────────────────────


class InsufficientVotesAtTimeout(ConsensusError):
    code = StatusCode.INSUFFICIENT_VOTES_AT_TIMEOUT
    default_message = "Insufficient votes at timeout"


class MaxRoundsExceeded(ConsensusError):
    code = StatusCode.MAX_ROUNDS_EXCEEDED
    default_message = "Consensus exceeded configured max rounds"


class ConsensusNotReached(ConsensusError):
    code = StatusCode.CONSENSUS_NOT_REACHED
    default_message = "Consensus not reached"


class ConsensusFailed(ConsensusError):
    code = StatusCode.CONSENSUS_FAILED
    default_message = "Consensus failed"


class VoterCapacityExceeded(ConsensusError):
    """Engine-specific: device voter lanes exhausted for this proposal."""

    code = StatusCode.VOTER_CAPACITY_EXCEEDED
    default_message = "Pool voter capacity exceeded for proposal"


# ── Signature scheme errors (reference: src/signing.rs:77-86) ────────────


class ConsensusSchemeError(ConsensusError):
    """Error raised by a signature scheme (sign or verify failure)."""

    code = StatusCode.SIGNATURE_SCHEME
    default_message = "Signature scheme failure"

    @classmethod
    def sign(cls, detail: str) -> "ConsensusSchemeError":
        return cls(f"Signing failed: {detail}")

    @classmethod
    def verify(cls, detail: str) -> "ConsensusSchemeError":
        return cls(f"Verification rejected inputs: {detail}")


_CODE_TO_ERROR: dict[int, type[ConsensusError]] = {
    cls.code: cls
    for cls in [
        InvalidConsensusThreshold,
        InvalidTimeout,
        InvalidExpectedVotersCount,
        InvalidMaxRounds,
        InvalidVoteSignature,
        EmptySignature,
        DuplicateVote,
        UserAlreadyVoted,
        VoteExpired,
        EmptyVoteOwner,
        InvalidVoteHash,
        EmptyVoteHash,
        ProposalExpired,
        VoteProposalIdMismatch,
        ReceivedHashMismatch,
        ParentHashMismatch,
        InvalidVoteTimestamp,
        TimestampOlderThanCreationTime,
        SessionNotActive,
        SessionNotFound,
        ProposalAlreadyExist,
        ScopeNotFound,
        InsufficientVotesAtTimeout,
        MaxRoundsExceeded,
        ConsensusNotReached,
        ConsensusFailed,
        VoterCapacityExceeded,
        ConsensusSchemeError,
    ]
}


def error_for_code(code: int) -> type[ConsensusError] | None:
    """Map a dense device status code back to its exception class.

    Returns ``None`` for the non-error codes ``OK`` and ``ALREADY_REACHED``
    (a vote accepted by an already-decided session is a success in the
    reference semantics, src/session.rs:246). Raises ``ValueError`` only for
    codes this module does not define.
    """
    status = StatusCode(code)  # raises ValueError for genuinely unknown ints
    if status in (StatusCode.OK, StatusCode.ALREADY_REACHED):
        return None
    return _CODE_TO_ERROR[status]
