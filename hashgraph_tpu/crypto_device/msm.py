"""The Straus multi-scalar multiply over signature lanes — one dispatch.

Batch verification reduces to ONE curve equation: with fresh 128-bit
randomizers z_i, accept the whole batch iff

    8 * ( S*B + sum_i a_i*A_i + sum_i b_i*R_i ) == identity,

where S = sum z_i s_i (mod L), a_i = -z_i h_i (mod L), b_i = -z_i
(mod L). Negation happens in the *scalar* group rather than on points:
(L - k)*P == -k*P up to a small-order component, and the final
multiply-by-8 — the cofactored criterion this repo standardizes on
(PARITY.md) — clears exactly that component, so the identity test is
unaffected. That keeps the device graph free of point negations.

Shape of the computation (classic Straus/interleaved windows, the same
scheme as the native runtime's ed_verify_batch_range, turned 90°):

- every lane builds its 16-entry window table (T_k = T_{k-1} + P, a
  15-step lax.scan — one vectorized point add per step);
- 64 window iterations (lax.fori_loop): 4 doublings then one gathered
  table add per lane — every lane's nibble indexes its own table;
- a fixed-shape binary-tree reduction folds the lane accumulators:
  ceil(log2 L) masked pair-add steps inside the same jit (identity
  padding makes dead lanes self-absorbing);
- 3 doublings (the *8) and the projective identity test.

Everything from table build to verdict is one jitted function per lane
bucket; callers pad lanes to power-of-two buckets so the compile set
stays tiny and the persistent XLA cache pays for each shape once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import curve

WINDOWS = 64  # 4-bit windows over 256-bit scalars, MSB first


def scalars_to_nibbles(scalars: "list[int]") -> np.ndarray:
    """Host-side window decomposition: int32[n, 64], most significant
    nibble first (scalars already reduced mod L, so < 2^253). The only
    per-scalar Python work is the 32-byte export; nibble splitting is
    vectorized."""
    n = len(scalars)
    buf = np.frombuffer(
        b"".join(s.to_bytes(32, "little") for s in scalars), np.uint8
    ).reshape(n, 32)
    nibbles = np.empty((n, WINDOWS), np.uint8)
    nibbles[:, 0::2] = buf & 0xF        # little-endian nibble order
    nibbles[:, 1::2] = buf >> 4
    return nibbles[:, ::-1].astype(np.int32)  # MSB-first windows


@jax.jit
def _msm_is_identity(points, nibbles):
    """points: uint32[Lanes, 4, 16], nibbles: int32[Lanes, 64] ->
    uint32[] (1 iff 8 * sum_i scalar_i * point_i == identity)."""
    lanes = points.shape[0]
    lane_iota = jnp.arange(lanes)

    # Window tables: table[k] = k * P per lane, k = 0..15.
    def table_step(acc, _):
        nxt = curve.add(acc, points)
        return nxt, nxt
    _, tail = lax.scan(
        table_step, curve.identity((lanes,)), None, length=15
    )
    table = jnp.concatenate(
        [curve.identity((lanes,))[None], tail], axis=0
    )  # [16, Lanes, 4, 16]

    def window_step(w, acc):
        acc = curve.dbl(curve.dbl(curve.dbl(curve.dbl(acc))))
        sel = table[nibbles[:, w], lane_iota]  # gather per lane
        return curve.add(acc, sel)

    acc = lax.fori_loop(
        0, WINDOWS, window_step, curve.identity((lanes,))
    )

    # Fixed-shape tree reduction: lane i <- lane 2i + lane 2i+1, with
    # out-of-range partners reading the (self-absorbing) identity.
    half_steps = max(1, int(np.ceil(np.log2(max(lanes, 2)))))
    ident = curve.identity((lanes,))

    def reduce_step(_, q):
        left = q[jnp.minimum(2 * lane_iota, lanes - 1)]
        right_idx = jnp.minimum(2 * lane_iota + 1, lanes - 1)
        right = jnp.where(
            (2 * lane_iota + 1 < lanes)[:, None, None],
            q[right_idx], ident,
        )
        summed = curve.add(left, right)
        # Lanes past the fold point decay to identity (their operands
        # are identity already once the frontier passes them).
        return jnp.where(
            (2 * lane_iota < lanes)[:, None, None], summed, ident
        )

    total = lax.fori_loop(0, half_steps, reduce_step, acc)[0]
    cofactored = lax.fori_loop(
        0, 3, lambda _, q: curve.dbl(q[None])[0], total
    )
    return curve.is_identity(cofactored).astype(jnp.uint32)


def msm_accepts(points, nibbles) -> bool:
    """Host entry: run the jitted MSM and pull the verdict flag."""
    return bool(_msm_is_identity(points, nibbles))
